"""Data-parallel GBDT scaling over the mesh ``data`` axis (1/2/4/8 devices).

Makes the "linear speed-up" claim of distributed LightGBM
(``/root/reference/docs/lightgbm.md:19-21``) falsifiable for this runtime:
the SAME dataset is fitted at every mesh width, reporting

- measured wall time per boosting iteration (CAVEAT below),
- XLA-compiled cost-model FLOPs of one boosting step per device — the
  hardware-independent compute-side evidence: it must shrink ~1/devices,
- the analytic per-pass allreduce payload (k*F*B*3*4 bytes — independent of
  both N and the device count: the histogram reduce is the ONLY
  communication, which is why the algorithm weak-scales),
- held-out AUC at every width (exact histogram sums -> parity).

CAVEAT: this rig emulates the mesh with virtual CPU devices on ONE physical
core (`xla_force_host_platform_device_count`), so wall time cannot flatten —
the devices time-share the core and collectives serialize. Wall time is
reported for honesty; the falsifiable scaling signal on this hardware is the
per-device cost-model FLOPs plus the constant communication volume. On a
real ICI mesh the same programs run one device per chip.

Run: ``python benchmarks/mesh_scaling.py`` (forces the CPU platform itself).
Writes ``docs/mesh_scaling.md``.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_ROWS = int(os.environ.get("MESH_BENCH_ROWS", 200_000))
N_FEATURES = 16
N_ITERS = 10
NUM_LEAVES = 15
MAX_BIN = 63


def main():
    from mmlspark_tpu.parallel.mesh import force_platform

    force_platform("cpu", min_devices=8)

    import jax
    import numpy as np

    from mmlspark_tpu.lightgbm.binning import bin_dataset
    from mmlspark_tpu.lightgbm.objectives import auc
    from mmlspark_tpu.lightgbm.train import TrainOptions, train
    from mmlspark_tpu.parallel.mesh import MeshConfig, make_mesh

    rng = np.random.default_rng(0)
    n_test = 40_000
    X = rng.normal(size=(N_ROWS + n_test, N_FEATURES))
    y = ((X[:, 0] + X[:, 1] * X[:, 2] + 0.5 * rng.normal(size=len(X))) > 0).astype(
        np.float64
    )
    Xtr, ytr = X[:N_ROWS], y[:N_ROWS]
    Xte, yte = X[N_ROWS:], y[N_ROWS:]
    bins, mapper = bin_dataset(Xtr, max_bin=MAX_BIN)

    opts = TrainOptions(
        objective="binary", num_iterations=N_ITERS, num_leaves=NUM_LEAVES,
        max_bin=MAX_BIN,
    )

    rows = []
    for d in (1, 2, 4, 8):
        mesh = (
            None if d == 1
            else make_mesh(MeshConfig(data=d), devices=jax.devices()[:d])
        )
        train(bins, ytr, opts, mapper=mapper, mesh=mesh)  # warm (compile)
        t0 = time.perf_counter()
        result = train(bins, ytr, opts, mapper=mapper, mesh=mesh)
        dt = time.perf_counter() - t0
        a = auc(yte, result.booster.raw_margin(Xte)[:, 0], np.ones(n_test))

        flops = _step_flops(d, bins, ytr, opts, mapper, mesh)
        rows.append(
            dict(
                devices=d,
                rows_per_device=N_ROWS // d,
                secs_per_iter=dt / N_ITERS,
                step_flops_per_device=flops,
                auc=a,
            )
        )
        print(rows[-1])

    aucs = [r["auc"] for r in rows]
    assert max(aucs) - min(aucs) < 2e-3, f"AUC parity violated: {aucs}"

    # Per-pass allreduce payload: the reduced histogram (leaf_batch nodes x
    # F x B x 3 f32) — independent of N and of the device count.
    k = min(opts.leaf_batch, NUM_LEAVES - 1)
    comm = k * N_FEATURES * (MAX_BIN + 1) * 3 * 4

    base = rows[0]["step_flops_per_device"]
    lines = [
        "# Mesh scaling — data-parallel GBDT (virtual 8-device CPU mesh)",
        "",
        f"Dataset {N_ROWS:,} x {N_FEATURES}, {N_ITERS} iterations, "
        f"{NUM_LEAVES} leaves, max_bin {MAX_BIN}. Same data at every width.",
        "",
        "| data devices | rows/device | wall secs/iter* | step FLOPs/device (XLA cost model) | vs 1-device | holdout AUC |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        ratio = (
            "—" if not (base and r["step_flops_per_device"])
            else f"{r['step_flops_per_device'] / base:.2f}x"
        )
        fl = r["step_flops_per_device"]
        lines.append(
            f"| {r['devices']} | {r['rows_per_device']:,} | "
            f"{r['secs_per_iter']:.3f} | {fl:.3g} | {ratio} | {r['auc']:.4f} |"
        )
    lines += [
        "",
        "*Wall time on this rig CANNOT flatten: the 8 virtual devices",
        "time-share ONE physical core and collectives serialize "
        "(`xla_force_host_platform_device_count`). The falsifiable scaling",
        "evidence here is the cost-model FLOPs column — the per-device",
        "compute of one compiled boosting step, which XLA partitions to",
        "~1/devices — plus the communication side: the only collective is",
        f"the histogram allreduce, {comm:,} bytes per pass "
        "(leaf_batch x F x B x 3 f32), independent of BOTH the row count",
        "and the device count. Compute shrinks per device, communication",
        "stays constant per pass: the weak-scaling shape of distributed",
        "LightGBM's own experiments (docs/lightgbm.md:19-21), with AUC",
        "parity at every width (exact histogram sums).",
        "",
        f"Generated by `benchmarks/mesh_scaling.py` (rows={N_ROWS:,}).",
    ]
    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "mesh_scaling.md",
    )
    with open(out, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    print(f"wrote {out}")


def _step_flops(d, bins, y, opts, mapper, mesh):
    """FLOPs of ONE compiled boosting step per device, from XLA's cost
    model. Under SPMD the analysis reports the per-device program."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mmlspark_tpu.lightgbm.objectives import get_objective
    from mmlspark_tpu.lightgbm.train import _make_step

    try:
        objective = get_objective(opts.objective)
        step = _make_step(opts, objective, opts.max_bin + 1, mesh)
        n, f = bins.shape
        edges = np.where(
            np.isfinite(mapper.edges), mapper.edges, np.finfo(np.float32).max
        ).astype(np.float32)

        if mesh is not None:
            from mmlspark_tpu.parallel.mesh import data_sharding, replicated

            sh_rows = data_sharding(mesh)
            sh_rep = replicated(mesh)
            bins_d = jax.device_put(bins.astype(np.uint8), sh_rows)
            y_d = jax.device_put(y.astype(np.float32), sh_rows)
            edges_d = jax.device_put(edges, sh_rep)
        else:
            bins_d = jnp.asarray(bins.astype(np.uint8))
            y_d = jnp.asarray(y.astype(np.float32))
            edges_d = jnp.asarray(edges)
        w_d = jnp.ones_like(y_d)
        margins = jnp.zeros((n, 1), jnp.float32)
        bag = jnp.ones(n, jnp.float32)
        fm = jnp.ones(f, jnp.float32)
        lowered = jax.jit(step).lower(
            bins_d, y_d, w_d, margins, edges_d, bag, fm, jnp.int32(0), None
        )
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost.get("flops", 0.0)) if cost else 0.0
    except Exception as e:  # cost model availability varies by backend
        print(f"  (cost analysis unavailable: {type(e).__name__}: {e})")
        return 0.0


if __name__ == "__main__":
    main()
