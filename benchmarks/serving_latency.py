"""Serving latency artifact — BASELINE config 5 (p50 < 5 ms target).

Measures the two components of a served single-row prediction and their
end-to-end composition:

1. HTTP edge + micro-batch loop overhead (trivial model, local socket);
2. warm jitted device forward of a real zoo model (ResNet-18, batch 1..8);
3. end-to-end: the ResNet served through ServingServer.

Caveat recorded in the output: on THIS rig the chip is remote-attached
through the axon relay, whose per-dispatch round-trip (~100ms+) dominates
any served device call; the honest per-component numbers are (1) measured
here and (2) measured on-chip with an on-device timing loop, composing to
the locally-attached expectation.

Run: ``python benchmarks/serving_latency.py`` (single chip).
"""

import json
import os
import sys
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST"
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _percentiles(times):
    times = sorted(times)
    n = len(times)
    return {
        "p50_ms": times[n // 2] * 1e3,
        "p90_ms": times[int(n * 0.9)] * 1e3,
        "p99_ms": times[min(n - 1, int(n * 0.99))] * 1e3,
    }


def http_edge_keepalive_latency(n=500):
    """One persistent HTTP/1.1 connection, n sequential requests — the
    steady-state client shape (no TCP setup per call)."""
    import http.client

    from mmlspark_tpu.core.pipeline import Transformer
    from mmlspark_tpu.serving import ServingServer

    class Doubler(Transformer):
        def transform(self, table):
            x = np.asarray(table.column("input"), dtype=np.float64)
            return table.with_column("prediction", x * 2)

    with ServingServer(Doubler(), max_latency_ms=0.2) as srv:
        conn = http.client.HTTPConnection("127.0.0.1", srv.info.port)
        conn.connect()
        import socket

        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        body = json.dumps({"input": 1.0}).encode()

        def call():
            conn.request("POST", "/", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 200

        for _ in range(20):
            call()
        times = []
        for _ in range(n):
            t0 = time.perf_counter()
            call()
            times.append(time.perf_counter() - t0)
        conn.close()
    return _percentiles(times)


def http_edge_latency(n=200):
    from mmlspark_tpu.core.pipeline import Transformer
    from mmlspark_tpu.serving import ServingServer

    class Doubler(Transformer):
        def transform(self, table):
            x = np.asarray(table.column("input"), dtype=np.float64)
            return table.with_column("prediction", x * 2)

    with ServingServer(Doubler(), max_latency_ms=0.5) as srv:
        for _ in range(10):
            _post(srv.info.url, {"input": 1.0})
        times = []
        for i in range(n):
            t0 = time.perf_counter()
            _post(srv.info.url, {"input": float(i)})
            times.append(time.perf_counter() - t0)
    return _percentiles(times)


def device_forward_latency(
    batch=1, iters=200, variant="resnet18", size=32, dtype="float32"
):
    """Warm jitted ResNet forward, timed with an on-device loop (one
    dispatch for all iters, so remote-tunnel round-trips amortize out; the
    ~100 ms sync fetch is subtracted via an empty-loop floor — at fewer
    reps it silently inflates every per-iter number)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mmlspark_tpu.models import init_resnet, resnet_apply

    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    params = jax.tree.map(
        lambda a: jnp.asarray(a, dt),
        init_resnet(
            variant=variant, num_classes=10, small_inputs=(size <= 64)
        ),
    )  # pin weights on device ONCE — numpy leaves re-upload per dispatch
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(batch, 3, size, size)), dt
    )

    @jax.jit
    def loop(params, x):
        def body(i, acc):
            out = resnet_apply(params, x * (1.0 + i.astype(dt) * dt(1e-9)))
            return acc + out.ravel()[0].astype(jnp.float32)

        return lax.fori_loop(0, iters, body, jnp.float32(0.0))

    @jax.jit
    def floor_loop(x):
        def body(i, acc):
            return acc + x.ravel()[0].astype(jnp.float32) * 0

        return lax.fori_loop(0, iters, body, jnp.float32(0.0))

    float(loop(params, x))  # compile
    float(floor_loop(x))
    # The sync fetch through the relay swings run to run — a single
    # floor/loop pair can even go negative. Median of 5 each.
    floors, runs = [], []
    for _ in range(5):
        t0 = time.perf_counter()
        float(floor_loop(x))
        floors.append(time.perf_counter() - t0)
    for _ in range(5):
        t0 = time.perf_counter()
        float(loop(params, x))
        runs.append(time.perf_counter() - t0)
    per_call = (float(np.median(runs)) - float(np.median(floors))) / iters
    return per_call * 1e3


def served_resnet_latency(n=30):
    import jax.numpy as jnp

    from mmlspark_tpu.core.pipeline import Transformer
    from mmlspark_tpu.models import init_resnet, resnet_apply
    from mmlspark_tpu.serving import ServingServer

    import jax

    params = jax.tree.map(
        jnp.asarray,
        init_resnet(variant="resnet18", num_classes=10, small_inputs=True),
    )
    fwd = jax.jit(resnet_apply)

    class ResNetModel(Transformer):
        def transform(self, table):
            col = table.column("input")
            x = jnp.asarray(np.stack(list(col)), jnp.float32)
            out = np.asarray(fwd(params, x))
            outcol = np.empty(len(out), dtype=object)
            for i in range(len(out)):
                outcol[i] = out[i].tolist()
            return table.with_column("prediction", outcol)

    img = np.random.default_rng(0).normal(size=(3, 32, 32)).tolist()
    with ServingServer(ResNetModel(), max_latency_ms=1.0) as srv:
        for _ in range(3):
            _post(srv.info.url, {"input": img})
        times = []
        for _ in range(n):
            t0 = time.perf_counter()
            _post(srv.info.url, {"input": img})
            times.append(time.perf_counter() - t0)
    return _percentiles(times)


def concurrent_load_latency(
    num_servers=3, num_clients=16, reqs_per_client=25, kill_worker=True
):
    """END-TO-END measured latency distribution under concurrent load —
    ``num_clients`` threads hammering a :class:`DistributedServingServer`
    (the ``HTTPv2Suite.scala:315-387`` shape). Midway through, one listener
    dies; its clients fail over to the surviving endpoints (the
    registry-discovery story), and the distribution INCLUDES the failed
    attempts' wall time. This is one measured pipeline number (HTTP parse →
    shared queue → micro-batch → model → cross-listener reply), not a
    composition."""
    import threading

    from mmlspark_tpu.core.pipeline import Transformer
    from mmlspark_tpu.serving import DistributedServingServer

    class Doubler(Transformer):
        def transform(self, table):
            x = np.asarray(table.column("input"), dtype=np.float64)
            return table.with_column("prediction", x * 2)

    results = {"times": [], "failovers": 0, "errors": 0}
    lock = threading.Lock()
    srv = DistributedServingServer(
        Doubler(), num_servers=num_servers, max_latency_ms=1.0
    ).start()
    urls = [info.url for info in srv.service_info]
    kill_after = num_clients * reqs_per_client // 2
    done = {"count": 0}

    def client(cid):
        for i in range(reqs_per_client):
            want = float(cid * 1000 + i)
            t0 = time.perf_counter()
            ok = False
            for attempt in range(len(urls)):
                url = urls[(cid + attempt) % len(urls)]
                try:
                    out = _post(url, {"input": want})
                    assert out["prediction"] == want * 2, out
                    ok = True
                    break
                except AssertionError:
                    raise
                except Exception:
                    with lock:
                        results["failovers"] += 1
            dt = time.perf_counter() - t0
            with lock:
                results["times"].append(dt)
                if not ok:
                    results["errors"] += 1
                done["count"] += 1

    def killer():
        # worker death mid-stream: stop one listener once half the requests
        # have completed (the shared batch loop keeps serving the others)
        while True:
            with lock:
                if done["count"] >= kill_after:
                    break
            time.sleep(0.002)
        srv.servers[0].stop()

    threads = [
        threading.Thread(target=client, args=(c,)) for c in range(num_clients)
    ]
    if kill_worker:
        threads.append(threading.Thread(target=killer))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    srv.stop()
    out = _percentiles(results["times"])
    out["requests"] = len(results["times"])
    out["failovers"] = results["failovers"]
    out["errors"] = results["errors"]
    return out


def main():
    import jax

    edge = http_edge_latency()
    edge_ka = http_edge_keepalive_latency()
    dev1 = device_forward_latency(batch=1)
    dev8 = device_forward_latency(batch=8)
    # BASELINE config 5 names ResNet-50 — measure THAT model at serving
    # shape (224x224, batch 1, bf16), not a stand-in. Long loops (device
    # work >> the ~100 ms relay sync) keep the per-call number stable even
    # on a loaded host (0.214/0.215 ms across back-to-back reps).
    r50_1 = device_forward_latency(
        batch=1, iters=2000, variant="resnet50", size=224, dtype="bfloat16"
    )
    r50_8 = device_forward_latency(
        batch=8, iters=500, variant="resnet50", size=224, dtype="bfloat16"
    )
    served = served_resnet_latency()
    load = concurrent_load_latency()
    report = {
        "backend": jax.default_backend(),
        "http_edge": edge,
        "http_edge_keepalive": edge_ka,
        "resnet18_forward_ms": {"batch1": dev1, "batch8": dev8},
        "resnet50_224_bf16_forward_ms": {"batch1": r50_1, "batch8": r50_8},
        "served_resnet18_end_to_end": served,
        "concurrent_load_distributed": load,
        "composed_locally_attached_p50_ms": edge["p50_ms"] + dev1,
        "composed_resnet50_p50_ms": edge_ka["p50_ms"] + r50_1,
        "note": (
            "end-to-end includes the remote-attach relay round-trip on this "
            "rig; composed = HTTP edge p50 + warm on-device forward, the "
            "locally-attached expectation; concurrent_load_distributed is a "
            "single MEASURED pipeline distribution (16 clients, 3 listeners, "
            "one killed mid-stream) with a host model — no relay in the path"
        ),
    }
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
