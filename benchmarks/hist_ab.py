"""A/B benchmark: histogram formulations on the real TPU chip.

Measures the GBDT hot op (``ops/histogram.py`` vs ``ops/pallas_histogram.py``)
at realistic training shapes and prints per-method wall time plus the
bandwidth roofline. Results are recorded in ``docs/perf_histogram.md``.

Run: ``python benchmarks/hist_ab.py`` (single real chip).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.ops.histogram import build_histograms

SHAPES = [
    # (rows, features, nodes, bins)  — leafwise child pass / depthwise levels
    (1 << 20, 28, 1, 256),   # leafwise + subtraction: one B-wide child pass
    (1 << 20, 28, 2, 256),   # two-child pass (voting-parallel path)
    (1 << 20, 28, 8, 256),   # depthwise level 3
    (1 << 18, 128, 1, 256),  # wide features
    (1 << 22, 28, 1, 64),    # 4M rows, small bins
]


def bench(method, bins, g, h, c, node, nodes, b, iters=20):
    """One jitted on-device fori_loop over `iters` histogram builds — a
    single dispatch, so remote-tunnel per-call latency amortizes away. The
    gradient is perturbed per iteration to defeat loop-invariant hoisting,
    and a scalar chained out forces execution."""
    from jax import lax as _lax

    @jax.jit
    def loop(bins_, g_, h_, c_, node_):
        def body(i, acc):
            gi = g_ * (1.0 + i.astype(jnp.float32) * 1e-9)
            out = build_histograms(bins_, gi, h_, c_, node_, nodes, b, method=method)
            return acc + out[0, 0, 0, 0]

        return _lax.fori_loop(0, iters, body, jnp.float32(0.0))

    float(loop(bins, g, h, c, node))  # warm / compile
    t0 = time.perf_counter()
    float(loop(bins, g, h, c, node))
    return (time.perf_counter() - t0) / iters


def main():
    print(f"backend: {jax.default_backend()}, device: {jax.devices()[0]}")
    for n, f, nodes, b in SHAPES:
        rng = np.random.default_rng(0)
        bins = jnp.asarray(rng.integers(0, b, size=(n, f)), dtype=jnp.int32)
        g = jnp.asarray(rng.normal(size=n), dtype=jnp.float32)
        h = jnp.asarray(rng.random(n), dtype=jnp.float32)
        c = jnp.ones(n, dtype=jnp.float32)
        node = jnp.asarray(rng.integers(0, nodes, size=n), dtype=jnp.int32)

        # bandwidth floor: ids int32 read + data 12B/row/feature-pass
        ids_bytes = 4 * n * f
        out_bytes = 4 * f * nodes * b * 3
        floor_bytes = ids_bytes + 12 * n + out_bytes

        row = f"N={n:>8} F={f:>4} nodes={nodes} B={b}: "
        results = {}
        for method in ("onehot", "pallas", "segment"):
            try:
                dt = bench(method, bins, g, h, c, node, nodes, b)
                gbps = floor_bytes / dt / 1e9
                results[method] = dt
                row += f"{method}={dt*1e3:7.2f}ms ({gbps:6.1f} GB/s eff)  "
            except Exception as e:
                row += f"{method}=FAIL({type(e).__name__})  "
        if "onehot" in results and "pallas" in results:
            row += f"speedup={results['onehot']/results['pallas']:.2f}x"
        print(row)


if __name__ == "__main__":
    main()
