"""BASELINE config 4: VowpalWabbit text classification, TPU vs CPU.

Amazon-reviews-like workload synthesized locally (zero-egress rig): a
vocabulary with class-dependent word frequencies, murmur-hashed bag-of-words
featurization (VowpalWabbitFeaturizer, the reference's "Java-side hashing"
path re-done in C++/numpy), then the jitted adagrad-SGD learner vs sklearn's
SGDClassifier(log_loss) on the identical hashed design matrix — accuracy
parity is part of the contract.

Prints ONE JSON line and writes it to benchmarks/vw_text_bench.json:

    python benchmarks/vw_text_bench.py
"""

import json
import os
import time

import numpy as np

N_DOCS = int(os.environ.get("VW_BENCH_DOCS", 200_000))
N_TEST = 20_000
VOCAB = 5000
DOC_LEN = 30
NUM_BITS = 18
PASSES = 3


def make_corpus(n, seed=0):
    rng = np.random.default_rng(seed)
    words = np.array([f"w{i}" for i in range(VOCAB)])
    # class-dependent word distributions (Zipf-ish base, tilted per class)
    base = 1.0 / np.arange(1, VOCAB + 1)
    tilt = rng.normal(size=VOCAB) * 0.7
    p_pos = base * np.exp(tilt)
    p_neg = base * np.exp(-tilt)
    p_pos /= p_pos.sum()
    p_neg /= p_neg.sum()
    y = rng.integers(0, 2, size=n).astype(np.float64)
    docs = np.empty(n, dtype=object)
    pos_draw = rng.choice(VOCAB, size=(n, DOC_LEN), p=p_pos)
    neg_draw = rng.choice(VOCAB, size=(n, DOC_LEN), p=p_neg)
    for i in range(n):
        toks = pos_draw[i] if y[i] > 0 else neg_draw[i]
        docs[i] = " ".join(words[toks])
    return docs, y


def main():
    from mmlspark_tpu.data.table import Table
    from mmlspark_tpu.vw import VowpalWabbitClassifier, VowpalWabbitFeaturizer

    import jax

    backend = jax.default_backend()
    docs, y = make_corpus(N_DOCS + N_TEST)
    t_all = Table({"text": docs, "label": y})

    feat = VowpalWabbitFeaturizer(
        inputCols=["text"], outputCol="features", numBits=NUM_BITS,
        stringSplit=True,
    )
    t0 = time.perf_counter()
    feats = feat.transform(t_all)
    featurize_s = time.perf_counter() - t0

    # combined featurizer + namespace-crossing pass (both column-vectorized)
    from mmlspark_tpu.vw import VowpalWabbitInteractions

    feat2 = VowpalWabbitFeaturizer(
        inputCols=["text"], outputCol="features2", numBits=NUM_BITS,
        stringSplit=True, prefixStringsWithColumnName=False,
    )
    inter = VowpalWabbitInteractions(
        inputCols=["features", "features2"], outputCol="crossed",
        numBits=NUM_BITS,
    )
    inter_docs = min(20_000, N_DOCS)
    t0 = time.perf_counter()
    inter.transform(feat2.transform(feats.head(inter_docs)))
    featurize_inter_s = time.perf_counter() - t0

    tr = feats.slice(0, N_DOCS)
    te = feats.slice(N_DOCS, N_DOCS + N_TEST)
    yte = y[N_DOCS:]

    VowpalWabbitClassifier(numPasses=PASSES, batchSize=1024).fit(tr)  # compile warm-up
    t0 = time.perf_counter()
    m = VowpalWabbitClassifier(numPasses=PASSES, batchSize=1024).fit(tr)
    fit_s = time.perf_counter() - t0
    acc_tpu = float((m.transform(te).column("prediction") == yte).mean())

    # CPU baseline: sklearn SGD logistic on the SAME hashed sparse matrix
    from scipy.sparse import csr_matrix
    from sklearn.linear_model import SGDClassifier

    def to_csr(tbl):
        from mmlspark_tpu.data.sparse import SparseRows

        col = tbl.column("features")
        if isinstance(col, SparseRows):  # CSR column: three array handoffs
            return csr_matrix(
                (col.values, col.indices, col.indptr),
                shape=(tbl.num_rows, 1 << NUM_BITS),
            )
        lens = np.array([len(rv[0]) for rv in col])
        indptr = np.concatenate([[0], np.cumsum(lens)])
        cols = np.concatenate([np.asarray(rv[0]) for rv in col])
        vals = np.concatenate([np.asarray(rv[1]) for rv in col])
        return csr_matrix(
            (vals, cols, indptr), shape=(tbl.num_rows, 1 << NUM_BITS)
        )

    Xtr, Xte = to_csr(tr), to_csr(te)
    ytr = y[:N_DOCS]
    times = []
    for run in range(3):
        sgd = SGDClassifier(loss="log_loss", max_iter=PASSES, tol=None,
                            random_state=run)
        t0 = time.perf_counter()
        sgd.fit(Xtr, ytr)
        times.append(time.perf_counter() - t0)
    cpu_s = float(np.median(times))
    acc_cpu = float((sgd.predict(Xte) == yte).mean())

    out = {
        "metric": f"vw_text_rows_per_sec_{backend}",
        "value": round(N_DOCS * PASSES / fit_s, 1),
        "unit": "rows*passes/sec",
        "vs_baseline": round(cpu_s / fit_s, 3),
        "tpu_fit_secs": round(fit_s, 3),
        "cpu_fit_secs": round(cpu_s, 3),
        "featurize_secs": round(featurize_s, 3),
        "featurize_interactions_secs": round(featurize_inter_s, 3),
        "featurize_interactions_docs": inter_docs,
        "acc_tpu": round(acc_tpu, 4),
        "acc_cpu": round(acc_cpu, 4),
        "docs": N_DOCS,
        "num_bits": NUM_BITS,
        "cpu_engine": "sklearn.SGDClassifier(log_loss, median of 3)",
    }
    print(json.dumps(out))
    with open(os.path.join(os.path.dirname(__file__), "vw_text_bench.json"), "w") as f:
        json.dump(out, f)


if __name__ == "__main__":
    main()
