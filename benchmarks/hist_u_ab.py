"""A/B: precomputed-U histogram pass vs the compare-built panel kernel.

Run ON the real chip, idle machine, one TPU process:

    python benchmarks/hist_u_ab.py [N] [F] [B] [K_NODES]

Measurement discipline (memory: axon tunnel): every timed op runs inside a
jitted 20-iteration ``fori_loop`` whose input is perturbed per iteration
(or XLA hoists the loop-invariant call), synced by fetching a small slice.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mmlspark_tpu.observability.profiler import get_profiler
from mmlspark_tpu.ops.histogram import build_histograms
from mmlspark_tpu.ops.u_histogram import (
    build_histograms_u,
    build_u,
    make_u_spec,
    stat_rows,
)

N = int(sys.argv[1]) if len(sys.argv) > 1 else 409_600
F = int(sys.argv[2]) if len(sys.argv) > 2 else 28
B = int(sys.argv[3]) if len(sys.argv) > 3 else 256
KN = int(sys.argv[4]) if len(sys.argv) > 4 else 8
# 200, NOT 20: the tunnel's ~100 ms sync-fetch latency adds ~5 ms/iter to a
# 20-rep loop (the round-3 inflation documented in docs/perf_histogram.md)
REPS = int(sys.argv[5]) if len(sys.argv) > 5 else 200


def sync(x):
    return np.asarray(x.reshape(-1)[:4])


def timed(make_loop, *args, label=""):
    # the profiler wrap books the first (compiling) call as
    # ProfileCompiled with the program's cost_analysis FLOPs/bytes, the
    # warm call as ProfileExecuted — the BENCH JSON's profiler section
    loop = get_profiler().wrap(jax.jit(make_loop), name=label or "loop")
    sync(loop(*args))  # compile
    t0 = time.perf_counter()
    sync(loop(*args))
    dt = (time.perf_counter() - t0) / REPS * 1000
    print(f"{label:40s} {dt:8.2f} ms/pass")
    return dt


def main():
    prof = get_profiler().enable()
    rng = np.random.default_rng(0)
    bins = rng.integers(0, B, size=(N, F)).astype(np.uint8)
    g = rng.normal(size=N).astype(np.float32)
    h = rng.uniform(0.1, 1.0, size=N).astype(np.float32)
    c = np.ones(N, np.float32)
    node = rng.integers(0, KN, size=N).astype(np.int32)

    bins_d = jnp.asarray(bins)
    g_d, h_d, c_d = jnp.asarray(g), jnp.asarray(h), jnp.asarray(c)
    node_d = jnp.asarray(node)
    spec = make_u_spec(B, F)
    print(f"N={N} F={F} B={B} nodes={KN} K_pad={spec.k_pad} "
          f"U_int8={spec.k_pad * N / 1e9:.2f} GB backend={jax.default_backend()}")

    # --- baseline: compare-built panel kernel (the previous hot path)
    def loop_cmp(bins_, g_, h_, c_, node_):
        def body(i, acc):
            gi = g_ * (1 + i.astype(jnp.float32) * 1e-9)
            hist = build_histograms(bins_, gi, h_, c_, node_, KN, B, method="pallas")
            return acc + hist[0, 0, 0, 0]

        return lax.fori_loop(0, REPS, body, jnp.float32(0.0))

    t_cmp = timed(loop_cmp, bins_d, g_d, h_d, c_d, node_d,
                  label="compare-built panel kernel")

    # --- U build (once per fit) — ONE jitted callable, warm timing
    build8 = jax.jit(lambda b_: build_u(b_, spec, jnp.int8))
    u8 = build8(bins_d)
    sync(u8)
    t0 = time.perf_counter()
    u8 = build8(bins_d)
    sync(u8)
    print(f"{'U build (int8, warm)':40s} "
          f"{(time.perf_counter() - t0) * 1000:8.2f} ms once/fit")

    # --- U pass, per-pass stat build vs per-tree hoisted stat rows
    def loop_u(hoist_stats):
        def fn(u_, g_, h_, c_, node_):
            pre = stat_rows(g_, h_, c_) if hoist_stats else None

            def body(i, acc):
                gi = g_ * (1 + i.astype(jnp.float32) * 1e-9)
                hist = build_histograms_u(
                    u_, gi, h_, c_, node_ + (i % 2), KN, spec,
                    stats=pre,
                )
                return acc + hist[0, 0, 0, 0]

            return lax.fori_loop(0, REPS, body, jnp.float32(0.0))

        return fn

    t_u = timed(loop_u(False), u8, g_d, h_d, c_d, node_d,
                label="U pass (stats built per pass)")
    t_uh = timed(loop_u(True), u8, g_d, h_d, c_d, node_d,
                 label="U pass (stat rows hoisted per tree)")

    print(f"speedup vs compare-built: {t_cmp / min(t_u, t_uh):.2f}x")

    # ONE JSON line (the bench.py artifact convention): headline numbers
    # plus the profiler section. Each profiled program is a REPS-iteration
    # fori_loop, so per-iteration timing/FLOPs = the program totals / REPS.
    snap = prof.snapshot()
    per_iter = {
        name: {
            "compile_s": f["compile_seconds"],
            "exec_ms_per_iter": (
                f["device_seconds"] / max(f["executions"], 1) / REPS * 1e3
            ),
            "flops_per_iter": f["flops"] / REPS,
            "bytes_per_iter": f["bytes_accessed"] / REPS,
        }
        for name, f in snap["functions"].items()
    }
    print(json.dumps({
        "bench": "hist_u_ab",
        "n": N, "f": F, "b": B, "nodes": KN, "reps": REPS,
        "ms_per_pass": {
            "compare_built": t_cmp, "u": t_u, "u_hoisted": t_uh,
        },
        "speedup_vs_compare_built": t_cmp / min(t_u, t_uh),
        "profiler": dict(snap, per_iteration=per_iter),
    }))


if __name__ == "__main__":
    main()
