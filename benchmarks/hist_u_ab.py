"""A/B: precomputed-U histogram pass vs the compare-built panel kernel.

Run ON the real chip, idle machine, one TPU process:

    python benchmarks/hist_u_ab.py [N] [F] [B] [K_NODES]

Measurement discipline (memory: axon tunnel): every timed op runs inside a
jitted 20-iteration ``fori_loop`` whose input is perturbed per iteration
(or XLA hoists the loop-invariant call), synced by fetching a small slice.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mmlspark_tpu.observability.profiler import get_profiler
from mmlspark_tpu.ops.histogram import build_histograms
from mmlspark_tpu.ops.u_histogram import (
    build_histograms_u,
    build_u,
    histogram_acc_dtype,
    make_u_spec,
    stat_rows,
    stat_rows_quant,
)

N = int(sys.argv[1]) if len(sys.argv) > 1 else 409_600
F = int(sys.argv[2]) if len(sys.argv) > 2 else 28
B = int(sys.argv[3]) if len(sys.argv) > 3 else 256
KN = int(sys.argv[4]) if len(sys.argv) > 4 else 8
# 200, NOT 20: the tunnel's ~100 ms sync-fetch latency adds ~5 ms/iter to a
# 20-rep loop (the round-3 inflation documented in docs/perf_histogram.md)
REPS = int(sys.argv[5]) if len(sys.argv) > 5 else 200


def sync(x):
    return np.asarray(x.reshape(-1)[:4])


def timed(make_loop, *args, label=""):
    # the profiler wrap books the first (compiling) call as
    # ProfileCompiled with the program's cost_analysis FLOPs/bytes, the
    # warm call as ProfileExecuted — the BENCH JSON's profiler section
    loop = get_profiler().wrap(jax.jit(make_loop), name=label or "loop")
    sync(loop(*args))  # compile
    t0 = time.perf_counter()
    sync(loop(*args))
    dt = (time.perf_counter() - t0) / REPS * 1000
    print(f"{label:40s} {dt:8.2f} ms/pass")
    return dt


def main():
    prof = get_profiler().enable()
    rng = np.random.default_rng(0)
    bins = rng.integers(0, B, size=(N, F)).astype(np.uint8)
    g = rng.normal(size=N).astype(np.float32)
    h = rng.uniform(0.1, 1.0, size=N).astype(np.float32)
    c = np.ones(N, np.float32)
    node = rng.integers(0, KN, size=N).astype(np.int32)

    bins_d = jnp.asarray(bins)
    g_d, h_d, c_d = jnp.asarray(g), jnp.asarray(h), jnp.asarray(c)
    node_d = jnp.asarray(node)
    spec = make_u_spec(B, F)
    print(f"N={N} F={F} B={B} nodes={KN} K_pad={spec.k_pad} "
          f"U_int8={spec.k_pad * N / 1e9:.2f} GB backend={jax.default_backend()}")

    # --- baseline: compare-built panel kernel (the previous hot path)
    def loop_cmp(bins_, g_, h_, c_, node_):
        def body(i, acc):
            gi = g_ * (1 + i.astype(jnp.float32) * 1e-9)
            hist = build_histograms(bins_, gi, h_, c_, node_, KN, B, method="pallas")
            return acc + hist[0, 0, 0, 0]

        return lax.fori_loop(0, REPS, body, jnp.float32(0.0))

    t_cmp = timed(loop_cmp, bins_d, g_d, h_d, c_d, node_d,
                  label="compare-built panel kernel")

    # --- U build (once per fit) — ONE jitted callable, warm timing
    build8 = jax.jit(lambda b_: build_u(b_, spec, jnp.int8))
    u8 = build8(bins_d)
    sync(u8)
    t0 = time.perf_counter()
    u8 = build8(bins_d)
    sync(u8)
    print(f"{'U build (int8, warm)':40s} "
          f"{(time.perf_counter() - t0) * 1000:8.2f} ms once/fit")

    # --- U pass, per-pass stat build vs per-tree hoisted stat rows
    def loop_u(hoist_stats):
        def fn(u_, g_, h_, c_, node_):
            pre = stat_rows(g_, h_, c_) if hoist_stats else None

            def body(i, acc):
                gi = g_ * (1 + i.astype(jnp.float32) * 1e-9)
                hist = build_histograms_u(
                    u_, gi, h_, c_, node_ + (i % 2), KN, spec,
                    stats=pre,
                )
                return acc + hist[0, 0, 0, 0]

            return lax.fori_loop(0, REPS, body, jnp.float32(0.0))

        return fn

    t_u = timed(loop_u(False), u8, g_d, h_d, c_d, node_d,
                label="U pass (stats built per pass)")
    t_uh = timed(loop_u(True), u8, g_d, h_d, c_d, node_d,
                 label="U pass (stat rows hoisted per tree)")

    print(f"speedup vs compare-built: {t_cmp / min(t_u, t_uh):.2f}x")

    # --- sibling subtraction A/B: a split level has 2*KN children. Without
    # subtraction the pass panels all 2*KN; with it, only the KN smaller
    # children ride the matmul and siblings are a vector subtract from the
    # cached parent histograms (which the leaf batch already materialized).
    node2_d = jnp.asarray(rng.integers(0, 2 * KN, size=N).astype(np.int32))

    def loop_both(u_, g_, h_, c_, node_):
        pre = stat_rows(g_, h_, c_)

        def body(i, acc):
            gi = g_ * (1 + i.astype(jnp.float32) * 1e-9)
            hist = build_histograms_u(u_, gi, h_, c_, node_ + (i % 2),
                                      2 * KN, spec, stats=pre)
            return acc + hist[0, 0, 0, 0]

        return lax.fori_loop(0, REPS, body, jnp.float32(0.0))

    def loop_sub(u_, g_, h_, c_, node_, parent_):
        pre = stat_rows(g_, h_, c_)

        def body(i, acc):
            gi = g_ * (1 + i.astype(jnp.float32) * 1e-9)
            small = build_histograms_u(u_, gi, h_, c_, node_ + (i % 2), KN,
                                       spec, stats=pre)
            sibling = parent_ - small
            return acc + small[0, 0, 0, 0] + sibling[0, 0, 0, 0]

        return lax.fori_loop(0, REPS, body, jnp.float32(0.0))

    parent = build_histograms_u(u8, g_d, h_d, c_d, node_d, KN, spec)
    t_both = timed(loop_both, u8, g_d, h_d, c_d, node2_d,
                   label=f"split level, both children (2x{KN})")
    t_sub = timed(loop_sub, u8, g_d, h_d, c_d, node_d, parent,
                  label=f"split level, subtraction ({KN}+derive)")
    print(f"subtraction speedup per split level: {t_both / t_sub:.2f}x")

    # --- packed (quantized int) accumulators: dequant deferred, so the
    # pass writes/streams narrow ints instead of f32
    acc_dt = jnp.dtype(histogram_acc_dtype(N, True))
    qstats = stat_rows_quant(g_d, h_d, c_d, jax.random.PRNGKey(0))

    def loop_packed(u_, g_, h_, c_, node_):
        def body(i, acc):
            hist = build_histograms_u(u_, g_, h_, c_, node_ + (i % 2), KN,
                                      spec, stats=qstats, dequant=False)
            return acc + hist[0, 0, 0, 0].astype(jnp.int32)

        return lax.fori_loop(0, REPS, body, jnp.int32(0)).astype(jnp.float32)

    t_packed = timed(loop_packed, u8, g_d, h_d, c_d, node_d,
                     label=f"U pass (packed {acc_dt.name} accumulators)")

    # --- fused Pallas bin+scatter-add: reads RAW BINS once per pass (4F
    # B/row as i32 lanes) instead of re-streaming the K_pad-byte/row U.
    # Interpret mode is orders slower, so only time it on a real chip.
    t_scatter = None
    if jax.default_backend() in ("tpu", "axon"):
        from mmlspark_tpu.ops.pallas_histogram import (
            bin_scatter_fits_vmem,
            build_histograms_bin_scatter,
        )

        if bin_scatter_fits_vmem(spec.k_pad, F):
            def loop_scatter(bins_, g_, h_, c_, node_):
                def body(i, acc):
                    hist = build_histograms_bin_scatter(
                        bins_, g_, h_, c_, node_ + (i % 2), KN, spec,
                        stats=qstats, dequant=False,
                    )
                    return acc + hist[0, 0, 0, 0].astype(jnp.int32)

                return lax.fori_loop(
                    0, REPS, body, jnp.int32(0)
                ).astype(jnp.float32)

            t_scatter = timed(loop_scatter, bins_d, g_d, h_d, c_d, node_d,
                              label="fused bin+scatter-add (Pallas)")
        else:
            print("fused bin+scatter-add: K_pad exceeds the VMEM tile budget")
    else:
        print("fused bin+scatter-add: skipped (not a TPU backend; "
              "interpret-mode timing is not comparable)")

    # Analytic roofline: bytes of ROW-SIZED input each pass must re-stream
    # from HBM (the traffic the U/EFB/subtraction work targets). Stats rows
    # ride along at 12 B/row f32 (3 B/row int8 on the quant path); the U
    # path re-reads the resident K_pad x N int8 one-hot, the raw-bins paths
    # re-read the (N, F) bins.
    bytes_per_row_restream = {
        "compare_built": F * bins_d.dtype.itemsize + 12,
        "u": spec.k_pad + 12,
        "u_hoisted": spec.k_pad + 12,
        "u_packed": spec.k_pad + 3,
        "bin_scatter": 4 * F + 32,
        # per split level (2*KN children resolved): both-children streams
        # rows twice vs once under subtraction
        "split_level_both": 2 * (spec.k_pad + 12),
        "split_level_subtraction": spec.k_pad + 12,
    }

    # ONE JSON line (the bench.py artifact convention): headline numbers
    # plus the profiler section. Each profiled program is a REPS-iteration
    # fori_loop, so per-iteration timing/FLOPs = the program totals / REPS.
    snap = prof.snapshot()
    per_iter = {
        name: {
            "compile_s": f["compile_seconds"],
            "exec_ms_per_iter": (
                f["device_seconds"] / max(f["executions"], 1) / REPS * 1e3
            ),
            "flops_per_iter": f["flops"] / REPS,
            "bytes_per_iter": f["bytes_accessed"] / REPS,
        }
        for name, f in snap["functions"].items()
    }
    ms = {
        "compare_built": t_cmp, "u": t_u, "u_hoisted": t_uh,
        "split_level_both": t_both, "split_level_subtraction": t_sub,
        "u_packed": t_packed,
    }
    if t_scatter is not None:
        ms["bin_scatter"] = t_scatter
    print(json.dumps({
        "bench": "hist_u_ab",
        "n": N, "f": F, "b": B, "nodes": KN, "reps": REPS,
        "ms_per_pass": ms,
        "speedup_vs_compare_built": t_cmp / min(t_u, t_uh),
        "subtraction": {
            "speedup_per_split_level": t_both / t_sub,
            "children_built_per_split": 1,
        },
        "packed": {
            "acc_dtype": acc_dt.name,
            "acc_bytes_vs_f32": acc_dt.itemsize / 4,
        },
        "bytes_per_row_restream": bytes_per_row_restream,
        "profiler": dict(snap, per_iteration=per_iter),
    }))


if __name__ == "__main__":
    main()
