"""BASELINE config 2: ImageFeaturizer ResNet-50 images/sec/chip.

Warm on-device forward loop at 224x224 (the reference's ImageNet input),
input perturbed per iteration, synced by a small fetch — the same
measurement discipline as the other kernel benches. Weights do not affect
throughput; the trained-artifact flow is examples/zoo_transfer_learning.py.

    python benchmarks/image_featurizer_bench.py [batch] [reps]
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mmlspark_tpu.models import init_resnet, resnet_apply

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 64
REPS = int(sys.argv[2]) if len(sys.argv) > 2 else 50


def main():
    params = init_resnet(variant="resnet50", num_classes=1000)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(BATCH, 3, 224, 224)).astype(np.float32))
    pdev = jax.tree_util.tree_map(jnp.asarray, params)

    results = {}
    for dtype, name in ((jnp.bfloat16, "bf16"), (None, "f32")):
        @jax.jit
        def loop(p, xb):
            def body(i, acc):
                feats = resnet_apply(
                    p, xb * (1 + i.astype(jnp.float32) * 1e-9), cut=1,
                    dtype=dtype,
                )
                return acc + feats[0, 0].astype(jnp.float32)

            return lax.fori_loop(0, REPS, body, jnp.float32(0.0))

        np.asarray(loop(pdev, x))  # compile
        t0 = time.perf_counter()
        np.asarray(loop(pdev, x))
        dt = time.perf_counter() - t0
        ips = BATCH * REPS / dt
        results[name] = round(ips, 1)
        print(f"resnet50 224x224 b{BATCH} {name}: {ips:,.0f} images/sec/chip")

    out = {
        "metric": f"imagefeaturizer_resnet50_images_per_sec_{jax.default_backend()}",
        "value": results.get("bf16"),
        "unit": "images/sec/chip",
        "batch": BATCH,
        "f32": results.get("f32"),
    }
    print(json.dumps(out))
    with open(os.path.join(os.path.dirname(__file__),
                           "image_featurizer_bench.json"), "w") as f:
        json.dump(out, f)


if __name__ == "__main__":
    main()
