#!/usr/bin/env python
"""Chaos-under-load campaign against the serving fleet (CI: fleet-chaos).

Stands up the WHOLE serving fleet with real processes — RegistrationService,
N supervised replica processes (self-registering, heartbeating live load
metadata), the deadline-aware FleetRouter in front, and the FleetController
autoscaler — then runs a scripted campaign of closed-loop clients through
the router while the chaos escalates:

  warmup   light load; every reply checked against the committed model;
  ramp     enough closed-loop clients to saturate the starting fleet —
           heartbeat inflight/shed climbs, the autoscaler scales up;
  kill     a replica process is SIGKILL'd mid-load: the router eats the
           dead hops (failover, breaker), the supervisor respawns it, the
           registry lease expires it out of rotation — clients never see
           a non-shed 5xx;
  storm    a seeded ``http_storm`` fault plan (``MMLSPARK_TPU_FAULT_SEED``)
           injects synthetic 503s at the router->replica edge until the
           victim's breaker trips; MID-STORM a new model version is
           committed to the shared ModelStore and every replica hot-swaps
           live — observed from the client side as the predictions flip;
  poison   (``--malformed``) a seeded flood of malformed requests — torn
           JSON, schema violations, NaN payloads, each directed by a
           ``FaultPlan.malformed_request`` directive — is thrown at the
           router as one poison client: every reply must be a structured
           400 carrying X-Trace-Id until the per-client breaker trips
           into 429 shedding, healthy clients stay served throughout,
           and after the reset window the poison client is admitted
           again (the breaker releases);
  drain    load drops to zero and the autoscaler retires capacity back
           down to the floor, deregistering each victim first.

Everything lands in ``--out``: the shared event log (router + controller
+ every replica append to it), ``slo.json``/``slo.md`` (the
:class:`SLOReport` fold plus per-phase client stats and the campaign
verdict), and ``report.html`` (the history-server render, Fleet section
included). Exit 0 iff every campaign check passed.

Usage:
  python tools/loadgen.py --out /tmp/fleet-campaign --short
  python tools/loadgen.py --payload sar --policy consistent_hash
"""

import argparse
import json
import os
import random
import signal
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

# runnable both installed (CI) and straight from a checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

AFFINE_V1 = {"scale": 2.0, "bias": 0.0, "work_ms": 3.0}
AFFINE_V2 = {"scale": 3.0, "bias": 1.0, "work_ms": 3.0}
# latency-storm model for the --quality campaign: same affine map (so the
# prediction DISTRIBUTION is unchanged and only the input shift can drift)
# but every micro-batch stalls long enough to burn the p99 budget
QUALITY_SLOW = {"scale": 2.0, "bias": 0.0, "work_ms": 120.0}


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


class LoadClients:
    """Closed-loop client pool: each worker POSTs to the router, waits for
    the reply, and immediately sends the next request. Concurrency is the
    load knob; every outcome is recorded under the current phase label."""

    def __init__(self, url, deadline_ms=1500.0, payload="affine"):
        self.url = url
        self.deadline_ms = float(deadline_ms)
        self.payload = payload
        self.phase = "idle"
        #: covariate-shift knob for the "quality" payload: added to the
        #: FIRST feature only, so drift must land on input[0] and never
        #: on input[1]
        self.shift = 0.0
        self.records = []  # (phase, status, latency_s, input, output)
        self._lock = threading.Lock()
        self._workers = []  # (thread, stop_event)

    def _one(self, x):
        body = json.dumps({"input": x}).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={
                "Content-Type": "application/json",
                "X-Deadline-Ms": str(int(self.deadline_ms)),
            },
        )
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                data = json.loads(resp.read())
                status = resp.status
        except urllib.error.HTTPError as e:
            status, data = e.code, None
            e.read()
        except Exception:
            status, data = -1, None  # transport failure to the ROUTER itself
        latency = time.monotonic() - t0
        out = data.get("prediction") if isinstance(data, dict) else None
        with self._lock:
            self.records.append((self.phase, status, latency, x, out))
        return status, out

    def _worker(self, stop, worker_id):
        i = 0
        rng = random.Random(9000 + worker_id)  # per-worker, deterministic
        while not stop.is_set():
            if self.payload == "quality":
                x = [rng.gauss(self.shift, 1.0), rng.gauss(0.0, 1.0)]
            elif self.payload == "affine":
                x = float((worker_id * 7 + i) % 10)
            else:
                x = (worker_id * 7 + i) % 64
            self._one(x)
            i += 1

    def set_concurrency(self, n):
        while len(self._workers) > n:
            _, stop = self._workers.pop()
            stop.set()
        while len(self._workers) < n:
            stop = threading.Event()
            t = threading.Thread(
                target=self._worker, args=(stop, len(self._workers)),
                daemon=True, name=f"loadgen-{len(self._workers)}",
            )
            self._workers.append((t, stop))
            t.start()

    def stop(self):
        for _, stop in self._workers:
            stop.set()
        for t, _ in self._workers:
            t.join(timeout=10.0)
        self._workers.clear()

    def phase_stats(self):
        with self._lock:
            records = list(self.records)
        out = {}
        for phase, status, latency, _, _ in records:
            s = out.setdefault(phase, {
                "requests": 0, "ok": 0, "shed": 0, "errors_5xx": 0,
                "transport": 0, "latencies": [],
            })
            s["requests"] += 1
            if status == 200:
                s["ok"] += 1
                s["latencies"].append(latency)
            elif status == 429:
                s["shed"] += 1
            elif status >= 500:
                s["errors_5xx"] += 1
            elif status == -1:
                s["transport"] += 1
        for s in out.values():
            lat = sorted(s.pop("latencies"))
            s["p50_ms"] = round(_quantile(lat, 0.50) * 1e3, 2)
            s["p95_ms"] = round(_quantile(lat, 0.95) * 1e3, 2)
            s["p99_ms"] = round(_quantile(lat, 0.99) * 1e3, 2)
        return out


def _malformed_body(kind, i):
    """One poison payload of the FaultPlan-directed ``kind``: torn JSON,
    a schema violation (missing input column), or a non-finite value
    (parses fine; only the pre-admission validator can catch it)."""
    if kind == "json":
        return b'{"input": [1.0, not json'
    if kind == "schema":
        return json.dumps({"wrong_col": [float(i)]}).encode()
    return b'{"input": NaN}'


def _post_json(url, payload, client_id=None, timeout=5.0):
    headers = {"Content-Type": "application/json"}
    if client_id:
        headers["X-Client-Id"] = client_id
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers=headers,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, None
    except Exception:
        return -1, None


def _malformed_storm(url, plan, client_id="poison-client", enough_shed=4):
    """Drain the plan's ``malformed_request`` directives as one poison
    client: each directive's kind picks the payload shape. Every reply is
    classified — a structured 400 must carry an error kind + rid in the
    body AND an X-Trace-Id header; 429s are the per-client breaker
    shedding us. Stops early once ``enough_shed`` 429s are observed
    (post-trip requests crawl behind Retry-After honoring)."""
    stats = {"sent": 0, "accepted": 0, "s400": 0, "s429": 0,
             "structured_400": 0, "missing_trace": 0, "other": 0}
    while stats["s429"] < enough_shed:
        kind = plan.take_malformed()
        if kind is None:
            break
        body = _malformed_body(kind, stats["sent"])
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/json",
                     "X-Client-Id": client_id},
        )
        stats["sent"] += 1
        try:
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                resp.read()
                stats["accepted"] += 1
        except urllib.error.HTTPError as e:
            data = e.read()
            if not e.headers.get("X-Trace-Id"):
                stats["missing_trace"] += 1
            if e.code == 400:
                stats["s400"] += 1
                try:
                    err = json.loads(data).get("error")
                    if isinstance(err, dict) and err.get("kind") \
                            and err.get("rid"):
                        stats["structured_400"] += 1
                except (ValueError, AttributeError):
                    pass
            elif e.code == 429:
                stats["s429"] += 1
            else:
                stats["other"] += 1
        except Exception:
            stats["other"] += 1
    return stats


def run_campaign(args):
    from mmlspark_tpu import observability as obs
    from mmlspark_tpu.observability.federation import MetricsFederator
    from mmlspark_tpu.observability.registry import get_registry
    from mmlspark_tpu.observability.slo import SLOReport, SLOTargets
    from mmlspark_tpu.runtime.faults import FaultPlan, inject_faults
    from mmlspark_tpu.runtime.journal import ModelStore
    from mmlspark_tpu.serving.fleet import FleetController
    from mmlspark_tpu.serving.replicas import ReplicaSupervisor
    from mmlspark_tpu.serving.router import FleetRouter
    from mmlspark_tpu.serving.server import RegistrationService

    seed = int(os.environ.get("MMLSPARK_TPU_FAULT_SEED", str(args.seed)))
    short = args.short
    min_replicas, max_replicas = 2, (3 if short else 4)
    ramp_clients = 12 if short else 20
    dur = (lambda s, f: s if short else f)

    workdir = tempfile.mkdtemp(prefix="mmlspark-tpu-fleet-")
    store = ModelStore(os.path.join(workdir, "models"))
    if args.payload == "affine":
        store.commit(json.dumps(AFFINE_V1), name="model")
        factory = "mmlspark_tpu.serving.fleet:store_model_factory"
        hot_swap = {
            "loader": "mmlspark_tpu.serving.fleet:store_model_loader",
            "root": workdir, "name": "model", "poll_s": 0.2,
        }
    else:
        factory = "mmlspark_tpu.serving.fleet:sar_demo_factory"
        hot_swap = None

    registry = RegistrationService(ttl_s=2.0).start()
    sup = ReplicaSupervisor(
        factory,
        num_replicas=min_replicas,
        workdir=os.path.join(workdir, "replicas"),
        seed=seed,
        heartbeat_timeout_s=5.0,
        registry_url=registry.info.url,
        registry_heartbeat_s=0.2,
        hot_swap=hot_swap,
        server_options={
            "max_batch_size": 8, "max_latency_ms": 1.0,
            "max_pending": 32, "shed_retry_after_s": 0.05,
            # campaign-sized poison breaker: trips after a handful of
            # malformed requests, releases fast enough to re-probe
            "malformed_threshold": 6, "malformed_window_s": 10.0,
            "malformed_reset_s": 1.0,
        },
    )
    sup.start()
    deadline = time.monotonic() + 30.0
    while len(registry.services) < min_replicas:
        if time.monotonic() > deadline:
            raise TimeoutError("replicas never registered")
        time.sleep(0.1)

    router = FleetRouter(
        registry_url=registry.info.url, policy=args.policy,
        discovery_interval_s=0.1, hop_timeout_s=2.0,
    ).start()
    # federation: the controller steers on live /metrics scrapes instead
    # of heartbeat lag, and the flight recorder bundles the fleet snapshot
    federator = MetricsFederator(registry.info.url)
    recorder = obs.get_recorder()
    if recorder is not None:
        recorder.federator = federator
    controller = FleetController(
        sup, registry_url=registry.info.url, federator=federator,
        min_replicas=min_replicas, max_replicas=max_replicas,
        scale_up_inflight=1.5, scale_down_inflight=0.5,
        scale_up_shed_rate=1.0, cooldown_s=1.0,
        down_sustain_s=1.5, interval_s=0.2,
    ).start()

    clients = LoadClients(router.url, payload=args.payload)
    kill_windows = []
    checks = {}
    max_live = sup.live_count
    try:
        # -- warmup: light load, correctness spot-checks ---------------------
        clients.phase = "warmup"
        status, out = clients._one(4.0 if args.payload == "affine" else 4)
        assert status == 200, f"warmup request failed: {status}"
        if args.payload == "affine":
            want = AFFINE_V1["scale"] * 4.0 + AFFINE_V1["bias"]
            assert out == want, f"expected {want}, got {out}"
        else:
            assert isinstance(out, list) and len(out) == 5, out
        clients.set_concurrency(2)
        time.sleep(dur(2.0, 3.0))
        print(f"warmup: fleet={sup.live_count} first reply {out}")

        # -- ramp: saturate the floor fleet, watch the autoscaler ------------
        clients.phase = "ramp"
        clients.set_concurrency(ramp_clients)
        ramp_deadline = time.monotonic() + dur(8.0, 12.0)
        while time.monotonic() < ramp_deadline:
            max_live = max(max_live, sup.live_count)
            if max_live > min_replicas and time.monotonic() > \
                    ramp_deadline - dur(2.0, 3.0):
                break  # scaled; keep a little sustained post-scale load
            time.sleep(0.1)
        checks["scaled_up"] = max_live > min_replicas
        print(f"ramp: {ramp_clients} clients, fleet peaked at {max_live}")

        # -- kill: SIGKILL a replica under load ------------------------------
        clients.phase = "kill"
        victim = max(sup._procs)
        pid = sup._procs[victim].pid
        kill_start = time.monotonic()
        os.kill(pid, signal.SIGKILL)
        t0 = time.monotonic()
        while not any(s.reason == "signal:9" for s in sup.exit_statuses):
            if time.monotonic() - t0 > 30.0:
                raise TimeoutError("supervisor never booked the kill")
            time.sleep(0.1)  # controller.step() runs poll() for us
        time.sleep(dur(2.0, 4.0))  # lease expiry + respawn under load
        kill_windows.append((kill_start, time.monotonic()))
        checks["kill_respawned"] = any(
            s.reason == "signal:9" for s in sup.exit_statuses
        )
        print(f"kill: replica {victim} (pid {pid}) SIGKILL'd, "
              f"fleet now {sup.live_count}")

        # -- storm: injected 503s trip a breaker; hot swap mid-storm ---------
        clients.phase = "storm"
        target = registry.services[0]
        plan = FaultPlan(seed=seed).http_storm(
            count=12, status=503, url_part=f":{target.port}/",
        )
        swap_seen = False
        with inject_faults(plan):
            time.sleep(dur(1.0, 2.0))
            if args.payload == "affine":
                store.commit(json.dumps(AFFINE_V2), name="model")
                want = AFFINE_V2["scale"] * 4.0 + AFFINE_V2["bias"]
                swap_deadline = time.monotonic() + 15.0
                while time.monotonic() < swap_deadline:
                    s, out = clients._one(4.0)
                    if s == 200 and out == want:
                        swap_seen = True
                        break
                    time.sleep(0.1)
            else:
                time.sleep(dur(1.0, 2.0))
        breaker_trips = sum(
            1 for e in obs.replay(event_log_path())
            if type(e).__name__ == "BreakerTripped"
        )
        checks["storm_fired"] = bool(plan.fired)
        checks["hot_swap_observed"] = (
            swap_seen if args.payload == "affine" else None
        )
        print(f"storm: {len(plan.fired)} faults fired, "
              f"{breaker_trips} breaker trips, hot swap seen: {swap_seen}")
        # post-swap warm window, still labeled "storm" (excluded from the
        # steady fold): the swapped model's jitted apply recompiles per
        # batch shape, and the closed-loop load re-warms those shapes here
        # so the drain tail measures steady state, not cold compiles
        time.sleep(dur(2.0, 3.0))

        # -- poison: seeded malformed-request flood (--malformed) ------------
        if args.malformed:
            clients.phase = "poison"
            poison_plan = FaultPlan(seed=seed)
            for kind in ("json", "schema", "nan"):
                poison_plan.malformed_request(count=dur(8, 16), kind=kind)
            pstats = _malformed_storm(router.url, poison_plan)
            # a healthy client keeps being served while the poison client
            # is shed — the breaker is per X-Client-Id, not per replica
            s_h, _ = clients._one(4.0 if args.payload == "affine" else 4)
            # every tripped breaker must also RELEASE: after reset_s, a
            # valid request from the poison client probes each replica
            # directly (the router would stop at the first) so the
            # PoisonClientBlocked/Released event pairs all close
            time.sleep(1.2)
            released = 0
            for svc in list(registry.services):
                s_r, _ = _post_json(
                    svc.url,
                    {"input": 4.0 if args.payload == "affine" else 4},
                    client_id="poison-client",
                )
                released += 1 if s_r == 200 else 0
            checks["malformed_storm_fired"] = any(
                f[0] == "malformed_request" for f in poison_plan.fired
            )
            checks["malformed_none_accepted"] = pstats["accepted"] == 0
            checks["malformed_400s_structured"] = (
                pstats["s400"] > 0
                and pstats["structured_400"] == pstats["s400"]
                and pstats["missing_trace"] == 0
            )
            # the router retries 429s onto untripped replicas (and the
            # short reset window re-admits the client between hops), so
            # the CLIENT may never see a 429 even while replicas shed —
            # count the replica-side RequestShed events as well
            replica_sheds = sum(
                1 for e in obs.merge(event_log_path())
                if type(e).__name__ == "RequestShed"
                and getattr(e, "reason", "") == "malformed_rate"
            )
            checks["poison_breaker_shed"] = (
                pstats["s429"] + replica_sheds > 0
            )
            checks["poison_client_released"] = released > 0
            checks["healthy_during_poison"] = s_h == 200
            print(
                f"poison: {pstats['sent']} malformed sent -> "
                f"{pstats['s400']} structured 400s, {pstats['s429']} client "
                f"429s + {replica_sheds} replica shed(s), healthy probe "
                f"{s_h}, released on {released} replica(s)"
            )

        # -- drain: load off, autoscaler retires back to the floor -----------
        clients.phase = "drain"
        clients.set_concurrency(0)
        drain_deadline = time.monotonic() + dur(20.0, 30.0)
        while sup.live_count > min_replicas:
            if time.monotonic() > drain_deadline:
                break
            time.sleep(0.2)
        checks["scaled_down"] = sup.live_count == min_replicas
        print(f"drain: fleet back to {sup.live_count}")
    finally:
        clients.stop()
        controller.stop()
        router.stop()
        sup.stop()
        registry.stop()

    # -- fold ----------------------------------------------------------------
    # federate the per-process segments (router/controller in the driver
    # log, each replica in events.jsonl@replica-<i>) into one merged
    # fleet log — the file CI's check_eventlog validates
    merged_path = os.path.join(args.out, "fleet-events.jsonl")
    merged_count = obs.write_merged(event_log_path(), merged_path)
    events = obs.merge(event_log_path())
    segments = obs.collect(event_log_path())
    print(f"fleet log: {merged_count} events from "
          f"{len(segments)} processes -> {merged_path}")
    targets = SLOTargets()
    report = SLOReport.fold(None, events=events, targets=targets)
    if not report.ok():
        obs.maybe_record("slo_budget", detail=(
            f"campaign SLO missed: apply p50 {report.apply_p50_ms:.2f}ms "
            f"p99 {report.apply_p99_ms:.2f}ms, error budget "
            f"{report.error_budget_consumed:.1%}"
        ))
    phases = clients.phase_stats()
    non_shed_5xx = sum(s["errors_5xx"] for s in phases.values())
    transport = sum(s["transport"] for s in phases.values())
    steady = sorted(
        lat for phase, status, lat, _, _ in clients.records
        if status == 200 and phase not in ("kill", "storm", "poison")
    )
    steady_p99_ms = _quantile(steady, 0.99) * 1e3
    # the affine payload is judged against the docs/serving_latency.md
    # tail target; SAR's jitted top-k recompiles per distinct micro-batch
    # shape, so its cold-shape tails get a looser (still bounded) budget
    p99_target_ms = args.p99_target or (
        targets.p99_ms if args.payload == "affine" else 250.0
    )
    fleet_events = [e for e in events if type(e).__name__ == "FleetScaled"]
    routed = [e for e in events if type(e).__name__ == "RequestRouted"]

    # trace continuity over the merged log: every successfully served
    # routed request's trace id must resolve to spans from BOTH sides of
    # the wire — the router's root/hop spans and the replica's serving
    # spans, distinct processes under one trace id
    spans_by_trace = {}
    for e in events:
        if type(e).__name__ == "SpanRecorded":
            spans_by_trace.setdefault(e.trace_id, []).append(e)

    def _chain_ok(trace_id):
        spans = spans_by_trace.get(trace_id, [])
        names = {s.name for s in spans}
        procs = {getattr(s, "process", "") for s in spans}
        return (
            "router.request" in names
            and "serving.request" in names
            and len(procs) >= 2
        )

    served_routed = [e for e in routed if e.status == 200 and e.trace_id]
    broken = [e.trace_id for e in served_routed if not _chain_ok(e.trace_id)]
    checks["trace_continuity"] = bool(served_routed) and not broken
    if broken:
        print(f"trace continuity broken for {len(broken)} of "
              f"{len(served_routed)} traces (e.g. {broken[:3]})")

    incident_dir = os.environ.get("MMLSPARK_TPU_INCIDENT_DIR", "")
    bundles = sorted(
        d for d in (os.listdir(incident_dir) if os.path.isdir(incident_dir)
                    else [])
        if not d.startswith(".")
    )
    checks["incident_recorded"] = bool(bundles)
    print(f"incidents: {len(bundles)} bundle(s) in {incident_dir}")

    checks["zero_non_shed_5xx"] = non_shed_5xx == 0 and transport == 0
    checks["steady_p99_within_target"] = steady_p99_ms <= p99_target_ms
    checks["fleet_events_logged"] = len(fleet_events) >= 2
    checks["routing_events_logged"] = len(routed) > 0
    checks["slo_ok"] = report.ok()
    ok = all(v for v in checks.values() if v is not None)

    campaign = {
        "seed": seed,
        "payload": args.payload,
        "policy": args.policy,
        "min_replicas": min_replicas,
        "max_replicas": max_replicas,
        "max_live": max_live,
        "steady_p99_ms": round(steady_p99_ms, 2),
        "p99_target_ms": p99_target_ms,
        "non_shed_5xx": non_shed_5xx,
        "router_transport_failures": transport,
        "fleet_scaled": [
            {"direction": e.direction, "replicas": e.replicas,
             "reason": e.reason} for e in fleet_events
        ],
        "requests_routed": len(routed),
        "merged_events": merged_count,
        "processes": sorted(segments),
        "traces_served": len(served_routed),
        "incident_bundles": bundles,
        "kill_windows_s": [round(b - a, 2) for a, b in kill_windows],
        "phases": phases,
        "checks": checks,
        "ok": ok,
    }

    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "slo.json"), "w") as fh:
        json.dump({"slo": report.to_dict(), "campaign": campaign}, fh,
                  indent=2, sort_keys=True)
    md = [
        f"Chaos-under-load campaign: payload={args.payload} "
        f"policy={args.policy} seed={seed} "
        f"fleet {min_replicas}..{max_replicas} (peak {max_live}).",
        "",
        report.to_markdown(),
        "",
        "| phase | requests | ok | shed | 5xx | p50 | p99 |",
        "|---|---|---|---|---|---|---|",
    ]
    for phase in ("warmup", "ramp", "kill", "storm", "poison", "drain"):
        s = phases.get(phase)
        if s is None:
            continue
        md.append(
            f"| {phase} | {s['requests']} | {s['ok']} | {s['shed']} "
            f"| {s['errors_5xx']} | {s['p50_ms']:.2f} ms "
            f"| {s['p99_ms']:.2f} ms |"
        )
    md += [
        "",
        "| check | result |",
        "|---|---|",
    ]
    md += [
        f"| {name} | {'pass' if v else 'FAIL'} |"
        for name, v in checks.items() if v is not None
    ]
    with open(os.path.join(args.out, "slo.md"), "w") as fh:
        fh.write("\n".join(md) + "\n")
    from mmlspark_tpu.observability.history import render_report

    with open(os.path.join(args.out, "report.html"), "w") as fh:
        fh.write(render_report(
            events, metrics=get_registry().summary(),
            title="serving fleet chaos campaign",
        ))

    print("\n".join(md))
    print(f"\ncampaign {'OK' if ok else 'FAILED'}; "
          f"artifacts in {args.out}")
    return 0 if ok else 1


def measure_bare_overhead(rows=1 << 20, iters=10, repeats=7):
    """The <5% ambient-gate guard: time ``PipelineModel.transform`` —
    which carries the tracing AND quality gates — against the raw stage
    loop (``ml_transform``) over the same table, and return the overhead
    in percent. Rounds interleave the two paths and each takes its
    best-of-``repeats`` so scheduler noise cancels instead of landing on
    one side. Must run before the campaign exports
    ``MMLSPARK_TPU_QUALITY_*`` so this process measures the bare,
    unconfigured posture every production transform pays."""
    import numpy as np

    from mmlspark_tpu.core.pipeline import (
        Transformer,
        make_pipeline_model,
        ml_transform,
    )
    from mmlspark_tpu.data.table import Table

    class _Affine(Transformer):
        def transform(self, table):
            x = np.asarray(table.column("input"), dtype=np.float64)
            return Table({"input": x, "prediction": x * 2.0 + 1.0})

    stage = _Affine()
    model = make_pipeline_model(stage)
    table = Table({"input": np.arange(rows, dtype=np.float64)})

    def timed(fn):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        return time.perf_counter() - t0

    ml_transform(table, stage)  # warm both paths
    model.transform(table)
    bare = gated = float("inf")
    for _ in range(repeats):
        bare = min(bare, timed(lambda: ml_transform(table, stage)))
        gated = min(gated, timed(lambda: model.transform(table)))
    return max(0.0, (gated - bare) / bare * 100.0)


def run_quality_campaign(args):
    """Model-quality campaign (CI: quality-chaos): the same real-process
    fleet, judged by the live quality plane end to end. A fit-time
    reference profile is committed next to model version 1; every replica
    installs a QualityMonitor from the inherited environment and sketches
    its own traffic; the driver runs the multi-window burn-rate
    AlertEvaluator over federated scrapes (wired into the FleetController
    as the scale-down advisory). The chaos is a seeded covariate-shift
    storm on input[0] only, then a latency storm hot-swapped in as a
    slow model version. Verdicts: drift fires on the shifted feature and
    never the stable one, onset/recovery events pair up, alerts fire in
    the storm and resolve after it, the incident bundle carries a drift
    table, and the bare-transform ambient gate stays under 5%."""
    from mmlspark_tpu import observability as obs
    from mmlspark_tpu.observability.alerts import AlertEvaluator
    from mmlspark_tpu.observability.federation import MetricsFederator
    from mmlspark_tpu.observability.quality import ReferenceProfile
    from mmlspark_tpu.observability.registry import get_registry
    from mmlspark_tpu.observability.slo import (
        SLOReport,
        SLOTargets,
        fleet_summary,
    )
    from mmlspark_tpu.runtime.journal import ModelStore
    from mmlspark_tpu.serving.fleet import FleetController
    from mmlspark_tpu.serving.replicas import ReplicaSupervisor
    from mmlspark_tpu.serving.router import FleetRouter
    from mmlspark_tpu.serving.server import RegistrationService

    seed = int(os.environ.get("MMLSPARK_TPU_FAULT_SEED", str(args.seed)))
    checks = {}

    overhead_pct = measure_bare_overhead()
    checks["bare_overhead_under_5pct"] = overhead_pct < 5.0
    print(f"bare-transform gate overhead: {overhead_pct:.2f}% (budget 5%)")

    workdir = tempfile.mkdtemp(prefix="mmlspark-tpu-quality-")
    store = ModelStore(os.path.join(workdir, "models"))
    store.commit(json.dumps(AFFINE_V1), name="model")  # version 1

    # fit-time reference for version 1: both features standard normal,
    # predictions the committed affine map of them
    rng = random.Random(seed)
    ref_rows = [[rng.gauss(0.0, 1.0), rng.gauss(0.0, 1.0)]
                for _ in range(768)]
    ref_preds = [
        [AFFINE_V1["scale"] * a + AFFINE_V1["bias"],
         AFFINE_V1["scale"] * b + AFFINE_V1["bias"]]
        for a, b in ref_rows
    ]
    ReferenceProfile.capture(
        "model", 1, {"input": ref_rows, "prediction": ref_preds}
    ).commit(store)

    # exported BEFORE the supervisor snapshots its spawn environment:
    # every replica self-installs a monitor against the shared store.
    # CI-sized window so the campaign turns it over within seconds; the
    # min-window floor keeps small-sample PSI noise from false-firing.
    os.environ["MMLSPARK_TPU_QUALITY_STORE"] = os.path.join(workdir, "models")
    os.environ["MMLSPARK_TPU_QUALITY_MODEL"] = "model"
    os.environ["MMLSPARK_TPU_QUALITY_WINDOW"] = "256"
    os.environ["MMLSPARK_TPU_QUALITY_EVAL_EVERY"] = "32"
    os.environ["MMLSPARK_TPU_QUALITY_MIN_WINDOW"] = "192"

    min_replicas, max_replicas = 2, 3
    registry_svc = RegistrationService(ttl_s=2.0).start()
    sup = ReplicaSupervisor(
        "mmlspark_tpu.serving.fleet:store_model_factory",
        num_replicas=min_replicas,
        workdir=os.path.join(workdir, "replicas"),
        seed=seed,
        heartbeat_timeout_s=5.0,
        registry_url=registry_svc.info.url,
        registry_heartbeat_s=0.2,
        hot_swap={
            "loader": "mmlspark_tpu.serving.fleet:store_model_loader",
            "root": workdir, "name": "model", "poll_s": 0.2,
        },
        server_options={
            "max_batch_size": 8, "max_latency_ms": 1.0,
            "max_pending": 32, "shed_retry_after_s": 0.05,
        },
    )
    sup.start()
    deadline = time.monotonic() + 30.0
    while len(registry_svc.services) < min_replicas:
        if time.monotonic() > deadline:
            raise TimeoutError("replicas never registered")
        time.sleep(0.1)

    router = FleetRouter(
        registry_url=registry_svc.info.url, policy=args.policy,
        discovery_interval_s=0.1, hop_timeout_s=2.0,
    ).start()
    federator = MetricsFederator(registry_svc.info.url)
    recorder = obs.get_recorder()
    if recorder is not None:
        recorder.federator = federator
    # the live alerting edge, on CI timescales (2 s / 8 s windows)
    targets = SLOTargets()
    evaluator = AlertEvaluator(
        targets=targets,
        source=lambda: fleet_summary(federator.scrape()),
        windows=(2.0, 8.0), threshold=1.0,
    ).start(interval_s=0.5)
    controller = FleetController(
        sup, registry_url=registry_svc.info.url, federator=federator,
        min_replicas=min_replicas, max_replicas=max_replicas,
        scale_up_inflight=4.0, scale_down_inflight=0.5,
        scale_up_shed_rate=4.0, cooldown_s=1.0,
        down_sustain_s=1.5, interval_s=0.2,
        alert_advisor=evaluator.active_alerts,
    ).start()

    clients = LoadClients(router.url, payload="quality")

    def quality_events(kind):
        return [e for e in obs.merge(event_log_path())
                if type(e).__name__ == kind]

    def wait_for(predicate, timeout_s, what):
        stop_at = time.monotonic() + timeout_s
        while time.monotonic() < stop_at:
            if predicate():
                return True
            time.sleep(0.5)
        print(f"timeout waiting for {what}")
        return False

    def all_cleared():
        det = {e.feature for e in quality_events("DriftDetected")}
        clr = {e.feature for e in quality_events("DriftCleared")}
        return bool(det) and det <= clr

    try:
        # -- warmup: correctness probe, then span the long alert window -----
        clients.phase = "warmup"
        status, out = clients._one([1.0, -1.0])
        assert status == 200, f"warmup request failed: {status}"
        want = [AFFINE_V1["scale"] * 1.0 + AFFINE_V1["bias"],
                AFFINE_V1["scale"] * -1.0 + AFFINE_V1["bias"]]
        assert out == want, f"expected {want}, got {out}"
        clients.set_concurrency(2)
        time.sleep(9.0)
        checks["no_false_drift"] = not quality_events("DriftDetected")
        checks["no_false_alert"] = not evaluator.active_alerts()
        print(f"warmup: fleet={sup.live_count}, reply {out}, "
              f"false drift/alerts: none" if checks["no_false_drift"]
              else f"warmup: FALSE drift {quality_events('DriftDetected')}")

        # -- shift: seeded covariate storm on input[0] only ------------------
        clients.phase = "shift"
        clients.shift = 4.0
        clients.set_concurrency(4)
        checks["drift_detected_on_shifted"] = wait_for(
            lambda: any(e.feature == "input[0]"
                        for e in quality_events("DriftDetected")),
            30.0, "DriftDetected(input[0])",
        )
        print("shift: drift onsets on "
              f"{sorted({e.feature for e in quality_events('DriftDetected')})}")

        # -- storm: slow model hot-swapped in burns the latency budget -------
        clients.phase = "storm"
        store.commit(json.dumps(QUALITY_SLOW), name="model")  # version 2
        checks["alert_fired_in_storm"] = wait_for(
            lambda: "latency" in evaluator.active_alerts(),
            25.0, "AlertFired(latency)",
        )
        print(f"storm: active alerts {evaluator.active_alerts()}")

        # -- recover: fast model back, shift off; every onset must pair ------
        clients.phase = "recover"
        store.commit(json.dumps(AFFINE_V1), name="model")  # version 3
        clients.shift = 0.0
        checks["alert_resolved"] = (
            checks["alert_fired_in_storm"]
            and wait_for(lambda: not evaluator.active_alerts(),
                         30.0, "AlertResolved")
        )
        checks["drift_cleared"] = (
            checks["drift_detected_on_shifted"]
            and wait_for(all_cleared, 60.0, "DriftCleared for every onset")
        )
        time.sleep(2.0)  # settle: no late re-fire may leave an open pair
        checks["drift_cleared"] = checks["drift_cleared"] and all_cleared()

        # -- drain -----------------------------------------------------------
        clients.phase = "drain"
        clients.set_concurrency(0)
        time.sleep(1.0)
    finally:
        clients.stop()
        controller.stop()
        evaluator.stop()
        router.stop()
        sup.stop()
        registry_svc.stop()

    # -- fold ----------------------------------------------------------------
    merged_path = os.path.join(args.out, "quality-events.jsonl")
    merged_count = obs.write_merged(event_log_path(), merged_path)
    events = obs.merge(event_log_path())
    segments = obs.collect(event_log_path())
    print(f"quality log: {merged_count} events from "
          f"{len(segments)} processes -> {merged_path}")
    report = SLOReport.fold(None, events=events, targets=targets)
    phases = clients.phase_stats()
    non_shed_5xx = sum(s["errors_5xx"] for s in phases.values())
    transport = sum(s["transport"] for s in phases.values())
    checks["zero_non_shed_5xx"] = non_shed_5xx == 0 and transport == 0

    detected = sorted({
        e.feature for e in events if type(e).__name__ == "DriftDetected"
    })
    checks["no_drift_on_stable"] = not any(
        f in ("input[1]", "prediction[1]") for f in detected
    )
    checks["alert_events_paired"] = (
        any(type(e).__name__ == "AlertFired" for e in events)
        and any(type(e).__name__ == "AlertResolved" for e in events)
    )

    incident_dir = os.environ.get("MMLSPARK_TPU_INCIDENT_DIR", "")
    bundles = sorted(
        d for d in (os.listdir(incident_dir) if os.path.isdir(incident_dir)
                    else [])
        if not d.startswith(".")
    )
    quality_bundles = []
    for b in bundles:
        try:
            with open(os.path.join(incident_dir, b, "quality.json")) as fh:
                if json.load(fh).get("drift"):
                    quality_bundles.append(b)
        except (OSError, ValueError):
            continue
    checks["bundle_has_drift_table"] = bool(quality_bundles)
    print(f"incidents: {len(bundles)} bundle(s), "
          f"{len(quality_bundles)} with a drift table")
    ok = all(v for v in checks.values() if v is not None)

    campaign = {
        "seed": seed,
        "payload": "quality",
        "policy": args.policy,
        "min_replicas": min_replicas,
        "max_replicas": max_replicas,
        "bare_overhead_pct": round(overhead_pct, 3),
        "drifted_features": detected,
        "active_alerts_at_exit": list(evaluator.active_alerts()),
        "non_shed_5xx": non_shed_5xx,
        "router_transport_failures": transport,
        "merged_events": merged_count,
        "processes": sorted(segments),
        "incident_bundles": bundles,
        "quality_bundles": quality_bundles,
        "phases": phases,
        "checks": checks,
        "ok": ok,
    }

    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "slo.json"), "w") as fh:
        json.dump({"slo": report.to_dict(), "campaign": campaign}, fh,
                  indent=2, sort_keys=True)
    md = [
        f"Model-quality campaign: seed={seed} fleet {min_replicas}"
        f"..{max_replicas}, shift storm on input[0], "
        f"latency storm work_ms={QUALITY_SLOW['work_ms']:g}.",
        "",
        report.to_markdown(),
        "",
        "| check | result |",
        "|---|---|",
    ]
    md += [
        f"| {name} | {'pass' if v else 'FAIL'} |"
        for name, v in checks.items() if v is not None
    ]
    with open(os.path.join(args.out, "slo.md"), "w") as fh:
        fh.write("\n".join(md) + "\n")
    from mmlspark_tpu.observability.history import render_report

    with open(os.path.join(args.out, "report.html"), "w") as fh:
        fh.write(render_report(
            events, metrics=get_registry().summary(),
            title="model-quality chaos campaign",
        ))

    print("\n".join(md))
    print(f"\ncampaign {'OK' if ok else 'FAILED'}; artifacts in {args.out}")
    return 0 if ok else 1


def event_log_path():
    return os.environ["MMLSPARK_TPU_EVENT_LOG"]


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="tools/loadgen.py",
        description="Chaos-under-load campaign against the serving fleet.",
    )
    parser.add_argument("--out", default="fleet-campaign",
                        help="artifact directory (slo.json, slo.md, "
                             "report.html, events.jsonl)")
    parser.add_argument("--seed", type=int, default=11,
                        help="fault seed (MMLSPARK_TPU_FAULT_SEED wins)")
    parser.add_argument("--payload", choices=("affine", "sar"),
                        default="affine",
                        help="campaign model: hot-swappable affine, or "
                             "SAR top-k recommendation")
    parser.add_argument("--policy", choices=("least_loaded",
                                             "consistent_hash"),
                        default="least_loaded")
    parser.add_argument("--p99-target", type=float, default=None,
                        help="steady-state p99 budget in ms (default: the "
                             "SLO target for affine, 250 for sar)")
    parser.add_argument("--short", action="store_true",
                        help="CI-sized campaign (~30 s)")
    parser.add_argument("--quality", action="store_true",
                        help="model-quality campaign instead: covariate-"
                             "shift + latency storms judged by the "
                             "drift/alert plane (CI: quality-chaos)")
    parser.add_argument("--malformed", action="store_true",
                        help="add a poison phase: a seeded malformed-"
                             "request flood (torn JSON / schema violation "
                             "/ NaN payloads) that must be answered with "
                             "structured, traced 400s and per-client 429 "
                             "shedding while healthy clients keep being "
                             "served (CI: data-chaos)")
    args = parser.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    # shared across the router, the controller, and every replica process;
    # truncate so a re-run into the same --out folds only its own campaign.
    # Each replica writes its own events.jsonl@replica-<i> segment; clear
    # stale ones too or the merge would federate a previous run's ghosts.
    log = os.path.abspath(os.path.join(args.out, "events.jsonl"))
    open(log, "w").close()
    import glob

    for stale in glob.glob(glob.escape(log) + "@*"):
        os.unlink(stale)
    os.environ["MMLSPARK_TPU_EVENT_LOG"] = log
    os.environ.setdefault(
        "MMLSPARK_TPU_INCIDENT_DIR",
        os.path.abspath(os.path.join(args.out, "incidents")),
    )
    if args.quality:
        return run_quality_campaign(args)
    return run_campaign(args)


if __name__ == "__main__":
    sys.exit(main())
