#!/usr/bin/env python
"""Kill-and-restart smoke for the streaming micro-batch engine (CI:
streaming-chaos).

A child process runs a checkpointed :class:`StreamingQuery` (file source,
``max_per_trigger=1`` -> one epoch per chunk) whose :class:`ModelCommitSink`
incrementally fits a LightGBM model. The parent SIGKILLs the child at BOTH
designated crash windows — ``post_wal`` (epoch planned + logged, nothing
processed) and ``pre_commit`` (sink ran and journaled, commit log missing) —
via the ambient :class:`FaultPlan`'s ``kill_stream`` directive, then restarts.
The headline exactly-once invariants, asserted against an undisturbed
reference run:

  * every epoch lands in the commit log exactly once;
  * a journaled epoch is NEVER refitted across restarts (the fit journal
    holds exactly one record per epoch over all runs combined);
  * the final ModelStore version AND model bytes equal the undisturbed
    run's — no skipped, duplicated, or double-applied epoch anywhere;
  * a warm-restarted server serves that same version.

Exit code 0 + "streaming chaos smoke OK" on success.

Usage: python tools/streaming_chaos_smoke.py                 # the smoke
       python tools/streaming_chaos_smoke.py --child R I [E P]  # victim
"""

import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import zlib

# runnable both installed (CI) and straight from a checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

NUM_CHUNKS = 4
MODEL = "chaos"


def make_chunks(incoming: str) -> None:
    rng = np.random.default_rng(11)
    os.makedirs(incoming, exist_ok=True)
    for i in range(NUM_CHUNKS):
        X = rng.normal(size=(60, 4))
        y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
        final = os.path.join(incoming, f"part-{i:05d}.npz")
        np.savez(final + ".tmp.npz", features=X, label=y)
        os.rename(final + ".tmp.npz", final)


def run_child(root: str, incoming: str, kill_epoch=None, kill_point=None) -> None:
    """One (re)start of the query; dies mid-epoch when a kill is armed."""
    os.environ["MMLSPARK_TPU_CHECKPOINT_DIR"] = root
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.runtime.faults import FaultPlan, inject_faults
    from mmlspark_tpu.streaming import (
        FileStreamSource,
        ModelCommitSink,
        StreamingQuery,
    )

    source = FileStreamSource(incoming, pattern="part-*.npz", max_per_trigger=1)
    sink = ModelCommitSink(
        lambda: LightGBMClassifier(numIterations=4, numLeaves=7, seed=5),
        name=MODEL,
    )
    query = StreamingQuery(source, sink, name="chaos")
    plan = FaultPlan()
    if kill_epoch is not None:
        plan.kill_stream(int(kill_epoch), kill_point)
    with inject_faults(plan):
        query.process_all_available()
    sink.close()


def spawn(root: str, incoming: str, kill=None) -> subprocess.Popen:
    argv = [sys.executable, os.path.abspath(__file__), "--child", root, incoming]
    if kill is not None:
        argv += [str(kill[0]), kill[1]]
    return subprocess.Popen(argv, env={**os.environ, "JAX_PLATFORMS": "cpu"})


def final_state(root: str):
    """(version, crc32-of-model-text, committed epochs, journal epochs)."""
    from mmlspark_tpu.runtime.journal import ModelStore

    store = ModelStore(os.path.join(root, "models"))
    version, text = store.latest(MODEL)
    commits = sorted(
        int(os.path.basename(p)[:-5])
        for p in glob.glob(os.path.join(root, "streaming", "chaos", "commits", "*.json"))
    )
    journal_epochs = []
    for path in glob.glob(os.path.join(root, "streaming-models", "**", "journal.jsonl"),
                          recursive=True):
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                if line.strip():
                    journal_epochs.append(int(json.loads(line)["task"]))
    return version, zlib.crc32(text.encode()), commits, sorted(journal_epochs)


def main() -> int:
    work = tempfile.mkdtemp(prefix="mmlspark-tpu-streamchaos-")
    incoming = os.path.join(work, "incoming")
    make_chunks(incoming)

    # undisturbed reference run (own checkpoint root, fresh process)
    ref_root = os.path.join(work, "ref")
    child = spawn(ref_root, incoming)
    assert child.wait(timeout=600) == 0, "undisturbed run failed"
    ref_version, ref_crc, ref_commits, ref_journal = final_state(ref_root)
    assert ref_commits == list(range(NUM_CHUNKS)), ref_commits
    print(f"undisturbed run: v{ref_version:06d} crc={ref_crc:08x} "
          f"epochs={ref_commits}")

    # chaos run: die at post_wal of epoch 1, restart, die at pre_commit of
    # epoch 2, restart, finish — both crash windows, one checkpoint
    chaos_root = os.path.join(work, "chaos")
    for kill in [(1, "post_wal"), (2, "pre_commit")]:
        child = spawn(chaos_root, incoming, kill=kill)
        child.wait(timeout=600)
        assert child.returncode == -signal.SIGKILL, (
            f"expected SIGKILL death at {kill}, got rc={child.returncode}"
        )
        print(f"child SIGKILLed at epoch {kill[0]} ({kill[1]})")
    child = spawn(chaos_root, incoming)
    assert child.wait(timeout=600) == 0, "final restart failed"

    version, crc, commits, journal = final_state(chaos_root)
    print(f"chaos run:       v{version:06d} crc={crc:08x} epochs={commits}")
    assert commits == list(range(NUM_CHUNKS)), (
        f"each epoch must commit exactly once: {commits}"
    )
    assert journal == list(range(NUM_CHUNKS)), (
        f"a journaled epoch was refitted (or skipped): {journal}"
    )
    assert (version, crc) == (ref_version, ref_crc), (
        f"diverged from undisturbed run: v{version} crc={crc:08x} "
        f"!= v{ref_version} crc={ref_crc:08x}"
    )

    # the serving plane recovers the identical version after the chaos
    os.environ["MMLSPARK_TPU_CHECKPOINT_DIR"] = chaos_root
    from mmlspark_tpu.lightgbm import LightGBMClassificationModel
    from mmlspark_tpu.serving import recover_model, warm_restart_server

    recovered = recover_model(
        LightGBMClassificationModel.from_model_string, name=MODEL
    )
    assert recovered is not None and recovered[0] == ref_version
    server = warm_restart_server(
        LightGBMClassificationModel.from_model_string, name=MODEL
    )
    try:
        assert server.model_version == ref_version
        assert server.info.model_version == ref_version
    finally:
        server._httpd.server_close()
    print(f"warm restart serves v{server.model_version:06d} "
          f"(matches undisturbed run)")
    print("streaming chaos smoke OK")
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--child":
        kill = sys.argv[4:6]
        run_child(
            sys.argv[2], sys.argv[3],
            kill_epoch=kill[0] if kill else None,
            kill_point=kill[1] if kill else None,
        )
        sys.exit(0)
    sys.exit(main())
