"""Vendor a Forest Covertype sample for the ``gbdt_real_*`` bench block.

Covertype (Blackard & Dean, UCI) is the canonical Exclusive-Feature-
Bundling dataset: 10 continuous columns plus 44 one-hot indicator columns
(4 wilderness areas + 40 soil types) that bundle down to 2 dense columns.
This script downloads it ONCE via sklearn's ``fetch_covtype``, takes a
shuffled sample, binarizes the label the standard way (class 2 — lodgepole
pine, ~49% of rows — vs rest), and writes
``tests/fixtures/covtype_sample.npz`` with ``X`` (float32) and ``y``
(uint8). ``bench.py`` picks the fixture up automatically and labels the
``gbdt_real_*`` block ``covtype_sample``; without it the block falls back
to sklearn's bundled digits.

Network-gated: the download needs outbound HTTPS. In a network-less
container the script exits 2 with a message instead of a stack trace,
and records the failed attempt in ``covtype_fetch_attempt.json`` next
to the fixture target so ``bench.py`` can label the digits fallback
with *why* it is a fallback — run it once on a connected host and
commit/copy the npz.

Usage::

    python tools/fetch_covtype.py [--rows 100000] [--seed 0]
"""

import argparse
import json
import os
import sys
from datetime import datetime, timezone


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--rows", type=int, default=100_000,
        help="sample size (full dataset is 581,012 rows)",
    )
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            os.pardir, "tests", "fixtures", "covtype_sample.npz",
        ),
    )
    args = ap.parse_args()

    import numpy as np

    try:
        from sklearn.datasets import fetch_covtype

        data = fetch_covtype(shuffle=False)
    except Exception as e:  # URLError / socket errors / HTTP failures
        print(
            "covtype download failed (this script needs network access; "
            f"run it on a connected host): {e}",
            file=sys.stderr,
        )
        attempt = os.path.join(
            os.path.dirname(os.path.abspath(args.out)),
            "covtype_fetch_attempt.json",
        )
        os.makedirs(os.path.dirname(attempt), exist_ok=True)
        with open(attempt, "w") as f:
            json.dump(
                {
                    "attempted_at": datetime.now(timezone.utc).isoformat(),
                    "error": f"{type(e).__name__}: {e}",
                    "rows_requested": args.rows,
                    "seed": args.seed,
                },
                f,
                indent=2,
            )
        print(f"attempt recorded at {attempt}", file=sys.stderr)
        return 2

    X = np.asarray(data.data, dtype=np.float32)
    y = (np.asarray(data.target) == 2).astype(np.uint8)  # lodgepole vs rest
    rng = np.random.default_rng(args.seed)
    idx = rng.permutation(len(X))[: args.rows]
    out = os.path.abspath(args.out)
    os.makedirs(os.path.dirname(out), exist_ok=True)
    np.savez_compressed(out, X=X[idx], y=y[idx])
    stale = os.path.join(os.path.dirname(out), "covtype_fetch_attempt.json")
    if os.path.exists(stale):
        os.remove(stale)
    print(
        f"wrote {out}: X={X[idx].shape} y positive rate "
        f"{float(y[idx].mean()):.3f}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
