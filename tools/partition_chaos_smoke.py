#!/usr/bin/env python
"""Network-partition chaos smoke (CI: partition-chaos).

Runs the partition-tolerance tentpole end to end with REAL processes,
driven by the seeded fault plan (``MMLSPARK_TPU_FAULT_SEED`` pins every
chaos decision):

  1. a clean 2-process histogram-allreduce fit — the baseline model;
  2. the same fit with a ``net_corrupt`` directive: one garbled collective
     frame is caught by the CRC framing and absorbed by a bounded
     retransmit — same epoch count, model BITWISE identical;
  3. the same fit with a ``net_partition`` directive under the default
     health policy: both sides hit the collective io deadline (no hang),
     the driver collects the revoked reports, votes the partitioned
     member off, quarantines it (partition weight >= threshold), the
     gang SHRINKS and the fit resumes from the shared journal;
  4. the partition again under a lenient health tracker: the victim is
     respawned instead of dropped, the re-formed gang has the original
     membership, and the resumed model is BITWISE identical to baseline;
  5. a serving fleet under a registry OUTAGE: router + replicas keep
     serving from the last-known-good table (zero non-shed 5xx for the
     whole window), and a restarted registry recovers the journaled
     leases (``LeaseRecovered``) without any replica re-registering from
     scratch.

The driver event log is validated with ``tools/check_eventlog.py
--partition`` (every ``NetworkPartitioned`` onset must pair with a later
``GroupReformed``).

Exit code 0 + "partition chaos smoke OK" on success.
"""

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

# runnable both installed (CI) and straight from a checkout
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

NUM_PROCESSES = 2
NUM_ITERATIONS = 6
PARTITION_AFTER_ROUND = 2
OUTAGE_WINDOW_S = 2.0


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else None)


def chaos_fit(event_log: str) -> None:
    import numpy as np

    from mmlspark_tpu.lightgbm.procfit import (
        fit_process_group,
        model_texts_close,
    )
    from mmlspark_tpu.lightgbm.train import TrainOptions
    from mmlspark_tpu.runtime.faults import FaultPlan
    from mmlspark_tpu.runtime.health import HealthTracker

    seed = int(os.environ.get("MMLSPARK_TPU_FAULT_SEED", "11"))
    rng = np.random.default_rng(7)
    n = 400
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] + rng.normal(scale=0.4, size=n) > 0).astype(
        np.float32
    )
    opts = TrainOptions(
        objective="binary", num_iterations=NUM_ITERATIONS, num_leaves=7,
        max_bin=32, min_data_in_leaf=5, seed=2,
    )
    gopts = {"epoch_timeout_s": 180.0, "io_timeout_s": 5.0}

    baseline = fit_process_group(
        X, y, opts, num_processes=NUM_PROCESSES, group_options=dict(gopts),
    )
    assert baseline.epochs == 1, baseline.epochs
    print(f"baseline fit: {baseline.iterations} iterations, 1 epoch")

    # -- scenario A: corrupt frame absorbed by the CRC retransmit ------------
    plan = FaultPlan(seed=seed).net_corrupt(1, n=1, epoch=0)
    absorbed = fit_process_group(
        X, y, opts, num_processes=NUM_PROCESSES,
        group_options={**gopts, "faults": plan},
    )
    assert absorbed.model_text == baseline.model_text, (
        "corrupt-absorbed fit diverged from the undisturbed fit"
    )
    assert absorbed.epochs == 1, absorbed.epochs
    assert [f[0] for f in plan.fired] == ["net_corrupt"], plan.fired
    print("scenario A: one garbled collective frame absorbed by CRC "
          "retransmit, model bitwise-identical, no re-formation")

    # -- scenario B: partition -> revoke -> quarantine -> gang shrink --------
    plan = FaultPlan(seed=seed).net_partition(
        0, 1, epoch=0, after_round=PARTITION_AFTER_ROUND
    )
    shrunk = fit_process_group(
        X, y, opts, num_processes=NUM_PROCESSES,
        group_options={**gopts, "faults": plan},
    )
    assert shrunk.epochs == 2, shrunk.epochs
    assert model_texts_close(shrunk.model_text, baseline.model_text), (
        "shrunken-gang fit drifted beyond histogram-resharding tolerance"
    )
    assert [f[0] for f in plan.fired] == ["net_partition"], plan.fired
    partitioned = [s for s in shrunk.exit_statuses if s.reason == "partition"]
    assert len(partitioned) == 1, shrunk.exit_statuses
    victim = partitioned[0].member
    print(f"scenario B: partition revoked both sides within the io "
          f"deadline, member {victim} voted off + quarantined, gang shrank "
          f"to {NUM_PROCESSES - 1}, fit resumed from the journal")

    # -- scenario C: partition with a lenient tracker -> respawn -------------
    plan = FaultPlan(seed=seed).net_partition(
        0, 1, epoch=0, after_round=PARTITION_AFTER_ROUND
    )
    lenient = HealthTracker(threshold=10.0, window_s=600.0, parole_s=600.0)
    respawned = fit_process_group(
        X, y, opts, num_processes=NUM_PROCESSES,
        group_options={**gopts, "faults": plan, "health": lenient},
    )
    assert respawned.epochs == 2, respawned.epochs
    assert respawned.model_text == baseline.model_text, (
        "respawned-gang fit diverged from the undisturbed fit"
    )
    assert [f[0] for f in plan.fired] == ["net_partition"], plan.fired
    print("scenario C: same partition under a lenient health tracker — "
          "victim respawned, membership restored, model bitwise-identical")

    from mmlspark_tpu import observability as obs

    events = obs.replay(event_log)
    names = [type(e).__name__ for e in events]
    assert names.count("NetworkPartitioned") == 2, names
    assert names.count("GroupReformed") == 2, names
    print("event log: NetworkPartitioned=2 GroupReformed=2")


def chaos_registry_outage(event_log: str) -> None:
    from mmlspark_tpu.serving.replicas import ReplicaSupervisor
    from mmlspark_tpu.serving.router import FleetRouter
    from mmlspark_tpu.serving.server import RegistrationService

    journal_dir = tempfile.mkdtemp(prefix="chaos-registry-")
    registry = RegistrationService(
        ttl_s=30.0, journal_dir=journal_dir
    ).start()
    port = registry.info.port
    registry_url = f"http://127.0.0.1:{port}"

    with ReplicaSupervisor(
        "mmlspark_tpu.serving.replicas:demo_model_factory",
        num_replicas=2, registry_url=registry_url,
        registry_heartbeat_s=0.2, heartbeat_timeout_s=10.0,
    ) as sup:
        sup.wait_ready(30.0)
        deadline = time.monotonic() + 30.0
        while len(registry.services) < 2 and time.monotonic() < deadline:
            time.sleep(0.1)
        lease_names = sorted(s.name for s in registry.services)
        assert len(lease_names) == 2, lease_names

        router = FleetRouter(
            registry_url=registry_url, discovery_interval_s=0.1,
        ).start()
        try:
            deadline = time.monotonic() + 10.0
            while len(router.refresh()) < 2 and time.monotonic() < deadline:
                time.sleep(0.1)
            status, out = _post(router.url, {"input": 21.0})
            assert status == 200 and out["prediction"] == 42.0, (status, out)
            print(f"fleet up: 2 replicas registered ({lease_names}), "
                  f"router serving")

            # -- the outage: kill the registry mid-flight --------------------
            registry.stop()
            served = shed = 0
            t_end = time.monotonic() + OUTAGE_WINDOW_S
            while time.monotonic() < t_end:
                status, out = _post(router.url, {"input": 21.0})
                if status == 200:
                    assert out["prediction"] == 42.0, out
                    served += 1
                elif status == 429:
                    shed += 1  # admission shed is load policy, not outage
                else:
                    raise AssertionError(
                        f"non-shed {status} during the registry outage: {out}"
                    )
                time.sleep(0.05)
            assert served > 0, "no requests served during the outage window"
            assert router._stale, "router never noticed the outage"
            print(f"registry outage: {served} served + {shed} shed from the "
                  f"stale table, zero non-shed 5xx")

            # -- restart on the SAME port: journaled leases come back --------
            restarted = RegistrationService(
                ttl_s=30.0, port=port, journal_dir=journal_dir
            ).start()
            try:
                recovered = sorted(s.name for s in restarted.services)
                assert recovered == lease_names, (
                    f"journal recovery mismatch: {recovered} != {lease_names}"
                )
                # replicas keep heartbeating the recovered leases — no 404,
                # no re-register; the lease table must stay intact for a
                # full heartbeat cycle
                time.sleep(1.0)
                assert sorted(s.name for s in restarted.services) == \
                    lease_names
                deadline = time.monotonic() + 10.0
                while router._stale and time.monotonic() < deadline:
                    time.sleep(0.1)
                assert not router._stale, "router still stale after restart"
                status, out = _post(router.url, {"input": 5.0})
                assert status == 200 and out["prediction"] == 10.0
                print("registry restarted: journaled leases recovered, "
                      "heartbeats resumed against them, router table fresh")
            finally:
                restarted.stop()
        finally:
            router.stop()

    from mmlspark_tpu import observability as obs

    events = obs.replay(event_log)
    names = [type(e).__name__ for e in events]
    assert names.count("LeaseRecovered") == 2, names
    assert "RegistryUnavailable" in names, names
    print(f"event log: LeaseRecovered=2 "
          f"RegistryUnavailable={names.count('RegistryUnavailable')}")


def main() -> int:
    os.environ.setdefault("MMLSPARK_TPU_FAULT_SEED", "11")
    fit_log = tempfile.mktemp(prefix="partition-events-", suffix=".jsonl")
    os.environ["MMLSPARK_TPU_EVENT_LOG"] = fit_log
    chaos_fit(fit_log)
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    check = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "check_eventlog.py"),
         "--partition", fit_log],
        capture_output=True, text=True, env=env,
    )
    sys.stdout.write(check.stdout)
    sys.stderr.write(check.stderr)
    assert check.returncode == 0, "check_eventlog --partition failed"

    # get_bus() re-syncs the env-driven sink on every call, so pointing
    # the env var at a fresh path re-homes the driver sink for part two
    serve_log = tempfile.mktemp(prefix="registry-events-", suffix=".jsonl")
    os.environ["MMLSPARK_TPU_EVENT_LOG"] = serve_log
    chaos_registry_outage(serve_log)
    os.environ.pop("MMLSPARK_TPU_EVENT_LOG", None)
    print("partition chaos smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
