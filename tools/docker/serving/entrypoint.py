"""Serving container entrypoint: load a saved pipeline/model, serve it.

    python entrypoint.py --model /models/pipeline --port 8890 [--servers 2]

``--model`` is a stage saved with ``.save()`` (PipelineModel or any
transformer); requests POST ``{"<input-col>": value}`` to ``/`` and get the
transformed row back. ``/healthz`` on the registry port reports liveness
for the k8s probes (tools/helm).
"""

import argparse
import signal
import sys
import threading


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True, help="path saved via stage.save()")
    ap.add_argument("--host", default="0.0.0.0", help="bind address")
    ap.add_argument("--port", type=int, default=8890)
    ap.add_argument("--registry-port", type=int, default=8899)
    ap.add_argument("--servers", type=int, default=1, help="listener count")
    ap.add_argument("--input-col", default="input")
    ap.add_argument("--output-col", default="prediction")
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-latency-ms", type=float, default=2.0)
    args = ap.parse_args()

    # Listeners bind port..port+servers-1; a registry port inside that range
    # would EADDRINUSE against listener i = registry_port - port at startup.
    if args.port <= args.registry_port < args.port + max(1, args.servers):
        ap.error(
            f"--registry-port {args.registry_port} collides with the listener "
            f"range {args.port}..{args.port + max(1, args.servers) - 1}; "
            "pick a registry port outside it"
        )

    from mmlspark_tpu.core.serialize import load_stage
    from mmlspark_tpu.serving import (
        DistributedServingServer,
        RegistrationService,
        ServingServer,
    )

    model = load_stage(args.model)
    registry = RegistrationService(host=args.host, port=args.registry_port).start()
    if args.servers > 1:
        server = DistributedServingServer(
            model,
            num_servers=args.servers,
            host=args.host,
            base_port=args.port,  # listeners bind port, port+1, ... (k8s Service)
            registry=registry,
            input_col=args.input_col,
            output_col=args.output_col,
            max_batch_size=args.max_batch,
            max_latency_ms=args.max_latency_ms,
        ).start()
        urls = [i.url for i in server.service_info]
    else:
        server = ServingServer(
            model,
            host=args.host,
            port=args.port,
            input_col=args.input_col,
            output_col=args.output_col,
            max_batch_size=args.max_batch,
            max_latency_ms=args.max_latency_ms,
        ).start()
        registry.register(server.info)
        urls = [server.info.url]
    print(f"serving {args.model} on {urls} (registry :{args.registry_port})", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.stop()
    registry.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
