#!/usr/bin/env bash
# graftlint CI wrapper (docs/static_analysis.md). Lints the package tree
# with the framework-aware rule set; extra args are passed through, so
# `tools/lint.sh --select jit-purity` or `tools/lint.sh tests/` work too.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m mmlspark_tpu.analysis.lint mmlspark_tpu/ --fail-on-violation "$@"
