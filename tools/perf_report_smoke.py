#!/usr/bin/env python
"""CI smoke for the perf-observability plane (the ``perf-report`` job).

Produces the artifacts the job uploads, into ``argv[1]`` (default: a
fresh tempdir):

- ``events.jsonl`` (+ rotated segments) — a chaos-run event log: a
  seeded ``kill_task`` scheduler job, a small loop-path GBDT fit with
  the profiler on, and live serving traffic;
- ``metrics.json`` — the registry ``summary()`` snapshot;
- ``slo.json`` / ``slo.md`` — the :class:`SLOReport` fold;
- ``report.html`` — the history-server render, asserted to contain the
  stage timeline, the task-attempt table, and the SLO table;
- ``overhead.json`` — the bare-transform observability-overhead
  measurement, guarded < 5% (the PR 3 baseline measured 2.9%).

The event log path is printed on the last line so the CI step can feed
it to tools/check_eventlog.py. Exits nonzero on any failed assertion.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import time
import urllib.request

import numpy as np

#: bare-transform overhead bound: observability fully on (event-log sink +
#: profiler) vs fully off must stay under 5% — with an absolute floor so a
#: shared-runner scheduling hiccup on a sub-millisecond workload can't
#: fail the job on noise alone.
OVERHEAD_LIMIT = 0.05
OVERHEAD_ABS_FLOOR_S = 0.010


def _bare_transform_seconds(model, table, calls: int = 30) -> float:
    """One sample: wall time of ``calls`` back-to-back transforms."""
    model.transform(table)  # warm
    t0 = time.perf_counter()
    for _ in range(calls):
        model.transform(table)
    return time.perf_counter() - t0


def main() -> int:
    art = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="mmlspark-tpu-perf-report-"
    )
    os.makedirs(art, exist_ok=True)
    log_path = os.path.join(art, "events.jsonl")

    from mmlspark_tpu.core.pipeline import Estimator, Model, Pipeline
    from mmlspark_tpu.data.table import Table
    from mmlspark_tpu.observability import (
        SLOReport,
        get_bus,
        get_profiler,
        get_registry,
        render_report,
        replay,
        timeline,
    )

    class _CenterModel(Model):
        mean = 0.0

        def transform(self, t: Table) -> Table:
            col = np.asarray(t.column("input"), dtype=np.float64)
            return Table({"prediction": col - self.mean})

    class _Center(Estimator):
        def _fit(self, t: Table) -> _CenterModel:
            m = _CenterModel()
            m.mean = float(np.mean(np.asarray(t.column("input"))))
            return m

    train_tbl = Table({"input": np.linspace(0.0, 9.0, 10)})
    big_tbl = Table({"input": np.linspace(0.0, 1.0, 200_000)})

    # -- 1. bare-transform overhead guard: observability OFF vs fully ON ------
    os.environ.pop("MMLSPARK_TPU_EVENT_LOG", None)
    get_bus()  # re-sync: detaches any env sink
    get_profiler().disable()
    model = Pipeline(stages=[_Center()]).fit(train_tbl)
    off = [_bare_transform_seconds(model, big_tbl) for _ in range(5)]

    os.environ["MMLSPARK_TPU_EVENT_LOG"] = log_path
    os.environ["MMLSPARK_TPU_EVENT_LOG_MAX_BYTES"] = str(256 * 1024)
    get_bus()  # re-sync: attaches the sink (rotation armed)
    prof = get_profiler().enable()
    on = [_bare_transform_seconds(model, big_tbl) for _ in range(5)]

    off_med, on_med = statistics.median(off), statistics.median(on)
    overhead = (on_med - off_med) / off_med if off_med else 0.0
    with open(os.path.join(art, "overhead.json"), "w") as fh:
        json.dump({
            "off_median_s": off_med, "on_median_s": on_med,
            "overhead_frac": overhead, "limit_frac": OVERHEAD_LIMIT,
            "off_runs_s": off, "on_runs_s": on,
        }, fh, indent=2)
    print(f"bare-transform overhead: {overhead:+.1%} "
          f"(off={off_med * 1e3:.1f}ms on={on_med * 1e3:.1f}ms, limit "
          f"{OVERHEAD_LIMIT:.0%})")
    assert (
        overhead < OVERHEAD_LIMIT
        or (on_med - off_med) < OVERHEAD_ABS_FLOOR_S
    ), f"observability overhead regressed: {overhead:.1%} (limit 5%)"

    # -- 2. seeded chaos: one task killed, retried, recovered -----------------
    from mmlspark_tpu import runtime

    plan = runtime.FaultPlan(seed=0).kill_task(1)
    pol = runtime.SchedulerPolicy(max_workers=2, backoff_base=0.01,
                                  faults=plan)
    out = runtime.run_partitioned(lambda x: x * 2, [1, 2, 3, 4], pol)
    assert out == [2, 4, 6, 8], out
    assert ("kill", 1, 0) in plan.fired, plan.fired

    # -- 3. small loop-path GBDT fit with the profiler on ---------------------
    from mmlspark_tpu.lightgbm.train import TrainOptions, train

    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 8)).astype(np.float32)
    y = (X[:, 0] + 0.25 * X[:, 1] > 0).astype(np.float32)
    train(X, y, TrainOptions(objective="binary", num_iterations=4,
                             num_leaves=7),
          iteration_hook=lambda it, tree: None)  # hook forces the loop path
    fns = prof.snapshot()["functions"]
    assert "gbdt.step" in fns and fns["gbdt.step"]["executions"] == 4, fns

    # -- 4. serving traffic -> SLO fold ---------------------------------------
    from mmlspark_tpu.serving import ServingServer

    n_requests = 8
    with ServingServer(model, max_latency_ms=1.0) as srv:
        base = srv.info.url.rstrip("/")
        for i in range(n_requests):
            req = urllib.request.Request(
                base, data=json.dumps({"input": float(i)}).encode(),
                headers={"Content-Type": "application/json"},
            )
            body = json.loads(urllib.request.urlopen(req, timeout=10).read())
            assert "prediction" in body, body

    events = replay(log_path)
    summary = timeline(events)
    assert summary["tasks"]["failed"] >= 1, summary["tasks"]
    assert summary["requests"]["count"] == n_requests, summary["requests"]
    assert summary["profiler"].get("gbdt.step", {}).get("executions") == 4, (
        summary["profiler"]
    )

    metrics = get_registry().summary()
    with open(os.path.join(art, "metrics.json"), "w") as fh:
        json.dump(metrics, fh, indent=2, default=float)
    report = SLOReport.fold(get_registry(), events=events)
    assert report.requests >= n_requests, report.to_dict()
    with open(os.path.join(art, "slo.json"), "w") as fh:
        fh.write(report.to_json())
    with open(os.path.join(art, "slo.md"), "w") as fh:
        fh.write(report.to_markdown() + "\n")

    # -- 5. the history-server render -----------------------------------------
    html_doc = render_report(events, metrics=metrics, title="perf-report smoke")
    html_path = os.path.join(art, "report.html")
    with open(html_path, "w") as fh:
        fh.write(html_doc)
    for needle in (
        "Stage timeline", "Task attempts", "apply p50",
        "Profiler roofline", "gbdt.step",
    ):
        assert needle in html_doc, f"history report missing {needle!r}"

    print(f"perf-report smoke ok: {len(events)} events, "
          f"{report.requests:.0f} requests, artifacts in {art}")
    print(log_path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
