#!/usr/bin/env python
"""Resource-exhaustion smoke: OOM and ENOSPC chaos end to end (CI:
resource-chaos).

Three scenarios, each a fresh child process (clean JAX + event-bus
state), all driven by seeded :class:`FaultPlan` exhaustion directives:

1. **device OOM during a GBDT fit** — ``oom_task(0, "device")`` raises
   RESOURCE_EXHAUSTED at the first histogram dispatch; the fit halves
   its U budget, re-streams the pass row-chunked (bit-exact math), and
   finishes. Asserted: the final model text is byte-identical to an
   undisturbed run's, and the event log carries the
   ``MemoryPressure`` -> ``HistogramDegraded`` pair
   (``check_eventlog.py --pressure`` validates the pairing contract).

2. **host OOM at a task boundary** — ``oom_task(1, "host")`` raises
   MemoryError when task 1 starts; the scheduler classifies it ``oom``
   (not a generic error), relaunches at reduced footprint, and the job's
   results are unchanged. Asserted: correct results, the directive
   fired, and a ``TaskRetried`` with ``reason="oom"`` in the event log.

3. **ENOSPC mid-stream** — ``disk_full("offsets/000001")`` fails epoch
   1's WAL write after epoch 0 committed; the query aborts cleanly
   (nonzero exit, no torn files). A restart without the fault commits
   every epoch exactly once, never refits a journaled epoch, and lands
   byte-identical to an undisturbed run.

Exit code 0 + "resource chaos smoke OK" on success.

Usage: python tools/resource_chaos_smoke.py                # the smoke
       python tools/resource_chaos_smoke.py --child-* ...  # victims
"""

import glob
import json
import os
import subprocess
import sys
import tempfile
import zlib

# runnable both installed (CI) and straight from a checkout
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np

NUM_CHUNKS = 4
MODEL = "reschaos"


# -- scenario 1: device OOM during fit ----------------------------------------

def run_fit_child(out_path: str, fault: bool) -> None:
    from mmlspark_tpu.lightgbm.binning import apply_bins, fit_bin_mapper
    from mmlspark_tpu.lightgbm.train import TrainOptions, train
    from mmlspark_tpu.runtime.faults import FaultPlan, inject_faults

    rng = np.random.default_rng(7)
    X = rng.normal(size=(400, 6))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    mapper = fit_bin_mapper(X, max_bin=63)
    bins = apply_bins(X, mapper)
    opts = TrainOptions(
        objective="binary", num_iterations=6, num_leaves=7, seed=3,
        histogram_method="u",
    )
    plan = FaultPlan()
    if fault:
        plan.oom_task(0, "device")
    with inject_faults(plan):
        result = train(bins, y, opts, mapper=mapper)
    if fault:
        assert ("oom_device", 0, 0) in plan.fired, plan.fired
    with open(out_path, "w", encoding="utf-8") as fh:
        fh.write(result.booster.model_to_string())


# -- scenario 2: host OOM at the task boundary --------------------------------

def run_tasks_child() -> None:
    from mmlspark_tpu import runtime

    plan = runtime.FaultPlan().oom_task(1, "host")
    with runtime.inject_faults(plan):
        results = runtime.run_partitioned(
            lambda x: x * x, [0, 1, 2, 3],
            runtime.SchedulerPolicy(max_workers=2),
        )
    assert results == [0, 1, 4, 9], results
    assert ("oom_host", 1, 0) in plan.fired, plan.fired


# -- scenario 3: ENOSPC mid-stream --------------------------------------------

def run_stream_child(root: str, incoming: str, fault: bool) -> None:
    os.environ["MMLSPARK_TPU_CHECKPOINT_DIR"] = root
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.runtime.faults import FaultPlan, inject_faults
    from mmlspark_tpu.streaming import (
        FileStreamSource,
        ModelCommitSink,
        StreamingQuery,
    )

    source = FileStreamSource(incoming, pattern="part-*.npz", max_per_trigger=1)
    sink = ModelCommitSink(
        lambda: LightGBMClassifier(numIterations=4, numLeaves=7, seed=5),
        name=MODEL,
    )
    query = StreamingQuery(source, sink, name="reschaos")
    plan = FaultPlan()
    if fault:
        # epoch 1's write-ahead log entry — fires AFTER epoch 0 committed
        plan.disk_full("offsets/000001", 1)
    with inject_faults(plan):
        query.process_all_available()
    sink.close()


# -- harness ------------------------------------------------------------------

def make_chunks(incoming: str) -> None:
    rng = np.random.default_rng(13)
    os.makedirs(incoming, exist_ok=True)
    for i in range(NUM_CHUNKS):
        X = rng.normal(size=(60, 4))
        y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
        final = os.path.join(incoming, f"part-{i:05d}.npz")
        np.savez(final + ".tmp.npz", features=X, label=y)
        os.rename(final + ".tmp.npz", final)


def spawn(argv, eventlog=None) -> int:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("MMLSPARK_TPU_EVENT_LOG", None)
    if eventlog is not None:
        env["MMLSPARK_TPU_EVENT_LOG"] = eventlog
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)] + argv, env=env,
    )
    child.wait(timeout=600)
    return child.returncode


def read_events(path: str):
    out = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            if line.strip():
                out.append(json.loads(line))
    return out


def check_pressure(path: str) -> None:
    env = {**os.environ}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    rc = subprocess.call([
        sys.executable, os.path.join(_REPO, "tools", "check_eventlog.py"),
        "--pressure", path,
    ], env=env)
    assert rc == 0, f"check_eventlog --pressure failed on {path}"


def stream_state(root: str):
    """(version, crc32-of-model-text, committed epochs, journal epochs)."""
    from mmlspark_tpu.runtime.journal import ModelStore

    store = ModelStore(os.path.join(root, "models"))
    version, text = store.latest(MODEL)
    commits = sorted(
        int(os.path.basename(p)[:-5])
        for p in glob.glob(
            os.path.join(root, "streaming", "reschaos", "commits", "*.json")
        )
    )
    journal_epochs = []
    for path in glob.glob(
        os.path.join(root, "streaming-models", "**", "journal.jsonl"),
        recursive=True,
    ):
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                if line.strip():
                    journal_epochs.append(int(json.loads(line)["task"]))
    return version, zlib.crc32(text.encode()), commits, sorted(journal_epochs)


def crc_of(path: str) -> int:
    with open(path, "rb") as fh:
        return zlib.crc32(fh.read())


def main() -> int:
    work = tempfile.mkdtemp(prefix="mmlspark-tpu-reschaos-")

    # 1. device OOM during fit: degraded retry, byte-identical model
    ref_model = os.path.join(work, "fit-ref.txt")
    oom_model = os.path.join(work, "fit-oom.txt")
    fit_log = os.path.join(work, "fit-events.jsonl")
    assert spawn(["--child-fit", ref_model, "0"]) == 0, "undisturbed fit failed"
    assert spawn(["--child-fit", oom_model, "1"], eventlog=fit_log) == 0, \
        "device-OOM fit did not recover"
    assert crc_of(ref_model) == crc_of(oom_model), (
        "degraded fit diverged from the undisturbed model"
    )
    kinds = [r.get("event") for r in read_events(fit_log)]
    assert "HistogramDegraded" in kinds, kinds
    assert "MemoryPressure" in kinds, kinds
    check_pressure(fit_log)
    print(f"device-OOM fit: degraded + byte-identical "
          f"(crc={crc_of(ref_model):08x})")

    # 2. host OOM at a task boundary: oom-classified relaunch
    task_log = os.path.join(work, "task-events.jsonl")
    assert spawn(["--child-tasks"], eventlog=task_log) == 0, \
        "host-OOM job did not recover"
    retried = [
        r for r in read_events(task_log)
        if r.get("event") == "TaskRetried" and r.get("reason") == "oom"
    ]
    assert retried, "no TaskRetried with reason='oom' in the event log"
    check_pressure(task_log)
    print(f"host-OOM task: {len(retried)} oom-classified relaunch(es)")

    # 3. ENOSPC mid-stream: clean abort, exactly-once resume
    incoming = os.path.join(work, "incoming")
    make_chunks(incoming)
    ref_root = os.path.join(work, "stream-ref")
    assert spawn(["--child-stream", ref_root, incoming, "0"]) == 0, \
        "undisturbed stream failed"
    ref_version, ref_crc, ref_commits, _ = stream_state(ref_root)
    assert ref_commits == list(range(NUM_CHUNKS)), ref_commits

    enospc_root = os.path.join(work, "stream-enospc")
    rc = spawn(["--child-stream", enospc_root, incoming, "1"])
    assert rc != 0, "injected ENOSPC should abort the query"
    assert spawn(["--child-stream", enospc_root, incoming, "0"]) == 0, \
        "post-ENOSPC restart failed"
    version, crc, commits, journal = stream_state(enospc_root)
    assert commits == list(range(NUM_CHUNKS)), (
        f"each epoch must commit exactly once: {commits}"
    )
    assert journal == list(range(NUM_CHUNKS)), (
        f"a journaled epoch was refitted (or skipped): {journal}"
    )
    assert (version, crc) == (ref_version, ref_crc), (
        f"diverged from undisturbed run: v{version} crc={crc:08x} "
        f"!= v{ref_version} crc={ref_crc:08x}"
    )
    print(f"ENOSPC stream: aborted at epoch 1, resumed to "
          f"v{version:06d} crc={crc:08x} epochs={commits}")

    print("resource chaos smoke OK")
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child-fit":
        run_fit_child(sys.argv[2], sys.argv[3] == "1")
        sys.exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "--child-tasks":
        run_tasks_child()
        sys.exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "--child-stream":
        run_stream_child(sys.argv[2], sys.argv[3], sys.argv[4] == "1")
        sys.exit(0)
    sys.exit(main())
