#!/usr/bin/env python
"""Process-death chaos smoke for the supervised fit gang (CI: process-chaos).

Runs the tentpole end to end with REAL processes, driven by the seeded
fault plan (``MMLSPARK_TPU_FAULT_SEED`` pins the chaos):

  1. a clean 2-process histogram-allreduce fit — the baseline model;
  2. the same fit with a ``kill_process`` directive: one member SIGKILLs
     itself at the first collective of a mid-fit iteration, the survivor
     catches the revoked socket group, the driver books the loss
     (ExitStatus + ProcessLost + health failure), re-forms the gang on
     fresh ports, and the fit resumes from the shared journal;
  3. a replica-serving pass: a supervised serving replica is SIGKILL'd
     mid-serve and comes back answering on a fresh port.

Asserted invariants: the recovered fit is BITWISE identical to the
undisturbed fit (zero re-execution of committed iterations), the event
log contains exactly the expected ProcessLost/GroupReformed/TaskRecovered
records, and the restarted replica serves again.

Exit code 0 + "process chaos smoke OK" on success.
"""

import json
import os
import signal
import sys
import tempfile
import time
import urllib.request

# runnable both installed (CI) and straight from a checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

NUM_PROCESSES = 2
KILL_MEMBER = 1
KILL_ITERATION = 3
NUM_ITERATIONS = 6


def chaos_fit(event_log: str) -> None:
    import numpy as np

    from mmlspark_tpu.lightgbm.procfit import fit_process_group
    from mmlspark_tpu.lightgbm.train import TrainOptions
    from mmlspark_tpu.runtime.faults import FaultPlan

    seed = int(os.environ.get("MMLSPARK_TPU_FAULT_SEED", "11"))
    rng = np.random.default_rng(7)
    n = 400
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] + rng.normal(scale=0.4, size=n) > 0).astype(
        np.float32
    )
    opts = TrainOptions(
        objective="binary", num_iterations=NUM_ITERATIONS, num_leaves=7,
        max_bin=32, min_data_in_leaf=5, seed=2,
    )

    baseline = fit_process_group(
        X, y, opts, num_processes=NUM_PROCESSES,
        group_options={"epoch_timeout_s": 180.0},
    )
    assert baseline.epochs == 1, baseline.epochs
    print(f"baseline fit: {baseline.iterations} iterations, 1 epoch")

    plan = FaultPlan(seed=seed).kill_process(
        KILL_MEMBER, iteration=KILL_ITERATION
    )
    chaos = fit_process_group(
        X, y, opts, num_processes=NUM_PROCESSES,
        group_options={"faults": plan, "epoch_timeout_s": 180.0},
    )
    assert chaos.model_text == baseline.model_text, (
        "recovered fit diverged from the undisturbed fit"
    )
    assert chaos.epochs == 2, chaos.epochs
    assert chaos.recovered_iterations == KILL_ITERATION, (
        chaos.recovered_iterations
    )
    assert plan.fired == [("kill_process", KILL_MEMBER, 0)], plan.fired
    killed = [s for s in chaos.exit_statuses if s.reason == "signal:9"]
    assert [s.member for s in killed] == [KILL_MEMBER], chaos.exit_statuses
    print(
        f"chaos fit: member {KILL_MEMBER} SIGKILL'd at iteration "
        f"{KILL_ITERATION}, re-formed, resumed {KILL_ITERATION} committed "
        f"iterations from the journal, model bitwise-identical"
    )

    from mmlspark_tpu import observability as obs

    events = obs.replay(event_log)
    names = [type(e).__name__ for e in events]
    assert names.count("ProcessLost") == 1, names.count("ProcessLost")
    assert names.count("GroupReformed") == 1
    recovered = [e for e in events if type(e).__name__ == "TaskRecovered"]
    assert sorted(e.task_id for e in recovered) == list(range(KILL_ITERATION))
    print("event log: ProcessLost=1 GroupReformed=1 "
          f"TaskRecovered={len(recovered)}")


def chaos_serving() -> None:
    from mmlspark_tpu.serving.replicas import ReplicaSupervisor

    def post(url, val):
        req = urllib.request.Request(
            url, data=json.dumps({"input": val}).encode(),
            headers={"Content-Type": "application/json"},
        )
        return json.loads(urllib.request.urlopen(req, timeout=10).read())

    with ReplicaSupervisor(
        "mmlspark_tpu.serving.replicas:demo_model_factory",
        num_replicas=2, heartbeat_timeout_s=5.0,
    ) as sup:
        for url in sup.urls().values():
            assert post(url, 21.0)["prediction"] == 42.0
        os.kill(sup._procs[1].pid, signal.SIGKILL)
        deadline = time.monotonic() + 30.0
        while not sup.exit_statuses and time.monotonic() < deadline:
            sup.poll()
            time.sleep(0.2)
        assert sup.exit_statuses and sup.exit_statuses[0].reason == "signal:9"
        sup.wait_ready(30.0)
        assert post(sup.urls()[1], 5.0)["prediction"] == 10.0
    print("serving chaos: replica SIGKILL'd, restarted on a fresh port, "
          "serving again")


def main() -> int:
    event_log = tempfile.mktemp(prefix="chaos-events-", suffix=".jsonl")
    os.environ["MMLSPARK_TPU_EVENT_LOG"] = event_log
    chaos_fit(event_log)
    os.environ.pop("MMLSPARK_TPU_EVENT_LOG", None)
    chaos_serving()
    print("process chaos smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
