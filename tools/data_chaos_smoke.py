#!/usr/bin/env python
"""Corruption-storm smoke for the poison-tolerant data plane (CI:
data-chaos).

Three acts, one seeded storm (``MMLSPARK_TPU_FAULT_SEED``):

1. **Sharded batch fit** — a shard set is corrupted two ways (torn file
   bytes, stale CRC sidecar); a ``mode="permissive"`` fit with
   ``bad_records_path`` must quarantine exactly those shards into the
   dead-letter store and produce a model **byte-identical** to a fit
   over the clean complement (deterministic survivor order is the whole
   point of the eager scan).

2. **Streaming corruption storm + SIGKILL** — a checkpointed
   :class:`StreamingQuery` over a permissive ``FileStreamSource`` eats
   the same two corruptions as whole-epoch quarantines while the parent
   SIGKILLs the child at ``pre_commit`` of one poisoned epoch and
   ``post_wal`` of the other. The DLQ must hold exactly one manifest per
   poisoned epoch across every restart (exactly-once under the WAL), and
   the final model must match an undisturbed run over the clean
   complement, byte for byte.

3. **Serving malformed storm** — the act-1 model serves over HTTP while
   a ``FaultPlan.malformed_request``-directed poison client floods it
   with torn JSON / schema violations / NaN payloads: every reply must
   be a structured 400 carrying ``X-Trace-Id`` until the per-client
   breaker sheds with 429s; a healthy client stays at 200 throughout and
   the poison client is admitted again after the reset window.

The event log (``--out``) is written for ``check_eventlog.py
--dataguard``: RecordsDeadLettered exactly-once per (source, epoch),
every PoisonClientBlocked paired with a PoisonClientReleased.

Exit code 0 + "data chaos smoke OK" on success.

Usage: python tools/data_chaos_smoke.py [--out DIR]        # the smoke
       python tools/data_chaos_smoke.py --child R I [E P]  # victim
"""

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request
import zlib

# runnable both installed (CI) and straight from a checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

NUM_SHARDS = 6
NUM_CHUNKS = 6
CORRUPT = (2, 4)  # index -> torn bytes, index -> stale CRC sidecar
MODEL = "datachaos"


def _seed() -> int:
    return int(os.environ.get("MMLSPARK_TPU_FAULT_SEED", "23"))


def corrupt_torn(path: str) -> None:
    """Truncate the file to 60% of its bytes — a torn write (the sidecar,
    if any, no longer matches either)."""
    size = os.path.getsize(path)
    with open(path, "rb+") as fh:
        fh.truncate(max(1, int(size * 0.6)))


def corrupt_sidecar(path: str) -> None:
    """Write a stale ``.crc32`` sidecar: the file is intact but the
    recorded checksum is wrong — bit-rot as the loader sees it."""
    with open(path + ".crc32", "w", encoding="utf-8") as fh:
        fh.write("deadbeef")


# -- act 1: sharded batch fit over a corrupted shard set ----------------------


def batch_fit_act(work: str):
    from mmlspark_tpu.data.sharded import ShardedDataset, fit_gbdt_sharded
    from mmlspark_tpu.dataguard import DeadLetterStore
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    rng = np.random.default_rng(_seed())
    X = rng.normal(size=(600, 6))
    y = (X[:, 0] - 0.5 * X[:, 3] > 0).astype(np.float64)
    shards_dir = os.path.join(work, "shards")
    ShardedDataset.write_shards(shards_dir, X, y, rows_per_shard=100)
    paths = sorted(glob.glob(os.path.join(shards_dir, "shard_*.npz")))
    assert len(paths) == NUM_SHARDS, paths
    corrupt_torn(paths[CORRUPT[0]])
    corrupt_sidecar(paths[CORRUPT[1]])

    def estimator():
        return LightGBMClassifier(numIterations=8, numLeaves=15, seed=7)

    clean = [p for i, p in enumerate(paths) if i not in CORRUPT]
    ref = fit_gbdt_sharded(estimator(), ShardedDataset(clean))
    ref_text = ref.booster.model_to_string()

    dlq_dir = os.path.join(work, "badrecords")
    ds = ShardedDataset(paths, mode="permissive", bad_records_path=dlq_dir)
    model = fit_gbdt_sharded(estimator(), ds)
    text = model.booster.model_to_string()

    assert len(ds.quarantined) == len(CORRUPT), [
        (r.source, r.reason) for r in ds.quarantined
    ]
    quarantined_paths = sorted(r.source for r in ds.quarantined)
    want_paths = sorted(paths[i] for i in CORRUPT)
    assert quarantined_paths == want_paths, quarantined_paths
    assert text == ref_text, (
        "permissive fit diverged from the clean-complement fit "
        f"(crc {zlib.crc32(text.encode()):08x} vs "
        f"{zlib.crc32(ref_text.encode()):08x})"
    )

    dlq = DeadLetterStore(dlq_dir, name="sharded")
    manifest = dlq.manifest()
    assert len(manifest) == 1 and manifest[0]["count"] == len(CORRUPT), manifest
    replayed = dlq.replay()
    assert sorted(r.source for r in replayed) == want_paths, replayed
    print(
        f"act 1 (batch): {len(ds.quarantined)} shard(s) quarantined "
        f"({', '.join(sorted(r.reason for r in ds.quarantined))}), model "
        f"byte-identical to clean complement "
        f"(crc {zlib.crc32(text.encode()):08x}), DLQ replay ok"
    )
    return model


# -- act 2: streaming corruption storm under SIGKILL --------------------------


def make_chunks(incoming: str) -> None:
    from mmlspark_tpu.data.sharded import write_shard_sidecar

    rng = np.random.default_rng(_seed() + 1)
    os.makedirs(incoming, exist_ok=True)
    for i in range(NUM_CHUNKS):
        X = rng.normal(size=(60, 4))
        y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
        final = os.path.join(incoming, f"part-{i:05d}.npz")
        np.savez(final + ".tmp.npz", features=X, label=y)
        os.rename(final + ".tmp.npz", final)
        write_shard_sidecar(final)


def run_child(root, incoming, kill_epoch=None, kill_point=None) -> None:
    """One (re)start of the permissive query; dies mid-epoch on a kill."""
    os.environ["MMLSPARK_TPU_CHECKPOINT_DIR"] = root
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.runtime.faults import FaultPlan, inject_faults
    from mmlspark_tpu.streaming import (
        FileStreamSource,
        ModelCommitSink,
        StreamingQuery,
    )

    source = FileStreamSource(
        incoming, pattern="part-*.npz", max_per_trigger=1, mode="permissive",
    )
    sink = ModelCommitSink(
        lambda: LightGBMClassifier(numIterations=4, numLeaves=7, seed=5),
        name=MODEL,
    )
    query = StreamingQuery(source, sink, name="datachaos")
    plan = FaultPlan(seed=_seed())
    if kill_epoch is not None:
        plan.kill_stream(int(kill_epoch), kill_point)
    with inject_faults(plan):
        query.process_all_available()
    sink.close()


def spawn(root, incoming, kill=None, label="child") -> subprocess.Popen:
    argv = [sys.executable, os.path.abspath(__file__), "--child", root, incoming]
    if kill is not None:
        argv += [str(kill[0]), kill[1]]
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "MMLSPARK_TPU_EVENT_LOG_PROCESS": label}
    return subprocess.Popen(argv, env=env)


def model_state(root):
    """(version, crc32 of the committed model text)."""
    from mmlspark_tpu.runtime.journal import ModelStore

    version, text = ModelStore(os.path.join(root, "models")).latest(MODEL)
    return version, zlib.crc32(text.encode())


def streaming_act(work: str) -> None:
    from mmlspark_tpu.dataguard import DeadLetterStore

    incoming = os.path.join(work, "incoming")
    make_chunks(incoming)
    torn = os.path.join(incoming, f"part-{CORRUPT[0]:05d}.npz")
    stale = os.path.join(incoming, f"part-{CORRUPT[1]:05d}.npz")
    corrupt_torn(torn)
    corrupt_sidecar(stale)

    # undisturbed reference: the clean complement only, same file names
    ref_incoming = os.path.join(work, "incoming-ref")
    os.makedirs(ref_incoming, exist_ok=True)
    for i in range(NUM_CHUNKS):
        if i in CORRUPT:
            continue
        name = f"part-{i:05d}.npz"
        with open(os.path.join(incoming, name), "rb") as src:
            data = src.read()
        with open(os.path.join(ref_incoming, name), "wb") as dst:
            dst.write(data)
    ref_root = os.path.join(work, "stream-ref")
    child = spawn(ref_root, ref_incoming, label="streamref")
    assert child.wait(timeout=600) == 0, "undisturbed run failed"
    ref_version, ref_crc = model_state(ref_root)
    print(f"act 2 reference: v{ref_version:06d} crc={ref_crc:08x} "
          f"({NUM_CHUNKS - len(CORRUPT)} clean chunks)")

    # chaos run: SIGKILL at pre_commit of the torn epoch (the DLQ manifest
    # is already down — the replay must NOT double-letter) and at post_wal
    # of the stale-sidecar epoch (nothing lettered yet — the replay must
    # letter exactly once); finish on the third start
    chaos_root = os.path.join(work, "stream-chaos")
    kills = [(CORRUPT[0], "pre_commit"), (CORRUPT[1], "post_wal")]
    for n, kill in enumerate(kills):
        child = spawn(chaos_root, incoming, kill=kill, label=f"chaos{n}")
        child.wait(timeout=600)
        assert child.returncode == -signal.SIGKILL, (
            f"expected SIGKILL death at {kill}, got rc={child.returncode}"
        )
        print(f"act 2: child SIGKILLed at epoch {kill[0]} ({kill[1]})")
    child = spawn(chaos_root, incoming, label=f"chaos{len(kills)}")
    assert child.wait(timeout=600) == 0, "final restart failed"

    version, crc = model_state(chaos_root)
    print(f"act 2 chaos:     v{version:06d} crc={crc:08x} "
          f"(2 epochs fully quarantined)")
    assert (version, crc) == (ref_version, ref_crc), (
        f"streaming model diverged from the clean-complement run: "
        f"v{version} crc={crc:08x} != v{ref_version} crc={ref_crc:08x}"
    )

    dlq = DeadLetterStore(
        os.path.join(chaos_root, "streaming", "datachaos", "deadletter"),
        name="datachaos",
    )
    assert dlq.epochs() == sorted(CORRUPT), (
        f"DLQ epochs {dlq.epochs()}, expected {sorted(CORRUPT)}"
    )
    for entry in dlq.manifest().values():
        assert entry["count"] == 1, entry  # one file quarantined per epoch
    for epoch in CORRUPT:
        (rec,) = dlq.replay(epoch)
        assert f"part-{epoch:05d}.npz" in rec.source, rec
    print(f"act 2: DLQ exactly-once across {len(kills)} SIGKILLs "
          f"(epochs {dlq.epochs()}, one letter each); replay ok")


# -- act 3: serving malformed storm -------------------------------------------


def _post(url, data, headers=None, timeout=5.0):
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _malformed_body(kind: str) -> bytes:
    if kind == "json":
        return b'{"features": [1.0, not json'
    if kind == "schema":
        return json.dumps({"wrong_col": [1.0] * 6}).encode()
    return b'{"features": [NaN, 0.0, 0.0, 0.0, 0.0, 0.0]}'


def serving_act(model) -> None:
    from mmlspark_tpu.runtime.faults import FaultPlan
    from mmlspark_tpu.serving import ServingServer

    plan = FaultPlan(seed=_seed())
    for kind in ("json", "schema", "nan"):
        plan.malformed_request(count=4, kind=kind)

    good_row = [0.1, -0.2, 0.3, -0.4, 0.5, -0.6]
    with ServingServer(
        model, input_col="features",
        malformed_threshold=4, malformed_window_s=30.0,
        malformed_reset_s=0.5,
    ) as srv:
        url = srv.info.url
        status, _, headers = _post(url, json.dumps(
            {"features": good_row}).encode())
        assert status == 200, f"warmup serve failed: {status}"

        s400 = s429 = 0
        while True:
            kind = plan.take_malformed()
            if kind is None:
                break
            status, body, headers = _post(
                url, _malformed_body(kind),
                headers={"X-Client-Id": "poison"},
            )
            assert headers.get("X-Trace-Id"), (
                f"{kind}: reply {status} carries no X-Trace-Id"
            )
            if status == 400:
                err = json.loads(body).get("error")
                assert isinstance(err, dict) and err.get("kind") \
                    and err.get("rid"), f"unstructured 400 body: {body!r}"
                s400 += 1
            elif status == 429:
                assert "Retry-After" in headers, headers
                s429 += 1
            else:
                raise AssertionError(
                    f"malformed {kind} request leaked through: {status}"
                )
            # the poison flood never disturbs a healthy client
            status, _, _ = _post(
                url, json.dumps({"features": good_row}).encode(),
                headers={"X-Client-Id": "healthy"},
            )
            assert status == 200, f"healthy client failed mid-storm: {status}"
        assert s400 >= 4 and s429 >= 1, (s400, s429)

        # after the reset window the breaker releases the poison client
        time.sleep(0.6)
        status, _, _ = _post(
            url, json.dumps({"features": good_row}).encode(),
            headers={"X-Client-Id": "poison"},
        )
        assert status == 200, f"poison client never released: {status}"
    print(f"act 3 (serving): {s400} structured+traced 400s, {s429} shed "
          f"429s, healthy client unaffected, breaker released")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools/data_chaos_smoke.py",
        description="Corruption-storm smoke for the poison-tolerant "
                    "data plane.",
    )
    parser.add_argument("--out", default=None,
                        help="artifact directory (event log lands here; "
                             "default: the temp workdir)")
    args = parser.parse_args(argv)

    work = tempfile.mkdtemp(prefix="mmlspark-tpu-datachaos-")
    out = os.path.abspath(args.out or work)
    os.makedirs(out, exist_ok=True)
    log = os.path.join(out, "events.jsonl")
    open(log, "w").close()
    for stale in glob.glob(glob.escape(log) + "@*"):
        os.unlink(stale)
    os.environ["MMLSPARK_TPU_EVENT_LOG"] = log

    model = batch_fit_act(work)
    streaming_act(work)
    serving_act(model)
    print(f"event log: {log}")
    print("data chaos smoke OK")
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--child":
        kill = sys.argv[4:6]
        run_child(
            sys.argv[2], sys.argv[3],
            kill_epoch=kill[0] if kill else None,
            kill_point=kill[1] if kill else None,
        )
        sys.exit(0)
    sys.exit(main())
