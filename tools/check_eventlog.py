#!/usr/bin/env python
"""Validate a JSON-lines event log written by ``MMLSPARK_TPU_EVENT_LOG``.

Checks every line against the typed-event registry
(:mod:`mmlspark_tpu.observability.events`): the line must be a JSON
object, name a known event type, carry every required field with a
JSON-compatible scalar of the declared type, and carry no unknown
fields. Timestamps must be monotonically sane (non-negative floats),
and duration-valued fields (``seconds``/``latency``/``duration``, the
Profile*/RequestServed/TaskFailed payloads) must be non-negative.

Rotated logs (``MMLSPARK_TPU_EVENT_LOG_MAX_BYTES``) are validated whole:
every ``<path>.<seq>`` segment plus the live file, in write order — and
federated logs whole too: per-process siblings
(``events.jsonl@replica-0``, ...) are discovered and validated alongside
the driver log, or pass an already-merged fleet log directly.

    python tools/check_eventlog.py /path/to/events.jsonl
    python tools/check_eventlog.py --trace-continuity fleet-events.jsonl

``--trace-continuity`` additionally asserts the distributed-tracing
contract over the (merged) stream: every successfully served
``RequestRouted`` trace id must resolve to its full cross-process span
chain — the router's root span AND the replica's serving span, from at
least two distinct processes, under one trace id.

``--pressure`` additionally asserts the resource-pressure contract:
every ``MemoryPressure``/``DiskPressure`` onset (level != "ok") must be
followed by a degradation event (``HistogramDegraded``, a
``memory_pressure`` ``RequestShed``, an ``oom`` ``TaskRetried``) or the
matching recovery record (same event type, level == "ok").

``--partition`` additionally asserts the partition-recovery contract:
every ``NetworkPartitioned`` onset must be followed by a
``GroupReformed`` (the gang revoked the partitioned member and re-formed
without it) — a partition that never re-forms is a hang the collective
deadline failed to break.

``--quality`` additionally asserts the model-quality contract: every
``DriftDetected`` onset must be followed by a ``DriftCleared`` for the
same feature (keyed per feature — drift on ``input[0]`` is not cleared
by a recovery on ``input[1]``), and every ``AlertFired`` by an
``AlertResolved`` for the same alert name. An onset that never recovers
inside the campaign means the storm outlived its injection window.

``--dataguard`` additionally asserts the poison-tolerance contract:
``RecordsDeadLettered`` must be exactly-once per (source, epoch) — a
duplicate means a replayed streaming epoch double-lettered its
quarantines past the DLQ manifest guard — and every
``PoisonClientBlocked`` must be followed by a ``PoisonClientReleased``
for the same client.

Exit status 0 with a one-line summary when the log is clean; 1 with one
diagnostic per bad line otherwise (CI gates on this; see the
``observability`` and ``fleet-chaos`` jobs in .github/workflows/ci.yml).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import typing

from mmlspark_tpu.observability import events as ev

#: dataclass annotation (a string under ``from __future__ import
#: annotations``) -> the JSON types it may decode from
_JSON_TYPES = {
    "int": (int,),
    "float": (int, float),
    "str": (str,),
    "bool": (bool,),
}

#: sink-level federation stamps — written by EventLogSink on every
#: record (and re-stamped by merge), deliberately NOT dataclass fields
_STAMP_FIELDS = {"process", "wt"}


def _check_record(rec: object) -> typing.List[str]:
    """Problems with one decoded line ([] when valid)."""
    if not isinstance(rec, dict):
        return ["line is not a JSON object"]
    kind = rec.get("event")
    cls = ev._EVENT_TYPES.get(kind or "")
    if cls is None:
        return [f"unknown event type {kind!r}"]
    problems = []
    fields = {f.name: f for f in dataclasses.fields(cls)}
    for name, f in fields.items():
        required = (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        )
        if name not in rec:
            if required:
                problems.append(f"{kind}: missing required field {name!r}")
            continue
        ann = f.type.__name__ if isinstance(f.type, type) else str(f.type)
        want = _JSON_TYPES.get(ann)
        got = rec[name]
        # bool is an int subclass; an int field holding True is still a bug
        if want is not None and (
            not isinstance(got, want)
            or (isinstance(got, bool) and bool not in want)
        ):
            problems.append(
                f"{kind}.{name}: expected {f.type}, got {type(got).__name__}"
            )
    unknown = set(rec) - set(fields) - {"event"} - _STAMP_FIELDS
    if unknown:
        problems.append(f"{kind}: unknown fields {sorted(unknown)}")
    t = rec.get("t")
    if isinstance(t, (int, float)) and t < 0:
        problems.append(f"{kind}: negative timestamp {t}")
    for dur_field in ("seconds", "latency", "duration"):
        v = rec.get(dur_field)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v < 0:
            problems.append(f"{kind}.{dur_field}: negative duration {v}")
    return problems


def check_trace_continuity(
    records: typing.List[dict],
) -> typing.Tuple[typing.List[str], str]:
    """(problems, summary) for the distributed-tracing contract over a
    decoded (merged) record stream: every 200-served RequestRouted trace
    id resolves to the router's root span AND a replica-side serving span
    from a different process."""
    spans: typing.Dict[str, typing.List[dict]] = {}
    served: typing.List[dict] = []
    for rec in records:
        kind = rec.get("event")
        if kind == "SpanRecorded" and rec.get("trace_id"):
            spans.setdefault(rec["trace_id"], []).append(rec)
        elif (
            kind == "RequestRouted"
            and rec.get("status") == 200
            and rec.get("trace_id")
        ):
            served.append(rec)
    problems = []
    cross_process = 0
    for rec in served:
        tid = rec["trace_id"]
        trace = spans.get(tid, [])
        names = {s.get("name") for s in trace}
        procs = {s.get("process", "") for s in trace}
        missing = {"router.request", "serving.request"} - names
        if missing:
            problems.append(
                f"trace {tid} (rid {rec.get('rid')}): "
                f"missing span(s) {sorted(missing)} "
                f"(have {sorted(n for n in names if n)})"
            )
        elif len(procs) < 2:
            problems.append(
                f"trace {tid} (rid {rec.get('rid')}): all spans from one "
                f"process {sorted(procs)} — the wire hop dropped the context"
            )
        else:
            cross_process += 1
    if not served:
        problems.append(
            "no 200-served RequestRouted events with a trace id — "
            "nothing to verify"
        )
    summary = (
        f"trace continuity: {cross_process}/{len(served)} served traces "
        f"span >=2 processes"
    )
    return problems, summary


def check_pressure_pairing(
    records: typing.List[dict],
) -> typing.Tuple[typing.List[str], str]:
    """(problems, summary) for the resource-pressure contract over a
    decoded record stream: every MemoryPressure/DiskPressure onset
    (level != "ok") must be followed by a degradation event — a
    HistogramDegraded, a RequestShed with reason ``memory_pressure``, or
    a TaskRetried with reason ``oom`` — or by the matching recovery
    record (same event type, level == "ok"). An onset nobody reacted to
    means the watchdog fired into the void."""
    onsets: typing.List[typing.Tuple[int, dict]] = []
    recoveries: typing.List[typing.Tuple[int, str]] = []
    degradations: typing.List[int] = []
    for i, rec in enumerate(records):
        kind = rec.get("event")
        if kind in ("MemoryPressure", "DiskPressure"):
            if rec.get("level") == "ok":
                recoveries.append((i, kind))
            else:
                onsets.append((i, rec))
        elif kind == "HistogramDegraded":
            degradations.append(i)
        elif kind == "RequestShed" and rec.get("reason") == "memory_pressure":
            degradations.append(i)
        elif kind == "TaskRetried" and rec.get("reason") == "oom":
            degradations.append(i)
    problems = []
    paired = 0
    for idx, rec in onsets:
        kind = rec["event"]
        reacted = any(j > idx for j in degradations) or any(
            j > idx and k == kind for j, k in recoveries
        )
        if reacted:
            paired += 1
        else:
            where = rec.get("source") or rec.get("path") or "?"
            problems.append(
                f"{kind} onset (level={rec.get('level')!r}, {where}) has no "
                f"subsequent degradation or recovery event — unpaired pressure"
            )
    summary = f"pressure pairing: {paired}/{len(onsets)} onsets paired"
    return problems, summary


def check_partition_pairing(
    records: typing.List[dict],
) -> typing.Tuple[typing.List[str], str]:
    """(problems, summary) for the partition-recovery contract over a
    decoded record stream: every NetworkPartitioned onset must be
    followed by a GroupReformed — the driver revoked the partitioned
    member and the surviving gang re-formed. An onset with no subsequent
    re-formation means the fit hung or died inside the partition."""
    onsets: typing.List[typing.Tuple[int, dict]] = []
    reformed: typing.List[int] = []
    for i, rec in enumerate(records):
        kind = rec.get("event")
        if kind == "NetworkPartitioned":
            onsets.append((i, rec))
        elif kind == "GroupReformed":
            reformed.append(i)
    problems = []
    paired = 0
    for idx, rec in onsets:
        if any(j > idx for j in reformed):
            paired += 1
        else:
            problems.append(
                f"NetworkPartitioned onset (member={rec.get('member')}, "
                f"epoch={rec.get('epoch')}) has no subsequent GroupReformed "
                f"— the gang never recovered from the partition"
            )
    summary = f"partition pairing: {paired}/{len(onsets)} onsets paired"
    return problems, summary


def check_quality_pairing(
    records: typing.List[dict],
) -> typing.Tuple[typing.List[str], str]:
    """(problems, summary) for the model-quality contract over a decoded
    record stream: every DriftDetected onset must be followed by a
    DriftCleared for the SAME feature, and every AlertFired by an
    AlertResolved for the SAME alert name. Pairing is keyed, not merely
    ordered — a clear on another feature does not recover this one."""
    drift_onsets: typing.List[typing.Tuple[int, dict]] = []
    drift_clears: typing.List[typing.Tuple[int, str]] = []
    alert_onsets: typing.List[typing.Tuple[int, dict]] = []
    alert_clears: typing.List[typing.Tuple[int, str]] = []
    for i, rec in enumerate(records):
        kind = rec.get("event")
        if kind == "DriftDetected":
            drift_onsets.append((i, rec))
        elif kind == "DriftCleared":
            drift_clears.append((i, str(rec.get("feature", ""))))
        elif kind == "AlertFired":
            alert_onsets.append((i, rec))
        elif kind == "AlertResolved":
            alert_clears.append((i, str(rec.get("alert", ""))))
    problems = []
    paired = 0
    for idx, rec in drift_onsets:
        feature = str(rec.get("feature", ""))
        if any(j > idx and f == feature for j, f in drift_clears):
            paired += 1
        else:
            problems.append(
                f"DriftDetected onset (feature={feature!r}, "
                f"{rec.get('stat')}={rec.get('value')}) has no subsequent "
                f"DriftCleared for that feature — drift never recovered"
            )
    for idx, rec in alert_onsets:
        alert = str(rec.get("alert", ""))
        if any(j > idx and a == alert for j, a in alert_clears):
            paired += 1
        else:
            problems.append(
                f"AlertFired onset (alert={alert!r}, slo={rec.get('slo')!r}) "
                f"has no subsequent AlertResolved for that alert — the burn "
                f"never recovered"
            )
    onsets = len(drift_onsets) + len(alert_onsets)
    summary = f"quality pairing: {paired}/{onsets} onsets paired"
    return problems, summary


def check_dataguard_pairing(
    records: typing.List[dict],
) -> typing.Tuple[typing.List[str], str]:
    """(problems, summary) for the poison-tolerance contract over a
    decoded record stream: RecordsDeadLettered must be exactly-once per
    (source, epoch) — a duplicate means a replayed epoch double-lettered
    its quarantines past the DLQ manifest guard — and every
    PoisonClientBlocked must be followed by a PoisonClientReleased for
    the SAME client (a breaker that never releases starves a client that
    stopped misbehaving)."""
    lettered: typing.Dict[typing.Tuple[str, int], int] = {}
    block_onsets: typing.List[typing.Tuple[int, dict]] = []
    releases: typing.List[typing.Tuple[int, str]] = []
    for i, rec in enumerate(records):
        kind = rec.get("event")
        if kind == "RecordsDeadLettered":
            key = (str(rec.get("source", "")), int(rec.get("epoch", -1)))
            lettered[key] = lettered.get(key, 0) + 1
        elif kind == "PoisonClientBlocked":
            block_onsets.append((i, rec))
        elif kind == "PoisonClientReleased":
            releases.append((i, str(rec.get("client", ""))))
    problems = []
    for (source, epoch), n in sorted(lettered.items()):
        if n > 1:
            problems.append(
                f"RecordsDeadLettered for ({source!r}, epoch {epoch}) "
                f"appeared {n} times — a replayed epoch double-lettered "
                f"its quarantines (DLQ exactly-once violated)"
            )
    paired = 0
    for idx, rec in block_onsets:
        client = str(rec.get("client", ""))
        if any(j > idx and c == client for j, c in releases):
            paired += 1
        else:
            problems.append(
                f"PoisonClientBlocked onset (client={client!r}) has no "
                f"subsequent PoisonClientReleased for that client — the "
                f"breaker never released"
            )
    summary = (
        f"dataguard pairing: {len(lettered)} dead-letter epoch(s) "
        f"exactly-once, {paired}/{len(block_onsets)} poison blocks released"
    )
    return problems, summary


def main(argv: typing.Optional[typing.List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools/check_eventlog.py",
        description="Validate a JSON-lines event log "
                    "(rotated + federated segments included).",
    )
    parser.add_argument("eventlog", help="event log path (driver log with "
                        "per-process siblings, or a merged fleet log)")
    parser.add_argument(
        "--trace-continuity", action="store_true",
        help="also assert every served RequestRouted trace id resolves "
             "to its full cross-process span chain",
    )
    parser.add_argument(
        "--pressure", action="store_true",
        help="also assert every MemoryPressure/DiskPressure onset pairs "
             "with a later degradation or recovery event",
    )
    parser.add_argument(
        "--partition", action="store_true",
        help="also assert every NetworkPartitioned onset pairs with a "
             "later GroupReformed (the gang recovered)",
    )
    parser.add_argument(
        "--quality", action="store_true",
        help="also assert every DriftDetected pairs with a later "
             "DriftCleared (same feature) and every AlertFired with a "
             "later AlertResolved (same alert)",
    )
    parser.add_argument(
        "--dataguard", action="store_true",
        help="also assert RecordsDeadLettered is exactly-once per "
             "(source, epoch) and every PoisonClientBlocked pairs with a "
             "later PoisonClientReleased (same client)",
    )
    args = parser.parse_args(argv)
    path = args.eventlog
    counts: typing.Dict[str, int] = {}
    valid_records: typing.List[dict] = []
    bad = 0
    # per-process siblings federate into the segment list; a plain or
    # already-merged log is just its own rotation chain
    collected = ev.collect(path)
    segments = [seg for label in sorted(collected)
                for seg in collected[label]]
    if not segments:
        segments = ev.log_segments(path)
    for seg in segments:
        with open(seg, "r", encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    print(f"{seg}:{lineno}: invalid JSON: {e}",
                          file=sys.stderr)
                    bad += 1
                    continue
                problems = _check_record(rec)
                for p in problems:
                    print(f"{seg}:{lineno}: {p}", file=sys.stderr)
                if problems:
                    bad += 1
                else:
                    counts[rec["event"]] = counts.get(rec["event"], 0) + 1
                    valid_records.append(rec)
    total = sum(counts.values())
    where = path if len(segments) == 1 else f"{path} ({len(segments)} segments)"
    summaries = []
    if args.trace_continuity:
        problems, summary = check_trace_continuity(valid_records)
        for p in problems:
            print(f"{path}: {p}", file=sys.stderr)
        bad += len(problems)
        summaries.append(summary)
    if args.pressure:
        problems, summary = check_pressure_pairing(valid_records)
        for p in problems:
            print(f"{path}: {p}", file=sys.stderr)
        bad += len(problems)
        summaries.append(summary)
    if args.partition:
        problems, summary = check_partition_pairing(valid_records)
        for p in problems:
            print(f"{path}: {p}", file=sys.stderr)
        bad += len(problems)
        summaries.append(summary)
    if args.quality:
        problems, summary = check_quality_pairing(valid_records)
        for p in problems:
            print(f"{path}: {p}", file=sys.stderr)
        bad += len(problems)
        summaries.append(summary)
    if args.dataguard:
        problems, summary = check_dataguard_pairing(valid_records)
        for p in problems:
            print(f"{path}: {p}", file=sys.stderr)
        bad += len(problems)
        summaries.append(summary)
    if bad:
        print(f"{where}: {bad} problem(s), {total} valid event(s)",
              file=sys.stderr)
        return 1
    breakdown = " ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    tail = "".join(f"; {s}" for s in summaries)
    print(f"{where}: {total} events ok ({breakdown}){tail}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
