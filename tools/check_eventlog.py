#!/usr/bin/env python
"""Validate a JSON-lines event log written by ``MMLSPARK_TPU_EVENT_LOG``.

Checks every line against the typed-event registry
(:mod:`mmlspark_tpu.observability.events`): the line must be a JSON
object, name a known event type, carry every required field with a
JSON-compatible scalar of the declared type, and carry no unknown
fields. Timestamps must be monotonically sane (non-negative floats),
and duration-valued fields (``seconds``/``latency``/``duration``, the
Profile*/RequestServed/TaskFailed payloads) must be non-negative.

Rotated logs (``MMLSPARK_TPU_EVENT_LOG_MAX_BYTES``) are validated whole:
every ``<path>.<seq>`` segment plus the live file, in write order.

    python tools/check_eventlog.py /path/to/events.jsonl

Exit status 0 with a one-line summary when the log is clean; 1 with one
diagnostic per bad line otherwise (CI gates on this; see the
``observability`` job in .github/workflows/ci.yml).
"""

from __future__ import annotations

import dataclasses
import json
import sys
import typing

from mmlspark_tpu.observability import events as ev

#: dataclass annotation (a string under ``from __future__ import
#: annotations``) -> the JSON types it may decode from
_JSON_TYPES = {
    "int": (int,),
    "float": (int, float),
    "str": (str,),
    "bool": (bool,),
}


def _check_record(rec: object) -> typing.List[str]:
    """Problems with one decoded line ([] when valid)."""
    if not isinstance(rec, dict):
        return ["line is not a JSON object"]
    kind = rec.get("event")
    cls = ev._EVENT_TYPES.get(kind or "")
    if cls is None:
        return [f"unknown event type {kind!r}"]
    problems = []
    fields = {f.name: f for f in dataclasses.fields(cls)}
    for name, f in fields.items():
        required = (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        )
        if name not in rec:
            if required:
                problems.append(f"{kind}: missing required field {name!r}")
            continue
        ann = f.type.__name__ if isinstance(f.type, type) else str(f.type)
        want = _JSON_TYPES.get(ann)
        got = rec[name]
        # bool is an int subclass; an int field holding True is still a bug
        if want is not None and (
            not isinstance(got, want)
            or (isinstance(got, bool) and bool not in want)
        ):
            problems.append(
                f"{kind}.{name}: expected {f.type}, got {type(got).__name__}"
            )
    unknown = set(rec) - set(fields) - {"event"}
    if unknown:
        problems.append(f"{kind}: unknown fields {sorted(unknown)}")
    t = rec.get("t")
    if isinstance(t, (int, float)) and t < 0:
        problems.append(f"{kind}: negative timestamp {t}")
    for dur_field in ("seconds", "latency", "duration"):
        v = rec.get(dur_field)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v < 0:
            problems.append(f"{kind}.{dur_field}: negative duration {v}")
    return problems


def main(argv: typing.List[str]) -> int:
    if len(argv) != 2:
        print(__doc__.strip().splitlines()[0], file=sys.stderr)
        print(f"usage: {argv[0]} EVENT_LOG", file=sys.stderr)
        return 2
    path = argv[1]
    counts: typing.Dict[str, int] = {}
    bad = 0
    segments = ev.log_segments(path)
    for seg in segments:
        with open(seg, "r", encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    print(f"{seg}:{lineno}: invalid JSON: {e}",
                          file=sys.stderr)
                    bad += 1
                    continue
                problems = _check_record(rec)
                for p in problems:
                    print(f"{seg}:{lineno}: {p}", file=sys.stderr)
                if problems:
                    bad += 1
                else:
                    counts[rec["event"]] = counts.get(rec["event"], 0) + 1
    total = sum(counts.values())
    where = path if len(segments) == 1 else f"{path} ({len(segments)} segments)"
    if bad:
        print(f"{where}: {bad} invalid line(s), {total} valid",
              file=sys.stderr)
        return 1
    breakdown = " ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    print(f"{where}: {total} events ok ({breakdown})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
