#!/usr/bin/env python
"""CI smoke for the observability layer (the ``observability`` job).

Starts a real :class:`~mmlspark_tpu.serving.ServingServer` on CPU with a
pipeline of two trivial stages, drives live HTTP traffic through it, and
asserts the three observability planes all light up:

1. ``GET /metrics`` serves Prometheus text with the serving histograms
   and counters populated;
2. ``GET /healthz`` reports uptime / model epoch / last-batch age;
3. the ``MMLSPARK_TPU_EVENT_LOG`` sink wrote replayable events whose
   timeline matches the traffic, and the request trace threads
   request -> batch -> apply with one trace id.

The event log path is printed on the last line so the CI step can feed
it to tools/check_eventlog.py. Exits nonzero on any failed assertion.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request

import numpy as np


def main() -> int:
    log_path = os.path.join(
        tempfile.mkdtemp(prefix="mmlspark-tpu-obs-"), "events.jsonl"
    )
    os.environ["MMLSPARK_TPU_EVENT_LOG"] = log_path

    from mmlspark_tpu.core.pipeline import Estimator, Model, Pipeline
    from mmlspark_tpu.data.table import Table
    from mmlspark_tpu.observability import (
        get_tracer, replay, timeline, format_timeline,
    )
    from mmlspark_tpu.serving import ServingServer

    class _CenterModel(Model):
        mean = 0.0

        def transform(self, t: Table) -> Table:
            col = np.asarray(t.column("input"), dtype=np.float64)
            return Table({"prediction": col - self.mean})

    class _Center(Estimator):
        def _fit(self, t: Table) -> _CenterModel:
            m = _CenterModel()
            m.mean = float(np.mean(np.asarray(t.column("input"))))
            return m

    # a real (if tiny) fitted pipeline, so fit-stage events appear too
    train = Table({"input": np.linspace(0.0, 9.0, 10)})
    model = Pipeline(stages=[_Center()]).fit(train)

    n_requests = 8
    with ServingServer(model, max_latency_ms=1.0) as srv:
        base = srv.info.url.rstrip("/")
        for i in range(n_requests):
            req = urllib.request.Request(
                base, data=json.dumps({"input": float(i)}).encode(),
                headers={"Content-Type": "application/json"},
            )
            body = json.loads(urllib.request.urlopen(req, timeout=10).read())
            assert "prediction" in body, f"bad reply: {body}"

        metrics = urllib.request.urlopen(base + "/metrics", timeout=10)
        ctype = metrics.headers["Content-Type"]
        assert ctype.startswith("text/plain"), ctype
        text = metrics.read().decode()
        for needle in (
            "# TYPE serving_requests_total counter",
            "# TYPE serving_queue_wait_seconds histogram",
            "# TYPE serving_batch_size histogram",
            "# TYPE serving_apply_latency_seconds histogram",
            "serving_replies_failed_total 0",
        ):
            assert needle in text, f"/metrics missing {needle!r}"
        served = [
            line for line in text.splitlines()
            if line.startswith("serving_requests_total ")
        ]
        assert served and float(served[0].split()[1]) == n_requests, served

        health = json.loads(
            urllib.request.urlopen(base + "/healthz", timeout=10).read()
        )
        assert health["status"] == "ok", health
        assert health["uptime_seconds"] >= 0, health
        assert health["model_epoch"] >= 1, health
        assert health["last_batch_age_seconds"] is not None, health

    # -- event log + timeline -------------------------------------------------
    events = replay(log_path)
    summary = timeline(events)
    print(format_timeline(summary))
    assert summary["requests"]["count"] == n_requests, summary["requests"]
    assert summary["requests"]["statuses"].get(200) == n_requests
    assert summary["batches"]["rows"] == n_requests, summary["batches"]
    assert any(s["name"] == "_Center" for s in summary["stages"]), (
        summary["stages"]
    )
    assert "PipelineModel" in summary["models"], summary["models"]

    # -- trace: request -> batch -> apply under ONE trace id ------------------
    tracer = get_tracer()
    roots = [r for r in tracer.export() if r["name"] == "serving.request"]
    assert len(roots) == n_requests, f"expected {n_requests} request spans"
    threaded = 0
    for root in roots:
        tree = tracer.span_tree(root["trace_id"])
        chain = {root["name"]}
        stack = list(tree["roots"])
        while stack:
            node = stack.pop()
            chain.add(node["name"])
            stack.extend(node["children"])
        if {"serving.request", "serving.batch", "serving.apply"} <= chain:
            threaded += 1
    # every batch joins its first request's trace; with micro-batching at
    # least one request per batch must carry the full chain
    assert threaded >= 1, "no trace threads request -> batch -> apply"

    # -- SLO fold: the registry-derived serving verdict -----------------------
    from mmlspark_tpu.observability import SLOReport, get_registry

    report = SLOReport.fold(get_registry(), events=events)
    assert report.requests >= n_requests, report.to_dict()
    assert report.e2e["count"] == n_requests, report.e2e
    md = report.to_markdown()
    assert "| apply p50 |" in md and "| queue |" in md, md
    print(md)

    print(f"observability smoke ok: {n_requests} requests, "
          f"{len(events)} events, {threaded} fully-threaded trace(s)")
    print(log_path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
