#!/usr/bin/env python
"""Kill-and-resume smoke for the durable fit journal (CI: runtime-faults).

A child process starts a durable partitioned job (``FitJournal`` under a
throwaway checkpoint dir) whose tasks are slow enough that the job is
mid-flight when the parent SIGKILLs it — the closest a test gets to a
real machine loss. The parent then reruns the SAME job in-process and
asserts the headline durability invariant:

  * every partition the child committed before dying is restored from
    its checkpoint — the task function runs ZERO times for them;
  * only the unfinished remainder executes;
  * the final results are exactly what an uninterrupted run produces.

Exit code 0 + "kill-resume smoke OK" on success; any assertion failure
is a non-zero exit for CI.

Usage: python tools/kill_resume_smoke.py            # the whole smoke
       python tools/kill_resume_smoke.py --child D  # internal: the victim
"""

import glob
import os
import signal
import subprocess
import sys
import tempfile
import time

# runnable both installed (CI) and straight from a checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_TASKS = 8
KEY = "kill-resume-smoke"
# Slow enough that the child is guaranteed mid-flight when killed, fast
# enough that the whole smoke stays in single-digit seconds.
TASK_SECONDS = 0.4


def _work(x):
    time.sleep(TASK_SECONDS)
    return x * x


def run_child(root: str) -> None:
    """The victim: run the durable job to completion (it won't get to)."""
    from mmlspark_tpu import runtime

    journal = runtime.FitJournal(root, key=KEY, num_tasks=NUM_TASKS)
    runtime.run_partitioned(
        _work,
        list(range(NUM_TASKS)),
        runtime.SchedulerPolicy(max_workers=2, backoff_base=0.01),
        journal=journal,
    )
    journal.close()


def main() -> int:
    root = tempfile.mkdtemp(prefix="mmlspark-tpu-killsmoke-")
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child", root],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )

    # Wait for SOME (not all) partitions to commit, then pull the plug.
    ckpt_glob = os.path.join(root, "*", "task-*.ckpt")
    deadline = time.monotonic() + 60.0
    committed_before = 0
    while time.monotonic() < deadline:
        committed_before = len(glob.glob(ckpt_glob))
        if committed_before >= 2:
            break
        if child.poll() is not None:
            print("FAIL: child finished before it could be killed; "
                  "raise NUM_TASKS or TASK_SECONDS", file=sys.stderr)
            return 1
        time.sleep(0.02)
    else:
        print("FAIL: no partitions committed within 60s", file=sys.stderr)
        child.kill()
        return 1

    child.send_signal(signal.SIGKILL)
    child.wait()
    assert child.returncode != 0, "SIGKILLed child cannot exit 0"
    committed_before = len(glob.glob(ckpt_glob))  # settle post-mortem
    print(f"killed child mid-fit with {committed_before}/{NUM_TASKS} "
          f"partitions committed")
    assert 0 < committed_before < NUM_TASKS, (
        f"need a genuine partial state, got {committed_before}/{NUM_TASKS}"
    )

    # Resume in THIS process: committed partitions must not re-execute.
    from mmlspark_tpu import runtime

    executed = []

    def counting_work(x):
        executed.append(x)
        return _work(x)

    journal = runtime.FitJournal(root, key=KEY, num_tasks=NUM_TASKS)
    restored = len(journal.restore())
    out = runtime.run_partitioned(
        counting_work,
        list(range(NUM_TASKS)),
        runtime.SchedulerPolicy(max_workers=2, backoff_base=0.01),
        journal=journal,
    )
    journal.close()

    assert restored == committed_before, (
        f"restored {restored} != committed {committed_before}"
    )
    assert out == [x * x for x in range(NUM_TASKS)], f"wrong results: {out}"
    assert len(executed) == NUM_TASKS - committed_before, (
        f"re-executed a committed partition: ran {sorted(executed)}, "
        f"but {committed_before} were already committed"
    )
    assert journal.appended == len(executed)
    print(f"resume executed only the {len(executed)} uncommitted "
          f"partitions (zero re-execution of {committed_before} committed)")
    print("kill-resume smoke OK")
    return 0


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--child":
        run_child(sys.argv[2])
        sys.exit(0)
    sys.exit(main())
