"""Example: continuous ingest -> incremental fit -> live commit -> hot serving.

    python examples/streaming_incremental_fit.py

The full streaming loop (ROADMAP item 5b, docs/streaming.md):

1. a producer drops ``part-NNNNN.npz`` training chunks into a directory;
2. a :class:`StreamingQuery` (ProcessingTime trigger) watches it through a
   :class:`FileStreamSource` and, per micro-batch, runs an incremental
   warm-start LightGBM fit (:class:`ModelCommitSink`) — each epoch's merged
   ensemble commits durably through FitJournal + ModelStore CURRENT swap;
3. a :func:`warm_restart_server` with ``watch=True`` serves the committed
   model and hot-swaps the moment a newer version commits — the version is
   visible in ``GET /healthz``, with zero restarts and zero dropped requests;
4. the event log replays into an ingest -> fit -> commit -> serve timeline.
"""

import json
import os
import sys
import tempfile
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORK = tempfile.mkdtemp(prefix="mmlspark-tpu-streaming-")
os.environ.setdefault("MMLSPARK_TPU_CHECKPOINT_DIR", os.path.join(WORK, "ckpt"))
os.environ.setdefault("MMLSPARK_TPU_EVENT_LOG", os.path.join(WORK, "events.jsonl"))

from mmlspark_tpu import observability as obs
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import LightGBMClassificationModel, LightGBMClassifier
from mmlspark_tpu.serving import warm_restart_server
from mmlspark_tpu.streaming import (
    FileStreamSource,
    ModelCommitSink,
    ProcessingTime,
    StreamingQuery,
)

MODEL = "stream"
RNG = np.random.default_rng(7)


def drop_chunk(incoming: str, index: int, rows: int = 80) -> None:
    """Produce one training chunk the way a Spark writer would: write to a
    temp name (invisible to the source), then atomically rename in."""
    X = RNG.normal(size=(rows, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    final = os.path.join(incoming, f"part-{index:05d}.npz")
    np.savez(final + ".tmp.npz", features=X, label=y)
    os.rename(final + ".tmp.npz", final)


class ServedModel(Transformer):
    """Adapts the fitted classifier to the serving input/output contract."""

    def __init__(self, model, **kw):
        super().__init__(**kw)
        self._model = model

    def transform(self, table):
        feats = np.stack(
            [np.asarray(v, dtype=np.float64) for v in table.column("input")]
        )
        scored = self._model.transform(Table({"features": feats}))
        return table.with_column(
            "prediction", scored.column("probability")[:, 1]
        )


def load_served(text: str) -> ServedModel:
    return ServedModel(LightGBMClassificationModel.from_model_string(text))


def get_json(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def post_row(url: str, row) -> float:
    req = urllib.request.Request(
        url, data=json.dumps({"input": row}).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())["prediction"]


def wait_for(predicate, timeout_s: float = 120.0, what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {what}")


def main() -> int:
    incoming = os.path.join(WORK, "incoming")
    os.makedirs(incoming)
    for i in range(2):
        drop_chunk(incoming, i)

    source = FileStreamSource(incoming, pattern="part-*.npz", max_per_trigger=1)
    sink = ModelCommitSink(
        lambda: LightGBMClassifier(numIterations=4, numLeaves=7, seed=3),
        name=MODEL,
    )
    query = StreamingQuery(
        source, sink, trigger=ProcessingTime(0.1), name="incremental-fit"
    )
    query.start()
    wait_for(lambda: len(sink.committed_epochs) >= 2, what="initial epochs")
    v_initial = sink.store.current_version(MODEL)
    print(f"initial backlog fit: epochs {sink.committed_epochs}, "
          f"model v{v_initial:06d}")

    # serve the committed model; watch=True hot-swaps on every new commit
    server = warm_restart_server(
        load_served, name=MODEL, watch=True, poll_s=0.05, input_col="input"
    ).start()
    try:
        url = server.info.url
        health = get_json(url + "healthz")
        assert health["model_version"] == v_initial, health
        p_before = post_row(url, [2.0, 1.0, 0.0, 0.0])
        print(f"serving v{health['model_version']:06d}: "
              f"p(+|x)={p_before:.3f}")

        # the stream keeps flowing: two more chunks arrive while serving
        for i in range(2, 4):
            drop_chunk(incoming, i)
        wait_for(lambda: len(sink.committed_epochs) >= 4, what="live epochs")
        v_final = sink.store.current_version(MODEL)
        assert v_final > v_initial, (v_initial, v_final)

        # the SAME server observes the swap between two requests — no
        # restart, just the CURRENT watcher noticing the new commit
        health = wait_for(
            lambda: (
                (h := get_json(url + "healthz"))["model_version"] == v_final
                and h
            ),
            what="hot swap",
        )
        p_after = post_row(url, [2.0, 1.0, 0.0, 0.0])
        print(f"hot-swapped to v{health['model_version']:06d} with zero "
              f"downtime: p(+|x)={p_after:.3f}")
        assert health["model_version"] == v_final
        assert server.model_version == v_final
        assert server.info.model_version == v_final
    finally:
        server.stop()
        query.stop()
        sink.close()

    # exactly-once bookkeeping: every epoch committed once, in order
    assert query.committed_epochs == sink.committed_epochs == [0, 1, 2, 3]

    summary = obs.timeline(obs.replay(os.environ["MMLSPARK_TPU_EVENT_LOG"]))
    report = obs.format_timeline(summary)
    print(report)
    assert summary["streaming"]["epochs"] == 4, summary["streaming"]
    assert summary["swaps"], "expected at least one ModelSwapped event"
    assert "== streaming ==" in report and "== swaps ==" in report
    print("streaming incremental fit example OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
