"""Example: distributed GBDT training over a device mesh.

    python examples/distributed_mesh_fit.py

Shards 100k rows over the mesh ``data`` axis (8 virtual CPU devices here;
the same code runs one-device-per-chip on a TPU pod slice). The histogram
build is a row-sum, so XLA inserts the cross-device allreduce — LightGBM's
data_parallel socket allreduce expressed as sharding annotations. See
docs/mesh_scaling.md for the measured scaling profile.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    # Request 8 virtual CPU devices BEFORE jax initializes (on a real pod
    # slice, skip this — jax.devices() already spans the slice).
    from mmlspark_tpu.parallel.mesh import force_platform

    force_platform("cpu", min_devices=8)

    import jax

    from mmlspark_tpu.data.table import Table
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.lightgbm.objectives import auc

    rng = np.random.default_rng(0)
    n, f = 100_000, 16
    X = rng.normal(size=(n, f))
    y = ((X[:, 0] + X[:, 1] * X[:, 2] + 0.4 * rng.normal(size=n)) > 0).astype(
        np.float64
    )
    n_train = int(0.8 * n)
    train_t = Table({"features": X[:n_train], "label": y[:n_train]})

    print(f"devices: {len(jax.devices())} ({jax.default_backend()})")

    # parallelism="data_parallel" (the default) builds the mesh over all
    # devices; numTasks caps it (the reference's executor-count knob).
    clf = LightGBMClassifier(numIterations=20, numLeaves=31, numTasks=8)
    model = clf.fit(train_t)

    margins = model.booster.raw_margin(X[n_train:])[:, 0]
    a = auc(y[n_train:], margins, np.ones(n - n_train))
    print(f"holdout AUC (8-way data-parallel fit): {a:.4f}")

    # The same model scores identically regardless of the training layout.
    serial = LightGBMClassifier(
        numIterations=20, numLeaves=31, parallelism="serial"
    ).fit(train_t)
    a_serial = auc(
        y[n_train:], serial.booster.raw_margin(X[n_train:])[:, 0],
        np.ones(n - n_train),
    )
    print(f"holdout AUC (single-device fit):       {a_serial:.4f}")
    assert abs(a - a_serial) < 5e-3


if __name__ == "__main__":
    main()
