"""Example: serve a trained model over HTTP with micro-batching.

    python examples/serve_model.py

Covers: training, wrapping into a ServingServer, concurrent clients,
endpoint discovery through a RegistrationService.
"""

import json
import os
import sys
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import LightGBMClassifier
from mmlspark_tpu.serving import (
    DistributedServingServer,
    RegistrationService,
)


def post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


class ServedModel(Transformer):
    """Adapts the fitted classifier to the serving input/output contract."""

    def __init__(self, model, **kw):
        super().__init__(**kw)
        self._model = model

    def transform(self, table):
        feats = np.stack([np.asarray(v, dtype=np.float64) for v in table.column("input")])
        scored = self._model.transform(Table({"features": feats}))
        return table.with_column("prediction", scored.column("probability")[:, 1])


def main():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 8))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    model = LightGBMClassifier(numIterations=30, numLeaves=15).fit(
        Table({"features": X, "label": y})
    )

    with RegistrationService() as registry:
        with DistributedServingServer(
            ServedModel(model), num_servers=2, registry_url=registry.info.url,
            max_batch_size=32, max_latency_ms=2.0,
        ):
            # clients discover endpoints through the registry
            with urllib.request.urlopen(registry.info.url + "services") as r:
                services = json.loads(r.read())
            urls = [f"http://{s['host']}:{s['port']}/" for s in services]
            print(f"discovered {len(urls)} endpoints")

            with ThreadPoolExecutor(max_workers=8) as pool:
                rows = [X[i].tolist() for i in range(16)]
                results = list(
                    pool.map(lambda args: post(urls[args[0] % len(urls)], {"input": args[1]}),
                             enumerate(rows))
                )
            preds = [round(r["prediction"], 3) for r in results]
            print("predictions:", preds[:8], "...")


if __name__ == "__main__":
    main()
