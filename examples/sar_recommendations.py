"""Example: SAR recommender with time decay + ranking evaluation.

    python examples/sar_recommendations.py

Smart Adaptive Recommendations (the reference's recommendation family):
event log → SAR (time-decayed affinity x jaccard item similarity) →
top-k recommendations → AdvancedRankingMetrics.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.recommendation import SAR


def make_events(n_users=120, n_items=40, seed=0):
    """Two taste clusters: even users like even items, odd like odd."""
    rng = np.random.default_rng(seed)
    users, items, times = [], [], []
    for u in range(n_users):
        pool = np.arange(u % 2, n_items, 2)
        for i in rng.choice(pool, size=8, replace=False):
            users.append(u)
            items.append(int(i))
            times.append(rng.integers(0, 1_000_000))
    return Table({
        "user": np.array(users, dtype=np.int64),
        "item": np.array(items, dtype=np.int64),
        "rating": np.ones(len(users)),
        "time": np.array(times, dtype=np.float64),
    })


def main():
    events = make_events()
    model = SAR(
        userCol="user", itemCol="item", ratingCol="rating", timeCol="time",
        supportThreshold=2, similarityFunction="jaccard",
    ).fit(events)

    recs = model.recommend_for_all_users(num_items=5)
    rec_items = np.stack(list(recs["recommendations"]))  # (U, 5) item ids

    # a user's recommendations should stay inside their taste cluster
    users = recs["user"].astype(int)
    in_cluster = (rec_items % 2 == (users[:, None] % 2)).mean()
    print(f"top-5 recommendations in the user's taste cluster: {in_cluster:.0%}")
    assert in_cluster > 0.95

    sim = model.getItemSimilarity()
    print(f"item-similarity matrix: {sim.shape}, "
          f"cross-cluster mass {sim[0, 1::2].sum() / max(sim[0].sum(), 1e-9):.1%}")


if __name__ == "__main__":
    main()
