"""Example: transfer learning — image featurization into a GBDT classifier.

    python examples/image_featurize_train.py

The reference's flagship notebook flow (ImageFeaturizer with a cut deep
network feeding a downstream learner): images → ImageTransformer
(resize/normalize) → ImageFeaturizer (headless ResNet-18 embeddings) →
LightGBMClassifier on the embeddings.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.image import ImageFeaturizer, ImageTransformer
from mmlspark_tpu.lightgbm import LightGBMClassifier
from mmlspark_tpu.models import init_resnet


def make_images(n=96, size=40, seed=0):
    """Synthetic two-class image set: class 1 has a bright center blob."""
    rng = np.random.default_rng(seed)
    imgs = np.empty(n, dtype=object)
    labels = np.zeros(n)
    for i in range(n):
        img = rng.normal(0.4, 0.15, size=(size, size, 3))
        if i % 2 == 1:
            c = size // 2
            img[c - 6 : c + 6, c - 6 : c + 6] += 0.5
            labels[i] = 1.0
        imgs[i] = np.clip(img, 0, 1)
    return imgs, labels


def main():
    imgs, labels = make_images()
    t = Table({"image": imgs, "label": labels})

    # 1. Standardize images on the way in (the OpenCV-stage analogue,
    #    fluent stage builders like the reference's ImageTransformer).
    t = (
        ImageTransformer(inputCol="image", outputCol="scaled")
        .resize(32, 32)
        .transform(t)
    )

    # 2. Headless backbone embeddings (cut layers off the classifier head).
    params = init_resnet(variant="resnet18", num_classes=2, small_inputs=True)
    t = ImageFeaturizer(
        inputCol="scaled",
        outputCol="features",
        modelParams=params,
        inputHeight=32,
        inputWidth=32,
        batchSize=16,
    ).transform(t)
    print("embeddings:", t["features"].shape)

    # 3. Train the GBDT on the embeddings.
    n_train = int(0.75 * t.num_rows)
    idx = np.arange(t.num_rows)
    train_t = t.filter(idx < n_train)
    test_t = t.filter(idx >= n_train)
    model = LightGBMClassifier(numIterations=30, numLeaves=15).fit(train_t)
    out = model.transform(test_t)
    acc = float((out["prediction"] == test_t["label"]).mean())
    print(f"holdout accuracy: {acc:.3f}")
    assert acc > 0.7, "transfer features should separate the blob classes"


if __name__ == "__main__":
    main()
