"""Example: trained-weight zoo artifact -> ModelDownloader -> ImageFeaturizer.

    python examples/zoo_transfer_learning.py          # full (TPU-sized) run
    ZOO_STEPS=40 python examples/zoo_transfer_learning.py   # CI-sized smoke

The reference's flagship transfer-learning flow (a TRAINED model from the
downloader repository feeding ImageFeaturizer, ``ModelDownloader.scala:125``
+ ``ImageFeaturizer.scala:40-86``) — with the weights genuinely LEARNED on
this rig (zero egress, so no ImageNet download): a ResNet-18 is pretrained
on five translation-randomized shape classes, published into a local model
repository as a ModelSchema artifact, downloaded back (hash-verified), and
its pooled features transferred to two UNSEEN shape classes, where they
beat both logistic-on-pixels and random-init features by a wide margin
(positions are random, so raw pixels carry little transferable signal —
exactly the regime transfer learning exists for).

Measured on the v5e (400 steps): transfer accuracy 0.86 with trained
features vs 0.72 random-init vs 0.63 pixels (docs/zoo_transfer.md).
"""

import os
import sys
import warnings

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.downloader.repository import LocalRepo, ModelDownloader
from mmlspark_tpu.image import ImageFeaturizer
from mmlspark_tpu.models import (
    init_resnet,
    load_zoo_params,
    publish_model,
    train_resnet_classifier,
)

STEPS = int(os.environ.get("ZOO_STEPS", 400))
N_PER = int(os.environ.get("ZOO_N_PER", 240 if STEPS >= 300 else 60))
SIZE = 32


def draw(shape, rng, size=SIZE):
    img = rng.normal(0, 0.15, size=(size, size)).astype(np.float32)
    s = rng.integers(8, 13)
    cy, cx = rng.integers(s // 2 + 2, size - s // 2 - 2, size=2)
    yy, xx = np.mgrid[0:size, 0:size]
    dy, dx = yy - cy, xx - cx
    if shape == "square":
        m = (abs(dy) <= s // 2) & (abs(dx) <= s // 2)
    elif shape == "circle":
        m = dy * dy + dx * dx <= (s // 2) ** 2
    elif shape == "cross":
        m = ((abs(dy) <= 1) | (abs(dx) <= 1)) & (abs(dy) <= s // 2) & (abs(dx) <= s // 2)
    elif shape == "hstripes":
        m = (abs(dy) <= s // 2) & (abs(dx) <= s // 2) & (dy % 3 == 0)
    elif shape == "vstripes":
        m = (abs(dy) <= s // 2) & (abs(dx) <= s // 2) & (dx % 3 == 0)
    elif shape == "ring":
        r2 = dy * dy + dx * dx
        m = (r2 <= (s // 2) ** 2) & (r2 >= (s // 2 - 2) ** 2)
    elif shape == "frame":
        m = (abs(dy) <= s // 2) & (abs(dx) <= s // 2) & (
            (abs(dy) >= s // 2 - 1) | (abs(dx) >= s // 2 - 1)
        )
    img[m] += 1.0
    return np.clip(img, 0, 1.5)


def make(shapes, n_per, seed):
    rng = np.random.default_rng(seed)
    X = np.stack([draw(s, rng) for s in np.repeat(shapes, n_per)])
    y = np.repeat(np.arange(len(shapes)), n_per)
    perm = rng.permutation(len(y))
    return X[perm], y[perm]


def main():
    warnings.filterwarnings("ignore")
    # 1. Pretrain on five shape classes (random positions/sizes).
    Xp, yp = make(["square", "circle", "cross", "hstripes", "vstripes"], N_PER, 0)
    params = init_resnet(variant="resnet18", num_classes=5, small_inputs=True,
                         in_channels=1)
    trained, acc = train_resnet_classifier(
        params, Xp[:, None], yp, num_steps=STEPS, batch_size=64
    )
    print(f"pretrain accuracy: {acc:.3f} ({STEPS} steps)")

    # 2. Publish the TRAINED weights as a repository artifact, then consume
    #    it the way the reference does: downloader -> featurizer.
    import tempfile

    repo_dir = tempfile.mkdtemp(prefix="zoo_repo_")
    cache_dir = tempfile.mkdtemp(prefix="zoo_cache_")
    publish_model(repo_dir, "resnet18_shapes", trained, (SIZE, SIZE))
    dl = ModelDownloader(cache_dir, LocalRepo(repo_dir))
    print("repository models:", [s.name for s in dl.list_models()])
    loaded = load_zoo_params(dl, "resnet18_shapes")

    # 3. Transfer: features for two UNSEEN shape classes.
    Xt, yt = make(["ring", "frame"], max(120, N_PER), 7)
    imgs = np.empty(len(yt), dtype=object)
    for i in range(len(yt)):
        imgs[i] = Xt[i][:, :, None]  # HWC
    t = Table({"image": imgs, "label": yt.astype(np.float64)})

    def featurize(p):
        return ImageFeaturizer(
            inputCol="image", outputCol="features", modelParams=p,
            inputHeight=SIZE, inputWidth=SIZE, scale=1.0, batchSize=64,
        ).transform(t)["features"]

    from sklearn.linear_model import LogisticRegression
    from sklearn.model_selection import cross_val_score

    def cv(X):
        return cross_val_score(LogisticRegression(max_iter=500), X, yt, cv=3).mean()

    acc_trained = cv(np.asarray(featurize(loaded)))
    acc_random = cv(np.asarray(featurize(params)))
    acc_pixels = cv(Xt.reshape(len(yt), -1))
    print(f"transfer accuracy — trained zoo features: {acc_trained:.4f}, "
          f"random-init features: {acc_random:.4f}, raw pixels: {acc_pixels:.4f}")

    if STEPS >= 300:
        assert acc_trained >= acc_pixels + 0.10, (acc_trained, acc_pixels)
        assert acc_trained >= acc_random + 0.05, (acc_trained, acc_random)
        print("OK: trained zoo features beat pixels by >=0.10 and "
              "random-init by >=0.05")
    else:
        print("(smoke run: margin assertions need ZOO_STEPS >= 300)")


if __name__ == "__main__":
    main()
