"""Example: import an ONNX model and serve it through DNNModel.

    python examples/onnx_import_eval.py

The CNTK-model-import analogue: an ONNX graph (authored here with the
vendored wire codec — no onnx package needed) is lowered to a jittable JAX
function and applied as a batched table transform.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.dnn import DNNModel
from mmlspark_tpu.dnn.onnx_import import from_onnx
from mmlspark_tpu.dnn.onnx_proto import encode_model, encode_node, encode_tensor


def author_mlp(d_in=8, d_hidden=16, d_out=3, seed=0):
    """A 2-layer MLP as raw ONNX protobuf bytes."""
    rng = np.random.default_rng(seed)
    w1 = rng.normal(size=(d_in, d_hidden)).astype(np.float32) * 0.4
    b1 = np.zeros(d_hidden, np.float32)
    w2 = rng.normal(size=(d_hidden, d_out)).astype(np.float32) * 0.4
    b2 = np.zeros(d_out, np.float32)
    nodes = [
        encode_node("MatMul", ["x", "w1"], ["h0"]),
        encode_node("Add", ["h0", "b1"], ["h1"]),
        encode_node("Relu", ["h1"], ["h2"]),
        encode_node("MatMul", ["h2", "w2"], ["h3"]),
        encode_node("Add", ["h3", "b2"], ["logits"]),
        encode_node("Softmax", ["logits"], ["probs"]),
    ]
    inits = {"w1": w1, "b1": b1, "w2": w2, "b2": b2}
    return encode_model(nodes, inits, ["x"], ["probs"])


def main():
    buf = author_mlp()
    fn, params = from_onnx(buf)  # jittable (params, {"x": ...}) -> {"probs": ...}

    rng = np.random.default_rng(1)
    X = rng.normal(size=(32, 8)).astype(np.float32)
    model = DNNModel(
        applyFn=fn,
        modelParams=params,
        feedDict={"x": "features"},
        fetchDict={"probs": "probs"},
        batchSize=16,
    )
    out = model.transform(Table({"features": X}))
    probs = out["probs"]
    print(f"probs: {probs.shape}, rows sum to {probs.sum(axis=1)[:3]}")
    assert np.allclose(probs.sum(axis=1), 1.0, rtol=1e-5)
    assert probs.shape == (32, 3)


if __name__ == "__main__":
    main()
