"""Example: unsupervised anomaly detection with IsolationForest.

    python examples/anomaly_detection.py

The reference re-exports LinkedIn's isolation forest; here the algorithm is
implemented natively (vectorized tree growth, on-device scoring). Planted
outliers must receive the top anomaly scores.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.isolationforest import IsolationForest


def main():
    rng = np.random.default_rng(0)
    inliers = rng.normal(0, 1, size=(600, 6))
    outliers = rng.normal(0, 1, size=(12, 6)) + rng.choice([-6, 6], size=(12, 1))
    X = np.vstack([inliers, outliers])
    truth = np.r_[np.zeros(len(inliers)), np.ones(len(outliers))]

    model = IsolationForest(
        numEstimators=100,
        maxSamples=128.0,
        contamination=len(outliers) / len(X),
    ).fit(Table({"features": X}))

    out = model.transform(Table({"features": X}))
    scores = out["outlierScore"]
    flagged = out["predictedLabel"].astype(bool)

    print(f"mean score inliers:  {scores[truth == 0].mean():.3f}")
    print(f"mean score outliers: {scores[truth == 1].mean():.3f}")
    hit_rate = truth[flagged].mean() if flagged.any() else 0.0
    print(f"flagged {int(flagged.sum())} rows; {hit_rate:.0%} are planted outliers")
    assert scores[truth == 1].mean() > scores[truth == 0].mean() + 0.1
    assert hit_rate > 0.6


if __name__ == "__main__":
    main()
