"""Example: long-context attention sharded over the mesh ``seq`` axis.

    python examples/long_context_ring_attention.py

Ring attention: a sequence longer than one device's memory budget shards
over the ``seq`` axis; K/V blocks rotate around the ring via ppermute
(nearest-neighbor ICI on real hardware) with online-softmax accumulation,
so the full (S, S) score matrix never materializes. Verified here against
the O(S^2) reference on an 8-virtual-device mesh.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from mmlspark_tpu.parallel.mesh import force_platform

    force_platform("cpu", min_devices=8)

    import jax.numpy as jnp

    from mmlspark_tpu.ops.ring_attention import attention_reference, ring_attention
    from mmlspark_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=1, seq=8))
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 1024, 4, 32  # 128 positions per device
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )

    out_ring = ring_attention(q, k, v, mesh, causal=True)
    out_ref = attention_reference(q, k, v, causal=True)
    err = float(np.max(np.abs(np.asarray(out_ring) - np.asarray(out_ref))))
    print(f"causal ring attention over seq=8: S={s}, max |err| vs O(S^2) ref = {err:.2e}")
    assert err < 1e-4

    # communication story: each device exchanges its (S/8, d) K/V block 7
    # times — all nearest-neighbor hops, no all-gather of the sequence
    per_hop = (s // 8) * h * d * 4 * 2
    print(f"per-device per-hop K/V traffic: {per_hop/1024:.0f} KiB x 7 hops")


if __name__ == "__main__":
    main()
