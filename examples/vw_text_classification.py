"""Example: VW-style text classification (hashed bag-of-words features).

    python examples/vw_text_classification.py

The reference's VW-on-Spark flow (BASELINE config 4's shape at example
scale): raw text → VowpalWabbitFeaturizer (murmur3 feature hashing, the
native-hashing path) → optional VowpalWabbitInteractions (quadratic
namespace crosses) → VowpalWabbitClassifier (adagrad SGD on-device).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.vw import VowpalWabbitClassifier, VowpalWabbitFeaturizer

POSITIVE = ["great", "excellent", "love", "wonderful", "amazing", "best"]
NEGATIVE = ["terrible", "awful", "hate", "worst", "boring", "broken"]
FILLER = ["the", "movie", "product", "it", "was", "arrived", "today", "really"]


def make_reviews(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    texts = np.empty(n, dtype=object)
    labels = np.zeros(n)
    for i in range(n):
        label = i % 2
        pool = POSITIVE if label else NEGATIVE
        words = list(rng.choice(FILLER, size=6)) + list(
            rng.choice(pool, size=rng.integers(1, 4))
        )
        rng.shuffle(words)
        texts[i] = " ".join(words)
        labels[i] = float(label)
    return texts, labels


def main():
    texts, labels = make_reviews()
    t = Table({"text": texts, "label": labels})

    # Hash words into a 2^15-dim sparse space (VW's core trick; murmur3 via
    # the host C++ library when built).
    t = VowpalWabbitFeaturizer(
        inputCols=["text"], outputCol="features", numBits=15, stringSplit=True
    ).transform(t)

    n_train = int(0.8 * t.num_rows)
    idx = np.arange(t.num_rows)
    train_t, test_t = t.filter(idx < n_train), t.filter(idx >= n_train)

    clf = VowpalWabbitClassifier(numPasses=8, passThroughArgs="--learning_rate 0.8")
    model = clf.fit(train_t)
    out = model.transform(test_t)
    acc = float((out["prediction"] == test_t["label"]).mean())
    print(f"holdout accuracy: {acc:.3f}  ({t.num_rows} reviews, 2^15 hash bits)")
    assert acc > 0.9, "hashed sentiment words should be separable"

    stats = model.get_performance_statistics()
    print(
        "performance statistics:",
        {name: stats[name][0] for name in sorted(stats.columns)[:5]},
    )


if __name__ == "__main__":
    main()
