"""Example: train the flagship GBDT classifier end to end.

    python examples/train_gbdt.py

Covers: table construction, fit with LightGBM-style params, prediction
columns, SHAP explanations, native-model save/load, feature importances,
and the plot helpers (confusion matrix + ROC, saved as a PNG).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import LightGBMClassificationModel, LightGBMClassifier


def main():
    from sklearn.datasets import load_breast_cancer

    d = load_breast_cancer()
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(d.target))
    X, y = d.data[perm], d.target[perm].astype(np.float64)
    n_train = int(0.8 * len(y))
    train_t = Table({"features": X[:n_train], "label": y[:n_train]})
    test_t = Table({"features": X[n_train:], "label": y[n_train:]})

    clf = LightGBMClassifier(
        numIterations=60,
        numLeaves=31,
        learningRate=0.1,
        featuresShapCol="shap",  # per-feature SHAP explanations
        # TPU throughput knob: LightGBM's gradient-quantization training
        # (s8 integer-MXU histogram pass, ~15% faster fits on-chip; falls
        # back to exact bf16 stats with a warning off-TPU)
        useQuantizedGrad=True,
    )
    model = clf.fit(train_t)
    out = model.transform(test_t)

    probs = out.column("probability")[:, 1]
    acc = (out.column("prediction") == y[n_train:]).mean()
    print(f"test accuracy: {acc:.4f}")
    print(f"first row p(malignant): {probs[0]:.4f}")
    print(f"SHAP row sums == margins: {np.allclose(out.column('shap').sum(axis=1), model.booster.raw_margin(X[n_train:])[:, 0], atol=1e-4)}")

    top = np.argsort(model.get_feature_importances("split"))[::-1][:5]
    print("top-5 features by split count:", [d.feature_names[i] for i in top])

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from mmlspark_tpu import plot

    fig, (ax_cm, ax_roc) = plt.subplots(1, 2, figsize=(12, 5))
    scored = out.with_column("p1", probs)
    plot.confusion_matrix(scored, "label", "prediction", labels=[0.0, 1.0], ax=ax_cm)
    plot.roc(scored, "label", "p1", ax=ax_roc)
    fig.savefig("/tmp/gbdt_eval.png", bbox_inches="tight")
    print("saved confusion matrix + ROC to /tmp/gbdt_eval.png")

    path = "/tmp/gbdt_model.txt"
    model.save_native_model(path)
    reloaded = LightGBMClassificationModel.load_native_model(path)
    assert np.allclose(
        reloaded.transform(test_t).column("probability"), out.column("probability")
    )
    print(f"native model round-tripped through {path}")

    # Categorical features: declare slots and the engine runs native-style
    # set splits (one-vs-rest below maxCatToOnehot, sorted-set above).
    cat = rng.integers(0, 6, size=len(y)).astype(np.float64)
    eff = np.array([1.5, -2.0, 0.5, 3.0, -1.0, 0.0])
    yc = (eff[cat.astype(int)] + X[:, 0] / X[:, 0].std() > 0).astype(np.float64)
    Xc = np.column_stack([cat, X[:, :4]])
    mc = LightGBMClassifier(
        numIterations=30, numLeaves=15, categoricalSlotIndexes=[0],
        minDataPerGroup=1,
    ).fit(Table({"features": Xc[:n_train], "label": yc[:n_train]}))
    acc_cat = (
        mc.transform(Table({"features": Xc[n_train:], "label": yc[n_train:]}))
        .column("prediction") == yc[n_train:]
    ).mean()
    print(f"categorical-feature model test accuracy: {acc_cat:.4f}")
    assert acc_cat > 0.85


if __name__ == "__main__":
    main()
