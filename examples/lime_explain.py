"""Example: explain model predictions with tabular LIME.

    python examples/lime_explain.py

Covers: training a learner, wrapping it as the LIME inner model, fitting
TabularLIME, and reading per-row local explanations.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import LightGBMRegressor
from mmlspark_tpu.lime import TabularLIME


def main():
    rng = np.random.default_rng(0)
    n, f = 3000, 6
    X = rng.normal(size=(n, f))
    # ground truth uses features 0 and 2 only
    y = 3.0 * X[:, 0] - 2.0 * X[:, 2] + 0.1 * rng.normal(size=n)

    model = LightGBMRegressor(numIterations=60, numLeaves=31).fit(
        Table({"features": X, "label": y})
    )

    # the fitted regressor already maps a features column to 'prediction',
    # which is exactly the inner-model contract LIME expects
    lime = TabularLIME(
        model=model,
        inputCol="features",
        outputCol="weights",
        nSamples=500,
        seed=0,
    )
    explain_t = Table({"features": X[:5]})
    weights = lime.fit(explain_t).transform(explain_t).column("weights")

    print("per-row local linear explanations (one weight per feature):")
    for i, w in enumerate(np.asarray(weights, dtype=np.float64)):
        print(f"  row {i}: " + "  ".join(f"f{j}={w[j]:+.2f}" for j in range(f)))
    mean_abs = np.abs(np.asarray(weights, dtype=np.float64)).mean(axis=0)
    print("mean |weight| per feature:", np.round(mean_abs, 2))
    print("=> features 0 and 2 dominate, matching the generating function")


if __name__ == "__main__":
    main()
