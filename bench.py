"""Headline benchmark: GBDT training on TPU vs a REAL CPU GBDT.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, ...}

Workload: binary-classification boosting on a Higgs-like dense matrix
(BASELINE.json config 3's shape at bench-friendly scale), leaf-wise growth
with LightGBM-default 31 leaves — the flagship semantics.

``value`` is TPU row-iterations/sec (rows × boosting iterations / fit wall
time; binning included, one-time XLA compile excluded — production runs hit
the persistent compilation cache). ``vs_baseline`` is the speedup over
sklearn's ``HistGradientBoostingClassifier`` — the same histogram-GBDT
algorithm family as LightGBM, run at matched settings (same rows, features,
iterations, leaves, bins, learning rate; median of 3 runs). Both sides also
report held-out AUC so the comparison is at matched quality, per the
"identical AUC" clause of the ≥10× north star (BASELINE.md).
"""

import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 400_000))
N_FEATURES = int(os.environ.get("BENCH_FEATURES", 28))
N_ITERS = int(os.environ.get("BENCH_ITERS", 100))  # LightGBM's default
N_TEST = 50_000
NUM_LEAVES = 31
LEARNING_RATE = 0.1
MAX_BIN = 255
CPU_RUNS = 3
TPU_RUNS = 5  # median-of-5: per-run tunnel transfer variance is ±0.5s


def _make_data(n, f, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float64)
    logit = (
        X[:, 0] * 1.5
        + X[:, 1] * X[:, 2]
        + 0.8 * np.sin(X[:, 3])
        + 0.5 * rng.normal(size=n)
    )
    y = (logit > 0).astype(np.float64)
    return X, y


# Mixed workload: the data distribution real tabular users have —
# categorical + ordinal + a few continuous columns (the reference's own
# perf claims are dataset-level, lightgbm.md:17-21). Effective bin width
# B≈64, the regime where the packed-U layout (K = Σ_f B_f) shines.
MIXED_CARDS = (4, 8, 12, 16, 24, 32, 48, 64)  # 8 categorical features
MIXED_ORDINALS = 12  # integer features with <= 64 levels
MIXED_CONTINUOUS = 8
MIXED_MAX_BIN = 63


def _make_mixed_data(n, seed=0):
    rng = np.random.default_rng(seed)
    cats = [rng.integers(0, c, size=n).astype(np.float64) for c in MIXED_CARDS]
    effs = [rng.normal(size=c) for c in MIXED_CARDS]
    ords = [
        rng.integers(0, 64, size=n).astype(np.float64)
        for _ in range(MIXED_ORDINALS)
    ]
    conts = rng.normal(size=(n, MIXED_CONTINUOUS))
    logit = (
        effs[1][cats[1].astype(int)]
        + 0.8 * effs[4][cats[4].astype(int)]
        + 0.03 * (ords[0] - 32)
        + 0.5 * ((ords[1] > 40) & (cats[0] == 2))
        + conts[:, 0]
        + 0.6 * rng.normal(size=n)
    )
    y = (logit > 0).astype(np.float64)
    X = np.column_stack(cats + ords + [conts])
    cat_idx = list(range(len(MIXED_CARDS)))
    return X, y, cat_idx


# Sparse workload: blocks of one-hot indicator columns (the output of any
# categorical-encoding featurizer — and the shape EFB was invented for:
# LightGBM paper §4). Indicators within a block are mutually exclusive, so
# feature bundling packs each block into ONE dense column and the histogram
# width K = Σ_f B_f drops measurably; the bench reports K before/after.
SPARSE_BLOCKS = 12
SPARSE_CARD = 16  # indicators per block -> 192 one-hot features
SPARSE_CONTINUOUS = 2
SPARSE_MAX_BIN = 63
SPARSE_ROWS = min(N_ROWS, 200_000)


def _make_sparse_data(n, seed=2):
    rng = np.random.default_rng(seed)
    cats = rng.integers(0, SPARSE_CARD, size=(n, SPARSE_BLOCKS))
    effs = rng.normal(size=(SPARSE_BLOCKS, SPARSE_CARD))
    X = np.zeros((n, SPARSE_BLOCKS * SPARSE_CARD + SPARSE_CONTINUOUS))
    X[
        np.arange(n)[:, None],
        np.arange(SPARSE_BLOCKS)[None, :] * SPARSE_CARD + cats,
    ] = 1.0
    conts = rng.normal(size=(n, SPARSE_CONTINUOUS))
    X[:, SPARSE_BLOCKS * SPARSE_CARD:] = conts
    logit = (
        effs[0][cats[:, 0]]
        + 0.8 * effs[3][cats[:, 3]]
        + 0.6 * conts[:, 0]
        + 0.5 * rng.normal(size=n)
    )
    y = (logit > 0).astype(np.float64)
    return X, y


def _load_real_data():
    """(source, X, y) for the gbdt_real_* block. Prefers the vendored
    Covertype sample (``tools/fetch_covtype.py`` writes it; requires
    network once, ROADMAP 5a) — 10 continuous + 44 binary indicator
    columns, the canonical EFB dataset. Falls back to sklearn's bundled
    digits (odd vs even digits) so the real-data block always runs in
    network-less containers; the JSON labels which source was used."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tests", "fixtures", "covtype_sample.npz",
    )
    if os.path.exists(path):
        d = np.load(path)
        return "covtype_sample", d["X"].astype(np.float64), d["y"].astype(np.float64)
    from sklearn.datasets import load_digits

    d = load_digits()
    return (
        "sklearn_digits_odd_vs_even",
        d.data.astype(np.float64),
        (d.target % 2).astype(np.float64),
    )


def _bundling_k(X, max_bin):
    """(k_before, k_after, num_features, num_columns, conflicts) from one
    host binning pass each way — the measured histogram-width reduction
    feature bundling buys on this matrix."""
    from mmlspark_tpu.lightgbm.binning import bin_dataset

    _, m_plain = bin_dataset(X, max_bin=max_bin)
    _, m_bund = bin_dataset(X, max_bin=max_bin, feature_bundling=True)
    k_before = int(sum(int(b) for b in m_plain.num_bins))
    spec = m_bund.bundles
    if spec is None:
        return k_before, k_before, X.shape[1], X.shape[1], 0
    return (
        k_before,
        int(spec.k_packed),
        int(spec.num_features),
        int(spec.num_columns),
        int(spec.conflict_count),
    )


def _chunked_u_evidence():
    """Static proof (no device needed) that a >1M-row headline-shape fit
    takes the chunked MXU path, not a gather fallback: runs the exact
    u-spec selection logic train() uses for a 4M-row fit of the headline
    feature set against the configured HBM budget."""
    from mmlspark_tpu.ops.u_histogram import (
        chunked_u_spec,
        make_u_spec,
        num_u_chunks,
        u_bytes,
    )

    rows = 4_000_000
    try:
        budget = int(os.environ.get("MMLSPARK_TPU_U_BUDGET", str(8 << 30)))
    except ValueError:
        budget = 8 << 30
    spec = make_u_spec(MAX_BIN + 1, N_FEATURES, None)
    resident = u_bytes(rows, spec)
    out = {
        "rows": rows,
        "k_packed": int(spec.k_pad),
        "budget_bytes": budget,
        "resident_one_hot_bytes": int(resident),
    }
    if resident > budget:
        cspec = chunked_u_spec(rows, spec, budget)
        out["path"] = "mxu_chunked"
        out["chunk_rows"] = int(cspec.chunk_rows)
        out["num_chunks"] = int(num_u_chunks(rows, cspec))
    else:
        out["path"] = "mxu_resident"
    return out


def _hist_bytes_evidence(leaf_batch=8):
    """Analytic bytes-per-build roofline for the 255-bin continuous
    headline shape (deviceless, like the chunked-U selection trace): the
    row-proportional HBM bytes ONE histogram pass over a ``leaf_batch``
    split frontier must stream, per variant. "r05_u_path" is the previous
    round's hot path (resident U, both children built, f32 panel);
    "subtraction" keys only the smaller children (panel width halves,
    siblings derive from the leaf cache); "subtraction_packed" rides the
    quantized int8 panel; "fused_subtraction_packed" is the Pallas
    bin+scatter-add kernel, which reads the raw binned rows once (int32
    lanes + an 8-row f32 aux block) instead of re-streaming the K_pad-byte
    one-hot row."""
    from mmlspark_tpu.ops.u_histogram import make_u_spec

    spec = make_u_spec(MAX_BIN + 1, N_FEATURES, None)
    k = leaf_batch
    per_row = {
        "r05_u_path": spec.k_pad + 3 * 2 * k * 4,
        "subtraction": spec.k_pad + 3 * k * 4,
        "subtraction_packed": spec.k_pad + 3 * k * 1,
        "fused_subtraction_packed": 4 * N_FEATURES + 32 + 3 * k * 1,
    }
    before = per_row["r05_u_path"]
    return {
        "shape": f"{N_FEATURES}cont x {MAX_BIN + 1}bins, leaf_batch={k}",
        "k_packed": int(spec.k_pad),
        "bytes_per_row_per_build": per_row,
        "reduction_vs_r05": {
            name: round(before / b, 3) for name, b in per_row.items()
        },
    }


def _auc(y, score):
    from mmlspark_tpu.lightgbm.objectives import auc

    return auc(y, score, np.ones(len(y)))


def _fit_tpu(
    X, y, Xt, max_bin=MAX_BIN, cat_idx=None, extra_opts=None,
    bundling=False, n_iters=None,
):
    """Returns (wire_secs, resident_secs, binning_host_secs, wire_runs,
    resident_runs, test margins, booster)."""
    from mmlspark_tpu.lightgbm.binning import bin_dataset, bin_dataset_to_device
    from mmlspark_tpu.lightgbm.train import TrainOptions, train

    opts = TrainOptions(
        objective="binary",
        num_iterations=n_iters or N_ITERS,
        num_leaves=NUM_LEAVES,
        learning_rate=LEARNING_RATE,
        max_bin=max_bin,
        growth="leafwise",
        **(extra_opts or {}),
    )
    kw = {"categorical_features": cat_idx} if cat_idx else {}
    if bundling:
        kw["feature_bundling"] = True
    # Compile warm-up: jit programs are shape-specialized, so run ONE
    # full-size fit untimed; the timed runs below then hit the in-process
    # executable cache and measure binning + boosting only. Median of
    # TPU_RUNS timed fits — host<->device transfer latency varies run to
    # run on remote-attached chips, and the CPU side is already a median.
    # Binning + upload run overlapped (bin_dataset_to_device): chunked
    # async device_put hides the host binning behind the wire transfer.
    bins, mapper = bin_dataset_to_device(X, max_bin=max_bin, **kw)
    train(bins, y, opts, mapper=mapper)

    times = []
    result = None
    for _ in range(TPU_RUNS):
        t0 = time.perf_counter()
        bins, mapper = bin_dataset_to_device(X, max_bin=max_bin, **kw)
        result = train(bins, y, opts, mapper=mapper)
        times.append(time.perf_counter() - t0)
    # Decomposition: the same fit with bins already device-resident (median
    # of TPU_RUNS, like the wire-inclusive number). On this rig the host->device
    # wire is a remote-attach tunnel whose throughput swings ~5x run to run;
    # production hosts pay ~1 ms for this transfer (PCIe), so the resident
    # number is the hardware-limited fit time.
    resident = []
    for _ in range(TPU_RUNS):
        t0 = time.perf_counter()
        result = train(bins, y, opts, mapper=mapper)
        resident.append(time.perf_counter() - t0)
    resident_secs = float(np.median(resident))
    # Host-only binning cost (no device in the path) so the artifact's
    # wire-vs-compute split is self-evident: wire ≈ median(times) -
    # resident - binning overlap; binning itself is stable host work.
    t0 = time.perf_counter()
    bin_dataset(X, max_bin=max_bin, **kw)
    binning_secs = time.perf_counter() - t0
    margins = result.booster.raw_margin(Xt)[:, 0]
    return (
        float(np.median(times)),
        resident_secs,
        binning_secs,
        [round(t, 3) for t in times],
        [round(t, 3) for t in resident],
        margins,
        result.booster,
    )


def _predict_throughput_tpu(booster, X, reps=10):
    """Warm on-device predict loop (path-matrix formulation): rows/sec with
    the input device-resident — remote-attach transfer excluded, the same
    measurement discipline as the training number (compile excluded)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mmlspark_tpu.lightgbm.booster import (
        _paths_cache,
        _predict_margin_paths_jit,
    )

    t = booster._used_trees(None)
    pc = _paths_cache(booster, t)
    Xd = jnp.asarray(X, jnp.float32)
    cargs = [jnp.asarray(a) for a in (pc.feats, pc.thrs, pc.nanl, pc.zm, pc.P, pc.plen, pc.lvals)]
    isc = jnp.asarray(booster.init_score)

    @jax.jit
    def loop(Xd, f, th, nl, zm_, Pm, pl, lv, isc):
        def body(i, acc):
            m = _predict_margin_paths_jit(
                Xd * (1 + i.astype(jnp.float32) * 1e-9), f, th, nl, zm_, Pm, pl, lv, isc, 1
            )
            return acc + m[0, 0]

        import jax.lax as _lax

        return _lax.fori_loop(0, reps, body, jnp.float32(0.0))

    float(loop(Xd, *cargs, isc))  # compile
    t0 = time.perf_counter()
    float(loop(Xd, *cargs, isc))
    return len(X) * reps / (time.perf_counter() - t0)


def _predict_throughput_cpu(clf, X, reps=3):
    clf.predict_proba(X[:1000])  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        clf.predict_proba(X)
    return len(X) * reps / (time.perf_counter() - t0)


def _fit_cpu(X, y, Xt, max_bin=MAX_BIN, cat_idx=None):
    """sklearn HistGradientBoosting (LightGBM-style CPU GBDT); median of
    CPU_RUNS fits for a stable baseline. Categorical slots are declared to
    the CPU engine too, so the mixed comparison is algorithm-for-algorithm
    (both sides run native categorical split search)."""
    from sklearn.ensemble import HistGradientBoostingClassifier

    cat_kw = {}
    if cat_idx:
        mask = np.zeros(X.shape[1], dtype=bool)
        mask[cat_idx] = True
        cat_kw["categorical_features"] = mask
    times, margins = [], None
    for run in range(CPU_RUNS):
        clf = HistGradientBoostingClassifier(
            max_iter=N_ITERS,
            max_leaf_nodes=NUM_LEAVES,
            learning_rate=LEARNING_RATE,
            max_bins=max_bin,
            early_stopping=False,
            random_state=run,
            **cat_kw,
        )
        t0 = time.perf_counter()
        clf.fit(X, y)
        times.append(time.perf_counter() - t0)
        margins = clf.decision_function(Xt)
    return float(np.median(times)), margins, clf


def sweep_guard(block):
    """Regression guard for the many-models sweep plane (shared with
    tests/test_sweep.py): batched fitting must beat the candidate-at-a-
    time baseline on models/sec AND actually amortize compilation — at
    least one shape-bucket holds >1 candidate, and the batched run
    compiles strictly fewer programs than it has candidates."""
    cand = block["sweep_candidates"]
    assert cand >= 12, block
    assert max(block["sweep_bucket_sizes"]) > 1, block
    assert block["sweep_batched_compiles"] < cand, block
    assert (
        block["sweep_models_per_sec_batched"]
        > block["sweep_models_per_sec_sequential"]
    ), block
    return block


def _sweep_block():
    """Many-models sweep evidence (docs/automl_sweep.md): a >=12-candidate
    GBDT grid fit through the batched ``TrainValidSweep`` plane vs the
    same candidates fit one at a time, with ``ProfileCompiled`` counts as
    the compile-amortization proof (buckets, not candidates, compile)."""
    from mmlspark_tpu.automl.hyperparam import GridSpace
    from mmlspark_tpu.automl.tune import _evaluate
    from mmlspark_tpu.data.table import Table
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.observability import ProfileCompiled, get_bus
    from mmlspark_tpu.sweep import TrainValidSweep, bucket_candidates

    rows = min(N_ROWS, int(os.environ.get("BENCH_SWEEP_ROWS", 20_000)))
    iters = min(N_ITERS, int(os.environ.get("BENCH_SWEEP_ITERS", 10)))
    n_cand = max(12, int(os.environ.get("BENCH_SWEEP_CANDIDATES", 12)))
    # off-grid learning rates (no collision with the headline fits) x two
    # numLeaves values -> exactly two shape-buckets of n_cand/2 candidates
    lrs = [
        round(float(v), 4)
        for v in np.linspace(0.055, 0.295, -(-n_cand // 2))
    ]
    space = GridSpace({"learningRate": lrs, "numLeaves": [15, 31]})
    maps = list(space.param_maps())

    X, y = _make_data(rows, N_FEATURES, seed=9)
    tbl = Table({"features": X, "label": y.astype(np.float64)})
    est = LightGBMClassifier(
        labelCol="label", featuresCol="features", numIterations=iters,
    )
    buckets = bucket_candidates([(est, m) for m in maps])

    bus = get_bus()
    compiles = []
    listener = (
        lambda e: compiles.append(e.name)
        if isinstance(e, ProfileCompiled) else None
    )

    sweep = TrainValidSweep(
        estimator=est, paramSpace=space, labelCol="label",
        evaluationMetric="AUC", seed=3, commitModel=False,
    )
    bus.add_listener(listener)
    try:
        t0 = time.perf_counter()
        swept = sweep.fit(tbl)
        batched_secs = time.perf_counter() - t0
    finally:
        bus.remove_listener(listener)
    batched_compiles = sum(1 for n in compiles if n == "gbdt.scan_many")

    # candidate-at-a-time baseline on the SAME split/candidates/metric:
    # each distinct learningRate bakes into its own program, so the
    # sequential pass pays one compile per candidate
    mask = sweep._split(tbl.num_rows)
    train, valid = tbl.filter(mask), tbl.filter(~mask)
    compiles.clear()
    bus.add_listener(listener)
    try:
        t0 = time.perf_counter()
        seq_scores = []
        for m in maps:
            fitted = est.copy(m).fit(train)
            seq_scores.append(
                _evaluate(fitted.transform(valid), "label", "AUC")
            )
        seq_secs = time.perf_counter() - t0
    finally:
        bus.remove_listener(listener)
    # the single-model fit compiles as "gbdt.scan" (fused scan path) or
    # "gbdt.step" (per-iteration path on a device mesh) depending on
    # dispatch — either way it is one program per distinct learningRate
    seq_compiles = sum(1 for n in compiles if n in ("gbdt.scan", "gbdt.step"))

    return sweep_guard({
        "sweep_candidates": len(maps),
        "sweep_buckets": len(buckets),
        "sweep_bucket_sizes": [b.size for b in buckets],
        "sweep_rows": rows,
        "sweep_iterations": iters,
        "sweep_batched_secs": round(batched_secs, 3),
        "sweep_sequential_secs": round(seq_secs, 3),
        "sweep_models_per_sec_batched": round(len(maps) / batched_secs, 3),
        "sweep_models_per_sec_sequential": round(len(maps) / seq_secs, 3),
        "sweep_batched_vs_sequential": round(seq_secs / batched_secs, 3),
        "sweep_batched_compiles": batched_compiles,
        "sweep_sequential_compiles": seq_compiles,
        "sweep_best_params": swept.getBestParams(),
        "sweep_best_auc": round(float(swept.getBestMetric()), 5),
    })


def main():
    # the BENCH artifact carries its own attribution: per-program
    # compile/execute timing and the roofline section ride in "profiler"
    from mmlspark_tpu.observability.profiler import get_profiler

    prof = get_profiler().enable()

    # Capture the fit-path evidence events: HistogramChunked is the live
    # proof a fit streamed its U pass in row chunks (vs silently falling
    # off the MXU path), FeatureBundled records each EFB packing decision.
    from mmlspark_tpu.observability import (
        FeatureBundled,
        HistogramChunked,
        HistogramSubtracted,
        get_bus,
    )

    captured = []
    get_bus().add_listener(
        lambda e: captured.append(e)
        if isinstance(e, (FeatureBundled, HistogramChunked, HistogramSubtracted))
        else None
    )

    X, y = _make_data(N_ROWS + N_TEST, N_FEATURES)
    Xtr, ytr = X[:N_ROWS], y[:N_ROWS]
    Xte, yte = X[N_ROWS:], y[N_ROWS:]

    import jax

    backend = jax.default_backend()
    (
        tpu_secs, resident_secs, binning_secs, wire_runs, resident_runs,
        tpu_margins, booster,
    ) = _fit_tpu(Xtr, ytr, Xte)
    tpu_tput = N_ROWS * N_ITERS / tpu_secs
    auc_tpu = _auc(yte, tpu_margins)
    # throughput is per-row: cap the measurement batch so the one-dispatch
    # (N, T, I) decision tensor stays in HBM at any BENCH_ROWS
    pred_rows = min(N_ROWS, 400_000)
    pred_tpu = _predict_throughput_tpu(booster, Xtr[:pred_rows])

    try:
        cpu_secs, cpu_margins, clf = _fit_cpu(Xtr, ytr, Xte)
        cpu_tput = N_ROWS * N_ITERS / cpu_secs
        auc_cpu = _auc(yte, cpu_margins)
        vs = tpu_tput / cpu_tput
        pred_cpu = _predict_throughput_cpu(clf, Xtr[:pred_rows])
    except Exception as e:  # pragma: no cover
        print(f"cpu baseline failed: {e}", file=sys.stderr)
        cpu_secs, auc_cpu, vs, pred_cpu = 0.0, 0.0, 0.0, 0.0

    # Mixed categorical/ordinal workload (realistic tabular distribution,
    # effective B≈64): the packed-U layout's strong regime, reported as its
    # own metric block. The CPU engine gets the same categorical
    # declarations — both sides run their native categorical algorithms.
    mx, my, mcat = _make_mixed_data(N_ROWS + N_TEST, seed=1)
    mXtr, mytr, mXte, myte = mx[:N_ROWS], my[:N_ROWS], mx[N_ROWS:], my[N_ROWS:]
    (
        m_secs, m_resident, m_binning, m_wire_runs, m_resident_runs,
        m_margins, _,
    ) = _fit_tpu(mXtr, mytr, mXte, max_bin=MIXED_MAX_BIN, cat_idx=mcat)
    # TPU-side mixed numbers stand on their own; the CPU-relative keys join
    # only when the baseline engine can run the categorical workload.
    mixed = {
        "gbdt_mixed_train_row_iterations_per_sec": round(
            N_ROWS * N_ITERS / m_secs, 1
        ),
        "gbdt_mixed_tpu_fit_secs": round(m_secs, 3),
        "gbdt_mixed_tpu_fit_secs_device_resident": round(m_resident, 3),
        "gbdt_mixed_binning_host_secs": round(m_binning, 3),
        "gbdt_mixed_auc_tpu": round(float(_auc(myte, m_margins)), 5),
        "gbdt_mixed_wire_runs_secs": m_wire_runs,
        "gbdt_mixed_resident_runs_secs": m_resident_runs,
        "gbdt_mixed_shape": (
            f"{len(MIXED_CARDS)}cat(card<=64)+{MIXED_ORDINALS}ord(64)"
            f"+{MIXED_CONTINUOUS}cont, max_bin={MIXED_MAX_BIN}"
        ),
    }
    try:
        mc_secs, mc_margins, _mclf = _fit_cpu(
            mXtr, mytr, mXte, max_bin=MIXED_MAX_BIN + 1, cat_idx=mcat
        )
        mixed.update(
            {
                "gbdt_mixed_vs_baseline": round(mc_secs / m_secs, 3),
                "gbdt_mixed_vs_baseline_device_resident": round(
                    mc_secs / m_resident, 3
                ),
                "gbdt_mixed_cpu_fit_secs": round(mc_secs, 3),
                "gbdt_mixed_auc_cpu": round(float(_auc(myte, mc_margins)), 5),
            }
        )
    except Exception as e:  # pragma: no cover
        print(f"mixed cpu baseline failed: {e}", file=sys.stderr)

    # Throughput preset on the SAME continuous workload: LightGBM's own
    # gradient-quantization training (use_quantized_grad — 8-bit
    # stochastically-rounded g/h, s8 x s8 integer MXU histogram pass) plus
    # a 16-leaf frontier batch (one fewer U stream per tree). Quality is
    # reported, not assumed: AUC lands within ~0.001 of the exact fit and
    # above the CPU engine's. Compared against the same CPU run as the
    # headline (the CPU engine has no quantized mode at matched settings).
    (
        q_secs, q_resident, _q_binning, _q_wire_runs, q_resident_runs,
        q_margins, _,
    ) = _fit_tpu(
        Xtr, ytr, Xte,
        extra_opts={"use_quantized_grad": True, "leaf_batch": 16},
    )
    quant = {
        "gbdt_quant_train_row_iterations_per_sec": round(
            N_ROWS * N_ITERS / q_secs, 1
        ),
        "gbdt_quant_tpu_fit_secs": round(q_secs, 3),
        "gbdt_quant_tpu_fit_secs_device_resident": round(q_resident, 3),
        "gbdt_quant_auc_tpu": round(float(_auc(yte, q_margins)), 5),
        "gbdt_quant_resident_runs_secs": q_resident_runs,
        "gbdt_quant_config": "use_quantized_grad=True, leaf_batch=16",
    }
    if cpu_secs:
        quant["gbdt_quant_vs_baseline"] = round(cpu_secs / q_secs, 3)
        quant["gbdt_quant_vs_baseline_device_resident"] = round(
            cpu_secs / q_resident, 3
        )

    # Sibling-subtraction A/B on the headline shape: the headline and
    # quant fits above already run subtraction (the default); this block
    # re-fits both with histogram_subtraction=False so the artifact
    # carries the measured on/off delta AND the parity clause — the
    # default-config dAUC is the CI regression guard (<= 2e-5).
    (
        _so_secs, so_resident, _sob, _sowr, _sorr, so_margins, _,
    ) = _fit_tpu(
        Xtr, ytr, Xte, extra_opts={"histogram_subtraction": False},
    )
    so_auc = float(_auc(yte, so_margins))
    (
        _qo_secs, qo_resident, _qob, _qowr, _qorr, qo_margins, _,
    ) = _fit_tpu(
        Xtr, ytr, Xte,
        extra_opts={
            "use_quantized_grad": True, "leaf_batch": 16,
            "histogram_subtraction": False,
        },
    )
    # Quant-path byte-identity, measured live: subtraction is an integer
    # subtraction of integer partial sums, so the model text must be
    # byte-identical on/off. The quant preset above auto-selects the U
    # path only on TPU backends, so this check FORCES histogram_method='u'
    # (runs everywhere, CPU smoke included) at a declared reduced scale.
    import dataclasses as _dc

    from mmlspark_tpu.lightgbm.binning import bin_dataset as _bin
    from mmlspark_tpu.lightgbm.train import TrainOptions as _TO
    from mmlspark_tpu.lightgbm.train import train as _train

    qi_rows = min(N_ROWS, 50_000)
    qi_iters = min(N_ITERS, 20)
    qi_opts = _TO(
        objective="binary", num_iterations=qi_iters, num_leaves=NUM_LEAVES,
        learning_rate=LEARNING_RATE, max_bin=MAX_BIN, growth="leafwise",
        histogram_method="u", use_quantized_grad=True,
    )
    qi_bins, qi_mapper = _bin(Xtr[:qi_rows], max_bin=MAX_BIN)
    qi_on = _train(qi_bins, ytr[:qi_rows], qi_opts, mapper=qi_mapper)
    qi_off = _train(
        qi_bins, ytr[:qi_rows],
        _dc.replace(qi_opts, histogram_subtraction=False),
        mapper=qi_mapper,
    )
    sub = {
        "gbdt_sub_config": "histogram_subtraction A/B, headline shape",
        "gbdt_sub_on_fit_secs_device_resident": round(resident_secs, 3),
        "gbdt_sub_off_fit_secs_device_resident": round(so_resident, 3),
        "gbdt_sub_speedup_device_resident": round(
            so_resident / resident_secs, 3
        ),
        "gbdt_sub_dauc": round(abs(float(auc_tpu) - so_auc), 7),
        "gbdt_quant_sub_off_fit_secs_device_resident": round(qo_resident, 3),
        "gbdt_quant_sub_speedup_device_resident": round(
            qo_resident / q_resident, 3
        ),
        # the quant preset's own margins on/off — informational; identical
        # only where the preset actually rides the quantized U path (TPU)
        "gbdt_quant_sub_max_abs_margin_delta": float(
            np.max(np.abs(np.asarray(q_margins) - np.asarray(qo_margins)))
        ),
        "gbdt_quant_sub_byte_identical": bool(
            qi_on.booster.model_to_string()
            == qi_off.booster.model_to_string()
        ),
        "gbdt_quant_sub_byte_identity_config": (
            f"histogram_method='u', use_quantized_grad=True,"
            f" rows={qi_rows}, iterations={qi_iters}"
        ),
    }

    # Sparse one-hot workload: the Exclusive Feature Bundling regime.
    # Same fit bundled and unbundled; the block reports the measured K
    # (= Σ_f B_f histogram width) before/after packing, both fit times,
    # and both AUCs — the parity clause is |ΔAUC|, not a vibe.
    sx, sy = _make_sparse_data(SPARSE_ROWS + N_TEST)
    sXtr, sytr = sx[:SPARSE_ROWS], sy[:SPARSE_ROWS]
    sXte, syte = sx[SPARSE_ROWS:], sy[SPARSE_ROWS:]
    s_k_before, s_k_after, s_f, s_cols, s_conf = _bundling_k(
        sXtr, SPARSE_MAX_BIN
    )
    (s_secs, s_resident, _sb, _swr, _srr, s_margins, _) = _fit_tpu(
        sXtr, sytr, sXte, max_bin=SPARSE_MAX_BIN
    )
    (sb_secs, sb_resident, _sbb, _sbwr, _sbrr, sb_margins, _) = _fit_tpu(
        sXtr, sytr, sXte, max_bin=SPARSE_MAX_BIN, bundling=True
    )
    s_auc, sb_auc = float(_auc(syte, s_margins)), float(_auc(syte, sb_margins))
    sparse = {
        "gbdt_sparse_shape": (
            f"{SPARSE_BLOCKS}x{SPARSE_CARD} one-hot blocks"
            f"+{SPARSE_CONTINUOUS}cont, rows={SPARSE_ROWS},"
            f" max_bin={SPARSE_MAX_BIN}"
        ),
        "gbdt_sparse_k_before_bundling": s_k_before,
        "gbdt_sparse_k_after_bundling": s_k_after,
        "gbdt_sparse_k_reduction": round(s_k_before / max(s_k_after, 1), 3),
        "gbdt_sparse_columns_before": s_f,
        "gbdt_sparse_columns_after": s_cols,
        "gbdt_sparse_bundle_conflicts": s_conf,
        "gbdt_sparse_tpu_fit_secs": round(s_secs, 3),
        "gbdt_sparse_tpu_fit_secs_bundled": round(sb_secs, 3),
        "gbdt_sparse_tpu_fit_secs_device_resident": round(s_resident, 3),
        "gbdt_sparse_tpu_fit_secs_device_resident_bundled": round(
            sb_resident, 3
        ),
        "gbdt_sparse_bundled_speedup_device_resident": round(
            s_resident / sb_resident, 3
        ),
        "gbdt_sparse_auc_tpu": round(s_auc, 5),
        "gbdt_sparse_auc_tpu_bundled": round(sb_auc, 5),
        "gbdt_sparse_bundling_dauc": round(abs(s_auc - sb_auc), 6),
    }

    # Real-dataset mode (ROADMAP 5a): the vendored Covertype sample when
    # tools/fetch_covtype.py has run, else sklearn's bundled digits — the
    # synthetic-only bench criticism, answered with labeled provenance.
    r_src, rX, ry = _load_real_data()
    r_rows = len(rX)
    r_split = max(1, int(r_rows * 0.8))
    r_iters = min(N_ITERS, 100)
    rXtr, rytr = rX[:r_split], ry[:r_split]
    rXte, ryte = rX[r_split:], ry[r_split:]
    r_k_before, r_k_after, _rf, _rc, r_conf = _bundling_k(rXtr, MAX_BIN)
    (r_secs, r_resident, _rb, _rwr, _rrr, r_margins, _) = _fit_tpu(
        rXtr, rytr, rXte, n_iters=r_iters
    )
    (rb_secs, rb_resident, _rbb, _rbwr, _rbrr, rb_margins, _) = _fit_tpu(
        rXtr, rytr, rXte, n_iters=r_iters, bundling=True
    )
    r_auc = float(_auc(ryte, r_margins))
    rb_auc = float(_auc(ryte, rb_margins))
    real = {
        "gbdt_real_source": r_src,
        "gbdt_real_rows": r_rows,
        "gbdt_real_features": int(rX.shape[1]),
        "gbdt_real_iterations": r_iters,
        "gbdt_real_k_before_bundling": r_k_before,
        "gbdt_real_k_after_bundling": r_k_after,
        "gbdt_real_bundle_conflicts": r_conf,
        "gbdt_real_tpu_fit_secs": round(r_secs, 3),
        "gbdt_real_tpu_fit_secs_bundled": round(rb_secs, 3),
        "gbdt_real_tpu_fit_secs_device_resident": round(r_resident, 3),
        "gbdt_real_tpu_fit_secs_device_resident_bundled": round(
            rb_resident, 3
        ),
        "gbdt_real_auc_tpu": round(r_auc, 5),
        "gbdt_real_auc_tpu_bundled": round(rb_auc, 5),
        "gbdt_real_bundling_dauc": round(abs(r_auc - rb_auc), 6),
    }
    # When the digits fallback is active because a covtype download was
    # tried and failed (network-less container), carry the recorded
    # attempt so the provenance is "attempted, unreachable" rather than
    # silently synthetic-adjacent.
    attempt_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tests", "fixtures", "covtype_fetch_attempt.json",
    )
    if r_src != "covtype_sample" and os.path.exists(attempt_path):
        with open(attempt_path) as f:
            real["gbdt_real_covtype_fetch_attempt"] = json.load(f)
    try:
        rc_secs, rc_margins, _rclf = _fit_cpu(rXtr, rytr, rXte)
        real["gbdt_real_cpu_fit_secs"] = round(rc_secs, 3)
        real["gbdt_real_auc_cpu"] = round(float(_auc(ryte, rc_margins)), 5)
        real["gbdt_real_vs_baseline_device_resident"] = round(
            rc_secs / r_resident, 3
        )
    except Exception as e:  # pragma: no cover
        print(f"real cpu baseline failed: {e}", file=sys.stderr)

    # Many-models sweep: >=12-candidate grid, batched vs sequential
    # models/sec, ProfileCompiled amortization proof. sweep_guard raises
    # inside — a regression here fails the bench job, not just a number.
    sweep = _sweep_block()

    chunk_events = [
        {
            "rows": e.rows,
            "k_packed": e.k_packed,
            "chunk_rows": e.chunk_rows,
            "num_chunks": e.num_chunks,
            "budget_bytes": e.budget_bytes,
            "acc_dtype": e.acc_dtype,
            "bytes_saved": e.bytes_saved,
        }
        for e in captured
        if isinstance(e, HistogramChunked)
    ]
    sub_events = [
        {
            "rows": e.rows,
            "num_leaves": e.num_leaves,
            "packed_columns": e.packed_columns,
            "packed_bins": e.packed_bins,
            "acc_dtype": e.acc_dtype,
            "cache_bytes": e.cache_bytes,
            "bytes_saved_per_tree": e.bytes_saved_per_tree,
        }
        for e in captured
        if isinstance(e, HistogramSubtracted)
    ]
    bundle_events = [
        {
            "num_features": e.num_features,
            "num_columns": e.num_columns,
            "k_before": e.k_before,
            "k_after": e.k_after,
            "conflicts": e.conflicts,
        }
        for e in captured
        if isinstance(e, FeatureBundled)
    ]

    print(
        json.dumps(
            {
                "metric": f"gbdt_leafwise_train_row_iterations_per_sec_{backend}",
                "value": round(tpu_tput, 1),
                "unit": "rows*iters/sec",
                "vs_baseline": round(vs, 3),
                "tpu_fit_secs": round(tpu_secs, 3),
                "tpu_fit_secs_device_resident": round(resident_secs, 3),
                "vs_baseline_device_resident": (
                    round(cpu_secs / resident_secs, 3) if cpu_secs else 0.0
                ),
                # Decomposition so the artifact explains its own variance:
                # wire = what the tunnel upload adds over the resident fit;
                # per-run lists expose the tunnel's 5x run-to-run swing.
                "binning_host_secs": round(binning_secs, 3),
                "upload_overhead_secs": round(tpu_secs - resident_secs, 3),
                "wire_runs_secs": wire_runs,
                "resident_runs_secs": resident_runs,
                "cpu_fit_secs": round(cpu_secs, 3),
                "auc_tpu": round(float(auc_tpu), 5),
                "auc_cpu": round(float(auc_cpu), 5),
                "predict_rows_per_sec_tpu": round(pred_tpu, 0),
                "predict_rows_per_sec_cpu": round(pred_cpu, 0),
                "predict_vs_cpu": round(pred_tpu / pred_cpu, 2) if pred_cpu else 0.0,
                "cpu_engine": "sklearn.HistGradientBoostingClassifier(median of 3)",
                # Declared configs, stated where the numbers live: every
                # block above runs the DEFAULT config (exact bf16
                # histogram accumulation) unless its *_config key says
                # otherwise; the 9.6x-class throughput preset is opt-in.
                "gbdt_default_config": (
                    "exact bf16 histograms: use_quantized_grad=False,"
                    " leaf_batch=8, histogram_subtraction=True"
                ),
                "gbdt_fast_preset": (
                    "use_quantized_grad=True, leaf_batch=16 (opt-in;"
                    " measured in the gbdt_quant_* block)"
                ),
                **mixed,
                **quant,
                **sub,
                **sparse,
                **real,
                **sweep,
                # Chunked-U evidence: the static 4M-row selection trace
                # (proof the >1M shape compiles to the streamed MXU path)
                # plus any HistogramChunked events the fits above actually
                # published — live at BENCH_ROWS large enough to exceed
                # MMLSPARK_TPU_U_BUDGET.
                "u_chunking_4m_selection": _chunked_u_evidence(),
                # Bytes-per-build roofline for the 255-bin continuous
                # shape: the byte reduction subtraction + packed panels +
                # the fused bin+scatter kernel buy per histogram pass.
                "hist_bytes_per_build_255bin": _hist_bytes_evidence(),
                "histogram_chunked_events": chunk_events[:8],
                "histogram_chunked_event_count": len(chunk_events),
                "histogram_subtracted_events": sub_events[:8],
                "histogram_subtracted_event_count": len(sub_events),
                "feature_bundled_events": bundle_events[:8],
                "profiler": prof.snapshot(),
            }
        )
    )


if __name__ == "__main__":
    main()
