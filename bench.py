"""Headline benchmark: GBDT training on TPU vs a REAL CPU GBDT.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, ...}

Workload: binary-classification boosting on a Higgs-like dense matrix
(BASELINE.json config 3's shape at bench-friendly scale), leaf-wise growth
with LightGBM-default 31 leaves — the flagship semantics.

``value`` is TPU row-iterations/sec (rows × boosting iterations / fit wall
time; binning included, one-time XLA compile excluded — production runs hit
the persistent compilation cache). ``vs_baseline`` is the speedup over
sklearn's ``HistGradientBoostingClassifier`` — the same histogram-GBDT
algorithm family as LightGBM, run at matched settings (same rows, features,
iterations, leaves, bins, learning rate; median of 3 runs). Both sides also
report held-out AUC so the comparison is at matched quality, per the
"identical AUC" clause of the ≥10× north star (BASELINE.md).
"""

import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 400_000))
N_FEATURES = int(os.environ.get("BENCH_FEATURES", 28))
N_ITERS = int(os.environ.get("BENCH_ITERS", 100))  # LightGBM's default
N_TEST = 50_000
NUM_LEAVES = 31
LEARNING_RATE = 0.1
MAX_BIN = 255
CPU_RUNS = 3
TPU_RUNS = 5  # median-of-5: per-run tunnel transfer variance is ±0.5s


def _make_data(n, f, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float64)
    logit = (
        X[:, 0] * 1.5
        + X[:, 1] * X[:, 2]
        + 0.8 * np.sin(X[:, 3])
        + 0.5 * rng.normal(size=n)
    )
    y = (logit > 0).astype(np.float64)
    return X, y


# Mixed workload: the data distribution real tabular users have —
# categorical + ordinal + a few continuous columns (the reference's own
# perf claims are dataset-level, lightgbm.md:17-21). Effective bin width
# B≈64, the regime where the packed-U layout (K = Σ_f B_f) shines.
MIXED_CARDS = (4, 8, 12, 16, 24, 32, 48, 64)  # 8 categorical features
MIXED_ORDINALS = 12  # integer features with <= 64 levels
MIXED_CONTINUOUS = 8
MIXED_MAX_BIN = 63


def _make_mixed_data(n, seed=0):
    rng = np.random.default_rng(seed)
    cats = [rng.integers(0, c, size=n).astype(np.float64) for c in MIXED_CARDS]
    effs = [rng.normal(size=c) for c in MIXED_CARDS]
    ords = [
        rng.integers(0, 64, size=n).astype(np.float64)
        for _ in range(MIXED_ORDINALS)
    ]
    conts = rng.normal(size=(n, MIXED_CONTINUOUS))
    logit = (
        effs[1][cats[1].astype(int)]
        + 0.8 * effs[4][cats[4].astype(int)]
        + 0.03 * (ords[0] - 32)
        + 0.5 * ((ords[1] > 40) & (cats[0] == 2))
        + conts[:, 0]
        + 0.6 * rng.normal(size=n)
    )
    y = (logit > 0).astype(np.float64)
    X = np.column_stack(cats + ords + [conts])
    cat_idx = list(range(len(MIXED_CARDS)))
    return X, y, cat_idx


def _auc(y, score):
    from mmlspark_tpu.lightgbm.objectives import auc

    return auc(y, score, np.ones(len(y)))


def _fit_tpu(X, y, Xt, max_bin=MAX_BIN, cat_idx=None, extra_opts=None):
    """Returns (wire_secs, resident_secs, binning_host_secs, wire_runs,
    resident_runs, test margins, booster)."""
    from mmlspark_tpu.lightgbm.binning import bin_dataset, bin_dataset_to_device
    from mmlspark_tpu.lightgbm.train import TrainOptions, train

    opts = TrainOptions(
        objective="binary",
        num_iterations=N_ITERS,
        num_leaves=NUM_LEAVES,
        learning_rate=LEARNING_RATE,
        max_bin=max_bin,
        growth="leafwise",
        **(extra_opts or {}),
    )
    kw = {"categorical_features": cat_idx} if cat_idx else {}
    # Compile warm-up: jit programs are shape-specialized, so run ONE
    # full-size fit untimed; the timed runs below then hit the in-process
    # executable cache and measure binning + boosting only. Median of
    # TPU_RUNS timed fits — host<->device transfer latency varies run to
    # run on remote-attached chips, and the CPU side is already a median.
    # Binning + upload run overlapped (bin_dataset_to_device): chunked
    # async device_put hides the host binning behind the wire transfer.
    bins, mapper = bin_dataset_to_device(X, max_bin=max_bin, **kw)
    train(bins, y, opts, mapper=mapper)

    times = []
    result = None
    for _ in range(TPU_RUNS):
        t0 = time.perf_counter()
        bins, mapper = bin_dataset_to_device(X, max_bin=max_bin, **kw)
        result = train(bins, y, opts, mapper=mapper)
        times.append(time.perf_counter() - t0)
    # Decomposition: the same fit with bins already device-resident (median
    # of TPU_RUNS, like the wire-inclusive number). On this rig the host->device
    # wire is a remote-attach tunnel whose throughput swings ~5x run to run;
    # production hosts pay ~1 ms for this transfer (PCIe), so the resident
    # number is the hardware-limited fit time.
    resident = []
    for _ in range(TPU_RUNS):
        t0 = time.perf_counter()
        result = train(bins, y, opts, mapper=mapper)
        resident.append(time.perf_counter() - t0)
    resident_secs = float(np.median(resident))
    # Host-only binning cost (no device in the path) so the artifact's
    # wire-vs-compute split is self-evident: wire ≈ median(times) -
    # resident - binning overlap; binning itself is stable host work.
    t0 = time.perf_counter()
    bin_dataset(X, max_bin=max_bin, **kw)
    binning_secs = time.perf_counter() - t0
    margins = result.booster.raw_margin(Xt)[:, 0]
    return (
        float(np.median(times)),
        resident_secs,
        binning_secs,
        [round(t, 3) for t in times],
        [round(t, 3) for t in resident],
        margins,
        result.booster,
    )


def _predict_throughput_tpu(booster, X, reps=10):
    """Warm on-device predict loop (path-matrix formulation): rows/sec with
    the input device-resident — remote-attach transfer excluded, the same
    measurement discipline as the training number (compile excluded)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mmlspark_tpu.lightgbm.booster import (
        _paths_cache,
        _predict_margin_paths_jit,
    )

    t = booster._used_trees(None)
    pc = _paths_cache(booster, t)
    Xd = jnp.asarray(X, jnp.float32)
    cargs = [jnp.asarray(a) for a in (pc.feats, pc.thrs, pc.nanl, pc.zm, pc.P, pc.plen, pc.lvals)]
    isc = jnp.asarray(booster.init_score)

    @jax.jit
    def loop(Xd, f, th, nl, zm_, Pm, pl, lv, isc):
        def body(i, acc):
            m = _predict_margin_paths_jit(
                Xd * (1 + i.astype(jnp.float32) * 1e-9), f, th, nl, zm_, Pm, pl, lv, isc, 1
            )
            return acc + m[0, 0]

        import jax.lax as _lax

        return _lax.fori_loop(0, reps, body, jnp.float32(0.0))

    float(loop(Xd, *cargs, isc))  # compile
    t0 = time.perf_counter()
    float(loop(Xd, *cargs, isc))
    return len(X) * reps / (time.perf_counter() - t0)


def _predict_throughput_cpu(clf, X, reps=3):
    clf.predict_proba(X[:1000])  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        clf.predict_proba(X)
    return len(X) * reps / (time.perf_counter() - t0)


def _fit_cpu(X, y, Xt, max_bin=MAX_BIN, cat_idx=None):
    """sklearn HistGradientBoosting (LightGBM-style CPU GBDT); median of
    CPU_RUNS fits for a stable baseline. Categorical slots are declared to
    the CPU engine too, so the mixed comparison is algorithm-for-algorithm
    (both sides run native categorical split search)."""
    from sklearn.ensemble import HistGradientBoostingClassifier

    cat_kw = {}
    if cat_idx:
        mask = np.zeros(X.shape[1], dtype=bool)
        mask[cat_idx] = True
        cat_kw["categorical_features"] = mask
    times, margins = [], None
    for run in range(CPU_RUNS):
        clf = HistGradientBoostingClassifier(
            max_iter=N_ITERS,
            max_leaf_nodes=NUM_LEAVES,
            learning_rate=LEARNING_RATE,
            max_bins=max_bin,
            early_stopping=False,
            random_state=run,
            **cat_kw,
        )
        t0 = time.perf_counter()
        clf.fit(X, y)
        times.append(time.perf_counter() - t0)
        margins = clf.decision_function(Xt)
    return float(np.median(times)), margins, clf


def main():
    # the BENCH artifact carries its own attribution: per-program
    # compile/execute timing and the roofline section ride in "profiler"
    from mmlspark_tpu.observability.profiler import get_profiler

    prof = get_profiler().enable()

    X, y = _make_data(N_ROWS + N_TEST, N_FEATURES)
    Xtr, ytr = X[:N_ROWS], y[:N_ROWS]
    Xte, yte = X[N_ROWS:], y[N_ROWS:]

    import jax

    backend = jax.default_backend()
    (
        tpu_secs, resident_secs, binning_secs, wire_runs, resident_runs,
        tpu_margins, booster,
    ) = _fit_tpu(Xtr, ytr, Xte)
    tpu_tput = N_ROWS * N_ITERS / tpu_secs
    auc_tpu = _auc(yte, tpu_margins)
    # throughput is per-row: cap the measurement batch so the one-dispatch
    # (N, T, I) decision tensor stays in HBM at any BENCH_ROWS
    pred_rows = min(N_ROWS, 400_000)
    pred_tpu = _predict_throughput_tpu(booster, Xtr[:pred_rows])

    try:
        cpu_secs, cpu_margins, clf = _fit_cpu(Xtr, ytr, Xte)
        cpu_tput = N_ROWS * N_ITERS / cpu_secs
        auc_cpu = _auc(yte, cpu_margins)
        vs = tpu_tput / cpu_tput
        pred_cpu = _predict_throughput_cpu(clf, Xtr[:pred_rows])
    except Exception as e:  # pragma: no cover
        print(f"cpu baseline failed: {e}", file=sys.stderr)
        cpu_secs, auc_cpu, vs, pred_cpu = 0.0, 0.0, 0.0, 0.0

    # Mixed categorical/ordinal workload (realistic tabular distribution,
    # effective B≈64): the packed-U layout's strong regime, reported as its
    # own metric block. The CPU engine gets the same categorical
    # declarations — both sides run their native categorical algorithms.
    mx, my, mcat = _make_mixed_data(N_ROWS + N_TEST, seed=1)
    mXtr, mytr, mXte, myte = mx[:N_ROWS], my[:N_ROWS], mx[N_ROWS:], my[N_ROWS:]
    (
        m_secs, m_resident, m_binning, m_wire_runs, m_resident_runs,
        m_margins, _,
    ) = _fit_tpu(mXtr, mytr, mXte, max_bin=MIXED_MAX_BIN, cat_idx=mcat)
    # TPU-side mixed numbers stand on their own; the CPU-relative keys join
    # only when the baseline engine can run the categorical workload.
    mixed = {
        "gbdt_mixed_train_row_iterations_per_sec": round(
            N_ROWS * N_ITERS / m_secs, 1
        ),
        "gbdt_mixed_tpu_fit_secs": round(m_secs, 3),
        "gbdt_mixed_tpu_fit_secs_device_resident": round(m_resident, 3),
        "gbdt_mixed_binning_host_secs": round(m_binning, 3),
        "gbdt_mixed_auc_tpu": round(float(_auc(myte, m_margins)), 5),
        "gbdt_mixed_wire_runs_secs": m_wire_runs,
        "gbdt_mixed_resident_runs_secs": m_resident_runs,
        "gbdt_mixed_shape": (
            f"{len(MIXED_CARDS)}cat(card<=64)+{MIXED_ORDINALS}ord(64)"
            f"+{MIXED_CONTINUOUS}cont, max_bin={MIXED_MAX_BIN}"
        ),
    }
    try:
        mc_secs, mc_margins, _mclf = _fit_cpu(
            mXtr, mytr, mXte, max_bin=MIXED_MAX_BIN + 1, cat_idx=mcat
        )
        mixed.update(
            {
                "gbdt_mixed_vs_baseline": round(mc_secs / m_secs, 3),
                "gbdt_mixed_vs_baseline_device_resident": round(
                    mc_secs / m_resident, 3
                ),
                "gbdt_mixed_cpu_fit_secs": round(mc_secs, 3),
                "gbdt_mixed_auc_cpu": round(float(_auc(myte, mc_margins)), 5),
            }
        )
    except Exception as e:  # pragma: no cover
        print(f"mixed cpu baseline failed: {e}", file=sys.stderr)

    # Throughput preset on the SAME continuous workload: LightGBM's own
    # gradient-quantization training (use_quantized_grad — 8-bit
    # stochastically-rounded g/h, s8 x s8 integer MXU histogram pass) plus
    # a 16-leaf frontier batch (one fewer U stream per tree). Quality is
    # reported, not assumed: AUC lands within ~0.001 of the exact fit and
    # above the CPU engine's. Compared against the same CPU run as the
    # headline (the CPU engine has no quantized mode at matched settings).
    (
        q_secs, q_resident, _q_binning, _q_wire_runs, q_resident_runs,
        q_margins, _,
    ) = _fit_tpu(
        Xtr, ytr, Xte,
        extra_opts={"use_quantized_grad": True, "leaf_batch": 16},
    )
    quant = {
        "gbdt_quant_train_row_iterations_per_sec": round(
            N_ROWS * N_ITERS / q_secs, 1
        ),
        "gbdt_quant_tpu_fit_secs": round(q_secs, 3),
        "gbdt_quant_tpu_fit_secs_device_resident": round(q_resident, 3),
        "gbdt_quant_auc_tpu": round(float(_auc(yte, q_margins)), 5),
        "gbdt_quant_resident_runs_secs": q_resident_runs,
        "gbdt_quant_config": "use_quantized_grad=True, leaf_batch=16",
    }
    if cpu_secs:
        quant["gbdt_quant_vs_baseline"] = round(cpu_secs / q_secs, 3)
        quant["gbdt_quant_vs_baseline_device_resident"] = round(
            cpu_secs / q_resident, 3
        )

    print(
        json.dumps(
            {
                "metric": f"gbdt_leafwise_train_row_iterations_per_sec_{backend}",
                "value": round(tpu_tput, 1),
                "unit": "rows*iters/sec",
                "vs_baseline": round(vs, 3),
                "tpu_fit_secs": round(tpu_secs, 3),
                "tpu_fit_secs_device_resident": round(resident_secs, 3),
                "vs_baseline_device_resident": (
                    round(cpu_secs / resident_secs, 3) if cpu_secs else 0.0
                ),
                # Decomposition so the artifact explains its own variance:
                # wire = what the tunnel upload adds over the resident fit;
                # per-run lists expose the tunnel's 5x run-to-run swing.
                "binning_host_secs": round(binning_secs, 3),
                "upload_overhead_secs": round(tpu_secs - resident_secs, 3),
                "wire_runs_secs": wire_runs,
                "resident_runs_secs": resident_runs,
                "cpu_fit_secs": round(cpu_secs, 3),
                "auc_tpu": round(float(auc_tpu), 5),
                "auc_cpu": round(float(auc_cpu), 5),
                "predict_rows_per_sec_tpu": round(pred_tpu, 0),
                "predict_rows_per_sec_cpu": round(pred_cpu, 0),
                "predict_vs_cpu": round(pred_tpu / pred_cpu, 2) if pred_cpu else 0.0,
                "cpu_engine": "sklearn.HistGradientBoostingClassifier(median of 3)",
                **mixed,
                **quant,
                "profiler": prof.snapshot(),
            }
        )
    )


if __name__ == "__main__":
    main()
