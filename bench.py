"""Headline benchmark: GBDT training on TPU vs a REAL CPU GBDT.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, ...}

Workload: binary-classification boosting on a Higgs-like dense matrix
(BASELINE.json config 3's shape at bench-friendly scale), leaf-wise growth
with LightGBM-default 31 leaves — the flagship semantics.

``value`` is TPU row-iterations/sec (rows × boosting iterations / fit wall
time; binning included, one-time XLA compile excluded — production runs hit
the persistent compilation cache). ``vs_baseline`` is the speedup over
sklearn's ``HistGradientBoostingClassifier`` — the same histogram-GBDT
algorithm family as LightGBM, run at matched settings (same rows, features,
iterations, leaves, bins, learning rate; median of 3 runs). Both sides also
report held-out AUC so the comparison is at matched quality, per the
"identical AUC" clause of the ≥10× north star (BASELINE.md).
"""

import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 400_000))
N_FEATURES = int(os.environ.get("BENCH_FEATURES", 28))
N_ITERS = int(os.environ.get("BENCH_ITERS", 100))  # LightGBM's default
N_TEST = 50_000
NUM_LEAVES = 31
LEARNING_RATE = 0.1
MAX_BIN = 255
CPU_RUNS = 3
TPU_RUNS = 5  # median-of-5: per-run tunnel transfer variance is ±0.5s


def _make_data(n, f, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float64)
    logit = (
        X[:, 0] * 1.5
        + X[:, 1] * X[:, 2]
        + 0.8 * np.sin(X[:, 3])
        + 0.5 * rng.normal(size=n)
    )
    y = (logit > 0).astype(np.float64)
    return X, y


def _auc(y, score):
    from mmlspark_tpu.lightgbm.objectives import auc

    return auc(y, score, np.ones(len(y)))


def _fit_tpu(X, y, Xt):
    """Returns (fit_seconds excluding compile, test margins)."""
    from mmlspark_tpu.lightgbm.binning import bin_dataset_to_device
    from mmlspark_tpu.lightgbm.train import TrainOptions, train

    opts = TrainOptions(
        objective="binary",
        num_iterations=N_ITERS,
        num_leaves=NUM_LEAVES,
        learning_rate=LEARNING_RATE,
        max_bin=MAX_BIN,
        growth="leafwise",
    )
    # Compile warm-up: jit programs are shape-specialized, so run ONE
    # full-size fit untimed; the timed runs below then hit the in-process
    # executable cache and measure binning + boosting only. Median of
    # TPU_RUNS timed fits — host<->device transfer latency varies run to
    # run on remote-attached chips, and the CPU side is already a median.
    # Binning + upload run overlapped (bin_dataset_to_device): chunked
    # async device_put hides the host binning behind the wire transfer.
    bins, mapper = bin_dataset_to_device(X, max_bin=MAX_BIN)
    train(bins, y, opts, mapper=mapper)

    times = []
    result = None
    for _ in range(TPU_RUNS):
        t0 = time.perf_counter()
        bins, mapper = bin_dataset_to_device(X, max_bin=MAX_BIN)
        result = train(bins, y, opts, mapper=mapper)
        times.append(time.perf_counter() - t0)
    # Decomposition: the same fit with bins already device-resident (median
    # of TPU_RUNS, like the wire-inclusive number). On this rig the host->device
    # wire is a remote-attach tunnel whose throughput swings ~5x run to run;
    # production hosts pay ~1 ms for this transfer (PCIe), so the resident
    # number is the hardware-limited fit time.
    resident = []
    for _ in range(TPU_RUNS):
        t0 = time.perf_counter()
        result = train(bins, y, opts, mapper=mapper)
        resident.append(time.perf_counter() - t0)
    resident_secs = float(np.median(resident))
    margins = result.booster.raw_margin(Xt)[:, 0]
    return float(np.median(times)), resident_secs, margins, result.booster


def _predict_throughput_tpu(booster, X, reps=10):
    """Warm on-device predict loop (path-matrix formulation): rows/sec with
    the input device-resident — remote-attach transfer excluded, the same
    measurement discipline as the training number (compile excluded)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mmlspark_tpu.lightgbm.booster import (
        _paths_cache,
        _predict_margin_paths_jit,
    )

    t = booster._used_trees(None)
    pc = _paths_cache(booster, t)
    Xd = jnp.asarray(X, jnp.float32)
    cargs = [jnp.asarray(a) for a in (pc.feats, pc.thrs, pc.nanl, pc.zm, pc.P, pc.plen, pc.lvals)]
    isc = jnp.asarray(booster.init_score)

    @jax.jit
    def loop(Xd, f, th, nl, zm_, Pm, pl, lv, isc):
        def body(i, acc):
            m = _predict_margin_paths_jit(
                Xd * (1 + i.astype(jnp.float32) * 1e-9), f, th, nl, zm_, Pm, pl, lv, isc, 1
            )
            return acc + m[0, 0]

        import jax.lax as _lax

        return _lax.fori_loop(0, reps, body, jnp.float32(0.0))

    float(loop(Xd, *cargs, isc))  # compile
    t0 = time.perf_counter()
    float(loop(Xd, *cargs, isc))
    return len(X) * reps / (time.perf_counter() - t0)


def _predict_throughput_cpu(clf, X, reps=3):
    clf.predict_proba(X[:1000])  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        clf.predict_proba(X)
    return len(X) * reps / (time.perf_counter() - t0)


def _fit_cpu(X, y, Xt):
    """sklearn HistGradientBoosting (LightGBM-style CPU GBDT); median of
    CPU_RUNS fits for a stable baseline."""
    from sklearn.ensemble import HistGradientBoostingClassifier

    times, margins = [], None
    for run in range(CPU_RUNS):
        clf = HistGradientBoostingClassifier(
            max_iter=N_ITERS,
            max_leaf_nodes=NUM_LEAVES,
            learning_rate=LEARNING_RATE,
            max_bins=MAX_BIN,
            early_stopping=False,
            random_state=run,
        )
        t0 = time.perf_counter()
        clf.fit(X, y)
        times.append(time.perf_counter() - t0)
        margins = clf.decision_function(Xt)
    return float(np.median(times)), margins, clf


def main():
    X, y = _make_data(N_ROWS + N_TEST, N_FEATURES)
    Xtr, ytr = X[:N_ROWS], y[:N_ROWS]
    Xte, yte = X[N_ROWS:], y[N_ROWS:]

    import jax

    backend = jax.default_backend()
    tpu_secs, resident_secs, tpu_margins, booster = _fit_tpu(Xtr, ytr, Xte)
    tpu_tput = N_ROWS * N_ITERS / tpu_secs
    auc_tpu = _auc(yte, tpu_margins)
    # throughput is per-row: cap the measurement batch so the one-dispatch
    # (N, T, I) decision tensor stays in HBM at any BENCH_ROWS
    pred_rows = min(N_ROWS, 400_000)
    pred_tpu = _predict_throughput_tpu(booster, Xtr[:pred_rows])

    try:
        cpu_secs, cpu_margins, clf = _fit_cpu(Xtr, ytr, Xte)
        cpu_tput = N_ROWS * N_ITERS / cpu_secs
        auc_cpu = _auc(yte, cpu_margins)
        vs = tpu_tput / cpu_tput
        pred_cpu = _predict_throughput_cpu(clf, Xtr[:pred_rows])
    except Exception as e:  # pragma: no cover
        print(f"cpu baseline failed: {e}", file=sys.stderr)
        cpu_secs, auc_cpu, vs, pred_cpu = 0.0, 0.0, 0.0, 0.0

    print(
        json.dumps(
            {
                "metric": f"gbdt_leafwise_train_row_iterations_per_sec_{backend}",
                "value": round(tpu_tput, 1),
                "unit": "rows*iters/sec",
                "vs_baseline": round(vs, 3),
                "tpu_fit_secs": round(tpu_secs, 3),
                "tpu_fit_secs_device_resident": round(resident_secs, 3),
                "vs_baseline_device_resident": (
                    round(cpu_secs / resident_secs, 3) if cpu_secs else 0.0
                ),
                "cpu_fit_secs": round(cpu_secs, 3),
                "auc_tpu": round(float(auc_tpu), 5),
                "auc_cpu": round(float(auc_cpu), 5),
                "predict_rows_per_sec_tpu": round(pred_tpu, 0),
                "predict_rows_per_sec_cpu": round(pred_cpu, 0),
                "predict_vs_cpu": round(pred_tpu / pred_cpu, 2) if pred_cpu else 0.0,
                "cpu_engine": "sklearn.HistGradientBoostingClassifier(median of 3)",
            }
        )
    )


if __name__ == "__main__":
    main()
