"""Headline benchmark: GBDT training throughput on TPU vs host CPU.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Workload: binary-classification boosting on a Higgs-like dense matrix
(BASELINE.json config 3's shape at bench-friendly scale). ``value`` is
TPU row-iterations/sec (rows × boosting iterations / wall time, steady
state, compile excluded). ``vs_baseline`` is the speedup over the same
jitted program on the host CPU backend — the reference's LightGBM runs
on CPU, and BASELINE.md's north-star target is ≥10× CPU rows/sec.
"""

import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 400_000))
N_FEATURES = int(os.environ.get("BENCH_FEATURES", 28))
N_ITERS = int(os.environ.get("BENCH_ITERS", 10))
N_WARMUP = 2
CPU_ROWS = min(N_ROWS, 100_000)  # CPU baseline measured at reduced scale


def _make_data(n, f, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 1.5 + X[:, 1] * X[:, 2] + 0.5 * rng.normal(size=n)
    y = (logit > 0).astype(np.float64)
    return X, y


def _throughput(n_rows, n_feat, iters, warmup):
    """Steady-state row-iterations/sec of the jitted boosting step on the
    current JAX backend."""
    import jax

    from mmlspark_tpu.lightgbm.binning import bin_dataset
    from mmlspark_tpu.lightgbm.objectives import get_objective
    from mmlspark_tpu.lightgbm.train import TrainOptions, _make_step

    X, y = _make_data(n_rows, n_feat)
    bins, mapper = bin_dataset(X)
    opts = TrainOptions(objective="binary", num_leaves=31)
    objective = get_objective("binary")
    num_bins = opts.max_bin + 1
    step = _make_step(opts, objective, num_bins)

    import jax.numpy as jnp

    edges = np.where(np.isfinite(mapper.edges), mapper.edges, np.finfo(np.float32).max)
    bins_d = jnp.asarray(bins, dtype=jnp.int32)
    y_d = jnp.asarray(y, dtype=jnp.float32)
    w_d = jnp.ones(n_rows, dtype=jnp.float32)
    edges_d = jnp.asarray(edges, dtype=jnp.float32)
    bag = jnp.ones(n_rows, dtype=jnp.float32)
    fm = jnp.ones(n_feat, dtype=jnp.float32)
    init = objective.init_score(y, 1, np.ones(n_rows))
    margins = jnp.broadcast_to(jnp.asarray(init)[None, :], (n_rows, 1)).astype(jnp.float32)

    for _ in range(warmup):
        sf, sb, st, lv, margins = step(bins_d, y_d, w_d, margins, edges_d, bag, fm)
    jax.block_until_ready(margins)

    t0 = time.perf_counter()
    for _ in range(iters):
        sf, sb, st, lv, margins = step(bins_d, y_d, w_d, margins, edges_d, bag, fm)
    jax.block_until_ready(margins)
    dt = time.perf_counter() - t0
    return n_rows * iters / dt


def _cpu_baseline_subprocess() -> float:
    """Measure the CPU baseline in a clean subprocess: once TPU compute has
    run in a process, backend switching silently keeps dispatching to TPU,
    so an in-process 'CPU' measurement would be bogus."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--cpu-baseline"],
        capture_output=True, text=True, env=env, timeout=1800,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    for line in out.stdout.strip().splitlines()[::-1]:
        try:
            return float(line)
        except ValueError:
            continue
    raise RuntimeError(f"cpu baseline failed: {out.stderr[-500:]}")


def main():
    if "--cpu-baseline" in sys.argv:
        print(_throughput(CPU_ROWS, N_FEATURES, 3, 1))
        return

    import jax

    tpu_backend = jax.default_backend()
    tpu_tput = _throughput(N_ROWS, N_FEATURES, N_ITERS, N_WARMUP)

    try:
        cpu_tput = _cpu_baseline_subprocess()
        vs_baseline = tpu_tput / cpu_tput
    except Exception as e:  # pragma: no cover
        print(f"cpu baseline failed: {e}", file=sys.stderr)
        vs_baseline = 0.0

    print(
        json.dumps(
            {
                "metric": f"gbdt_train_row_iterations_per_sec_{tpu_backend}",
                "value": round(tpu_tput, 1),
                "unit": "rows*iters/sec",
                "vs_baseline": round(vs_baseline, 3) if vs_baseline else 0.0,
            }
        )
    )


if __name__ == "__main__":
    main()
