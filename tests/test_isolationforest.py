"""isolationforest/ tests — mirrors reference ``isolationforest/``
VerifyIsolationForest."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.isolationforest import IsolationForest, IsolationForestModel
from mmlspark_tpu.isolationforest.forest import c_factor


def test_c_factor_known_values():
    assert c_factor(1) == 0.0
    assert c_factor(2) == 1.0
    # c(256) ~ 10.24 (standard iForest constant)
    assert 10.0 < c_factor(256) < 10.5


@pytest.fixture
def anomaly_table(rng):
    inliers = rng.normal(size=(300, 4))
    outliers = rng.normal(size=(10, 4)) * 0.5 + 8.0
    X = np.vstack([inliers, outliers])
    return Table({"features": X}), np.array([0] * 300 + [1] * 10)


def test_outliers_score_higher(anomaly_table):
    table, truth = anomaly_table
    model = IsolationForest(numEstimators=50, maxSamples=64.0).fit(table)
    out = model.transform(table)
    scores = out["outlierScore"]
    assert scores.min() >= 0.0 and scores.max() <= 1.0
    assert scores[truth == 1].mean() > scores[truth == 0].mean() + 0.1


def test_contamination_threshold(anomaly_table):
    table, truth = anomaly_table
    model = IsolationForest(
        numEstimators=50, maxSamples=64.0, contamination=10 / 310
    ).fit(table)
    out = model.transform(table)
    flagged = out["predictedLabel"].astype(bool)
    # most flagged rows are the planted outliers
    assert flagged.sum() >= 5
    assert truth[flagged].mean() > 0.6


def test_deterministic_given_seed(anomaly_table):
    table, _ = anomaly_table
    a = IsolationForest(numEstimators=10, randomSeed=3).fit(table)
    b = IsolationForest(numEstimators=10, randomSeed=3).fit(table)
    np.testing.assert_allclose(
        a.transform(table)["outlierScore"], b.transform(table)["outlierScore"]
    )


def test_save_load(anomaly_table, tmp_path):
    table, _ = anomaly_table
    model = IsolationForest(numEstimators=10).fit(table)
    model.save(str(tmp_path / "iforest"))
    loaded = IsolationForestModel.load(str(tmp_path / "iforest"))
    np.testing.assert_allclose(
        model.transform(table)["outlierScore"],
        loaded.transform(table)["outlierScore"],
    )


def test_feature_subsampling(anomaly_table):
    table, truth = anomaly_table
    model = IsolationForest(numEstimators=60, maxFeatures=0.5).fit(table)
    out = model.transform(table)
    assert out["outlierScore"][truth == 1].mean() > out["outlierScore"][truth == 0].mean()
