"""Partition-tolerant coordination tests (PR: network chaos + collective
timeouts + registry-outage-tolerant serving).

In-process coverage of the three planes:

- **network chaos plane**: ``FaultPlan.net_*`` directive registration,
  epoch scoping, driver-side ``mark_net_fired`` acknowledgement, the
  HTTP-edge ``check_net`` enactment, and :class:`NetChaos` seeded
  determinism;
- **collective plane**: the CRC-framed, acknowledged allreduce — injected
  wire corruption is absorbed by a bounded retransmit with byte-identical
  results, and a partition surfaces as :class:`GroupRevokedError` with
  blame within the io deadline on BOTH sides (threads standing in for
  processes, as in ``test_procgroup.py``);
- **registry plane**: lease journaling + recovery across a registry
  restart (``LeaseRecovered`` events, CRC-guarded journal), FakeClock
  lease expiry, and the router's stale-table behavior under connection
  refusal, malformed/truncated ``/services`` JSON, and corrupted bodies.
"""

import json
import socket
import threading
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mmlspark_tpu.observability.events import (
    LeaseRecovered,
    RegistryUnavailable,
    get_bus,
)
from mmlspark_tpu.runtime.faults import FaultPlan, check_net, inject_faults
from mmlspark_tpu.runtime.netchaos import NetChaos, corrupt_bytes
from mmlspark_tpu.runtime.procgroup import (
    AllreduceGroup,
    GroupRevokedError,
    pick_port,
)
from mmlspark_tpu.serving.router import FleetRouter
from mmlspark_tpu.serving.server import RegistrationService, ServiceInfo


class _Capture:
    """Event-bus listener collecting events by type name."""

    def __init__(self, *types):
        self.types = types
        self.events = []

    def __call__(self, event):
        if not self.types or isinstance(event, self.types):
            self.events.append(event)

    def __enter__(self):
        get_bus().add_listener(self)
        return self

    def __exit__(self, *exc):
        get_bus().remove_listener(self)


# ---------------------------------------------------------------------------
# network chaos plane
# ---------------------------------------------------------------------------


class TestNetDirectives:
    def test_gang_directives_are_epoch_scoped(self):
        plan = (
            FaultPlan(seed=1)
            .net_partition(0, 1, epoch=0, after_round=2)
            .net_corrupt(1, n=3, epoch=1)
        )
        assert plan.net_directives(0) == [{
            "target": "gang", "kind": "partition", "a": 0, "b": 1,
            "epoch": 0, "after_round": 2,
        }]
        assert [d["kind"] for d in plan.net_directives(1)] == ["corrupt"]
        assert len(plan.net_directives()) == 2
        assert plan.pending == 2

    def test_mark_net_fired_pops_and_books(self):
        plan = FaultPlan(seed=1).net_partition(0, 1, epoch=0)
        # either involved member acknowledges the partition
        assert plan.mark_net_fired("partition", member=1, epoch=0)
        assert not plan.mark_net_fired("partition", member=1, epoch=0)
        assert plan.fired == [("net_partition", 1, 0)]
        assert plan.pending == 0

    def test_mark_net_fired_respects_kind_and_epoch(self):
        plan = FaultPlan(seed=1).net_delay(1, ms=50.0, epoch=2)
        assert not plan.mark_net_fired("partition", member=1, epoch=2)
        assert not plan.mark_net_fired("delay", member=1, epoch=0)
        assert plan.mark_net_fired("delay", member=1, epoch=2)

    def test_http_partition_raises_unreachable(self):
        plan = FaultPlan(seed=1).net_partition("registry:1234")
        with inject_faults(plan):
            with pytest.raises(OSError, match="partition"):
                check_net("http://registry:1234/services")
            # consumed: the next call passes clean
            assert check_net("http://registry:1234/services") is None
        assert plan.fired == [("net_partition", 0, 0)]

    def test_http_drop_times_out_and_corrupt_passes_through(self):
        plan = (
            FaultPlan(seed=1)
            .net_drop("svc-a", p=1.0)
            .net_corrupt("svc-b", n=1)
        )
        with inject_faults(plan):
            with pytest.raises(socket.timeout):
                check_net("http://svc-a/predict")
            directive = check_net("http://svc-b/predict")
            assert directive["kind"] == "corrupt"
            assert check_net("http://unrelated/") is None
        assert [f[0] for f in plan.fired] == ["net_drop", "net_corrupt"]

    def test_unmatched_url_untouched(self):
        plan = FaultPlan(seed=1).net_partition("registry")
        with inject_faults(plan):
            assert check_net("http://replica-0:9/predict") is None
        assert plan.pending == 1


class TestNetChaos:
    def test_corrupt_bytes_preserves_length_and_differs(self):
        data = b"\x00\x01\x02payload"
        garbled = corrupt_bytes(data)
        assert len(garbled) == len(data)
        assert garbled != data
        assert corrupt_bytes(b"") == b""

    def test_partition_applies_after_round(self):
        directives = FaultPlan(seed=0).net_partition(
            0, 1, epoch=0, after_round=1
        ).net_directives(0)
        chaos = NetChaos(directives, member=0, epoch=0, seed=7)
        assert chaos.active
        assert not chaos.partitioned(1, 0)
        assert chaos.partitioned(1, 1)
        assert chaos.on_send(1, 0, b"x") == b"x"
        assert chaos.on_send(1, 1, b"x") is None

    def test_partition_is_symmetric_and_scoped(self):
        directives = FaultPlan(seed=0).net_partition(0, 1).net_directives(0)
        for member, peer in ((0, 1), (1, 0)):
            chaos = NetChaos(directives, member=member, epoch=0, seed=7)
            assert chaos.on_send(peer, 0, b"x") is None
        # a third member is unaffected
        chaos2 = NetChaos(directives, member=2, epoch=0, seed=7)
        assert not chaos2.active
        assert chaos2.on_send(0, 0, b"x") == b"x"

    def test_corrupt_budget_is_bounded(self):
        directives = FaultPlan(seed=0).net_corrupt(1, n=1).net_directives(0)
        chaos = NetChaos(directives, member=1, epoch=0, seed=3)
        first = chaos.on_send(0, 0, b"payload!")
        second = chaos.on_send(0, 0, b"payload!")
        assert first != b"payload!"
        assert second == b"payload!"

    def test_drop_is_seed_deterministic(self):
        directives = FaultPlan(seed=0).net_drop(0, p=0.5).net_directives(0)

        def outcomes(seed):
            chaos = NetChaos(directives, member=0, epoch=0, seed=seed)
            return [chaos.on_send(1, r, b"f") is None for r in range(32)]

        assert outcomes(5) == outcomes(5)
        assert any(outcomes(5))
        assert not all(outcomes(5))

    def test_wrong_epoch_or_member_is_inert(self):
        directives = FaultPlan(seed=0).net_delay(
            1, ms=5.0, epoch=3
        ).net_directives()
        assert not NetChaos(directives, member=1, epoch=0, seed=1).active
        assert not NetChaos(directives, member=0, epoch=3, seed=1).active
        assert NetChaos(directives, member=1, epoch=3, seed=1).active


# ---------------------------------------------------------------------------
# collective plane
# ---------------------------------------------------------------------------


class TestCollectiveRobustness:
    def _run_pair(self, port, chaos_by_member, io_timeout=5.0, rounds=2,
                  max_retransmits=2):
        """Two members (threads), optional per-member NetChaos. Returns
        (results, errors, groups)."""
        results = {}
        errors = {}
        groups = {}

        def member(rank):
            g = AllreduceGroup(
                rank, 2, port, timeout=15.0, io_timeout=io_timeout,
                member=rank, members=[0, 1],
                chaos=chaos_by_member.get(rank),
                max_retransmits=max_retransmits,
            )
            groups[rank] = g
            try:
                out = []
                for _ in range(rounds):
                    out.append(np.asarray(
                        g.allreduce(np.full(8, float(rank + 1), np.float32))
                    ))
                results[rank] = out
            except GroupRevokedError as e:
                errors[rank] = e
            finally:
                g.close()

        threads = [threading.Thread(target=member, args=(r,)) for r in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        assert not any(t.is_alive() for t in threads), "collective hung"
        return results, errors, groups

    def test_corrupt_frame_absorbed_by_retransmit(self):
        directives = FaultPlan(seed=0).net_corrupt(1, n=1).net_directives(0)
        chaos = NetChaos(directives, member=1, epoch=0, seed=11)
        port = pick_port(seed=210)
        results, errors, groups = self._run_pair(port, {1: chaos})
        assert not errors
        for rank in (0, 1):
            for arr in results[rank]:
                np.testing.assert_array_equal(arr, np.full(8, 3.0, np.float32))
        # sender books the retransmit, receiver the CRC drop
        assert groups[1].stats["retransmits"] == 1
        assert groups[0].stats["crc_drops"] == 1

    def test_retransmit_exhaustion_revokes(self):
        # infinite corruption budget: every send garbled, NAKs exhaust
        directives = FaultPlan(seed=0).net_corrupt(
            1, n=1000
        ).net_directives(0)
        chaos = NetChaos(directives, member=1, epoch=0, seed=11)
        port = pick_port(seed=211)
        results, errors, groups = self._run_pair(
            port, {1: chaos}, io_timeout=3.0, rounds=1, max_retransmits=1
        )
        assert 1 in errors  # the corrupting sender runs out of retries
        assert not any(t for t in results.get(1, []))

    def test_partition_revokes_both_sides_with_blame(self):
        plan = FaultPlan(seed=0).net_partition(0, 1, epoch=0, after_round=1)
        directives = plan.net_directives(0)
        chaos = {
            r: NetChaos(directives, member=r, epoch=0, seed=13)
            for r in (0, 1)
        }
        port = pick_port(seed=212)
        results, errors, groups = self._run_pair(
            port, chaos, io_timeout=1.0, rounds=2
        )
        # round 0 completed, round 1 partitioned: no results, both revoked
        assert set(errors) == {0, 1}
        assert errors[0].suspect == 1  # rank 0 blames its silent peer
        assert errors[1].suspect == 0  # non-root blames the coordinator
        assert errors[1].stats is not None

    def test_formation_timeout_blames_coordinator(self):
        port = pick_port(seed=213)
        with pytest.raises(GroupRevokedError) as exc_info:
            AllreduceGroup(
                1, 2, port, timeout=1.0, member=1, members=[0, 1]
            )
        assert exc_info.value.suspect == 0


# ---------------------------------------------------------------------------
# registry plane
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestLeaseExpiryFakeClock:
    def test_lease_expires_without_heartbeat(self):
        clock = FakeClock()
        reg = RegistrationService(ttl_s=5.0, clock=clock).start()
        reg.register(ServiceInfo("r-0", "127.0.0.1", 9000))
        assert [s.name for s in reg.services] == ["r-0"]
        clock.advance(5.1)
        assert reg.services == []
        # an expired lease's heartbeat is rejected: re-register required
        assert not reg.heartbeat("r-0")
        reg.stop()

    def test_heartbeat_extends_lease(self):
        clock = FakeClock()
        reg = RegistrationService(ttl_s=5.0, clock=clock).start()
        reg.register(ServiceInfo("r-0", "127.0.0.1", 9000))
        clock.advance(4.0)
        assert reg.heartbeat("r-0", inflight=3)
        clock.advance(4.0)  # 8s after register, 4s after heartbeat
        assert [s.name for s in reg.services] == ["r-0"]
        assert reg.services[0].inflight == 3
        reg.stop()


class TestLeaseJournal:
    def test_restart_recovers_leases_with_events(self, tmp_path):
        jd = str(tmp_path / "registry")
        first = RegistrationService(ttl_s=30.0, journal_dir=jd).start()
        first.register(ServiceInfo(
            "r-0", "127.0.0.1", 9100, model_version=3, inflight=2,
        ))
        first.register(ServiceInfo("r-1", "127.0.0.1", 9101))
        first.stop()

        with _Capture(LeaseRecovered) as cap:
            clock = FakeClock()
            second = RegistrationService(
                ttl_s=30.0, clock=clock, journal_dir=jd
            ).start()
        names = sorted(s.name for s in second.services)
        assert names == ["r-0", "r-1"]
        svc = {s.name: s for s in second.services}["r-0"]
        assert svc.model_version == 3 and svc.inflight == 2
        assert sorted(e.name for e in cap.events) == ["r-0", "r-1"]
        assert all(e.age_s >= 0.0 for e in cap.events)
        # the recovered lease got a FRESH grace period, so a replica that
        # keeps heartbeating never has to re-register from scratch
        clock.advance(29.0)
        assert second.heartbeat("r-0")
        second.stop()

    def test_deregister_drops_from_journal(self, tmp_path):
        jd = str(tmp_path / "registry")
        first = RegistrationService(journal_dir=jd).start()
        first.register(ServiceInfo("r-0", "127.0.0.1", 9100))
        first.register(ServiceInfo("r-1", "127.0.0.1", 9101))
        first.deregister("r-0")
        first.stop()
        second = RegistrationService(journal_dir=jd).start()
        assert [s.name for s in second.services] == ["r-1"]
        second.stop()

    def test_corrupt_journal_discarded(self, tmp_path):
        jd = tmp_path / "registry"
        first = RegistrationService(journal_dir=str(jd)).start()
        first.register(ServiceInfo("r-0", "127.0.0.1", 9100))
        first.stop()
        path = jd / RegistrationService.JOURNAL_NAME
        payload = path.read_bytes()
        path.write_bytes(payload[:-4] + b"!!!!")  # torn write
        assert f"{zlib.crc32(path.read_bytes()):08x}" != \
            (jd / (RegistrationService.JOURNAL_NAME + ".crc")).read_text()
        second = RegistrationService(journal_dir=str(jd)).start()
        assert second.services == []  # discarded, started empty
        second.stop()

    def test_no_journal_dir_keeps_old_behavior(self, tmp_path):
        reg = RegistrationService()
        reg.register(ServiceInfo("r-0", "127.0.0.1", 9100))
        assert reg._journal_path is None
        reg._httpd.server_close()


class _RawServer:
    """HTTP server answering GET /services with fixed raw bytes."""

    def __init__(self, raw: bytes):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                self.send_response(200)
                self.send_header("Content-Length", str(len(outer.raw)))
                self.end_headers()
                self.wfile.write(outer.raw)

            def log_message(self, *args):
                pass

        self.raw = raw
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def __enter__(self):
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        ).start()
        return self

    def __exit__(self, *exc):
        self._httpd.shutdown()
        self._httpd.server_close()


class TestRouterRegistryOutage:
    def _table(self):
        return [{"name": "r-0", "host": "127.0.0.1", "port": 9200}]

    def test_connection_refused_keeps_stale_table(self):
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            dead_port = probe.getsockname()[1]
        router = FleetRouter(registry_url=f"http://127.0.0.1:{dead_port}")
        router._replicas = [ServiceInfo("r-0", "127.0.0.1", 9200)]
        with _Capture(RegistryUnavailable) as cap:
            table = router.refresh()
            router.refresh()  # second failure: same outage, no new event
        assert [s.name for s in table] == ["r-0"]
        assert router._stale
        assert len(cap.events) == 1
        assert cap.events[0].source == "router"
        assert cap.events[0].stale_replicas == 1
        router._httpd.server_close()

    def test_malformed_json_keeps_stale_table(self):
        with _RawServer(b'[{"name": "r-1", truncated') as srv:
            router = FleetRouter(registry_url=srv.url)
            router._replicas = [ServiceInfo("r-0", "127.0.0.1", 9200)]
            assert [s.name for s in router.refresh()] == ["r-0"]
            assert router._stale
            router._httpd.server_close()

    def test_corrupted_body_via_net_chaos_keeps_stale_table(self):
        with _RawServer(json.dumps(self._table()).encode()) as srv:
            router = FleetRouter(registry_url=srv.url)
            plan = FaultPlan(seed=1).net_corrupt(srv.url, n=1)
            with inject_faults(plan):
                router._replicas = [ServiceInfo("r-9", "127.0.0.1", 9300)]
                assert [s.name for s in router.refresh()] == ["r-9"]
                assert router._stale
                # chaos budget spent: next poll recovers the real table
                assert [s.name for s in router.refresh()] == ["r-0"]
                assert not router._stale
            assert plan.fired == [("net_corrupt", 0, 0)]
            router._httpd.server_close()

    def test_discovery_thread_survives_outage(self):
        with _RawServer(b"not json at all") as srv:
            router = FleetRouter(
                registry_url=srv.url, discovery_interval_s=0.02
            )
            router.start()
            try:
                import time

                time.sleep(0.2)  # many failing polls
                assert router._discover_thread.is_alive()
                assert router._stale
            finally:
                router.stop()

    def test_recovery_clears_stale_flag(self):
        with _RawServer(json.dumps(self._table()).encode()) as srv:
            router = FleetRouter(registry_url=srv.url)
            router._stale = True
            router._m_stale.set(1)
            assert [s.name for s in router.refresh()] == ["r-0"]
            assert not router._stale
            router._httpd.server_close()
