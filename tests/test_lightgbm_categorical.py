"""Categorical feature splits, end to end.

The reference forwards ``categoricalSlotIndexes``/``categoricalSlotNames``
to native LightGBM, which runs categorical split finding
(``lightgbm/LightGBMParams.scala:125-133``, ``LightGBMBase.scala:148-156``).
This suite pins the TPU re-implementation: value-identity binning, the
sorted-prefix set search, set routing in both growth modes, predict/SHAP
consistency, serde (JSON + LightGBM model text with cat bitsets), and
import of a pinned LightGBM-format categorical model file."""

import os

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import LightGBMClassifier, LightGBMRegressor
from mmlspark_tpu.lightgbm.binning import bin_dataset, cat_to_bins
from mmlspark_tpu.lightgbm.booster import Booster
from mmlspark_tpu.lightgbm.model_text import from_lightgbm_text, to_lightgbm_text
from mmlspark_tpu.lightgbm.objectives import auc
from mmlspark_tpu.lightgbm.train import TrainOptions, train

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "lightgbm_categorical_model.txt"
)


def _cat_data(n=5000, n_cat=12, seed=0):
    rng = np.random.default_rng(seed)
    cat = rng.integers(0, n_cat, size=n)
    eff = rng.normal(size=n_cat) * 2.0
    Xn = rng.normal(size=(n, 3))
    logit = eff[cat] + Xn[:, 0] + 0.3 * rng.normal(size=n)
    y = (logit > 0).astype(np.float64)
    X = np.column_stack([cat.astype(np.float64), Xn])
    return X, y


class TestCatBinning:
    def test_value_identity_bins(self):
        vals = np.array([7.0, 3.0, 11.0])  # frequency order
        col = np.array([3.0, 7.0, 11.0, 5.0, np.nan, 7.0])
        bins = cat_to_bins(col, vals)
        # 7 -> bin 1, 3 -> bin 2, 11 -> bin 3; unseen/NaN -> 0
        np.testing.assert_array_equal(bins, [2, 1, 3, 0, 0, 1])

    def test_mapper_orders_by_frequency(self):
        X = np.array([[5.0], [5.0], [5.0], [2.0], [2.0], [9.0]])
        _, mp = bin_dataset(X, max_bin=15, categorical_features=[0])
        np.testing.assert_array_equal(mp.cat_values[0], [5.0, 2.0, 9.0])
        assert mp.is_categorical(0) and mp.num_bins[0] == 4

    def test_capacity_overflow_goes_missing(self):
        X = np.arange(20, dtype=np.float64)[:, None]
        bins, mp = bin_dataset(X, max_bin=8, categorical_features=[0])
        assert len(mp.cat_values[0]) == 7  # max_bin - 1 value bins
        assert (bins == 0).sum() == 13  # the rest -> missing bin

    def test_csr_matches_dense(self):
        """Categorical binning on CSR input is bit-identical to the dense
        path (implicit zeros count toward category 0.0's frequency)."""
        from mmlspark_tpu.data.sparse import CSRMatrix

        rng = np.random.default_rng(4)
        n, f = 400, 3
        X = np.zeros((n, f))
        X[:, 0] = rng.integers(0, 6, size=n)  # categorical incl. many zeros
        X[:, 1] = rng.normal(size=n)
        X[:, 2] = np.where(rng.uniform(size=n) < 0.5, 0.0,
                           rng.integers(1, 4, size=n))  # sparse categorical
        mask = X != 0
        indptr = np.concatenate([[0], np.cumsum(mask.sum(axis=1))])
        csr = CSRMatrix(
            indptr=indptr.astype(np.int64),
            indices=np.nonzero(mask)[1].astype(np.int64),
            data=X[mask].astype(np.float64),
            shape=(n, f),
        )
        bd, md = bin_dataset(X, max_bin=15, categorical_features=[0, 2])
        bs, ms = bin_dataset(csr, max_bin=15, categorical_features=[0, 2])
        np.testing.assert_array_equal(bs, bd)
        for j in (0, 2):
            np.testing.assert_array_equal(ms.cat_values[j], md.cat_values[j])


class TestCatTraining:
    def test_beats_numeric_coding_and_matches_sklearn(self):
        X, y = _cat_data()
        ones = np.ones(len(y))
        base = dict(objective="binary", num_iterations=20, num_leaves=15, max_bin=63)
        b0, m0 = bin_dataset(X, max_bin=63)
        a_num = auc(y, train(b0, y, TrainOptions(**base), mapper=m0)
                    .booster.raw_margin(X)[:, 0], ones)
        b1, m1 = bin_dataset(X, max_bin=63, categorical_features=[0])
        r = train(b1, y, TrainOptions(**base), mapper=m1)
        a_cat = auc(y, r.booster.raw_margin(X)[:, 0], ones)
        assert r.booster.has_categorical
        assert a_cat > a_num  # set splits isolate categories a cut cannot

        from sklearn.ensemble import HistGradientBoostingClassifier
        from sklearn.metrics import roc_auc_score

        clf = HistGradientBoostingClassifier(
            max_iter=20, max_leaf_nodes=15, categorical_features=[0],
            early_stopping=False,
        )
        clf.fit(X, y)
        a_sk = roc_auc_score(y, clf.decision_function(X))
        assert a_cat >= a_sk - 0.01, (a_cat, a_sk)

    def test_depthwise_growth(self):
        X, y = _cat_data(n=2500, n_cat=8, seed=2)
        bins, mp = bin_dataset(X, max_bin=31, categorical_features=[0])
        r = train(
            bins, y,
            TrainOptions(objective="binary", num_iterations=6, num_leaves=15,
                         max_bin=31, growth="depthwise", max_depth=4),
            mapper=mp,
        )
        assert r.booster.has_categorical
        a = auc(y, r.booster.raw_margin(X)[:, 0], np.ones(len(y)))
        assert a > 0.9, a

    def test_u_histogram_path(self):
        X, y = _cat_data(n=2500, n_cat=8, seed=3)
        bins, mp = bin_dataset(X, max_bin=31, categorical_features=[0])
        base = dict(objective="binary", num_iterations=6, num_leaves=15, max_bin=31)
        r0 = train(bins, y, TrainOptions(**base), mapper=mp)
        ru = train(bins, y, TrainOptions(**base, histogram_method="u"), mapper=mp)
        a0 = auc(y, r0.booster.raw_margin(X)[:, 0], np.ones(len(y)))
        au = auc(y, ru.booster.raw_margin(X)[:, 0], np.ones(len(y)))
        assert abs(a0 - au) < 0.005, (a0, au)

    def test_max_cat_threshold_caps_set_size(self):
        X, y = _cat_data(n=4000, n_cat=40, seed=4)
        bins, mp = bin_dataset(X, max_bin=63, categorical_features=[0])
        r = train(
            bins, y,
            TrainOptions(objective="binary", num_iterations=5, num_leaves=15,
                         max_bin=63, max_cat_threshold=3),
            mapper=mp,
        )
        b = r.booster
        sizes = b.cat_masks[b.cat_nodes].sum(axis=-1)
        assert sizes.size and sizes.max() <= 3

    def test_one_vs_rest_singleton_left_sets(self):
        """Native max_cat_to_onehot semantics: cardinality <= the bound
        switches to one-vs-rest search, so every categorical left set is a
        SINGLE category; lowering the bound restores sorted-set splits with
        multi-category sets. Pins the OVR-vs-sorted divergence."""
        X, y = _cat_data(n=4000, n_cat=4, seed=11)
        bins, mp = bin_dataset(X, max_bin=31, categorical_features=[0])
        base = dict(objective="binary", num_iterations=8, num_leaves=15,
                    max_bin=31, min_data_per_group=1)
        r_ovr = train(
            bins, y, TrainOptions(**base, max_cat_to_onehot=4), mapper=mp
        )
        b = r_ovr.booster
        sizes = b.cat_masks[b.cat_nodes].sum(axis=-1)
        assert sizes.size and sizes.max() == 1  # one-vs-rest: singletons only

        r_sorted = train(
            bins, y, TrainOptions(**base, max_cat_to_onehot=1), mapper=mp
        )
        bs = r_sorted.booster
        sizes_s = bs.cat_masks[bs.cat_nodes].sum(axis=-1)
        assert sizes_s.size and sizes_s.max() > 1  # sorted prefixes group cats
        # the two algorithms genuinely diverge on the same data: sorted-set
        # search groups categories OVR cannot express. Pin that on the
        # serialized models — margins may still coincide when a sorted
        # prefix happens to partition the rows exactly like a singleton
        # (it does here for some histogram summation orders), but the
        # trees themselves must differ.
        assert b.model_to_string() != bs.model_to_string()

    def test_min_data_per_group_gates_sorted_candidates(self):
        """A category below min_data_per_group cannot enter a sorted-set
        left split (native gate); shrinking the gate re-admits it."""
        rng = np.random.default_rng(13)
        n = 2000
        # category 7 is rare (~40 rows) but perfectly predictive
        cat = rng.integers(0, 7, size=n).astype(np.float64)
        rare = rng.random(n) < 0.02
        cat[rare] = 7.0
        y = ((cat == 7.0) | (rng.random(n) < 0.2)).astype(np.float64)
        X = np.column_stack([cat, rng.normal(size=(n, 2))])
        bins, mp = bin_dataset(X, max_bin=31, categorical_features=[0])
        base = dict(objective="binary", num_iterations=4, num_leaves=7,
                    max_bin=31, max_cat_to_onehot=1, min_data_in_leaf=5)

        def rare_bin_used_left(booster):
            # cat_values is frequency-ordered; value v sits at bin index+1
            rare_bin = mp.cat_values[0].tolist().index(7.0) + 1
            used = booster.cat_masks[booster.cat_nodes]
            return used.size and bool(used[:, rare_bin].any())

        r_gated = train(
            bins, y, TrainOptions(**base, min_data_per_group=100), mapper=mp
        )
        r_open = train(
            bins, y, TrainOptions(**base, min_data_per_group=1), mapper=mp
        )
        assert not rare_bin_used_left(r_gated.booster)
        assert rare_bin_used_left(r_open.booster)

    def test_valid_set_and_early_stopping_route_cats(self):
        X, y = _cat_data(n=3000, seed=5)
        bins, mp = bin_dataset(X, max_bin=31, categorical_features=[0])
        bv, _ = bin_dataset(X[:500], max_bin=31, mapper=mp)
        r = train(
            bins, y,
            TrainOptions(objective="binary", num_iterations=10, num_leaves=7,
                         max_bin=31, early_stopping_round=5),
            mapper=mp,
            valid_sets=[("v", bv, y[:500], None)],
        )
        scores = r.evals["v"]["auc"]
        assert len(scores) >= 5 and scores[-1] > 0.9

    def test_unseen_category_and_nan_route_right(self):
        X, y = _cat_data(n=2000, seed=6)
        bins, mp = bin_dataset(X, max_bin=31, categorical_features=[0])
        b = train(bins, y, TrainOptions(objective="binary", num_iterations=5,
                                        num_leaves=7, max_bin=31), mapper=mp).booster
        Xu = X[:3].copy()
        Xu[0, 0] = 999.0
        Xu[1, 0] = np.nan
        out = b.raw_margin(Xu)
        assert np.isfinite(out).all()
        # unseen and NaN take the same (right) path at every cat node
        np.testing.assert_allclose(out[0], out[1], rtol=1e-6)

    def test_shap_additivity_and_leaf_predict(self):
        X, y = _cat_data(n=1500, seed=7)
        bins, mp = bin_dataset(X, max_bin=31, categorical_features=[0])
        b = train(bins, y, TrainOptions(objective="binary", num_iterations=4,
                                        num_leaves=7, max_bin=31), mapper=mp).booster
        sh = b.features_shap(X[:100]).sum(-1)[:, 0]
        np.testing.assert_allclose(sh, b.raw_margin(X[:100])[:, 0],
                                   rtol=1e-4, atol=1e-4)
        leaves = b.predict_leaf(X[:100])
        assert leaves.shape == (100, b.num_trees)
        assert np.asarray(b.is_leaf)[0][leaves[:, 0]].all()


class TestCatSerde:
    def test_json_round_trip(self):
        X, y = _cat_data(n=1500, seed=8)
        bins, mp = bin_dataset(X, max_bin=31, categorical_features=[0])
        b = train(bins, y, TrainOptions(objective="binary", num_iterations=4,
                                        num_leaves=7, max_bin=31), mapper=mp).booster
        b2 = Booster.from_string(b.to_json_string())
        assert b2.has_categorical
        np.testing.assert_allclose(b2.raw_margin(X[:300]), b.raw_margin(X[:300]),
                                   rtol=1e-6)

    def test_model_text_round_trip(self):
        X, y = _cat_data(n=2000, seed=9)
        bins, mp = bin_dataset(X, max_bin=31, categorical_features=[0])
        b = train(bins, y, TrainOptions(objective="binary", num_iterations=6,
                                        num_leaves=7, max_bin=31), mapper=mp).booster
        text = to_lightgbm_text(b)
        assert "cat_boundaries=" in text and "cat_threshold=" in text
        b2 = from_lightgbm_text(text)
        np.testing.assert_allclose(b2.raw_margin(X)[:, 0], b.raw_margin(X)[:, 0],
                                   rtol=1e-5, atol=1e-5)
        Xu = X[:4].copy()
        Xu[0, 0] = 777.0
        Xu[1, 0] = np.nan
        np.testing.assert_allclose(b2.raw_margin(Xu), b.raw_margin(Xu),
                                   rtol=1e-5, atol=1e-5)

    def test_non_integer_categories_refuse_export(self):
        X, y = _cat_data(n=1000, seed=10)
        X[:, 0] = X[:, 0] + 0.5  # fractional category values
        bins, mp = bin_dataset(X, max_bin=31, categorical_features=[0])
        b = train(bins, y, TrainOptions(objective="binary", num_iterations=3,
                                        num_leaves=7, max_bin=31), mapper=mp).booster
        if b.has_categorical:
            with pytest.raises(ValueError, match="non-negative integers"):
                to_lightgbm_text(b)


class TestPinnedLightGBMCatModel:
    """The checked-in LightGBM-format categorical model file: hand-verified
    bitsets (set {1, 3, 34} spans two uint32 words: 10 = 2^1+2^3, 4 = 2^2
    at offset 32), so the interop path runs in every environment, pip
    ``lightgbm`` or not."""

    def test_import_and_hand_computed_predictions(self):
        with open(FIXTURE) as f:
            b = Booster.from_string(f.read())
        assert b.has_categorical
        np.testing.assert_array_equal(sorted(b.cat_values[0]), [1, 3, 34])
        X = np.array([
            [1.0, 0.0],    # in set -> 1.5 ; 0 <= 0.25 -> 0.2
            [34.0, 1.0],   # in set -> 1.5 ; 1 > 0.25 -> -0.3
            [2.0, 0.0],    # not in set -> -0.5 ; 0.2
            [40.0, 0.0],   # unseen -> -0.5 ; 0.2
            [np.nan, np.nan],  # NaN cat -> right -0.5; NaN num, missing none -> like 0.0 -> 0.2
        ])
        margins = b.raw_margin(X)[:, 0]
        np.testing.assert_allclose(
            margins, [1.7, 1.2, -0.3, -0.3, -0.3], rtol=1e-6, atol=1e-6
        )

    def test_reexport_preserves_bitsets(self):
        with open(FIXTURE) as f:
            b = Booster.from_string(f.read())
        text = to_lightgbm_text(b)
        assert "cat_threshold=10 4" in text
        b2 = from_lightgbm_text(text)
        X = np.array([[1.0, 0.0], [2.0, 1.0], [34.0, 0.3]])
        np.testing.assert_allclose(b2.raw_margin(X), b.raw_margin(X), rtol=1e-6)


class TestCatEstimatorAPI:
    def test_classifier_slot_indexes_and_names(self):
        X, y = _cat_data(n=2000, seed=11)
        t = Table({"features": X, "label": y})
        m1 = LightGBMClassifier(
            numIterations=5, numLeaves=7, categoricalSlotIndexes=[0]
        ).fit(t)
        assert m1.booster.has_categorical
        m2 = LightGBMClassifier(
            numIterations=5, numLeaves=7, categoricalSlotNames=["f0"]
        ).fit(t)
        np.testing.assert_allclose(
            m2.booster.raw_margin(X), m1.booster.raw_margin(X), rtol=1e-6
        )
        with pytest.raises(ValueError, match="unknown feature name"):
            LightGBMClassifier(
                numIterations=2, categoricalSlotNames=["nope"]
            ).fit(t)

    def test_regressor_with_cats_and_save_load(self, tmp_path):
        X, y0 = _cat_data(n=1500, seed=12)
        yr = y0 * 3.0 + X[:, 1]
        t = Table({"features": X, "label": yr})
        m = LightGBMRegressor(
            numIterations=5, numLeaves=7, categoricalSlotIndexes=[0]
        ).fit(t)
        p = tmp_path / "cat_model"
        m.save(str(p))
        from mmlspark_tpu.core.serialize import load_stage

        m2 = load_stage(str(p))
        np.testing.assert_allclose(
            m2.booster.raw_margin(X), m.booster.raw_margin(X), rtol=1e-6
        )

    def test_native_model_save_load_with_cats(self, tmp_path):
        X, y = _cat_data(n=1500, seed=13)
        t = Table({"features": X, "label": y})
        m = LightGBMClassifier(
            numIterations=4, numLeaves=7, categoricalSlotIndexes=[0]
        ).fit(t)
        p = tmp_path / "native.txt"
        m.save_native_model(str(p))
        m2 = type(m).load_native_model(str(p))
        np.testing.assert_allclose(
            m2.booster.raw_margin(X)[:, 0], m.booster.raw_margin(X)[:, 0],
            rtol=1e-5, atol=1e-5,
        )


class TestCatSparseEstimator:
    def test_sparse_column_fit_matches_dense(self):
        """Sparse (indices, values) feature columns with categorical slots
        train the same model as the densified table."""
        rng = np.random.default_rng(6)
        n = 1500
        cat = rng.integers(0, 6, size=n)
        eff = np.array([2.0, -2.0, 1.5, -1.5, 0.5, -0.5])
        Xn = rng.normal(size=(n, 2))
        y = ((eff[cat] + Xn[:, 0]) > 0).astype(np.float64)
        X = np.column_stack([cat.astype(np.float64), Xn])
        sparse_col = np.empty(n, dtype=object)
        for i in range(n):
            nz = np.nonzero(X[i])[0]
            sparse_col[i] = (nz.astype(np.int64), X[i][nz])
        td = Table({"features": X, "label": y})
        ts = Table({"features": sparse_col, "label": y},
                   metadata={"features": {"sparse_dim": 3}})
        kw = dict(numIterations=5, numLeaves=7, categoricalSlotIndexes=[0],
                  parallelism="serial", seed=0)
        md = LightGBMClassifier(**kw).fit(td)
        ms = LightGBMClassifier(**kw).fit(ts)
        assert ms.booster.has_categorical
        np.testing.assert_allclose(
            ms.booster.leaf_values, md.booster.leaf_values, rtol=1e-6
        )


class TestCatPredictKernelDispatch:
    """Predict picks the matmul kernel normally and the memory-bounded
    gather kernel when the dense mask matrix would blow the size gate —
    both must score identically."""

    def test_gather_fallback_matches_matmul(self, monkeypatch):
        X, y = _cat_data(n=2000, n_cat=9, seed=31)
        bins, mp = bin_dataset(X, max_bin=31, categorical_features=[0])
        r = train(
            bins, y,
            TrainOptions(objective="binary", num_iterations=6, num_leaves=15,
                         max_bin=31, min_data_per_group=1),
            mapper=mp,
        )
        b = r.booster
        from mmlspark_tpu.lightgbm import booster as B

        ref = b.raw_margin(X[:300])
        leaves_ref = b.predict_leaf(X[:300])
        assert B._cat_paths_cache(b, b._used_trees(None))[0] == "matmul"

        monkeypatch.setattr(B, "_CM_BYTES_CAP", 0)  # force the gather path
        object.__setattr__(b, "_cat_path_cache", None)  # drop cached tables
        cat = B._cat_paths_cache(b, b._used_trees(None))
        assert cat[0] == "gather"
        np.testing.assert_allclose(b.raw_margin(X[:300]), ref, rtol=1e-6)
        np.testing.assert_array_equal(b.predict_leaf(X[:300]), leaves_ref)
