"""Leaf-wise growth, SHAP, and voting-parallel tests.

Reference anchors: leaf-wise is LightGBM's defining algorithm
(``numLeaves`` bounds leaves, ``lightgbm/LightGBMParams.scala:13-251``);
SHAP is ``LightGBMBooster.featuresShap`` (``LightGBMBooster.scala:240-275``);
voting-parallel is ``tree_learner=voting_parallel`` + ``topK``
(``LightGBMParams.scala:20-24``).
"""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import LightGBMClassifier, LightGBMRegressor
from mmlspark_tpu.lightgbm.binning import bin_dataset
from mmlspark_tpu.lightgbm.booster import Booster
from mmlspark_tpu.lightgbm.objectives import auc as auc_metric
from mmlspark_tpu.lightgbm.train import TrainOptions, train


def _make_binary(n=3000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 1.5 + X[:, 1] * X[:, 2] + 0.3 * rng.normal(size=n)
    y = (logit > 0).astype(np.float64)
    return X, y


def _to_table(X, y):
    return Table({"features": X.astype(np.float64), "label": y})


def test_leafwise_honors_num_leaves():
    X, y = _make_binary()
    bins, mapper = bin_dataset(X, max_bin=63)
    opts = TrainOptions(
        objective="binary", num_iterations=3, num_leaves=8, max_bin=63,
        growth="leafwise", min_data_in_leaf=5,
    )
    r = train(bins, y, opts, mapper=mapper)
    b = r.booster
    # Every tree has at most num_leaves reachable leaves, and the tree can be
    # deeper than ceil(log2(num_leaves)) — the signature of best-first growth.
    for t in range(b.num_trees):
        n_leaves = int(b.is_leaf[t].sum())
        assert 1 <= n_leaves <= 8
    assert b.max_depth >= 3


def test_leafwise_beats_or_matches_depthwise_quality():
    X, y = _make_binary(seed=3)
    n_train = 2400
    bins, mapper = bin_dataset(X, max_bin=63)
    scores = {}
    for growth in ("leafwise", "depthwise"):
        opts = TrainOptions(
            objective="binary", num_iterations=30, num_leaves=15, max_bin=63,
            growth=growth,
        )
        r = train(bins[:n_train], y[:n_train], opts, mapper=mapper)
        m = r.booster.raw_margin(X[n_train:])[:, 0]
        scores[growth] = auc_metric(
            y[n_train:], m, np.ones(len(y) - n_train)
        )
    assert scores["leafwise"] > 0.9
    # Leaf-wise should be competitive with the balanced-tree fast path.
    assert scores["leafwise"] >= scores["depthwise"] - 0.02


def test_leafwise_max_depth_cap():
    X, y = _make_binary()
    bins, mapper = bin_dataset(X, max_bin=63)
    opts = TrainOptions(
        objective="binary", num_iterations=3, num_leaves=31, max_depth=3,
        max_bin=63, growth="leafwise",
    )
    r = train(bins, y, opts, mapper=mapper)
    assert r.booster.max_depth <= 3


def test_shap_sums_to_margin():
    X, y = _make_binary(n=800)
    bins, mapper = bin_dataset(X, max_bin=63)
    opts = TrainOptions(objective="binary", num_iterations=8, num_leaves=7, max_bin=63)
    r = train(bins, y, opts, mapper=mapper)
    phi = r.booster.features_shap(X[:100])  # (N, 1, F+1)
    margins = r.booster.raw_margin(X[:100])
    np.testing.assert_allclose(phi.sum(axis=-1), margins, rtol=1e-4, atol=1e-4)
    # The two informative features should dominate attribution mass.
    mass = np.abs(phi[:, 0, :-1]).mean(axis=0)
    assert mass[0] == mass.max()


def test_shap_multiclass_sums_to_margin():
    rng = np.random.default_rng(5)
    n = 900
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] > 0.4).astype(int) + (X[:, 1] > 0.2).astype(int)
    bins, mapper = bin_dataset(X, max_bin=31)
    opts = TrainOptions(
        objective="multiclass", num_class=3, num_iterations=5, num_leaves=7,
        max_bin=31,
    )
    r = train(bins, y.astype(np.float64), opts, mapper=mapper)
    phi = r.booster.features_shap(X[:40])  # (N, 3, F+1)
    np.testing.assert_allclose(
        phi.sum(axis=-1), r.booster.raw_margin(X[:40]), rtol=1e-4, atol=1e-4
    )


def test_features_shap_col_output():
    X, y = _make_binary(n=600)
    clf = LightGBMClassifier(
        numIterations=5, numLeaves=7, featuresShapCol="shap", minDataInLeaf=5
    )
    model = clf.fit(_to_table(X, y))
    out = model.transform(_to_table(X[:30], y[:30]))
    shap = out["shap"]
    assert shap.shape == (30, X.shape[1] + 1)  # binary: C=1 → F+1 contribs
    raw = out["rawPrediction"][:, 1]  # positive-class margin
    np.testing.assert_allclose(shap.sum(axis=1), raw, rtol=1e-4, atol=1e-4)


def test_shap_serde_roundtrip():
    X, y = _make_binary(n=500)
    bins, mapper = bin_dataset(X, max_bin=31)
    opts = TrainOptions(objective="binary", num_iterations=3, num_leaves=7, max_bin=31)
    b = train(bins, y, opts, mapper=mapper).booster
    b2 = Booster.from_string(b.model_to_string())
    np.testing.assert_allclose(
        b2.features_shap(X[:20]), b.features_shap(X[:20]), rtol=1e-6
    )


def test_voting_parallel_quality(mesh8):
    X, y = _make_binary(n=2048, f=16, seed=7)
    bins, mapper = bin_dataset(X, max_bin=63)
    base = dict(
        objective="binary", num_iterations=15, num_leaves=15, max_bin=63,
    )
    r_full = train(
        bins, y, TrainOptions(**base), mapper=mapper, mesh=mesh8
    )
    r_vote = train(
        bins, y,
        TrainOptions(**base, tree_learner="voting_parallel", top_k=6),
        mapper=mapper, mesh=mesh8,
    )
    w = np.ones(len(y))
    auc_full = auc_metric(y, r_full.booster.raw_margin(X)[:, 0], w)
    auc_vote = auc_metric(y, r_vote.booster.raw_margin(X)[:, 0], w)
    # Voting reduces comms F→topK; quality must stay close to the full
    # data_parallel reduction (PV-Tree guarantee).
    assert auc_vote > auc_full - 0.02, (auc_vote, auc_full)


def test_voting_parallel_estimator_param(mesh8):
    X, y = _make_binary(n=1024)
    clf = LightGBMClassifier(
        numIterations=5, numLeaves=7, parallelism="voting_parallel", topK=4
    )
    model = clf.fit(_to_table(X, y))
    out = model.transform(_to_table(X[:50], y[:50]))
    assert "prediction" in out.columns


def test_regressor_leafwise_quality():
    rng = np.random.default_rng(11)
    X = rng.normal(size=(2000, 8))
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 3) + 0.1 * rng.normal(size=2000)
    reg = LightGBMRegressor(numIterations=40, numLeaves=31)
    model = reg.fit(_to_table(X, y))
    pred = model.transform(_to_table(X, y))["prediction"]
    r2 = 1 - np.var(y - pred) / np.var(y)
    assert r2 > 0.9, r2


def test_voting_parallel_feature_fraction(mesh8):
    """featureFraction masks must steer the vote: masked-out features may
    not spend top-K slots, so growth continues on the allowed ones."""
    X, y = _make_binary(n=2048, f=16, seed=9)
    bins, mapper = bin_dataset(X, max_bin=63)
    r = train(
        bins, y,
        TrainOptions(
            objective="binary", num_iterations=10, num_leaves=15, max_bin=63,
            tree_learner="voting_parallel", top_k=4, feature_fraction=0.5, seed=3,
        ),
        mapper=mapper, mesh=mesh8,
    )
    w = np.ones(len(y))
    score = auc_metric(y, r.booster.raw_margin(X)[:, 0], w)
    assert score > 0.8, score
    # trees actually grew (premature-leaf regression guard)
    assert (~r.booster.is_leaf).sum() > 0


class TestBoostingTypes:
    """rf/dart/goss are real algorithms, not accepted-and-ignored strings
    (LightGBMParams.scala boostingType)."""

    def _data(self, n=800, f=8, seed=21):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, f))
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
        return X, y

    def test_goss_differs_from_gbdt_and_learns(self):
        X, y = self._data()
        bins, mapper = bin_dataset(X, max_bin=63)
        base = dict(objective="binary", num_iterations=15, num_leaves=15, max_bin=63)
        r_gbdt = train(bins, y, TrainOptions(**base), mapper=mapper)
        r_goss = train(
            bins, y, TrainOptions(**base, boosting_type="goss"), mapper=mapper
        )
        w = np.ones(len(y))
        auc_goss = auc_metric(y, r_goss.booster.raw_margin(X)[:, 0], w)
        assert auc_goss > 0.9, auc_goss
        # the sampled histogram must actually change the trees
        assert not np.array_equal(
            r_gbdt.booster.leaf_values, r_goss.booster.leaf_values
        )

    def test_goss_rejects_bagging(self):
        X, y = self._data(n=100)
        bins, mapper = bin_dataset(X, max_bin=31)
        with pytest.raises(ValueError, match="goss"):
            train(
                bins, y,
                TrainOptions(
                    objective="binary", num_iterations=2, boosting_type="goss",
                    bagging_fraction=0.5, bagging_freq=1,
                ),
                mapper=mapper,
            )

    def test_rf_mode_averages(self):
        X, y = self._data()
        bins, mapper = bin_dataset(X, max_bin=63)
        r = train(
            bins, y,
            TrainOptions(
                objective="binary", num_iterations=10, num_leaves=15, max_bin=63,
                boosting_type="rf", bagging_fraction=0.6, bagging_freq=1,
            ),
            mapper=mapper,
        )
        w = np.ones(len(y))
        score = auc_metric(y, r.booster.raw_margin(X)[:, 0], w)
        assert score > 0.9, score
        # averaged leaves: magnitudes an order below full-strength trees
        mags = np.abs(r.booster.leaf_values[r.booster.is_leaf])
        assert mags.max() < 2.0

    def test_rf_requires_bagging(self):
        X, y = self._data(n=100)
        bins, mapper = bin_dataset(X, max_bin=31)
        with pytest.raises(ValueError, match="rf"):
            train(
                bins, y,
                TrainOptions(objective="binary", num_iterations=2, boosting_type="rf"),
                mapper=mapper,
            )

    def test_dart_learns_and_scales_trees(self):
        X, y = self._data()
        bins, mapper = bin_dataset(X, max_bin=63)
        r = train(
            bins, y,
            TrainOptions(
                objective="binary", num_iterations=20, num_leaves=15, max_bin=63,
                boosting_type="dart", drop_rate=0.3, seed=5,
            ),
            mapper=mapper,
        )
        w = np.ones(len(y))
        score = auc_metric(y, r.booster.raw_margin(X)[:, 0], w)
        assert score > 0.9, score

    def test_dart_rejects_early_stopping(self):
        X, y = self._data(n=100)
        bins, mapper = bin_dataset(X, max_bin=31)
        with pytest.raises(ValueError, match="dart"):
            train(
                bins, y,
                TrainOptions(
                    objective="binary", num_iterations=2, boosting_type="dart",
                    early_stopping_round=2,
                ),
                mapper=mapper,
            )

    def test_estimator_boosting_type_param(self):
        X, y = self._data(n=300)
        t = _to_table(X, y)
        m = LightGBMClassifier(
            numIterations=5, numLeaves=7, boostingType="dart", dropRate=0.2,
            parallelism="serial",
        ).fit(t)
        out = m.transform(t)
        assert "prediction" in out.columns


class TestPathMatrixPredict:
    """Pin the path-matrix predict to the pointer-routing kernels: leaf
    assignments bit-identical, margins within fp32 summation order."""

    @pytest.mark.parametrize("growth", ["leafwise", "depthwise"])
    @pytest.mark.parametrize("classes,obj", [(1, "binary"), (3, "multiclass")])
    def test_matches_routing_kernels(self, growth, classes, obj):
        import jax.numpy as jnp

        from mmlspark_tpu.lightgbm.booster import (
            _predict_leaf_jit,
            _predict_margin_jit,
        )

        rng = np.random.default_rng(13)
        X = rng.normal(size=(2000, 8))
        X[::9, 2] = np.nan
        y = (
            (np.abs(np.nan_to_num(X[:, 0])).astype(int) % 3).astype(np.float64)
            if classes > 1
            else (np.nan_to_num(X[:, 0]) + X[:, 1] > 0).astype(np.float64)
        )
        bins, mapper = bin_dataset(X, max_bin=63)
        r = train(
            bins, y,
            TrainOptions(
                objective=obj, num_class=classes, num_iterations=6,
                num_leaves=7, max_bin=63, growth=growth,
            ),
            mapper=mapper,
        )
        b = r.booster
        t = b._used_trees(None)
        old_m = np.asarray(_predict_margin_jit(
            jnp.asarray(X, jnp.float32), jnp.asarray(b.split_feature[:t]),
            jnp.asarray(b.split_threshold[:t]), jnp.asarray(b.left_child[:t]),
            jnp.asarray(b.right_child[:t]), jnp.asarray(b.is_leaf[:t]),
            jnp.asarray(b.leaf_values[:t]), jnp.asarray(b.init_score),
            b.num_classes, b.max_depth,
        ))
        np.testing.assert_allclose(b.raw_margin(X), old_m, rtol=1e-5, atol=1e-6)
        old_l = np.asarray(_predict_leaf_jit(
            jnp.asarray(X, jnp.float32), jnp.asarray(b.split_feature[:t]),
            jnp.asarray(b.split_threshold[:t]), jnp.asarray(b.left_child[:t]),
            jnp.asarray(b.right_child[:t]), jnp.asarray(b.is_leaf[:t]),
            b.max_depth,
        ))
        np.testing.assert_array_equal(b.predict_leaf(X), old_l)


class TestLeafBatchRatio:
    def test_ratio_one_reproduces_exact_best_first(self):
        """leaf_batch_ratio=1.0 only batches exact gain ties, so (absent
        ties) every pass splits one leaf and the tree equals the
        leaf_batch=1 sequential build bit for bit."""
        X, y = _make_binary(n=700)
        bins, mapper = bin_dataset(X, max_bin=31)
        base = dict(objective="binary", num_iterations=4, num_leaves=15, max_bin=31)
        seq = train(bins, y, TrainOptions(**base, leaf_batch=1), mapper=mapper)
        gated = train(
            bins, y, TrainOptions(**base, leaf_batch=8, leaf_batch_ratio=1.0),
            mapper=mapper,
        )
        for field in ("split_feature", "split_bin", "left_child", "right_child",
                      "is_leaf"):
            np.testing.assert_array_equal(
                getattr(gated.booster, field), getattr(seq.booster, field),
                err_msg=field,
            )
        np.testing.assert_allclose(
            gated.booster.leaf_values, seq.booster.leaf_values, rtol=1e-6
        )

    def test_ratio_gate_still_fills_leaf_budget(self):
        X, y = _make_binary(n=700)
        bins, mapper = bin_dataset(X, max_bin=31)
        r = train(
            bins, y,
            TrainOptions(objective="binary", num_iterations=2, num_leaves=15,
                         max_bin=31, leaf_batch=8, leaf_batch_ratio=0.3),
            mapper=mapper,
        )
        # every tree still reaches the leaf budget when data supports it
        assert (np.asarray(r.booster.is_leaf).sum(axis=1) == 15).all()

    def test_negative_min_gain_terminates(self):
        """A negative min_gain_to_split (legal on a directly-constructed
        TrainOptions) combined with leaf_batch_ratio must still make
        progress: the pass best always qualifies for its own ratio gate,
        so the while_loop cannot spin on an uncommittable frontier."""
        X, y = _make_binary(n=400)
        bins, mapper = bin_dataset(X, max_bin=15)
        r = train(
            bins, y,
            TrainOptions(objective="binary", num_iterations=2, num_leaves=7,
                         max_bin=15, min_gain_to_split=-5.0,
                         leaf_batch=4, leaf_batch_ratio=0.5),
            mapper=mapper,
        )
        assert r.booster.num_trees == 2


class TestScanSegmentation:
    """The one-dispatch scanned fit splits into equal segments when a single
    device program would run past the remote-attach watchdog
    (MMLSPARK_TPU_SCAN_ROW_ITERS); margins thread between dispatches, so
    results must be BIT-identical to the unsegmented scan — including GOSS,
    whose per-iteration rng folds on the GLOBAL iteration id."""

    @pytest.mark.parametrize("boosting", ["gbdt", "goss"])
    def test_segmented_scan_is_bit_identical(self, boosting, monkeypatch):
        X, y = _make_binary(n=3000, f=8, seed=17)
        bins, mapper = bin_dataset(X, max_bin=31)
        opts = TrainOptions(
            objective="binary", num_iterations=9, num_leaves=15, max_bin=31,
            boosting_type=boosting,
        )
        single = train(bins, y, opts, mapper=mapper)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_ROW_ITERS", "9000")  # 3 segments
        segmented = train(bins, y, opts, mapper=mapper)
        np.testing.assert_array_equal(
            np.asarray(segmented.booster.leaf_values),
            np.asarray(single.booster.leaf_values),
        )
        np.testing.assert_array_equal(
            np.asarray(segmented.booster.split_feature),
            np.asarray(single.booster.split_feature),
        )


class TestCategoricalURouting:
    """Row routing through categorical splits has two formulations: the
    matmul against the fit-resident one-hot U (TPU hot path) and the
    per-leaf mask gather (no-U fallback, what the mesh/CPU paths use).
    This pins the membership MATH of the matmul formulation — exactly the
    expression the leafwise builder traces — against the direct gather.
    (Comparing whole fits would conflate routing with the histogram
    pass's different fp summation order.)"""

    def test_membership_matmul_matches_gather(self):
        import jax.numpy as jnp

        from mmlspark_tpu.ops.u_histogram import (
            build_u, cat_row_maps, make_u_spec, membership_matmul,
        )

        rng = np.random.default_rng(23)
        n, k, b = 1000, 8, 16
        widths = [5, 16, 9, 3]  # ragged per-feature bin counts
        f = len(widths)
        bins_np = np.column_stack(
            [rng.integers(0, w, size=n) for w in widths]
        ).astype(np.int32)
        spec = make_u_spec(b, f, widths)
        u = build_u(jnp.asarray(bins_np), spec)

        sf = jnp.asarray(rng.integers(0, f, size=k), jnp.int32)
        scm = jnp.asarray(rng.random((k, b)) < 0.4)

        # the SAME helpers the leafwise builder traces, with a STRICT
        # subset of categorical features (the production shape): leaves
        # splitting on a non-categorical feature must produce all-False
        # rows (the caller masks them via the node's is-categorical flag)
        cat_subset = [0, 2]
        rows_np, fr_np, lr_np = cat_row_maps(spec, cat_subset)
        in_set = np.asarray(
            membership_matmul(
                u[jnp.asarray(rows_np)],
                jnp.asarray(fr_np), jnp.asarray(lr_np), sf, scm, n,
            )
        )

        # the gather reference, row by row
        scm_np = np.asarray(scm)
        sf_np = np.asarray(sf)
        expected = np.stack(
            [
                scm_np[jj][bins_np[:, sf_np[jj]]]
                if sf_np[jj] in cat_subset
                else np.zeros(n, bool)
                for jj in range(k)
            ]
        )
        np.testing.assert_array_equal(in_set, expected)
