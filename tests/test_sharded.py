"""Out-of-core sharded ingest (SURVEY.md §7 hard parts: ingest at
Higgs-1B scale) — shard streaming, streaming binning into a uint8 memmap,
and an out-of-core GBDT fit on the CPU mesh matching the in-memory fit."""

import numpy as np
import pytest

from mmlspark_tpu.data.sharded import ShardedDataset, fit_gbdt_sharded
from mmlspark_tpu.lightgbm import LightGBMClassifier
from mmlspark_tpu.lightgbm.binning import bin_dataset
from mmlspark_tpu.lightgbm.objectives import auc as auc_metric


@pytest.fixture(scope="module")
def shard_data(tmp_path_factory):
    rng = np.random.default_rng(0)
    n, f = 20_000, 12
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.2 * rng.normal(size=n) > 0).astype(np.float64)
    out = tmp_path_factory.mktemp("shards")
    ds = ShardedDataset.write_shards(str(out), X, y, rows_per_shard=3_000)
    return ds, X, y


class TestShardedDataset:
    def test_scan_and_iter(self, shard_data):
        ds, X, y = shard_data
        assert ds.num_rows == len(X)
        assert ds.num_features == X.shape[1]
        assert len(ds.paths) == 7  # ceil(20k / 3k)
        total = 0
        for Xs, ys, ws in ds.iter_shards():
            assert Xs.shape[1] == X.shape[1]
            assert ws is None
            total += len(Xs)
        assert total == len(X)

    def test_streaming_binning_matches_in_memory(self, shard_data, tmp_path):
        ds, X, y = shard_data
        # full-sample mapper == in-memory mapper (same rows, same rng path
        # not guaranteed across layouts — compare the BINS they induce)
        mapper = ds.fit_mapper(max_bin=63, sample_per_shard=10**9)
        bins_mem, _ = bin_dataset(X, max_bin=63, mapper=mapper)
        bins_stream, y_out, w_out = ds.bin_to_memmap(
            mapper, out_path=str(tmp_path / "bins.u8")
        )
        assert bins_stream.dtype == np.uint8
        np.testing.assert_array_equal(np.asarray(bins_stream), bins_mem)
        np.testing.assert_array_equal(y_out, y)
        assert w_out is None

    def test_out_of_core_fit_matches_quality(self, shard_data, mesh8):
        ds, X, y = shard_data
        clf = LightGBMClassifier(numIterations=15, numLeaves=15, maxBin=63)
        model = fit_gbdt_sharded(clf, ds, mesh=mesh8, sample_per_shard=5_000)
        margins = model.booster.raw_margin(X)[:, 0]
        score = auc_metric(y, margins, np.ones(len(y)))
        # in-memory reference at identical settings
        from mmlspark_tpu.data.table import Table

        ref = LightGBMClassifier(
            numIterations=15, numLeaves=15, maxBin=63, parallelism="serial"
        ).fit(Table({"features": X, "label": y}))
        ref_score = auc_metric(y, ref.booster.raw_margin(X)[:, 0], np.ones(len(y)))
        assert score > ref_score - 0.01, (score, ref_score)

    def test_missing_labels_raise(self, tmp_path):
        rng = np.random.default_rng(1)
        ds = ShardedDataset.write_shards(
            str(tmp_path / "nolabel"), rng.normal(size=(100, 3)), y=None,
            rows_per_shard=50,
        )
        mapper = ds.fit_mapper(max_bin=15)
        with pytest.raises(ValueError, match="no labels"):
            ds.bin_to_memmap(mapper)

    def test_mismatched_shards_raise(self, tmp_path):
        rng = np.random.default_rng(2)
        d = tmp_path / "bad"
        d.mkdir()
        np.savez(d / "a.npz", X=rng.normal(size=(10, 3)), y=np.zeros(10))
        np.savez(d / "b.npz", X=rng.normal(size=(10, 4)), y=np.zeros(10))
        ds = ShardedDataset([str(d / "a.npz"), str(d / "b.npz")])
        with pytest.raises(ValueError, match="features"):
            ds.num_rows

    def test_empty_raises(self):
        with pytest.raises(ValueError, match="no shard"):
            ShardedDataset([])
