"""Process-group supervisor tests (``mmlspark_tpu.runtime.procgroup``).

The fast tests exercise the in-process pieces: the seeded port prober,
the socket star allreduce (threads standing in for processes), the
worker-side fault directive check, and the spec/exit-status plumbing.
The ``slow`` tests spawn REAL worker processes and cover the tentpole
claims: a gang that completes, and a gang whose member is SIGKILL'd
mid-collective yet re-forms and finishes, with the loss booked as
events, health failures, and structured exit statuses.
"""

import json
import os
import socket
import threading

import numpy as np
import pytest

from mmlspark_tpu.runtime.faults import FaultPlan
from mmlspark_tpu.runtime.procgroup import (
    AllreduceGroup,
    ExitStatus,
    GangFailedError,
    GroupRevokedError,
    ProcessGroup,
    pick_port,
    scrub_env,
)


class TestPickPort:
    def test_seeded_is_deterministic(self):
        assert pick_port(seed=42) == pick_port(seed=42)

    def test_exclude_respected(self):
        first = pick_port(seed=7)
        second = pick_port(seed=7, exclude={first})
        assert second != first

    def test_port_is_bindable(self):
        port = pick_port(seed=3)
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", port))


class TestScrubEnv:
    def test_strips_accelerator_vars_and_pins_cpu(self):
        env = scrub_env({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "PALLAS_AXON_X": "1", "AXON_Y": "2", "TPU_Z": "3",
            "HOME": "/root",
        })
        assert "XLA_FLAGS" not in env
        assert not any(k.startswith(("PALLAS_AXON", "AXON", "TPU_")) for k in env)
        assert env["JAX_PLATFORMS"] == "cpu"
        assert env["HOME"] == "/root"

    def test_repo_root_on_pythonpath(self):
        env = scrub_env({})
        import mmlspark_tpu

        root = os.path.dirname(os.path.dirname(mmlspark_tpu.__file__))
        assert root in env["PYTHONPATH"].split(os.pathsep)


class TestAllreduceGroup:
    def _run_group(self, world, arrays, port):
        results = [None] * world
        errors = []

        def member(rank):
            try:
                g = AllreduceGroup(rank, world, port, timeout=20.0)
                results[rank] = np.asarray(g.allreduce(arrays[rank]))
                g.barrier()
                g.close()
            except Exception as e:  # noqa: BLE001
                errors.append((rank, e))

        threads = [threading.Thread(target=member, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not errors, errors
        return results

    def test_three_member_sum(self):
        world = 3
        arrays = [np.full((2, 4), float(r + 1), np.float32) for r in range(world)]
        port = pick_port(seed=100)
        results = self._run_group(world, arrays, port)
        for r in range(world):
            np.testing.assert_allclose(results[r], np.full((2, 4), 6.0))

    def test_single_member_is_identity(self):
        g = AllreduceGroup(0, 1, 0)
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_array_equal(g.allreduce(x), x)
        g.barrier()
        g.close()

    def test_peer_death_revokes_group(self):
        port = pick_port(seed=101)
        ready = threading.Event()
        outcome = {}

        def survivor():
            g = AllreduceGroup(0, 2, port, timeout=10.0)
            ready.set()
            try:
                g.allreduce(np.ones(4, np.float32))
                g.allreduce(np.ones(4, np.float32))  # peer is gone by now
                outcome["error"] = None
            except GroupRevokedError:
                outcome["error"] = "revoked"
                assert g.revoked
            finally:
                g.close()

        t = threading.Thread(target=survivor)
        t.start()
        peer = AllreduceGroup(1, 2, port, timeout=10.0)
        peer.allreduce(np.ones(4, np.float32))
        peer.close()  # vanish without a second round
        t.join(timeout=20.0)
        assert outcome.get("error") == "revoked"


class TestFaultDirectives:
    def test_kill_process_plan_round_trip(self):
        plan = FaultPlan(seed=1).kill_process(2, iteration=5, epoch=0)
        directives = plan.process_kill_directives()
        assert directives == [{"member": 2, "iteration": 5, "epoch": 0}]
        # worker side: only the targeted member at the targeted iteration
        assert FaultPlan.should_die(directives, member=2, iteration=5, epoch=0)
        assert not FaultPlan.should_die(directives, member=1, iteration=5, epoch=0)
        assert not FaultPlan.should_die(directives, member=2, iteration=4, epoch=0)

    def test_mark_killed_is_one_shot(self):
        plan = FaultPlan(seed=1).kill_process(1, iteration=0)
        assert plan.mark_process_killed(1)
        assert not plan.mark_process_killed(1)
        assert ("kill_process", 1, 0) in plan.fired
        assert plan.process_kill_directives() == []

    def test_exit_status_signal(self):
        dead = ExitStatus(member=0, pid=1, returncode=-9, reason="signal:9", epoch=0)
        clean = ExitStatus(member=1, pid=2, returncode=0, reason="exit:0", epoch=0)
        assert dead.signal == 9
        assert clean.signal is None


class TestSpecPlumbing:
    def test_write_spec_ships_fault_directives_once(self, tmp_path):
        plan = FaultPlan(seed=2).kill_process(0, iteration=1)
        pg = ProcessGroup(
            2, "mmlspark_tpu.runtime.procgroup:demo_entry",
            workdir=str(tmp_path), rendezvous="none", faults=plan,
        )
        pg._write_spec(0)
        spec = json.loads((tmp_path / "epoch-0.json").read_text())
        assert spec["members"] == [0, 1]
        assert spec["faults"] == [{"member": 0, "iteration": 1, "epoch": 0}]
        assert spec["entry"] == "mmlspark_tpu.runtime.procgroup:demo_entry"
        # after the driver books the kill, the NEXT spec ships no directive
        plan.mark_process_killed(0)
        pg._write_spec(1)
        spec1 = json.loads((tmp_path / "epoch-1.json").read_text())
        assert spec1["faults"] == []

    def test_spec_ports_differ_per_epoch(self, tmp_path):
        pg = ProcessGroup(
            2, "mmlspark_tpu.runtime.procgroup:demo_entry",
            workdir=str(tmp_path), rendezvous="none", seed=5,
        )
        pg._write_spec(0)
        pg._write_spec(1)
        s0 = json.loads((tmp_path / "epoch-0.json").read_text())
        s1 = json.loads((tmp_path / "epoch-1.json").read_text())
        assert s0["coordinator_port"] != s1["coordinator_port"]
        assert s0["reduce_port"] != s0["coordinator_port"]


@pytest.mark.slow
class TestProcessGroupLive:
    """Real spawned worker processes."""

    def test_happy_path_allreduce(self, tmp_path):
        with ProcessGroup(
            3, "mmlspark_tpu.runtime.procgroup:demo_entry",
            payload={"iterations": 2, "expect_members": [0, 1, 2]},
            workdir=str(tmp_path), rendezvous="none", epoch_timeout_s=120.0,
        ) as pg:
            results = pg.run()
        assert sorted(results) == [0, 1, 2]
        for res in results.values():
            assert res["total"] == 32.0 * 6  # (1+2+3) * 4*8 grid
        assert pg.epoch == 0

    def test_sigkill_reform_and_complete(self, tmp_path):
        plan = FaultPlan(seed=9).kill_process(1, iteration=1)
        with ProcessGroup(
            2, "mmlspark_tpu.runtime.procgroup:demo_entry",
            payload={"iterations": 3},
            workdir=str(tmp_path), rendezvous="none",
            epoch_timeout_s=120.0, faults=plan,
        ) as pg:
            results = pg.run()
        assert sorted(results) == [0, 1]
        assert pg.epoch == 1  # one re-formation
        assert [s.reason for s in pg.exit_statuses] == ["signal:9"]
        assert pg.exit_statuses[0].member == 1
        assert plan.fired == [("kill_process", 1, 0)]
        assert pg.health.score(1) > 0

    def test_payload_failure_surfaces_worker_log(self, tmp_path):
        with ProcessGroup(
            1, "mmlspark_tpu.runtime.procgroup:no_such_entry",
            workdir=str(tmp_path), rendezvous="none", epoch_timeout_s=60.0,
        ) as pg:
            with pytest.raises(RuntimeError, match="no_such_entry"):
                pg.run()

    def test_no_respawn_exhausts_gang(self, tmp_path):
        plan = FaultPlan(seed=3).kill_process(0, iteration=0)
        with ProcessGroup(
            1, "mmlspark_tpu.runtime.procgroup:demo_entry",
            payload={"iterations": 2}, workdir=str(tmp_path),
            rendezvous="none", epoch_timeout_s=60.0, respawn=False,
            faults=plan,
        ) as pg:
            with pytest.raises(GangFailedError):
                pg.run()
