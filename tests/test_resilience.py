"""resilience/ tests — seeded chaos with injectable clocks, zero real
sleeps on every retry/breaker path (the fake-clock discipline of
``tests/test_runtime.py`` applied to the request plane)."""

import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.io.http.clients import AsyncHTTPClient, HTTPClient
from mmlspark_tpu.io.http.schema import (
    EntityData,
    HeaderData,
    HTTPRequestData,
    HTTPResponseData,
    StatusLineData,
)
from mmlspark_tpu.observability.events import BreakerTripped, RequestShed, get_bus
from mmlspark_tpu.observability.registry import MetricsRegistry
from mmlspark_tpu.resilience import (
    AdmissionController,
    BreakerOpenError,
    BreakerRegistry,
    CircuitBreaker,
    Deadline,
    DeadlineExceededError,
    RetryBudget,
    RetryPolicy,
    current_deadline,
    deadline_scope,
    parse_retry_after,
)
from mmlspark_tpu.runtime.faults import FaultPlan, inject_faults
from mmlspark_tpu.serving.server import ServingServer, _BatchLoop, _PendingRequest

from tests.http_mock import MockService


class FakeClock:
    """Monotonic clock whose time only moves when told (or when a fake
    sleep is taken), so breaker cooldowns and deadlines are exact."""

    def __init__(self, start: float = 1000.0):
        self.t = start
        self.sleeps = []

    def now(self) -> float:
        return self.t

    def advance(self, seconds: float) -> None:
        self.t += seconds

    def sleep(self, seconds: float) -> None:
        self.sleeps.append(seconds)
        self.t += seconds


def _get(url: str, method: str = "GET") -> HTTPRequestData:
    return HTTPRequestData(url=url, method=method)


def _response(status: int, payload=None, headers=()) -> HTTPResponseData:
    return HTTPResponseData(
        statusLine=StatusLineData("HTTP/1.1", status, ""),
        headers=[HeaderData(k, v) for k, v in headers],
        entity=EntityData(content=json.dumps(payload or {}).encode()),
    )


class TestCircuitBreaker:
    def _breaker(self, fc, **kw):
        kw.setdefault("failure_threshold", 3)
        kw.setdefault("window_s", 10.0)
        kw.setdefault("reset_timeout_s", 5.0)
        return CircuitBreaker(
            "dep", clock=fc.now, registry=MetricsRegistry(), **kw
        )

    def test_trips_open_at_threshold(self):
        fc = FakeClock()
        br = self._breaker(fc)
        br.record_failure()
        br.record_failure()
        assert br.state == "closed" and br.allow()
        br.record_failure()
        assert br.state == "open" and br.trips == 1
        assert not br.allow()
        assert br.retry_after() == pytest.approx(5.0)

    def test_window_expiry_forgets_old_failures(self):
        fc = FakeClock()
        br = self._breaker(fc)
        br.record_failure()
        br.record_failure()
        fc.advance(11.0)  # both failures age out of the 10s window
        br.record_failure()
        assert br.state == "closed"

    def test_half_open_probe_success_closes(self):
        fc = FakeClock()
        br = self._breaker(fc)
        for _ in range(3):
            br.record_failure()
        fc.advance(5.0)
        assert br.state == "half_open"
        assert br.allow()          # the single probe
        assert not br.allow()      # half_open_max=1: second caller rejected
        br.record_success()
        assert br.state == "closed" and br.allow()

    def test_half_open_probe_failure_reopens(self):
        fc = FakeClock()
        br = self._breaker(fc)
        for _ in range(3):
            br.record_failure()
        fc.advance(5.0)
        assert br.allow()
        br.record_failure()
        assert br.state == "open"
        # the cooldown restarted from the probe failure
        assert br.retry_after() == pytest.approx(5.0)

    def test_gauge_and_trip_counter_exported(self):
        fc = FakeClock()
        reg = MetricsRegistry()
        br = CircuitBreaker(
            "api.example:443", failure_threshold=1, clock=fc.now, registry=reg
        )
        br.record_failure()
        text = reg.exposition()
        assert 'resilience_breaker_state{breaker="api.example:443"} 2' in text
        assert 'resilience_breaker_trips_total{breaker="api.example:443"} 1' in text

    def test_trip_publishes_event(self):
        fc = FakeClock()
        br = self._breaker(fc, failure_threshold=1)
        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        try:
            br.record_failure()
        finally:
            bus.remove_listener(seen.append)
        trips = [e for e in seen if isinstance(e, BreakerTripped)]
        assert len(trips) == 1 and trips[0].breaker == "dep"

    def test_registry_keys_by_host(self):
        fc = FakeClock()
        reg = BreakerRegistry(clock=fc.now, registry=MetricsRegistry())
        a = reg.for_url("http://h1:8080/path/x")
        b = reg.for_url("http://h1:8080/other")
        c = reg.for_url("http://h2:8080/path/x")
        assert a is b and a is not c
        assert a.name == "h1:8080"


class TestRetryPolicy:
    def test_seeded_jitter_is_deterministic(self):
        d1 = [RetryPolicy(seed=42).delay(i) for i in range(6)]
        d2 = [RetryPolicy(seed=42).delay(i) for i in range(6)]
        d3 = [RetryPolicy(seed=43).delay(i) for i in range(6)]
        assert d1 == d2 and d1 != d3
        # full jitter: bounded by min(cap, base * 2**n)
        for i, d in enumerate(d1):
            assert 0.0 <= d <= min(5.0, 0.1 * 2 ** i)

    def test_legacy_waits_schedule(self):
        p = RetryPolicy.from_legacy_waits((0.1, 0.5, 1.0))
        assert p.max_attempts == 4
        assert [p.delay(i) for i in range(3)] == [0.1, 0.5, 1.0]

    def test_parse_retry_after_delta_and_http_date(self):
        assert parse_retry_after("120") == 120.0
        assert parse_retry_after(" 0 ") == 0.0
        assert parse_retry_after("-5") == 0.0
        import email.utils

        when = "Wed, 21 Oct 2015 07:28:00 GMT"
        ts = email.utils.parsedate_to_datetime(when).timestamp()
        assert parse_retry_after(when, now_wall=lambda: ts - 90) == pytest.approx(90.0)
        assert parse_retry_after(when, now_wall=lambda: ts + 90) == 0.0
        assert parse_retry_after("soonish") is None
        assert parse_retry_after(None) is None

    def test_retry_after_only_on_429_and_503(self):
        p = RetryPolicy(seed=0)
        headers = {"Retry-After": "9"}
        assert p.retry_after(headers, 503) == 9.0
        assert p.retry_after(headers, 429) == 9.0
        assert p.retry_after(headers, 500) is None

    def test_budget_caps_retries(self):
        reg = MetricsRegistry()
        budget = RetryBudget(ratio=0.0, min_tokens=1.0, registry=reg)
        fc = FakeClock()
        p = RetryPolicy(
            max_attempts=10, base=0.0, seed=0, budget=budget, sleep=fc.sleep
        )
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("down")

        with pytest.raises(ValueError):
            p.run(fn)
        # 1 first attempt + exactly min_tokens=1 budgeted retry
        assert len(calls) == 2
        assert reg.get("resilience_retry_budget_exhausted_total").value == 1

    def test_run_returns_after_transient_failures(self):
        fc = FakeClock()
        p = RetryPolicy(max_attempts=5, base=0.5, seed=7, sleep=fc.sleep)
        state = {"n": 0}

        def fn():
            state["n"] += 1
            if state["n"] < 3:
                raise OSError("flaky")
            return "ok"

        assert p.run(fn) == "ok"
        assert len(fc.sleeps) == 2 and all(s >= 0 for s in fc.sleeps)


class TestDeadline:
    def test_header_round_trip_and_expiry(self):
        fc = FakeClock()
        d = Deadline.after(1.0, clock=fc.now)
        assert d.to_header() == "1000"
        fc.advance(0.4)
        assert d.to_header() == "600"
        d2 = Deadline.from_header(d.to_header(), clock=fc.now)
        assert d2.remaining() == pytest.approx(0.6)
        fc.advance(0.7)
        assert d.expired and d.to_header() == "0"
        assert Deadline.from_header("garbage") is None
        assert Deadline.from_header(None) is None

    def test_scope_tighter_outer_wins(self):
        fc = FakeClock()
        assert current_deadline() is None
        with deadline_scope(1.0, clock=fc.now) as outer:
            with deadline_scope(5.0, clock=fc.now) as inner:
                assert inner is outer  # callee cannot extend the budget
                assert current_deadline().remaining() == pytest.approx(1.0)
            with deadline_scope(0.25, clock=fc.now) as tighter:
                assert tighter is not outer
                assert current_deadline().remaining() == pytest.approx(0.25)
        assert current_deadline() is None


class TestHTTPClientResilience:
    """Seeded chaos against the rewritten client: every sleep is fake."""

    def _policy(self, fc, attempts=10, **kw):
        kw.setdefault("base", 0.0)
        kw.setdefault("seed", 0)
        return RetryPolicy(max_attempts=attempts, sleep=fc.sleep, **kw)

    def test_storm_trips_breaker_and_stops_outbound(self):
        fc = FakeClock()
        breakers = BreakerRegistry(
            failure_threshold=3, window_s=100.0, reset_timeout_s=60.0,
            registry=MetricsRegistry(),
        )
        client = HTTPClient(policy=self._policy(fc), breakers=breakers)
        plan = FaultPlan(seed=0).http_storm(count=10, status=503)
        with inject_faults(plan):
            with pytest.raises(BreakerOpenError) as ei:
                client.send(_get("http://127.0.0.1:9/predict"))
        # exactly threshold attempts went "out"; the rest were cut locally
        assert [f[0] for f in plan.fired] == ["http_status"] * 3
        assert plan.pending == 7
        assert breakers.get("127.0.0.1:9").state == "open"
        assert ei.value.retry_after > 0

    def test_throttle_does_not_trip_breaker(self):
        fc = FakeClock()
        breakers = BreakerRegistry(
            failure_threshold=2, registry=MetricsRegistry()
        )
        client = HTTPClient(
            policy=self._policy(fc, attempts=4), breakers=breakers
        )
        plan = FaultPlan(seed=0).http_storm(count=4, status=429)
        with inject_faults(plan):
            resp = client.send(_get("http://127.0.0.1:9/limited"))
        assert resp.status_code == 429  # exhausted retries, returned loudly
        assert breakers.get("127.0.0.1:9").state == "closed"

    def test_retry_after_honored_on_503(self):
        fc = FakeClock()
        client = HTTPClient(policy=self._policy(fc), breakers=None)
        with MockService() as mock:
            plan = FaultPlan(seed=0).http_storm(
                count=2, status=503, retry_after=2.5
            )
            with inject_faults(plan):
                resp = client.send(_get(mock.url + "/x"))
        assert resp.status_code == 200
        assert fc.sleeps == [2.5, 2.5]  # jitter base 0 raised to the hint

    def test_terminal_retryable_status_logged_not_silent(self, caplog):
        fc = FakeClock()
        client = HTTPClient(policy=self._policy(fc, attempts=2), breakers=None)
        plan = FaultPlan(seed=0).http_storm(count=5, status=503)
        with caplog.at_level("WARNING", logger="mmlspark_tpu.io.http"):
            with inject_faults(plan):
                resp = client.send(_get("http://127.0.0.1:9/down"))
        assert resp.status_code == 503
        assert "giving up" in caplog.text

    def test_connection_reset_fault_raises_after_retries(self):
        fc = FakeClock()
        client = HTTPClient(policy=self._policy(fc, attempts=2), breakers=None)
        plan = FaultPlan(seed=0).http_reset(count=5)
        with inject_faults(plan):
            with pytest.raises(ConnectionResetError):
                client.send(_get("http://127.0.0.1:9/reset"))
        assert [f[0] for f in plan.fired] == ["http_reset"] * 2

    def test_deadline_forwarded_as_header(self):
        client = HTTPClient(breakers=None)
        with MockService() as mock:
            with deadline_scope(30.0):
                resp = client.send(_get(mock.url + "/fwd"))
            assert resp.status_code == 200
            ms = int(mock.requests[0]["headers"]["X-Deadline-Ms"])
        assert 0 < ms <= 30_000

    def test_expired_deadline_short_circuits(self):
        client = HTTPClient(breakers=None)
        with MockService() as mock:
            fc = FakeClock()
            expired = Deadline.after(-1.0, clock=fc.now)
            with deadline_scope(expired, clock=fc.now):
                with pytest.raises(DeadlineExceededError):
                    client.send(_get(mock.url + "/late"))
            assert mock.requests == []  # no wasted wire call

    def test_async_breaker_open_degrades_to_synthetic_503(self):
        breakers = BreakerRegistry(
            failure_threshold=1, reset_timeout_s=60.0,
            registry=MetricsRegistry(),
        )
        breakers.for_url("http://127.0.0.1:9/").record_failure()
        client = AsyncHTTPClient(concurrency=2, breakers=breakers)
        out = client.send_all([
            None, _get("http://127.0.0.1:9/a"), _get("http://127.0.0.1:9/b"),
        ])
        assert out[0] is None
        for resp in out[1:]:
            assert resp.status_code == 503
            assert "Retry-After" in resp.header_map()


class _Doubler(Transformer):
    def transform(self, table):
        x = np.asarray(table.column("input"), dtype=np.float64)
        return table.with_column("prediction", x * 2)


class _GatedModel(Transformer):
    """Blocks every transform until ``release`` is set."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.release = threading.Event()
        self.calls = 0

    def transform(self, table):
        self.calls += 1
        assert self.release.wait(timeout=10.0), "model gate never released"
        x = np.asarray(table.column("input"), dtype=np.float64)
        return table.with_column("prediction", x * 2)


def _post(url, payload, timeout=10, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read() or b"{}")


class TestServingAdmission:
    def test_overload_sheds_with_429_retry_after(self):
        model = _GatedModel()
        with ServingServer(
            model, max_latency_ms=1.0, max_pending=2, shed_retry_after_s=0.25,
        ) as srv:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [
                    pool.submit(_post, srv.info.url, {"input": float(i)})
                    for i in range(2)
                ]
                deadline = time.monotonic() + 5.0
                while srv.loop.admission.inflight < 2:
                    assert time.monotonic() < deadline, "requests never admitted"
                    time.sleep(0.005)
                status, headers, _ = _post(srv.info.url, {"input": 9.0})
                assert status == 429
                assert headers["Retry-After"] == "0.25"
                model.release.set()
                results = [f.result() for f in futs]
            assert sorted(r[0] for r in results) == [200, 200]
            # capacity freed after replies (release runs just after the
            # response write, so poll briefly)
            deadline = time.monotonic() + 5.0
            while srv.loop.admission.inflight > 0:
                assert time.monotonic() < deadline, "admission never released"
                time.sleep(0.005)

    def test_shed_counted_and_published(self):
        reg = MetricsRegistry()
        adm = AdmissionController(max_pending=1, registry=reg, name="t")
        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        try:
            assert adm.try_acquire()
            assert not adm.try_acquire()
        finally:
            bus.remove_listener(seen.append)
        adm.release()
        assert reg.get("serving_shed_total").value == 1
        sheds = [e for e in seen if isinstance(e, RequestShed)]
        assert len(sheds) == 1 and sheds[0].reason == "max_pending"

    def test_health_reports_inflight(self):
        with ServingServer(_Doubler(), max_pending=4) as srv:
            with urllib.request.urlopen(
                srv.info.url + "healthz", timeout=5
            ) as r:
                health = json.loads(r.read())
        assert health["inflight"] == 0


class TestServingDeadlines:
    def test_expired_requests_purged_before_model_apply(self):
        class Exploder(Transformer):
            def transform(self, table):
                raise AssertionError("model must not run on expired requests")

        reg = MetricsRegistry()
        loop = _BatchLoop(Exploder(), "input", "prediction", 8, 1.0, registry=reg)
        fc = FakeClock()
        dead = _PendingRequest(
            rid="r-dead", payload=1.0,
            deadline=Deadline.after(-0.1, clock=fc.now),
        )
        loop.submit(dead)
        loop._process([dead])  # loop not started: drive one batch directly
        assert dead.status == 504 and dead.event.is_set()
        assert b"deadline exceeded" in dead.response
        assert loop._pending == {}
        assert reg.get("serving_expired_total").value == 1

    def test_zero_deadline_header_yields_504(self):
        with ServingServer(_Doubler()) as srv:
            status, _, body = _post(
                srv.info.url, {"input": 1.0}, headers={"X-Deadline-Ms": "0"}
            )
        assert status == 504 and body["error"] == "timeout"

    def test_reply_timeout_forgets_rid(self):
        model = _GatedModel()
        with ServingServer(
            model, max_latency_ms=1.0, reply_timeout_s=0.2, drain_timeout_s=0.2,
        ) as srv:
            status, _, _ = _post(srv.info.url, {"input": 1.0})
            assert status == 504
            assert srv.loop._pending == {}  # 504 deregistered the rid
            model.release.set()

    def test_graceful_drain_answers_admitted_requests(self):
        srv = ServingServer(_Doubler(), max_latency_ms=1.0).start()
        try:
            status, _, out = _post(srv.info.url, {"input": 4.0})
            assert status == 200 and out["prediction"] == 8.0
        finally:
            srv.stop()
        # the drain left nothing queued or half-processed
        assert srv.loop.queue.empty()
        assert srv.loop.uncommitted_epochs == []


class _PollSvc:
    """Minimal stand-in exercising CognitiveServicesBase._poll."""

    def __new__(cls, **params):
        from mmlspark_tpu.cognitive.base import CognitiveServicesBase

        class Svc(CognitiveServicesBase):
            polling = True

            def prepare_entity(self, table, row):
                return {}

        return Svc(outputCol="out", url="http://unused", **params)


class TestCognitivePolling:
    def _resp_202(self):
        return _response(202, headers=[("Operation-Location", "http://op/1")])

    def _patch_client(self, monkeypatch, responses):
        calls = []

        class FakeClient:
            def __init__(self, *a, **kw):
                pass

            def send(self, request):
                calls.append(request)
                return responses[min(len(calls) - 1, len(responses) - 1)]

        monkeypatch.setattr(
            "mmlspark_tpu.io.http.clients.HTTPClient", FakeClient
        )
        return calls

    def test_wall_clock_deadline_bounds_polling(self, monkeypatch):
        svc = _PollSvc(
            pollingIntervalMs=50, maxPollingRetries=1000, pollingDeadlineMs=100
        )
        calls = self._patch_client(
            monkeypatch, [_response(200, {"status": "running"})]
        )
        fc = FakeClock()
        with pytest.raises(TimeoutError, match="polling deadline"):
            svc._poll(self._resp_202(), None, clock=fc.now, sleep=fc.sleep)
        # 100ms budget / 50ms interval: a couple of polls, not 1000
        # (float rounding can slip one extra ~0-length wait through)
        assert len(calls) <= 2
        assert sum(fc.sleeps) == pytest.approx(0.1, abs=1e-6)

    def test_poll_honors_retry_after_hint(self, monkeypatch):
        svc = _PollSvc(
            pollingIntervalMs=50, maxPollingRetries=10,
            pollingDeadlineMs=10_000_000,
        )
        self._patch_client(monkeypatch, [
            _response(503, {}, headers=[("Retry-After", "3")]),
            _response(200, {"status": "succeeded", "v": 1}),
        ])
        fc = FakeClock()
        out = svc._poll(self._resp_202(), None, clock=fc.now, sleep=fc.sleep)
        assert out == {"status": "succeeded", "v": 1}
        assert fc.sleeps == [0.05, 3.0]  # throttled poll stretched the wait

    def test_ambient_deadline_clips_poll(self, monkeypatch):
        svc = _PollSvc(
            pollingIntervalMs=50, maxPollingRetries=1000,
            pollingDeadlineMs=10_000_000,
        )
        self._patch_client(
            monkeypatch, [_response(200, {"status": "running"})]
        )
        fc = FakeClock()
        with deadline_scope(0.08, clock=fc.now):
            with pytest.raises(TimeoutError):
                svc._poll(self._resp_202(), None, clock=fc.now, sleep=fc.sleep)


class TestDownloaderRetry:
    def test_success_after_transient_failures(self):
        from mmlspark_tpu.downloader.repository import FaultToleranceUtils

        def run_once():
            fc = FakeClock()
            state = {"n": 0}

            def fn():
                state["n"] += 1
                if state["n"] < 3:
                    raise OSError("transient")
                return "payload"

            out = FaultToleranceUtils.retry_with_timeout(
                fn, times=3, backoff=0.5, sleep=fc.sleep
            )
            return out, fc.sleeps

        out1, sleeps1 = run_once()
        out2, sleeps2 = run_once()
        assert out1 == out2 == "payload"
        assert len(sleeps1) == 2
        assert sleeps1 == sleeps2  # seeded jitter: reproducible schedule

    def test_exhaustion_reraises_last_error(self):
        from mmlspark_tpu.downloader.repository import FaultToleranceUtils

        fc = FakeClock()

        def fn():
            raise KeyError("gone")

        with pytest.raises(KeyError):
            FaultToleranceUtils.retry_with_timeout(
                fn, times=2, backoff=0.1, sleep=fc.sleep
            )
        assert len(fc.sleeps) == 1
