"""Table substrate tests."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table, find_unused_column_name


def test_construction_and_schema(basic_table):
    assert basic_table.num_rows == 4
    assert basic_table.columns == ["numbers", "doubles", "words"]
    assert basic_table.schema["doubles"] == np.float64


def test_length_mismatch():
    with pytest.raises(ValueError):
        Table({"a": [1, 2], "b": [1, 2, 3]})


def test_vector_column():
    t = Table({"features": [[1.0, 2.0], [3.0, 4.0]]})
    assert t["features"].shape == (2, 2)


def test_ragged_column():
    t = Table({"tokens": [["a", "b"], ["c"]]})
    assert t["tokens"].dtype == object
    assert list(t["tokens"][1]) == ["c"]


def test_select_drop_rename(basic_table):
    assert basic_table.select("numbers").columns == ["numbers"]
    assert "words" not in basic_table.drop("words")
    r = basic_table.rename("words", "instruments")
    assert "instruments" in r and "words" not in r
    with pytest.raises(KeyError):
        basic_table.select("nope")


def test_filter_take_sort(basic_table):
    f = basic_table.filter(basic_table["numbers"] >= 2)
    assert f.num_rows == 2
    t = basic_table.take([3, 0])
    assert list(t["numbers"]) == [3, 0]
    s = basic_table.sort_by("doubles", ascending=False)
    assert list(s["doubles"]) == [3.5, 2.5, 1.5, 0.0]


def test_partitions():
    t = Table({"x": np.arange(10)}).repartition(3)
    bounds = t.partition_bounds()
    assert len(bounds) == 3
    assert sum(hi - lo for lo, hi in bounds) == 10
    parts = list(t.partitions())
    assert sum(p.num_rows for p in parts) == 10


def test_concat_and_split():
    t = Table({"x": np.arange(20.0), "s": np.array([f"r{i}" for i in range(20)], dtype=object)})
    a, b = t.random_split([0.5, 0.5], seed=1)
    assert a.num_rows + b.num_rows == 20
    back = Table.concat([a, b])
    assert back.num_rows == 20
    assert set(back["s"]) == set(t["s"])


def test_pandas_roundtrip(basic_table):
    df = basic_table.to_pandas()
    t2 = Table.from_pandas(df)
    assert t2.columns == basic_table.columns
    np.testing.assert_allclose(t2["doubles"], basic_table["doubles"])


def test_find_unused_column_name(basic_table):
    assert find_unused_column_name("words", basic_table) == "words_1"
    assert find_unused_column_name("fresh", basic_table) == "fresh"


def test_metadata_propagation(basic_table):
    t = basic_table.with_metadata("words", {"categorical": True})
    assert t.metadata("words") == {"categorical": True}
    assert t.select("words").metadata("words") == {"categorical": True}
    assert t.rename("words", "w").metadata("w") == {"categorical": True}
