"""LightGBM model-text interop (reference ``LightGBMBooster.scala:277-310``,
save/load API ``LightGBMClassifier.scala:172-194``).

The round-trip against the real ``lightgbm`` package runs when it is
installed (skipped otherwise); the hand-written model strings below pin the
format semantics — node encoding, leaf references, decision_type missing
bits, init-score folding — independently of it.
"""

import numpy as np
import pytest

from mmlspark_tpu.lightgbm.binning import bin_dataset
from mmlspark_tpu.lightgbm.booster import Booster
from mmlspark_tpu.lightgbm.model_text import from_lightgbm_text, to_lightgbm_text
from mmlspark_tpu.lightgbm.train import TrainOptions, train


def _fit(objective="binary", num_class=1, n=600, f=6, iters=4, leaves=7, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    if objective == "multiclass":
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float64) + (X[:, 2] > 0.5)
    elif objective == "binary":
        y = ((X[:, 0] + X[:, 1] * X[:, 2]) > 0).astype(np.float64)
    else:
        y = X[:, 0] * 2.0 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=n)
    bins, mapper = bin_dataset(X, max_bin=31)
    opts = TrainOptions(
        objective=objective, num_iterations=iters, num_leaves=leaves,
        max_bin=31, num_class=num_class,
    )
    return train(bins, y, opts, mapper=mapper).booster, X


class TestExportImportRoundTrip:
    @pytest.mark.parametrize("objective,num_class", [
        ("binary", 1), ("regression", 1), ("multiclass", 3),
    ])
    def test_margins_survive(self, objective, num_class):
        b, X = _fit(objective, num_class)
        s = to_lightgbm_text(b)
        b2 = from_lightgbm_text(s)
        np.testing.assert_allclose(
            b2.raw_margin(X[:100]), b.raw_margin(X[:100]), rtol=1e-5, atol=1e-6
        )

    def test_init_score_folded_into_first_iteration(self):
        b, X = _fit("binary")
        assert np.any(np.asarray(b.init_score) != 0)
        b2 = from_lightgbm_text(to_lightgbm_text(b))
        assert np.all(np.asarray(b2.init_score) == 0)
        np.testing.assert_allclose(
            b2.raw_margin(X[:50]), b.raw_margin(X[:50]), rtol=1e-5, atol=1e-6
        )

    def test_nan_routing_survives(self):
        b, X = _fit("binary")
        Xn = X[:200].copy()
        Xn[::3, 0] = np.nan
        Xn[::5, 2] = np.nan
        b2 = from_lightgbm_text(to_lightgbm_text(b))
        np.testing.assert_allclose(
            b2.raw_margin(Xn), b.raw_margin(Xn), rtol=1e-5, atol=1e-6
        )

    def test_shap_survives(self):
        b, X = _fit("binary")
        b2 = from_lightgbm_text(to_lightgbm_text(b))
        np.testing.assert_allclose(
            b2.features_shap(X[:20]), b.features_shap(X[:20]), rtol=1e-4, atol=1e-5
        )

    def test_booster_from_string_autodetects(self):
        b, X = _fit("regression")
        for s in (b.model_to_string(), b.to_json_string()):
            b2 = Booster.from_string(s)
            np.testing.assert_allclose(
                b2.raw_margin(X[:20]), b.raw_margin(X[:20]), rtol=1e-5, atol=1e-6
            )


class TestFormatStructure:
    def test_header_and_tree_sizes_are_byte_accurate(self):
        b, _ = _fit("binary", iters=3)
        s = to_lightgbm_text(b)
        assert s.startswith("tree\nversion=v3\n")
        header, _, rest = s.partition("\n\n")
        fields = dict(
            line.partition("=")[::2] for line in header.splitlines() if "=" in line
        )
        assert fields["num_class"] == "1"
        assert fields["objective"].startswith("binary")
        sizes = [int(x) for x in fields["tree_sizes"].split()]
        assert len(sizes) == b.num_trees
        # each recorded size must cover exactly one "Tree=i\n...\n\n\n" block
        pos = 0
        for i, size in enumerate(sizes):
            block = rest[pos : pos + size]
            assert block.startswith(f"Tree={i}\n")
            assert block.endswith("\n\n\n")
            pos += size
        assert rest[pos:].startswith("end of trees")

    def test_leaf_references_are_ones_complement(self):
        b, _ = _fit("binary", iters=1, leaves=3)
        s = to_lightgbm_text(b)
        block = s.split("Tree=0\n", 1)[1]
        get = lambda k: block.split(f"{k}=", 1)[1].splitlines()[0].split()
        left = [int(v) for v in get("left_child")]
        right = [int(v) for v in get("right_child")]
        leaves = [v for v in left + right if v < 0]
        assert sorted(~np.array(leaves)) == list(range(len(get("leaf_value"))))
        assert all(int(v) == 10 for v in get("decision_type"))


class TestImportedSemantics:
    # A hand-written 1-tree model: root splits feature 0 at 0.5 (NaN left),
    # left child splits feature 1 at -1 with decision_type=8 (missing NaN,
    # default RIGHT). Leaves: L0=10, L1=20, L2=30.
    MODEL = """tree
version=v3
num_class=1
num_tree_per_iteration=1
label_index=0
max_feature_idx=1
objective=regression
feature_names=f0 f1
feature_infos=[-10:10] [-10:10]
tree_sizes=300

Tree=0
num_leaves=3
num_cat=0
split_feature=0 1
split_gain=5 3
threshold=0.5 -1
decision_type=10 8
left_child=1 -1
right_child=-3 -2
leaf_value=10 20 30
leaf_weight=4 3 3
leaf_count=4 3 3
internal_value=0 0
internal_weight=10 7
internal_count=10 7
is_linear=0
shrinkage=1


end of trees

feature_importances:
f0=1
f1=1

parameters:
end of parameters

pandas_categorical:null
"""

    def test_hand_model_routing(self):
        b = from_lightgbm_text(self.MODEL)
        X = np.array([
            [0.0, -2.0],   # left at root, then f1 <= -1 -> leaf0 = 10
            [0.0, 0.0],    # left, f1 > -1 -> leaf1 = 20
            [1.0, 0.0],    # right at root -> leaf2 = 30
            [np.nan, 0.0], # NaN at root: default LEFT -> then f1>-1 -> 20
            [0.0, np.nan], # NaN at inner node: default RIGHT -> 20
        ])
        np.testing.assert_allclose(
            b.raw_margin(X)[:, 0], [10.0, 20.0, 30.0, 20.0, 20.0]
        )

    def test_missing_none_treats_nan_as_zero(self):
        # decision_type=2: default_left, missing None -> NaN behaves like 0.0
        model = self.MODEL.replace("decision_type=10 8", "decision_type=2 2")
        b = from_lightgbm_text(model)
        X = np.array([
            [np.nan, 0.0],  # 0.0 <= 0.5 -> left, f1: 0 > -1 -> leaf1 = 20
            [0.0, np.nan],  # left; NaN~0 > -1 -> right -> leaf1 = 20
        ])
        np.testing.assert_allclose(b.raw_margin(X)[:, 0], [20.0, 20.0])

    def test_single_leaf_tree(self):
        model = self.MODEL
        block = """Tree=0
num_leaves=1
num_cat=0
leaf_value=7.5
is_linear=0
shrinkage=1
"""
        start = model.index("Tree=0")
        end = model.index("end of trees")
        model = model[:start] + block + "\n\n" + model[end:]
        b = from_lightgbm_text(model)
        np.testing.assert_allclose(
            b.raw_margin(np.zeros((3, 2)))[:, 0], [7.5, 7.5, 7.5]
        )

    @pytest.mark.parametrize("mutation,err", [
        # a categorical decision_type bit without the cat bitset arrays is
        # structurally invalid (well-formed cat models import since round 4;
        # zero_as_missing imports too — see
        # test_zero_as_missing_import_and_round_trip)
        (("decision_type=10 8", "decision_type=10 9"), "cat_boundaries"),
        # is_linear=1 without its leaf_const array is malformed
        (("is_linear=0", "is_linear=1"), "leaf_const"),
    ])
    def test_unsupported_features_raise(self, mutation, err):
        with pytest.raises(ValueError, match=err):
            from_lightgbm_text(self.MODEL.replace(*mutation))


class TestLinearTrees:
    """linear_tree=true models (per-leaf linear outputs): import, f64
    evaluation with the native NaN fallback, round-trips, SHAP contract."""

    # Same routing as TestImportedSemantics.MODEL; leaf0 = 1 + 0.5*f0,
    # leaf1 = 2 + 1*f0 - 1*f1, leaf2 = 3 (empty model).
    LINEAR_FIELDS = (
        "is_linear=1\n"
        "leaf_const=1 2 3\n"
        "num_features=1 2 0\n"
        "leaf_features=0 0 1\n"
        "leaf_coeff=0.5 1 -1"
    )

    def _model(self):
        return TestImportedSemantics.MODEL.replace("is_linear=0", self.LINEAR_FIELDS)

    def test_linear_leaf_outputs(self):
        b = from_lightgbm_text(self._model())
        assert b.has_linear
        X = np.array([
            [0.0, -2.0],    # leaf0: 1 + 0.5*0
            [4.0, -2.0],    # f0=4 routes RIGHT at root -> leaf2
            [0.25, 4.0],    # leaf1: 2 + 0.25 - 4
            [1.0, 0.0],     # leaf2: const 3, no features
        ])
        np.testing.assert_allclose(
            b.raw_margin(X)[:, 0], [1.0, 3.0, -1.75, 3.0], atol=1e-12
        )

    def test_nan_in_leaf_model_falls_back_to_plain_output(self):
        b = from_lightgbm_text(self._model())
        # NaN at root routes per default_left (LEFT), then f1 > -1 -> leaf1;
        # leaf1's model uses f0 = NaN -> plain leaf_value 20, NOT the
        # linear expression.
        X = np.array([[np.nan, 0.0], [0.25, np.nan]])
        # second row: leaf1 via routing (f1 NaN routes right at inner node
        # -> leaf1); model uses f1 = NaN -> fallback 20
        np.testing.assert_allclose(b.raw_margin(X)[:, 0], [20.0, 20.0])

    def test_model_text_round_trip(self):
        b = from_lightgbm_text(self._model())
        s = b.model_to_string()
        assert "is_linear=1" in s
        b2 = from_lightgbm_text(s)
        X = np.array([[0.0, -2.0], [0.25, 4.0], [1.0, 0.0], [np.nan, 0.0]])
        np.testing.assert_allclose(b2.raw_margin(X), b.raw_margin(X), atol=1e-12)

    def test_json_round_trip(self):
        from mmlspark_tpu.lightgbm.booster import Booster

        b = from_lightgbm_text(self._model())
        b2 = Booster.from_string(b.to_json_string())
        assert b2.has_linear
        X = np.array([[0.25, 4.0], [np.nan, 0.0]])
        np.testing.assert_allclose(b2.raw_margin(X), b.raw_margin(X), atol=1e-12)

    def test_single_leaf_linear_tree(self):
        model = TestImportedSemantics.MODEL
        block = (
            "Tree=0\nnum_leaves=1\nnum_cat=0\nleaf_value=7.5\n"
            "is_linear=1\nleaf_const=5\nnum_features=0\n"
            "leaf_features=\nleaf_coeff=\nshrinkage=1\n"
        )
        start = model.index("Tree=0")
        end = model.index("end of trees")
        model = model[:start] + block + "\n\n" + model[end:]
        b = from_lightgbm_text(model)
        # empty model: output is the CONST (5), not the plain value (7.5)
        np.testing.assert_allclose(b.raw_margin(np.zeros((2, 2)))[:, 0], [5.0, 5.0])

    def test_shap_raises_with_clear_message(self):
        b = from_lightgbm_text(self._model())
        with pytest.raises(NotImplementedError, match="linear-tree"):
            b.features_shap(np.zeros((2, 2)))

    def test_malformed_linear_block_raises(self):
        bad = self._model().replace("leaf_coeff=0.5 1 -1", "leaf_coeff=0.5 1")
        with pytest.raises(ValueError, match="leaf_features/leaf_coeff"):
            from_lightgbm_text(bad)

    def test_real_lightgbm_linear_round_trip(self):
        lgb = pytest.importorskip("lightgbm")
        rng = np.random.default_rng(5)
        X = rng.normal(size=(1500, 6))
        y = X[:, 0] * 2.0 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=1500)
        reg = lgb.LGBMRegressor(
            n_estimators=8, num_leaves=7, linear_tree=True
        ).fit(X, y)
        s = reg.booster_.model_to_string()
        b = from_lightgbm_text(s)
        theirs = reg.booster_.predict(X[:300], raw_score=True)
        ours = b.raw_margin(X[:300])[:, 0]
        np.testing.assert_allclose(ours, theirs, rtol=1e-6, atol=1e-8)
        # our re-export loads back into the native runtime
        b2 = lgb.Booster(model_str=b.model_to_string())
        np.testing.assert_allclose(
            b2.predict(X[:300], raw_score=True), theirs, rtol=1e-6, atol=1e-8
        )


class TestAgainstRealLightGBM:
    """Bidirectional interop with the actual LightGBM runtime (skipped when
    the package is absent — the driver image has no pip lightgbm)."""

    def test_their_model_scores_identically_here(self):
        lgb = pytest.importorskip("lightgbm")
        rng = np.random.default_rng(0)
        X = rng.normal(size=(2000, 8))
        y = ((X[:, 0] + X[:, 1] * X[:, 2]) > 0).astype(int)
        m = lgb.LGBMClassifier(n_estimators=10, num_leaves=15).fit(X, y)
        s = m.booster_.model_to_string()
        b = from_lightgbm_text(s)
        theirs = m.booster_.predict(X[:200], raw_score=True)
        ours = b.raw_margin(X[:200])[:, 0]
        np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)

    def test_our_model_scores_identically_there(self):
        lgb = pytest.importorskip("lightgbm")
        b, X = _fit("binary")
        their_booster = lgb.Booster(model_str=to_lightgbm_text(b))
        theirs = their_booster.predict(X[:200], raw_score=True)
        ours = b.raw_margin(X[:200])[:, 0]
        np.testing.assert_allclose(theirs, ours, rtol=1e-5, atol=1e-6)

    def test_their_categorical_zero_as_missing_model_scores_here(self):
        """Native model with BOTH categorical splits and zero_as_missing —
        the two import semantics the hand fixtures pin, exercised against
        the real engine in one model."""
        lgb = pytest.importorskip("lightgbm")
        rng = np.random.default_rng(7)
        n = 3000
        cat = rng.integers(0, 6, size=n).astype(np.float64)
        num = rng.normal(size=(n, 3))
        num[rng.random((n, 3)) < 0.3] = 0.0  # zeros => missing
        eff = np.array([1.5, -2.0, 0.5, 3.0, -1.0, 0.0])
        y = (eff[cat.astype(int)] + num[:, 0] > 0).astype(int)
        X = np.column_stack([cat, num])
        m = lgb.LGBMClassifier(
            n_estimators=12, num_leaves=15, zero_as_missing=True,
            use_missing=True,
        ).fit(X, y, categorical_feature=[0])
        b = from_lightgbm_text(m.booster_.model_to_string())
        assert b.has_categorical
        Xt = X[:400].copy()
        Xt[::7, 1] = np.nan  # NaN and 0.0 must route identically here
        theirs = m.booster_.predict(Xt, raw_score=True)
        ours = b.raw_margin(Xt)[:, 0]
        np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)

    def test_multiclass_round_trip_both_ways(self):
        lgb = pytest.importorskip("lightgbm")
        rng = np.random.default_rng(9)
        X = rng.normal(size=(2400, 5))
        y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)  # 3 classes
        m = lgb.LGBMClassifier(
            objective="multiclass", num_class=3, n_estimators=8, num_leaves=7
        ).fit(X, y)
        b = from_lightgbm_text(m.booster_.model_to_string())
        assert b.num_classes == 3
        theirs = m.booster_.predict(X[:200], raw_score=True)
        np.testing.assert_allclose(
            b.raw_margin(X[:200]), theirs, rtol=1e-5, atol=1e-6
        )
        # and OUR multiclass booster loads into their runtime
        b2, X2 = _fit("multiclass", num_class=3)
        their_booster = lgb.Booster(model_str=to_lightgbm_text(b2))
        np.testing.assert_allclose(
            their_booster.predict(X2[:200], raw_score=True),
            b2.raw_margin(X2[:200]),
            rtol=1e-5, atol=1e-6,
        )


class TestWarmStartFromText:
    def test_continue_training_from_lightgbm_text(self):
        """A booster round-tripped through the LightGBM text format can seed
        continued training via modelString (the reference's saveNativeModel ->
        setModelString flow, LightGBMClassifier.scala:172-194)."""
        from mmlspark_tpu.data.table import Table
        from mmlspark_tpu.lightgbm.classifier import LightGBMClassifier

        rng = np.random.default_rng(3)
        X = rng.normal(size=(800, 6))
        y = ((X[:, 0] + X[:, 1] * X[:, 2]) > 0).astype(np.float64)
        t = Table({"features": X, "label": y})

        m1 = LightGBMClassifier(numIterations=5, numLeaves=7).fit(t)
        text = m1.get_model_string()
        assert text.startswith("tree\n")

        m2 = LightGBMClassifier(
            numIterations=5, numLeaves=7, modelString=text
        ).fit(t)
        # the continuation starts from the text model's margins: first new
        # tree must differ from a cold fit's first tree
        cold = LightGBMClassifier(numIterations=5, numLeaves=7).fit(t)
        assert not np.allclose(
            m2.booster.leaf_values[0], cold.booster.leaf_values[0]
        )
        # and the warm model must outperform (or match) the 5-tree base
        from mmlspark_tpu.lightgbm.objectives import auc

        base = auc(y, m1.booster.raw_margin(X)[:, 0], np.ones(len(y)))
        warm = auc(y, m2.booster.raw_margin(X)[:, 0]
                   + m1.booster.raw_margin(X)[:, 0], np.ones(len(y)))
        assert warm >= base - 1e-6


def test_ranker_round_trip():
    """lambdarank boosters survive the text format (scores are raw margins,
    so the round trip is rank-exact)."""
    from mmlspark_tpu.data.table import Table
    from mmlspark_tpu.lightgbm import LightGBMRanker

    rng = np.random.default_rng(9)
    q, per = 20, 10
    n = q * per
    X = rng.normal(size=(n, 5))
    rel = np.clip(X[:, 0] * 1.5 + 1.5, 0, 4).round()
    t = Table({
        "features": X, "label": rel.astype(np.float64),
        "query": np.repeat(np.arange(q), per).astype(np.int64),
    })
    b = LightGBMRanker(numIterations=3, groupCol="query", minDataInLeaf=2).fit(t).booster
    b2 = from_lightgbm_text(to_lightgbm_text(b))
    assert b2.objective == "lambdarank"
    np.testing.assert_allclose(
        b2.raw_margin(X), b.raw_margin(X), rtol=1e-5, atol=1e-6
    )


def test_imported_f64_thresholds_route_like_lightgbm():
    """Imported thresholds stay float64 and predict snaps them DOWN to f32,
    so f32 feature values falling between an f64 threshold and its
    round-to-nearest f32 narrowing route exactly as native LightGBM's f64
    comparison would."""
    # a threshold strictly between two adjacent f32 values, closer to the
    # UPPER one (round-to-nearest would round up past it)
    lo = np.float32(1.0)
    hi = np.nextafter(lo, np.float32(2.0))
    thr64 = float(lo) + 0.75 * (float(hi) - float(lo))
    assert np.float32(thr64) == hi  # round-to-nearest narrows UP
    text = "\n".join([
        "tree", "version=v3", "num_class=1", "num_tree_per_iteration=1",
        "label_index=0", "max_feature_idx=0", "objective=regression",
        "feature_names=f0", "feature_infos=[0:2]", "tree_sizes=0", "",
        "Tree=0", "num_leaves=2", "num_cat=0", "split_feature=0",
        "split_gain=1", f"threshold={thr64!r}", "decision_type=10",
        "left_child=-1", "right_child=-2", "leaf_value=-1 1",
        "leaf_weight=1 1", "leaf_count=1 1", "internal_value=0",
        "internal_weight=2", "internal_count=2", "is_linear=0",
        "shrinkage=1", "", "", "end of trees", "",
        "pandas_categorical:null", "",
    ])
    b = from_lightgbm_text(text)
    assert b.split_threshold.dtype == np.float64
    # x = hi is ABOVE thr64, so LightGBM routes it right (leaf value 1);
    # a round-to-nearest f32 threshold (== hi) would wrongly route it left.
    X = np.array([[float(lo)], [float(hi)]], dtype=np.float64)
    out = b.raw_margin(X)[:, 0]
    assert out[0] == -1.0  # lo <= thr64 -> left
    assert out[1] == 1.0   # hi > thr64 -> right (fails if narrowing rounds up)
    # the JSON round-trip preserves the f64 dtype
    b2 = type(b).from_string(b.to_json_string())
    assert b2.split_threshold.dtype == np.float64
    # TreeSHAP must use the same snapped comparison grid as predict, or
    # additivity breaks on exactly these straddling thresholds
    np.testing.assert_allclose(b.features_shap(X).sum(axis=-1)[:, 0], out)


def test_zero_as_missing_import_and_round_trip():
    """missing_type=Zero (zero_as_missing=true) imports: a 0.0 OR NaN value
    routes per default_left at such nodes; the re-export preserves the
    decision_type bits."""
    # decision_type = bit1 (default_left) | 1 << 2 (missing Zero) = 6
    text = "\n".join([
        "tree", "version=v3", "num_class=1", "num_tree_per_iteration=1",
        "label_index=0", "max_feature_idx=0", "objective=regression",
        "feature_names=f0", "feature_infos=[-5:5]", "tree_sizes=0", "",
        "Tree=0", "num_leaves=2", "num_cat=0", "split_feature=0",
        "split_gain=1", "threshold=-1.5", "decision_type=6",
        "left_child=-1", "right_child=-2", "leaf_value=1 -1",
        "leaf_weight=1 1", "leaf_count=1 1", "internal_value=0",
        "internal_weight=2", "internal_count=2", "is_linear=0",
        "shrinkage=1", "", "", "end of trees", "",
        "pandas_categorical:null", "",
    ])
    b = from_lightgbm_text(text)
    assert b.zero_missing is not None and b.zero_missing.any()
    X = np.array([[0.0], [np.nan], [-3.0], [2.0]])
    out = b.raw_margin(X)[:, 0]
    # 0.0 and NaN are missing -> default_left (set) -> left leaf (1);
    # -3 <= -1.5 -> left; 2 > -1.5 -> right. NOTE without zero_missing,
    # 0.0 would compare 0 <= -1.5 -> RIGHT, so row 0 pins the semantics.
    np.testing.assert_allclose(out, [1.0, 1.0, 1.0, -1.0])
    # SHAP additivity under zero_missing routing
    np.testing.assert_allclose(b.features_shap(X).sum(-1)[:, 0], out,
                               rtol=1e-6, atol=1e-6)
    # round trip preserves the Zero missing-type bits
    text2 = to_lightgbm_text(b)
    assert "decision_type=6" in text2
    b2 = from_lightgbm_text(text2)
    np.testing.assert_allclose(b2.raw_margin(X)[:, 0], out)


def test_zero_as_missing_k_zero_threshold():
    """Values within LightGBM's kZeroThreshold (|x| <= 1e-35) count as zero
    at zero_as_missing nodes — exact-zero-only comparison would misroute
    denormal-small values vs the native runtime."""
    text = "\n".join([
        "tree", "version=v3", "num_class=1", "num_tree_per_iteration=1",
        "label_index=0", "max_feature_idx=0", "objective=regression",
        "feature_names=f0", "feature_infos=[-5:5]", "tree_sizes=0", "",
        "Tree=0", "num_leaves=2", "num_cat=0", "split_feature=0",
        "split_gain=1", "threshold=-1.5", "decision_type=6",
        "left_child=-1", "right_child=-2", "leaf_value=1 -1",
        "leaf_weight=1 1", "leaf_count=1 1", "internal_value=0",
        "internal_weight=2", "internal_count=2", "is_linear=0",
        "shrinkage=1", "", "", "end of trees", "",
        "pandas_categorical:null", "",
    ])
    b = from_lightgbm_text(text)
    X = np.array([[1e-36], [-1e-36], [1e-30]])
    out = b.raw_margin(X)[:, 0]
    # +-1e-36 are "zero" -> missing -> default_left -> 1; 1e-30 is a real
    # value: 1e-30 > -1.5 -> right -> -1
    np.testing.assert_allclose(out, [1.0, 1.0, -1.0])
    np.testing.assert_allclose(b.features_shap(X).sum(-1)[:, 0], out,
                               rtol=1e-6, atol=1e-6)
