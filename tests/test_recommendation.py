"""recommendation/ tests — mirrors reference ``recommendation/`` suites
(SARSpec, RankingAdapterSpec, RankingEvaluatorSpec, RankingTrainValidation
SplitSpec under ``src/test/scala/com/microsoft/ml/spark/recommendation/``)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.recommendation import (
    SAR,
    AdvancedRankingMetrics,
    RankingAdapter,
    RankingEvaluator,
    RankingTrainValidationSplit,
    RecommendationIndexer,
)


@pytest.fixture
def events():
    # 4 users × 5 items; users 0/1 share items {0,1,2}, users 2/3 share {3,4}.
    users, items = [], []
    for u, its in [(0, [0, 1, 2]), (1, [0, 1, 2]), (2, [3, 4]), (3, [3, 4, 0])]:
        for i in its:
            users.append(u)
            items.append(i)
    return Table({
        "user": np.array(users, dtype=np.int64),
        "item": np.array(items, dtype=np.int64),
        "rating": np.ones(len(users)),
    })


class TestSAR:
    def test_cooccurrence_similarity(self, events):
        model = SAR(supportThreshold=1, similarityFunction="cooccurrence").fit(events)
        sim = model.getItemSimilarity()
        # items 0 and 1 co-occur for users {0,1}: count 2
        assert sim[0, 1] == 2.0
        # item 0 occurs for users {0,1,3}: diagonal 3
        assert sim[0, 0] == 3.0
        assert sim[3, 4] == 2.0
        assert sim[1, 3] == 0.0

    def test_jaccard_similarity(self, events):
        model = SAR(supportThreshold=1).fit(events)
        sim = model.getItemSimilarity()
        # jaccard(0,1) = 2 / (3 + 2 - 2) = 2/3
        np.testing.assert_allclose(sim[0, 1], 2 / 3, rtol=1e-6)
        np.testing.assert_allclose(sim[1, 2], 1.0, rtol=1e-6)

    def test_lift_similarity(self, events):
        model = SAR(supportThreshold=1, similarityFunction="lift").fit(events)
        sim = model.getItemSimilarity()
        np.testing.assert_allclose(sim[0, 1], 2 / (3 * 2), rtol=1e-6)

    def test_support_threshold(self, events):
        model = SAR(supportThreshold=3, similarityFunction="cooccurrence").fit(events)
        sim = model.getItemSimilarity()
        assert sim[0, 1] == 0.0  # cooccur 2 < threshold 3
        assert sim[0, 0] == 3.0

    def test_time_decay(self):
        t = Table({
            "user": np.array([0, 0], dtype=np.int64),
            "item": np.array([0, 1], dtype=np.int64),
            "timestamp": np.array([0.0, 30 * 24 * 3600.0]),  # 30 days apart
        })
        model = SAR(timeDecayCoeff=30, supportThreshold=1).fit(t)
        aff = model.getUserAffinity()
        # newer event has affinity 1, older has 2^-1 = 0.5
        np.testing.assert_allclose(aff[0], [0.5, 1.0], rtol=1e-6)

    def test_recommendations(self, events):
        model = SAR(supportThreshold=1).fit(events)
        recs = model.recommend_for_all_users(3)
        assert recs.num_rows == 4
        # user 0's top recommendations come from items similar to {0,1,2}
        top = set(int(v) for v in recs["recommendations"][0])
        assert {0, 1, 2} & top
        # scores are descending
        r = recs["ratings"][0]
        assert all(r[i] >= r[i + 1] for i in range(len(r) - 1))

    def test_user_subset(self, events):
        model = SAR(supportThreshold=1).fit(events)
        sub = Table({"user": np.array([2, 2, 3], dtype=np.int64),
                     "item": np.array([0, 0, 0], dtype=np.int64)})
        recs = model.recommend_for_user_subset(sub, 2)
        assert list(recs["user"]) == [2, 3]

    def test_transform_scores(self, events):
        model = SAR(supportThreshold=1).fit(events)
        out = model.transform(events)
        assert "prediction" in out
        assert out["prediction"].shape == (events.num_rows,)

    def test_save_load(self, events, tmp_path):
        from mmlspark_tpu.recommendation import SARModel

        model = SAR(supportThreshold=1).fit(events)
        model.save(str(tmp_path / "sar"))
        loaded = SARModel.load(str(tmp_path / "sar"))
        np.testing.assert_allclose(
            model.getItemSimilarity(), loaded.getItemSimilarity())


class TestRankingMetrics:
    def test_known_values(self):
        pairs = [([1, 2, 3], [1, 3]), ([4, 5], [6])]
        m = AdvancedRankingMetrics(pairs, k=3, n_items=6)
        # AP row 1: hits at ranks 1 and 3 -> (1/1 + 2/3)/2 = 5/6; row 2: 0
        np.testing.assert_allclose(m.mean_average_precision(), (5 / 6) / 2)
        np.testing.assert_allclose(m.mean_reciprocal_rank(), 0.5)
        # precision@3: row1 2/3, row2 0
        np.testing.assert_allclose(m.precision_at_k(), (2 / 3) / 2)
        # recallAtK quirk: |∩| / |pred|
        np.testing.assert_allclose(m.recall_at_k(), (2 / 3) / 2)
        # diversity: recommended {1..5} of 6 items
        np.testing.assert_allclose(m.diversity_at_k(), 5 / 6)
        np.testing.assert_allclose(m.max_diversity(), 1.0)

    def test_ndcg_perfect(self):
        pairs = [([1, 2], [1, 2])]
        m = AdvancedRankingMetrics(pairs, k=2, n_items=2)
        np.testing.assert_allclose(m.ndcg_at(), 1.0)

    def test_evaluator(self):
        t = Table({
            "prediction": np.array([[1, 2, 3], [4, 5, 6]]),
            "label": np.array([[1, 3, 7], [9, 9, 9]]),
        })
        ev = RankingEvaluator(k=3, nItems=10, metricName="precisionAtk")
        val = ev.evaluate(t)
        np.testing.assert_allclose(val, (2 / 3) / 2)
        allm = ev.get_metrics_map(t)
        assert set(allm) == set(AdvancedRankingMetrics._DISPATCH)


class TestRankingAdapter:
    def test_fit_transform(self, events):
        adapter = RankingAdapter(recommender=SAR(supportThreshold=1), k=3)
        model = adapter.fit(events)
        out = model.transform(events)
        assert set(out.columns) == {"prediction", "label"}
        assert out.num_rows == 4  # one row per user
        ev = RankingEvaluator(k=3, nItems=5)
        assert 0.0 <= ev.evaluate(out) <= 1.0


class TestRecommendationIndexer:
    def test_roundtrip(self):
        t = Table({
            "customer": np.array(["alice", "bob", "alice"], dtype=object),
            "product": np.array(["x", "y", "y"], dtype=object),
        })
        model = RecommendationIndexer(
            userInputCol="customer", userOutputCol="user",
            itemInputCol="product", itemOutputCol="item",
        ).fit(t)
        out = model.transform(t)
        assert set(np.unique(out["user"])) == {0, 1}
        users = model.recover_user(out["user"])
        assert list(users) == ["alice", "bob", "alice"]


class TestRankingTVS:
    def test_split_and_fit(self, events):
        tvs = RankingTrainValidationSplit(
            estimator=SAR(supportThreshold=1),
            evaluator=RankingEvaluator(k=2, nItems=5),
            trainRatio=0.6,
            seed=7,
        )
        train, valid = tvs.split(events)
        assert train.num_rows + valid.num_rows == events.num_rows
        # every user keeps at least one train event
        assert set(np.unique(train["user"])) == {0, 1, 2, 3}
        model = tvs.fit(events)
        assert model.getValidationMetrics()
        out = model.transform(events)
        assert "prediction" in out

    def test_min_ratings_filter(self, events):
        tvs = RankingTrainValidationSplit(
            estimator=SAR(supportThreshold=1), minRatingsU=3, minRatingsI=1,
            userCol="user", itemCol="item",
        )
        filtered = tvs._filter_min_ratings(events)
        # users 2 has only 2 events -> dropped
        assert 2 not in set(np.unique(filtered["user"]))


def test_cold_start_ids(events):
    # Regression: unseen user/item ids must not crash scoring.
    model = SAR(supportThreshold=1).fit(events)
    t = Table({"user": np.array([0, 99], dtype=np.int64),
               "item": np.array([55, 0], dtype=np.int64)})
    out = model.transform(t)
    assert out["prediction"][0] == 0.0 and out["prediction"][1] == 0.0
    recs = model.recommend_for_user_subset(
        Table({"user": np.array([1, 42], dtype=np.int64),
               "item": np.array([0, 0], dtype=np.int64)}), 2)
    assert list(recs["user"]) == [1]
