"""Pallas histogram kernel — interpret-mode correctness on the CPU mesh
(the real-chip A/B lives in ``benchmarks/hist_ab.py`` and
``docs/perf_histogram.md``)."""

import numpy as np
import jax.numpy as jnp
import pytest

from mmlspark_tpu.ops.histogram import build_histograms
from mmlspark_tpu.ops.pallas_histogram import (
    build_histograms_pallas,
    pick_bw,
)


def _case(n, f, nodes, b, seed=0):
    rng = np.random.default_rng(seed)
    bins = jnp.asarray(rng.integers(0, b, size=(n, f)), dtype=jnp.int32)
    g = jnp.asarray(rng.normal(size=n), dtype=jnp.float32)
    h = jnp.asarray(rng.random(n), dtype=jnp.float32)
    c = jnp.asarray((rng.random(n) < 0.8), dtype=jnp.float32)
    node = jnp.asarray(rng.integers(0, nodes, size=n), dtype=jnp.int32)
    return bins, g, h, c, node


@pytest.mark.parametrize("n,f,nodes,b", [(3000, 5, 2, 33), (1024, 3, 4, 17)])
def test_pallas_matches_segment(n, f, nodes, b):
    bins, g, h, c, node = _case(n, f, nodes, b)
    ref = build_histograms(bins, g, h, c, node, nodes, b, method="segment")
    pal = build_histograms_pallas(
        bins, g, h, c, node, nodes, b, interpret=True
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(pal), rtol=1e-5, atol=1e-5)


def test_pallas_pads_ragged_rows():
    # N not a multiple of the row block: padding rows must contribute nothing.
    bins, g, h, c, node = _case(2500, 2, 2, 9)
    ref = build_histograms(bins, g, h, c, node, 2, 9, method="segment")
    pal = build_histograms_pallas(bins, g, h, c, node, 2, 9, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(pal), rtol=1e-5, atol=1e-5)


def test_pick_bw_budget():
    assert pick_bw(512) >= 128  # leafwise hot shape fits
    assert pick_bw(100_000) == 0  # absurd K refuses


def test_method_dispatch_falls_back():
    # K too large for the VMEM budget: method="pallas" silently degrades to
    # the XLA one-hot rather than erroring.
    bins, g, h, c, node = _case(512, 2, 8, 256)  # K = 2048
    assert pick_bw(8 * 256) == 0
    out = build_histograms(bins, g, h, c, node, 8, 256, method="pallas")
    ref = build_histograms(bins, g, h, c, node, 8, 256, method="segment")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_pallas_oob_value_error():
    bins, g, h, c, node = _case(512, 2, 2, 9)
    with pytest.raises(ValueError, match="VMEM budget"):
        build_histograms_pallas(bins, g, h, c, node, 2, 9, bw=0)
