"""Pallas histogram kernel — interpret-mode correctness on the CPU mesh
(the real-chip A/B lives in ``benchmarks/hist_ab.py`` and
``docs/perf_histogram.md``)."""

import numpy as np
import jax.numpy as jnp
import pytest

from mmlspark_tpu.ops.histogram import build_histograms
from mmlspark_tpu.ops.pallas_histogram import (
    build_histograms_pallas,
    pick_bw,
)


def _case(n, f, nodes, b, seed=0):
    rng = np.random.default_rng(seed)
    bins = jnp.asarray(rng.integers(0, b, size=(n, f)), dtype=jnp.int32)
    g = jnp.asarray(rng.normal(size=n), dtype=jnp.float32)
    h = jnp.asarray(rng.random(n), dtype=jnp.float32)
    c = jnp.asarray((rng.random(n) < 0.8), dtype=jnp.float32)
    node = jnp.asarray(rng.integers(0, nodes, size=n), dtype=jnp.int32)
    return bins, g, h, c, node


@pytest.mark.parametrize("n,f,nodes,b", [(3000, 5, 2, 33), (1024, 3, 4, 17)])
def test_pallas_matches_segment(n, f, nodes, b):
    bins, g, h, c, node = _case(n, f, nodes, b)
    ref = build_histograms(bins, g, h, c, node, nodes, b, method="segment")
    pal = build_histograms_pallas(
        bins, g, h, c, node, nodes, b, interpret=True
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(pal), rtol=1e-5, atol=1e-5)


def test_pallas_pads_ragged_rows():
    # N not a multiple of the row block: padding rows must contribute nothing.
    bins, g, h, c, node = _case(2500, 2, 2, 9)
    ref = build_histograms(bins, g, h, c, node, 2, 9, method="segment")
    pal = build_histograms_pallas(bins, g, h, c, node, 2, 9, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(pal), rtol=1e-5, atol=1e-5)


def test_pick_bw_budget():
    assert pick_bw(512) >= 128  # leafwise hot shape fits
    assert pick_bw(100_000) == 0  # absurd K refuses


def test_method_dispatch_falls_back():
    # K too large for the VMEM budget: method="pallas" silently degrades to
    # the XLA one-hot rather than erroring.
    bins, g, h, c, node = _case(512, 2, 8, 256)  # K = 2048
    assert pick_bw(8 * 256) == 0
    out = build_histograms(bins, g, h, c, node, 8, 256, method="pallas")
    ref = build_histograms(bins, g, h, c, node, 8, 256, method="segment")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_pallas_oob_value_error():
    bins, g, h, c, node = _case(512, 2, 2, 9)
    with pytest.raises(ValueError, match="VMEM budget"):
        build_histograms_pallas(bins, g, h, c, node, 2, 9, bw=0)


class TestBinScatter:
    """Fused bin+scatter-add kernel: reads raw binned rows once and
    scatters into narrow VMEM accumulators — vs the resident-U MXU path,
    which re-streams K_pad bytes/row. Interpret-mode parity against
    ``build_histograms_u`` (f32 to rounding, quant bit-exact)."""

    def _u_case(self, seed=0, n=700, k=4):
        from mmlspark_tpu.ops.u_histogram import build_u, make_u_spec

        rng = np.random.default_rng(seed)
        widths = [16, 3, 9, 16, 7]
        f, b = len(widths), 16
        bins = np.stack(
            [rng.integers(0, w, size=n) for w in widths], axis=1
        ).astype(np.int32)
        g = rng.normal(size=n).astype(np.float32)
        h = rng.uniform(0.1, 1, size=n).astype(np.float32)
        c = (rng.uniform(size=n) > 0.2).astype(np.float32)
        node = rng.integers(-1, k + 2, size=n).astype(np.int32)
        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        return bins, g, h, c, node, k, spec, u

    def test_f32_matches_u_builder(self):
        from mmlspark_tpu.ops.pallas_histogram import (
            build_histograms_bin_scatter,
        )
        from mmlspark_tpu.ops.u_histogram import build_histograms_u

        bins, g, h, c, node, k, spec, u = self._u_case()
        ref = np.asarray(build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec,
        ))
        out = np.asarray(build_histograms_bin_scatter(
            jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
            jnp.asarray(c), jnp.asarray(node), k, spec, interpret=True,
        ))
        np.testing.assert_array_equal(out[..., 2], ref[..., 2])
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-4)

    @pytest.mark.parametrize("dequant", [True, False])
    def test_quant_bit_exact(self, dequant):
        import jax

        from mmlspark_tpu.ops.pallas_histogram import (
            build_histograms_bin_scatter,
        )
        from mmlspark_tpu.ops.u_histogram import (
            build_histograms_u,
            stat_rows_quant,
        )

        bins, g, h, c, node, k, spec, u = self._u_case(seed=3)
        stats = stat_rows_quant(
            jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jax.random.PRNGKey(2),
        )
        ref = np.asarray(build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec, stats=stats, dequant=dequant,
        ))
        out = np.asarray(build_histograms_bin_scatter(
            jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
            jnp.asarray(c), jnp.asarray(node), k, spec, stats=stats,
            dequant=dequant, interpret=True,
        ))
        np.testing.assert_array_equal(out, ref)  # integer path: bit-exact

    def test_panel_width_guard(self):
        from mmlspark_tpu.ops.pallas_histogram import (
            build_histograms_bin_scatter,
        )

        bins, g, h, c, node, _, spec, _ = self._u_case()
        with pytest.raises(ValueError, match="lane group"):
            build_histograms_bin_scatter(
                jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                jnp.asarray(c), jnp.asarray(node), 64, spec, interpret=True,
            )

    def test_vmem_gate(self):
        from mmlspark_tpu.ops.pallas_histogram import bin_scatter_fits_vmem

        assert bin_scatter_fits_vmem(7168, 28)  # 255-bin headline shape
        assert not bin_scatter_fits_vmem(60_000, 28)  # absurd K refuses
