"""ONNX import: vendored protobuf codec + graph walker vs torch forward
with identical weights (the CNTK-evaluator replacement, SURVEY.md §7 step 5;
reference ``com/microsoft/CNTK/SerializableFunction.scala:17-143``)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.dnn import DNNModel
from mmlspark_tpu.dnn.onnx_import import from_onnx
from mmlspark_tpu.dnn.onnx_proto import (
    decode_model,
    decode_tensor,
    encode_model,
    encode_node,
    encode_tensor,
)


class TestProtoCodec:
    def test_tensor_roundtrip(self):
        for arr in (
            np.arange(12, dtype=np.float32).reshape(3, 4),
            np.array([1, -2, 3], dtype=np.int64),
            np.float32(2.5).reshape(()),
        ):
            name, back = decode_tensor(encode_tensor("w", np.atleast_1d(arr)))
            assert name == "w"
            np.testing.assert_array_equal(back, np.atleast_1d(arr))

    def test_model_roundtrip(self):
        w = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
        node = encode_node("MatMul", ["x", "w"], ["y"])
        buf = encode_model([node], {"w": w}, ["x", "w"], ["y"], opset=13)
        model = decode_model(buf)
        assert model["opset"] == 13
        g = model["graph"]
        assert g["nodes"][0]["op_type"] == "MatMul"
        assert g["nodes"][0]["input"] == ["x", "w"]
        np.testing.assert_array_equal(g["initializers"]["w"], w)
        assert g["outputs"] == ["y"]

    def test_attributes_roundtrip(self):
        node_buf = encode_node(
            "Conv", ["x", "w"], ["y"],
            attrs={"strides": [2, 2], "pads": [1, 1, 1, 1], "alpha": 0.5},
        )
        buf = encode_model([node_buf], {}, ["x"], ["y"])
        node = decode_model(buf)["graph"]["nodes"][0]
        assert node["attrs"]["strides"] == [2, 2]
        assert node["attrs"]["pads"] == [1, 1, 1, 1]
        assert abs(node["attrs"]["alpha"] - 0.5) < 1e-7


def _mlp_onnx_and_torch(seed=0):
    import torch
    import torch.nn as nn

    rng = np.random.default_rng(seed)
    w1 = rng.normal(size=(16, 10)).astype(np.float32) * 0.3
    b1 = rng.normal(size=16).astype(np.float32)
    w2 = rng.normal(size=(4, 16)).astype(np.float32) * 0.3
    b2 = rng.normal(size=4).astype(np.float32)

    nodes = [
        encode_node("Gemm", ["x", "w1", "b1"], ["h"], attrs={"transB": 1}),
        encode_node("Relu", ["h"], ["hr"]),
        encode_node("Gemm", ["hr", "w2", "b2"], ["logits"], attrs={"transB": 1}),
        encode_node("Softmax", ["logits"], ["probs"], attrs={"axis": -1}),
    ]
    buf = encode_model(
        nodes, {"w1": w1, "b1": b1, "w2": w2, "b2": b2}, ["x"], ["probs"]
    )

    tm = nn.Sequential(nn.Linear(10, 16), nn.ReLU(), nn.Linear(16, 4), nn.Softmax(-1))
    with torch.no_grad():
        tm[0].weight.copy_(torch.from_numpy(w1))
        tm[0].bias.copy_(torch.from_numpy(b1))
        tm[2].weight.copy_(torch.from_numpy(w2))
        tm[2].bias.copy_(torch.from_numpy(b2))
    return buf, tm.eval()


def _cnn_onnx_and_torch(seed=1):
    import torch
    import torch.nn as nn

    rng = np.random.default_rng(seed)
    wc = rng.normal(size=(6, 3, 3, 3)).astype(np.float32) * 0.2
    bc = rng.normal(size=6).astype(np.float32)
    scale = rng.random(6).astype(np.float32) + 0.5
    bias = rng.normal(size=6).astype(np.float32)
    mean = rng.normal(size=6).astype(np.float32) * 0.1
    var = rng.random(6).astype(np.float32) + 0.5
    wl = rng.normal(size=(5, 6 * 8 * 8)).astype(np.float32) * 0.1
    bl = rng.normal(size=5).astype(np.float32)

    nodes = [
        encode_node(
            "Conv", ["x", "wc", "bc"], ["c"],
            attrs={"pads": [1, 1, 1, 1], "strides": [1, 1], "kernel_shape": [3, 3]},
        ),
        encode_node(
            "BatchNormalization",
            ["c", "scale", "bias", "mean", "var"], ["bn"],
            attrs={"epsilon": 1e-5},
        ),
        encode_node("Relu", ["bn"], ["r"]),
        encode_node(
            "MaxPool", ["r"], ["p"],
            attrs={"kernel_shape": [2, 2], "strides": [2, 2]},
        ),
        encode_node("Flatten", ["p"], ["fl"], attrs={"axis": 1}),
        encode_node("Gemm", ["fl", "wl", "bl"], ["y"], attrs={"transB": 1}),
    ]
    inits = {
        "wc": wc, "bc": bc, "scale": scale, "bias": bias,
        "mean": mean, "var": var, "wl": wl, "bl": bl,
    }
    buf = encode_model(nodes, inits, ["x"], ["y"])

    tm = nn.Sequential(
        nn.Conv2d(3, 6, 3, padding=1),
        nn.BatchNorm2d(6),
        nn.ReLU(),
        nn.MaxPool2d(2),
        nn.Flatten(),
        nn.Linear(6 * 8 * 8, 5),
    )
    with torch.no_grad():
        tm[0].weight.copy_(torch.from_numpy(wc))
        tm[0].bias.copy_(torch.from_numpy(bc))
        tm[1].weight.copy_(torch.from_numpy(scale))
        tm[1].bias.copy_(torch.from_numpy(bias))
        tm[1].running_mean.copy_(torch.from_numpy(mean))
        tm[1].running_var.copy_(torch.from_numpy(var))
        tm[5].weight.copy_(torch.from_numpy(wl))
        tm[5].bias.copy_(torch.from_numpy(bl))
    return buf, tm.eval()


class TestFromOnnx:
    def test_mlp_matches_torch(self):
        import torch

        buf, tm = _mlp_onnx_and_torch()
        fn, params = from_onnx(buf)
        x = np.random.default_rng(2).normal(size=(7, 10)).astype(np.float32)
        ours = np.asarray(fn(params, {"x": x})["probs"])
        with torch.no_grad():
            theirs = tm(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-5)

    def test_cnn_matches_torch(self):
        import torch

        buf, tm = _cnn_onnx_and_torch()
        fn, params = from_onnx(buf)
        x = np.random.default_rng(3).normal(size=(2, 3, 16, 16)).astype(np.float32)
        ours = np.asarray(fn(params, {"x": x})["y"])
        with torch.no_grad():
            theirs = tm(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(ours, theirs, rtol=1e-3, atol=1e-4)

    def test_file_roundtrip(self, tmp_path):
        buf, _ = _mlp_onnx_and_torch()
        p = tmp_path / "mlp.onnx"
        p.write_bytes(buf)
        fn, params = from_onnx(str(p))
        x = np.zeros((1, 10), np.float32)
        out = fn(params, {"x": x})["probs"]
        np.testing.assert_allclose(np.asarray(out).sum(), 1.0, rtol=1e-5)

    def test_unsupported_op_raises(self):
        buf = encode_model(
            [encode_node("FancyCustomOp", ["x"], ["y"])], {}, ["x"], ["y"]
        )
        fn, params = from_onnx(buf)
        with pytest.raises(NotImplementedError, match="FancyCustomOp"):
            fn(params, {"x": np.zeros((1, 2), np.float32)})

    def test_dnnmodel_integration(self):
        buf, _ = _mlp_onnx_and_torch()
        fn, params = from_onnx(buf)
        rng = np.random.default_rng(4)
        X = rng.normal(size=(9, 10)).astype(np.float64)
        t = Table({"feats": X})
        model = DNNModel(
            applyFn=fn,
            modelParams=params,
            feedDict={"x": "feats"},
            fetchDict={"scores": "probs"},
            batchSize=4,
        )
        out = model.transform(t)
        scores = out.column("scores")
        assert scores.shape == (9, 4)
        np.testing.assert_allclose(np.sum(scores, axis=1), 1.0, rtol=1e-4)


def test_default_valued_attrs_decode():
    """proto3 omits default-valued scalars: an attribute carrying axis=0
    arrives as name+type only and must decode to 0, not None."""
    from mmlspark_tpu.dnn.onnx_proto import _ld, _tag, _varint, decode_attribute

    # name="axis", type=INT(2), no 'i' field on the wire
    buf = _ld(1, b"axis") + _tag(20, 0) + _varint(2)
    name, val = decode_attribute(buf)
    assert name == "axis" and val == 0
    buf_f = _ld(1, b"beta") + _tag(20, 0) + _varint(1)
    assert decode_attribute(buf_f) == ("beta", 0.0)


def test_concat_axis_zero_via_wire_default():
    from mmlspark_tpu.dnn.onnx_proto import _ld, _tag, _varint

    # Hand-build Concat with the axis attribute omitted-as-default.
    attr = _ld(1, b"axis") + _tag(20, 0) + _varint(2)
    node = (
        _ld(1, b"a") + _ld(1, b"b") + _ld(2, b"y")
        + _ld(3, b"c0") + _ld(4, b"Concat") + _ld(5, attr)
    )
    buf = encode_model([node], {}, ["a", "b"], ["y"])
    fn, params = from_onnx(buf)
    a = np.ones((2, 3), np.float32)
    b = np.zeros((1, 3), np.float32)
    out = np.asarray(fn(params, {"a": a, "b": b})["y"])
    assert out.shape == (3, 3)


def test_multi_output_node_raises():
    node = encode_node(
        "MaxPool", ["x"], ["y", "indices"],
        attrs={"kernel_shape": [2, 2], "strides": [2, 2]},
    )
    buf = encode_model([node], {}, ["x"], ["y"])
    fn, params = from_onnx(buf)
    with pytest.raises(NotImplementedError, match="2 outputs"):
        fn(params, {"x": np.zeros((1, 1, 4, 4), np.float32)})


def test_unsqueeze_mixed_negative_axes():
    """ONNX Unsqueeze axes refer to the OUTPUT rank: axes=[-3, 1] on a 1-D
    input must produce shape (1, 1, S) like numpy's expand_dims on the
    normalized axes, not raise or misplace dims."""
    from mmlspark_tpu.dnn.onnx_proto import encode_model, encode_node

    for axes, in_shape, want in [
        ([-3, 1], (5,), (1, 1, 5)),
        ([0, -1], (5,), (1, 5, 1)),
        ([1], (2, 3), (2, 1, 3)),
        ([-1], (2, 3), (2, 3, 1)),
    ]:
        buf = encode_model(
            [encode_node("Unsqueeze", ["x"], ["y"], attrs={"axes": axes})], {}, ["x"], ["y"]
        )
        fn, params = from_onnx(buf)
        x = np.zeros(in_shape, np.float32)
        out = np.asarray(fn(params, {"x": x})["y"])
        assert out.shape == want, (axes, out.shape, want)
