"""Plot helpers: parity with the reference's plot/plot.py surface.

The math (confusion counts, ROC sweep) is checked against sklearn — the
very library the reference delegates to — and the rendering is smoke-run
headless on the Agg backend.
"""

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt
import numpy as np
import pytest

from mmlspark_tpu import plot
from mmlspark_tpu.data.table import Table


@pytest.fixture(autouse=True)
def _close_figures():
    yield
    plt.close("all")


def test_roc_points_matches_sklearn():
    from sklearn.metrics import roc_curve

    rng = np.random.default_rng(3)
    y = (rng.random(200) > 0.6).astype(np.int64)
    scores = np.clip(y * 0.4 + rng.random(200) * 0.6, 0, 1)
    fpr, tpr, thr = plot.roc_points(y, scores)
    fpr_sk, tpr_sk, thr_sk = roc_curve(y, scores, drop_intermediate=False)
    np.testing.assert_allclose(fpr, fpr_sk, atol=1e-12)
    np.testing.assert_allclose(tpr, tpr_sk, atol=1e-12)
    np.testing.assert_allclose(thr[1:], thr_sk[1:], atol=1e-12)


def test_roc_points_degenerate_single_class():
    fpr, tpr, _ = plot.roc_points(np.zeros(5), np.linspace(0, 1, 5))
    assert np.all(tpr == 0.0)
    assert fpr[-1] == pytest.approx(1.0)


def test_confusion_matrix_counts_match_sklearn():
    from sklearn.metrics import confusion_matrix as sk_cm

    rng = np.random.default_rng(7)
    y = rng.integers(0, 3, size=120)
    y_hat = np.where(rng.random(120) < 0.7, y, rng.integers(0, 3, size=120))
    cm = plot._confusion_counts(np.asarray(y), np.asarray(y_hat), [0, 1, 2])
    np.testing.assert_array_equal(cm, sk_cm(y, y_hat, labels=[0, 1, 2]))


def test_confusion_matrix_renders_from_table():
    t = Table(
        {
            "label": np.array([0.0, 0.0, 1.0, 1.0, 1.0]),
            "prediction": np.array([0.0, 1.0, 1.0, 1.0, 0.0]),
        }
    )
    ax = plot.confusion_matrix(t, "label", "prediction")
    # Heatmap image present, accuracy banner present, cell texts present.
    assert len(ax.images) == 1
    texts = [txt.get_text() for txt in ax.texts]
    assert any("Accuracy" in s for s in texts)
    assert {"1", "2"} <= set(texts)  # counts of the 2x2 cells
    # camelCase parity alias.
    assert plot.confusionMatrix is plot.confusion_matrix


def test_roc_renders_and_binarizes_labels():
    t = Table(
        {
            "label": np.array([0.1, 0.2, 0.9, 0.8]),  # binarized at thresh=0.5
            "score": np.array([0.3, 0.1, 0.7, 0.9]),
        }
    )
    ax = plot.roc(t, "label", "score")
    (line,) = ax.lines
    xs, ys = line.get_data()
    assert xs[0] == 0.0 and ys[0] == 0.0
    assert xs[-1] == 1.0 and ys[-1] == 1.0
    # Perfect separation here: TPR hits 1.0 while FPR is still 0.
    assert 1.0 in ys[xs == 0.0]
