"""Deep inference stack: DNNModel, torch import, ResNet zoo
(reference ``cntk/`` suites — SURVEY.md §2.4)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.dnn import DNNModel, from_torch
from mmlspark_tpu.models import init_resnet, resnet_apply


def _torch_cnn():
    import torch.nn as nn

    return nn.Sequential(
        nn.Conv2d(3, 8, 3, stride=1, padding=1),
        nn.BatchNorm2d(8),
        nn.ReLU(),
        nn.MaxPool2d(2),
        nn.Conv2d(8, 16, 3, stride=2, padding=1, groups=2),
        nn.ReLU(),
        nn.AdaptiveAvgPool2d((1, 1)),
        nn.Flatten(),
        nn.Linear(16, 5),
        nn.Softmax(dim=-1),
    )


class _ResidualNet:
    """Built lazily so torch imports stay inside tests."""

    def __new__(cls):
        import torch
        import torch.nn as nn
        import torch.nn.functional as F

        class Block(nn.Module):
            def __init__(self):
                super().__init__()
                self.conv1 = nn.Conv2d(4, 4, 3, padding=1)
                self.conv2 = nn.Conv2d(4, 4, 3, padding=1)
                self.fc = nn.Linear(4, 3)

            def forward(self, x):
                h = F.relu(self.conv1(x))
                h = self.conv2(h) + x  # residual add
                h = torch.flatten(F.adaptive_avg_pool2d(h, (1, 1)), 1)
                return self.fc(h)

        return Block()


def test_torch_import_matches_torch():
    import torch

    torch.manual_seed(0)
    net = _torch_cnn().eval()
    x = np.random.default_rng(0).standard_normal((4, 3, 16, 16)).astype(np.float32)
    with torch.no_grad():
        expected = net(torch.from_numpy(x)).numpy()
    fn, params = from_torch(net)
    got = np.asarray(fn(params, {"input": x})["output"])
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_torch_import_residual():
    import torch

    torch.manual_seed(1)
    net = _ResidualNet().eval()
    x = np.random.default_rng(1).standard_normal((2, 4, 8, 8)).astype(np.float32)
    with torch.no_grad():
        expected = net(torch.from_numpy(x)).numpy()
    fn, params = from_torch(net)
    got = np.asarray(fn(params, {"input": x})["output"])
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_dnn_model_transform_batched():
    import torch

    torch.manual_seed(0)
    net = _torch_cnn().eval()
    fn, params = from_torch(net)
    n = 23  # deliberately not a multiple of batchSize: exercises padding
    images = np.random.default_rng(2).standard_normal((n, 3, 16, 16)).astype(np.float32)
    t = Table({"id": np.arange(n), "images": [img for img in images]})
    model = DNNModel(
        applyFn=fn,
        modelParams=params,
        feedDict={"input": "images"},
        fetchDict={"scores": "output"},
        batchSize=8,
    )
    out = model.transform(t)
    assert out["scores"].shape == (n, 5)
    with torch.no_grad():
        expected = net(torch.from_numpy(images)).numpy()
    np.testing.assert_allclose(out["scores"], expected, rtol=1e-4, atol=1e-5)


def test_dnn_model_sharded(mesh8):
    fn = lambda params, inputs: {"output": inputs["x"] * params["scale"]}
    n = 40
    t = Table({"x": np.arange(n, dtype=np.float32)})
    model = DNNModel(
        applyFn=fn,
        modelParams={"scale": np.float32(3.0)},
        feedDict={"x": "x"},
        fetchDict={"y": "output"},
        batchSize=16,
        shardOverMesh=True,
    )
    out = model.transform(t)
    np.testing.assert_allclose(out["y"], np.arange(n) * 3.0)


def test_dnn_model_single_io_convenience():
    fn = lambda params, inputs: inputs["input"] + 1.0
    model = (
        DNNModel(applyFn=fn, modelParams={}, batchSize=4)
        .setInputCol("x")
        .setOutputCol("y")
    )
    t = Table({"x": np.arange(6, dtype=np.float32)})
    out = model.transform(t)
    np.testing.assert_allclose(out["y"], np.arange(6) + 1.0)
    assert model.getInputCol() == "x" and model.getOutputCol() == "y"


def test_dnn_model_missing_feed():
    model = DNNModel(applyFn=lambda p, i: i, modelParams={})
    with pytest.raises(ValueError):
        model.transform(Table({"x": np.arange(3.0)}))


def test_resnet_shapes_and_cut():
    import jax

    params = init_resnet(variant="resnet18", num_classes=7, small_inputs=True)
    x = np.random.default_rng(0).standard_normal((2, 3, 32, 32)).astype(np.float32)
    logits = jax.jit(lambda p, v: resnet_apply(p, v))(params, x)
    assert logits.shape == (2, 7)
    feats = resnet_apply(params, x, cut=1)
    assert feats.shape == (2, 512)
    fmap = resnet_apply(params, x, cut=2)
    assert fmap.shape == (2, 512, 4, 4)


def test_resnet50_bottleneck():
    params = init_resnet(variant="resnet50", num_classes=3, small_inputs=True)
    x = np.zeros((1, 3, 32, 32), np.float32)
    feats = resnet_apply(params, x, cut=1)
    assert feats.shape == (1, 2048)


def test_resnet_in_dnn_model():
    params = init_resnet(variant="resnet18", num_classes=4, small_inputs=True)
    fn = lambda p, inputs: {"output": resnet_apply(p, inputs["input"])}
    images = np.random.default_rng(3).standard_normal((5, 3, 32, 32)).astype(np.float32)
    t = Table({"images": [im for im in images]})
    model = DNNModel(
        applyFn=fn,
        modelParams=params,
        feedDict={"input": "images"},
        fetchDict={"scores": "output"},
        batchSize=4,
    )
    out = model.transform(t)
    assert out["scores"].shape == (5, 4)
    assert np.isfinite(out["scores"]).all()


def test_onnx_gate():
    from mmlspark_tpu.dnn import onnx_import

    if not onnx_import.onnx_available():
        with pytest.raises(ImportError):
            onnx_import.from_onnx("/tmp/nope.onnx")
