"""nn/ tests — mirrors reference ``nn/`` suites (BallTreeTest, KNNTest,
ConditionalKNNTest under ``src/test/scala/com/microsoft/ml/spark/nn/``)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.nn import KNN, BallTree, ConditionalBallTree, ConditionalKNN


def _index(rng, n=200, d=8):
    keys = rng.normal(size=(n, d))
    values = [f"v{i}" for i in range(n)]
    return keys, values


def _brute_topk(keys, q, k):
    scores = keys @ q
    order = np.argsort(-scores)[:k]
    return order, scores[order]


class TestBallTree:
    def test_matches_brute_force(self, rng):
        keys, values = _index(rng)
        tree = BallTree(keys, values, leaf_size=10)
        for _ in range(5):
            q = rng.normal(size=8)
            got = tree.find_maximum_inner_products(q, k=7)
            exp_idx, exp_scores = _brute_topk(keys, q, 7)
            assert [m.index for m in got] == list(exp_idx)
            np.testing.assert_allclose([m.distance for m in got], exp_scores, rtol=1e-9)

    def test_save_load(self, rng, tmp_path):
        keys, values = _index(rng, n=50)
        tree = BallTree(keys, values, leaf_size=5)
        path = str(tmp_path / "tree.pkl")
        tree.save(path)
        loaded = BallTree.load(path)
        q = rng.normal(size=8)
        assert [m.index for m in tree.find_maximum_inner_products(q, 3)] == \
               [m.index for m in loaded.find_maximum_inner_products(q, 3)]

    def test_duplicate_points(self):
        keys = np.ones((20, 4))
        tree = BallTree(keys, list(range(20)), leaf_size=3)
        got = tree.find_maximum_inner_products(np.ones(4), k=3)
        assert len(got) == 3
        assert all(abs(m.distance - 4.0) < 1e-12 for m in got)


class TestConditionalBallTree:
    def test_conditioner_filters(self, rng):
        keys, values = _index(rng, n=100)
        labels = ["even" if i % 2 == 0 else "odd" for i in range(100)]
        tree = ConditionalBallTree(keys, values, labels, leaf_size=8)
        q = rng.normal(size=8)
        got = tree.find_maximum_inner_products(q, k=5, conditioner={"even"})
        assert all(int(m.index) % 2 == 0 for m in got)
        # equals brute force over the even subset
        even = np.arange(0, 100, 2)
        scores = keys[even] @ q
        exp = even[np.argsort(-scores)[:5]]
        assert [m.index for m in got] == list(exp)


@pytest.mark.parametrize("method", ["brute", "balltree"])
class TestKNN:
    def test_fit_transform(self, rng, method):
        keys, values = _index(rng)
        index = Table({"features": keys, "values": np.array(values, dtype=object)})
        queries = Table({"features": rng.normal(size=(11, 8))})
        model = KNN(k=4, method=method, outputCol="matches").fit(index)
        out = model.transform(queries)
        matches = out["matches"]
        assert len(matches) == 11
        for r in range(11):
            exp_idx, exp_scores = _brute_topk(keys, queries["features"][r], 4)
            assert [m["value"] for m in matches[r]] == [values[i] for i in exp_idx]
            np.testing.assert_allclose(
                [m["distance"] for m in matches[r]], exp_scores, rtol=1e-4)


class TestConditionalKNN:
    def test_per_row_conditioners(self, rng):
        keys, values = _index(rng, n=60)
        labels = [["a", "b", "c"][i % 3] for i in range(60)]
        index = Table({
            "features": keys,
            "values": np.array(values, dtype=object),
            "labels": np.array(labels, dtype=object),
        })
        conds = [{"a"}, {"b"}, {"a", "c"}, {"b", "c"}, {"a", "b", "c"}]
        queries = Table({
            "features": rng.normal(size=(5, 8)),
            "conditioner": np.array(conds, dtype=object),
        })
        model = ConditionalKNN(k=3, labelCol="labels", outputCol="m").fit(index)
        out = model.transform(queries)
        for r in range(5):
            for m in out["m"][r]:
                assert m["label"] in conds[r]
            # matches brute force over admissible rows
            mask = np.array([l in conds[r] for l in labels])
            sub = np.where(mask)[0]
            scores = keys[sub] @ queries["features"][r]
            exp = sub[np.argsort(-scores)[:3]]
            assert [values[i] for i in exp] == [m["value"] for m in out["m"][r]]

    def test_empty_conditioner(self, rng):
        keys, values = _index(rng, n=10)
        index = Table({
            "features": keys,
            "values": np.array(values, dtype=object),
            "labels": np.array(["x"] * 10, dtype=object),
        })
        queries = Table({
            "features": rng.normal(size=(2, 8)),
            "conditioner": np.array([{"nope"}, {"x"}], dtype=object),
        })
        model = ConditionalKNN(k=2, labelCol="labels", outputCol="m").fit(index)
        out = model.transform(queries)
        assert out["m"][0] == []
        assert len(out["m"][1]) == 2

    def test_model_save_load(self, rng, tmp_path):
        keys, values = _index(rng, n=30)
        index = Table({"features": keys, "values": np.array(values, dtype=object)})
        model = KNN(k=2, outputCol="m").fit(index)
        path = str(tmp_path / "knn_model")
        model.save(path)
        from mmlspark_tpu.nn import KNNModel

        loaded = KNNModel.load(path)
        queries = Table({"features": rng.normal(size=(3, 8))})
        a = model.transform(queries)["m"]
        b = loaded.transform(queries)["m"]
        for r in range(3):
            assert [m["value"] for m in a[r]] == [m["value"] for m in b[r]]


def test_knn_k_larger_than_index(rng):
    keys = rng.normal(size=(3, 4))
    index = Table({"features": keys, "values": np.array(["a", "b", "c"], dtype=object)})
    model = KNN(k=5, outputCol="m").fit(index)
    out = model.transform(Table({"features": rng.normal(size=(2, 4))}))
    assert len(out["m"][0]) == 3
