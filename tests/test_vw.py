"""VW-equivalent learner tests (reference: vw test suites + Amazon-reviews
text classification config, BASELINE.md config 4)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.ops.hashing import murmur32_bytes, murmur32_ints, murmur32_strings
from mmlspark_tpu.vw import (
    VowpalWabbitClassifier,
    VowpalWabbitClassificationModel,
    VowpalWabbitFeaturizer,
    VowpalWabbitInteractions,
    VowpalWabbitRegressor,
)


def test_murmur_reference_vectors():
    # canonical murmur3_x86_32 test vectors
    assert murmur32_bytes(b"", 0) == 0
    assert murmur32_bytes(b"", 1) == 0x514E28B7
    assert murmur32_bytes(b"abc", 0) == 0xB3DD93FA
    assert murmur32_bytes(b"Hello, world!", 1234) == 0xFAF6CDB3


def test_murmur_int_vectorized_consistency():
    vals = np.asarray([0, 1, 42, 2**31 - 1], dtype=np.uint32)
    vec = murmur32_ints(vals, seed=7)
    for i, v in enumerate(vals):
        assert vec[i] == murmur32_bytes(int(v).to_bytes(4, "little"), 7)


def test_featurizer_types():
    t = Table(
        {
            "num": np.array([1.5, 2.0, 0.0]),
            "txt": np.array(["good movie", "bad movie", "meh"], dtype=object),
            "vec": np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]]),
            "flag": np.array([True, False, True]),
        }
    )
    f = VowpalWabbitFeaturizer(
        inputCols=["num", "txt", "vec", "flag"], outputCol="features",
        stringSplit=True, numBits=15,
    )
    out = f.transform(t)
    assert out.metadata("features")["sparse_dim"] == 1 << 15
    idx0, val0 = out["features"][0]
    # num(1) + 2 tokens + vec(2) + flag(1) = 6 features (modulo collisions)
    assert len(idx0) >= 5
    assert (idx0 < (1 << 15)).all()
    # same text token hashes identically across rows
    idx_a = set(out["features"][0][0])
    idx_b = set(out["features"][1][0])
    assert idx_a & idx_b  # "movie" token + shared numeric/vector/bias features


def test_classifier_text_pipeline():
    rng = np.random.default_rng(0)
    pos_words = ["great", "excellent", "love", "wonderful", "best"]
    neg_words = ["terrible", "awful", "hate", "worst", "boring"]
    neutral = ["movie", "film", "plot", "actor", "scene", "the", "a"]
    texts, labels = [], []
    for i in range(800):
        y = i % 2
        pool = pos_words if y else neg_words
        words = list(rng.choice(pool, size=2)) + list(rng.choice(neutral, size=4))
        rng.shuffle(words)
        texts.append(" ".join(words))
        labels.append(float(y))
    t = Table({"text": np.array(texts, dtype=object), "label": np.array(labels)})
    feat = VowpalWabbitFeaturizer(inputCols=["text"], outputCol="features", stringSplit=True)
    t2 = feat.transform(t)
    clf = VowpalWabbitClassifier(numPasses=3).fit(t2)
    out = clf.transform(t2)
    acc = (out["prediction"] == np.array(labels)).mean()
    assert acc > 0.95, acc
    assert out["probability"].shape == (800, 2)


def test_regressor_dense_features():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(600, 6)).astype(np.float64)
    w_true = np.array([1.0, -2.0, 0.5, 0.0, 3.0, -1.0])
    y = X @ w_true + 0.7 + 0.05 * rng.normal(size=600)
    t = Table({"features": X, "label": y})
    m = VowpalWabbitRegressor(numPasses=10, learningRate=0.5).fit(t)
    pred = m.transform(t)["prediction"]
    r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
    assert r2 > 0.95, r2


def test_regressor_quantile_loss():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(1000, 3))
    y = X[:, 0] + rng.normal(size=1000)
    t = Table({"features": X, "label": y})
    m = VowpalWabbitRegressor(
        numPasses=8, passThroughArgs="--loss_function quantile --quantile_tau 0.9"
    ).fit(t)
    pred = m.transform(t)["prediction"]
    assert 0.75 < (y <= pred).mean() <= 1.0


def test_interactions_cross():
    t = Table(
        {
            "a": np.array(["x", "y"], dtype=object),
            "b": np.array(["u", "v"], dtype=object),
        }
    )
    fa = VowpalWabbitFeaturizer(inputCols=["a"], outputCol="fa", numBits=10)
    fb = VowpalWabbitFeaturizer(inputCols=["b"], outputCol="fb", numBits=10)
    t = fb.transform(fa.transform(t))
    inter = VowpalWabbitInteractions(inputCols=["fa", "fb"], outputCol="cross", numBits=10)
    out = inter.transform(t)
    (i0, v0), (i1, v1) = out["cross"][0], out["cross"][1]
    assert len(i0) == 1 and len(i1) == 1
    assert i0[0] != i1[0]  # different crossed pairs hash differently


def test_warm_start_initial_model():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 4))
    y = (X[:, 0] > 0).astype(float)
    t = Table({"features": X, "label": y})
    m1 = VowpalWabbitClassifier(numPasses=2).fit(t)
    m2 = VowpalWabbitClassifier(numPasses=2, initialModel=m1.getModelWeights()).fit(t)
    from mmlspark_tpu.lightgbm.objectives import binary_logloss

    ll1 = binary_logloss(y, m1._margins(t), np.ones(300))
    ll2 = binary_logloss(y, m2._margins(t), np.ones(300))
    assert ll2 <= ll1 + 1e-6


def test_save_load(tmp_path, table_equal):
    rng = np.random.default_rng(4)
    X = rng.normal(size=(100, 3))
    y = (X[:, 0] > 0).astype(float)
    t = Table({"features": X, "label": y})
    m = VowpalWabbitClassifier(numPasses=1).fit(t)
    p = str(tmp_path / "vw")
    m.save(p)
    loaded = VowpalWabbitClassificationModel.load(p)
    table_equal(m.transform(t), loaded.transform(t))


def test_performance_statistics():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(64, 2))
    t = Table({"features": X, "label": (X[:, 0] > 0).astype(float)})
    m = VowpalWabbitClassifier(numPasses=1).fit(t)
    stats = m.get_performance_statistics()
    assert "rows" in stats.columns and stats["rows"][0] == 64
    assert stats["learn_time_s"][0] > 0


class TestPassThroughArgs:
    """The passThroughArgs contract (VowpalWabbitBase.scala:140-159,420-436):
    implemented flags work, unknown flags RAISE instead of silently training
    a different model."""

    def _data(self, n=800, seed=7):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, 6))
        y = (X[:, 0] * 1.5 + X[:, 1] > 0).astype(float)
        return Table({"features": X, "label": y}), X, y

    def test_unknown_flag_raises(self):
        t, _, _ = self._data(100)
        for bad in ("--cubic abc", "--nn 5", "--boosting 10", "-q ab"):
            with pytest.raises(ValueError, match="unsupported VW flag"):
                VowpalWabbitClassifier(numPasses=1, passThroughArgs=bad).fit(t)

    def test_noop_diagnostic_flags_are_skipped_with_warning(self, caplog):
        """Benign diagnostic/IO flags (no effect on the model in this
        runtime) must not fail fits that worked when args passed straight
        through to native VW."""
        import logging

        t, _, _ = self._data(100)
        with caplog.at_level(logging.WARNING, logger="mmlspark_tpu.vw"):
            m = VowpalWabbitClassifier(
                numPasses=2,
                passThroughArgs=(
                    "--quiet --holdout_off --cache_file /tmp/x.cache "
                    "--passes 4 -P 1000"
                ),
            ).fit(t)
        assert m is not None
        skipped = [r.message for r in caplog.records if "ignoring diagnostic" in r.message]
        assert len(skipped) == 4  # --quiet --holdout_off --cache_file -P
        # The model-changing flag in the same string still applied.
        assert m.getTrainingStats()["passes"] == 4

    def test_equals_form_and_known_flags(self):
        t, X, y = self._data()
        m = VowpalWabbitClassifier(
            numPasses=1, passThroughArgs="--passes=4 --learning_rate 0.4"
        ).fit(t)
        assert m.getTrainingStats()["passes"] == 4

    def test_ftrl_trains_and_differs_from_adagrad(self):
        t, X, y = self._data()
        from mmlspark_tpu.lightgbm.objectives import auc

        m_ada = VowpalWabbitClassifier(numPasses=4).fit(t)
        m_ftrl = VowpalWabbitClassifier(
            numPasses=4, passThroughArgs="--ftrl --ftrl_alpha 0.1"
        ).fit(t)
        ones = np.ones(len(y))
        a_ada = auc(y, m_ada._margins(t), ones)
        a_ftrl = auc(y, m_ftrl._margins(t), ones)
        # different optimizer, comparable quality
        assert a_ftrl > 0.9 and a_ada > 0.9, (a_ftrl, a_ada)
        assert not np.allclose(
            m_ftrl.getModelWeights(), m_ada.getModelWeights()
        )

    def test_ftrl_l1_sparsifies(self):
        t, X, y = self._data()
        m = VowpalWabbitClassifier(
            numPasses=3, l1=0.05, passThroughArgs="--ftrl"
        ).fit(t)
        w = np.asarray(m.getModelWeights())
        dense = VowpalWabbitClassifier(numPasses=3, passThroughArgs="--ftrl").fit(t)
        wd = np.asarray(dense.getModelWeights())
        assert (w != 0).sum() <= (wd != 0).sum()

    def test_link_logistic_regressor(self):
        rng = np.random.default_rng(8)
        X = rng.normal(size=(500, 4))
        y = (X[:, 0] > 0).astype(float)
        t = Table({"features": X, "label": y})
        m = VowpalWabbitRegressor(
            numPasses=3,
            passThroughArgs="--loss_function logistic --link logistic",
        )
        # logistic loss wants -1/+1 labels; the regressor keeps raw labels,
        # so emulate VW's workflow with 0/1 -> margins then link
        model = m.fit(t)
        pred = model.transform(t).column("prediction")
        assert ((pred >= 0) & (pred <= 1)).all()
        margins = model._margins(t)
        np.testing.assert_allclose(pred, 1 / (1 + np.exp(-margins)), rtol=1e-6)

    def test_link_unknown_raises(self):
        t, _, _ = self._data(100)
        with pytest.raises(ValueError, match="--link"):
            VowpalWabbitRegressor(passThroughArgs="--link glf1").fit(t)

    def test_noconstant(self):
        t, X, y = self._data()
        m = VowpalWabbitClassifier(numPasses=2, passThroughArgs="--noconstant").fit(t)
        assert m.getConstantIndex() == -1
        # all-zero rows score exactly 0 (no bias term anywhere)
        t0 = Table({"features": np.zeros((3, 6)), "label": np.zeros(3)})
        np.testing.assert_array_equal(m._margins(t0), 0.0)

    def test_hash_seed_changes_hashed_features(self):
        rows = [[("a", 1.0), ("b", 2.0)]] * 50
        col = np.empty(50, dtype=object)
        for i in range(50):
            col[i] = rows[i]
        from mmlspark_tpu.vw.featurizer import VowpalWabbitFeaturizer

        raw = Table({"text": ["a b c"] * 60 + ["d e"] * 60,
                     "label": [1.0] * 60 + [0.0] * 60})
        feats = VowpalWabbitFeaturizer(
            inputCols=["text"], outputCol="features", numBits=12
        ).transform(raw)
        m0 = VowpalWabbitClassifier(numPasses=2).fit(feats)
        m1 = VowpalWabbitClassifier(
            numPasses=2, passThroughArgs="--hash_seed 99"
        ).fit(feats)
        # the constant feature lands on a different slot under the new seed
        assert m0.getConstantIndex() != m1.getConstantIndex()

    def test_bit_precision_flag_sets_space(self):
        # raw (un-featurized) hashed column: -b governs the space size
        col = np.empty(40, dtype=object)
        rng = np.random.default_rng(3)
        for i in range(40):
            col[i] = (rng.integers(0, 1 << 12, size=4), np.ones(4, np.float32))
        t = Table({"features": col, "label": (rng.uniform(size=40) > 0.5).astype(float)})
        m = VowpalWabbitClassifier(numPasses=1, passThroughArgs="-b 14").fit(t)
        assert len(m.getModelWeights()) == 1 << 14
