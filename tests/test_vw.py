"""VW-equivalent learner tests (reference: vw test suites + Amazon-reviews
text classification config, BASELINE.md config 4)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.ops.hashing import murmur32_bytes, murmur32_ints, murmur32_strings
from mmlspark_tpu.vw import (
    VowpalWabbitClassifier,
    VowpalWabbitClassificationModel,
    VowpalWabbitFeaturizer,
    VowpalWabbitInteractions,
    VowpalWabbitRegressor,
)


def test_murmur_reference_vectors():
    # canonical murmur3_x86_32 test vectors
    assert murmur32_bytes(b"", 0) == 0
    assert murmur32_bytes(b"", 1) == 0x514E28B7
    assert murmur32_bytes(b"abc", 0) == 0xB3DD93FA
    assert murmur32_bytes(b"Hello, world!", 1234) == 0xFAF6CDB3


def test_murmur_int_vectorized_consistency():
    vals = np.asarray([0, 1, 42, 2**31 - 1], dtype=np.uint32)
    vec = murmur32_ints(vals, seed=7)
    for i, v in enumerate(vals):
        assert vec[i] == murmur32_bytes(int(v).to_bytes(4, "little"), 7)


def test_featurizer_types():
    t = Table(
        {
            "num": np.array([1.5, 2.0, 0.0]),
            "txt": np.array(["good movie", "bad movie", "meh"], dtype=object),
            "vec": np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]]),
            "flag": np.array([True, False, True]),
        }
    )
    f = VowpalWabbitFeaturizer(
        inputCols=["num", "txt", "vec", "flag"], outputCol="features",
        stringSplit=True, numBits=15,
    )
    out = f.transform(t)
    assert out.metadata("features")["sparse_dim"] == 1 << 15
    idx0, val0 = out["features"][0]
    # num(1) + 2 tokens + vec(2) + flag(1) = 6 features (modulo collisions)
    assert len(idx0) >= 5
    assert (idx0 < (1 << 15)).all()
    # same text token hashes identically across rows
    idx_a = set(out["features"][0][0])
    idx_b = set(out["features"][1][0])
    assert idx_a & idx_b  # "movie" token + shared numeric/vector/bias features


def test_classifier_text_pipeline():
    rng = np.random.default_rng(0)
    pos_words = ["great", "excellent", "love", "wonderful", "best"]
    neg_words = ["terrible", "awful", "hate", "worst", "boring"]
    neutral = ["movie", "film", "plot", "actor", "scene", "the", "a"]
    texts, labels = [], []
    for i in range(800):
        y = i % 2
        pool = pos_words if y else neg_words
        words = list(rng.choice(pool, size=2)) + list(rng.choice(neutral, size=4))
        rng.shuffle(words)
        texts.append(" ".join(words))
        labels.append(float(y))
    t = Table({"text": np.array(texts, dtype=object), "label": np.array(labels)})
    feat = VowpalWabbitFeaturizer(inputCols=["text"], outputCol="features", stringSplit=True)
    t2 = feat.transform(t)
    clf = VowpalWabbitClassifier(numPasses=3).fit(t2)
    out = clf.transform(t2)
    acc = (out["prediction"] == np.array(labels)).mean()
    assert acc > 0.95, acc
    assert out["probability"].shape == (800, 2)


def test_regressor_dense_features():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(600, 6)).astype(np.float64)
    w_true = np.array([1.0, -2.0, 0.5, 0.0, 3.0, -1.0])
    y = X @ w_true + 0.7 + 0.05 * rng.normal(size=600)
    t = Table({"features": X, "label": y})
    m = VowpalWabbitRegressor(numPasses=10, learningRate=0.5).fit(t)
    pred = m.transform(t)["prediction"]
    r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
    assert r2 > 0.95, r2


def test_regressor_quantile_loss():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(1000, 3))
    y = X[:, 0] + rng.normal(size=1000)
    t = Table({"features": X, "label": y})
    m = VowpalWabbitRegressor(
        numPasses=8, passThroughArgs="--loss_function quantile --quantile_tau 0.9"
    ).fit(t)
    pred = m.transform(t)["prediction"]
    assert 0.75 < (y <= pred).mean() <= 1.0


def test_interactions_cross():
    t = Table(
        {
            "a": np.array(["x", "y"], dtype=object),
            "b": np.array(["u", "v"], dtype=object),
        }
    )
    fa = VowpalWabbitFeaturizer(inputCols=["a"], outputCol="fa", numBits=10)
    fb = VowpalWabbitFeaturizer(inputCols=["b"], outputCol="fb", numBits=10)
    t = fb.transform(fa.transform(t))
    inter = VowpalWabbitInteractions(inputCols=["fa", "fb"], outputCol="cross", numBits=10)
    out = inter.transform(t)
    (i0, v0), (i1, v1) = out["cross"][0], out["cross"][1]
    assert len(i0) == 1 and len(i1) == 1
    assert i0[0] != i1[0]  # different crossed pairs hash differently


def test_warm_start_initial_model():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 4))
    y = (X[:, 0] > 0).astype(float)
    t = Table({"features": X, "label": y})
    m1 = VowpalWabbitClassifier(numPasses=2).fit(t)
    m2 = VowpalWabbitClassifier(numPasses=2, initialModel=m1.getModelWeights()).fit(t)
    from mmlspark_tpu.lightgbm.objectives import binary_logloss

    ll1 = binary_logloss(y, m1._margins(t), np.ones(300))
    ll2 = binary_logloss(y, m2._margins(t), np.ones(300))
    assert ll2 <= ll1 + 1e-6


def test_save_load(tmp_path, table_equal):
    rng = np.random.default_rng(4)
    X = rng.normal(size=(100, 3))
    y = (X[:, 0] > 0).astype(float)
    t = Table({"features": X, "label": y})
    m = VowpalWabbitClassifier(numPasses=1).fit(t)
    p = str(tmp_path / "vw")
    m.save(p)
    loaded = VowpalWabbitClassificationModel.load(p)
    table_equal(m.transform(t), loaded.transform(t))


def test_performance_statistics():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(64, 2))
    t = Table({"features": X, "label": (X[:, 0] > 0).astype(float)})
    m = VowpalWabbitClassifier(numPasses=1).fit(t)
    stats = m.get_performance_statistics()
    assert "rows" in stats.columns and stats["rows"][0] == 64
    assert stats["learn_time_s"][0] > 0
