"""Resource-exhaustion resilience tests: the pressure watchdog, OOM and
ENOSPC fault injection, and graceful degradation across the fit, task,
serving, and checkpoint planes (docs/resilience.md "Resource pressure").

Every test that raises the ambient :class:`PressureLevel` restores it —
the level is process-global and a leaked WARN would tighten every
admission bound in the rest of the suite.
"""

import errno
import glob
import json
import os

import numpy as np
import pytest

from mmlspark_tpu import runtime
from mmlspark_tpu.observability.events import (
    DiskPressure,
    EventLogSink,
    HistogramDegraded,
    IncidentSkipped,
    MemoryPressure,
    TaskRetried,
    get_bus,
)
from mmlspark_tpu.observability.registry import MetricsRegistry
from mmlspark_tpu.resilience import AdmissionController
from mmlspark_tpu.runtime.faults import (
    DeviceOomError,
    FaultPlan,
    check_write,
    inject_faults,
    is_oom_error,
)
from mmlspark_tpu.runtime.health import HealthTracker
from mmlspark_tpu.runtime.journal import _atomic_write
from mmlspark_tpu.runtime.pressure import (
    PressureLevel,
    ResourceWatchdog,
    _footprint_hint,
    current_pressure_level,
    reduced_footprint,
    set_pressure_level,
)


@pytest.fixture(autouse=True)
def _reset_levels():
    yield
    set_pressure_level("memory", PressureLevel.OK)
    set_pressure_level("disk", PressureLevel.OK)


@pytest.fixture
def bus_events():
    seen = []
    bus = get_bus()
    bus.add_listener(seen.append)
    yield seen
    bus.remove_listener(seen.append)


# -- fault directives ---------------------------------------------------------


class TestExhaustionFaults:
    def test_oom_task_rejects_unknown_kind(self):
        with pytest.raises(ValueError):
            FaultPlan().oom_task(0, kind="gpu")

    def test_host_oom_fires_once_at_task_start(self):
        plan = FaultPlan().oom_task(2, "host")
        with pytest.raises(MemoryError):
            plan.apply_on_start(2, 0)
        assert ("oom_host", 2, 0) in plan.fired
        plan.apply_on_start(2, 1)  # consumed; the relaunch runs clean

    def test_device_oom_fires_at_histogram_dispatch(self):
        plan = FaultPlan().oom_task(0, "device")
        with pytest.raises(DeviceOomError) as ei:
            plan.apply_on_histogram(0, 0)
        assert "RESOURCE_EXHAUSTED" in str(ei.value)
        assert ("oom_device", 0, 0) in plan.fired
        plan.apply_on_histogram(0, 1)  # consumed

    def test_is_oom_error_classification(self):
        assert is_oom_error(MemoryError())
        assert is_oom_error(DeviceOomError("RESOURCE_EXHAUSTED: out of HBM"))
        assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED by XLA"))
        assert not is_oom_error(RuntimeError("network down"))

    def test_disk_full_fails_matching_writes(self, tmp_path):
        plan = FaultPlan().disk_full("victim", 2)
        with inject_faults(plan):
            with pytest.raises(OSError) as ei:
                _atomic_write(str(tmp_path / "victim-a"), b"x")
            assert ei.value.errno == errno.ENOSPC
            _atomic_write(str(tmp_path / "other"), b"x")  # no substring match
            with pytest.raises(OSError):
                _atomic_write(str(tmp_path / "victim-b"), b"x")
            # count exhausted: the volume has "space" again
            _atomic_write(str(tmp_path / "victim-c"), b"x")
        assert (tmp_path / "other").read_bytes() == b"x"
        assert (tmp_path / "victim-c").read_bytes() == b"x"
        assert not (tmp_path / "victim-a").exists()
        assert sum(1 for f in plan.fired if f[0] == "disk_full") == 2

    def test_check_write_is_noop_without_a_plan(self, tmp_path):
        check_write(str(tmp_path / "anything"))


# -- pressure level + footprint hint ------------------------------------------


class TestPressureLevel:
    def test_set_and_read(self):
        assert current_pressure_level("memory") == PressureLevel.OK
        prev = set_pressure_level("memory", PressureLevel.CRITICAL)
        assert prev == PressureLevel.OK
        assert current_pressure_level("memory") == PressureLevel.CRITICAL

    def test_footprint_hint_scoped(self):
        assert reduced_footprint() == 0
        with _footprint_hint(2):
            assert reduced_footprint() == 2
            with _footprint_hint(3):
                assert reduced_footprint() == 3
            assert reduced_footprint() == 2
        assert reduced_footprint() == 0


# -- the watchdog -------------------------------------------------------------


class TestResourceWatchdog:
    def _watchdog(self, state, disk):
        return ResourceWatchdog(
            checkpoint_dir="/tmp",
            eventlog_dir=None,
            registry=MetricsRegistry(),
            hbm_sampler=lambda: [("d0", state["used"], 100.0)],
            rss_sampler=lambda: None,
            disk_sampler=lambda p: disk["free_total"],
        )

    def test_memory_transitions_publish_onset_and_recovery(self, bus_events):
        state = {"used": 10.0}
        wd = self._watchdog(state, {"free_total": (90.0, 100.0)})
        assert wd.poll()["memory"] == PressureLevel.OK
        state["used"] = 90.0
        assert wd.poll()["memory"] == PressureLevel.WARN
        assert current_pressure_level("memory") == PressureLevel.WARN
        state["used"] = 99.0
        assert wd.poll()["memory"] == PressureLevel.CRITICAL
        state["used"] = 99.0
        wd.poll()  # steady state: no repeat event
        state["used"] = 10.0
        assert wd.poll()["memory"] == PressureLevel.OK
        mem = [e for e in bus_events if isinstance(e, MemoryPressure)]
        assert [e.level for e in mem] == ["warn", "critical", "ok"]
        assert mem[0].source == "hbm:d0"

    def test_disk_transitions(self, bus_events):
        state = {"used": 10.0}
        disk = {"free_total": (50.0, 100.0)}
        wd = self._watchdog(state, disk)
        assert wd.poll()["disk"] == PressureLevel.OK
        disk["free_total"] = (4.0, 100.0)  # 96% used
        assert wd.poll()["disk"] == PressureLevel.CRITICAL
        assert current_pressure_level("disk") == PressureLevel.CRITICAL
        disk["free_total"] = (60.0, 100.0)
        assert wd.poll()["disk"] == PressureLevel.OK
        levels = [e.level for e in bus_events if isinstance(e, DiskPressure)]
        assert levels == ["critical", "ok"]


# -- serving degradation ------------------------------------------------------


class TestAdmissionUnderPressure:
    def _controller(self, max_pending=8):
        return AdmissionController(
            max_pending=max_pending, registry=MetricsRegistry(),
        )

    def test_bound_tightens_and_restores(self):
        ac = self._controller(8)
        assert ac.effective_max_pending() == 8
        set_pressure_level("memory", PressureLevel.WARN)
        assert ac.effective_max_pending() == 4
        set_pressure_level("memory", PressureLevel.CRITICAL)
        assert ac.effective_max_pending() == 2
        set_pressure_level("memory", PressureLevel.OK)
        assert ac.effective_max_pending() == 8

    def test_sheds_with_memory_pressure_reason(self, bus_events):
        ac = self._controller(8)
        set_pressure_level("memory", PressureLevel.WARN)
        for _ in range(4):
            assert ac.try_acquire()
        assert not ac.try_acquire()  # 5th: over the tightened bound
        sheds = [
            e for e in bus_events
            if type(e).__name__ == "RequestShed"
        ]
        assert sheds and sheds[-1].reason == "memory_pressure"
        # recovery: the full bound is back without any release
        set_pressure_level("memory", PressureLevel.OK)
        assert ac.try_acquire()

    def test_batch_loop_bound(self):
        from mmlspark_tpu.serving.server import _BatchLoop

        loop = _BatchLoop(
            model=lambda t: t, input_col="x", output_col="y",
            max_batch_size=16, max_latency_ms=1.0,
            registry=MetricsRegistry(),
        )
        assert loop.effective_max_batch_size() == 16
        set_pressure_level("memory", PressureLevel.CRITICAL)
        assert loop.effective_max_batch_size() == 4
        set_pressure_level("memory", PressureLevel.OK)
        assert loop.effective_max_batch_size() == 16


# -- scheduler OOM classification ---------------------------------------------


class TestSchedulerOom:
    def test_host_oom_relaunches_and_classifies(self, bus_events):
        plan = FaultPlan().oom_task(1, "host")
        with inject_faults(plan):
            results = runtime.run_partitioned(
                lambda x: x * 10, [1, 2, 3],
                runtime.SchedulerPolicy(max_workers=2),
            )
        assert results == [10, 20, 30]
        assert ("oom_host", 1, 0) in plan.fired
        retried = [e for e in bus_events if isinstance(e, TaskRetried)]
        assert any(e.reason == "oom" for e in retried)

    def test_health_books_oom_heavier(self):
        h = HealthTracker(threshold=3.0, oom_weight=2.0)
        h.note_failure(0, "oom")
        assert h.score(0) == 2.0
        h.note_failure(1, "error")
        assert h.score(1) == 1.0
        h.note_failure(0, "oom")  # 4.0 >= threshold: quarantined
        assert h.is_quarantined(0)
        assert not h.is_quarantined(1)


# -- OOM-degraded fit parity --------------------------------------------------


class TestDegradedFitParity:
    def _fit(self, plan):
        from mmlspark_tpu.lightgbm.binning import apply_bins, fit_bin_mapper
        from mmlspark_tpu.lightgbm.train import TrainOptions, train

        rng = np.random.default_rng(5)
        X = rng.normal(size=(200, 4))
        y = (X[:, 0] - 0.4 * X[:, 1] > 0).astype(np.float64)
        mapper = fit_bin_mapper(X, max_bin=31)
        bins = apply_bins(X, mapper)
        opts = TrainOptions(
            objective="binary", num_iterations=4, num_leaves=5, seed=9,
            histogram_method="u",
        )
        with inject_faults(plan):
            result = train(bins, y, opts, mapper=mapper)
        return result.booster.model_to_string()

    def test_device_oom_degrades_to_identical_model(self, bus_events):
        reference = self._fit(FaultPlan())
        plan = FaultPlan().oom_task(0, "device")
        degraded = self._fit(plan)
        assert ("oom_device", 0, 0) in plan.fired
        assert degraded == reference  # byte-identical despite the retry
        booked = [e for e in bus_events if isinstance(e, HistogramDegraded)]
        assert booked and booked[0].retries == 1
        assert booked[0].chunk_rows > 0
        assert any(
            isinstance(e, MemoryPressure) and e.level == "critical"
            for e in bus_events
        )


# -- ENOSPC on the checkpoint/streaming plane ---------------------------------


class TestStreamingEnospc:
    def test_epoch_aborts_cleanly_and_resumes(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MMLSPARK_TPU_CHECKPOINT_DIR", str(tmp_path))
        from mmlspark_tpu.lightgbm import LightGBMClassifier
        from mmlspark_tpu.streaming import (
            FileStreamSource,
            ModelCommitSink,
            StreamingQuery,
        )

        incoming = tmp_path / "incoming"
        incoming.mkdir()
        rng = np.random.default_rng(3)
        for i in range(3):
            X = rng.normal(size=(50, 3))
            y = (X[:, 0] > 0).astype(np.float64)
            np.savez(incoming / f"part-{i:05d}.npz", features=X, label=y)

        def make_query():
            source = FileStreamSource(
                str(incoming), pattern="part-*.npz", max_per_trigger=1
            )
            sink = ModelCommitSink(
                lambda: LightGBMClassifier(
                    numIterations=2, numLeaves=4, seed=1
                ),
                name="enospc-test",
            )
            return StreamingQuery(source, sink, name="enospc-test"), sink

        query, sink = make_query()
        plan = FaultPlan().disk_full("offsets/000001", 1)
        with inject_faults(plan):
            with pytest.raises(OSError) as ei:
                query.process_all_available()
        assert ei.value.errno == errno.ENOSPC
        assert query.committed_epochs == [0]  # epoch 0 landed; 1 aborted
        sink.close()

        # space returns: a restarted query finishes every epoch
        query2, sink2 = make_query()
        query2.process_all_available()
        assert query2.committed_epochs == [0, 1, 2]
        sink2.close()
        # zero refits: the journal holds each epoch exactly once
        epochs = []
        for path in glob.glob(
            str(tmp_path / "streaming-models" / "**" / "journal.jsonl"),
            recursive=True,
        ):
            with open(path, "r", encoding="utf-8") as fh:
                epochs += [
                    int(json.loads(line)["task"])
                    for line in fh if line.strip()
                ]
        assert sorted(epochs) == [0, 1, 2]


# -- event-log + incident ENOSPC hardening ------------------------------------


class TestEventLogEnospc:
    def test_sink_counts_and_drops(self, tmp_path):
        log = tmp_path / "events.jsonl"
        sink = EventLogSink(str(log))
        sink(MemoryPressure(source="host", level="warn",
                            used_bytes=1.0, limit_bytes=2.0))
        plan = FaultPlan().disk_full("events.jsonl", 2)
        with inject_faults(plan):
            sink(MemoryPressure(source="host", level="critical",
                                used_bytes=1.0, limit_bytes=2.0))
            sink(MemoryPressure(source="host", level="ok",
                                used_bytes=1.0, limit_bytes=2.0))
        sink(DiskPressure(path="/x", level="warn",
                          free_bytes=1.0, total_bytes=100.0))
        sink.close()
        assert sink.write_errors == 2
        lines = [
            json.loads(x) for x in log.read_text().splitlines() if x.strip()
        ]
        assert [r["event"] for r in lines] == ["MemoryPressure", "DiskPressure"]

    def test_flight_recorder_skips_bundle(self, tmp_path, bus_events):
        from mmlspark_tpu.observability.incidents import FlightRecorder

        recorder = FlightRecorder(str(tmp_path / "incidents"), cooldown_s=0.0)
        plan = FaultPlan().disk_full("incidents", 1)
        with inject_faults(plan):
            assert recorder.record("slo_budget", detail="test") is None
        skipped = [e for e in bus_events if isinstance(e, IncidentSkipped)]
        assert skipped and skipped[0].trigger == "slo_budget"
        assert "No space left" in skipped[0].reason
        # space returns: the next record succeeds
        path = recorder.record("slo_budget", detail="test")
        assert path is not None and os.path.isdir(path)


# -- sharded ingest: bounded row-range loads ----------------------------------


class TestShardedRowRanges:
    def _dataset(self, tmp_path, n=70, f=4, rows_per_shard=30):
        from mmlspark_tpu.data.sharded import ShardedDataset

        rng = np.random.default_rng(17)
        X = rng.normal(size=(n, f))
        y = (X[:, 0] > 0).astype(np.float64)
        w = rng.uniform(0.5, 1.5, size=n)
        ds = ShardedDataset.write_shards(
            str(tmp_path / "shards"), X, y, w, rows_per_shard=rows_per_shard
        )
        return ds, X, y, w

    def test_load_rows_matches_full_decode(self, tmp_path):
        from mmlspark_tpu.data.sharded import ShardedDataset

        ds, X, y, w = self._dataset(tmp_path)
        path = ds.paths[0]
        full_X, full_y, full_w = ShardedDataset._load(path)
        part_X, part_y, part_w = ShardedDataset.load_rows(path, 5, 21)
        np.testing.assert_array_equal(part_X, full_X[5:21])
        np.testing.assert_array_equal(part_y, full_y[5:21])
        np.testing.assert_array_equal(part_w, full_w[5:21])

    def test_load_rows_npy(self, tmp_path):
        from mmlspark_tpu.data.sharded import ShardedDataset

        arr = np.arange(40, dtype=np.float64).reshape(10, 4)
        path = str(tmp_path / "only.npy")
        np.save(path, arr)
        X, y, w = ShardedDataset.load_rows(path, 2, 7)
        np.testing.assert_array_equal(X, arr[2:7])
        assert y is None and w is None

    def test_scheduled_binning_with_row_ranges(self, tmp_path):
        ds, X, y, w = self._dataset(tmp_path)
        mapper = ds.fit_mapper(max_bin=15)
        seq_bins, seq_y, seq_w = ds.bin_to_memmap(
            mapper, out_path=str(tmp_path / "seq.u8")
        )
        sched_bins, sched_y, sched_w = ds.bin_to_memmap(
            mapper,
            out_path=str(tmp_path / "sched.u8"),
            policy=runtime.SchedulerPolicy(max_workers=2),
            rows_per_task=13,
        )
        np.testing.assert_array_equal(
            np.asarray(sched_bins), np.asarray(seq_bins)
        )
        np.testing.assert_array_equal(sched_y, seq_y)
        np.testing.assert_array_equal(sched_w, seq_w)

    def test_pressure_splits_tasks(self, tmp_path):
        ds, X, y, w = self._dataset(tmp_path)
        mapper = ds.fit_mapper(max_bin=15)
        seq_bins, _, _ = ds.bin_to_memmap(
            mapper, out_path=str(tmp_path / "seq2.u8")
        )
        set_pressure_level("memory", PressureLevel.WARN)
        try:
            split_bins, _, _ = ds.bin_to_memmap(
                mapper,
                out_path=str(tmp_path / "split.u8"),
                policy=runtime.SchedulerPolicy(max_workers=2),
            )
        finally:
            set_pressure_level("memory", PressureLevel.OK)
        np.testing.assert_array_equal(
            np.asarray(split_bins), np.asarray(seq_bins)
        )
