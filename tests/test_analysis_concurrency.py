"""graftlint v2 tests: concurrency & protocol rules, lock graph, witness.

Per rule family a positive fixture (violation), a negative (clean), and
a suppressed one, plus the whole-program pieces lint_source can't reach:
cross-module ABBA cycles via lint_contexts, the runtime lock witness
(install / record / dump), the witness-vs-static cross-check, and the
self-scan asserting the repo itself is clean under the full rule set.
"""

import json
import os
import threading

import pytest

from mmlspark_tpu.analysis import all_rules
from mmlspark_tpu.analysis.base import FileContext
from mmlspark_tpu.analysis.lint import lint_contexts, lint_source
from mmlspark_tpu.analysis.lockgraph import ConcurrencyIndex, blocking_reason
from mmlspark_tpu.analysis.witness import (
    WITNESS_RULE,
    LockWitness,
    check_witness,
    install_from_env,
    load_reports,
)


def rules_of(violations):
    return [v.rule for v in violations]


def lint_at(path, src, select=None):
    """lint_source with a path the path-scoped rules recognize."""
    violations, _ = lint_contexts([FileContext(path, src)], select)
    return violations


# ---------------------------------------------------------------------------
# Family 1: lock order
# ---------------------------------------------------------------------------


ABBA_SRC = (
    "import threading\n"
    "\n"
    "class A:\n"
    "    def __init__(self, b):\n"
    "        self._a_lock = threading.Lock()\n"
    "        self.b = b\n"
    "\n"
    "    def forward(self):\n"
    "        with self._a_lock:\n"
    "            with self.b._b_lock:\n"
    "                pass\n"
    "\n"
    "class B:\n"
    "    def __init__(self, a):\n"
    "        self._b_lock = threading.Lock()\n"
    "        self.a = a\n"
    "\n"
    "    def backward(self):\n"
    "        with self._b_lock:\n"
    "            with self.a._a_lock:\n"
    "                pass\n"
)


class TestLockOrder:
    def test_abba_cycle_flagged(self):
        found = lint_source(ABBA_SRC, select=["lock-order"])
        assert rules_of(found) == ["lock-order"]
        assert "ABBA" in found[0].message

    def test_consistent_order_clean(self):
        src = ABBA_SRC.replace(
            "        with self._b_lock:\n"
            "            with self.a._a_lock:\n",
            "        with self.a._a_lock:\n"
            "            with self._b_lock:\n",
        )
        assert lint_source(src, select=["lock-order"]) == []

    def test_cross_module_cycle(self):
        # The same ABBA split across two modules: the acquisition graph
        # is whole-program, so the cycle must still be found, anchored
        # at exactly one of the two files.
        mod_a = (
            "import threading\n"
            "from mmlspark_tpu.runtime.modb import B\n"
            "\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self._a_lock = threading.Lock()\n"
            "        self.b = B()\n"
            "\n"
            "    def forward(self):\n"
            "        with self._a_lock:\n"
            "            self.b.poke()\n"
        )
        mod_b = (
            "import threading\n"
            "\n"
            "class B:\n"
            "    def __init__(self):\n"
            "        self._b_lock = threading.Lock()\n"
            "        self.a = None\n"
            "\n"
            "    def poke(self):\n"
            "        with self._b_lock:\n"
            "            pass\n"
            "\n"
            "    def backward(self):\n"
            "        with self._b_lock:\n"
            "            with self.a._a_lock:\n"
            "                pass\n"
        )
        contexts = [
            FileContext("mmlspark_tpu/runtime/moda.py", mod_a),
            FileContext("mmlspark_tpu/runtime/modb.py", mod_b),
        ]
        violations, _ = lint_contexts(contexts, select=["lock-order"])
        assert rules_of(violations) == ["lock-order"]

    def test_suppressed(self):
        # the cycle anchors at its smallest edge site — the inner
        # acquisition in forward() — so that line hosts the suppression
        src = ABBA_SRC.replace(
            "            with self.b._b_lock:\n",
            "            with self.b._b_lock:"
            "  # graftlint: disable=lock-order\n",
        )
        assert lint_source(src, select=["lock-order"]) == []


class TestLockBlocking:
    def test_callee_sleep_flagged(self):
        src = (
            "import threading, time\n"
            "\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "\n"
            "    def slow(self):\n"
            "        time.sleep(1.0)\n"
            "\n"
            "    def work(self):\n"
            "        with self._lock:\n"
            "            self.slow()\n"
        )
        found = lint_at(
            "mmlspark_tpu/runtime/w.py", src, select=["lock-blocking"]
        )
        assert rules_of(found) == ["lock-blocking"]
        assert "time.sleep" in found[0].message

    def test_direct_sleep_is_lock_disciplines(self):
        # direct blocking in the with-body belongs to lock-discipline;
        # lock-blocking only follows the call graph
        src = (
            "import threading, time\n"
            "\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "\n"
            "    def work(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1.0)\n"
        )
        path = "mmlspark_tpu/runtime/w.py"
        assert lint_at(path, src, select=["lock-blocking"]) == []
        assert rules_of(
            lint_at(path, src, select=["lock-discipline"])
        ) == ["lock-discipline"]

    def test_non_blocking_callee_clean(self):
        src = (
            "import threading\n"
            "\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0\n"
            "\n"
            "    def bump(self):\n"
            "        self._n += 1\n"
            "\n"
            "    def work(self):\n"
            "        with self._lock:\n"
            "            self.bump()\n"
        )
        assert lint_at(
            "mmlspark_tpu/runtime/w.py", src, select=["lock-blocking"]
        ) == []

    def test_outside_concurrent_parts_not_scanned(self):
        src = (
            "import threading, time\n"
            "\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "\n"
            "    def slow(self):\n"
            "        time.sleep(1.0)\n"
            "\n"
            "    def work(self):\n"
            "        with self._lock:\n"
            "            self.slow()\n"
        )
        assert lint_at(
            "mmlspark_tpu/cognitive/w.py", src, select=["lock-blocking"]
        ) == []

    def test_blocking_reason_catalog(self):
        import ast as _ast

        def call(src):
            return _ast.parse(src).body[0].value

        assert blocking_reason(call("time.sleep(1)"))
        assert blocking_reason(call("sock.recv(1024)"))
        assert blocking_reason(call("t.join()"))
        assert blocking_reason(call("q.get()"))
        assert blocking_reason(call("t.join(timeout=1.0)")) is None
        assert blocking_reason(call("', '.join(parts)")) is None
        assert blocking_reason(call("d.get('k')")) is None


# ---------------------------------------------------------------------------
# Family 2: collective consistency
# ---------------------------------------------------------------------------


class TestCollectiveDeadline:
    PATH = "mmlspark_tpu/runtime/g.py"

    def test_allreduce_group_without_timeout(self):
        src = (
            "def form(members, size):\n"
            "    return AllreduceGroup(members, size)\n"
        )
        found = lint_at(self.PATH, src, select=["collective-deadline"])
        assert rules_of(found) == ["collective-deadline"]

    def test_unbounded_wait_and_join(self):
        src = (
            "def f(ev, t):\n"
            "    ev.wait()\n"
            "    t.join()\n"
        )
        found = lint_at(self.PATH, src, select=["collective-deadline"])
        assert rules_of(found) == [
            "collective-deadline", "collective-deadline",
        ]

    def test_bounded_forms_clean(self):
        src = (
            "def f(members, size, ev, t, parts):\n"
            "    g = AllreduceGroup(members, size, timeout=5.0)\n"
            "    ev.wait(timeout=2.0)\n"
            "    t.join(1.0)\n"
            "    return ', '.join(parts)\n"
        )
        assert lint_at(self.PATH, src, select=["collective-deadline"]) == []

    def test_suppressed(self):
        src = (
            "def f(ev):\n"
            "    ev.wait()  # graftlint: disable=collective-deadline\n"
        )
        assert lint_at(self.PATH, src, select=["collective-deadline"]) == []


class TestCollectiveRankBranch:
    PATH = "mmlspark_tpu/runtime/c.py"

    def test_rank_guarded_collective(self):
        src = (
            "def f(rank, grad):\n"
            "    if rank == 0:\n"
            "        return psum(grad)\n"
            "    return grad\n"
        )
        found = lint_at(self.PATH, src, select=["collective-rank-branch"])
        assert rules_of(found) == ["collective-rank-branch"]
        assert "'rank'" in found[0].message

    def test_member_attribute_guard(self):
        src = (
            "def f(self, grad):\n"
            "    if self.member_id != 0:\n"
            "        barrier()\n"
        )
        found = lint_at(self.PATH, src, select=["collective-rank-branch"])
        assert rules_of(found) == ["collective-rank-branch"]

    def test_world_size_guard_is_uniform(self):
        src = (
            "def f(world_size, grad):\n"
            "    if world_size > 1:\n"
            "        return psum(grad)\n"
            "    return grad\n"
        )
        assert lint_at(
            self.PATH, src, select=["collective-rank-branch"]
        ) == []

    def test_nested_function_resets_guard(self):
        # the callee runs wherever it is called from: defining a helper
        # inside a rank branch is not itself a guarded collective
        src = (
            "def f(rank, grad):\n"
            "    if rank == 0:\n"
            "        def helper(g):\n"
            "            return psum(g)\n"
            "    return grad\n"
        )
        assert lint_at(
            self.PATH, src, select=["collective-rank-branch"]
        ) == []

    def test_suppressed(self):
        src = (
            "def f(rank, grad):\n"
            "    if rank == 0:\n"
            "        return psum(grad)"
            "  # graftlint: disable=collective-rank-branch\n"
        )
        assert lint_at(
            self.PATH, src, select=["collective-rank-branch"]
        ) == []


# ---------------------------------------------------------------------------
# Family 3: protocol ordering
# ---------------------------------------------------------------------------


class TestWalBeforeCommit:
    PATH = "mmlspark_tpu/streaming/q.py"

    def test_commit_without_wal(self):
        src = (
            "class Q:\n"
            "    def step(self, epoch):\n"
            "        self._write_commit(epoch)\n"
        )
        found = lint_at(self.PATH, src, select=["wal-before-commit"])
        assert rules_of(found) == ["wal-before-commit"]

    def test_commit_before_wal(self):
        src = (
            "class Q:\n"
            "    def step(self, epoch):\n"
            "        self._write_commit(epoch)\n"
            "        self._write_wal(epoch)\n"
        )
        found = lint_at(self.PATH, src, select=["wal-before-commit"])
        assert rules_of(found) == ["wal-before-commit"]

    def test_wal_then_commit_clean(self):
        src = (
            "class Q:\n"
            "    def step(self, epoch):\n"
            "        self._write_wal(epoch)\n"
            "        self._write_commit(epoch)\n"
        )
        assert lint_at(self.PATH, src, select=["wal-before-commit"]) == []

    def test_outside_streaming_not_scanned(self):
        src = (
            "class Q:\n"
            "    def step(self, epoch):\n"
            "        self._write_commit(epoch)\n"
        )
        assert lint_at(
            "mmlspark_tpu/serving/q.py", src, select=["wal-before-commit"]
        ) == []


class TestJournalBeforeStore:
    PATH = "mmlspark_tpu/streaming/s.py"

    def test_store_commit_without_journal(self):
        src = (
            "class Sink:\n"
            "    def flush(self, text):\n"
            "        self._store.commit(text)\n"
        )
        found = lint_at(self.PATH, src, select=["journal-before-store"])
        assert rules_of(found) == ["journal-before-store"]

    def test_journal_record_first_clean(self):
        src = (
            "class Sink:\n"
            "    def flush(self, epoch, text):\n"
            "        self._journal.record(epoch)\n"
            "        self._store.commit(text)\n"
        )
        assert lint_at(self.PATH, src, select=["journal-before-store"]) == []

    def test_caller_records_clean(self):
        # the journal write may live in a same-class caller of the
        # commit helper (the ModelCommitSink split)
        src = (
            "class Sink:\n"
            "    def run(self, epoch, text):\n"
            "        self._journal.record(epoch)\n"
            "        self._commit(text)\n"
            "\n"
            "    def _commit(self, text):\n"
            "        self._store.commit(text)\n"
        )
        assert lint_at(self.PATH, src, select=["journal-before-store"]) == []


class TestTmpRenameAtomicity:
    PATH = "mmlspark_tpu/streaming/ckpt.py"

    def test_bare_open_w_flagged(self):
        src = (
            "def save(path, data):\n"
            "    with open(path, 'w') as fh:\n"
            "        fh.write(data)\n"
        )
        found = lint_at(self.PATH, src, select=["tmp-rename-atomicity"])
        assert rules_of(found) == ["tmp-rename-atomicity"]

    def test_write_text_flagged(self):
        src = (
            "def save(path, data):\n"
            "    path.write_text(data)\n"
        )
        found = lint_at(self.PATH, src, select=["tmp-rename-atomicity"])
        assert rules_of(found) == ["tmp-rename-atomicity"]

    def test_renaming_writer_exempt(self):
        src = (
            "import os\n"
            "def save(path, data):\n"
            "    tmp = path + '.tmp'\n"
            "    with open(tmp, 'w') as fh:\n"
            "        fh.write(data)\n"
            "    os.replace(tmp, path)\n"
        )
        assert lint_at(self.PATH, src, select=["tmp-rename-atomicity"]) == []

    def test_atomic_named_writer_exempt(self):
        src = (
            "def _atomic_write(path, data):\n"
            "    with open(path, 'w') as fh:\n"
            "        fh.write(data)\n"
        )
        assert lint_at(self.PATH, src, select=["tmp-rename-atomicity"]) == []

    def test_append_mode_clean(self):
        src = (
            "def log(path, line):\n"
            "    with open(path, 'a') as fh:\n"
            "        fh.write(line)\n"
        )
        assert lint_at(self.PATH, src, select=["tmp-rename-atomicity"]) == []

    def test_journal_py_covered(self):
        src = (
            "def save(path, data):\n"
            "    with open(path, 'w') as fh:\n"
            "        fh.write(data)\n"
        )
        found = lint_at(
            "mmlspark_tpu/runtime/journal.py", src,
            select=["tmp-rename-atomicity"],
        )
        assert rules_of(found) == ["tmp-rename-atomicity"]

    def test_dataguard_covered(self):
        # the dead-letter store is durable state: a torn manifest would
        # break the exactly-once contract, so dataguard/ is in scope
        src = (
            "def save_manifest(path, data):\n"
            "    with open(path, 'w') as fh:\n"
            "        fh.write(data)\n"
        )
        found = lint_at(
            "mmlspark_tpu/dataguard/dlq.py", src,
            select=["tmp-rename-atomicity"],
        )
        assert rules_of(found) == ["tmp-rename-atomicity"]

    def test_real_dlq_writer_passes(self):
        # the shipped DeadLetterStore must satisfy its own lint: every
        # durable write goes through _atomic_write (tmp + rename)
        import mmlspark_tpu.dataguard.dlq as dlq_mod

        with open(dlq_mod.__file__, "r", encoding="utf-8") as fh:
            src = fh.read()
        assert lint_at(
            "mmlspark_tpu/dataguard/dlq.py", src,
            select=["tmp-rename-atomicity"],
        ) == []

    def test_real_dataguard_package_passes_lock_rules(self):
        import glob as _glob
        import os as _os

        import mmlspark_tpu.dataguard as pkg

        pkg_dir = _os.path.dirname(pkg.__file__)
        contexts = []
        for path in sorted(_glob.glob(_os.path.join(pkg_dir, "*.py"))):
            rel = _os.path.join(
                "mmlspark_tpu", "dataguard", _os.path.basename(path)
            )
            with open(path, "r", encoding="utf-8") as fh:
                contexts.append(FileContext(rel, fh.read()))
        violations, _ = lint_contexts(
            contexts,
            select=["lock-discipline", "lock-blocking", "lock-order",
                    "tmp-rename-atomicity"],
        )
        assert violations == []


class TestOnsetRecoveryPairing:
    def test_onset_without_recovery(self):
        src = (
            "def down(bus, name):\n"
            "    bus.publish(RegistryUnavailable(source=name))\n"
        )
        found = lint_source(src, select=["onset-recovery-pairing"])
        assert rules_of(found) == ["onset-recovery-pairing"]
        assert "RegistryRecovered" in found[0].message

    def test_paired_recovery_clean(self):
        src = (
            "def down(bus, name):\n"
            "    bus.publish(RegistryUnavailable(source=name))\n"
            "\n"
            "def up(bus, name):\n"
            "    bus.publish(RegistryRecovered(source=name))\n"
        )
        assert lint_source(src, select=["onset-recovery-pairing"]) == []

    def test_literal_pressure_without_ok(self):
        src = (
            "def warn(bus):\n"
            "    bus.publish(MemoryPressure(level='critical'))\n"
        )
        found = lint_source(src, select=["onset-recovery-pairing"])
        assert rules_of(found) == ["onset-recovery-pairing"]

    def test_dynamic_pressure_level_clean(self):
        src = (
            "def report(bus, level):\n"
            "    bus.publish(MemoryPressure(level=level))\n"
        )
        assert lint_source(src, select=["onset-recovery-pairing"]) == []

    def test_pressure_with_degradation_event_clean(self):
        src = (
            "def warn(bus):\n"
            "    bus.publish(MemoryPressure(level='critical'))\n"
            "    bus.publish(RequestShed(count=1))\n"
        )
        assert lint_source(src, select=["onset-recovery-pairing"]) == []


# ---------------------------------------------------------------------------
# ConcurrencyIndex internals
# ---------------------------------------------------------------------------


class TestConcurrencyIndex:
    def test_lock_defs_and_edges(self):
        ctx = FileContext("mmlspark_tpu/runtime/pair.py", ABBA_SRC)
        index = ConcurrencyIndex([ctx])
        assert len(index.lock_defs) == 2
        keys = set(index.lock_defs)
        assert any(k.endswith("A._a_lock") for k in keys)
        assert any(k.endswith("B._b_lock") for k in keys)
        assert len(index.edges) == 2  # A->B and B->A
        assert len(index.cycles()) == 1

    def test_lock_sites_match_witness_identity(self):
        ctx = FileContext("mmlspark_tpu/runtime/pair.py", ABBA_SRC)
        index = ConcurrencyIndex([ctx])
        sites = index.lock_sites()
        # LockDef sites are package-relative path:line — the same key
        # the runtime witness derives from allocation frames
        assert ("mmlspark_tpu/runtime/pair.py", 5) in sites
        assert ("mmlspark_tpu/runtime/pair.py", 15) in sites


# ---------------------------------------------------------------------------
# Runtime lock witness
# ---------------------------------------------------------------------------


FIXTURE_MOD = (
    "import threading\n"
    "a = threading.Lock()\n"
    "b = threading.Lock()\n"
    "with a:\n"
    "    with b:\n"
    "        pass\n"
)


class TestLockWitness:
    def test_install_wraps_package_allocations_only(self):
        w = LockWitness()
        w.install()
        try:
            # allocation frame inside the package marker -> wrapped
            exec(compile(FIXTURE_MOD, "mmlspark_tpu/fake/fx.py", "exec"), {})
            # allocation from this test file (outside the package) -> raw
            raw = threading.Lock()
            assert type(raw) is type(_new_raw_lock())
        finally:
            w.uninstall()
        report = w.report()
        assert report["sites"] == {
            "mmlspark_tpu/fake/fx.py:2": "lock",
            "mmlspark_tpu/fake/fx.py:3": "lock",
        }
        assert report["edges"] == [{
            "from": "mmlspark_tpu/fake/fx.py:2",
            "to": "mmlspark_tpu/fake/fx.py:3",
            "count": 1,
        }]

    def test_uninstall_restores_factories(self):
        w = LockWitness()
        w.install()
        w.uninstall()
        assert threading.Lock is _ORIG_LOCK_REF
        assert threading.RLock is _ORIG_RLOCK_REF

    def test_rlock_reentry_is_not_an_edge(self):
        w = LockWitness()
        w._record_acquire("mmlspark_tpu/x.py:1", "rlock")
        w._record_acquire("mmlspark_tpu/x.py:1", "rlock")
        assert w.report()["edges"] == []

    def test_dump_and_load(self, tmp_path):
        w = LockWitness()
        w._record_acquire("mmlspark_tpu/x.py:1", "lock")
        w._record_acquire("mmlspark_tpu/y.py:2", "lock")
        out = tmp_path / "lockwitness-1.json"
        w.dump(str(out))
        assert not list(tmp_path.glob("*.tmp.*"))  # tmp+rename, no litter
        reports = load_reports([str(tmp_path)])
        assert len(reports) == 1
        assert reports[0]["edges"][0]["from"] == "mmlspark_tpu/x.py:1"

    def test_install_from_env_requires_flag(self, monkeypatch):
        from mmlspark_tpu.analysis import witness as wmod

        monkeypatch.delenv("MMLSPARK_TPU_LOCKCHECK", raising=False)
        monkeypatch.setattr(wmod, "_ACTIVE", None)
        assert install_from_env() is None


class TestWitnessCheck:
    @staticmethod
    def _static_ab_context():
        # static graph: one edge A._a_lock -> B._b_lock
        src = ABBA_SRC.replace(
            "        with self._b_lock:\n"
            "            with self.a._a_lock:\n",
            "        with self.a._a_lock:\n"
            "            with self._b_lock:\n",
        )
        return FileContext("mmlspark_tpu/runtime/pair.py", src)

    def test_runtime_inversion_of_static_edge(self):
        ctx = self._static_ab_context()
        report = {
            "version": 1,
            "sites": {
                "mmlspark_tpu/runtime/pair.py:5": "lock",
                "mmlspark_tpu/runtime/pair.py:15": "lock",
            },
            "edges": [{
                # witnessed B -> A, inverting the static A -> B
                "from": "mmlspark_tpu/runtime/pair.py:15",
                "to": "mmlspark_tpu/runtime/pair.py:5",
                "count": 3,
            }],
        }
        found = check_witness([report], [ctx])
        assert rules_of(found) == [WITNESS_RULE]
        assert "static" in found[0].message

    def test_direct_runtime_inversion(self):
        ctx = self._static_ab_context()
        edges = [
            {"from": "mmlspark_tpu/io/h.py:10",
             "to": "mmlspark_tpu/io/h.py:20", "count": 1},
            {"from": "mmlspark_tpu/io/h.py:20",
             "to": "mmlspark_tpu/io/h.py:10", "count": 1},
        ]
        report = {"version": 1, "sites": {}, "edges": edges}
        found = check_witness([report], [ctx])
        assert rules_of(found) == [WITNESS_RULE]
        assert "runtime lock-order inversion" in found[0].message

    def test_consistent_witness_clean(self):
        ctx = self._static_ab_context()
        report = {
            "version": 1,
            "sites": {},
            "edges": [{
                # same order as the static edge: consistent
                "from": "mmlspark_tpu/runtime/pair.py:5",
                "to": "mmlspark_tpu/runtime/pair.py:15",
                "count": 7,
            }],
        }
        assert check_witness([report], [ctx]) == []


# ---------------------------------------------------------------------------
# Self-scan: the repo must be clean under the full v2 rule set
# ---------------------------------------------------------------------------


class TestSelfScan:
    def test_new_rules_registered(self):
        names = set(all_rules())
        assert {
            "lock-order", "lock-blocking", "collective-deadline",
            "collective-rank-branch", "wal-before-commit",
            "journal-before-store", "tmp-rename-atomicity",
            "onset-recovery-pairing",
        } <= names

    def test_repo_clean_under_full_rule_set(self):
        from mmlspark_tpu.analysis.lint import lint_paths

        pkg = os.path.join(os.path.dirname(__file__), "..", "mmlspark_tpu")
        violations, _, errors = lint_paths([os.path.normpath(pkg)])
        assert errors == []
        assert violations == [], "\n".join(v.render() for v in violations)


_ORIG_LOCK_REF = threading.Lock
_ORIG_RLOCK_REF = threading.RLock


def _new_raw_lock():
    return _ORIG_LOCK_REF()
