"""GBDT learner tests — modeled on the reference's verification suites
(``lightgbm/split1/VerifyLightGBMClassifier.scala``) with the golden-AUC
benchmark style of ``core/test/benchmarks/Benchmarks.scala``: breast-cancer
AUC golden 0.99247 ± 0.01 (``benchmarks_VerifyLightGBMClassifier.csv``)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import (
    LightGBMClassificationModel,
    LightGBMClassifier,
    LightGBMRanker,
    LightGBMRegressor,
)
from mmlspark_tpu.lightgbm.binning import bin_dataset
from mmlspark_tpu.lightgbm.objectives import auc as auc_metric


def _to_table(X, y, extra=None):
    cols = {"features": X.astype(np.float64), "label": y.astype(np.float64)}
    if extra:
        cols.update(extra)
    return Table(cols)


@pytest.fixture(scope="module")
def breast_cancer():
    from sklearn.datasets import load_breast_cancer

    d = load_breast_cancer()
    return d.data, d.target


def test_binning_roundtrip():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 4))
    X[::17, 2] = np.nan
    bins, mapper = bin_dataset(X, max_bin=63)
    assert bins.dtype == np.uint8
    assert bins[::17, 2].max() == 0  # NaN -> missing bin
    assert bins[:, 0].max() <= 63
    # monotonicity: higher raw value -> bin not lower
    col = X[:, 1]
    order = np.argsort(col)
    assert (np.diff(bins[order, 1].astype(int)) >= 0).all()


def test_classifier_breast_cancer_auc_golden(breast_cancer):
    X, y = breast_cancer
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(y))
    X, y = X[perm], y[perm]
    n_train = int(0.8 * len(y))
    train_t = _to_table(X[:n_train], y[:n_train])
    test_t = _to_table(X[n_train:], y[n_train:])

    clf = LightGBMClassifier(numIterations=60, numLeaves=31, learningRate=0.1)
    model = clf.fit(train_t)
    out = model.transform(test_t)
    assert set(["rawPrediction", "probability", "prediction"]) <= set(out.columns)
    probs = out["probability"]
    assert probs.shape == (len(y) - n_train, 2)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)
    score = auc_metric(y[n_train:], probs[:, 1], np.ones(len(y) - n_train))
    # reference golden: breast-cancer gbdt AUC 0.99247 (±0.01), BASELINE.md
    assert score > 0.98, f"AUC {score}"


def test_classifier_early_stopping(breast_cancer):
    X, y = breast_cancer
    n = len(y)
    rng = np.random.default_rng(1)
    valid = rng.random(n) < 0.25
    t = _to_table(X, y, {"isVal": valid})
    clf = LightGBMClassifier(
        numIterations=200,
        validationIndicatorCol="isVal",
        earlyStoppingRound=5,
    )
    model = clf.fit(t)
    booster = model.booster
    assert booster.best_iteration > 0
    assert booster.best_iteration <= booster.num_iterations <= 200


def test_multiclass(rng):
    from sklearn.datasets import make_classification

    X, y = make_classification(
        n_samples=600, n_features=10, n_informative=6, n_classes=3, random_state=7
    )
    t = _to_table(X, y)
    model = LightGBMClassifier(numIterations=30).fit(t)
    out = model.transform(t)
    assert out["probability"].shape == (600, 3)
    acc = (out["prediction"] == y).mean()
    assert acc > 0.85, acc


def test_regressor_quality():
    from sklearn.datasets import make_regression

    X, y = make_regression(n_samples=800, n_features=8, noise=5.0, random_state=3)
    t = _to_table(X, y)
    model = LightGBMRegressor(numIterations=80, objective="regression").fit(t)
    pred = model.transform(t)["prediction"]
    r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
    assert r2 > 0.8, r2


def test_regressor_quantile():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(2000, 3))
    y = X[:, 0] * 2 + rng.normal(size=2000)
    t = _to_table(X, y)
    model = LightGBMRegressor(numIterations=50, objective="quantile", alpha=0.9).fit(t)
    pred = model.transform(t)["prediction"]
    frac_below = (y <= pred).mean()
    assert 0.8 < frac_below < 0.97, frac_below


def test_quantile_and_l1_are_scale_invariant():
    """Percentile leaf renewal (native RenewTreeOutput): quantile/L1
    gradients are constant-magnitude, so WITHOUT renewal the fit moves by
    at most ~lr per iteration in raw label units and never reaches the
    target percentile on unscaled data. Renewal makes coverage independent
    of the label scale — the native engine's behavior."""
    rng = np.random.default_rng(9)
    X = rng.normal(size=(2000, 3))
    base = X[:, 0] * 2 + rng.normal(size=2000)
    for scale in (1.0, 1000.0):
        y = base * scale
        m = LightGBMRegressor(
            numIterations=50, objective="quantile", alpha=0.9
        ).fit(_to_table(X, y))
        cov = (y <= m.transform(_to_table(X, y))["prediction"]).mean()
        assert 0.8 < cov < 0.97, (scale, cov)
        ml1 = LightGBMRegressor(
            numIterations=50, objective="regression_l1"
        ).fit(_to_table(X, y))
        below = (y <= ml1.transform(_to_table(X, y))["prediction"]).mean()
        # L1 fits the conditional MEDIAN at any scale
        assert 0.4 < below < 0.6, (scale, below)


def test_weight_column(breast_cancer):
    X, y = breast_cancer
    w = np.where(y == 1, 10.0, 1.0)
    t = _to_table(X, y, {"w": w})
    m = LightGBMClassifier(numIterations=10, weightCol="w").fit(t)
    out = m.transform(t)
    # heavy positive weight should push mean probability up vs unweighted
    m0 = LightGBMClassifier(numIterations=10).fit(_to_table(X, y))
    p_w = out["probability"][:, 1].mean()
    p_0 = m0.transform(_to_table(X, y))["probability"][:, 1].mean()
    assert p_w > p_0


def test_save_load_and_native_string(tmp_path, breast_cancer, table_equal):
    X, y = breast_cancer
    t = _to_table(X[:200], y[:200])
    model = LightGBMClassifier(numIterations=5).fit(t)
    p = str(tmp_path / "m")
    model.save(p)
    loaded = LightGBMClassificationModel.load(p)
    table_equal(model.transform(t), loaded.transform(t))

    native = str(tmp_path / "model.txt")
    model.save_native_model(native)
    m2 = LightGBMClassificationModel.load_native_model(native)
    np.testing.assert_allclose(
        m2.booster.raw_margin(X[:50]), model.booster.raw_margin(X[:50]), rtol=1e-6
    )


def test_leaf_prediction_and_importances(breast_cancer):
    X, y = breast_cancer
    t = _to_table(X[:300], y[:300])
    model = LightGBMClassifier(numIterations=4, leafPredictionCol="leaves").fit(t)
    out = model.transform(t)
    leaves = out["leaves"]
    assert leaves.shape == (300, 4)
    imp = model.get_feature_importances()
    assert imp.shape == (X.shape[1],) and imp.sum() > 0


def test_ranker_improves_ndcg():
    rng = np.random.default_rng(9)
    q, per_group = 40, 12
    n = q * per_group
    X = rng.normal(size=(n, 5))
    rel = np.clip((X[:, 0] + rng.normal(scale=0.4, size=n)) * 1.5 + 1.5, 0, 4).round()
    group = np.repeat(np.arange(q), per_group)
    t = _to_table(X, rel, {"query": group.astype(np.int64)})
    model = LightGBMRanker(
        numIterations=30, groupCol="query", minDataInLeaf=5
    ).fit(t)
    out = model.transform(t)
    from mmlspark_tpu.lightgbm.ranker import ndcg_at_k

    score = ndcg_at_k(rel, out["prediction"], group, k=5)
    base = ndcg_at_k(rel, rng.normal(size=n), group, k=5)
    assert score > base + 0.15, (score, base)
    assert score > 0.75, score


def test_init_score_warm_start(breast_cancer):
    X, y = breast_cancer
    t = _to_table(X, y)
    m1 = LightGBMClassifier(numIterations=10).fit(t)
    margins = m1.booster.raw_margin(X)[:, 0]
    t2 = _to_table(X, y, {"init": margins})
    m2 = LightGBMClassifier(numIterations=10, initScoreCol="init").fit(t2)
    # continued model should beat fresh 10-iteration model on train logloss
    from mmlspark_tpu.lightgbm.objectives import binary_logloss

    # m2 is a delta model on top of the provided margins
    delta = m2.booster.raw_margin(X)[:, 0]
    ll_cont = binary_logloss(y, margins + delta, np.ones(len(y)))
    ll_base = binary_logloss(y, margins, np.ones(len(y)))
    assert ll_cont < ll_base


class TestParamSurfaceAudit:
    """Round-4 param-audit additions (docs/api_parity.md): every param the
    reference exposes either works or is documented as deliberately
    omitted."""

    def _unbalanced(self, n=3000, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, 6))
        y = ((X[:, 0] + 0.5 * rng.normal(size=n)) > 1.2).astype(np.float64)
        return Table({"features": X, "label": y}), X, y

    def test_is_unbalance_shifts_operating_point(self):
        from sklearn.metrics import recall_score

        t, X, y = self._unbalanced()
        m0 = LightGBMClassifier(numIterations=10, numLeaves=15).fit(t)
        m1 = LightGBMClassifier(numIterations=10, numLeaves=15,
                                isUnbalance=True).fit(t)
        r0 = recall_score(y, m0.transform(t).column("prediction"))
        r1 = recall_score(y, m1.transform(t).column("prediction"))
        assert r1 > r0, (r0, r1)

    def test_boost_from_average_off(self):
        t, X, y = self._unbalanced(n=600)
        m = LightGBMClassifier(numIterations=2, boostFromAverage=False).fit(t)
        np.testing.assert_allclose(m.booster.init_score, 0.0)

    def test_slot_names_and_max_bin_by_feature(self):
        t, X, y = self._unbalanced(n=800)
        names = list("abcdef")
        m = LightGBMClassifier(
            numIterations=3, slotNames=names, maxBinByFeature=[16] * 6,
            binSampleCount=500,
        ).fit(t)
        assert m.booster.feature_names == names
        internal = ~np.asarray(m.booster.is_leaf) & np.isfinite(
            np.asarray(m.booster.split_threshold)
        )  # dead slots keep the sentinel bin
        assert (np.asarray(m.booster.split_bin)[internal] <= 16).all()
        with pytest.raises(ValueError, match="slotNames"):
            LightGBMClassifier(numIterations=1, slotNames=["x"]).fit(t)

    def test_stratified_bagging(self):
        t, X, y = self._unbalanced()
        m = LightGBMClassifier(
            numIterations=6, numLeaves=7,
            posBaggingFraction=1.0, negBaggingFraction=0.3, baggingFreq=1,
        ).fit(t)
        from mmlspark_tpu.lightgbm.objectives import auc

        a = auc(y, m.booster.raw_margin(X)[:, 0], np.ones(len(y)))
        assert a > 0.85, a

    def test_provide_training_metric(self):
        t, X, y = self._unbalanced(n=800)
        m = LightGBMClassifier(
            numIterations=5, isProvideTrainingMetric=True
        ).fit(t)
        scores = m._train_evals["training"]["auc"]
        assert len(scores) == 5
        assert scores[-1] >= scores[0]

    def test_binary_only_guards(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(300, 4))
        y3 = np.digitize(X[:, 0], [-0.5, 0.5]).astype(np.float64)
        t3 = Table({"features": X, "label": y3})
        with pytest.raises(ValueError, match="isUnbalance"):
            LightGBMClassifier(numIterations=2, isUnbalance=True).fit(t3)
        from mmlspark_tpu.lightgbm import LightGBMRegressor

        tr = Table({"features": X, "label": X[:, 0] * 10})
        with pytest.raises(ValueError, match="binary"):
            LightGBMRegressor(
                numIterations=2, negBaggingFraction=0.3, baggingFreq=1
            ).fit(tr)

    def test_max_bin_by_feature_rejects_out_of_range(self):
        t, X, y = self._unbalanced(n=300)
        with pytest.raises(ValueError, match="maxBinByFeature"):
            LightGBMClassifier(numIterations=1, maxBinByFeature=[300] * 6).fit(t)

    def test_is_unbalance_rejects_noncontiguous_labels(self):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(200, 3))
        y = np.where(X[:, 0] > 0, 2.0, 0.0)  # labels {0, 2}
        with pytest.raises(ValueError, match="isUnbalance"):
            LightGBMClassifier(numIterations=2, isUnbalance=True).fit(
                Table({"features": X, "label": y})
            )


class TestRankerLabelGain:
    def _ltr(self, seed=9):
        rng = np.random.default_rng(seed)
        q, per = 30, 10
        n = q * per
        X = rng.normal(size=(n, 5))
        rel = np.clip((X[:, 0] + rng.normal(scale=0.4, size=n)) * 1.5 + 1.5,
                      0, 4).round()
        group = np.repeat(np.arange(q), per)
        return Table({"features": X, "label": rel.astype(np.float64),
                      "query": group.astype(np.int64)}), X, rel, group

    def test_custom_label_gain_trains_and_evaluates(self):
        from mmlspark_tpu.lightgbm.ranker import ndcg_at_k

        t, X, rel, group = self._ltr()
        lg = [0.0, 1.0, 3.0, 7.0, 100.0]  # heavy top-relevance emphasis
        m = LightGBMRanker(
            numIterations=15, groupCol="query", minDataInLeaf=3,
            labelGain=lg, seed=0, parallelism="serial",
        ).fit(t)
        score = m.transform(t)["prediction"]
        nd = ndcg_at_k(rel, score, group, k=5, label_gain=lg)
        base = ndcg_at_k(rel, np.random.default_rng(0).normal(size=len(rel)),
                         group, k=5, label_gain=lg)
        assert nd > base + 0.1, (nd, base)
        # the custom table trains a DIFFERENT model than the default
        m0 = LightGBMRanker(
            numIterations=15, groupCol="query", minDataInLeaf=3, seed=0,
            parallelism="serial",
        ).fit(t)
        assert not np.allclose(score, m0.transform(t)["prediction"])

    def test_short_label_gain_raises(self):
        t, *_ = self._ltr()
        with pytest.raises(ValueError, match="labelGain"):
            LightGBMRanker(
                numIterations=2, groupCol="query", labelGain=[0.0, 1.0]
            ).fit(t)

    def test_regrouped_refit_uses_fresh_group_structure(self):
        """Two ranker fits on the SAME rows with different uniform group
        sizes have byte-identical group-index arrays of different shapes —
        the program cache must not conflate them."""
        from mmlspark_tpu.lightgbm.ranker import ndcg_at_k

        rng = np.random.default_rng(3)
        n = 600
        X = rng.normal(size=(n, 4))
        rel = np.clip(X[:, 0] * 1.5 + 1.5, 0, 4).round()

        def fit(per):
            group = np.repeat(np.arange(n // per), per)
            t = Table({"features": X, "label": rel.astype(np.float64),
                       "query": group.astype(np.int64)})
            m = LightGBMRanker(numIterations=8, groupCol="query",
                               minDataInLeaf=3, seed=0,
                               parallelism="serial").fit(t)
            return ndcg_at_k(rel, m.transform(t)["prediction"], group, k=5)

        nd10 = fit(10)
        nd20 = fit(20)  # reshape of the SAME arange — must not reuse closure
        # both must be properly trained models, not one real + one garbage
        assert nd10 > 0.85 and nd20 > 0.85, (nd10, nd20)
