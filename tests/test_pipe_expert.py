"""Pipeline (pipe axis) and MoE (expert axis) parallelism — the last two
mesh axes exercised on the 8-virtual-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mmlspark_tpu.ops.expert_parallel import moe_apply
from mmlspark_tpu.ops.pipeline_parallel import pipeline_apply
from mmlspark_tpu.parallel.mesh import MeshConfig, make_mesh


def _stage_fn(params, h):
    w, b = params
    return jnp.tanh(h @ w + b)


def _stack_params(rng, stages, d):
    ws = jnp.asarray(rng.normal(size=(stages, d, d)) * 0.5, jnp.float32)
    bs = jnp.asarray(rng.normal(size=(stages, d)) * 0.1, jnp.float32)
    return (ws, bs)


def _sequential(params, x):
    ws, bs = params
    h = x
    for i in range(ws.shape[0]):
        h = _stage_fn((ws[i], bs[i]), h)
    return h


class TestPipelineParallel:
    def test_matches_sequential(self):
        mesh = make_mesh(MeshConfig(data=1, pipe=4), devices=jax.devices()[:4])
        rng = np.random.default_rng(0)
        params = _stack_params(rng, 4, 16)
        x = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
        ref = _sequential(params, x)
        out = pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)

    def test_single_microbatch_and_many(self):
        mesh = make_mesh(MeshConfig(data=1, pipe=8))
        rng = np.random.default_rng(1)
        params = _stack_params(rng, 8, 8)
        x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
        ref = _sequential(params, x)
        for m in (1, 2, 16):
            out = pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=m)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5,
                err_msg=f"microbatches={m}",
            )

    def test_pipe_axis_one_falls_back(self):
        mesh = make_mesh(MeshConfig(data=8, pipe=1))
        rng = np.random.default_rng(2)
        params = _stack_params(rng, 3, 8)
        x = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
        out = pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=2)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_sequential(params, x)), rtol=2e-4, atol=2e-5
        )

    def test_indivisible_batch_raises(self):
        mesh = make_mesh(MeshConfig(data=1, pipe=4), devices=jax.devices()[:4])
        rng = np.random.default_rng(3)
        params = _stack_params(rng, 4, 8)
        x = jnp.asarray(rng.normal(size=(10, 8)), jnp.float32)
        with pytest.raises(ValueError, match="not divisible"):
            pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=3)


def _expert_fn(params, x):
    w, b = params
    return x @ w + b


class TestExpertParallel:
    def _setup(self, e=4, b=24, d=8, seed=0):
        rng = np.random.default_rng(seed)
        ws = jnp.asarray(rng.normal(size=(e, d, d)) * 0.3, jnp.float32)
        bs = jnp.asarray(rng.normal(size=(e, d)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(b, d)), jnp.float32)
        gates = jnp.asarray(rng.normal(size=(b, e)), jnp.float32)
        return (ws, bs), x, gates

    def _reference(self, params, x, gates):
        ws, bs = params
        probs = np.asarray(jax.nn.softmax(gates, axis=1))
        assign = np.asarray(jnp.argmax(gates, axis=1))
        out = np.zeros((x.shape[0], ws.shape[2]), np.float32)
        xn = np.asarray(x)
        for i in range(x.shape[0]):
            e = assign[i]
            out[i] = (xn[i] @ np.asarray(ws[e]) + np.asarray(bs[e])) * probs[i, e]
        return out

    def test_matches_reference(self):
        mesh = make_mesh(MeshConfig(data=1, expert=4), devices=jax.devices()[:4])
        params, x, gates = self._setup()
        out = moe_apply(_expert_fn, params, x, gates, mesh)
        np.testing.assert_allclose(
            np.asarray(out), self._reference(params, x, gates), rtol=2e-4, atol=2e-5
        )

    def test_expert_axis_one_falls_back(self):
        mesh = make_mesh(MeshConfig(data=8, expert=1))
        params, x, gates = self._setup(seed=1)
        out = moe_apply(_expert_fn, params, x, gates, mesh)
        np.testing.assert_allclose(
            np.asarray(out), self._reference(params, x, gates), rtol=2e-4, atol=2e-5
        )

    def test_all_axes_engaged(self):
        """Every one of the five mesh axes now has a real consumer: this
        test documents the inventory (data: GBDT/DNN batch; model:
        feature-parallel bins + TP matmuls; seq: ring attention; pipe:
        pipeline_apply; expert: moe_apply)."""
        mesh = make_mesh(MeshConfig(data=2, expert=4))
        params, x, gates = self._setup(seed=2)
        out = moe_apply(_expert_fn, params, x, gates, mesh)
        np.testing.assert_allclose(
            np.asarray(out), self._reference(params, x, gates), rtol=2e-4, atol=2e-5
        )


def test_stage_count_mismatch_raises():
    mesh = make_mesh(MeshConfig(data=1, pipe=4), devices=jax.devices()[:4])
    rng = np.random.default_rng(5)
    params = _stack_params(rng, 8, 8)  # 8 stages over a 4-way pipe
    x = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
    with pytest.raises(ValueError, match="one stage per device"):
        pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=2)


def test_expert_count_mismatch_raises():
    mesh = make_mesh(MeshConfig(data=1, expert=4), devices=jax.devices()[:4])
    rng = np.random.default_rng(6)
    ws = jnp.asarray(rng.normal(size=(8, 8, 8)), jnp.float32)
    bs = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
    gates = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
    with pytest.raises(ValueError, match="one expert per device"):
        moe_apply(_expert_fn, (ws, bs), x, gates, mesh)


class TestDNNModelConsumers:
    """The pipe/expert ops behind the PUBLIC DNNModel API — a user-facing
    transform engages the axes, not just the raw ops."""

    def test_pipeline_mode_through_dnnmodel(self):
        from mmlspark_tpu.data.table import Table
        from mmlspark_tpu.dnn import DNNModel

        rng = np.random.default_rng(0)
        d, n, p = 8, 24, 4
        params = _stack_params(rng, p, d)
        X = rng.normal(size=(n, d)).astype(np.float32)

        out = DNNModel(
            pipelineStageFn=_stage_fn,
            modelParams=params,
            feedDict={"x": "f"},
            fetchDict={"y": "output"},
            batchSize=8,
            numMicrobatches=2,
            meshConfig=MeshConfig(data=2, pipe=p),
        ).transform(Table({"f": X}))

        want = np.asarray(_sequential(params, jnp.asarray(X)))
        np.testing.assert_allclose(out.column("y"), want, rtol=2e-4, atol=2e-5)

    def test_moe_mode_through_dnnmodel(self):
        from mmlspark_tpu.data.table import Table
        from mmlspark_tpu.dnn import DNNModel

        rng = np.random.default_rng(1)
        d, n, e = 8, 30, 8
        experts = (
            jnp.asarray(rng.normal(size=(e, d, d)) * 0.5, jnp.float32),
            jnp.asarray(rng.normal(size=(e, d)) * 0.1, jnp.float32),
        )
        gate = jnp.asarray(rng.normal(size=(d, e)), jnp.float32)
        X = rng.normal(size=(n, d)).astype(np.float32)

        def expert_fn(params, x):
            w, b = params
            return jnp.tanh(x @ w + b)

        out = DNNModel(
            expertFn=expert_fn,
            modelParams={"experts": experts, "gate": gate},
            feedDict={"x": "f"},
            fetchDict={"y": "output"},
            batchSize=10,
            meshConfig=MeshConfig(data=1, expert=e),
        ).transform(Table({"f": X}))

        # reference: dense per-token top-1 expert
        logits = X @ np.asarray(gate)
        probs = np.exp(logits - logits.max(axis=1, keepdims=True))
        probs /= probs.sum(axis=1, keepdims=True)
        assign = logits.argmax(axis=1)
        want = np.zeros_like(X)
        for i in range(n):
            w_, b_ = np.asarray(experts[0][assign[i]]), np.asarray(experts[1][assign[i]])
            want[i] = np.tanh(X[i] @ w_ + b_) * probs[i, assign[i]]
        np.testing.assert_allclose(out.column("y"), want, rtol=2e-4, atol=2e-5)

    def test_mode_exclusivity_raises(self):
        from mmlspark_tpu.dnn import DNNModel

        with pytest.raises(ValueError, match="exactly one of"):
            DNNModel(
                applyFn=lambda p, i: i,
                pipelineStageFn=_stage_fn,
                feedDict={"x": "f"},
                fetchDict={"y": "output"},
            )._jitted()
        with pytest.raises(ValueError, match="exactly one of"):
            DNNModel(feedDict={"x": "f"}, fetchDict={"y": "output"})._jitted()

    def test_moe_params_shape_validated(self):
        from mmlspark_tpu.dnn import DNNModel

        m = DNNModel(
            expertFn=lambda p, x: x,
            modelParams={"gate": np.zeros((4, 2))},  # missing 'experts'
            feedDict={"x": "f"},
            fetchDict={"y": "output"},
        )
        _, _, place = m._jitted()
        with pytest.raises(ValueError, match="experts"):
            place(m.getModelParams())


def test_pipeline_mode_unbatched_pads_to_microbatches():
    """miniBatcher=False with a row count not divisible by numMicrobatches
    must pad internally instead of raising."""
    from mmlspark_tpu.data.table import Table
    from mmlspark_tpu.dnn import DNNModel

    rng = np.random.default_rng(2)
    d, p = 8, 4
    params = _stack_params(rng, p, d)
    X = rng.normal(size=(10, d)).astype(np.float32)  # 10 % 4 != 0
    out = DNNModel(
        pipelineStageFn=_stage_fn,
        modelParams=params,
        feedDict={"x": "f"}, fetchDict={"y": "output"},
        miniBatcher=False, numMicrobatches=p,
        meshConfig=MeshConfig(data=2, pipe=p),
    ).transform(Table({"f": X}))
    want = np.asarray(_sequential(params, jnp.asarray(X)))
    np.testing.assert_allclose(out.column("y"), want, rtol=2e-4, atol=2e-5)


class TestExpertA2A:
    """Capacity-based all_to_all MoE dispatch (the GShard layout): tokens
    shard over the expert axis; overflow tokens drop to zero output."""

    def _setup(self, e=4, b=32, d=8, seed=0, skew=None):
        rng = np.random.default_rng(seed)
        ws = jnp.asarray(rng.normal(size=(e, d, d)) * 0.3, jnp.float32)
        bs = jnp.asarray(rng.normal(size=(e, d)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(b, d)), jnp.float32)
        gates = rng.normal(size=(b, e)).astype(np.float32)
        if skew is not None:
            gates[:, skew] += 10.0  # route (almost) everything to one expert
        return (ws, bs), x, jnp.asarray(gates)

    def test_matches_masked_dense_when_capacity_ample(self):
        from mmlspark_tpu.ops.expert_parallel import moe_apply, moe_apply_a2a

        mesh = make_mesh(MeshConfig(data=1, expert=4), devices=jax.devices()[:4])
        params, x, gates = self._setup()
        # capacity_factor high enough that nothing drops
        a2a = moe_apply_a2a(_expert_fn, params, x, gates, mesh, capacity_factor=4.0)
        dense = moe_apply(_expert_fn, params, x, gates, mesh)
        np.testing.assert_allclose(
            np.asarray(a2a), np.asarray(dense), rtol=2e-4, atol=2e-5
        )

    def test_overflow_tokens_drop_to_zero(self):
        from mmlspark_tpu.ops.expert_parallel import moe_apply_a2a

        mesh = make_mesh(MeshConfig(data=1, expert=4), devices=jax.devices()[:4])
        params, x, gates = self._setup(skew=2)  # everyone wants expert 2
        out = np.asarray(
            moe_apply_a2a(_expert_fn, params, x, gates, mesh, capacity_factor=1.0)
        )
        # per source: 8 local tokens, cap = ceil(8/4*1.0) = 2 slots for
        # expert 2 -> exactly 2 kept per device, 6 dropped (zero rows)
        zero_rows = (np.abs(out) < 1e-12).all(axis=1)
        assert zero_rows.sum() == 4 * 6, zero_rows.sum()
        # kept tokens match the dense computation for expert 2
        probs = np.asarray(jax.nn.softmax(gates, axis=1))
        xn = np.asarray(x)
        w2, b2 = np.asarray(params[0][2]), np.asarray(params[1][2])
        for i in np.nonzero(~zero_rows)[0]:
            want = (xn[i] @ w2 + b2) * probs[i, 2]
            np.testing.assert_allclose(out[i], want, rtol=2e-4, atol=2e-5)

    def test_expert_axis_one_falls_back(self):
        from mmlspark_tpu.ops.expert_parallel import moe_apply, moe_apply_a2a

        mesh = make_mesh(MeshConfig(data=8, expert=1))
        params, x, gates = self._setup(seed=2)
        np.testing.assert_allclose(
            np.asarray(moe_apply_a2a(_expert_fn, params, x, gates, mesh)),
            np.asarray(moe_apply(_expert_fn, params, x, gates, mesh)),
            rtol=2e-4, atol=2e-5,
        )

    def test_indivisible_batch_raises(self):
        from mmlspark_tpu.ops.expert_parallel import moe_apply_a2a

        mesh = make_mesh(MeshConfig(data=1, expert=4), devices=jax.devices()[:4])
        params, x, gates = self._setup(b=30)
        with pytest.raises(ValueError, match="not divisible"):
            moe_apply_a2a(_expert_fn, params, x, gates, mesh)
