"""Host C++ library (native/mmlspark_native.cpp) — bit-parity with the
numpy reference paths and graceful fallback when absent (SURVEY.md §2.20:
C++ where host-side)."""

import shutil

import numpy as np
import pytest

import mmlspark_tpu.native as native_mod
from mmlspark_tpu.native import (
    apply_bins_native,
    build,
    murmur3_bytes_native,
    murmur3_ints_native,
    murmur3_strings_native,
    native_available,
)


def _pack(tokens, encoding="utf-8"):
    bs = [t.encode(encoding) for t in tokens]
    lens = np.array([len(b) for b in bs], dtype=np.int64)
    starts = np.zeros(len(bs), dtype=np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    return np.frombuffer(b"".join(bs), dtype=np.uint8), starts, lens


@pytest.fixture(scope="module", autouse=True)
def built_library():
    if not native_available():
        if shutil.which("make") is None or shutil.which("g++") is None:
            pytest.skip("no native toolchain in this environment")
        build()
    assert native_available()


def _numpy_apply_bins(X, mapper):
    """The pure-numpy reference (native disabled)."""
    from mmlspark_tpu.lightgbm.binning import MISSING_BIN

    n, f = X.shape
    out = np.zeros((n, f), dtype=np.uint8)
    for j in range(f):
        col = X[:, j].astype(np.float32)
        nan_mask = np.isnan(col)
        b = 1 + np.searchsorted(mapper.edges[j].astype(np.float32), col, side="left")
        b = np.where(nan_mask, MISSING_BIN, b)
        out[:, j] = np.clip(b, 0, mapper.max_bin).astype(np.uint8)
    return out


class TestBinningParity:
    @pytest.mark.parametrize("max_bin", [255, 31])
    def test_bit_identical_to_numpy(self, max_bin):
        from mmlspark_tpu.lightgbm.binning import fit_bin_mapper

        rng = np.random.default_rng(0)
        X = rng.normal(size=(2000, 9))
        X[::13, 4] = np.nan
        X[:, 8] = rng.choice([0.0, 1.0, 2.0], size=2000)  # low cardinality
        mapper = fit_bin_mapper(X, max_bin=max_bin)
        ours = apply_bins_native(X, mapper.edges, mapper.max_bin)
        np.testing.assert_array_equal(ours, _numpy_apply_bins(X, mapper))

    def test_boundary_values_route_identically(self):
        """Values exactly on an edge must take the same bin in both paths
        (the float32-grid contract that keeps train/predict/SHAP aligned)."""
        from mmlspark_tpu.lightgbm.binning import fit_bin_mapper

        rng = np.random.default_rng(1)
        base = rng.normal(size=(500, 3))
        mapper = fit_bin_mapper(base, max_bin=63)
        # probe exactly at the edges
        probes = np.stack(
            [mapper.edges[j][np.isfinite(mapper.edges[j])][:40] for j in range(3)],
            axis=1,
        )
        ours = apply_bins_native(probes, mapper.edges, mapper.max_bin)
        np.testing.assert_array_equal(ours, _numpy_apply_bins(probes, mapper))

    def test_apply_bins_dispatches_to_native(self):
        from mmlspark_tpu.lightgbm.binning import bin_dataset

        rng = np.random.default_rng(2)
        X = rng.normal(size=(300, 5))
        bins, mapper = bin_dataset(X, max_bin=63)
        np.testing.assert_array_equal(bins, _numpy_apply_bins(X, mapper))


class TestMurmurParity:
    """Compare the C++ implementations against the PURE-python reference —
    ops.hashing dispatches to native itself, so the reference side is
    computed with the library disabled."""

    def test_bytes_matches_python(self, monkeypatch):
        from mmlspark_tpu.ops.hashing import murmur32_bytes

        cases = [
            (data, seed)
            for data in (b"", b"a", b"ab", b"abc", b"abcd", b"hello tpu world", bytes(range(37)))
            for seed in (0, 1, 0xDEADBEEF)
        ]
        native_vals = [murmur3_bytes_native(d, s) for d, s in cases]
        assert all(v is not None for v in native_vals)
        with monkeypatch.context() as m:
            m.setattr(native_mod, "_LIB", None)
            m.setattr(native_mod, "_LOAD_ATTEMPTED", True)
            pure = [murmur32_bytes(d, s) for d, s in cases]
        assert native_vals == pure

    def test_ints_match_python(self, monkeypatch):
        from mmlspark_tpu.ops.hashing import murmur32_ints

        rng = np.random.default_rng(3)
        vals = rng.integers(0, 2**32, size=1000, dtype=np.uint32)
        native_vals = murmur3_ints_native(vals, seed=7)
        with monkeypatch.context() as m:
            m.setattr(native_mod, "_LIB", None)
            m.setattr(native_mod, "_LOAD_ATTEMPTED", True)
            pure = murmur32_ints(vals, seed=7)
        np.testing.assert_array_equal(native_vals, pure)


class TestMurmurStringsParity:
    """The array-of-strings entry (one call per featurizer column) must agree
    byte-for-byte with the scalar bytes hash — prefixes of every alignment,
    1-3 byte tails, empty strings, multi-byte codepoints."""

    TOKENS = [
        "", "a", "ab", "abc", "abcd", "abcde", "héllo", "wörld", "漢字", "™",
        "χρώμα", "x" * 37, "the quick brown fox", "𝔘𝔫𝔦𝔠𝔬𝔡𝔢",
    ]

    @pytest.mark.parametrize("prefix", [b"", b"c", b"ns!", b"text", b"abcdefgh"])
    @pytest.mark.parametrize("seed", [0, 7, 0xDEADBEEF])
    def test_matches_scalar_bytes_hash(self, prefix, seed):
        buf, starts, lens = _pack(self.TOKENS)
        got = murmur3_strings_native(buf, starts, lens, seed, prefix)
        assert got is not None
        want = [
            murmur3_bytes_native(prefix + t.encode("utf-8"), seed)
            for t in self.TOKENS
        ]
        np.testing.assert_array_equal(got, np.array(want, dtype=np.uint32))

    def test_random_strings_match_numpy_fallback(self, monkeypatch):
        from mmlspark_tpu.ops.hashing import murmur32_bytes_batch

        rng = np.random.default_rng(11)
        alphabet = list("abc 01\t\n") + ["é", "漢", "™", "𝔘", " ", " "]
        tokens = [
            "".join(rng.choice(alphabet, size=rng.integers(0, 12)))
            for _ in range(300)
        ]
        buf, starts, lens = _pack(tokens)
        native_vals = murmur32_bytes_batch(buf, starts, lens, 5, b"pfx")
        with monkeypatch.context() as m:
            m.setattr(native_mod, "_LIB", None)
            m.setattr(native_mod, "_LOAD_ATTEMPTED", True)
            pure = murmur32_bytes_batch(buf, starts, lens, 5, b"pfx")
        np.testing.assert_array_equal(native_vals, pure)


class TestFallback:
    def test_absent_library_returns_none(self, monkeypatch):
        monkeypatch.setattr(native_mod, "_LIB", None)
        monkeypatch.setattr(native_mod, "_LOAD_ATTEMPTED", True)
        assert native_mod.apply_bins_native(np.zeros((2, 2)), np.zeros((2, 1)), 3) is None
        assert native_mod.murmur3_bytes_native(b"x") is None
        # binning still works through the numpy path
        from mmlspark_tpu.lightgbm.binning import bin_dataset

        bins, _ = bin_dataset(np.random.default_rng(0).normal(size=(50, 3)), max_bin=15)
        assert bins.dtype == np.uint8
