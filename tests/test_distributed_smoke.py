"""Two-process ``jax.distributed`` bootstrap smoke.

Executes :func:`mmlspark_tpu.parallel.mesh.distributed_init` for REAL: a
coordinator and a worker process rendezvous over localhost (the surviving
driver-rendezvous role of the reference's ``LightGBMUtils.scala:117-186``
socket collect/broadcast), then run one cross-process ``psum`` and check
both sides observe the global sum. Everything else in the distributed
stack is exercised on the in-process 8-device mesh; this is the one path
that needs actual separate processes.
"""

import os
import subprocess
import socket
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import os, sys

    sys.path.insert(0, sys.argv[3])

    pid, port = int(sys.argv[1]), sys.argv[2]

    # The container sitecustomize may pre-create a client at interpreter
    # startup; the process group must form BEFORE any backend exists, so
    # tear down whatever got built (the same hazard tests/conftest.py
    # handles with force_platform).
    from jax._src import xla_bridge

    if getattr(xla_bridge, "_backends", None):
        xla_bridge._clear_backends()

    from mmlspark_tpu.parallel.mesh import distributed_init

    # executor-keyed convention: process ids derive from the sorted
    # executor list, exactly how a driver would number its workers.
    topo = distributed_init(
        coordinator_address=f"127.0.0.1:{port}",
        executor_ids=["exec-b", "exec-a"],
        local_executor_id=["exec-a", "exec-b"][pid],
    )
    import jax
    import jax.numpy as jnp

    assert jax.process_count() == 2, jax.process_count()
    assert jax.process_index() == pid, (jax.process_index(), pid)
    assert topo.num_devices == 2, topo.num_devices

    # one real cross-process collective: psum of (pid + 1) over both
    # processes' devices must be 3 on BOTH sides
    local = jnp.full((jax.local_device_count(), 1), float(pid + 1))
    total = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(local)
    assert float(total[0, 0]) == 3.0, total
    print(f"OK {pid}", flush=True)
    """
)


def _run_pair(script, port, env):
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), REPO],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    return procs, outs


def test_two_process_psum(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)

    # Scrub the child env: no XLA_FLAGS (one CPU device per process) and —
    # critically — no PALLAS_AXON*/AXON* vars, or the container
    # sitecustomize dials the TPU relay from BOTH children at interpreter
    # start (one TPU client at a time; a second wedges the relay) and
    # pre-creates a backend before the process group can form.
    env = {
        k: v
        for k, v in os.environ.items()
        if k != "XLA_FLAGS" and not k.startswith(("PALLAS_AXON", "AXON", "TPU_"))
    }
    env["JAX_PLATFORMS"] = "cpu"

    # The ephemeral port is probed then released before the coordinator
    # child rebinds it — a TOCTOU window another process can steal. Retry
    # on a fresh port rather than flaking.
    for attempt in range(3):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs, outs = _run_pair(script, port, env)
        if all(p.returncode == 0 for p in procs):
            break
        bind_lost = any(
            "Failed to bind" in out or "address already in use" in out.lower()
            for out in outs
        )
        if not (bind_lost and attempt < 2):
            break
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"OK {pid}" in out, out
