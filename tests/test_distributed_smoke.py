"""Two-process ``jax.distributed`` bootstrap smoke.

Executes :func:`mmlspark_tpu.parallel.mesh.distributed_init` for REAL: a
coordinator and a worker process rendezvous over localhost (the surviving
driver-rendezvous role of the reference's ``LightGBMUtils.scala:117-186``
socket collect/broadcast), then run one cross-process collective and
check both sides observe the global sum.

The collective has two layers, matching how the process-parallel fit
actually works (``runtime/procgroup.py``): an XLA ``psum`` when the
backend supports multi-process computation, else the host-level socket
allreduce — the analogue of LightGBM's own ``Network::Allreduce``, which
likewise never runs inside the accelerator program. jax's CPU backend
raises ``Multiprocess computations aren't implemented`` for the former,
so on CPU the socket path is the one under test; the rendezvous
assertions (process_count/process_index/topology) run either way.

Hardening baked in here: worker ports come from the seeded
``pick_port`` prober with a bounded retry on bind races, and a failing
worker's full output (stderr is merged into stdout) is propagated into
the assertion message instead of a bare exit code.
"""

import os
import subprocess
import sys
import textwrap

from mmlspark_tpu.runtime.procgroup import pick_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import os, sys, traceback

    sys.path.insert(0, sys.argv[3])

    pid, port, reduce_port = int(sys.argv[1]), sys.argv[2], int(sys.argv[4])

    # The container sitecustomize may pre-create a client at interpreter
    # startup; the process group must form BEFORE any backend exists, so
    # tear down whatever got built (the same hazard tests/conftest.py
    # handles with force_platform).
    from jax._src import xla_bridge

    if getattr(xla_bridge, "_backends", None):
        xla_bridge._clear_backends()

    from mmlspark_tpu.parallel.mesh import distributed_init

    # executor-keyed convention: process ids derive from the sorted
    # executor list, exactly how a driver would number its workers.
    topo = distributed_init(
        coordinator_address=f"127.0.0.1:{port}",
        executor_ids=["exec-b", "exec-a"],
        local_executor_id=["exec-a", "exec-b"][pid],
    )
    import jax
    import jax.numpy as jnp

    assert jax.process_count() == 2, jax.process_count()
    assert jax.process_index() == pid, (jax.process_index(), pid)
    assert topo.num_devices == 2, topo.num_devices

    # one real cross-process collective: the global sum of (pid + 1) over
    # both processes must be 3 on BOTH sides. Try the XLA layer first;
    # backends without multi-process computation (CPU) fall back to the
    # host-level socket allreduce — the layer the process-parallel fit
    # rides (procgroup.AllreduceGroup over jax.pure_callback).
    layer = "psum"
    try:
        local = jnp.full((jax.local_device_count(), 1), float(pid + 1))
        total = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(local)
        value = float(total[0, 0])
    except RuntimeError as e:
        if "Multiprocess computations" not in str(e):
            raise
        layer = "socket"
        from mmlspark_tpu.parallel.mesh import distributed_shutdown
        from mmlspark_tpu.runtime.procgroup import AllreduceGroup

        # release the distributed client BEFORE host collectives: a live
        # coordination-service poller aborts survivors on peer exit
        distributed_shutdown()
        import numpy as np

        group = AllreduceGroup(pid, 2, reduce_port, timeout=60.0)
        value = float(group.allreduce(np.full((1,), float(pid + 1)))[0])
        group.close()
    assert value == 3.0, (layer, value)
    print(f"OK {pid} via {layer}", flush=True)
    """
)


def _run_pair(script, port, reduce_port, env):
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), REPO,
             str(reduce_port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    return procs, outs


def test_two_process_collective(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)

    # Scrub the child env: no XLA_FLAGS (one CPU device per process) and —
    # critically — no PALLAS_AXON*/AXON* vars, or the container
    # sitecustomize dials the TPU relay from BOTH children at interpreter
    # start (one TPU client at a time; a second wedges the relay) and
    # pre-creates a backend before the process group can form.
    env = {
        k: v
        for k, v in os.environ.items()
        if k != "XLA_FLAGS" and not k.startswith(("PALLAS_AXON", "AXON", "TPU_"))
    }
    env["JAX_PLATFORMS"] = "cpu"

    # Seeded bind-probed ports; the probe releases before the coordinator
    # child rebinds — a TOCTOU window another process can steal. Retry on
    # fresh ports rather than flaking.
    for attempt in range(3):
        port = pick_port(seed=7000 + attempt)
        reduce_port = pick_port(seed=8000 + attempt, exclude={port})
        procs, outs = _run_pair(script, port, reduce_port, env)
        if all(p.returncode == 0 for p in procs):
            break
        bind_lost = any(
            "Failed to bind" in out or "address already in use" in out.lower()
            for out in outs
        )
        if not (bind_lost and attempt < 2):
            break
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"OK {pid}" in out, out
