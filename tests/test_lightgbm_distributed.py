"""Distributed-vs-serial equivalence — the data_parallel contract.

LightGBM's data_parallel mode must produce the same model regardless of the
number of workers (histogram allreduce is exact). Same here: an 8-shard mesh
run must match the single-device run up to float summation order.
"""

import numpy as np

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import LightGBMClassifier


def test_data_parallel_matches_serial():
    from sklearn.datasets import load_breast_cancer

    d = load_breast_cancer()
    t = Table({"features": d.data.astype(np.float64), "label": d.target.astype(np.float64)})

    kw = dict(numIterations=15, numLeaves=15, seed=0)
    m_serial = LightGBMClassifier(parallelism="serial", **kw).fit(t)
    m_dist = LightGBMClassifier(parallelism="data_parallel", **kw).fit(t)

    p_serial = m_serial.transform(t)["probability"][:, 1]
    p_dist = m_dist.transform(t)["probability"][:, 1]
    # identical tree structure; tiny float drift from reduction order only
    assert (
        m_serial.booster.split_feature == m_dist.booster.split_feature
    ).mean() > 0.98
    np.testing.assert_allclose(p_serial, p_dist, atol=2e-3)


def test_num_tasks_caps_shards():
    from sklearn.datasets import load_breast_cancer

    d = load_breast_cancer()
    t = Table({"features": d.data.astype(np.float64), "label": d.target.astype(np.float64)})
    m = LightGBMClassifier(numIterations=3, numTasks=2).fit(t)
    assert m.booster.num_trees == 3
