"""Distributed-vs-serial equivalence — the data_parallel contract.

LightGBM's data_parallel mode must produce the same model regardless of the
number of workers (histogram allreduce is exact). Same here: an 8-shard mesh
run must match the single-device run up to float summation order.
"""

import numpy as np

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import LightGBMClassifier


def test_data_parallel_matches_serial():
    from sklearn.datasets import load_breast_cancer

    d = load_breast_cancer()
    t = Table({"features": d.data.astype(np.float64), "label": d.target.astype(np.float64)})

    kw = dict(numIterations=15, numLeaves=15, seed=0)
    m_serial = LightGBMClassifier(parallelism="serial", **kw).fit(t)
    m_dist = LightGBMClassifier(parallelism="data_parallel", **kw).fit(t)

    p_serial = m_serial.transform(t)["probability"][:, 1]
    p_dist = m_dist.transform(t)["probability"][:, 1]
    # identical tree structure; tiny float drift from reduction order only
    assert (
        m_serial.booster.split_feature == m_dist.booster.split_feature
    ).mean() > 0.98
    np.testing.assert_allclose(p_serial, p_dist, atol=2e-3)


def test_num_tasks_caps_shards():
    from sklearn.datasets import load_breast_cancer

    d = load_breast_cancer()
    t = Table({"features": d.data.astype(np.float64), "label": d.target.astype(np.float64)})
    m = LightGBMClassifier(numIterations=3, numTasks=2).fit(t)
    assert m.booster.num_trees == 3


def test_mesh_fit_with_bagging_validation_early_stop(mesh8):
    """The loop path under the mesh with everything on: bagging resampling,
    feature fraction, a validation set, and early stopping — collective
    programs interleaved with per-iteration host decisions."""
    import numpy as np

    from mmlspark_tpu.lightgbm.binning import bin_dataset
    from mmlspark_tpu.lightgbm.objectives import auc
    from mmlspark_tpu.lightgbm.train import TrainOptions, train

    rng = np.random.default_rng(4)
    n, f = 16384, 10
    X = rng.normal(size=(n, f))
    y = ((X[:, 0] + X[:, 1] * X[:, 2] + 0.3 * rng.normal(size=n)) > 0).astype(
        np.float64
    )
    bins, mapper = bin_dataset(X, max_bin=63)
    vb, _ = bin_dataset(X[:4000], mapper=mapper)
    opts = TrainOptions(
        objective="binary", num_iterations=25, num_leaves=15, max_bin=63,
        bagging_fraction=0.7, bagging_freq=1, feature_fraction=0.8,
        early_stopping_round=5, seed=11,
    )
    r = train(
        bins, y, opts, mapper=mapper, mesh=mesh8,
        valid_sets=[("v", vb, y[:4000], None)],
    )
    assert 1 <= r.booster.num_trees <= 25
    a = auc(y, r.booster.raw_margin(X)[:, 0], np.ones(n))
    assert a > 0.85, a
    assert len(r.evals["v"]["auc"]) == r.booster.num_trees
