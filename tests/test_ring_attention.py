"""Ring attention over the mesh seq axis vs the O(S^2) reference —
long-context sequence parallelism on the 8-virtual-device CPU mesh."""

import numpy as np
import jax.numpy as jnp
import pytest

from mmlspark_tpu.ops.ring_attention import attention_reference, ring_attention
from mmlspark_tpu.parallel.mesh import MeshConfig, make_mesh


def _qkv(b=2, s=64, h=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.fixture()
def seq_mesh():
    return make_mesh(MeshConfig(data=1, seq=8))


class TestRingAttention:
    def test_matches_reference(self, seq_mesh):
        q, k, v = _qkv()
        ref = attention_reference(q, k, v)
        ring = ring_attention(q, k, v, seq_mesh)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(ref), rtol=2e-4, atol=2e-5)

    def test_causal_matches_reference(self, seq_mesh):
        q, k, v = _qkv(seed=1)
        ref = attention_reference(q, k, v, causal=True)
        ring = ring_attention(q, k, v, seq_mesh, causal=True)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(ref), rtol=2e-4, atol=2e-5)

    def test_seq_axis_one_falls_back(self):
        mesh = make_mesh(MeshConfig(data=8, seq=1))
        q, k, v = _qkv(s=32, seed=2)
        out = ring_attention(q, k, v, mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)

    def test_indivisible_sequence_raises(self, seq_mesh):
        q, k, v = _qkv(s=60, seed=3)
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, seq_mesh)

    def test_data_x_seq_mesh(self):
        """Batch sharded over data AND sequence over seq simultaneously."""
        mesh = make_mesh(MeshConfig(data=2, seq=4))
        q, k, v = _qkv(b=4, s=32, seed=4)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(mesh, P("data", "seq"))
        q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
        ring = ring_attention(q, k, v, mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(ref), rtol=2e-4, atol=2e-5)


class TestA2AAttention:
    """All-to-all (Ulysses) sequence parallelism — the second long-context
    layout, head-parallel inner attention between two all_to_all reshards."""

    def _qkv(self, b=2, s=64, h=8, d=16, seed=0):
        rng = np.random.default_rng(seed)
        return [
            jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
            for _ in range(3)
        ]

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        from mmlspark_tpu.ops.a2a_attention import a2a_attention

        mesh = make_mesh(MeshConfig(data=1, seq=8))
        q, k, v = self._qkv()
        out = a2a_attention(q, k, v, mesh, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5
        )

    def test_matches_ring(self):
        from mmlspark_tpu.ops.a2a_attention import a2a_attention
        from mmlspark_tpu.ops.ring_attention import ring_attention

        mesh = make_mesh(MeshConfig(data=1, seq=8))
        q, k, v = self._qkv(seed=3)
        a2a = a2a_attention(q, k, v, mesh, causal=True)
        ring = ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(a2a), np.asarray(ring), rtol=2e-4, atol=2e-5
        )

    def test_data_and_seq_axes_together(self):
        from mmlspark_tpu.ops.a2a_attention import a2a_attention

        mesh = make_mesh(MeshConfig(data=2, seq=4))
        q, k, v = self._qkv(s=32, h=4, seed=4)
        out = a2a_attention(q, k, v, mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5
        )

    def test_head_count_constraint(self):
        from mmlspark_tpu.ops.a2a_attention import a2a_attention

        mesh = make_mesh(MeshConfig(data=1, seq=8))
        q, k, v = self._qkv(h=6)  # 6 % 8 != 0
        with pytest.raises(ValueError, match="num_heads divisible"):
            a2a_attention(q, k, v, mesh)

    def test_seq_axis_one_falls_back(self):
        from mmlspark_tpu.ops.a2a_attention import a2a_attention

        mesh = make_mesh(MeshConfig(data=8, seq=1))
        q, k, v = self._qkv(h=3, seed=5)  # odd head count fine at p=1
        out = a2a_attention(q, k, v, mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(attention_reference(q, k, v)),
            rtol=2e-4, atol=2e-5,
        )
