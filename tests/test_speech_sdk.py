"""SpeechToTextSDK streaming transport (``SpeechToTextSDK.scala:66-249`` /
``AudioStreams.scala:16-84``): WAV pull-stream validation and chunked
streaming against an in-process endpoint that decodes transfer chunks."""

import io
import json
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mmlspark_tpu.cognitive import SpeechToTextSDK, WavStream
from mmlspark_tpu.cognitive.audio import CompressedStream, make_audio_stream
from mmlspark_tpu.data.table import Table


def make_wav(n_samples=16000, extra_fmt=0) -> bytes:
    """Valid PCM mono 16 kHz 16-bit WAV."""
    pcm = (np.sin(np.linspace(0, 100, n_samples)) * 20000).astype("<i2").tobytes()
    fmt_size = 16 + extra_fmt
    fmt = struct.pack("<HHIIHH", 1, 1, 16000, 32000, 2, 16) + b"\0" * extra_fmt
    return (
        b"RIFF" + struct.pack("<I", 36 + extra_fmt + len(pcm)) + b"WAVE"
        + b"fmt " + struct.pack("<I", fmt_size) + fmt
        + b"data" + struct.pack("<I", len(pcm)) + pcm
    )


class ChunkedSpeechMock:
    """Speech endpoint that DECODES the chunked request body, records every
    transfer chunk, and replies with one 'Recognizing' event per chunk plus
    a final 'Success' utterance — the SDK event stream shape."""

    def __init__(self):
        self.calls = []
        mock = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_POST(self):  # noqa: N802
                assert self.headers.get("Transfer-Encoding") == "chunked", (
                    "client must stream (no Content-Length upload)"
                )
                chunks = []
                while True:
                    size = int(self.rfile.readline().strip(), 16)
                    if size == 0:
                        self.rfile.readline()  # trailing CRLF
                        break
                    chunks.append(self.rfile.read(size))
                    self.rfile.readline()
                mock.calls.append({
                    "path": self.path,
                    "headers": dict(self.headers),
                    "chunks": chunks,
                })
                events = [
                    {"RecognitionStatus": "Recognizing",
                     "DisplayText": f"partial-{i}", "Offset": i}
                    for i in range(len(chunks))
                ] + [{"RecognitionStatus": "Success",
                      "DisplayText": f"hello after {len(chunks)} chunks",
                      "Offset": 0, "Duration": 100}]
                data = json.dumps(events).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}/speech"

    def __enter__(self):
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self

    def __exit__(self, *exc):
        self._httpd.shutdown()
        self._httpd.server_close()


class TestWavStream:
    def test_frames_reassemble_payload(self):
        wav = make_wav(8000)
        ws = WavStream(wav, chunk_size=1000)
        frames = list(ws.frames())
        assert all(len(f) <= 1000 for f in frames)
        assert len(frames) == 16  # 16000 bytes of PCM
        assert b"".join(frames) == wav[44:]
        assert ws.data_length == 16000

    def test_extended_format_header(self):
        ws = WavStream(make_wav(1000, extra_fmt=2))
        assert b"".join(ws.frames()) == make_wav(1000, extra_fmt=2)[-2000:]

    @pytest.mark.parametrize("mutate,err", [
        (lambda b: b"JUNK" + b[4:], "RIFF"),
        (lambda b: b[:8] + b"EVAW" + b[12:], "WAVE"),
        # stereo
        (lambda b: b[:22] + struct.pack("<H", 2) + b[24:], "single channel"),
        # 8 kHz
        (lambda b: b[:24] + struct.pack("<I", 8000) + b[28:], "samples per second"),
        # 8-bit
        (lambda b: b[:34] + struct.pack("<H", 8) + b[36:], "bits per sample"),
        # non-PCM
        (lambda b: b[:20] + struct.pack("<H", 3) + b[22:], "PCM"),
    ])
    def test_header_contract(self, mutate, err):
        with pytest.raises(ValueError, match=err):
            WavStream(mutate(make_wav(100)))

    def test_compressed_passthrough_and_factory(self):
        blob = b"\xff\xfbnot-really-mp3" * 100
        cs = CompressedStream(blob, chunk_size=256)
        assert b"".join(cs.frames()) == blob
        assert isinstance(make_audio_stream(make_wav(10), "wav"), WavStream)
        assert isinstance(make_audio_stream(blob, "mp3"), CompressedStream)
        with pytest.raises(ValueError, match="fileType"):
            make_audio_stream(blob, "flac")


class TestSpeechToTextSDK:
    def test_streams_chunks_and_collects_events(self):
        wav = make_wav(16000)  # 32000 PCM bytes -> 10 chunks of 3200
        with ChunkedSpeechMock() as mock:
            sdk = SpeechToTextSDK(
                url=mock.url, subscriptionKey="k", outputCol="text",
                audioDataCol="audio", language="en-US",
            )
            t = Table({"audio": np.array([wav], dtype=object)})
            out = sdk.transform(t)
        events = out["text"][0]
        call = mock.calls[0]
        assert len(call["chunks"]) == 10
        assert b"".join(call["chunks"]) == wav[44:]
        assert call["headers"]["Ocp-Apim-Subscription-Key"] == "k"
        assert "language=en-US" in call["path"]
        # intermediate events kept by default
        assert [e["RecognitionStatus"] for e in events].count("Recognizing") == 10
        assert events[-1]["DisplayText"] == "hello after 10 chunks"

    def test_finals_only_when_streaming_disabled(self):
        with ChunkedSpeechMock() as mock:
            sdk = SpeechToTextSDK(
                url=mock.url, subscriptionKey="k", outputCol="text",
                streamIntermediateResults=False,
            )
            out = sdk.transform(Table({"audio": np.array([make_wav(4800)], dtype=object)}))
        events = out["text"][0]
        assert len(events) == 1
        assert events[0]["RecognitionStatus"] == "Success"

    def test_invalid_wav_routes_to_error_col(self):
        with ChunkedSpeechMock() as mock:
            sdk = SpeechToTextSDK(
                url=mock.url, subscriptionKey="k", outputCol="text",
                errorCol="err",
            )
            out = sdk.transform(
                Table({"audio": np.array([b"not audio", make_wav(1600)], dtype=object)})
            )
        assert out["text"][0] is None and "RIFF" in out["err"][0]
        assert out["text"][1] is not None and out["err"][1] is None

    def test_custom_endpoint_id_rides_query(self):
        with ChunkedSpeechMock() as mock:
            SpeechToTextSDK(
                url=mock.url, subscriptionKey="k", outputCol="text",
                endpointId="my-model",
            ).transform(Table({"audio": np.array([make_wav(1600)], dtype=object)}))
        assert "cid=my-model" in mock.calls[0]["path"]


def test_preexisting_query_string_preserved():
    """A query already on the configured url must survive param assembly."""
    with ChunkedSpeechMock() as mock:
        SpeechToTextSDK(
            url=mock.url + "?initialSilenceTimeoutMs=600",
            subscriptionKey="k", outputCol="text",
        ).transform(Table({"audio": np.array([make_wav(1600)], dtype=object)}))
    path = mock.calls[0]["path"]
    assert "initialSilenceTimeoutMs=600" in path and "language=en-US" in path
