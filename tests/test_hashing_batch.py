"""Property tests for the batched murmur path (ops.hashing.murmur32_bytes_batch)
and the integer dtype-coercion contract — native and numpy-fallback paths must
agree with the scalar reference on every input, or the VW feature space
silently shifts between environments."""

import numpy as np
import pytest

import mmlspark_tpu.native as native_mod
from mmlspark_tpu.ops.hashing import (
    _coerce_u32,
    murmur32_bytes,
    murmur32_bytes_batch,
    murmur32_ints,
)


def _pack(tokens):
    bs = [t.encode("utf-8") for t in tokens]
    lens = np.array([len(b) for b in bs], dtype=np.int64)
    starts = np.zeros(len(bs), dtype=np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    return np.frombuffer(b"".join(bs), dtype=np.uint8), starts, lens


def _random_tokens(rng, count):
    """Unicode strings covering 1-3 byte utf-8 tails, empty strings, and
    multi-byte codepoints (2, 3, and 4 byte encodings)."""
    pieces = list("abcdefgh 0123") + ["é", "ß", "χ", "漢", "字", "™", "𝔘", "🎉"]
    return [
        "".join(rng.choice(pieces, size=int(rng.integers(0, 14))))
        for _ in range(count)
    ]


@pytest.fixture(params=["native", "fallback"])
def hash_path(request, monkeypatch):
    """Run the test body under both dispatch paths. The native param skips
    when no library is loadable (fallback still runs)."""
    if request.param == "native":
        if native_mod.load_library() is None:
            pytest.skip("native library unavailable")
    else:
        monkeypatch.setattr(native_mod, "_LIB", None)
        monkeypatch.setattr(native_mod, "_LOAD_ATTEMPTED", True)
    return request.param


class TestBatchedMurmurProperty:
    @pytest.mark.parametrize("seed", [0, 1, 0xCAFEBABE])
    @pytest.mark.parametrize("prefix", [b"", b"x", b"ns", b"col", b"abcd", b"colname!"])
    def test_batch_equals_scalar_on_random_unicode(self, hash_path, seed, prefix):
        rng = np.random.default_rng(seed + len(prefix))
        tokens = _random_tokens(rng, 200)
        buf, starts, lens = _pack(tokens)
        got = murmur32_bytes_batch(buf, starts, lens, seed, prefix)
        want = np.array(
            [murmur32_bytes(prefix + t.encode("utf-8"), seed) for t in tokens],
            dtype=np.uint32,
        )
        np.testing.assert_array_equal(got, want)

    def test_edge_tokens(self, hash_path):
        """Empty string, 1-3 byte tails, embedded NULs, 4-byte codepoints."""
        tokens = ["", "a", "ab", "abc", "abcd", "\x00", "a\x00b", "🎉", "é™", "x" * 65]
        buf, starts, lens = _pack(tokens)
        got = murmur32_bytes_batch(buf, starts, lens, 3, b"p!")
        want = np.array(
            [murmur32_bytes(b"p!" + t.encode("utf-8"), 3) for t in tokens],
            dtype=np.uint32,
        )
        np.testing.assert_array_equal(got, want)

    def test_golden_row_tokens_match_scalar(self, hash_path):
        """The exact tokens pinned by the featurizer golden fixture hash the
        same through the batch entry as through the old per-token scalar."""
        from tests.test_vw_featurizer_golden import golden_table

        t = golden_table()
        tokens = []
        for v in t.column("text"):
            if v is not None:
                tokens.extend(v.split())
        for v in t.column("tags"):
            if v:
                tokens.extend(str(x) for x in v)
        buf, starts, lens = _pack(tokens)
        for prefix in (b"", b"text", b"tags"):
            got = murmur32_bytes_batch(buf, starts, lens, 0, prefix)
            want = np.array(
                [murmur32_bytes(prefix + tok.encode("utf-8"), 0) for tok in tokens],
                dtype=np.uint32,
            )
            np.testing.assert_array_equal(got, want)

    def test_empty_batch(self, hash_path):
        z = np.zeros(0, dtype=np.int64)
        out = murmur32_bytes_batch(np.zeros(0, dtype=np.uint8), z, z, 9, b"p")
        assert out.size == 0 and out.dtype == np.uint32


class TestIntDtypeCoercion:
    def test_int_and_float_inputs_never_diverge(self, hash_path):
        """murmur32_ints(float64 zeros) was fed straight to C, where
        float->unsigned conversion is undefined for negatives; the int64 hop
        makes every dtype land on the same uint32 grid in both paths."""
        vals = [0.0, 1.0, -1.0, 2.0, 255.0, 4294967295.0, -2147483648.0]
        as_f64 = np.array(vals, dtype=np.float64)
        as_i64 = as_f64.astype(np.int64)
        as_u32 = as_i64.astype(np.uint32)
        h_f = murmur32_ints(as_f64, seed=5)
        h_i = murmur32_ints(as_i64, seed=5)
        h_u = murmur32_ints(as_u32, seed=5)
        np.testing.assert_array_equal(h_f, h_i)
        np.testing.assert_array_equal(h_f, h_u)

    def test_coerce_u32_rule(self):
        np.testing.assert_array_equal(
            _coerce_u32(np.array([0.0, -1.0, 2.5])),
            np.array([0, 4294967295, 2], dtype=np.uint32),
        )
        assert _coerce_u32(np.zeros(3, dtype=np.uint32)).dtype == np.uint32
