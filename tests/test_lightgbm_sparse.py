"""Sparse (CSR) GBDT ingest — the ``LGBM_DatasetCreateFromCSRSpark`` path
(reference ``lightgbm/LightGBMUtils.scala:246-266``): binning, training, and
predict on sparse features must match the equivalent dense pipeline exactly
(implicit entries are 0.0; explicit NaN is missing)."""

import numpy as np
import pytest

from mmlspark_tpu.data.sparse import (
    CSRMatrix,
    csr_column_to_matrix,
    is_sparse_column,
)
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import LightGBMClassifier, LightGBMRegressor
from mmlspark_tpu.lightgbm.binning import (
    apply_bins_csr,
    bin_dataset,
    fit_bin_mapper,
    fit_bin_mapper_csr,
)


def _random_sparse(rng, n, f, density=0.3, nan_frac=0.02):
    dense = np.zeros((n, f))
    mask = rng.random((n, f)) < density
    dense[mask] = rng.normal(size=mask.sum())
    nan_mask = rng.random((n, f)) < nan_frac
    dense[nan_mask] = np.nan
    return dense


class TestCSRMatrix:
    def test_from_dense_roundtrip(self):
        rng = np.random.default_rng(0)
        dense = _random_sparse(rng, 50, 7)
        csr = CSRMatrix.from_dense(dense)
        back = csr.to_dense()
        np.testing.assert_array_equal(np.isnan(back), np.isnan(dense))
        np.testing.assert_array_equal(back[~np.isnan(dense)], dense[~np.isnan(dense)])

    def test_from_rows_and_column(self):
        rows = [
            (np.array([0, 3]), np.array([1.0, 2.0])),
            (np.array([], dtype=np.int64), np.array([])),
            (np.array([1]), np.array([-4.0])),
        ]
        csr = CSRMatrix.from_rows(rows, num_features=5)
        assert csr.shape == (3, 5)
        assert csr.nnz == 3
        dense = csr.to_dense()
        assert dense[0, 3] == 2.0 and dense[2, 1] == -4.0 and dense[1].sum() == 0

        col = np.empty(3, dtype=object)
        for i, r in enumerate(rows):
            col[i] = r
        assert is_sparse_column(col)
        csr2 = csr_column_to_matrix(col, num_features=5)
        np.testing.assert_array_equal(csr2.to_dense(), dense)

    def test_row_slice_and_take(self):
        rng = np.random.default_rng(1)
        dense = _random_sparse(rng, 40, 5, nan_frac=0)
        csr = CSRMatrix.from_dense(dense)
        np.testing.assert_array_equal(csr.row_slice(10, 25).to_dense(), dense[10:25])
        idx = np.array([3, 1, 39, 7])
        np.testing.assert_array_equal(csr.take_rows(idx).to_dense(), dense[idx])
        mask = rng.random(40) < 0.5
        np.testing.assert_array_equal(csr.take_rows(mask).to_dense(), dense[mask])

    def test_to_csc(self):
        rng = np.random.default_rng(2)
        dense = _random_sparse(rng, 30, 4, nan_frac=0)
        csr = CSRMatrix.from_dense(dense)
        col_indptr, row_ids, values = csr.to_csc()
        for j in range(4):
            lo, hi = col_indptr[j], col_indptr[j + 1]
            got = np.zeros(30)
            got[row_ids[lo:hi]] = values[lo:hi]
            np.testing.assert_array_equal(got, dense[:, j])


class TestSparseBinning:
    @pytest.mark.parametrize("max_bin", [255, 15])
    def test_mapper_matches_dense(self, max_bin):
        rng = np.random.default_rng(3)
        dense = _random_sparse(rng, 800, 6, density=0.4)
        # one low-cardinality column to hit the unique-values path
        dense[:, 5] = rng.choice([0.0, 1.0, 2.5], size=800)
        csr = CSRMatrix.from_dense(dense)
        m_dense = fit_bin_mapper(dense, max_bin=max_bin)
        m_csr = fit_bin_mapper_csr(csr, max_bin=max_bin)
        np.testing.assert_array_equal(m_dense.num_bins, m_csr.num_bins)
        np.testing.assert_array_equal(m_dense.edges, m_csr.edges)

    def test_mapper_matches_dense_sampled(self):
        rng = np.random.default_rng(4)
        dense = _random_sparse(rng, 3000, 3, density=0.5)
        csr = CSRMatrix.from_dense(dense)
        m_dense = fit_bin_mapper(dense, max_bin=31, sample_cnt=1000, seed=7)
        m_csr = fit_bin_mapper_csr(csr, max_bin=31, sample_cnt=1000, seed=7)
        np.testing.assert_array_equal(m_dense.edges, m_csr.edges)

    def test_bins_match_dense(self):
        rng = np.random.default_rng(5)
        dense = _random_sparse(rng, 500, 8, density=0.25)
        csr = CSRMatrix.from_dense(dense)
        bins_dense, mapper = bin_dataset(dense, max_bin=63)
        bins_csr = apply_bins_csr(csr, mapper)
        np.testing.assert_array_equal(bins_dense, bins_csr)

    def test_bin_dataset_dispatches(self):
        rng = np.random.default_rng(6)
        dense = _random_sparse(rng, 200, 4)
        bins_d, m_d = bin_dataset(dense, max_bin=31)
        bins_s, m_s = bin_dataset(CSRMatrix.from_dense(dense), max_bin=31)
        np.testing.assert_array_equal(bins_d, bins_s)
        np.testing.assert_array_equal(m_d.edges, m_s.edges)


def _sparse_table(dense, y):
    col = np.empty(len(dense), dtype=object)
    for i in range(len(dense)):
        row = dense[i]
        nz = np.nonzero((row != 0) | np.isnan(row))[0]
        col[i] = (nz, row[nz])
    return Table({"features": col, "label": y.astype(np.float64)})


class TestSparseTraining:
    def test_classifier_sparse_matches_dense(self):
        rng = np.random.default_rng(7)
        n = 400
        dense = _random_sparse(rng, n, 6, density=0.4, nan_frac=0)
        y = (dense[:, 0] + 0.5 * dense[:, 1] > 0).astype(np.float64)
        t_dense = Table({"features": dense, "label": y})
        t_sparse = _sparse_table(dense, y)

        kw = dict(numIterations=15, numLeaves=7, parallelism="serial")
        m_dense = LightGBMClassifier(**kw).fit(t_dense)
        m_sparse = LightGBMClassifier(**kw).fit(t_sparse)

        np.testing.assert_array_equal(
            m_dense.booster.split_feature, m_sparse.booster.split_feature
        )
        np.testing.assert_allclose(
            m_dense.booster.leaf_values, m_sparse.booster.leaf_values, rtol=1e-6
        )
        out_d = m_dense.transform(t_dense)
        out_s = m_sparse.transform(t_sparse)
        np.testing.assert_allclose(
            out_d.column("probability"), out_s.column("probability"), rtol=1e-6
        )

    def test_regressor_sparse_fits(self):
        rng = np.random.default_rng(8)
        dense = _random_sparse(rng, 300, 5, density=0.5, nan_frac=0.01)
        yr = np.nan_to_num(dense[:, 0]) * 2 + rng.normal(scale=0.1, size=300)
        t = _sparse_table(dense, yr)
        model = LightGBMRegressor(numIterations=20, numLeaves=7, parallelism="serial").fit(t)
        out = model.transform(t)
        pred = out.column("prediction")
        assert np.corrcoef(pred, yr)[0, 1] > 0.8

    def test_booster_csr_predict_matches_dense(self):
        rng = np.random.default_rng(9)
        dense = _random_sparse(rng, 250, 6, density=0.4, nan_frac=0)
        y = (dense.sum(axis=1) > 0).astype(np.float64)
        model = LightGBMClassifier(
            numIterations=10, numLeaves=7, parallelism="serial"
        ).fit(Table({"features": dense, "label": y}))
        b = model.booster
        csr = CSRMatrix.from_dense(dense)
        np.testing.assert_allclose(b.raw_margin(csr), b.raw_margin(dense), rtol=1e-6)
        np.testing.assert_array_equal(b.predict_leaf(csr), b.predict_leaf(dense))
        shap_s = b.features_shap(csr)
        shap_d = b.features_shap(dense)
        np.testing.assert_allclose(shap_s, shap_d, rtol=1e-5, atol=1e-6)

    def test_sparse_shap_column(self):
        rng = np.random.default_rng(10)
        dense = _random_sparse(rng, 120, 4, density=0.5, nan_frac=0)
        y = (dense[:, 0] > 0).astype(np.float64)
        t = _sparse_table(dense, y)
        model = LightGBMClassifier(
            numIterations=5, numLeaves=5, parallelism="serial", featuresShapCol="shap"
        ).fit(t)
        out = model.transform(t)
        shap = out.column("shap")
        assert shap.shape == (120, 5)  # F + bias


class TestSparseFeatureCount:
    def _fit(self):
        rng = np.random.default_rng(11)
        dense = _random_sparse(rng, 300, 6, density=0.4, nan_frac=0)
        y = (dense[:, 0] > 0).astype(np.float64)
        model = LightGBMClassifier(
            numIterations=10, numLeaves=7, parallelism="serial"
        ).fit(_sparse_table(dense, y))
        return model, dense, y

    def test_narrow_predict_batch_keeps_trained_width(self):
        """A predict batch whose explicit indices stop short of the trained F
        must densify to the full width, not silently shrink."""
        model, dense, y = self._fit()
        narrow = dense.copy()
        narrow[:, 4:] = 0.0  # rows now only reach index 3
        out_sparse = model.transform(_sparse_table(narrow, y))
        out_dense = model.transform(Table({"features": narrow, "label": y}))
        np.testing.assert_allclose(
            out_sparse.column("probability"),
            out_dense.column("probability"),
            rtol=1e-6,
        )

    def test_out_of_range_index_raises(self):
        model, dense, y = self._fit()
        col = np.empty(2, dtype=object)
        col[0] = (np.array([0, 2]), np.array([1.0, 1.0]))
        col[1] = (np.array([99]), np.array([1.0]))  # beyond trained F=6
        bad = Table({"features": col, "label": y[:2]})
        with pytest.raises(ValueError, match="out of range"):
            model.transform(bad)


def test_weighted_quantile_matches_numpy_bitwise():
    from mmlspark_tpu.lightgbm.binning import _weighted_quantile

    rng = np.random.default_rng(12)
    qs = np.linspace(0, 1, 64)
    for _ in range(50):
        col = rng.normal(size=rng.integers(5, 500))
        col = np.round(col, 2)  # force repeats
        u, c = np.unique(col, return_counts=True)
        ours = _weighted_quantile(u, c, qs)
        theirs = np.quantile(col, qs, method="linear")
        np.testing.assert_array_equal(ours, theirs)
