"""mmlspark_tpu.runtime — fault-tolerant partition scheduler tests.

Every fault here is *injected deterministically* (seeded FaultPlan keyed
on (task, attempt)), so each test asserts one specific recovery sequence:
the fault fired (``plan.fired``), the job survived it, and — for the
fit-parity tests — the output is bit-identical to the clean run.
"""

import threading
import time

import numpy as np
import pytest

from mmlspark_tpu import runtime
from mmlspark_tpu.data import Table
from mmlspark_tpu.lightgbm import LightGBMClassifier

# tight-but-safe knobs: fast heartbeats, near-zero backoff
FAST = dict(backoff_base=0.01, heartbeat_interval=0.02)


def fast_policy(**kw):
    merged = dict(FAST)
    merged.update(kw)
    return runtime.SchedulerPolicy(**merged)


# ---------------------------------------------------------------------------
# scheduler core
# ---------------------------------------------------------------------------


def test_run_partitioned_happy_path():
    out = runtime.run_partitioned(
        lambda x: x * 10, list(range(8)), fast_policy(max_workers=4)
    )
    assert out == [x * 10 for x in range(8)]


def test_results_ordered_despite_stragglers():
    # task 0 finishes LAST; results still come back in shard order
    def work(x):
        if x == 0:
            time.sleep(0.2)
        return x + 100

    out = runtime.run_partitioned(work, [0, 1, 2, 3], fast_policy(max_workers=4))
    assert out == [100, 101, 102, 103]


def test_retry_on_transient_failure():
    attempts = {"n": 0}
    lock = threading.Lock()

    def flaky(x):
        with lock:
            attempts["n"] += 1
            if attempts["n"] == 1:
                raise ValueError("transient")
        return x

    m = runtime.RuntimeMetrics()
    out = runtime.run_partitioned(
        flaky, [7], fast_policy(max_workers=1), metrics=m
    )
    assert out == [7]
    assert m.retries_total == 1
    assert m.summary()["failures_error"] == 1


def test_retry_exhaustion_fails_job():
    pol = fast_policy(max_workers=1, max_retries=2)
    m = runtime.RuntimeMetrics()
    with pytest.raises(runtime.JobFailedError):
        runtime.run_partitioned(
            lambda x: (_ for _ in ()).throw(ValueError("always")),
            [1], pol, metrics=m,
        )
    # 1 initial + 2 retries, all failed
    assert m.summary()["failures_error"] == 3
    assert m.retries_total == 2


def test_backoff_policy_deterministic_and_bounded():
    p = runtime.SchedulerPolicy(
        seed=42, backoff_base=0.1, backoff_factor=2.0, backoff_jitter=0.25,
        backoff_max=1.0,
    )
    # same (seed, task, failure) -> identical delay; different seed differs
    assert p.backoff(3, 2) == runtime.SchedulerPolicy(
        seed=42, backoff_base=0.1, backoff_factor=2.0, backoff_jitter=0.25,
        backoff_max=1.0,
    ).backoff(3, 2)
    assert p.backoff(3, 2) != runtime.SchedulerPolicy(
        seed=43, backoff_base=0.1, backoff_factor=2.0, backoff_jitter=0.25,
        backoff_max=1.0,
    ).backoff(3, 2)
    # exponential envelope: base * factor^(k-1), plus at most 25% jitter
    for k, expect in ((1, 0.1), (2, 0.2), (3, 0.4)):
        d = p.backoff(0, k)
        assert expect <= d <= expect * 1.25
    # capped at backoff_max (+ jitter)
    assert p.backoff(0, 30) <= 1.0 * 1.25


def test_empty_job():
    assert runtime.run_partitioned(lambda x: x, []) == []


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------


def test_executor_death_retries_and_replaces_worker():
    plan = runtime.FaultPlan(seed=7).kill_task(2)
    m = runtime.RuntimeMetrics()
    out = runtime.run_partitioned(
        lambda x: x * 2, [0, 1, 2, 3],
        fast_policy(max_workers=2, faults=plan), metrics=m,
    )
    assert out == [0, 2, 4, 6]
    assert plan.fired == [("kill", 2, 0)]
    s = m.summary()
    assert s["failures_executor_death"] == 1
    assert s["retries_total"] == 1
    assert s["retries_per_task"] == {2: 1}


def test_executor_death_with_single_worker_respawns():
    # the ONLY worker dies; the driver must notice and spawn a replacement
    # to run the retry (no surviving executor to fall back on)
    plan = runtime.FaultPlan().kill_task(0)
    out = runtime.run_partitioned(
        lambda x: x + 1, [1, 2], fast_policy(max_workers=1, faults=plan)
    )
    assert out == [2, 3]
    assert plan.fired == [("kill", 0, 0)]


def test_kill_random_task_is_seeded():
    v1 = runtime.FaultPlan(seed=5).kill_random_task(32)
    v2 = runtime.FaultPlan(seed=5).kill_random_task(32)
    assert v1._kill.keys() == v2._kill.keys()


def test_heartbeat_loss_redispatch():
    # The executor running task 0 stops heartbeating and hangs; the driver
    # must declare it lost, re-dispatch task 0 elsewhere, and finish.
    plan = runtime.FaultPlan(seed=3).drop_heartbeat(0)
    m = runtime.RuntimeMetrics()
    pol = fast_policy(
        max_workers=2, faults=plan, heartbeat_timeout=0.15
    )
    out = runtime.run_partitioned(lambda x: x + 1, [10, 20, 30], pol, metrics=m)
    assert out == [11, 21, 31]
    assert ("drop_heartbeat", 0, 0) in plan.fired
    s = m.summary()
    assert s["failures_heartbeat"] == 1
    assert s["retries_total"] >= 1


def test_task_timeout_redispatch():
    plan = runtime.FaultPlan().delay_task(1, 0.5)
    m = runtime.RuntimeMetrics()
    pol = fast_policy(max_workers=2, faults=plan, task_timeout=0.1)
    out = runtime.run_partitioned(lambda x: x, [5, 6], pol, metrics=m)
    assert out == [5, 6]
    assert m.summary()["failures_timeout"] == 1


def test_inject_faults_is_ambient():
    plan = runtime.FaultPlan(seed=1).kill_task(0)
    with runtime.inject_faults(plan) as p:
        assert runtime.current_faults() is p
        out = runtime.run_partitioned(
            lambda x: -x, [1, 2], fast_policy(max_workers=2)
        )
    assert runtime.current_faults() is None
    assert out == [-1, -2] and plan.fired


# ---------------------------------------------------------------------------
# lineage
# ---------------------------------------------------------------------------


def test_lineage_recompute_on_lost_partition():
    lin = runtime.Lineage()
    lin.record(0, lambda: 40, lambda v: v + 2, describe="40+2")
    first = {"seen": False}
    lock = threading.Lock()

    def work(x):
        with lock:
            if not first["seen"]:
                first["seen"] = True
                raise runtime.PartitionLostError("input buffer evicted")
        return x * 2

    m = runtime.RuntimeMetrics()
    out = runtime.run_partitioned(
        work, [lin._shards[0]], fast_policy(max_workers=1),
        lineage=lin, metrics=m,
    )
    assert out == [84]
    assert lin.recomputes[0] == 1
    assert m.summary()["lineage_recomputes"] == 1


def test_lineage_materialize_order():
    shard = runtime.ShardLineage(
        source=lambda: [1, 2], transforms=(sorted, tuple)
    )
    assert shard.materialize() == (1, 2)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def test_metrics_per_task_timings_and_retries():
    plan = runtime.FaultPlan().kill_task(1)
    m = runtime.RuntimeMetrics()
    runtime.run_partitioned(
        lambda x: x, [0, 1, 2], fast_policy(max_workers=2, faults=plan),
        metrics=m,
    )
    s = m.summary()
    assert s["tasks_done"] == 3
    assert s["dispatches"] == 4  # 3 tasks + 1 retry
    assert set(s["per_task"]) == {0, 1, 2}
    for t in s["per_task"].values():
        assert t["attempts"] >= 1 and t["run"] >= 0.0 and t["queue_wait"] >= 0.0
    assert s["per_task"][1]["attempts"] == 2
    assert s["retries_per_task"] == {1: 1}
    # phase aggregates ride the embedded StopWatch (core/profiling shape)
    assert set(s["phases"]) >= {"queue_wait", "run"}


# ---------------------------------------------------------------------------
# fault-injected fit parity (the acceptance bar)
# ---------------------------------------------------------------------------


def _fit_table(n=400, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.normal(size=n) > 0).astype(
        np.float64
    )
    return Table({"features": X, "label": y}), X, y


def _auc(y, score):
    order = np.argsort(score)
    ranks = np.empty(len(y))
    ranks[order] = np.arange(1, len(y) + 1)
    pos = y > 0
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def test_fault_injected_fit_bit_identical():
    """A seeded executor kill mid-fit (binning runs on the scheduler) must
    retry/recompute and yield bit-identical model text to the clean run."""
    table, X, y = _fit_table()

    def estimator():
        return LightGBMClassifier(
            numIterations=10, numLeaves=7, parallelism="serial", seed=3,
        )

    clean = estimator().fit(table)
    clean_text = clean.booster.model_to_string()

    plan = runtime.FaultPlan(seed=11).kill_random_task(3)
    est = estimator().setNumExecutors(3)
    with runtime.inject_faults(plan):
        faulted = est.fit(table)

    assert plan.fired and plan.fired[0][0] == "kill"
    assert faulted.booster.model_to_string() == clean_text
    # runtime metrics observed the death + retry
    s = est._runtime_metrics.summary()
    assert s["failures_executor_death"] >= 1 and s["retries_total"] >= 1
    # AUC parity follows from model-text parity; assert it end-to-end anyway
    auc_clean = _auc(y, clean.booster.raw_margin(X).ravel())
    auc_fault = _auc(y, faulted.booster.raw_margin(X).ravel())
    assert auc_fault == auc_clean


def test_heartbeat_loss_during_fit_bit_identical():
    """The network-partitioned-executor variant: suppressed heartbeats on a
    binning task must re-dispatch and still produce the clean model."""
    table, _, _ = _fit_table(n=300)

    def estimator():
        return LightGBMClassifier(
            numIterations=8, numLeaves=7, parallelism="serial", seed=5,
        )

    clean_text = estimator().fit(table).booster.model_to_string()

    plan = runtime.FaultPlan(seed=2).drop_heartbeat(1)
    pol = fast_policy(max_workers=2, heartbeat_timeout=0.15, faults=plan)
    with runtime.policy(pol):
        faulted = estimator().fit(table)
    assert ("drop_heartbeat", 1, 0) in plan.fired
    assert faulted.booster.model_to_string() == clean_text


def test_ambient_policy_routes_binning():
    table, _, _ = _fit_table(n=200)
    est = LightGBMClassifier(
        numIterations=5, numLeaves=5, parallelism="serial", seed=1
    )
    with runtime.policy(max_workers=2, **FAST):
        est.fit(table)
    assert est._runtime_metrics.summary()["tasks_done"] == 2


# ---------------------------------------------------------------------------
# executor pool plumbing
# ---------------------------------------------------------------------------


def test_pool_drain_and_shutdown():
    pool = runtime.ExecutorPool(2, heartbeat_interval=0.02)
    try:
        sched = runtime.Scheduler(pool=pool, policy=fast_policy(max_workers=2))
        assert sched.run(lambda x: x, [1, 2, 3]) == [1, 2, 3]
        assert pool.drain(timeout=2.0)
    finally:
        pool.shutdown()
    with pytest.raises(RuntimeError):
        pool.submit(object())


def test_scheduler_reuse_accumulates_metrics():
    with runtime.Scheduler(policy=fast_policy(max_workers=2)) as sched:
        sched.run(lambda x: x, [1, 2])
        sched.run(lambda x: x, [3, 4, 5])
    assert sched.metrics.summary()["tasks_done"] == 5
