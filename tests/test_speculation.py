"""Compute-plane robustness: speculative execution, executor quarantine,
and durable fit checkpoint/recovery.

Same posture as tests/test_runtime.py: every straggler/failure is
*injected deterministically* (seeded FaultPlan keyed on (task, attempt)),
quarantine/parole runs on a fake clock, and the kill-and-resume tests
assert the headline invariant — a rerun with the same journal performs
ZERO re-executions of committed partitions, with bit-identical results.
"""

import os
import pickle
import threading
import time

import numpy as np
import pytest

from mmlspark_tpu import runtime
from mmlspark_tpu.observability import (
    TaskRecovered,
    TaskSpeculated,
    WorkerParoled,
    WorkerQuarantined,
    format_timeline,
    get_bus,
    replay,
    timeline,
)
from mmlspark_tpu.runtime.health import HealthTracker
from mmlspark_tpu.runtime.journal import FitJournal, ModelStore

# tight-but-safe knobs: fast heartbeats, near-zero backoff
FAST = dict(backoff_base=0.01, heartbeat_interval=0.02)


def fast_policy(**kw):
    merged = dict(FAST)
    merged.update(kw)
    return runtime.SchedulerPolicy(**merged)


class FakeClock:
    """Monotonic clock whose time only moves when told, so quarantine
    and parole boundaries are exact (no real sleeps)."""

    def __init__(self, start: float = 1000.0):
        self.t = start

    def now(self) -> float:
        return self.t

    def advance(self, seconds: float) -> None:
        self.t += seconds


# ---------------------------------------------------------------------------
# speculative execution
# ---------------------------------------------------------------------------


class TestSpeculation:
    def test_straggler_overtaken_bit_identical(self):
        # clean run first: the reference output
        shards = [np.arange(16, dtype=np.float64) + i for i in range(4)]
        expect = runtime.run_partitioned(
            lambda x: np.sqrt(x) * 2.0, shards, fast_policy(max_workers=2)
        )

        # task 3 straggles 30 s (cancellable); speculation must overtake
        events = []
        bus = get_bus()
        bus.add_listener(events.append)
        plan = runtime.FaultPlan(seed=11).slow_task(3, 30.0)
        m = runtime.RuntimeMetrics()
        try:
            t0 = time.monotonic()
            out = runtime.run_partitioned(
                lambda x: np.sqrt(x) * 2.0,
                shards,
                fast_policy(
                    max_workers=2, speculation=True,
                    speculation_multiplier=1.5, speculation_quantile=0.5,
                    faults=plan,
                ),
                metrics=m,
            )
            elapsed = time.monotonic() - t0
        finally:
            bus.remove_listener(events.append)
        # the straggler fault fired AND the job finished long before 30 s
        assert ("slow_task", 3, 0) in plan.fired
        assert elapsed < 10.0
        # bit-identical to the clean run, in shard order
        for got, want in zip(out, expect):
            assert got.tobytes() == want.tobytes()
        s = m.summary()
        assert s["speculative_launched"] >= 1
        assert s["speculative_wins"] >= 1
        spec = [e for e in events if isinstance(e, TaskSpeculated)]
        assert spec and spec[0].task_id == 3
        assert spec[0].age > spec[0].median

    def test_speculative_copy_runs_on_different_worker(self):
        seen = {}
        lock = threading.Lock()

        def work(x):
            with lock:
                seen.setdefault(x, []).append(threading.current_thread().name)
            if x == 3:
                # first attempt of task 3 straggles via the fault plan
                pass
            return x

        plan = runtime.FaultPlan(seed=3).slow_task(3, 30.0)
        out = runtime.run_partitioned(
            work, [0, 1, 2, 3],
            fast_policy(
                max_workers=2, speculation=True, speculation_quantile=0.5,
                faults=plan,
            ),
        )
        assert out == [0, 1, 2, 3]
        # the straggling task ran (at least) twice, on distinct workers
        assert len(seen[3]) >= 2
        assert len(set(seen[3])) >= 2

    def test_no_speculation_below_quantile(self):
        # every task straggles equally -> no completed median to compare
        # against until they finish; with quantile 1.0 nothing speculates
        m = runtime.RuntimeMetrics()
        out = runtime.run_partitioned(
            lambda x: x, [0, 1, 2, 3],
            fast_policy(
                max_workers=2, speculation=True, speculation_quantile=1.0
            ),
            metrics=m,
        )
        assert out == [0, 1, 2, 3]
        assert m.summary()["speculative_launched"] == 0


# ---------------------------------------------------------------------------
# result integrity (end-to-end CRC)
# ---------------------------------------------------------------------------


class TestResultIntegrity:
    def test_corrupt_result_detected_and_retried(self):
        plan = runtime.FaultPlan(seed=5).corrupt_result(1)
        m = runtime.RuntimeMetrics()
        shards = [np.arange(8, dtype=np.float64) + i for i in range(3)]
        out = runtime.run_partitioned(
            lambda x: x * 3.0, shards,
            fast_policy(max_workers=2, faults=plan), metrics=m,
        )
        assert ("corrupt_result", 1, 0) in plan.fired
        # the retry computed a clean copy — values are exact
        assert out[1].tobytes() == (shards[1] * 3.0).tobytes()
        s = m.summary()
        assert s["failures_corrupt"] == 1
        assert s["retries_total"] >= 1

    def test_result_integrity_policy_checksums_everything(self):
        # no fault: result_integrity=True just verifies every result
        out = runtime.run_partitioned(
            lambda x: x + 1, [1, 2, 3],
            fast_policy(max_workers=2, result_integrity=True),
        )
        assert out == [2, 3, 4]


# ---------------------------------------------------------------------------
# health tracking + quarantine
# ---------------------------------------------------------------------------


class TestHealthTracker:
    def test_quarantine_after_threshold_and_parole(self):
        clock = FakeClock()
        ht = HealthTracker(
            threshold=3.0, window_s=60.0, parole_s=30.0, clock=clock.now
        )
        ht.note_failure(1, "error")
        ht.note_failure(1, "error")
        assert not ht.is_quarantined(1)
        ht.note_failure(1, "error")
        assert ht.is_quarantined(1)
        assert ht.quarantined_workers() == {1}
        # parole: exactly at +30 s the worker rejoins with a clean slate
        clock.advance(29.9)
        assert ht.is_quarantined(1)
        clock.advance(0.2)
        assert not ht.is_quarantined(1)
        assert ht.score(1) == 0.0
        assert ht.paroles == 1

    def test_rolling_window_forgets_old_failures(self):
        clock = FakeClock()
        ht = HealthTracker(threshold=3.0, window_s=10.0, clock=clock.now)
        ht.note_failure(2, "error")
        ht.note_failure(2, "error")
        clock.advance(11.0)  # both age out of the window
        ht.note_failure(2, "error")
        assert not ht.is_quarantined(2)
        assert ht.score(2) == 1.0

    def test_straggles_count_at_a_discount(self):
        clock = FakeClock()
        ht = HealthTracker(
            threshold=2.0, straggle_weight=0.5, clock=clock.now
        )
        for _ in range(3):
            ht.note_straggle(4)
        assert not ht.is_quarantined(4)  # 1.5 < 2.0
        ht.note_straggle(4)
        assert ht.is_quarantined(4)  # 2.0 >= 2.0

    def test_all_quarantined_and_next_parole(self):
        clock = FakeClock()
        ht = HealthTracker(threshold=1.0, parole_s=30.0, clock=clock.now)
        assert not ht.all_quarantined([])  # vacuous truth would fail-fast
        ht.note_failure(1, "error")
        assert ht.all_quarantined([1])
        assert not ht.all_quarantined([1, 2])
        assert ht.next_parole_in() == pytest.approx(30.0)

    def test_threshold_must_be_positive(self):
        with pytest.raises(ValueError):
            HealthTracker(threshold=0.0)


class TestQuarantineIntegration:
    def test_failing_worker_quarantined_no_dispatch_until_parole(self):
        """A worker with 3 injected failures receives no further attempts
        until its parole elapses (fake clock; no real parole sleeps)."""
        clock = FakeClock()
        ht = HealthTracker(
            threshold=3.0, window_s=60.0, parole_s=30.0, clock=clock.now
        )
        pol = fast_policy(
            max_workers=2, max_retries=6, quarantine_fail_fast=False
        )
        sched = runtime.Scheduler(policy=pol, health=ht)
        try:
            workers_used = []
            lock = threading.Lock()
            state = {"bad": None, "fails": 0}

            def flaky(x):
                # worker-affine fault — the shape quarantine exists to
                # contain: the first worker to pull ANY task fails every
                # attempt it is given. The healthy worker parks its task
                # until quarantine fires, so every retry funnels back to
                # the bad worker until its third strike. Deterministic.
                wid = int(threading.current_thread().name.rsplit("-", 1)[-1])
                with lock:
                    workers_used.append(wid)
                    if state["bad"] is None:
                        state["bad"] = wid
                    if wid == state["bad"]:
                        state["fails"] += 1
                        raise ValueError("injected")
                deadline = time.monotonic() + 10.0
                while ht.quarantines == 0 and time.monotonic() < deadline:
                    time.sleep(0.002)
                return x

            out = sched.run(flaky, [0, 1, 2, 3])
            assert out == [0, 1, 2, 3]
            # the bad worker absorbed exactly 3 failures (admission control
            # cut it off the instant it crossed the threshold)
            assert state["fails"] == 3
            assert ht.quarantines == 1
            quarantined = ht.quarantined_workers()
            assert len(quarantined) == 1
            bad = next(iter(quarantined))
            assert bad == state["bad"]
            # while quarantined, a fresh job dispatches nothing to it
            before = len([w for w in workers_used if w == bad])
            out2 = sched.run(lambda x: x * 2, [0, 1, 2, 3])
            assert out2 == [0, 2, 4, 6]
            after = len([w for w in workers_used if w == bad])
            assert after == before  # zero new dispatches on the quarantined worker
            # parole: advance the fake clock past parole_s and it rejoins
            clock.advance(30.1)
            assert not ht.is_quarantined(bad)
            assert ht.paroles == 1
        finally:
            sched.close()

    def test_all_quarantined_fails_fast_with_clear_error(self):
        events = []
        bus = get_bus()
        bus.add_listener(events.append)
        try:
            pol = fast_policy(
                max_workers=1, max_retries=8,
                quarantine_threshold=2.0, parole_s=60.0,
            )
            with pytest.raises(runtime.AllWorkersQuarantinedError) as ei:
                runtime.run_partitioned(
                    lambda x: (_ for _ in ()).throw(ValueError("boom")),
                    [0], pol,
                )
            assert "quarantined" in str(ei.value)
            assert "parole" in str(ei.value)
            # the error IS a JobFailedError and carries structured history
            assert isinstance(ei.value, runtime.JobFailedError)
            hist = ei.value.history[0]
            assert all(a.reason == "error" for a in hist)
            assert all(a.worker > 0 for a in hist)
            assert [e for e in events if isinstance(e, WorkerQuarantined)]
        finally:
            bus.remove_listener(events.append)


# ---------------------------------------------------------------------------
# structured failure history
# ---------------------------------------------------------------------------


class TestAttemptHistory:
    def test_job_failed_error_carries_attempt_history(self):
        pol = fast_policy(max_workers=1, max_retries=2)
        with pytest.raises(runtime.JobFailedError) as ei:
            runtime.run_partitioned(
                lambda x: (_ for _ in ()).throw(ValueError("always")), [5], pol
            )
        hist = ei.value.history
        assert list(hist) == [0]
        infos = hist[0]
        assert len(infos) == 3  # 1 initial + 2 retries
        assert [a.attempt for a in infos] == [0, 1, 2]
        assert all(a.reason == "error" for a in infos)
        assert all(a.worker > 0 for a in infos)
        assert all(not a.speculative for a in infos)
        text = ei.value.describe()
        assert "task 0: attempt 0" in text and "error" in text

    def test_format_timeline_renders_attempts_and_quarantines(self, tmp_path, monkeypatch):
        path = tmp_path / "events.jsonl"
        monkeypatch.setenv("MMLSPARK_TPU_EVENT_LOG", str(path))
        get_bus()  # attach the sink
        try:
            pol = fast_policy(
                max_workers=1, max_retries=4,
                quarantine_threshold=2.0, parole_s=60.0,
            )
            with pytest.raises(runtime.AllWorkersQuarantinedError):
                runtime.run_partitioned(
                    lambda x: (_ for _ in ()).throw(ValueError("no")), [0], pol
                )
        finally:
            monkeypatch.delenv("MMLSPARK_TPU_EVENT_LOG")
            get_bus()  # detach + close the sink
        summary = timeline(replay(str(path)))
        assert summary["tasks"]["attempts"][0][0]["reason"] == "error"
        assert summary["quarantines"]
        text = format_timeline(summary)
        assert "attempt 0" in text
        assert "quarantine" in text


# ---------------------------------------------------------------------------
# durable journal: kill-and-resume with zero re-execution
# ---------------------------------------------------------------------------


class TestFitJournal:
    def test_resume_with_zero_reexecution(self, tmp_path):
        shards = [np.arange(6, dtype=np.float64) + i for i in range(4)]
        calls = []
        lock = threading.Lock()

        def work(x):
            with lock:
                calls.append(float(x[0]))
            return x * 2.0

        j1 = FitJournal(str(tmp_path), key="job-a", num_tasks=4)
        first = runtime.run_partitioned(
            work, shards, fast_policy(max_workers=2), journal=j1
        )
        j1.close()
        assert j1.appended == 4 and len(calls) == 4

        # "new process": a fresh journal on the same dir restores all four
        events = []
        bus = get_bus()
        bus.add_listener(events.append)
        try:
            j2 = FitJournal(str(tmp_path), key="job-a", num_tasks=4)
            second = runtime.run_partitioned(
                work, shards, fast_policy(max_workers=2), journal=j2
            )
            j2.close()
        finally:
            bus.remove_listener(events.append)
        assert len(calls) == 4  # ZERO re-executions
        assert j2.appended == 0
        for a, b in zip(first, second):
            assert a.tobytes() == b.tobytes()  # bit-identical restore
        recovered = [e for e in events if isinstance(e, TaskRecovered)]
        assert sorted(e.task_id for e in recovered) == [0, 1, 2, 3]

    def test_partial_crash_resumes_only_missing_tasks(self, tmp_path):
        """Simulated mid-job death: tasks 0/2 committed before the crash;
        the rerun executes ONLY 1/3."""
        shards = [10.0, 11.0, 12.0, 13.0]
        j1 = FitJournal(str(tmp_path), key="job-b", num_tasks=4)
        j1.record(0, 20.0)
        j1.record(2, 24.0)
        j1.close()

        calls = []
        lock = threading.Lock()

        def work(x):
            with lock:
                calls.append(x)
            return x * 2.0

        j2 = FitJournal(str(tmp_path), key="job-b", num_tasks=4)
        out = runtime.run_partitioned(
            work, shards, fast_policy(max_workers=2), journal=j2
        )
        j2.close()
        assert out == [20.0, 22.0, 24.0, 26.0]
        assert sorted(calls) == [11.0, 13.0]
        assert j2.appended == 2

    def test_corrupt_checkpoint_recomputes_that_task(self, tmp_path):
        j1 = FitJournal(str(tmp_path), key="job-c", num_tasks=2)
        j1.record(0, "alpha")
        j1.record(1, "beta")
        j1.close()
        # bit-rot one checkpoint body
        victim = os.path.join(j1.dir, "task-00001.ckpt")
        blob = bytearray(open(victim, "rb").read())
        blob[-1] ^= 0xFF
        with open(victim, "wb") as fh:
            fh.write(bytes(blob))

        j2 = FitJournal(str(tmp_path), key="job-c", num_tasks=2)
        restored = j2.restore()
        assert restored == {0: "alpha"}  # corrupt entry dropped, not served
        j2.close()

    def test_torn_tail_journal_line_is_ignored(self, tmp_path):
        j1 = FitJournal(str(tmp_path), key="job-d", num_tasks=2)
        j1.record(0, 1.5)
        j1.close()
        with open(os.path.join(j1.dir, "journal.jsonl"), "a") as fh:
            fh.write('{"task": 1, "ck')  # crash mid-append
        j2 = FitJournal(str(tmp_path), key="job-d", num_tasks=2)
        assert j2.restore() == {0: 1.5}
        j2.close()

    def test_stale_key_or_task_count_resets(self, tmp_path):
        j1 = FitJournal(str(tmp_path), key="job-e", num_tasks=3)
        j1.record(0, "x")
        j1.close()
        # same key, different partitioning: stale — must start clean
        j2 = FitJournal(str(tmp_path), key="job-e", num_tasks=5)
        assert j2.restore() == {}
        j2.close()

    def test_record_is_idempotent(self, tmp_path):
        j = FitJournal(str(tmp_path), key="job-f", num_tasks=1)
        assert j.record(0, "once") is True
        assert j.record(0, "twice") is False  # raced/duplicate: not rewritten
        j.close()
        j2 = FitJournal(str(tmp_path), key="job-f", num_tasks=1)
        assert j2.restore() == {0: "once"}
        j2.close()

    def test_revalidate_rejects_restored_result(self, tmp_path):
        j1 = FitJournal(str(tmp_path), key="job-g", num_tasks=2)
        j1.record(0, -1.0)  # poisoned checkpoint (fails revalidation)
        j1.record(1, 12.0)
        j1.close()
        calls = []
        j2 = FitJournal(str(tmp_path), key="job-g", num_tasks=2)
        out = runtime.run_partitioned(
            lambda x: calls.append(x) or x * 2.0,
            [5.0, 6.0],
            fast_policy(max_workers=1),
            journal=j2,
            revalidate=lambda i, r: r >= 0,
        )
        j2.close()
        assert out == [10.0, 12.0]
        assert calls == [5.0]  # only the rejected task re-ran


class TestModelStore:
    def test_commit_and_latest_roundtrip(self, tmp_path):
        store = ModelStore(str(tmp_path))
        assert store.latest() is None
        assert store.commit("tree v1") == 1
        assert store.commit("tree v2") == 2
        version, text = store.latest()
        assert (version, text) == (2, "tree v2")

    def test_torn_current_falls_back_to_newest_verified(self, tmp_path):
        store = ModelStore(str(tmp_path))
        store.commit("good one")
        store.commit("good two")
        # crash mid-commit: CURRENT points at a file that fails its CRC
        with open(os.path.join(str(tmp_path), "model-000002.txt"), "w") as fh:
            fh.write("torn garba")
        version, text = store.latest()
        assert (version, text) == (1, "good one")

    def test_missing_current_scans_versions(self, tmp_path):
        store = ModelStore(str(tmp_path))
        store.commit("only")
        os.remove(os.path.join(str(tmp_path), "model.CURRENT"))
        assert ModelStore(str(tmp_path)).latest() == (1, "only")


# ---------------------------------------------------------------------------
# durable fit + warm restart, end to end
# ---------------------------------------------------------------------------


class TestDurableFitEndToEnd:
    def _table(self):
        from mmlspark_tpu.data import Table

        rng = np.random.default_rng(0)
        X = rng.normal(size=(120, 4))
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
        return Table({"features": X, "label": y}), X

    def test_fit_commits_model_and_server_warm_restarts(self, tmp_path, monkeypatch):
        from mmlspark_tpu.lightgbm import LightGBMClassifier
        from mmlspark_tpu.serving import recover_model, warm_restart_server

        monkeypatch.setenv("MMLSPARK_TPU_CHECKPOINT_DIR", str(tmp_path))
        table, X = self._table()
        est = LightGBMClassifier(numIterations=5, numLeaves=4, numTasks=2)
        model = est.fit(table)
        # the fit committed its model text atomically under the root:
        # the stored bytes are exactly what the fitted model serialises to
        name = type(model).__name__.lower()
        store = ModelStore(os.path.join(str(tmp_path), "models"))
        assert store.latest(name) == (1, model.get_model_string())
        # recovery rebuilds a model that predicts identically
        recovered = recover_model(type(model).from_model_string, name=name)
        assert recovered is not None
        version, warm = recovered
        assert version == 1
        np.testing.assert_allclose(
            warm.booster.raw_margin(X), model.booster.raw_margin(X),
            rtol=1e-5, atol=1e-6,
        )
        # and a warm-restarted server serves it
        srv = warm_restart_server(type(model).from_model_string, name=name)
        np.testing.assert_allclose(
            srv.model.booster.raw_margin(X), model.booster.raw_margin(X),
            rtol=1e-5, atol=1e-6,
        )

    def test_binning_journal_resumes_partitioned_fit(self, tmp_path, monkeypatch):
        from mmlspark_tpu.lightgbm import LightGBMClassifier

        monkeypatch.setenv("MMLSPARK_TPU_CHECKPOINT_DIR", str(tmp_path))
        table, X = self._table()

        def fit_once():
            est = LightGBMClassifier(
                numIterations=5, numLeaves=4, numExecutors=2
            )
            return est.fit(table)

        m1 = fit_once()
        binning_root = os.path.join(str(tmp_path), "binning")
        [job_dir] = os.listdir(binning_root)
        journal = os.path.join(binning_root, job_dir, "journal.jsonl")
        lines_before = len(open(journal).read().splitlines())
        assert lines_before >= 1
        # rerun (same params + data): binning restores from checkpoints —
        # the journal gains no new lines, and the model is bit-identical
        m2 = fit_once()
        lines_after = len(open(journal).read().splitlines())
        assert lines_after == lines_before
        assert m1.get_model_string() == m2.get_model_string()


# ---------------------------------------------------------------------------
# shard CRC sidecars
# ---------------------------------------------------------------------------


class TestShardChecksums:
    def test_write_shards_emits_sidecars_and_loads_verify(self, tmp_path):
        from mmlspark_tpu.data.sharded import ShardedDataset

        X = np.arange(40, dtype=np.float64).reshape(10, 4)
        y = np.arange(10, dtype=np.float64)
        ds = ShardedDataset.write_shards(
            str(tmp_path), X, y, rows_per_shard=5
        )
        for p in ds.paths:
            assert os.path.exists(p + ".crc32")
        # clean load works
        total = sum(len(sx) for sx, _, _ in ds.iter_shards())
        assert total == 10

    def test_corrupt_shard_raises_partition_lost(self, tmp_path):
        from mmlspark_tpu.data.sharded import ShardedDataset
        from mmlspark_tpu.runtime.lineage import PartitionLostError

        X = np.arange(40, dtype=np.float64).reshape(10, 4)
        y = np.arange(10, dtype=np.float64)
        ds = ShardedDataset.write_shards(
            str(tmp_path), X, y, rows_per_shard=5
        )
        blob = bytearray(open(ds.paths[0], "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        with open(ds.paths[0], "wb") as fh:
            fh.write(bytes(blob))
        with pytest.raises(PartitionLostError, match="CRC"):
            list(ds.iter_shards())
