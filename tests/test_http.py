"""io/http tests — real in-process servers + real clients, the reference's
serving-suite pattern (SURVEY.md §4)."""

import threading

import numpy as np
import pytest

from http_mock import MockService
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.io.http import (
    AsyncHTTPClient,
    HTTPClient,
    HTTPRequestData,
    HTTPTransformer,
    JSONInputParser,
    JSONOutputParser,
    PartitionConsolidator,
    SimpleHTTPTransformer,
    StringOutputParser,
)


class TestClients:
    def test_roundtrip(self):
        with MockService() as svc:
            resp = HTTPClient().send(
                HTTPRequestData.from_json(svc.url, {"x": 1})
            )
            assert resp.status_code == 200
            assert resp.json() == {"echo": {"x": 1}}

    def test_retry_on_429_with_retry_after(self):
        calls = {"n": 0}

        def behavior(path, body):
            calls["n"] += 1
            if calls["n"] == 1:
                return 429, {"error": "throttled"}, {"Retry-After": "0.05"}
            return 200, {"ok": True}, {}

        with MockService(behavior) as svc:
            resp = HTTPClient(retries=(0.01,)).send(
                HTTPRequestData.from_json(svc.url, {})
            )
            assert resp.status_code == 200 and calls["n"] == 2

    def test_gives_up_after_retries(self):
        with MockService(lambda p, b: (503, {}, {})) as svc:
            resp = HTTPClient(retries=(0.01, 0.01)).send(
                HTTPRequestData.from_json(svc.url, {})
            )
            assert resp.status_code == 503

    def test_async_order_and_nulls(self):
        with MockService(lambda p, b: (200, {"v": b["i"]}, {})) as svc:
            reqs = [
                None if i % 3 == 0 else HTTPRequestData.from_json(svc.url, {"i": i})
                for i in range(10)
            ]
            out = AsyncHTTPClient(concurrency=4).send_all(reqs)
            for i, r in enumerate(out):
                if i % 3 == 0:
                    assert r is None
                else:
                    assert r.json() == {"v": i}


class TestTransformers:
    def test_http_transformer(self):
        with MockService() as svc:
            t = Table({"req": np.array(
                [HTTPRequestData.from_json(svc.url, {"i": i}) for i in range(5)],
                dtype=object,
            )})
            out = HTTPTransformer(inputCol="req", outputCol="resp").transform(t)
            assert all(r.status_code == 200 for r in out["resp"])

    def test_simple_http_transformer(self):
        with MockService(lambda p, b: (200, {"sentiment": "pos"}, {})) as svc:
            t = Table({"text": np.array(["a", "b"], dtype=object)})
            out = SimpleHTTPTransformer(
                inputCol="text",
                outputCol="parsed",
                inputParser=JSONInputParser(url=svc.url),
                outputParser=JSONOutputParser(),
            ).transform(t)
            assert out["parsed"][0] == {"sentiment": "pos"}
            assert out["parsed_error"][0] is None

    def test_simple_http_error_column(self):
        def behavior(path, body):
            if body == "bad":
                return 400, {"error": "nope"}, {}
            return 200, {"ok": True}, {}

        with MockService(behavior) as svc:
            t = Table({"text": np.array(["good", "bad"], dtype=object)})
            out = SimpleHTTPTransformer(
                inputCol="text",
                outputCol="parsed",
                inputParser=JSONInputParser(url=svc.url),
                outputParser=JSONOutputParser(),
            ).transform(t)
            assert out["parsed"][0] == {"ok": True}
            assert out["parsed"][1] is None
            assert "400" in out["parsed_error"][1]

    def test_string_output_parser(self):
        with MockService(lambda p, b: (200, {"x": 1}, {})) as svc:
            t = Table({"text": np.array(["q"], dtype=object)})
            out = SimpleHTTPTransformer(
                inputCol="text",
                outputCol="raw",
                inputParser=JSONInputParser(url=svc.url),
                outputParser=StringOutputParser(),
            ).transform(t)
            assert out["raw"][0] == '{"x": 1}'

    def test_partition_consolidator_shares_client(self):
        with MockService() as svc:
            reqs = np.array(
                [HTTPRequestData.from_json(svc.url, {"i": i}) for i in range(4)],
                dtype=object,
            )
            t = Table({"req": reqs})
            c = PartitionConsolidator(inputCol="req", outputCol="resp", concurrency=2)
            out1 = c.transform(t)
            out2 = c.transform(t)
            assert all(r.status_code == 200 for r in out1["resp"])
            assert all(r.status_code == 200 for r in out2["resp"])
