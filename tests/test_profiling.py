"""Profiling/logging utilities (SURVEY.md §5 tracing/profiling)."""

import logging
import os

import numpy as np
import pytest

from mmlspark_tpu.core.profiling import StopWatch, annotate, get_logger, profile_trace


class TestStopWatch:
    def test_accumulates_phases(self):
        sw = StopWatch()
        with sw.measure("a"):
            pass
        with sw.measure("a"):
            pass
        with sw.measure("b"):
            pass
        s = sw.summary()
        assert set(s) == {"a", "b"}
        assert s["a"] >= 0 and s["b"] >= 0

    def test_log_emits(self, caplog, monkeypatch):
        sw = StopWatch()
        with sw.measure("phase"):
            pass
        logger = get_logger("mmlspark_tpu.test")
        # the framework root doesn't propagate (own stderr handler); let
        # caplog see records for the assertion
        monkeypatch.setattr(logging.getLogger("mmlspark_tpu"), "propagate", True)
        with caplog.at_level(logging.INFO, logger="mmlspark_tpu"):
            sw.log(logger)
        assert any("phase" in r.message for r in caplog.records)


class TestTrace:
    def test_profile_trace_writes_artifacts(self, tmp_path):
        import jax
        import jax.numpy as jnp

        out = str(tmp_path / "xprof")
        with profile_trace(out):
            with annotate("matmul-region"):
                x = jnp.ones((64, 64))
                jax.block_until_ready(x @ x)
        # the profiler lays out plugins/profile/<run>/...
        found = []
        for root, _, files in os.walk(out):
            found.extend(files)
        assert found, "no trace artifacts written"

    def test_annotation_noop_outside_trace(self):
        with annotate("free-standing"):
            assert True


def test_logger_level_env(monkeypatch):
    # fresh root handler picks the env level
    root = logging.getLogger("mmlspark_tpu")
    for h in list(root.handlers):
        root.removeHandler(h)
    monkeypatch.setenv("MMLSPARK_TPU_LOGLEVEL", "INFO")
    logger = get_logger("mmlspark_tpu.x")
    assert logging.getLogger("mmlspark_tpu").level == logging.INFO
