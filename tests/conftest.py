"""Test harness configuration.

Forces an 8-virtual-device CPU platform so every distributed code path
(shard_map/psum over the mesh) is exercised without TPU hardware — the
analogue of the reference running multi-worker LightGBM on `local[*]`
partitions (SURVEY.md §4 "Distributed behavior without a real cluster").

Must run before any jax import, hence the env mutation at module import time.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The container's sitecustomize may have already initialized a TPU backend at
# interpreter startup; tear it down and re-point JAX at the virtual-CPU fleet.
from mmlspark_tpu.parallel.mesh import force_platform  # noqa: E402

force_platform("cpu", min_devices=8)

import jax  # noqa: E402

assert len(jax.devices()) == 8, jax.devices()

import numpy as np
import pytest


@pytest.fixture(scope="session")
def mesh8():
    from mmlspark_tpu.parallel import make_mesh

    return make_mesh()


@pytest.fixture(autouse=True, scope="module")
def _bound_compiled_program_accumulation():
    """Evict compiled-program caches after every test module.

    The full suite in ONE process accumulates hundreds of XLA:CPU
    executables; past a threshold the compiler itself segfaults inside
    ``backend_compile_and_load`` while building the next big shard_map
    program (reproduced deterministically at ~300 tests on the
    voting-parallel training step; neither half of the suite alone
    triggers it, and the CI shard layout used to mask it). Module scope
    keeps within-file program reuse intact while bounding the process-wide
    footprint — the same ``mmlspark_tpu.clear_compiled_caches()`` a
    long-lived production process should call between workloads.
    """
    yield
    import mmlspark_tpu

    mmlspark_tpu.clear_compiled_caches()


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


def assert_tables_equal(a, b, rtol=1e-5, atol=1e-6):
    """Tolerant Table equality — the `DataFrameEquality` analogue
    (reference `core/test/base/TestBase.scala:244-316`)."""
    assert a.columns == b.columns, f"{a.columns} != {b.columns}"
    assert a.num_rows == b.num_rows
    for name in a.columns:
        ca, cb = a[name], b[name]
        if ca.dtype == object or cb.dtype == object:
            assert list(map(str, ca.ravel())) == list(map(str, cb.ravel())), name
        elif np.issubdtype(ca.dtype, np.floating):
            np.testing.assert_allclose(
                ca.astype(float), cb.astype(float), rtol=rtol, atol=atol, err_msg=name
            )
        else:
            np.testing.assert_array_equal(ca, cb, err_msg=name)


@pytest.fixture()
def table_equal():
    return assert_tables_equal


@pytest.fixture()
def basic_table():
    """`makeBasicDF` fixture analogue (TestBase.scala:191-205)."""
    from mmlspark_tpu.data.table import Table

    return Table(
        {
            "numbers": np.array([0, 1, 2, 3], dtype=np.int64),
            "doubles": np.array([0.0, 1.5, 2.5, 3.5]),
            "words": np.array(["guitars", "drums", "bass", "keys"], dtype=object),
        }
    )
