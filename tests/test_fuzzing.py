"""Fuzzing meta-suite — the ``FuzzingTest.scala:27-197`` analogue.

Reflectively discovers every concrete public PipelineStage subclass in the
package and enforces that each one (a) has a fixture in
``tests/fuzzing_objects.py``, (b) is produced by a fixtured estimator's
``fit`` (``fit_produces``), or (c) carries an explicit exemption with a
reason. For every fixture the suite then runs the two reference fuzzing
traits: ExperimentFuzzing (fit/transform executes) and SerializationFuzzing
(save/load roundtrips preserve params and transform output).

Adding a new stage without a fixture fails ``test_every_stage_is_covered``
— the honesty-keeping mechanism SURVEY.md §4 calls out.
"""

import importlib
import inspect
import pkgutil

import numpy as np
import pytest

import mmlspark_tpu
from mmlspark_tpu.core.pipeline import Estimator, PipelineStage

from fuzzing_objects import EXEMPT, TEST_OBJECTS, TestObject

_SKIP_MODULES = ("mmlspark_tpu.cognitive",)  # service stubs fuzzed in test_cognitive


def discover_stage_classes():
    """Every concrete public PipelineStage subclass in the package."""
    found = {}
    for m in pkgutil.walk_packages(mmlspark_tpu.__path__, "mmlspark_tpu."):
        if m.name.startswith(_SKIP_MODULES):
            continue
        mod = importlib.import_module(m.name)
        for name, obj in vars(mod).items():
            if (
                inspect.isclass(obj)
                and issubclass(obj, PipelineStage)
                and obj.__module__ == m.name
                and not name.startswith("_")
                and not inspect.isabstract(obj)
            ):
                found[f"{obj.__module__}.{name}"] = obj
    return found


DISCOVERED = discover_stage_classes()
_PRODUCED = set()
for _fx_name, _fx in TEST_OBJECTS.items():
    pass  # fit_produces is declared per-fixture; resolved lazily in the test


def _produced_model_names():
    names = set()
    for maker in TEST_OBJECTS.values():
        obj = maker()
        if obj.fit_produces:
            names.add(obj.fit_produces)
    return names


def test_every_stage_is_covered():
    produced = _produced_model_names()
    missing = []
    for qual in sorted(DISCOVERED):
        if qual in TEST_OBJECTS or qual in EXEMPT or qual in produced:
            continue
        missing.append(qual)
    assert not missing, (
        "stages without fuzzing coverage (add a fixture to "
        f"tests/fuzzing_objects.py or an EXEMPT reason): {missing}"
    )


def test_no_stale_entries():
    stale = [q for q in list(TEST_OBJECTS) + list(EXEMPT) if q not in DISCOVERED]
    assert not stale, f"fixtures/exemptions for classes that no longer exist: {stale}"


def _approx_equal(x, y):
    """Recursive tolerant equality over scalars/arrays/dicts/sequences —
    serde may turn np.float64 into float, tuples into lists, etc."""
    if isinstance(x, dict) and isinstance(y, dict):
        assert set(x) == set(y), (x, y)
        for k in x:
            _approx_equal(x[k], y[k])
        return
    if isinstance(x, (list, tuple)) and isinstance(y, (list, tuple)):
        assert len(x) == len(y), (x, y)
        for xi, yi in zip(x, y):
            _approx_equal(xi, yi)
        return
    xa, ya = np.asarray(x), np.asarray(y)
    if xa.dtype.kind in "fc" and xa.shape == ya.shape:
        np.testing.assert_allclose(xa, ya, rtol=1e-5, atol=1e-6)
    elif xa.dtype.kind in "iub" and ya.dtype.kind in "iubfc":
        np.testing.assert_allclose(
            xa.astype(np.float64), ya.astype(np.float64), rtol=1e-5
        )
    else:
        assert str(x) == str(y)


def _tables_close(a, b):
    assert set(a.columns) == set(b.columns), (a.columns, b.columns)
    for c in a.columns:
        ca, cb = a.column(c), b.column(c)
        if ca.dtype == object or cb.dtype == object:
            assert len(ca) == len(cb)
            for x, y in zip(ca, cb):
                _approx_equal(x, y)
        elif ca.dtype.kind in "fc":
            np.testing.assert_allclose(ca, cb, rtol=1e-5, atol=1e-6)
        else:
            np.testing.assert_array_equal(ca, cb)


@pytest.fixture(params=sorted(TEST_OBJECTS), ids=lambda q: q.rsplit(".", 1)[-1])
def test_object(request) -> TestObject:
    return TEST_OBJECTS[request.param]()


def test_experiment_fuzzing(test_object):
    """Fit/transform executes without error (ExperimentFuzzing,
    Fuzzing.scala:75-103)."""
    stage = test_object.stage
    table = test_object.table
    tt = test_object.transform_table or table
    if isinstance(stage, Estimator):
        model = stage.fit(table)
        if test_object.fit_produces:
            got = f"{type(model).__module__}.{type(model).__qualname__}"
            assert got == test_object.fit_produces, got
        if test_object.check_transform:
            out = model.transform(tt)
            assert out.num_rows >= 0
    elif test_object.check_transform:
        out = stage.transform(tt)
        assert out.num_rows >= 0


def test_serialization_fuzzing(test_object, tmp_path):
    """Save/load roundtrip of the stage (and fitted model) preserves the
    transform (SerializationFuzzing, Fuzzing.scala:105-181)."""
    stage = test_object.stage
    table = test_object.table
    tt = test_object.transform_table or table

    p1 = str(tmp_path / "stage")
    stage.save(p1)
    reloaded = type(stage).load(p1)
    assert type(reloaded) is type(stage)

    if isinstance(stage, Estimator):
        model = stage.fit(table)
        p2 = str(tmp_path / "model")
        model.save(p2)
        model2 = type(model).load(p2)
        if test_object.check_transform:
            _tables_close(model.transform(tt), model2.transform(tt))
    elif test_object.check_transform:
        _tables_close(stage.transform(tt), reloaded.transform(tt))


def test_ci_shards_cover_every_test_file():
    """Every tests/test_*.py must appear in a CI shard — a new test file
    that CI never runs is a silent coverage hole (the same class of
    meta-check as the stage-fixture requirement above)."""
    import os
    import re

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ci = open(os.path.join(root, ".github", "workflows", "ci.yml")).read()
    sharded = set(re.findall(r"tests/test_\w+\.py", ci))
    on_disk = {
        f"tests/{f}" for f in os.listdir(os.path.dirname(os.path.abspath(__file__)))
        if f.startswith("test_") and f.endswith(".py")
    }
    missing = sorted(on_disk - sharded)
    assert not missing, f"test files absent from CI shards: {missing}"
