"""streaming/ tests — micro-batch engine semantics against the Structured
Streaming contract: offset/WAL/commit bookkeeping, restart-from-checkpoint
with exactly-once epoch delivery, incremental-fit parity with the
``numBatches`` chaining machinery, and the live hot-swap of a served model."""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.observability.events import (
    ModelSwapped,
    StreamEpochCommitted,
    StreamEpochStarted,
    format_timeline,
    get_bus,
    timeline,
)
from mmlspark_tpu.runtime.faults import FaultPlan, inject_faults
from mmlspark_tpu.runtime.journal import ModelStore
from mmlspark_tpu.serving import RegistrationService, ServiceInfo, ServingServer
from mmlspark_tpu.streaming import (
    AvailableNow,
    FileStreamSource,
    ForeachBatchSink,
    MemorySink,
    MemoryStream,
    ModelCommitSink,
    Once,
    ProcessingTime,
    StreamingQuery,
)


def _chunk(rng, rows=40, cols=4):
    X = rng.normal(size=(rows, cols))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return Table({"features": X, "label": y})


def _drop_npz(d, index, table):
    final = os.path.join(d, f"part-{index:05d}.npz")
    np.savez(
        final + ".tmp.npz",
        **{name: table.column(name) for name in table.columns},
    )
    os.rename(final + ".tmp.npz", final)


def _auc(y, score):
    order = np.argsort(score)
    ranks = np.empty(len(y))
    ranks[order] = np.arange(1, len(y) + 1)
    pos = y > 0
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


class TestSources:
    def test_memory_stream_offsets_and_blocks(self):
        ms = MemoryStream()
        assert ms.latest_offset() == 0
        ms.add(Table({"x": np.arange(3)}))
        ms.add(Table({"x": np.arange(3, 7)}))
        assert ms.latest_offset() == 2
        assert ms.plan_batch(0, 2) == [0, 1]
        assert ms.load_batch([1]).num_rows == 4
        with pytest.raises(ValueError):
            ms.plan_batch(0, 3)
        with pytest.raises(ValueError, match="not survive a restart"):
            ms.load_batch([9])

    def test_file_source_orders_and_hides_partials(self, tmp_path):
        d = str(tmp_path)
        rng = np.random.default_rng(0)
        _drop_npz(d, 1, _chunk(rng))
        _drop_npz(d, 0, _chunk(rng))
        # half-written outputs and dotfiles never become offsets
        open(os.path.join(d, "part-00002.npz.tmp"), "w").close()
        open(os.path.join(d, ".hidden.npz"), "w").close()
        src = FileStreamSource(d, pattern="part-*")
        assert src.latest_offset() == 2
        assert src.plan_batch(0, 2) == ["part-00000.npz", "part-00001.npz"]
        assert src.load_batch(src.plan_batch(0, 2)).num_rows == 80

    def test_file_source_offsets_stable_across_rescans(self, tmp_path):
        d = str(tmp_path)
        rng = np.random.default_rng(0)
        _drop_npz(d, 5, _chunk(rng))
        src = FileStreamSource(d)
        assert src.latest_offset() == 1
        # a late-arriving earlier name must NOT shift existing offsets
        _drop_npz(d, 1, _chunk(rng))
        assert src.latest_offset() == 2
        assert src.plan_batch(0, 2) == ["part-00005.npz", "part-00001.npz"]

    def test_file_source_jsonl_and_unknown_ext(self, tmp_path):
        d = str(tmp_path)
        with open(os.path.join(d, "rows.jsonl"), "w") as fh:
            fh.write('{"a": 1}\n{"a": 2}\n')
        src = FileStreamSource(d, pattern="*.jsonl")
        assert src.load_batch(src.plan_batch(0, src.latest_offset())).num_rows == 2
        with open(os.path.join(d, "bad.xyz"), "w") as fh:
            fh.write("nope")
        src2 = FileStreamSource(d, pattern="*.xyz")
        with pytest.raises(ValueError, match="no loader"):
            src2.load_batch(src2.plan_batch(0, src2.latest_offset()))


class TestQuery:
    def test_once_and_available_now(self, tmp_path):
        ms = MemoryStream(max_per_trigger=1)
        for i in range(3):
            ms.add(Table({"x": np.full(2, i)}))
        sink = MemorySink()
        q = StreamingQuery(ms, sink, trigger=Once(),
                           checkpoint_dir=str(tmp_path / "q"))
        q.start()
        assert q.await_termination(10)
        assert [e for e, _ in sink.batches] == [0]  # Once = one rate-limited epoch
        q2 = StreamingQuery(ms, sink, trigger=AvailableNow(),
                            checkpoint_dir=str(tmp_path / "q"))
        q2.start()
        assert q2.await_termination(10)
        assert q2.exception is None
        assert [e for e, _ in sink.batches] == [0, 1, 2]
        assert sink.rows == 6
        assert q2.committed_epochs == [0, 1, 2]

    def test_processing_time_picks_up_live_data(self, tmp_path):
        ms = MemoryStream()
        sink = MemorySink()
        q = StreamingQuery(ms, sink, trigger=ProcessingTime(0.02),
                           checkpoint_dir=str(tmp_path / "q"))
        with q:
            ms.add(Table({"x": np.arange(2)}))
            deadline = time.monotonic() + 10
            while sink.rows < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            ms.add(Table({"x": np.arange(3)}))
            while sink.rows < 5 and time.monotonic() < deadline:
                time.sleep(0.01)
        assert sink.rows == 5
        assert not q.active

    def test_restart_resumes_from_commit_log(self, tmp_path):
        d, ckpt = str(tmp_path / "in"), str(tmp_path / "ckpt")
        os.makedirs(d)
        rng = np.random.default_rng(1)
        for i in range(2):
            _drop_npz(d, i, _chunk(rng, rows=5))
        sink = MemorySink()
        q = StreamingQuery(FileStreamSource(d, max_per_trigger=1), sink,
                           trigger=AvailableNow(), checkpoint_dir=ckpt)
        q.start()
        assert q.await_termination(10) and q.exception is None
        assert q.committed_epochs == [0, 1]
        # restart: fresh source + sink on the same checkpoint, plus new data
        _drop_npz(d, 2, _chunk(rng, rows=5))
        sink2 = MemorySink()
        q2 = StreamingQuery(FileStreamSource(d, max_per_trigger=1), sink2,
                            trigger=AvailableNow(), checkpoint_dir=ckpt)
        assert q2._next_epoch == 2 and q2._offset == 2
        q2.start()
        assert q2.await_termination(10) and q2.exception is None
        # only the NEW epoch processed; committed epochs never re-deliver
        assert [e for e, _ in sink2.batches] == [2]
        assert q2.committed_epochs == [0, 1, 2]

    def test_wal_replay_pins_manifest(self, tmp_path):
        """An uncommitted planned epoch replays the exact WAL manifest,
        even though the directory has since grown."""
        d, ckpt = str(tmp_path / "in"), str(tmp_path / "ckpt")
        os.makedirs(d)
        rng = np.random.default_rng(2)
        _drop_npz(d, 0, _chunk(rng, rows=5))
        # hand-build the crashed run's checkpoint: epoch 0 planned, no commit
        os.makedirs(os.path.join(ckpt, "offsets"))
        with open(os.path.join(ckpt, "offsets", "000000.json"), "w") as fh:
            json.dump({"epoch": 0, "start": 0, "end": 1,
                       "manifest": ["part-00000.npz"]}, fh)
        _drop_npz(d, 1, _chunk(rng, rows=7))  # arrives after the "crash"
        sink = MemorySink()
        q = StreamingQuery(FileStreamSource(d), sink, trigger=AvailableNow(),
                           checkpoint_dir=ckpt)
        assert q._replay is not None
        q.start()
        assert q.await_termination(10) and q.exception is None
        # epoch 0 = the pinned single-file manifest; epoch 1 = the rest
        assert sink.batches[0][0] == 0 and sink.batches[0][1].num_rows == 5
        assert sink.batches[1][0] == 1 and sink.batches[1][1].num_rows == 7

    def test_sinks_dedupe_replayed_epochs(self):
        seen = []
        fb = ForeachBatchSink(lambda t, e: seen.append(e))
        t = Table({"x": np.arange(2)})
        fb.process_batch(0, t)
        fb.process_batch(0, t)  # WAL replay duplicate
        assert seen == [0]
        ms = MemorySink()
        ms.process_batch(3, t)
        ms.process_batch(3, t)
        assert len(ms.batches) == 1

    def test_kill_stream_directive_sigkills(self, monkeypatch):
        kills = []
        monkeypatch.setattr(
            "mmlspark_tpu.streaming.query.os.kill",
            lambda pid, sig: kills.append((pid, sig)),
        )
        ms = MemoryStream()
        ms.add(Table({"x": np.arange(2)}))
        q = StreamingQuery(ms, MemorySink(), checkpoint_dir=None)
        plan = FaultPlan(seed=0).kill_stream(0, "pre_commit")
        with inject_faults(plan):
            q.process_all_available()
        assert kills and kills[0][0] == os.getpid()
        assert plan.fired == [("kill_stream", 0, 0)]
        with pytest.raises(ValueError):
            FaultPlan(seed=0).kill_stream(0, "mid_sink")

    def test_streaming_events_fold_into_timeline(self, tmp_path):
        events = []
        bus = get_bus()
        bus.add_listener(events.append)
        try:
            ms = MemoryStream()
            ms.add(Table({"x": np.arange(4)}))
            q = StreamingQuery(ms, MemorySink(), trigger=Once(),
                               name="tq", checkpoint_dir=str(tmp_path))
            q.start()
            assert q.await_termination(10)
        finally:
            bus.remove_listener(events.append)
        assert any(isinstance(e, StreamEpochStarted) for e in events)
        committed = [e for e in events if isinstance(e, StreamEpochCommitted)]
        assert committed and committed[0].rows == 4
        summary = timeline(events)
        assert summary["streaming"]["epochs"] == 1
        assert summary["streaming"]["rows"] == 4
        assert summary["streaming"]["queries"] == {"tq": [0]}
        assert "== streaming ==" in format_timeline(summary)


@pytest.mark.slow
class TestModelCommitSink:
    """Incremental-fit parity: the streamed path must not silently shift
    models relative to the manual modelString chaining it is built on."""

    def _chunks(self, k=3, rows=40):
        rng = np.random.default_rng(9)
        return [_chunk(rng, rows=rows) for _ in range(k)]

    def _factory(self):
        from mmlspark_tpu.lightgbm import LightGBMClassifier

        return LightGBMClassifier(numIterations=4, numLeaves=7, seed=3)

    def _run_stream(self, chunks, root, name="m"):
        ms = MemoryStream(max_per_trigger=1)
        for c in chunks:
            ms.add(c)
        sink = ModelCommitSink(self._factory, name=name, root=root)
        q = StreamingQuery(ms, sink, trigger=AvailableNow(),
                           checkpoint_dir=os.path.join(root, "q"))
        q.start()
        assert q.await_termination(300)
        if q.exception is not None:
            raise q.exception
        return sink

    def test_streamed_fit_matches_manual_chaining(self, tmp_path):
        from mmlspark_tpu.lightgbm.base import _merge_boosters
        from mmlspark_tpu.lightgbm.booster import Booster

        chunks = self._chunks()
        sink = self._run_stream(chunks, str(tmp_path))
        assert sink.committed_epochs == [0, 1, 2]
        assert sink.versions == {0: 1, 1: 2, 2: 3}
        # manual modelString chaining over the same chunks, byte-for-byte
        text = None
        for c in chunks:
            est = self._factory()
            if text:
                est.set("modelString", text)
            delta = est.fit(c).booster
            merged = (
                _merge_boosters([Booster.from_string(text), delta])
                if text else delta
            )
            text = merged.model_to_string()
        assert text == sink.latest_text()

    def test_streamed_fit_auc_parity_with_concat_fit(self, tmp_path):
        from mmlspark_tpu.lightgbm.booster import Booster

        chunks = self._chunks(k=3, rows=80)
        sink = self._run_stream(chunks, str(tmp_path))
        streamed = Booster.from_string(sink.latest_text())
        concat = Table.concat(chunks)
        # one warm-start-free fit over everything, same total tree budget
        from mmlspark_tpu.lightgbm import LightGBMClassifier

        single = LightGBMClassifier(
            numIterations=12, numLeaves=7, seed=3
        ).fit(concat).booster
        rng = np.random.default_rng(77)
        Xt = rng.normal(size=(300, 4))
        yt = (Xt[:, 0] + 0.5 * Xt[:, 1] > 0).astype(np.float64)
        auc_stream = _auc(yt, streamed.raw_margin(Xt)[:, 0])
        auc_single = _auc(yt, single.raw_margin(Xt)[:, 0])
        assert streamed.num_trees == 12
        assert auc_stream > 0.85
        assert abs(auc_stream - auc_single) < 0.08

    def test_merge_round_trip_preserves_margins(self):
        from mmlspark_tpu.lightgbm.base import _merge_boosters
        from mmlspark_tpu.lightgbm.booster import Booster

        a, b = self._chunks(k=2)
        base = self._factory().fit(a).booster
        est = self._factory()
        est.set("modelString", base.model_to_string())
        delta = est.fit(b).booster
        merged = _merge_boosters(
            [Booster.from_string(base.model_to_string()), delta]
        )
        again = Booster.from_string(merged.model_to_string())
        X = np.asarray(a.column("features"))
        np.testing.assert_allclose(
            merged.raw_margin(X), again.raw_margin(X), rtol=1e-6
        )
        np.testing.assert_allclose(
            merged.raw_margin(X),
            base.raw_margin(X) + delta.raw_margin(X),
            rtol=1e-5, atol=1e-6,
        )

    def test_duplicate_epoch_never_refits_or_recommits(self, tmp_path):
        calls = []
        factory = self._factory

        def counting_factory():
            calls.append(1)
            return factory()

        root = str(tmp_path)
        chunks = self._chunks(k=2)
        sink = ModelCommitSink(counting_factory, name="m", root=root)
        sink.process_batch(0, chunks[0])
        v = sink.process_batch(0, chunks[0])  # WAL replay duplicate
        assert len(calls) == 1
        assert v == 1 and sink.versions == {0: 1}
        sink.close()
        # a fresh sink instance (restarted process) restores the journal
        # and also refuses to refit the journaled epoch
        sink2 = ModelCommitSink(counting_factory, name="m", root=root)
        assert sink2.committed_epochs == [0]
        v = sink2.process_batch(0, chunks[0])
        assert len(calls) == 1 and v == 1
        assert ModelStore(os.path.join(root, "models")).current_version("m") == 1
        sink2.process_batch(1, chunks[1])
        assert len(calls) == 2 and sink2.versions[1] == 2
        sink2.close()

    def test_requires_durable_root(self, monkeypatch):
        monkeypatch.delenv("MMLSPARK_TPU_CHECKPOINT_DIR", raising=False)
        with pytest.raises(ValueError, match="durable root"):
            ModelCommitSink(self._factory)


class _Scaler(Transformer):
    """Cheap text-loadable model for hot-swap tests: scales input by k."""

    def __init__(self, k, **kw):
        super().__init__(**kw)
        self.k = k

    def transform(self, table):
        x = np.asarray(table.column("input"), dtype=np.float64)
        return table.with_column("prediction", x * self.k)


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


class TestHotSwap:
    def test_current_swap_between_requests_without_restart(self, tmp_path):
        store = ModelStore(str(tmp_path / "models"))
        store.commit("3.0", name="scaler")
        swapped = []
        bus = get_bus()
        bus.add_listener(
            lambda e: swapped.append(e) if isinstance(e, ModelSwapped) else None
        )
        srv = ServingServer(_Scaler(1.0), max_latency_ms=1.0)
        srv.enable_hot_swap(
            lambda text: _Scaler(float(text)), root=str(tmp_path),
            name="scaler", poll_s=0.02,
        )
        with srv:
            deadline = time.monotonic() + 10
            while srv.model_version != 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            status, out = _post(srv.info.url, {"input": 7.0})
            assert status == 200 and out["prediction"] == 21.0
            assert _get(srv.info.url + "healthz")["model_version"] == 1
            # a new commit lands; the SAME listener swaps between requests
            store.commit("5.0", name="scaler")
            while srv.model_version != 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            status, out = _post(srv.info.url, {"input": 7.0})
            assert status == 200 and out["prediction"] == 35.0
            assert _get(srv.info.url + "healthz")["model_version"] == 2
            assert srv.info.model_version == 2
        assert [e.version for e in swapped] == [1, 2]
        assert all(e.server == "serving" for e in swapped)

    def test_bad_commit_keeps_serving_old_model(self, tmp_path):
        store = ModelStore(str(tmp_path / "models"))
        store.commit("2.0", name="scaler")
        srv = ServingServer(_Scaler(1.0), max_latency_ms=1.0)
        srv.enable_hot_swap(
            lambda text: _Scaler(float(text)), root=str(tmp_path),
            name="scaler", poll_s=0.02,
        )
        with srv:
            deadline = time.monotonic() + 10
            while srv.model_version != 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            store.commit("not-a-number", name="scaler")  # loader will raise
            time.sleep(0.2)
            status, out = _post(srv.info.url, {"input": 4.0})
            assert status == 200 and out["prediction"] == 8.0  # still v1
            assert srv.model_version == 1

    def test_hot_swap_requires_root(self, monkeypatch):
        monkeypatch.delenv("MMLSPARK_TPU_CHECKPOINT_DIR", raising=False)
        srv = ServingServer(_Scaler(1.0))
        with pytest.raises(ValueError, match="ModelStore root"):
            srv.enable_hot_swap(lambda text: _Scaler(float(text)))


class TestRegistryModelVersion:
    def test_services_reports_model_version(self):
        with RegistrationService() as reg:
            reg.register(ServiceInfo("a", "127.0.0.1", 1234, model_version=3))
            svcs = _get(reg.info.url + "services")
            assert svcs == [{"name": "a", "host": "127.0.0.1", "port": 1234,
                             "model_version": 3}]
            # a heartbeat carrying a new version updates the lease metadata
            assert reg.heartbeat("a", model_version=4)
            assert _get(reg.info.url + "services")[0]["model_version"] == 4

    def test_http_register_and_heartbeat_carry_version(self):
        with RegistrationService() as reg:
            base = reg.info.url.rstrip("/")
            req = urllib.request.Request(
                base + "/register",
                data=json.dumps({"name": "w", "host": "127.0.0.1",
                                 "port": 9, "model_version": 7}).encode(),
                method="POST",
            )
            assert urllib.request.urlopen(req, timeout=10).status == 200
            assert _get(base + "/services")[0]["model_version"] == 7
            req = urllib.request.Request(
                base + "/heartbeat",
                data=json.dumps({"name": "w", "model_version": 8}).encode(),
                method="POST",
            )
            assert urllib.request.urlopen(req, timeout=10).status == 200
            assert _get(base + "/services")[0]["model_version"] == 8
