"""Serving-fleet tests: router, autoscaler, and campaign payloads — real
HTTP servers and an in-process registry, matching the test_serving.py
posture (no subprocess replicas; the fleet-chaos CI job covers those)."""

import json
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.observability.events import (
    EventLogSink,
    FleetScaled,
    RequestRouted,
    RequestServed,
    SpanRecorded,
    get_bus,
    merge,
    process_log_path,
    timeline,
    write_merged,
)
from mmlspark_tpu.observability.registry import MetricsRegistry
from mmlspark_tpu.observability.slo import SLOReport
from mmlspark_tpu.observability.tracing import TRACE_HEADER
from mmlspark_tpu.resilience.budget import RetryBudget
from mmlspark_tpu.resilience.policy import RetryPolicy
from mmlspark_tpu.runtime.faults import FaultPlan, inject_faults
from mmlspark_tpu.runtime.journal import ModelStore
from mmlspark_tpu.serving import (
    FleetController,
    FleetRouter,
    RegistrationService,
    ServiceInfo,
    ServingServer,
)
from mmlspark_tpu.serving.fleet import (
    sar_demo_factory,
    store_model_factory,
    store_model_loader,
)


def _const_model(value):
    """table->table callable answering ``value`` for every row — replicas
    with distinct values make routing decisions observable from replies."""

    def model(table):
        n = len(np.atleast_1d(np.asarray(table.column("input"))))
        return Table({"prediction": np.full(n, float(value))})

    return model


def _post(url, payload, timeout=10, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else None)


def _post_headers(url, payload, timeout=10, headers=None):
    """Like _post, but also returns the response headers — the trace id
    rides every reply, error paths included."""
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers.items())
    except urllib.error.HTTPError as e:
        body = e.read()
        return (
            e.code,
            (json.loads(body) if body else None),
            dict(e.headers.items()),
        )


class _Fleet:
    """Two in-process replicas (answers 1.0 and 2.0) registered in an
    in-process registry, with isolated metrics registries so per-replica
    request counts are assertable."""

    def __init__(self):
        self.registry = RegistrationService().start()
        self.regs = {}
        self.servers = {}
        for name, value in (("replica-0", 1.0), ("replica-1", 2.0)):
            reg = MetricsRegistry()
            srv = ServingServer(
                _const_model(value), name=name, max_latency_ms=0.5,
                registry=reg,
            ).start()
            self.regs[name] = reg
            self.servers[name] = srv
            self.registry.register(srv.info)

    def requests_served(self, name):
        return self.regs[name].counter("serving_requests_total").value

    def close(self):
        for srv in self.servers.values():
            srv.stop()
        self.registry.stop()


@pytest.fixture()
def fleet():
    f = _Fleet()
    yield f
    f.close()


def _router(fleet, **kwargs):
    kwargs.setdefault("registry", fleet.registry)
    kwargs.setdefault("discovery_interval_s", 60.0)  # tests refresh by hand
    return FleetRouter(**kwargs)


class TestRouterRouting:
    def test_routes_and_answers(self, fleet):
        with _router(fleet) as router:
            status, out = _post(router.url, {"input": 3.0})
            assert status == 200
            assert out["prediction"] in (1.0, 2.0)

    def test_least_loaded_prefers_idle_replica(self, fleet):
        # replica-0 heartbeats heavy load; every pick must go to replica-1
        fleet.registry.heartbeat("replica-0", inflight=50)
        fleet.registry.heartbeat("replica-1", inflight=0)
        with _router(fleet) as router:
            answers = {_post(router.url, {"input": 1.0})[1]["prediction"]
                       for _ in range(8)}
            assert answers == {2.0}

    def test_consistent_hash_is_sticky_and_spreads(self, fleet):
        with _router(fleet, policy="consistent_hash") as router:
            for key in ("alpha", "beta", "gamma", "delta"):
                answers = {
                    _post(router.url, {"input": 1.0},
                          headers={"X-Routing-Key": key})[1]["prediction"]
                    for _ in range(5)
                }
                assert len(answers) == 1, f"key {key} moved between replicas"
            spread = {
                _post(router.url, {"input": 1.0},
                      headers={"X-Routing-Key": f"key-{i}"})[1]["prediction"]
                for i in range(32)
            }
            assert spread == {1.0, 2.0}

    def test_deregistered_replica_never_receives_a_request(self, fleet):
        with _router(fleet) as router:
            fleet.registry.deregister("replica-1")
            router.refresh()
            before = fleet.requests_served("replica-1")
            for _ in range(20):
                status, out = _post(router.url, {"input": 1.0})
                assert status == 200
                assert out["prediction"] == 1.0  # only replica-0 answers
            assert fleet.requests_served("replica-1") == before

    def test_dead_replica_costs_one_hop_not_an_error(self, fleet):
        # a ghost lease for an endpoint nobody listens on (the window
        # between a replica dying and its lease expiring)
        fleet.registry.register(ServiceInfo("replica-9", "127.0.0.1", 9))
        fleet.registry.heartbeat("replica-9", inflight=0)
        fleet.registry.heartbeat("replica-0", inflight=10)
        fleet.registry.heartbeat("replica-1", inflight=10)
        with _router(fleet) as router:
            failovers0 = router._m_failovers.value
            for _ in range(5):
                status, out = _post(router.url, {"input": 1.0})
                assert status == 200
                assert out["prediction"] in (1.0, 2.0)
            assert router._m_failovers.value > failovers0

    def test_dead_replica_fails_over_even_with_drained_retry_budget(
        self, fleet
    ):
        # the budget rations retries of attempts a replica actually
        # processed; a connection fast-fail to a dead port did no work
        # anywhere, so failover must happen even with zero retry tokens —
        # otherwise a SIGKILL'd replica's stale lease turns into
        # user-visible 502s until the TTL prunes it
        fleet.registry.register(ServiceInfo("replica-9", "127.0.0.1", 9))
        fleet.registry.heartbeat("replica-9", inflight=0)
        fleet.registry.heartbeat("replica-0", inflight=10)
        fleet.registry.heartbeat("replica-1", inflight=10)
        policy = RetryPolicy(
            max_attempts=3, budget=RetryBudget(ratio=0.0, min_tokens=0.0)
        )
        with _router(fleet, retry_policy=policy) as router:
            for _ in range(5):
                status, out = _post(router.url, {"input": 1.0})
                assert status == 200
                assert out["prediction"] in (1.0, 2.0)

    def test_no_replicas_is_503(self):
        with RegistrationService() as registry:
            with FleetRouter(registry=registry,
                             discovery_interval_s=60.0) as router:
                status, out = _post(router.url, {"input": 1.0})
                assert status == 503
                assert "no live replicas" in out["error"]


class _CaptureReplica:
    """A bare HTTP endpoint that records request headers and answers a
    fixed prediction — for asserting what the router forwards."""

    def __init__(self):
        seen = self.seen = []

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                seen.append(dict(self.headers.items()))
                body = b'{"prediction": 7.0}'
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        import threading

        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        self.info = ServiceInfo(
            "capture", "127.0.0.1", self.httpd.server_address[1]
        )

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TestRouterDeadlines:
    def test_deadline_header_shrinks_across_the_hop(self):
        capture = _CaptureReplica()
        try:
            with RegistrationService() as registry:
                registry.register(capture.info)
                with FleetRouter(registry=registry,
                                 discovery_interval_s=60.0) as router:
                    status, _ = _post(router.url, {"input": 1.0},
                                      headers={"X-Deadline-Ms": "800"})
                    assert status == 200
            forwarded = float(capture.seen[0]["X-Deadline-Ms"])
            assert 0 < forwarded <= 800
        finally:
            capture.close()

    def test_request_never_exceeds_deadline_under_storm(self, fleet):
        # every hop answers an injected 503; retries must stay inside the
        # client's 250 ms budget (waits are clipped to the deadline)
        plan = FaultPlan(seed=3).http_storm(count=100, status=503)
        with _router(fleet) as router:
            with inject_faults(plan):
                t0 = time.monotonic()
                status, _ = _post(router.url, {"input": 1.0},
                                  headers={"X-Deadline-Ms": "250"})
                elapsed = time.monotonic() - t0
            assert status in (503, 504)
            assert elapsed < 0.25 + 0.25, f"blew the deadline: {elapsed:.3f}s"

    def test_retry_budget_bounds_failover(self, fleet):
        # an empty budget means one hop per request, storm or not
        policy = RetryPolicy(
            max_attempts=4, base=0.001, cap=0.002, seed=0,
            budget=RetryBudget(ratio=0.0, min_tokens=0.0),
        )
        plan = FaultPlan(seed=3).http_storm(count=50, status=503)
        with _router(fleet, retry_policy=policy) as router:
            hops0 = router._m_hops.value
            with inject_faults(plan):
                for _ in range(5):
                    status, _ = _post(router.url, {"input": 1.0})
                    assert status == 503  # passed through, not retried
            assert router._m_hops.value - hops0 == 5

    def test_retry_lands_on_a_different_replica(self, fleet):
        # storm only replica-0's port; least-loaded prefers it (idle),
        # the failover must answer from replica-1
        fleet.registry.heartbeat("replica-0", inflight=0)
        fleet.registry.heartbeat("replica-1", inflight=10)
        port = fleet.servers["replica-0"].info.port
        with _router(fleet) as router:
            plan = FaultPlan(seed=3).http_storm(
                count=1, status=503, url_part=f":{port}/"
            )
            with inject_faults(plan):
                status, out = _post(router.url, {"input": 1.0})
            assert status == 200
            assert out["prediction"] == 2.0
            assert plan.fired, "the storm never hit replica-0"

    def test_tripped_breaker_takes_replica_out_of_rotation(self, fleet):
        from mmlspark_tpu.resilience.breaker import BreakerRegistry

        fleet.registry.heartbeat("replica-0", inflight=0)
        fleet.registry.heartbeat("replica-1", inflight=10)
        port = fleet.servers["replica-0"].info.port
        breakers = BreakerRegistry(
            failure_threshold=2, window_s=10.0, reset_timeout_s=30.0
        )
        with _router(fleet, breakers=breakers) as router:
            plan = FaultPlan(seed=3).http_storm(
                count=2, status=503, url_part=f":{port}/"
            )
            with inject_faults(plan):
                for _ in range(2):
                    status, _ = _post(router.url, {"input": 1.0})
                    assert status == 200  # failover absorbed each 503
            skips0 = router._m_skipped.value
            for _ in range(4):
                status, out = _post(router.url, {"input": 1.0})
                assert status == 200
                assert out["prediction"] == 2.0  # straight to replica-1
            assert router._m_skipped.value > skips0


class _FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


class _StubSupervisor:
    """The process plane reduced to bookkeeping — decide()/step() logic
    is testable with zero subprocesses."""

    def __init__(self, live=2, name="replica"):
        self.name = name
        self._procs = {i: object() for i in range(live)}
        self._next_index = live
        self.added = []
        self.retired = []
        self.polls = 0

    @property
    def live_count(self):
        return len(self._procs)

    def poll(self):
        self.polls += 1
        return []

    def add_replica(self, ready_timeout_s=None):
        index = self._next_index
        self._next_index += 1
        self._procs[index] = object()
        self.added.append(index)
        return index

    def retire_replica(self, index, grace_s=5.0):
        del self._procs[index]
        self.retired.append(index)


class _FakeRegistry:
    """Just the two surfaces FleetController touches in-process."""

    def __init__(self, services=()):
        self.services = list(services)
        self.deregistered = []

    def deregister(self, name):
        self.deregistered.append(name)
        return True


def _svc(i, inflight=0, shed=0, p99=1.0, name="replica"):
    return ServiceInfo(f"{name}-{i}", "127.0.0.1", 10000 + i,
                       inflight=inflight, shed_total=shed, p99_ms=p99)


class TestFleetControllerDecide:
    def _controller(self, sup, services, **kwargs):
        clock = kwargs.pop("clock", _FakeClock())
        kwargs.setdefault("min_replicas", 1)
        kwargs.setdefault("max_replicas", 4)
        kwargs.setdefault("scale_up_inflight", 4.0)
        kwargs.setdefault("scale_down_inflight", 1.0)
        kwargs.setdefault("cooldown_s", 3.0)
        kwargs.setdefault("down_sustain_s", 2.0)
        ctl = FleetController(sup, registry=_FakeRegistry(services),
                              clock=clock, **kwargs)
        return ctl, clock

    def test_scales_up_on_inflight(self):
        sup = _StubSupervisor(live=2)
        ctl, _ = self._controller(sup, [])
        decision = ctl.decide([_svc(0, inflight=6), _svc(1, inflight=8)])
        assert decision is not None and decision[0] == "up"

    def test_scales_up_on_shed_rate(self):
        sup = _StubSupervisor(live=2)
        ctl, clock = self._controller(sup, [])
        assert ctl.decide([_svc(0, shed=0), _svc(1, shed=0)]) is None
        clock.t += 1.0
        decision = ctl.decide([_svc(0, shed=10), _svc(1, shed=0)])
        assert decision is not None and decision[0] == "up"
        assert "shed" in decision[1]

    def test_no_scale_up_at_max(self):
        sup = _StubSupervisor(live=2)
        ctl, _ = self._controller(sup, [], max_replicas=2)
        assert ctl.decide([_svc(0, inflight=9), _svc(1, inflight=9)]) is None

    def test_scale_down_needs_sustained_idle(self):
        sup = _StubSupervisor(live=3)
        ctl, clock = self._controller(sup, [])
        idle = [_svc(i, inflight=0) for i in range(3)]
        assert ctl.decide(idle) is None  # first quiet sample: not yet
        clock.t += 1.0
        assert ctl.decide(idle) is None  # still inside down_sustain_s
        clock.t += 1.5
        decision = ctl.decide(idle)
        assert decision is not None and decision[0] == "down"

    def test_busy_sample_resets_the_idle_window(self):
        sup = _StubSupervisor(live=3)
        ctl, clock = self._controller(sup, [])
        idle = [_svc(i, inflight=0) for i in range(3)]
        assert ctl.decide(idle) is None
        clock.t += 1.5
        assert ctl.decide([_svc(i, inflight=9) for i in range(3)]) != \
            (None, None)  # busy (scales up); idle window must reset
        clock.t += 1.0
        assert ctl.decide(idle) is None  # idle restarts from zero

    def test_never_retires_below_min(self):
        sup = _StubSupervisor(live=2)
        ctl, clock = self._controller(sup, [], min_replicas=2)
        idle = [_svc(0, inflight=0), _svc(1, inflight=0)]
        ctl.decide(idle)
        clock.t += 10.0
        assert ctl.decide(idle) is None

    def test_below_min_scales_up_even_when_idle(self):
        sup = _StubSupervisor(live=1)
        ctl, _ = self._controller(sup, [], min_replicas=2)
        decision = ctl.decide([_svc(0, inflight=0)])
        assert decision is not None and decision[0] == "up"
        assert "below min" in decision[1]


class TestFleetControllerStep:
    def test_step_scales_up_publishes_and_cools_down(self):
        sup = _StubSupervisor(live=2)
        clock = _FakeClock()
        busy = [_svc(0, inflight=8), _svc(1, inflight=8)]
        registry = _FakeRegistry(busy)
        ctl = FleetController(
            sup, registry=registry, min_replicas=2, max_replicas=4,
            scale_up_inflight=4.0, cooldown_s=3.0, clock=clock,
        )
        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        try:
            assert ctl.step() == ("up", "inflight 8.0 >= 4")
            assert sup.added == [2]
            assert sup.polls == 1
            # cooldown: the same pressure produces no second action
            clock.t += 1.0
            assert ctl.step() is None
            clock.t += 5.0
            assert ctl.step() == ("up", "inflight 8.0 >= 4")
        finally:
            bus.remove_listener(seen.append)
        scaled = [e for e in seen if isinstance(e, FleetScaled)]
        assert [e.direction for e in scaled] == ["up", "up"]
        assert scaled[0].replicas == 3

    def test_step_retires_least_loaded_and_deregisters(self):
        sup = _StubSupervisor(live=3)
        clock = _FakeClock()
        idle = [_svc(0, inflight=3), _svc(1, inflight=0), _svc(2, inflight=1)]
        registry = _FakeRegistry(idle)
        ctl = FleetController(
            sup, registry=registry, min_replicas=1, max_replicas=4,
            scale_down_inflight=2.0, down_sustain_s=1.0, cooldown_s=0.5,
            clock=clock,
        )
        assert ctl.step() is None  # idle window opens
        clock.t += 1.5
        assert ctl.step() == ("down", "idle 1.5s (inflight 1.3)")
        assert sup.retired == [1]  # the idlest replica went first
        assert registry.deregistered == ["replica-1"]


class TestRegistryLoadMetadata:
    def test_http_register_heartbeat_deregister_carry_load(self):
        with RegistrationService() as registry:
            base = registry.info.url.rstrip("/")

            def post(path, payload):
                req = urllib.request.Request(
                    base + path, data=json.dumps(payload).encode(),
                    method="POST",
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=5) as r:
                    return r.status

            assert post("/register", {
                "name": "r0", "host": "127.0.0.1", "port": 12345,
                "inflight": 3, "shed_total": 1, "p99_ms": 2.5,
            }) == 200
            svc = registry.services[0]
            assert (svc.inflight, svc.shed_total, svc.p99_ms) == (3, 1, 2.5)

            assert post("/heartbeat", {
                "name": "r0", "inflight": 7, "shed_total": 4, "p99_ms": 9.0,
            }) == 200
            svc = registry.services[0]
            assert (svc.inflight, svc.shed_total, svc.p99_ms) == (7, 4, 9.0)

            with urllib.request.urlopen(base + "/services", timeout=5) as r:
                listed = json.loads(r.read())
            assert listed[0]["inflight"] == 7

            assert post("/deregister", {"name": "r0"}) == 200
            assert registry.services == []
            with pytest.raises(urllib.error.HTTPError) as err:
                post("/deregister", {"name": "r0"})
            assert err.value.code == 404

    def test_serving_server_reports_load_stats(self):
        with ServingServer(_const_model(1.0),
                           registry=MetricsRegistry()) as srv:
            _post(srv.info.url, {"input": 1.0})
            stats = srv.heartbeat_stats()
            assert stats["name"] == srv.info.name
            # the admission slot is released just AFTER the reply bytes
            # flush, so a fast client can observe inflight=1 for a tick;
            # the stat is eventually consistent
            deadline = time.monotonic() + 2.0
            while stats["inflight"] != 0 and time.monotonic() < deadline:
                time.sleep(0.01)
                stats = srv.heartbeat_stats()
            assert stats["inflight"] == 0  # idle again after the reply
            assert stats["shed_total"] == 0
            assert stats["p99_ms"] >= 0.0


class TestCampaignPayloads:
    def test_store_model_loader_parses_versions(self):
        model = store_model_loader('{"scale": 3.0, "bias": 1.0}')
        out = model(Table({"input": np.array([2.0, 4.0])}))
        assert list(out.column("prediction")) == [7.0, 13.0]

    def test_store_model_factory_serves_latest_commit(self, tmp_path):
        store = ModelStore(str(tmp_path / "models"))
        store.commit(json.dumps({"scale": 2.0}), name="model")
        store.commit(json.dumps({"scale": 5.0, "bias": 1.0}), name="model")
        model = store_model_factory(
            {"hot_swap": {"root": str(tmp_path), "name": "model"}}
        )
        out = model(Table({"input": np.array([2.0])}))
        assert out.column("prediction")[0] == 11.0

    def test_sar_topk_served_end_to_end(self):
        model = sar_demo_factory({"sar": {
            "n_users": 16, "n_items": 8, "events": 300,
            "num_items": 3, "seed": 1,
        }})
        with ServingServer(model, max_latency_ms=1.0,
                           registry=MetricsRegistry()) as srv:
            status, out = _post(srv.info.url, {"input": 2})
            assert status == 200
            recs = out["prediction"]
            assert len(recs) == 3
            assert all(0 <= i < 8 for i in recs)
            assert len(set(recs)) == 3  # distinct top-k items
            # cold start: unknown users get an answer, not an error
            status, out = _post(srv.info.url, {"input": 999})
            assert status == 200
            assert out["prediction"] == [-1, -1, -1]


class TestFleetObservability:
    def test_timeline_folds_routing_and_fleet(self):
        events = [
            RequestRouted(rid="r1", replica="replica-0", hops=1,
                          status=200, latency=0.01),
            RequestRouted(rid="r2", replica="replica-1", hops=2,
                          status=200, latency=0.02),
            FleetScaled(direction="up", replicas=3, replica=2,
                        reason="inflight"),
        ]
        tl = timeline(events)
        assert tl["routing"]["count"] == 2
        assert tl["routing"]["hops"] == 3
        assert tl["routing"]["failovers"] == 1
        assert tl["routing"]["by_replica"] == {
            "replica-0": 1, "replica-1": 1,
        }
        (entry,) = tl["fleet"]
        assert entry["direction"] == "up"
        assert entry["replicas"] == 3
        assert entry["replica"] == 2
        assert entry["reason"] == "inflight"

    def test_router_publishes_request_routed(self, fleet):
        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        try:
            with _router(fleet) as router:
                status, _ = _post(router.url, {"input": 1.0})
                assert status == 200
        finally:
            bus.remove_listener(seen.append)
        routed = [e for e in seen if isinstance(e, RequestRouted)]
        assert len(routed) == 1
        assert routed[0].status == 200
        assert routed[0].hops == 1
        assert routed[0].replica in ("replica-0", "replica-1")


class TestRouterTracing:
    def test_reply_carries_the_trace_id(self, fleet):
        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        try:
            with _router(fleet) as router:
                status, _, headers = _post_headers(router.url, {"input": 1.0})
                assert status == 200
        finally:
            bus.remove_listener(seen.append)
        trace_id = headers.get(TRACE_HEADER)
        assert trace_id
        [routed] = [e for e in seen if isinstance(e, RequestRouted)]
        assert routed.trace_id == trace_id

    def test_error_reply_still_carries_the_trace_id(self):
        # a user quoting a failed request's trace id must join against
        # the event log, so 503s carry the header too
        with RegistrationService() as registry:
            with FleetRouter(registry=registry,
                             discovery_interval_s=60.0) as router:
                status, out, headers = _post_headers(
                    router.url, {"input": 1.0}
                )
                assert status == 503
                assert "no live replicas" in out["error"]
                assert headers.get(TRACE_HEADER)

    def test_replica_spans_join_the_router_trace(self, fleet):
        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        try:
            with _router(fleet) as router:
                status, _, headers = _post_headers(router.url, {"input": 1.0})
                assert status == 200
        finally:
            bus.remove_listener(seen.append)
        trace_id = headers[TRACE_HEADER]
        spans = [e for e in seen
                 if isinstance(e, SpanRecorded) and e.trace_id == trace_id]
        names = {s.name for s in spans}
        assert {"router.request", "router.hop", "serving.request"} <= names
        hop = next(s for s in spans if s.name == "router.hop")
        serving = next(s for s in spans if s.name == "serving.request")
        # the wire context qualified the hop as the replica's parent
        assert serving.parent_id == f"driver:{hop.span_id}"

    def test_client_supplied_trace_is_adopted(self, fleet):
        with _router(fleet) as router:
            status, _, headers = _post_headers(
                router.url, {"input": 1.0},
                headers={TRACE_HEADER: "upstream-trace"},
            )
            assert status == 200
            assert headers[TRACE_HEADER] == "upstream-trace"


class TestFleetLogDeterminism:
    """The satellite contract: the SLO fold over a merged multi-process
    event log is deterministic under seeded chaos — re-merging the same
    segments is byte-identical, and the fleet report folds to identical
    JSON every time."""

    def test_merged_fold_is_deterministic_under_seeded_chaos(
        self, fleet, tmp_path
    ):
        base = str(tmp_path / "events.jsonl")
        plan = (
            FaultPlan(seed=11)
            .http_storm(count=3, status=503)
            .kill_process(1, iteration=4)
        )
        directives = plan.process_kill_directives()
        driver_sink = EventLogSink(base, process="driver")
        replica_sinks = {
            name: EventLogSink(process_log_path(base, name), process=name)
            for name in fleet.servers
        }
        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        bus.add_listener(driver_sink)  # the driver books its real stream
        try:
            with _router(fleet) as router:
                with inject_faults(plan):
                    for i in range(20):
                        _post(router.url, {"input": float(i)})
        finally:
            bus.remove_listener(driver_sink)
            bus.remove_listener(seen.append)
        assert any(kind == "http_status" for kind, _, _ in plan.fired)
        # each replica books the requests it served into its own segment,
        # until the seeded kill directive ends its stream mid-run
        alive = {name: True for name in replica_sinks}
        iters = {name: 0 for name in replica_sinks}
        for e in (e for e in seen if isinstance(e, RequestRouted)):
            name = e.replica
            if name not in replica_sinks:
                continue
            member = int(name.rsplit("-", 1)[1])
            if FaultPlan.should_die(
                directives, member, iteration=iters[name], epoch=0
            ):
                alive[name] = False
            iters[name] += 1
            if alive[name] and e.status == 200:
                replica_sinks[name](RequestServed(
                    rid=e.rid, status=e.status, latency=e.latency,
                    trace_id=e.trace_id,
                ))
        driver_sink.close()
        for sink in replica_sinks.values():
            sink.close()
        assert not alive["replica-1"], "the seeded kill never landed"
        # re-merging the same segments is byte-identical
        out1, out2 = str(tmp_path / "m1.jsonl"), str(tmp_path / "m2.jsonl")
        n1 = write_merged(base, out1)
        n2 = write_merged(base, out2)
        assert n1 == n2 > 0
        with open(out1, "rb") as a, open(out2, "rb") as b:
            assert a.read() == b.read()
        # and the fleet SLO fold over the merged stream is deterministic
        events = merge(base)
        assert {getattr(e, "process", "") for e in events} >= {
            "driver", "replica-0", "replica-1",
        }
        report = SLOReport.fold(None, events=events)
        assert report.requests > 0
        assert report.to_json() == SLOReport.fold(
            None, events=merge(base)
        ).to_json()
