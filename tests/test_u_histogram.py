"""Precomputed-U histogram path (ops/u_histogram.py) and its train wiring.

The U pass replaces the reference engine's per-iteration native histogram
construction (``lightgbm/TrainUtils.scala:220-315``) with one MXU
contraction against a fit-resident one-hot; these tests pin (a) numerical
agreement with the bf16-input reference model, (b) exact counts, (c) the
packed per-feature-width layout, and (d) end-to-end training parity when
the path is forced on CPU (``histogram_method='u'``)."""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from mmlspark_tpu.lightgbm.binning import bin_dataset
from mmlspark_tpu.lightgbm.objectives import auc
from mmlspark_tpu.lightgbm.train import TrainOptions, train
from mmlspark_tpu.ops.histogram import build_histograms
from mmlspark_tpu.ops.u_histogram import (
    build_histograms_u,
    build_u,
    make_u_spec,
    stat_rows,
    u_bytes,
)


def _mixed_case(seed=0, n=3000, k=5):
    rng = np.random.default_rng(seed)
    widths = [32, 5, 17, 32, 2, 9, 31]
    f, b = len(widths), 32
    bins = np.stack(
        [rng.integers(0, w, size=n) for w in widths], axis=1
    ).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.uniform(0.1, 1, size=n).astype(np.float32)
    c = (rng.uniform(size=n) > 0.2).astype(np.float32)
    node = rng.integers(-1, k + 2, size=n).astype(np.int32)  # incl. OOR keys
    return widths, f, b, bins, g, h, c, node


class TestUHistogram:
    def test_matches_bf16_reference_and_counts_exact(self):
        widths, f, b, bins, g, h, c, node = _mixed_case()
        k = 5
        m = ((node >= 0) & (node < k)).astype(np.float32)
        bf = lambda a: np.asarray(jnp.asarray(a, jnp.bfloat16), np.float32)
        # reference: exact sums of bf16-rounded inputs — the precision model
        # of the MXU pass (bf16 inputs, f32 accumulation)
        ref = np.asarray(build_histograms(
            jnp.asarray(bins), jnp.asarray(bf(g) * m), jnp.asarray(bf(h) * m),
            jnp.asarray(c * m), jnp.asarray(np.clip(node, 0, k - 1)), k, b,
            method="segment",
        ))
        spec = make_u_spec(b, f, per_feature=widths)
        assert spec.k == sum(widths)  # packed, not f*b
        u = build_u(jnp.asarray(bins), spec)
        assert u.shape[0] == spec.k_pad
        for stats in (None, stat_rows(jnp.asarray(g), jnp.asarray(h), jnp.asarray(c))):
            out = np.asarray(build_histograms_u(
                u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
                jnp.asarray(node), k, spec, stats=stats,
            ))
            np.testing.assert_array_equal(out[..., 2], ref[..., 2])  # counts
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-4)

    def test_out_of_range_nodes_are_the_in_leaf_mask(self):
        widths, f, b, bins, g, h, c, node = _mixed_case(seed=3)
        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        k = 4
        out = np.asarray(build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec,
        ))
        in_range = (node >= 0) & (node < k)
        # total count over all cells of feature 0 == rows with in-range keys
        assert out[:, 0, :, 2].sum() == (c * in_range).sum()

    def test_panel_width_guard(self):
        widths, f, b, bins, g, h, c, node = _mixed_case()
        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        with pytest.raises(ValueError, match="lane group"):
            build_histograms_u(
                u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
                jnp.asarray(node), 64, spec,
            )

    def test_u_bytes_budget(self):
        spec = make_u_spec(256, 28)
        assert u_bytes(400_000, spec) == 400_384 * spec.k_pad  # 512-aligned rows


class TestUTrainParity:
    def test_forced_u_path_matches_default(self):
        rng = np.random.default_rng(0)
        n = 3000
        X = rng.normal(size=(n, 8))
        y = ((X[:, 0] * 1.5 + X[:, 1] * X[:, 2]) > 0).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=63)
        base = dict(objective="binary", num_iterations=6, num_leaves=15, max_bin=63)
        r0 = train(bins, y, TrainOptions(**base), mapper=mp)
        ru = train(bins, y, TrainOptions(**base, histogram_method="u"), mapper=mp)
        a0 = auc(y, r0.booster.raw_margin(X)[:, 0], np.ones(n))
        au = auc(y, ru.booster.raw_margin(X)[:, 0], np.ones(n))
        # CPU default path is exact f32; the U path is the bf16 MXU model —
        # structurally near-identical trees, AUC within noise
        assert abs(a0 - au) < 0.005, (a0, au)

    @pytest.mark.parametrize("variant", ["depthwise", "goss", "bagging", "multiclass"])
    def test_u_path_boosting_variants(self, variant):
        rng = np.random.default_rng(1)
        n = 2000
        X = rng.normal(size=(n, 6))
        if variant == "multiclass":
            y = np.digitize(X[:, 0], [-0.5, 0.5]).astype(np.float64)
            extra = dict(objective="multiclass", num_class=3)
        else:
            y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
            extra = dict(objective="binary")
        if variant == "depthwise":
            extra.update(growth="depthwise", max_depth=4)
        elif variant == "goss":
            extra.update(boosting_type="goss")
        elif variant == "bagging":
            extra.update(bagging_fraction=0.7, bagging_freq=1)
        bins, mp = bin_dataset(X, max_bin=31)
        r = train(
            bins, y,
            TrainOptions(num_iterations=4, num_leaves=7, max_bin=31,
                         histogram_method="u", **extra),
            mapper=mp,
        )
        margins = r.booster.raw_margin(X)
        if variant == "multiclass":
            acc = (margins.argmax(1) == y).mean()
            assert acc > 0.7, acc
        else:
            a = auc(y, margins[:, 0], np.ones(n))
            assert a > 0.85, a

    def test_device_resident_bins_accepted(self):
        from mmlspark_tpu.lightgbm.binning import bin_dataset_to_device

        rng = np.random.default_rng(2)
        n = 1500
        X = rng.normal(size=(n, 5))
        y = (X[:, 0] > 0).astype(np.float64)
        bins_np, mp = bin_dataset(X, max_bin=31)
        bins_dev, mp2 = bin_dataset_to_device(X, max_bin=31)
        np.testing.assert_array_equal(np.asarray(bins_dev), bins_np)
        np.testing.assert_array_equal(mp2.edges, mp.edges)
        opts = TrainOptions(objective="binary", num_iterations=3,
                            num_leaves=7, max_bin=31)
        r_np = train(bins_np, y, opts, mapper=mp)
        r_dev = train(bins_dev, y, opts, mapper=mp2)
        np.testing.assert_allclose(
            r_dev.booster.leaf_values, r_np.booster.leaf_values, rtol=1e-6
        )

    def test_forced_u_with_voting_parallel_degrades_gracefully(self):
        rng = np.random.default_rng(3)
        n = 1200
        X = rng.normal(size=(n, 5))
        y = (X[:, 0] > 0).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=31)
        r = train(
            bins, y,
            TrainOptions(objective="binary", num_iterations=3, num_leaves=7,
                         max_bin=31, histogram_method="u",
                         tree_learner="voting_parallel", top_k=3),
            mapper=mp,
        )
        a = auc(y, r.booster.raw_margin(X)[:, 0], np.ones(n))
        assert a > 0.85, a


class TestQuantizedGrad:
    """LightGBM's use_quantized_grad analogue: 8-bit stochastically-rounded
    stat rows, s8 x s8 integer MXU pass, per-stat dequant scales."""

    def test_stat_rows_quant_counts_exact_and_sums_unbiased(self):
        import jax

        rng = np.random.default_rng(7)
        n = 20000
        g = rng.normal(size=n).astype(np.float32)
        h = rng.uniform(0.05, 1.0, size=n).astype(np.float32)
        c = (rng.uniform(size=n) > 0.3).astype(np.float32)
        from mmlspark_tpu.ops.u_histogram import stat_rows_quant

        stats, scales = stat_rows_quant(
            jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jax.random.PRNGKey(0),
        )
        stats = np.asarray(stats)
        scales = np.asarray(scales)
        assert stats.dtype == np.int8
        # counts are bit-exact 0/1, scale exactly 1
        np.testing.assert_array_equal(stats[2], c.astype(np.int8))
        assert scales[2] == 1.0
        # per-element quantization stays within one grid step of the input
        for row, x, s in ((0, g, scales[0]), (1, h, scales[1])):
            deq = stats[row].astype(np.float32) * s
            np.testing.assert_allclose(deq, x, atol=float(s) + 1e-7)
            # stochastic rounding is unbiased => SUM of dequantized values
            # concentrates: n * grid * O(1/sqrt(n)) tolerance
            assert abs(deq.sum() - x.sum()) < float(s) * 6 * np.sqrt(n)

    def test_quant_histogram_counts_exact_gh_within_grid(self):
        import jax

        widths, f, b, bins, g, h, c, node = _mixed_case(seed=3)
        k = 5
        from mmlspark_tpu.ops.u_histogram import stat_rows_quant

        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        exact = np.asarray(build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec,
        ))
        qstats = stat_rows_quant(
            jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jax.random.PRNGKey(1),
        )
        quant = np.asarray(build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec, stats=qstats,
        ))
        # counts ride the exact int path: bit-identical
        np.testing.assert_array_equal(quant[..., 2], exact[..., 2])
        # g/h bin sums: each of the <=n member rows contributes at most one
        # grid step of quantization error
        scales = np.asarray(qstats[1])
        n_bin = exact[..., 2]
        for s_idx in (0, 1):
            bound = scales[s_idx] * (n_bin + 1) + 1e-4
            assert (np.abs(quant[..., s_idx] - exact[..., s_idx]) <= bound).all()

    def test_end_to_end_quantized_fit_quality_and_determinism(self):
        rng = np.random.default_rng(11)
        n = 4000
        X = rng.normal(size=(n, 8))
        y = ((X[:, 0] + X[:, 1] * X[:, 2]) > 0).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=63)
        base = TrainOptions(objective="binary", num_iterations=25,
                            num_leaves=15, max_bin=63, histogram_method="u")
        import dataclasses

        r_exact = train(bins, y, base, mapper=mp)
        qopts = dataclasses.replace(base, use_quantized_grad=True)
        r_q = train(bins, y, qopts, mapper=mp)
        a_exact = auc(y, r_exact.booster.raw_margin(X)[:, 0], np.ones(n))
        a_q = auc(y, r_q.booster.raw_margin(X)[:, 0], np.ones(n))
        assert a_q > a_exact - 0.01, (a_q, a_exact)
        # seeded stochastic rounding: same options => identical model
        r_q2 = train(bins, y, qopts, mapper=mp)
        np.testing.assert_array_equal(
            r_q.booster.leaf_values, r_q2.booster.leaf_values
        )

    def test_param_flows_from_stage(self):
        from mmlspark_tpu.lightgbm.classifier import LightGBMClassifier

        stage = LightGBMClassifier(useQuantizedGrad=True)
        assert stage._make_options(num_class=1).use_quantized_grad is True
        assert (
            LightGBMClassifier()._make_options(num_class=1).use_quantized_grad
            is False
        )

    def test_multiclass_quantized(self):
        rng = np.random.default_rng(13)
        n = 3000
        X = rng.normal(size=(n, 6))
        y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
        bins, mp = bin_dataset(X, max_bin=31)
        opts = TrainOptions(objective="multiclass", num_class=3,
                            num_iterations=10, num_leaves=7, max_bin=31,
                            histogram_method="u", use_quantized_grad=True)
        r = train(bins, y.astype(np.float64), opts, mapper=mp)
        pred = r.booster.raw_margin(X).argmax(1)
        assert (pred == y).mean() > 0.8

    def test_quant_falls_back_with_warning_when_u_inactive(self, caplog):
        import logging

        rng = np.random.default_rng(17)
        n = 1500
        X = rng.normal(size=(n, 5))
        y = (X[:, 0] > 0).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=63)
        opts = TrainOptions(objective="binary", num_iterations=3,
                            num_leaves=7, max_bin=63,
                            use_quantized_grad=True,
                            tree_learner="voting_parallel", top_k=3)
        with caplog.at_level(logging.WARNING, logger="mmlspark_tpu.lightgbm"):
            r = train(bins, y, opts, mapper=mp)
        assert any("use_quantized_grad" in m for m in caplog.messages)
        assert r.booster.num_trees >= 1


    def test_quant_through_binary_classifier_stage(self):
        # regression: binary classifiers carry num_class=2 with ONE margin
        # column; the stochastic-rounding keys must follow grad.shape[1]
        from mmlspark_tpu.data.table import Table
        from mmlspark_tpu.lightgbm.classifier import LightGBMClassifier

        rng = np.random.default_rng(23)
        n = 1200
        X = rng.normal(size=(n, 6))
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
        tbl = Table({"features": X, "label": y})
        m = LightGBMClassifier(
            numIterations=8, useQuantizedGrad=True,
            featuresCol="features", labelCol="label",
        ).fit(tbl)
        p = np.asarray(m.transform(tbl)["probability"])[:, 1]
        assert auc(y, p, np.ones(n)) > 0.9


class TestChunkedU:
    """Row-chunked U pass: past the one-hot residency cliff the histogram
    pass streams row chunks through the same MXU contraction instead of
    falling back to the compare-built path (the old all-or-nothing budget
    cliff). Selection is pure host logic, so the >1M-row regression guard
    runs devicelessly in CI."""

    def test_over_budget_1m_shape_selects_chunked_mxu_path(self):
        # CI guard: the headline >1M-row shape (28 features x 256 bins)
        # must stream chunks on the MXU path, never fall off it
        from mmlspark_tpu.ops.u_histogram import chunked_u_spec, num_u_chunks

        spec = make_u_spec(256, 28)
        budget = 8 << 30  # the MMLSPARK_TPU_U_BUDGET default
        rows = 1_500_000
        assert u_bytes(rows, spec) > budget  # resident U would blow HBM
        c = chunked_u_spec(rows, spec, budget)
        assert c.chunk_rows > 0, "over-budget shape must chunk, not fall back"
        assert c.chunk_rows % 512 == 0  # row-alignment block
        assert c.widths == spec.widths and c.k_pad == spec.k_pad
        # double-buffered scan: current + next chunk one-hots fit the budget
        assert 2 * c.chunk_rows * c.k_pad <= budget
        assert num_u_chunks(rows, c) * c.chunk_rows >= rows
        # under-budget shapes keep the resident layout
        assert u_bytes(400_000, spec) <= budget

    def test_tiny_budget_floors_at_one_aligned_chunk(self):
        from mmlspark_tpu.ops.u_histogram import chunked_u_spec, num_u_chunks

        spec = make_u_spec(32, 7, per_feature=[32, 5, 17, 32, 2, 9, 31])
        c = chunked_u_spec(3000, spec, budget=1)
        assert c.chunk_rows == 512  # floor: one alignment block
        assert num_u_chunks(3000, c) == 6

    @pytest.mark.parametrize("quant", [False, True])
    def test_chunked_matches_resident(self, quant):
        import jax

        from mmlspark_tpu.ops.u_histogram import (
            build_histograms_u_chunked,
            chunked_u_spec,
            prepare_chunked_bins,
            stat_rows_quant,
        )

        widths, f, b, bins, g, h, c, node = _mixed_case()
        k = 5
        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        if quant:
            stats = stat_rows_quant(
                jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
                jax.random.PRNGKey(5),
            )
        else:
            stats = None
        ref = np.asarray(build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec, stats=stats,
        ))
        cspec = chunked_u_spec(len(bins), spec, budget=1)  # 512-row chunks
        chunks = prepare_chunked_bins(jnp.asarray(bins), cspec)
        assert chunks.shape == (6, f, 512)
        out = np.asarray(build_histograms_u_chunked(
            chunks, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, cspec, stats=stats,
        ))
        np.testing.assert_array_equal(out[..., 2], ref[..., 2])  # counts
        if quant:
            # integer accumulation: chunked partial sums are bit-exact
            np.testing.assert_array_equal(out, ref)
        else:
            # f32 accumulation: association differs only at rounding level
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-4)

    def test_train_over_budget_streams_chunks_and_publishes_event(
        self, monkeypatch
    ):
        from mmlspark_tpu.observability import HistogramChunked, get_bus

        rng = np.random.default_rng(29)
        n = 3000
        X = rng.normal(size=(n, 8))
        y = ((X[:, 0] * 1.5 + X[:, 1] * X[:, 2]) > 0).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=63)
        opts = TrainOptions(objective="binary", num_iterations=6,
                            num_leaves=15, max_bin=63, histogram_method="u")
        r_resident = train(bins, y, opts, mapper=mp)

        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        try:
            monkeypatch.setenv("MMLSPARK_TPU_U_BUDGET", "200000")
            r_chunked = train(bins, y, opts, mapper=mp)
        finally:
            bus.remove_listener(seen.append)
        ev = [e for e in seen if isinstance(e, HistogramChunked)]
        assert ev, "over-budget fit must publish HistogramChunked"
        assert ev[0].num_chunks > 1 and ev[0].chunk_rows % 512 == 0
        assert ev[0].budget_bytes == 200_000
        # same trees as the resident pass (f32 association tolerance)
        np.testing.assert_allclose(
            r_chunked.booster.leaf_values, r_resident.booster.leaf_values,
            rtol=1e-4, atol=1e-5,
        )
        a = auc(y, r_chunked.booster.raw_margin(X)[:, 0], np.ones(n))
        ar = auc(y, r_resident.booster.raw_margin(X)[:, 0], np.ones(n))
        assert abs(a - ar) < 0.002, (a, ar)


class TestFusedPanelDot:
    """The opt-in Pallas fusion (MMLSPARK_TPU_U_FUSED) must match the
    two-op XLA formulation bit-for-bit on the quant path and to bf16
    precision on the exact path (same precision model)."""

    @pytest.mark.parametrize("quant", [False, True])
    def test_matches_xla_path(self, quant):
        import jax

        from mmlspark_tpu.ops.u_histogram import (
            _fused_panel_dot,
            stat_rows_quant,
        )

        widths, f, b, bins, g, h, c, node = _mixed_case(seed=5, n=1024)
        k = 4
        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        if quant:
            stats, scales = stat_rows_quant(
                jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
                jax.random.PRNGKey(3),
            )
        else:
            stats = stat_rows(jnp.asarray(g), jnp.asarray(h), jnp.asarray(c))
        n = bins.shape[0]
        aux = jnp.concatenate([
            stats.astype(jnp.float32),
            jnp.asarray(node, jnp.float32)[None, :],
            jnp.zeros((4, n), jnp.float32),
        ])
        pad = u.shape[1] - n
        if pad:
            aux = jnp.pad(aux, ((0, 0), (0, pad)))
            aux = aux.at[3, n:].set(-1.0)
        fused = np.asarray(
            _fused_panel_dot(u, aux, k, quant=quant, interpret=True)
        )[:, : 3 * k]
        # XLA reference: the in-module non-fused branch
        key = jnp.tile(jnp.arange(k, dtype=jnp.int32), 3)[:, None]
        mask_t = key == jnp.asarray(node, jnp.int32)[None, :]
        if quant:
            panel = jnp.where(mask_t, jnp.repeat(stats, k, axis=0), jnp.int8(0))
            if pad:
                panel = jnp.pad(panel, ((0, 0), (0, pad)))
            ref = np.asarray(jnp.einsum(
                "kn,pn->kp", u.astype(jnp.int32), panel.astype(jnp.int32)))
            np.testing.assert_array_equal(fused, ref)
        else:
            panel = jnp.where(mask_t, jnp.repeat(stats, k, axis=0), jnp.bfloat16(0))
            if pad:
                panel = jnp.pad(panel, ((0, 0), (0, pad)))
            ref = np.asarray(jax.lax.dot_general(
                u.astype(jnp.bfloat16), panel,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))
            np.testing.assert_allclose(fused, ref, rtol=1e-5, atol=1e-3)


class TestAccumulatorDtype:
    """Deterministic overflow promotion for narrow histogram accumulators:
    f32 on the exact path; on the quant path the narrowest signed int whose
    range provably holds 127 * n_rows (each quantized stat is in [-127,
    127], so a bin's partial sum is bounded by 127 * members)."""

    def test_promotion_ladder(self):
        from mmlspark_tpu.ops.u_histogram import histogram_acc_dtype

        assert histogram_acc_dtype(10**9, False) == jnp.float32
        assert histogram_acc_dtype(258, True) == jnp.int16  # 127*258 = 32766
        assert histogram_acc_dtype(259, True) == jnp.int32
        assert histogram_acc_dtype(1 << 24, True) == jnp.int32

    def test_int16_tier_is_exact(self):
        import jax

        from mmlspark_tpu.ops.u_histogram import (
            histogram_acc_dtype,
            stat_rows_quant,
        )

        widths, f, b, bins, g, h, c, node = _mixed_case(seed=7, n=200)
        k = 3
        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins[:200]), spec)
        stats = stat_rows_quant(
            jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jax.random.PRNGKey(9),
        )
        assert histogram_acc_dtype(200, True) == jnp.int16
        packed16 = build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec, stats=stats, dequant=False,
        )
        assert packed16.dtype == jnp.int16
        full = build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec, stats=stats,
        )
        from mmlspark_tpu.ops.u_histogram import dequant_hist

        np.testing.assert_array_equal(
            np.asarray(dequant_hist(packed16, stats[1])), np.asarray(full)
        )


class TestSiblingSubtraction:
    """Sibling histogram subtraction (native LightGBM's always-on trick):
    build only the smaller child, derive the sibling as parent - smaller in
    PACKED space. On the quant path both orders are exact integer sums, so
    subtraction-on model text is byte-identical to subtraction-off; on the
    f32 path they differ only at rounding level."""

    def _fit_case(self, seed=11, n=1400):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, 8))
        y = ((X[:, 0] + X[:, 1] * X[:, 2]) > 0).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=63)
        return X, y, bins, mp

    def _ab(self, bins, y, opts, mp):
        import dataclasses

        r_on = train(bins, y, opts, mapper=mp)
        r_off = train(
            bins, y,
            dataclasses.replace(opts, histogram_subtraction=False),
            mapper=mp,
        )
        return r_on, r_off

    # Byte-identity is a QUANT-U-PATH property: integer subtraction in
    # spec space is exact, so the trained model text is identical with
    # subtraction on or off. Under MMLSPARK_TPU_NO_U=1 the quant request
    # falls back to f32 compare-built histograms (documented warning),
    # where parent - smaller rounds differently and a tipped split is
    # legitimate — the quant tests are skipped there and the f32 contract
    # (dAUC <= 2e-5) is pinned by test_f32_u_path_parity /
    # test_compare_built_path_parity instead.
    _quant_path = pytest.mark.skipif(
        os.environ.get("MMLSPARK_TPU_NO_U") == "1",
        reason="quant U path inactive under MMLSPARK_TPU_NO_U=1; f32 "
               "subtraction parity covered by the dAUC tests",
    )

    def _assert_model_parity(self, r_on, r_off):
        assert r_on.booster.model_to_string() == r_off.booster.model_to_string()

    def test_packed_space_subtraction_is_integer_exact(self):
        import jax

        from mmlspark_tpu.ops.u_histogram import stat_rows_quant

        widths, f, b, bins, g, h, c, _ = _mixed_case(seed=19)
        n = len(bins)
        # rows split 2 ways under one parent: node 0 = left, 1 = right
        child = (np.arange(n) % 3 == 0).astype(np.int32)
        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        stats = stat_rows_quant(
            jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jax.random.PRNGKey(7),
        )
        parent = build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.zeros(n, jnp.int32), 1, spec, stats=stats, dequant=False,
        )
        both = build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(child), 2, spec, stats=stats, dequant=False,
        )
        # parent - directly-built child == directly-built sibling, bit-exact
        np.testing.assert_array_equal(
            np.asarray(parent[0] - both[1]), np.asarray(both[0])
        )
        np.testing.assert_array_equal(
            np.asarray(parent[0] - both[0]), np.asarray(both[1])
        )

    @_quant_path
    def test_quant_model_text_byte_identical(self):
        X, y, bins, mp = self._fit_case()
        opts = TrainOptions(
            objective="binary", num_iterations=6, num_leaves=15,
            max_bin=63, histogram_method="u", use_quantized_grad=True,
        )
        r_on, r_off = self._ab(bins, y, opts, mp)
        self._assert_model_parity(r_on, r_off)

    def test_f32_u_path_parity(self):
        X, y, bins, mp = self._fit_case(seed=13)
        opts = TrainOptions(
            objective="binary", num_iterations=6, num_leaves=15,
            max_bin=63, histogram_method="u",
        )
        r_on, r_off = self._ab(bins, y, opts, mp)
        n = len(y)
        a_on = auc(y, r_on.booster.raw_margin(X)[:, 0], np.ones(n))
        a_off = auc(y, r_off.booster.raw_margin(X)[:, 0], np.ones(n))
        assert abs(a_on - a_off) <= 2e-5, (a_on, a_off)

    @_quant_path
    def test_bundled_quant_byte_identical(self):
        # EFB: subtraction must happen in PACKED space (before expansion);
        # _expand_bundled is linear, so the orders agree — and on the quant
        # path exactly.
        rng = np.random.default_rng(31)
        n = 1400
        blocks, card = 6, 5
        X = np.zeros((n, blocks * card))
        for bl in range(blocks):
            hot = rng.integers(0, card, n)
            X[np.arange(n), bl * card + hot] = rng.uniform(0.5, 2.0, n)
        X = np.hstack([X, rng.normal(size=(n, 3))])
        y = (X[:, 0] + 2 * X[:, card + 2] + X[:, -1] > 1.2).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=63, feature_bundling=True)
        assert mp.bundles is not None
        opts = TrainOptions(
            objective="binary", num_iterations=8, num_leaves=15,
            max_bin=63, histogram_method="u", use_quantized_grad=True,
        )
        r_on, r_off = self._ab(bins, y, opts, mp)
        self._assert_model_parity(r_on, r_off)

    @_quant_path
    def test_chunked_quant_byte_identical(self, monkeypatch):
        X, y, bins, mp = self._fit_case(seed=17)
        opts = TrainOptions(
            objective="binary", num_iterations=8, num_leaves=15,
            max_bin=63, histogram_method="u", use_quantized_grad=True,
        )
        monkeypatch.setenv("MMLSPARK_TPU_U_BUDGET", "120000")
        r_on, r_off = self._ab(bins, y, opts, mp)
        self._assert_model_parity(r_on, r_off)

    def test_compare_built_path_parity(self, monkeypatch):
        # MMLSPARK_TPU_NO_U=1: subtraction on the compare-built (non-U)
        # builders — the packed()/expand() split must hold there too
        monkeypatch.setenv("MMLSPARK_TPU_NO_U", "1")
        X, y, bins, mp = self._fit_case(seed=23)
        opts = TrainOptions(
            objective="binary", num_iterations=8, num_leaves=15, max_bin=63,
        )
        r_on, r_off = self._ab(bins, y, opts, mp)
        n = len(y)
        a_on = auc(y, r_on.booster.raw_margin(X)[:, 0], np.ones(n))
        a_off = auc(y, r_off.booster.raw_margin(X)[:, 0], np.ones(n))
        assert abs(a_on - a_off) <= 2e-5, (a_on, a_off)

    @_quant_path
    def test_multiclass_quant_byte_identical(self):
        X, y, bins, mp = self._fit_case(seed=37)
        y3 = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
        opts = TrainOptions(
            objective="multiclass", num_class=3, num_iterations=4,
            num_leaves=7, max_bin=63, histogram_method="u",
            use_quantized_grad=True,
        )
        r_on, r_off = self._ab(bins, y3.astype(np.float64), opts, mp)
        self._assert_model_parity(r_on, r_off)

    def test_event_published(self):
        from mmlspark_tpu.observability import HistogramSubtracted, get_bus

        X, y, bins, mp = self._fit_case(seed=41)
        opts = TrainOptions(
            objective="binary", num_iterations=4, num_leaves=15,
            max_bin=63, histogram_method="u", use_quantized_grad=True,
        )
        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        try:
            train(bins, y, opts, mapper=mp)
        finally:
            bus.remove_listener(seen.append)
        ev = [e for e in seen if isinstance(e, HistogramSubtracted)]
        assert ev, "subtraction fit must publish HistogramSubtracted"
        # quant at n > 258 rows -> int32 cache; under NO_U the quant
        # request falls back to f32 and the event reports that honestly
        exp = "float32" if os.environ.get("MMLSPARK_TPU_NO_U") == "1" else "int32"
        assert ev[0].acc_dtype == exp
        assert ev[0].children_per_split == 1
        assert ev[0].cache_bytes > 0 and ev[0].bytes_saved_per_tree > 0

    @pytest.mark.slow
    def test_procfit_two_process_parity(self):
        # procfit rejects the quant path, so the gang runs f32 histograms:
        # byte-identity is NOT a property there (parent - smaller rounds
        # differently than a direct build); the contract is structural
        # parity (model_texts_close) between subtraction on/off AND between
        # the 2-process gang and the serial fit, with the gang allreducing
        # only the smaller child per split.
        import dataclasses

        from mmlspark_tpu.lightgbm.procfit import (
            fit_process_group,
            model_texts_close,
        )

        X, y, _, _ = self._fit_case(seed=43, n=800)
        opts = TrainOptions(
            objective="binary", num_iterations=6, num_leaves=7,
            max_bin=32, min_data_in_leaf=5, seed=2,
        )
        r_on = fit_process_group(
            X, y, opts, num_processes=2,
            group_options={"epoch_timeout_s": 180.0},
        )
        r_off = fit_process_group(
            X, y, dataclasses.replace(opts, histogram_subtraction=False),
            num_processes=2, group_options={"epoch_timeout_s": 180.0},
        )
        assert model_texts_close(r_on.model_text, r_off.model_text)
        bins, mp = bin_dataset(X, max_bin=32)
        serial = train(bins, y, opts, mapper=mp)
        assert model_texts_close(
            r_on.model_text, serial.booster.model_to_string()
        )
