"""Precomputed-U histogram path (ops/u_histogram.py) and its train wiring.

The U pass replaces the reference engine's per-iteration native histogram
construction (``lightgbm/TrainUtils.scala:220-315``) with one MXU
contraction against a fit-resident one-hot; these tests pin (a) numerical
agreement with the bf16-input reference model, (b) exact counts, (c) the
packed per-feature-width layout, and (d) end-to-end training parity when
the path is forced on CPU (``histogram_method='u'``)."""

import numpy as np
import jax.numpy as jnp
import pytest

from mmlspark_tpu.lightgbm.binning import bin_dataset
from mmlspark_tpu.lightgbm.objectives import auc
from mmlspark_tpu.lightgbm.train import TrainOptions, train
from mmlspark_tpu.ops.histogram import build_histograms
from mmlspark_tpu.ops.u_histogram import (
    build_histograms_u,
    build_u,
    make_u_spec,
    stat_rows,
    u_bytes,
)


def _mixed_case(seed=0, n=3000, k=5):
    rng = np.random.default_rng(seed)
    widths = [32, 5, 17, 32, 2, 9, 31]
    f, b = len(widths), 32
    bins = np.stack(
        [rng.integers(0, w, size=n) for w in widths], axis=1
    ).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.uniform(0.1, 1, size=n).astype(np.float32)
    c = (rng.uniform(size=n) > 0.2).astype(np.float32)
    node = rng.integers(-1, k + 2, size=n).astype(np.int32)  # incl. OOR keys
    return widths, f, b, bins, g, h, c, node


class TestUHistogram:
    def test_matches_bf16_reference_and_counts_exact(self):
        widths, f, b, bins, g, h, c, node = _mixed_case()
        k = 5
        m = ((node >= 0) & (node < k)).astype(np.float32)
        bf = lambda a: np.asarray(jnp.asarray(a, jnp.bfloat16), np.float32)
        # reference: exact sums of bf16-rounded inputs — the precision model
        # of the MXU pass (bf16 inputs, f32 accumulation)
        ref = np.asarray(build_histograms(
            jnp.asarray(bins), jnp.asarray(bf(g) * m), jnp.asarray(bf(h) * m),
            jnp.asarray(c * m), jnp.asarray(np.clip(node, 0, k - 1)), k, b,
            method="segment",
        ))
        spec = make_u_spec(b, f, per_feature=widths)
        assert spec.k == sum(widths)  # packed, not f*b
        u = build_u(jnp.asarray(bins), spec)
        assert u.shape[0] == spec.k_pad
        for stats in (None, stat_rows(jnp.asarray(g), jnp.asarray(h), jnp.asarray(c))):
            out = np.asarray(build_histograms_u(
                u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
                jnp.asarray(node), k, spec, stats=stats,
            ))
            np.testing.assert_array_equal(out[..., 2], ref[..., 2])  # counts
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-4)

    def test_out_of_range_nodes_are_the_in_leaf_mask(self):
        widths, f, b, bins, g, h, c, node = _mixed_case(seed=3)
        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        k = 4
        out = np.asarray(build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec,
        ))
        in_range = (node >= 0) & (node < k)
        # total count over all cells of feature 0 == rows with in-range keys
        assert out[:, 0, :, 2].sum() == (c * in_range).sum()

    def test_panel_width_guard(self):
        widths, f, b, bins, g, h, c, node = _mixed_case()
        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        with pytest.raises(ValueError, match="lane group"):
            build_histograms_u(
                u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
                jnp.asarray(node), 64, spec,
            )

    def test_u_bytes_budget(self):
        spec = make_u_spec(256, 28)
        assert u_bytes(400_000, spec) == 400_384 * spec.k_pad  # 512-aligned rows


class TestUTrainParity:
    def test_forced_u_path_matches_default(self):
        rng = np.random.default_rng(0)
        n = 3000
        X = rng.normal(size=(n, 8))
        y = ((X[:, 0] * 1.5 + X[:, 1] * X[:, 2]) > 0).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=63)
        base = dict(objective="binary", num_iterations=6, num_leaves=15, max_bin=63)
        r0 = train(bins, y, TrainOptions(**base), mapper=mp)
        ru = train(bins, y, TrainOptions(**base, histogram_method="u"), mapper=mp)
        a0 = auc(y, r0.booster.raw_margin(X)[:, 0], np.ones(n))
        au = auc(y, ru.booster.raw_margin(X)[:, 0], np.ones(n))
        # CPU default path is exact f32; the U path is the bf16 MXU model —
        # structurally near-identical trees, AUC within noise
        assert abs(a0 - au) < 0.005, (a0, au)

    @pytest.mark.parametrize("variant", ["depthwise", "goss", "bagging", "multiclass"])
    def test_u_path_boosting_variants(self, variant):
        rng = np.random.default_rng(1)
        n = 2000
        X = rng.normal(size=(n, 6))
        if variant == "multiclass":
            y = np.digitize(X[:, 0], [-0.5, 0.5]).astype(np.float64)
            extra = dict(objective="multiclass", num_class=3)
        else:
            y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
            extra = dict(objective="binary")
        if variant == "depthwise":
            extra.update(growth="depthwise", max_depth=4)
        elif variant == "goss":
            extra.update(boosting_type="goss")
        elif variant == "bagging":
            extra.update(bagging_fraction=0.7, bagging_freq=1)
        bins, mp = bin_dataset(X, max_bin=31)
        r = train(
            bins, y,
            TrainOptions(num_iterations=4, num_leaves=7, max_bin=31,
                         histogram_method="u", **extra),
            mapper=mp,
        )
        margins = r.booster.raw_margin(X)
        if variant == "multiclass":
            acc = (margins.argmax(1) == y).mean()
            assert acc > 0.7, acc
        else:
            a = auc(y, margins[:, 0], np.ones(n))
            assert a > 0.85, a

    def test_device_resident_bins_accepted(self):
        from mmlspark_tpu.lightgbm.binning import bin_dataset_to_device

        rng = np.random.default_rng(2)
        n = 1500
        X = rng.normal(size=(n, 5))
        y = (X[:, 0] > 0).astype(np.float64)
        bins_np, mp = bin_dataset(X, max_bin=31)
        bins_dev, mp2 = bin_dataset_to_device(X, max_bin=31)
        np.testing.assert_array_equal(np.asarray(bins_dev), bins_np)
        np.testing.assert_array_equal(mp2.edges, mp.edges)
        opts = TrainOptions(objective="binary", num_iterations=3,
                            num_leaves=7, max_bin=31)
        r_np = train(bins_np, y, opts, mapper=mp)
        r_dev = train(bins_dev, y, opts, mapper=mp2)
        np.testing.assert_allclose(
            r_dev.booster.leaf_values, r_np.booster.leaf_values, rtol=1e-6
        )

    def test_forced_u_with_voting_parallel_degrades_gracefully(self):
        rng = np.random.default_rng(3)
        n = 1200
        X = rng.normal(size=(n, 5))
        y = (X[:, 0] > 0).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=31)
        r = train(
            bins, y,
            TrainOptions(objective="binary", num_iterations=3, num_leaves=7,
                         max_bin=31, histogram_method="u",
                         tree_learner="voting_parallel", top_k=3),
            mapper=mp,
        )
        a = auc(y, r.booster.raw_margin(X)[:, 0], np.ones(n))
        assert a > 0.85, a


class TestQuantizedGrad:
    """LightGBM's use_quantized_grad analogue: 8-bit stochastically-rounded
    stat rows, s8 x s8 integer MXU pass, per-stat dequant scales."""

    def test_stat_rows_quant_counts_exact_and_sums_unbiased(self):
        import jax

        rng = np.random.default_rng(7)
        n = 20000
        g = rng.normal(size=n).astype(np.float32)
        h = rng.uniform(0.05, 1.0, size=n).astype(np.float32)
        c = (rng.uniform(size=n) > 0.3).astype(np.float32)
        from mmlspark_tpu.ops.u_histogram import stat_rows_quant

        stats, scales = stat_rows_quant(
            jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jax.random.PRNGKey(0),
        )
        stats = np.asarray(stats)
        scales = np.asarray(scales)
        assert stats.dtype == np.int8
        # counts are bit-exact 0/1, scale exactly 1
        np.testing.assert_array_equal(stats[2], c.astype(np.int8))
        assert scales[2] == 1.0
        # per-element quantization stays within one grid step of the input
        for row, x, s in ((0, g, scales[0]), (1, h, scales[1])):
            deq = stats[row].astype(np.float32) * s
            np.testing.assert_allclose(deq, x, atol=float(s) + 1e-7)
            # stochastic rounding is unbiased => SUM of dequantized values
            # concentrates: n * grid * O(1/sqrt(n)) tolerance
            assert abs(deq.sum() - x.sum()) < float(s) * 6 * np.sqrt(n)

    def test_quant_histogram_counts_exact_gh_within_grid(self):
        import jax

        widths, f, b, bins, g, h, c, node = _mixed_case(seed=3)
        k = 5
        from mmlspark_tpu.ops.u_histogram import stat_rows_quant

        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        exact = np.asarray(build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec,
        ))
        qstats = stat_rows_quant(
            jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jax.random.PRNGKey(1),
        )
        quant = np.asarray(build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec, stats=qstats,
        ))
        # counts ride the exact int path: bit-identical
        np.testing.assert_array_equal(quant[..., 2], exact[..., 2])
        # g/h bin sums: each of the <=n member rows contributes at most one
        # grid step of quantization error
        scales = np.asarray(qstats[1])
        n_bin = exact[..., 2]
        for s_idx in (0, 1):
            bound = scales[s_idx] * (n_bin + 1) + 1e-4
            assert (np.abs(quant[..., s_idx] - exact[..., s_idx]) <= bound).all()

    def test_end_to_end_quantized_fit_quality_and_determinism(self):
        rng = np.random.default_rng(11)
        n = 4000
        X = rng.normal(size=(n, 8))
        y = ((X[:, 0] + X[:, 1] * X[:, 2]) > 0).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=63)
        base = TrainOptions(objective="binary", num_iterations=25,
                            num_leaves=15, max_bin=63, histogram_method="u")
        import dataclasses

        r_exact = train(bins, y, base, mapper=mp)
        qopts = dataclasses.replace(base, use_quantized_grad=True)
        r_q = train(bins, y, qopts, mapper=mp)
        a_exact = auc(y, r_exact.booster.raw_margin(X)[:, 0], np.ones(n))
        a_q = auc(y, r_q.booster.raw_margin(X)[:, 0], np.ones(n))
        assert a_q > a_exact - 0.01, (a_q, a_exact)
        # seeded stochastic rounding: same options => identical model
        r_q2 = train(bins, y, qopts, mapper=mp)
        np.testing.assert_array_equal(
            r_q.booster.leaf_values, r_q2.booster.leaf_values
        )

    def test_param_flows_from_stage(self):
        from mmlspark_tpu.lightgbm.classifier import LightGBMClassifier

        stage = LightGBMClassifier(useQuantizedGrad=True)
        assert stage._make_options(num_class=1).use_quantized_grad is True
        assert (
            LightGBMClassifier()._make_options(num_class=1).use_quantized_grad
            is False
        )

    def test_multiclass_quantized(self):
        rng = np.random.default_rng(13)
        n = 3000
        X = rng.normal(size=(n, 6))
        y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
        bins, mp = bin_dataset(X, max_bin=31)
        opts = TrainOptions(objective="multiclass", num_class=3,
                            num_iterations=10, num_leaves=7, max_bin=31,
                            histogram_method="u", use_quantized_grad=True)
        r = train(bins, y.astype(np.float64), opts, mapper=mp)
        pred = r.booster.raw_margin(X).argmax(1)
        assert (pred == y).mean() > 0.8

    def test_quant_falls_back_with_warning_when_u_inactive(self, caplog):
        import logging

        rng = np.random.default_rng(17)
        n = 1500
        X = rng.normal(size=(n, 5))
        y = (X[:, 0] > 0).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=63)
        opts = TrainOptions(objective="binary", num_iterations=3,
                            num_leaves=7, max_bin=63,
                            use_quantized_grad=True,
                            tree_learner="voting_parallel", top_k=3)
        with caplog.at_level(logging.WARNING, logger="mmlspark_tpu.lightgbm"):
            r = train(bins, y, opts, mapper=mp)
        assert any("use_quantized_grad" in m for m in caplog.messages)
        assert r.booster.num_trees >= 1


    def test_quant_through_binary_classifier_stage(self):
        # regression: binary classifiers carry num_class=2 with ONE margin
        # column; the stochastic-rounding keys must follow grad.shape[1]
        from mmlspark_tpu.data.table import Table
        from mmlspark_tpu.lightgbm.classifier import LightGBMClassifier

        rng = np.random.default_rng(23)
        n = 1200
        X = rng.normal(size=(n, 6))
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
        tbl = Table({"features": X, "label": y})
        m = LightGBMClassifier(
            numIterations=8, useQuantizedGrad=True,
            featuresCol="features", labelCol="label",
        ).fit(tbl)
        p = np.asarray(m.transform(tbl)["probability"])[:, 1]
        assert auc(y, p, np.ones(n)) > 0.9


class TestChunkedU:
    """Row-chunked U pass: past the one-hot residency cliff the histogram
    pass streams row chunks through the same MXU contraction instead of
    falling back to the compare-built path (the old all-or-nothing budget
    cliff). Selection is pure host logic, so the >1M-row regression guard
    runs devicelessly in CI."""

    def test_over_budget_1m_shape_selects_chunked_mxu_path(self):
        # CI guard: the headline >1M-row shape (28 features x 256 bins)
        # must stream chunks on the MXU path, never fall off it
        from mmlspark_tpu.ops.u_histogram import chunked_u_spec, num_u_chunks

        spec = make_u_spec(256, 28)
        budget = 8 << 30  # the MMLSPARK_TPU_U_BUDGET default
        rows = 1_500_000
        assert u_bytes(rows, spec) > budget  # resident U would blow HBM
        c = chunked_u_spec(rows, spec, budget)
        assert c.chunk_rows > 0, "over-budget shape must chunk, not fall back"
        assert c.chunk_rows % 512 == 0  # row-alignment block
        assert c.widths == spec.widths and c.k_pad == spec.k_pad
        # double-buffered scan: current + next chunk one-hots fit the budget
        assert 2 * c.chunk_rows * c.k_pad <= budget
        assert num_u_chunks(rows, c) * c.chunk_rows >= rows
        # under-budget shapes keep the resident layout
        assert u_bytes(400_000, spec) <= budget

    def test_tiny_budget_floors_at_one_aligned_chunk(self):
        from mmlspark_tpu.ops.u_histogram import chunked_u_spec, num_u_chunks

        spec = make_u_spec(32, 7, per_feature=[32, 5, 17, 32, 2, 9, 31])
        c = chunked_u_spec(3000, spec, budget=1)
        assert c.chunk_rows == 512  # floor: one alignment block
        assert num_u_chunks(3000, c) == 6

    @pytest.mark.parametrize("quant", [False, True])
    def test_chunked_matches_resident(self, quant):
        import jax

        from mmlspark_tpu.ops.u_histogram import (
            build_histograms_u_chunked,
            chunked_u_spec,
            prepare_chunked_bins,
            stat_rows_quant,
        )

        widths, f, b, bins, g, h, c, node = _mixed_case()
        k = 5
        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        if quant:
            stats = stat_rows_quant(
                jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
                jax.random.PRNGKey(5),
            )
        else:
            stats = None
        ref = np.asarray(build_histograms_u(
            u, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, spec, stats=stats,
        ))
        cspec = chunked_u_spec(len(bins), spec, budget=1)  # 512-row chunks
        chunks = prepare_chunked_bins(jnp.asarray(bins), cspec)
        assert chunks.shape == (6, f, 512)
        out = np.asarray(build_histograms_u_chunked(
            chunks, jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
            jnp.asarray(node), k, cspec, stats=stats,
        ))
        np.testing.assert_array_equal(out[..., 2], ref[..., 2])  # counts
        if quant:
            # integer accumulation: chunked partial sums are bit-exact
            np.testing.assert_array_equal(out, ref)
        else:
            # f32 accumulation: association differs only at rounding level
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-4)

    def test_train_over_budget_streams_chunks_and_publishes_event(
        self, monkeypatch
    ):
        from mmlspark_tpu.observability import HistogramChunked, get_bus

        rng = np.random.default_rng(29)
        n = 3000
        X = rng.normal(size=(n, 8))
        y = ((X[:, 0] * 1.5 + X[:, 1] * X[:, 2]) > 0).astype(np.float64)
        bins, mp = bin_dataset(X, max_bin=63)
        opts = TrainOptions(objective="binary", num_iterations=6,
                            num_leaves=15, max_bin=63, histogram_method="u")
        r_resident = train(bins, y, opts, mapper=mp)

        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        try:
            monkeypatch.setenv("MMLSPARK_TPU_U_BUDGET", "200000")
            r_chunked = train(bins, y, opts, mapper=mp)
        finally:
            bus.remove_listener(seen.append)
        ev = [e for e in seen if isinstance(e, HistogramChunked)]
        assert ev, "over-budget fit must publish HistogramChunked"
        assert ev[0].num_chunks > 1 and ev[0].chunk_rows % 512 == 0
        assert ev[0].budget_bytes == 200_000
        # same trees as the resident pass (f32 association tolerance)
        np.testing.assert_allclose(
            r_chunked.booster.leaf_values, r_resident.booster.leaf_values,
            rtol=1e-4, atol=1e-5,
        )
        a = auc(y, r_chunked.booster.raw_margin(X)[:, 0], np.ones(n))
        ar = auc(y, r_resident.booster.raw_margin(X)[:, 0], np.ones(n))
        assert abs(a - ar) < 0.002, (a, ar)


class TestFusedPanelDot:
    """The opt-in Pallas fusion (MMLSPARK_TPU_U_FUSED) must match the
    two-op XLA formulation bit-for-bit on the quant path and to bf16
    precision on the exact path (same precision model)."""

    @pytest.mark.parametrize("quant", [False, True])
    def test_matches_xla_path(self, quant):
        import jax

        from mmlspark_tpu.ops.u_histogram import (
            _fused_panel_dot,
            stat_rows_quant,
        )

        widths, f, b, bins, g, h, c, node = _mixed_case(seed=5, n=1024)
        k = 4
        spec = make_u_spec(b, f, per_feature=widths)
        u = build_u(jnp.asarray(bins), spec)
        if quant:
            stats, scales = stat_rows_quant(
                jnp.asarray(g), jnp.asarray(h), jnp.asarray(c),
                jax.random.PRNGKey(3),
            )
        else:
            stats = stat_rows(jnp.asarray(g), jnp.asarray(h), jnp.asarray(c))
        n = bins.shape[0]
        aux = jnp.concatenate([
            stats.astype(jnp.float32),
            jnp.asarray(node, jnp.float32)[None, :],
            jnp.zeros((4, n), jnp.float32),
        ])
        pad = u.shape[1] - n
        if pad:
            aux = jnp.pad(aux, ((0, 0), (0, pad)))
            aux = aux.at[3, n:].set(-1.0)
        fused = np.asarray(
            _fused_panel_dot(u, aux, k, quant=quant, interpret=True)
        )[:, : 3 * k]
        # XLA reference: the in-module non-fused branch
        key = jnp.tile(jnp.arange(k, dtype=jnp.int32), 3)[:, None]
        mask_t = key == jnp.asarray(node, jnp.int32)[None, :]
        if quant:
            panel = jnp.where(mask_t, jnp.repeat(stats, k, axis=0), jnp.int8(0))
            if pad:
                panel = jnp.pad(panel, ((0, 0), (0, pad)))
            ref = np.asarray(jnp.einsum(
                "kn,pn->kp", u.astype(jnp.int32), panel.astype(jnp.int32)))
            np.testing.assert_array_equal(fused, ref)
        else:
            panel = jnp.where(mask_t, jnp.repeat(stats, k, axis=0), jnp.bfloat16(0))
            if pad:
                panel = jnp.pad(panel, ((0, 0), (0, pad)))
            ref = np.asarray(jax.lax.dot_general(
                u.astype(jnp.bfloat16), panel,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))
            np.testing.assert_allclose(fused, ref, rtol=1e-5, atol=1e-3)
