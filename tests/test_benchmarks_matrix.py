"""The wide golden matrix — the reference's 190-row benchmark CSVs scaled
to this runtime (``benchmarks_VerifyLightGBMClassifier.csv`` is 31
dataset x boosting rows; ``benchmarks_VerifyTrainClassifier.csv`` is a
111-row learner matrix). Every row here is a pinned metric asserted in CI:
classifier x 4 datasets x 4 boosting types, regressor x 4 datasets x 4
boosting types, the TrainClassifier/TrainRegressor CROSS-LEARNER matrices
(7 classification + 6 regression learner families through the wrapper +
ComputeModelStatistics flow — 89 rows incl. the multiclass slice, the
VerifyTrainClassifier analogue), multiclass, categorical, VW per-loss (adagrad AND ftrl),
ragged-group LTR ndcg at several cutoffs, the train/tune wrappers, and
the quantized-gradient slice (use_quantized_grad AUC + logloss per
dataset, seeded-deterministic). 198 pinned rows total across the
golden_*.csv files — the reference's benchmark breadth — incl. the
regression-objective matrix (l1/huber/quantile/poisson/tweedie), per-cell
AUC AND logloss on the classifier matrix, and a labelGain-wired ranker
dataset.

Promote intended changes by copying the corresponding
``golden_matrix_*.csv.new.csv`` over its golden (the harness writes them
on every run)."""

import os

import numpy as np
import pytest

from mmlspark_tpu.core.benchmarks import BenchmarkSuite
from mmlspark_tpu.data.table import Table

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "benchmarks")


def _golden(name):
    return os.path.join(GOLDEN_DIR, f"golden_matrix_{name}.csv")

BOOSTING = (
    ("gbdt", {}),
    ("goss", {}),
    ("dart", {"dropRate": 0.2}),
    ("rf", {"baggingFraction": 0.6, "baggingFreq": 1}),
)


def _auc(y, score):
    from mmlspark_tpu.lightgbm.objectives import auc

    return float(auc(np.asarray(y, np.float64), np.asarray(score), np.ones(len(y))))


def _split(X, y, seed=0, frac=0.8):
    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(y))
    X, y = np.asarray(X)[perm], np.asarray(y, dtype=np.float64)[perm]
    n = int(frac * len(y))
    return (X[:n], y[:n]), (X[n:], y[n:])


def _table(X, y):
    return Table({"features": np.asarray(X, np.float64), "label": np.asarray(y, np.float64)})


@pytest.fixture(scope="module")
def class_sets():
    from sklearn.datasets import load_breast_cancer, load_digits, load_wine, make_classification

    bc = load_breast_cancer()
    dg = load_digits()
    wn = load_wine()
    Xs, ys = make_classification(
        n_samples=1500, n_features=12, n_informative=6, flip_y=0.05,
        random_state=11,
    )
    return {
        "breastcancer": _split(bc.data, bc.target, 0),
        "digitszero": _split(dg.data, (dg.target == 0).astype(float), 2),
        "winebinary": _split(wn.data, (wn.target == 0).astype(float), 1),
        "synthetic": _split(Xs, ys, 3),
    }


@pytest.fixture(scope="module")
def reg_sets():
    from sklearn.datasets import load_diabetes, make_friedman1, make_friedman2, make_regression

    db = load_diabetes()
    X1, y1 = make_friedman1(n_samples=900, n_features=10, noise=1.0, random_state=0)
    X2, y2 = make_friedman2(n_samples=900, noise=0.5, random_state=0)
    Xl, yl = make_regression(n_samples=900, n_features=8, noise=8.0, random_state=4)
    return {
        "diabetes": _split(db.data, db.target, 0),
        "friedman1": _split(X1, y1, 1),
        "friedman2": _split(X2, y2 / 100.0, 2),
        "linear": _split(Xl, yl, 3),
    }


def test_golden_matrix_classifiers(class_sets):
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.lightgbm.objectives import binary_logloss

    suite = BenchmarkSuite("matrix_classifier")
    for dname, ((Xtr, ytr), (Xte, yte)) in class_sets.items():
        for boosting, extra in BOOSTING:
            m = LightGBMClassifier(
                numIterations=30, numLeaves=15, boostingType=boosting,
                seed=0, parallelism="serial", **extra,
            ).fit(_table(Xtr, ytr))
            margins = m.booster.raw_margin(Xte)[:, 0]
            suite.add(f"{dname}_{boosting}_auc", _auc(yte, margins), 0.015)
            # second metric per cell, same fit: logloss catches calibration
            # drift AUC is blind to (rank-preserving margin scaling)
            suite.add(
                f"{dname}_{boosting}_logloss",
                float(binary_logloss(yte, margins, np.ones(len(yte)))),
                0.06, higher_is_better=False,
            )
    suite.verify(_golden("classifier"))


def test_golden_matrix_regressors(reg_sets):
    from mmlspark_tpu.lightgbm import LightGBMRegressor

    suite = BenchmarkSuite("matrix_regressor")
    for dname, ((Xtr, ytr), (Xte, yte)) in reg_sets.items():
        scale = float(np.std(ytr)) or 1.0
        for boosting, extra in BOOSTING:
            m = LightGBMRegressor(
                numIterations=40, numLeaves=15, boostingType=boosting,
                seed=0, parallelism="serial", **extra,
            ).fit(_table(Xtr, ytr))
            rmse = float(np.sqrt(np.mean((m.booster.raw_margin(Xte)[:, 0] - yte) ** 2)))
            suite.add(f"{dname}_{boosting}_rmse", rmse / scale, 0.08,
                      higher_is_better=False)
    suite.verify(_golden("regressor"))


@pytest.fixture(scope="module")
def multiclass_sets():
    """(name, X, y, iters) triples shared by BOTH multiclass golden suites —
    one definition so the dataset construction cannot silently diverge."""
    from sklearn.datasets import load_digits, load_wine, make_blobs

    wn = load_wine()
    dg = load_digits()
    Xb, yb = make_blobs(n_samples=900, centers=4, n_features=6,
                        cluster_std=3.0, random_state=5)
    return (
        ("wine", wn.data, wn.target, 25),
        ("digits10", dg.data[:900], dg.target[:900], 25),
        ("blobs4", Xb, yb, 15),
    )


def test_golden_matrix_multiclass_and_categorical(class_sets, multiclass_sets):
    from mmlspark_tpu.lightgbm import LightGBMClassifier, LightGBMRegressor

    suite = BenchmarkSuite("matrix_multiclass")
    for dname, X, y, iters in multiclass_sets:
        (Xtr, ytr), (Xte, yte) = _split(X, y, 1)
        m = LightGBMClassifier(
            objective="multiclass", numIterations=iters, numLeaves=15,
            minDataInLeaf=5, seed=0, parallelism="serial",
        ).fit(_table(Xtr, ytr))
        acc = float((m.booster.raw_margin(Xte).argmax(axis=1) == yte).mean())
        suite.add(f"{dname}_multiclass_acc", acc, 0.05)

    # categorical splits: classifier AND regressor rows
    rng = np.random.default_rng(21)
    nc = 2500
    catf = rng.integers(0, 10, size=nc)
    eff = rng.normal(size=10) * 2.0
    Xc = np.column_stack([catf.astype(np.float64), rng.normal(size=(nc, 3))])
    yc = ((eff[catf] + Xc[:, 1]) > 0).astype(np.float64)
    (Xtr, ytr), (Xte, yte) = _split(Xc, yc, 4)
    mc = LightGBMClassifier(
        numIterations=20, numLeaves=15, seed=0, parallelism="serial",
        categoricalSlotIndexes=[0],
    ).fit(_table(Xtr, ytr))
    suite.add("catshape_gbdt_auc", _auc(yte, mc.booster.raw_margin(Xte)[:, 0]), 0.015)

    ycr = eff[catf] + Xc[:, 1] + 0.2 * rng.normal(size=nc)
    (Xtr, ytr), (Xte, yte) = _split(Xc, ycr, 5)
    mr = LightGBMRegressor(
        numIterations=25, numLeaves=15, seed=0, parallelism="serial",
        categoricalSlotIndexes=[0],
    ).fit(_table(Xtr, ytr))
    rmse = float(np.sqrt(np.mean((mr.booster.raw_margin(Xte)[:, 0] - yte) ** 2)))
    suite.add("catshape_gbdt_rmse", rmse / float(np.std(ytr)), 0.08,
              higher_is_better=False)

    # isUnbalance golden (positive-recall at the default threshold)
    rngu = np.random.default_rng(31)
    Xu = rngu.normal(size=(2500, 6))
    yu = ((Xu[:, 0] + 0.5 * rngu.normal(size=2500)) > 1.2).astype(np.float64)
    (Xtr, ytr), (Xte, yte) = _split(Xu, yu, 6)
    mu = LightGBMClassifier(
        numIterations=15, numLeaves=15, isUnbalance=True, seed=0,
        parallelism="serial",
    ).fit(_table(Xtr, ytr))
    pred = (mu.booster.raw_margin(Xte)[:, 0] > 0).astype(float)
    pos = yte > 0.5
    suite.add("unbalanced_isunbalance_recall",
              float(pred[pos].mean()) if pos.any() else 0.0, 0.06)
    suite.verify(_golden("multiclass"))


def test_golden_matrix_cross_learner_classifiers(class_sets):
    """The TrainClassifier x learner matrix — the reference's
    ``benchmarks_VerifyTrainClassifier.csv`` shape (111 rows of learner x
    dataset metrics through the SAME wrapper): every classification learner
    family runs through TrainClassifier + ComputeModelStatistics, with
    accuracy AND AUC pinned per dataset."""
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.train import ComputeModelStatistics, TrainClassifier
    from mmlspark_tpu.vw import VowpalWabbitClassifier

    def lgbm(**kw):
        return LightGBMClassifier(
            numIterations=25, numLeaves=15, seed=0, parallelism="serial", **kw
        )

    LEARNERS = (
        ("lgbm_gbdt", lambda: lgbm()),
        ("lgbm_goss", lambda: lgbm(boostingType="goss")),
        ("lgbm_dart", lambda: lgbm(boostingType="dart", dropRate=0.2)),
        ("lgbm_rf", lambda: lgbm(
            boostingType="rf", baggingFraction=0.6, baggingFreq=1)),
        ("vw_logistic", lambda: VowpalWabbitClassifier(numPasses=8)),
        ("vw_ftrl", lambda: VowpalWabbitClassifier(
            numPasses=8, passThroughArgs="--ftrl --ftrl_alpha 0.1")),
        ("vw_hinge", lambda: VowpalWabbitClassifier(
            numPasses=8, passThroughArgs="--loss_function hinge")),
    )
    suite = BenchmarkSuite("matrix_trainclassifier")
    for dname, ((Xtr, ytr), (Xte, yte)) in class_sets.items():
        # one normalization for every learner (VW is scale-sensitive; trees
        # are invariant to it, so the comparison stays apples-to-apples)
        mu, sd = Xtr.mean(0), Xtr.std(0) + 1e-9
        Xtr_n, Xte_n = (Xtr - mu) / sd, (Xte - mu) / sd
        for lname, make in LEARNERS:
            m = TrainClassifier(model=make(), labelCol="label").fit(
                _table(Xtr_n, ytr)
            )
            stats = ComputeModelStatistics(labelCol="label").transform(
                m.transform(_table(Xte_n, yte))
            )
            suite.add(f"{dname}_{lname}_acc", float(stats["accuracy"][0]), 0.03)
            suite.add(f"{dname}_{lname}_auc", float(stats["AUC"][0]), 0.03)
    suite.verify(_golden("trainclassifier"))


def test_golden_matrix_cross_learner_multiclass(multiclass_sets):
    """Multiclass through the SAME TrainClassifier + ComputeModelStatistics
    wrapper flow: 3 datasets x 3 boosting types, accuracy pinned (the
    multiclass slice of the reference's cross-learner matrix)."""
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.train import ComputeModelStatistics, TrainClassifier

    suite = BenchmarkSuite("matrix_trainmulticlass")
    for dname, X, y, _iters in multiclass_sets:
        (Xtr, ytr), (Xte, yte) = _split(X, y, 7)
        for boosting, extra in (("gbdt", {}), ("goss", {}),
                                ("dart", {"dropRate": 0.2})):
            m = TrainClassifier(
                model=LightGBMClassifier(
                    objective="multiclass", numIterations=20, numLeaves=15,
                    minDataInLeaf=5, boostingType=boosting, seed=0,
                    parallelism="serial", **extra,
                ),
                labelCol="label",
            ).fit(_table(Xtr, ytr))
            stats = ComputeModelStatistics(labelCol="label").transform(
                m.transform(_table(Xte, yte))
            )
            suite.add(
                f"{dname}_lgbm_{boosting}_acc", float(stats["accuracy"][0]), 0.05
            )
    suite.verify(_golden("trainmulticlass"))


def test_golden_matrix_cross_learner_regressors(reg_sets):
    """TrainRegressor x learner matrix (the regression half of the
    reference's cross-learner benchmarks): scale-normalized RMSE through
    TrainRegressor + ComputeModelStatistics per learner family."""
    from mmlspark_tpu.lightgbm import LightGBMRegressor
    from mmlspark_tpu.train import ComputeModelStatistics, TrainRegressor
    from mmlspark_tpu.vw import VowpalWabbitRegressor

    def lgbm(**kw):
        return LightGBMRegressor(
            numIterations=35, numLeaves=15, seed=0, parallelism="serial", **kw
        )

    LEARNERS = (
        ("lgbm_gbdt", lambda: lgbm()),
        ("lgbm_goss", lambda: lgbm(boostingType="goss")),
        ("lgbm_dart", lambda: lgbm(boostingType="dart", dropRate=0.2)),
        ("lgbm_rf", lambda: lgbm(
            boostingType="rf", baggingFraction=0.6, baggingFreq=1)),
        ("vw_squared", lambda: VowpalWabbitRegressor(numPasses=10)),
        ("vw_ftrl", lambda: VowpalWabbitRegressor(
            numPasses=10, passThroughArgs="--ftrl --ftrl_alpha 0.1")),
    )
    suite = BenchmarkSuite("matrix_trainregressor")
    for dname, ((Xtr, ytr), (Xte, yte)) in reg_sets.items():
        mu, sd = Xtr.mean(0), Xtr.std(0) + 1e-9
        Xtr_n, Xte_n = (Xtr - mu) / sd, (Xte - mu) / sd
        scale = float(np.std(ytr)) or 1.0
        for lname, make in LEARNERS:
            m = TrainRegressor(model=make(), labelCol="label").fit(
                _table(Xtr_n, ytr)
            )
            stats = ComputeModelStatistics(
                labelCol="label", evaluationMetric="regression"
            ).transform(m.transform(_table(Xte_n, yte)))
            suite.add(
                f"{dname}_{lname}_rmse", float(stats["root_mean_squared_error"][0]) / scale,
                0.08, higher_is_better=False,
            )
    suite.verify(_golden("trainregressor"))


def test_golden_matrix_regression_objectives(reg_sets):
    """Objective-math goldens: every non-default regression objective
    (l1/huber/quantile/poisson/tweedie) pinned on two real datasets with an
    objective-appropriate metric — l1/huber by scale-normalized MAE,
    quantile by empirical coverage at alpha, poisson/tweedie by normalized
    RMSE on positive targets. A silent gradient/hessian regression in any
    objective moves its rows."""
    from mmlspark_tpu.lightgbm import LightGBMRegressor

    suite = BenchmarkSuite("matrix_objectives")
    for dname in ("diabetes", "friedman1"):  # both have positive targets
        (Xtr, ytr), (Xte, yte) = reg_sets[dname]
        scale = float(np.std(ytr)) or 1.0

        def fit(objective, **extra):
            return LightGBMRegressor(
                objective=objective, numIterations=40, numLeaves=15,
                seed=0, parallelism="serial", **extra,
            ).fit(_table(Xtr, ytr))

        for objective in ("regression_l1", "huber"):
            m = fit(objective)
            mae = float(np.mean(np.abs(m.booster.raw_margin(Xte)[:, 0] - yte)))
            suite.add(f"{dname}_{objective}_mae", mae / scale, 0.08,
                      higher_is_better=False)

        mq = fit("quantile", alpha=0.9)
        coverage = float((yte <= mq.booster.raw_margin(Xte)[:, 0]).mean())
        # |coverage - alpha| so drift in EITHER direction moves the row
        # (a one-sided coverage pin would pass an overshooting fit)
        suite.add(f"{dname}_quantile090_coverage_err", abs(coverage - 0.9),
                  0.07, higher_is_better=False)

        for objective in ("poisson", "tweedie"):
            m = fit(objective)
            pred = np.exp(m.booster.raw_margin(Xte)[:, 0])  # log-link margins
            rmse = float(np.sqrt(np.mean((pred - yte) ** 2)))
            suite.add(f"{dname}_{objective}_rmse", rmse / scale, 0.10,
                      higher_is_better=False)
    suite.verify(_golden("objectives"))


def test_golden_matrix_vw(class_sets, reg_sets):
    from mmlspark_tpu.vw import VowpalWabbitClassifier, VowpalWabbitRegressor
    from mmlspark_tpu.lightgbm.objectives import binary_logloss

    suite = BenchmarkSuite("matrix_vw")
    for dname in ("breastcancer", "synthetic"):
        (Xtr, ytr), (Xte, yte) = class_sets[dname]
        mu, sd = Xtr.mean(0), Xtr.std(0) + 1e-9
        Xtr_n, Xte_n = (Xtr - mu) / sd, (Xte - mu) / sd
        for args, label in (("", "adagrad"), ("--ftrl --ftrl_alpha 0.1", "ftrl")):
            m = VowpalWabbitClassifier(numPasses=5, passThroughArgs=args).fit(
                _table(Xtr_n, ytr)
            )
            margins = m._margins(_table(Xte_n, yte))
            suite.add(f"{dname}_vw_{label}_auc", _auc(yte, margins), 0.02)
        mh = VowpalWabbitClassifier(
            numPasses=5, passThroughArgs="--loss_function hinge"
        ).fit(_table(Xtr_n, ytr))
        suite.add(f"{dname}_vw_hinge_acc",
                  float(((mh._margins(_table(Xte_n, yte)) > 0) == (yte > 0.5)).mean()),
                  0.03)

    for dname in ("diabetes", "friedman1"):
        (Xtr, ytr), (Xte, yte) = reg_sets[dname]
        mu, sd = Xtr.mean(0), Xtr.std(0) + 1e-9
        ymu, ysd = ytr.mean(), ytr.std() or 1.0
        m = VowpalWabbitRegressor(numPasses=8).fit(
            _table((Xtr - mu) / sd, (ytr - ymu) / ysd)
        )
        pred = m._margins(_table((Xte - mu) / sd, yte)) * ysd + ymu
        suite.add(f"{dname}_vw_squared_rmse",
                  float(np.sqrt(np.mean((pred - yte) ** 2)) / ysd), 0.1,
                  higher_is_better=False)
        mq = VowpalWabbitRegressor(
            numPasses=8, passThroughArgs="--loss_function quantile --quantile_tau 0.5"
        ).fit(_table((Xtr - mu) / sd, (ytr - ymu) / ysd))
        predq = mq._margins(_table((Xte - mu) / sd, yte)) * ysd + ymu
        suite.add(f"{dname}_vw_quantile_mae",
                  float(np.mean(np.abs(predq - yte)) / ysd), 0.1,
                  higher_is_better=False)
    suite.verify(_golden("vw"))


def test_golden_matrix_ranker_ragged():
    """LTR goldens with RAGGED groups (sizes 3..25) at several ndcg
    cutoffs — the reference pins lambdarank metrics on a real LTR set
    (VerifyLightGBMRanker.scala); this is the deterministic local stand-in."""
    from mmlspark_tpu.lightgbm import LightGBMRanker
    from mmlspark_tpu.lightgbm.ranker import ndcg_at_k

    suite = BenchmarkSuite("matrix_ranker")
    # dataset "c" pins the labelGain wiring: a LINEAR gain table instead of
    # LightGBM's default 2^i - 1 must change the fitted ordering pressure
    for seed, tag, extra in ((9, "a", {}), (23, "b", {}),
                             (31, "c", {"labelGain": [0, 1, 2, 3, 4]})):
        rng = np.random.default_rng(seed)
        sizes = rng.integers(3, 26, size=50)
        n = int(sizes.sum())
        group = np.repeat(np.arange(len(sizes)), sizes)
        X = rng.normal(size=(n, 6))
        rel = np.clip(
            (X[:, 0] * 1.2 + 0.5 * X[:, 1] + rng.normal(scale=0.5, size=n)) + 1.5,
            0, 4,
        ).round()
        t = Table({
            "features": X, "label": rel.astype(np.float64),
            "query": group.astype(np.int64),
        })
        m = LightGBMRanker(
            numIterations=25, groupCol="query", minDataInLeaf=3, seed=0,
            parallelism="serial", **extra,
        ).fit(t)
        score = m.transform(t)["prediction"]
        ks = (3, 5, 10) if tag != "c" else (1, 3, 5, 10)
        for k in ks:
            suite.add(f"ltr{tag}_ndcg_at_{k}", float(ndcg_at_k(rel, score, group, k)),
                      0.02)
    suite.verify(_golden("ranker"))


def test_golden_matrix_wrappers(class_sets, reg_sets):
    from mmlspark_tpu.automl import TuneHyperparameters
    from mmlspark_tpu.lightgbm import LightGBMClassifier, LightGBMRegressor
    from mmlspark_tpu.train import TrainClassifier, TrainRegressor

    suite = BenchmarkSuite("matrix_wrappers")
    (Xtr, ytr), (Xte, yte) = class_sets["breastcancer"]
    tc = TrainClassifier(
        model=LightGBMClassifier(numIterations=15, numLeaves=7, parallelism="serial"),
        labelCol="label",
    ).fit(_table(Xtr, ytr))
    out = tc.transform(_table(Xte, yte))
    suite.add("breastcancer_trainclassifier_acc",
              float((out["prediction"] == yte).mean()), 0.03)

    (Xtr, ytr), (Xte, yte) = reg_sets["friedman1"]
    tr = TrainRegressor(
        model=LightGBMRegressor(numIterations=30, numLeaves=7, parallelism="serial"),
        labelCol="label",
    ).fit(_table(Xtr, ytr))
    outr = tr.transform(_table(Xte, yte))
    rmse = float(np.sqrt(np.mean((outr["prediction"] - yte) ** 2)))
    suite.add("friedman1_trainregressor_rmse", rmse / float(np.std(ytr)), 0.08,
              higher_is_better=False)

    (Xtr, ytr), (Xte, yte) = class_sets["synthetic"]
    from mmlspark_tpu.automl.hyperparam import DiscreteHyperParam

    tuned = TuneHyperparameters(
        models=LightGBMClassifier(numIterations=10, parallelism="serial"),
        paramSpace={"numLeaves": DiscreteHyperParam([7, 15])},
        evaluationMetric="accuracy",
        numFolds=2,
        numRuns=2,
        seed=0,
    ).fit(_table(Xtr, ytr))
    suite.add("synthetic_tune_best_acc", float(tuned.getBestMetric()), 0.03)
    suite.verify(_golden("wrappers"))


def test_golden_matrix_quantized(class_sets):
    """Quantized-gradient fits (use_quantized_grad) are seeded-
    deterministic — pin AUC + logloss across the classification datasets.
    Engine-level with histogram_method='u' so the quantized s8 pass
    actually runs under CPU CI (the stage default would silently fall back
    to exact stats off-TPU, pinning nothing new)."""
    from mmlspark_tpu.lightgbm.binning import bin_dataset
    from mmlspark_tpu.lightgbm.objectives import binary_logloss
    from mmlspark_tpu.lightgbm.train import TrainOptions, train

    suite = BenchmarkSuite("matrix_quant")
    for dname, ((Xtr, ytr), (Xte, yte)) in class_sets.items():
        bins, mp = bin_dataset(np.asarray(Xtr, np.float64), max_bin=255)
        opts = TrainOptions(
            objective="binary", num_iterations=30, num_leaves=15, seed=0,
            histogram_method="u", use_quantized_grad=True,
        )
        r = train(bins, np.asarray(ytr, np.float64), opts, mapper=mp)
        margins = r.booster.raw_margin(np.asarray(Xte, np.float64))[:, 0]
        suite.add(f"{dname}_quant_auc", _auc(yte, margins), 0.015)
        suite.add(
            f"{dname}_quant_logloss",
            float(binary_logloss(yte, margins, np.ones(len(yte)))),
            0.06, higher_is_better=False,
        )
    suite.verify(_golden("quant"))
