"""Params/pipeline contract tests (reference: core/contracts + fuzzing suites)."""

import numpy as np
import pytest

from mmlspark_tpu.core.params import (
    HasInputCol,
    HasOutputCol,
    Param,
    Params,
    gt,
    one_of,
    to_int,
    to_str,
)
from mmlspark_tpu.core.pipeline import (
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    Transformer,
    ml_transform,
)
from mmlspark_tpu.data.table import Table


class DummyStage(HasInputCol, HasOutputCol, Transformer):
    scale = Param("multiplier", default=2.0, converter=float, validator=gt(0))
    mode = Param("mode", default="fast", converter=to_str, validator=one_of("fast", "slow"))

    def transform(self, table):
        return table.with_column(
            self.getOutputCol(), table.column(self.getInputCol()) * self.getScale()
        )


class DoublerEstimator(HasInputCol, HasOutputCol, Estimator):
    def _fit(self, table):
        m = DoublerModel(
            inputCol=self.getInputCol(),
            outputCol=self.getOutputCol(),
            offset=float(np.mean(table.column(self.getInputCol()))),
        )
        m.parent = self
        return m


class DoublerModel(HasInputCol, HasOutputCol, Model):
    offset = Param("learned offset", default=0.0, converter=float)

    def transform(self, table):
        return table.with_column(
            self.getOutputCol(), table.column(self.getInputCol()) + self.getOffset()
        )


def test_param_defaults_and_accessors():
    s = DummyStage(inputCol="a", outputCol="b")
    assert s.getScale() == 2.0
    assert s.getInputCol() == "a"
    s.setScale(3)
    assert s.getScale() == 3.0 and isinstance(s.getScale(), float)
    assert s.scale == 3.0  # descriptor read


def test_param_validation():
    s = DummyStage(inputCol="a", outputCol="b")
    with pytest.raises(ValueError):
        s.setScale(-1)
    with pytest.raises(ValueError):
        s.setMode("medium")
    with pytest.raises(KeyError):
        s.set("nonexistent", 1)


def test_kwargs_construction_and_copy():
    s = DummyStage(inputCol="x", outputCol="y", scale=5)
    s2 = s.copy({"scale": 7})
    assert s.getScale() == 5 and s2.getScale() == 7
    assert s2.uid == s.uid
    assert "multiplier" in s.explainParams()


def test_transform(basic_table):
    s = DummyStage(inputCol="doubles", outputCol="out", scale=2)
    out = s.transform(basic_table)
    np.testing.assert_allclose(out["out"], basic_table["doubles"] * 2)
    # input untouched (immutability)
    assert "out" not in basic_table


def test_pipeline_fit_transform(basic_table):
    pipe = Pipeline(
        stages=[
            DummyStage(inputCol="doubles", outputCol="mid", scale=2),
            DoublerEstimator(inputCol="mid", outputCol="out"),
        ]
    )
    model = pipe.fit(basic_table)
    assert isinstance(model, PipelineModel)
    out = model.transform(basic_table)
    mid = basic_table["doubles"] * 2
    np.testing.assert_allclose(out["out"], mid + np.mean(mid))


def test_ml_transform_sugar(basic_table):
    out = ml_transform(
        basic_table,
        DummyStage(inputCol="doubles", outputCol="a2", scale=2),
        DummyStage(inputCol="a2", outputCol="a4", scale=2),
    )
    np.testing.assert_allclose(out["a4"], basic_table["doubles"] * 4)


def test_save_load_roundtrip(tmp_path, basic_table, table_equal):
    s = DummyStage(inputCol="doubles", outputCol="out", scale=3)
    p = str(tmp_path / "stage")
    s.save(p)
    s2 = DummyStage.load(p)
    assert s2.uid == s.uid and s2.getScale() == 3.0
    table_equal(s.transform(basic_table), s2.transform(basic_table))


def test_pipeline_model_save_load(tmp_path, basic_table, table_equal):
    pipe = Pipeline(
        stages=[
            DummyStage(inputCol="doubles", outputCol="mid", scale=2),
            DoublerEstimator(inputCol="mid", outputCol="out"),
        ]
    )
    model = pipe.fit(basic_table)
    p = str(tmp_path / "pm")
    model.save(p)
    loaded = PipelineModel.load(p)
    table_equal(model.transform(basic_table), loaded.transform(basic_table))


def test_complex_param_array_roundtrip(tmp_path):
    class ArrayHolder(Transformer):
        weights = Param("array param", is_complex=True)

        def transform(self, table):
            return table

    h = ArrayHolder(weights=np.arange(6.0).reshape(2, 3))
    p = str(tmp_path / "h")
    h.save(p)
    h2 = ArrayHolder.load(p)
    np.testing.assert_array_equal(h2.getWeights(), h.getWeights())
