"""Pinned VW featurizer feature-space goldens.

``tests/fixtures/golden_matrix_vw.csv`` stores the EXACT (indices, values)
the featurizer emits for a fixed table under a matrix of configs — string
split/unsplit columns, string arrays, maps, numeric/bool/dense columns,
collision-rich small spaces, both ``sumCollisions`` modes. The fixture was
generated from the original per-row implementation; any rewrite (including
the batched one) must reproduce it byte-for-byte, or the hashed feature
space has silently shifted and every downstream model breaks.

Regenerate (only when the feature space is INTENTIONALLY changed):

    python tests/test_vw_featurizer_golden.py --regen
"""

import csv
import os

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.vw import VowpalWabbitFeaturizer

GOLDEN = os.path.join(os.path.dirname(__file__), "fixtures", "golden_matrix_vw.csv")


def golden_table() -> Table:
    text = np.array(
        [
            "the quick brown fox",
            "jumps over the lazy dog the the",
            "meh",
            "",
            "héllo wörld 漢字 ™",
            "dup dup dup",
            "  spaced\ttabs\nnewline  ",
            None,
        ],
        dtype=object,
    )
    tags = np.empty(8, dtype=object)
    for i, v in enumerate(
        [
            ["red", "green", "blue"],
            ["red", "red"],
            [],
            None,
            ["solo"],
            ["χρώμα", "色"],
            ["x", "y", "z", "x"],
            ["end"],
        ]
    ):
        tags[i] = v
    kv = np.empty(8, dtype=object)
    for i, v in enumerate(
        [
            {"a": 1.0, "b": 2.0},
            {},
            None,
            {"c": 0.5},
            {"a": 1.0},
            {"d": -1.0, "e": 4.0},
            {"f": 2.25},
            {"g": 1.0},
        ]
    ):
        kv[i] = v
    rng_vec = np.arange(24, dtype=np.float64).reshape(8, 3) * 0.25 - 2.0
    return Table(
        {
            "text": text,
            "tags": tags,
            "kv": kv,
            "num": np.array([1.5, -2.0, 0.0, 3.25, -0.5, 1024.0, 7.0, 0.125]),
            "count": np.arange(1, 9, dtype=np.int32),
            "flag": np.array([True, False, True, True, False, False, True, False]),
            "vec": rng_vec,
        }
    )


#: config name -> VowpalWabbitFeaturizer kwargs (inputCols included).
GOLDEN_CONFIGS = {
    "split": dict(inputCols=["text"], stringSplit=True, numBits=18),
    "array": dict(inputCols=["tags"], numBits=18),
    "nosplit": dict(inputCols=["text"], stringSplit=False, numBits=18),
    "noprefix": dict(
        inputCols=["text"], stringSplit=True, numBits=12,
        prefixStringsWithColumnName=False, hashSeed=7,
    ),
    "nosum": dict(
        inputCols=["text", "tags"], stringSplit=True, numBits=6,
        sumCollisions=False,
    ),
    "lowbits_sum": dict(inputCols=["text", "tags"], stringSplit=True, numBits=4),
    "mixed": dict(
        inputCols=["num", "text", "vec", "flag", "kv", "count"],
        stringSplit=True, numBits=18,
    ),
}


def compute_rows():
    t = golden_table()
    out = []
    for cfg, kwargs in GOLDEN_CONFIGS.items():
        feats = VowpalWabbitFeaturizer(outputCol="features", **kwargs).transform(t)
        col = feats.column("features")
        for i in range(t.num_rows):
            idx, val = col[i]
            out.append(
                {
                    "cfg": cfg,
                    "row": i,
                    "indices": " ".join(str(int(x)) for x in idx),
                    "values": " ".join("%.9g" % float(v) for v in val),
                }
            )
    return out


def test_feature_space_matches_golden():
    if not os.path.exists(GOLDEN):
        pytest.fail(f"golden fixture missing: {GOLDEN} (run --regen)")
    with open(GOLDEN, newline="") as f:
        golden = list(csv.DictReader(f))
    computed = compute_rows()
    assert len(golden) == len(computed)
    for g, c in zip(golden, computed):
        where = f"{c['cfg']} row {c['row']}"
        assert g["cfg"] == c["cfg"] and int(g["row"]) == int(c["row"]), where
        assert g["indices"] == c["indices"], f"{where}: index drift"
        assert g["values"] == c["values"], f"{where}: value drift"


def test_golden_covers_text_and_array_columns():
    """The fixture must pin at least the two row families the rewrite can
    silently shift: a string-split column and a string-array column."""
    with open(GOLDEN, newline="") as f:
        cfgs = {r["cfg"] for r in csv.DictReader(f)}
    assert {"split", "array"} <= cfgs


if __name__ == "__main__":
    import sys

    if "--regen" not in sys.argv:
        sys.exit("refusing to overwrite golden without --regen")
    rows = compute_rows()
    with open(GOLDEN, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=["cfg", "row", "indices", "values"])
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {len(rows)} rows to {GOLDEN}")
