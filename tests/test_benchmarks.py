"""Golden-file benchmark regression tests — the ``Benchmarks.scala:16-110``
analogue (reference goldens: ``benchmarks_VerifyLightGBMClassifier.csv`` et
al., e.g. breast-cancer gbdt AUC 0.99247 ± 0.01). Measured values are
compared against ``tests/benchmarks/golden_metrics.csv``; the harness
writes ``*.new.csv`` next to it so promoting a new golden is one copy."""

import os

import numpy as np
import pytest

from mmlspark_tpu.core.benchmarks import Benchmark, BenchmarkSuite
from mmlspark_tpu.data.table import Table

GOLDEN = os.path.join(os.path.dirname(__file__), "benchmarks", "golden_metrics.csv")


def _auc(y, score):
    from mmlspark_tpu.lightgbm.objectives import auc

    return float(auc(np.asarray(y), np.asarray(score), np.ones(len(y))))


def _split(X, y, seed=0, frac=0.8):
    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(y))
    X, y = X[perm], np.asarray(y, dtype=np.float64)[perm]
    n = int(frac * len(y))
    return Table({"features": X[:n], "label": y[:n]}), (X[n:], y[n:])


@pytest.fixture(scope="module")
def datasets():
    from sklearn.datasets import (
        load_breast_cancer,
        load_diabetes,
        load_digits,
        load_wine,
        make_friedman1,
    )

    bc = load_breast_cancer()
    db = load_diabetes()
    wine = load_wine()  # 3-class
    digits = load_digits()  # second classifier dataset: digit 0 vs rest
    y_dig = (digits.target == 0).astype(np.float64)
    Xf, yf = make_friedman1(  # second regressor dataset (locally generated)
        n_samples=800, n_features=10, noise=1.0, random_state=0
    )

    out = {}
    for name, X, y, seed in (
        ("bc", bc.data, bc.target, 0),
        ("db", db.data, db.target, 0),
        ("wine", wine.data, wine.target, 1),
        ("digits", digits.data, y_dig, 2),
        ("friedman", Xf, yf, 3),
    ):
        out[f"{name}_train"], out[f"{name}_test"] = _split(X, y, seed)
    return out


def test_golden_metrics(datasets):
    from mmlspark_tpu.lightgbm import LightGBMClassifier, LightGBMRegressor
    from mmlspark_tpu.train import TrainClassifier
    from mmlspark_tpu.vw import VowpalWabbitRegressor

    suite = BenchmarkSuite("core_metrics")

    # LightGBMClassifier AUC per boosting type, mirroring the reference's
    # dataset x boosting-type golden matrix (VerifyLightGBMClassifier.csv)
    Xt, yt = datasets["bc_test"]
    for boosting, extra in (
        ("gbdt", {}),
        ("goss", {}),
        ("dart", {"dropRate": 0.2}),
        ("rf", {"baggingFraction": 0.6, "baggingFreq": 1}),
    ):
        clf = LightGBMClassifier(
            numIterations=40, numLeaves=15, boostingType=boosting, seed=0,
            parallelism="serial", **extra,
        )
        model = clf.fit(datasets["bc_train"])
        margins = model.booster.raw_margin(Xt)[:, 0]
        suite.add(f"breast_cancer_{boosting}_auc", _auc(yt, margins), 0.01)

    # LightGBMRegressor RMSE (VerifyLightGBMRegressor.csv loss rows)
    Xd, yd = datasets["db_test"]
    reg = LightGBMRegressor(numIterations=60, numLeaves=15, seed=0, parallelism="serial")
    rmodel = reg.fit(datasets["db_train"])
    pred = rmodel.booster.raw_margin(Xd)[:, 0]
    rmse = float(np.sqrt(np.mean((pred - yd) ** 2)))
    suite.add("diabetes_gbdt_rmse", rmse, 5.0, higher_is_better=False)

    # VowpalWabbitRegressor loss (VerifyVowpalWabbitRegressor.csv)
    vw = VowpalWabbitRegressor(numPasses=5)
    vmodel = vw.fit(datasets["db_train"])
    vout = vmodel.transform(Table({"features": Xd, "label": yd}))
    vrmse = float(np.sqrt(np.mean((vout.column("prediction") - yd) ** 2)))
    suite.add("diabetes_vw_rmse", vrmse, 10.0, higher_is_better=False)

    # TrainClassifier end-to-end accuracy (VerifyTrainClassifier.csv)
    tc = TrainClassifier(
        model=LightGBMClassifier(numIterations=20, numLeaves=7, parallelism="serial"),
        labelCol="label",
    )
    tmodel = tc.fit(datasets["bc_train"])
    tout = tmodel.transform(Table({"features": Xt, "label": yt}))
    acc = float((tout.column("prediction") == yt).mean())
    suite.add("breast_cancer_trainclassifier_acc", acc, 0.03)

    # Second dataset per family, mirroring the reference's multi-dataset
    # golden matrix (benchmarks_VerifyLightGBMClassifier.csv spans 8).
    Xg, yg = datasets["digits_test"]
    dclf = LightGBMClassifier(
        numIterations=30, numLeaves=15, seed=0, parallelism="serial"
    ).fit(datasets["digits_train"])
    suite.add(
        "digits_zero_gbdt_auc", _auc(yg, dclf.booster.raw_margin(Xg)[:, 0]), 0.01
    )

    Xfr, yfr = datasets["friedman_test"]
    freg = LightGBMRegressor(
        numIterations=60, numLeaves=15, seed=0, parallelism="serial"
    ).fit(datasets["friedman_train"])
    frmse = float(np.sqrt(np.mean((freg.booster.raw_margin(Xfr)[:, 0] - yfr) ** 2)))
    suite.add("friedman_gbdt_rmse", frmse, 0.5, higher_is_better=False)

    fvw = VowpalWabbitRegressor(numPasses=8).fit(datasets["friedman_train"])
    fvout = fvw.transform(Table({"features": Xfr, "label": yfr}))
    fvrmse = float(np.sqrt(np.mean((fvout.column("prediction") - yfr) ** 2)))
    suite.add("friedman_vw_rmse", fvrmse, 1.0, higher_is_better=False)

    # Categorical-split golden (categoricalSlotIndexes; the reference's
    # native engine exposes the same capability via LightGBMParams.scala:125)
    rngc = np.random.default_rng(21)
    nc = 3000
    catf = rngc.integers(0, 10, size=nc)
    ceff = rngc.normal(size=10) * 2.0
    Xc = np.column_stack([catf.astype(np.float64), rngc.normal(size=(nc, 3))])
    yc = ((ceff[catf] + Xc[:, 1]) > 0).astype(np.float64)
    ct_train, (Xct, yct) = _split(Xc, yc, seed=4)
    cclf = LightGBMClassifier(
        numIterations=20, numLeaves=15, seed=0, parallelism="serial",
        categoricalSlotIndexes=[0],
    ).fit(ct_train)
    suite.add(
        "categorical_gbdt_auc", _auc(yct, cclf.booster.raw_margin(Xct)[:, 0]), 0.01
    )

    # Multiclass golden (wine, 3 classes)
    Xw, yw = datasets["wine_test"]
    wclf = LightGBMClassifier(
        objective="multiclass", numIterations=30, numLeaves=7, seed=0,
        parallelism="serial", minDataInLeaf=5,
    ).fit(datasets["wine_train"])
    wacc = float(
        (wclf.booster.raw_margin(Xw).argmax(axis=1) == yw).mean()
    )
    suite.add("wine_multiclass_acc", wacc, 0.05)

    suite.verify(GOLDEN)


def test_golden_ranker_ndcg():
    """Ranker golden (the reference pins lambdarank metrics in its
    benchmark CSVs; here ndcg@5 on a deterministic synthetic query set)."""
    from mmlspark_tpu.lightgbm import LightGBMRanker
    from mmlspark_tpu.lightgbm.ranker import ndcg_at_k

    rng = np.random.default_rng(9)
    q, per_group = 40, 12
    n = q * per_group
    X = rng.normal(size=(n, 5))
    rel = np.clip((X[:, 0] + rng.normal(scale=0.4, size=n)) * 1.5 + 1.5, 0, 4).round()
    group = np.repeat(np.arange(q), per_group)
    t = Table({
        "features": X, "label": rel.astype(np.float64),
        "query": group.astype(np.int64),
    })
    model = LightGBMRanker(
        numIterations=30, groupCol="query", minDataInLeaf=5, seed=0,
        parallelism="serial",
    ).fit(t)
    score = ndcg_at_k(rel, model.transform(t)["prediction"], group, k=5)

    suite = BenchmarkSuite("ranker_metrics")
    suite.add("synthetic_ranker_ndcg5", float(score), 0.02)
    suite.verify(os.path.join(os.path.dirname(GOLDEN), "golden_ranker.csv"))


def test_golden_tune_hyperparameters(datasets):
    """TuneHyperparameters golden (benchmarks_VerifyTuneHyperparameters.csv
    analogue): the CV-best metric of a fixed sweep is pinned."""
    from mmlspark_tpu.automl import TuneHyperparameters
    from mmlspark_tpu.automl.hyperparam import (
        DiscreteHyperParam,
        DoubleRangeHyperParam,
    )
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    tuned = TuneHyperparameters(
        models=LightGBMClassifier(numIterations=15, parallelism="serial"),
        paramSpace={
            "numLeaves": DiscreteHyperParam([7, 15]),
            "learningRate": DoubleRangeHyperParam(0.05, 0.3),
        },
        evaluationMetric="accuracy",
        numFolds=3,
        numRuns=4,
        seed=5,
    ).fit(datasets["bc_train"])

    suite = BenchmarkSuite("tune_metrics")
    suite.add("breast_cancer_tune_best_acc", float(tuned.getBestMetric()), 0.03)
    suite.verify(os.path.join(os.path.dirname(GOLDEN), "golden_tune.csv"))


class TestHarness:
    def test_regression_detected(self, tmp_path):
        golden = tmp_path / "g.csv"
        s0 = BenchmarkSuite("s")
        s0.add("m1", 0.95, 0.01)
        s0.add("m2", 3.0, 0.5, higher_is_better=False)
        s0.write_csv(str(golden))

        ok = BenchmarkSuite("s")
        ok.add("m1", 0.945, 0.01)  # within precision
        ok.add("m2", 3.4, 0.5, higher_is_better=False)
        ok.verify(str(golden))

        # direction mistakes on the measuring side must not flip the check
        flipped = BenchmarkSuite("s")
        flipped.add("m1", 0.945, 0.01)
        flipped.add("m2", 500.0, 0.5)  # forgot higher_is_better=False
        with pytest.raises(AssertionError, match="higher_is_better mismatch"):
            flipped.verify(str(golden))

        bad = BenchmarkSuite("s")
        bad.add("m1", 0.90, 0.01)
        bad.add("m2", 3.0, 0.5, higher_is_better=False)
        with pytest.raises(AssertionError, match="m1"):
            bad.verify(str(golden))

    def test_unknown_and_missing_rows(self, tmp_path):
        golden = tmp_path / "g.csv"
        s0 = BenchmarkSuite("s")
        s0.add("m1", 1.0, 0.1)
        s0.write_csv(str(golden))

        extra = BenchmarkSuite("s")
        extra.add("m1", 1.0, 0.1)
        extra.add("new_metric", 2.0, 0.1)
        with pytest.raises(AssertionError, match="new_metric"):
            extra.verify(str(golden))

        partial = BenchmarkSuite("s")
        with pytest.raises(AssertionError, match="never measured"):
            partial.verify(str(golden))

    def test_improvement_passes(self, tmp_path):
        golden = tmp_path / "g.csv"
        s0 = BenchmarkSuite("s")
        s0.add("auc", 0.9, 0.01)
        s0.write_csv(str(golden))
        better = BenchmarkSuite("s")
        better.add("auc", 0.99, 0.01)
        better.verify(str(golden))  # improvements never fail


def test_api_reference_up_to_date():
    """The generated API reference (docs/api/) must match the code — the
    CI-validated codegen artifact (CodeGen.scala:15-48 analogue). Regenerate
    with `python -m mmlspark_tpu.core.apigen` after changing any Param."""
    from mmlspark_tpu.core.apigen import (
        _default_out_dir,
        _default_r_dir,
        check,
        check_r,
    )

    stale = check(_default_out_dir()) + check_r(_default_r_dir())
    assert not stale, f"API reference drift, regenerate: {stale}"
