"""Golden-file benchmark regression tests — the ``Benchmarks.scala:16-110``
analogue (reference goldens: ``benchmarks_VerifyLightGBMClassifier.csv`` et
al., e.g. breast-cancer gbdt AUC 0.99247 ± 0.01). Measured values are
compared against ``tests/benchmarks/golden_metrics.csv``; the harness
writes ``*.new.csv`` next to it so promoting a new golden is one copy."""

import os

import numpy as np
import pytest

from mmlspark_tpu.core.benchmarks import Benchmark, BenchmarkSuite
from mmlspark_tpu.data.table import Table

GOLDEN = os.path.join(os.path.dirname(__file__), "benchmarks", "golden_metrics.csv")


def _auc(y, score):
    from mmlspark_tpu.lightgbm.objectives import auc

    return float(auc(np.asarray(y), np.asarray(score), np.ones(len(y))))


@pytest.fixture(scope="module")
def datasets():
    from sklearn.datasets import load_breast_cancer, load_diabetes

    rng = np.random.default_rng(0)
    bc = load_breast_cancer()
    perm = rng.permutation(len(bc.target))
    Xb, yb = bc.data[perm], bc.target[perm].astype(np.float64)
    nb = int(0.8 * len(yb))

    db = load_diabetes()
    perm2 = rng.permutation(len(db.target))
    Xd, yd = db.data[perm2], db.target[perm2].astype(np.float64)
    nd = int(0.8 * len(yd))
    return {
        "bc_train": Table({"features": Xb[:nb], "label": yb[:nb]}),
        "bc_test": (Xb[nb:], yb[nb:]),
        "db_train": Table({"features": Xd[:nd], "label": yd[:nd]}),
        "db_test": (Xd[nd:], yd[nd:]),
    }


def test_golden_metrics(datasets):
    from mmlspark_tpu.lightgbm import LightGBMClassifier, LightGBMRegressor
    from mmlspark_tpu.train import TrainClassifier
    from mmlspark_tpu.vw import VowpalWabbitRegressor

    suite = BenchmarkSuite("core_metrics")

    # LightGBMClassifier AUC per boosting type, mirroring the reference's
    # dataset x boosting-type golden matrix (VerifyLightGBMClassifier.csv)
    Xt, yt = datasets["bc_test"]
    for boosting, extra in (
        ("gbdt", {}),
        ("goss", {}),
        ("dart", {"dropRate": 0.2}),
        ("rf", {"baggingFraction": 0.6, "baggingFreq": 1}),
    ):
        clf = LightGBMClassifier(
            numIterations=40, numLeaves=15, boostingType=boosting, seed=0,
            parallelism="serial", **extra,
        )
        model = clf.fit(datasets["bc_train"])
        margins = model.booster.raw_margin(Xt)[:, 0]
        suite.add(f"breast_cancer_{boosting}_auc", _auc(yt, margins), 0.01)

    # LightGBMRegressor RMSE (VerifyLightGBMRegressor.csv loss rows)
    Xd, yd = datasets["db_test"]
    reg = LightGBMRegressor(numIterations=60, numLeaves=15, seed=0, parallelism="serial")
    rmodel = reg.fit(datasets["db_train"])
    pred = rmodel.booster.raw_margin(Xd)[:, 0]
    rmse = float(np.sqrt(np.mean((pred - yd) ** 2)))
    suite.add("diabetes_gbdt_rmse", rmse, 5.0, higher_is_better=False)

    # VowpalWabbitRegressor loss (VerifyVowpalWabbitRegressor.csv)
    vw = VowpalWabbitRegressor(numPasses=5)
    vmodel = vw.fit(datasets["db_train"])
    vout = vmodel.transform(Table({"features": Xd, "label": yd}))
    vrmse = float(np.sqrt(np.mean((vout.column("prediction") - yd) ** 2)))
    suite.add("diabetes_vw_rmse", vrmse, 10.0, higher_is_better=False)

    # TrainClassifier end-to-end accuracy (VerifyTrainClassifier.csv)
    tc = TrainClassifier(
        model=LightGBMClassifier(numIterations=20, numLeaves=7, parallelism="serial"),
        labelCol="label",
    )
    tmodel = tc.fit(datasets["bc_train"])
    tout = tmodel.transform(Table({"features": Xt, "label": yt}))
    acc = float((tout.column("prediction") == yt).mean())
    suite.add("breast_cancer_trainclassifier_acc", acc, 0.03)

    suite.verify(GOLDEN)


class TestHarness:
    def test_regression_detected(self, tmp_path):
        golden = tmp_path / "g.csv"
        s0 = BenchmarkSuite("s")
        s0.add("m1", 0.95, 0.01)
        s0.add("m2", 3.0, 0.5, higher_is_better=False)
        s0.write_csv(str(golden))

        ok = BenchmarkSuite("s")
        ok.add("m1", 0.945, 0.01)  # within precision
        ok.add("m2", 3.4, 0.5, higher_is_better=False)
        ok.verify(str(golden))

        # direction mistakes on the measuring side must not flip the check
        flipped = BenchmarkSuite("s")
        flipped.add("m1", 0.945, 0.01)
        flipped.add("m2", 500.0, 0.5)  # forgot higher_is_better=False
        with pytest.raises(AssertionError, match="higher_is_better mismatch"):
            flipped.verify(str(golden))

        bad = BenchmarkSuite("s")
        bad.add("m1", 0.90, 0.01)
        bad.add("m2", 3.0, 0.5, higher_is_better=False)
        with pytest.raises(AssertionError, match="m1"):
            bad.verify(str(golden))

    def test_unknown_and_missing_rows(self, tmp_path):
        golden = tmp_path / "g.csv"
        s0 = BenchmarkSuite("s")
        s0.add("m1", 1.0, 0.1)
        s0.write_csv(str(golden))

        extra = BenchmarkSuite("s")
        extra.add("m1", 1.0, 0.1)
        extra.add("new_metric", 2.0, 0.1)
        with pytest.raises(AssertionError, match="new_metric"):
            extra.verify(str(golden))

        partial = BenchmarkSuite("s")
        with pytest.raises(AssertionError, match="never measured"):
            partial.verify(str(golden))

    def test_improvement_passes(self, tmp_path):
        golden = tmp_path / "g.csv"
        s0 = BenchmarkSuite("s")
        s0.add("auc", 0.9, 0.01)
        s0.write_csv(str(golden))
        better = BenchmarkSuite("s")
        better.add("auc", 0.99, 0.01)
        better.verify(str(golden))  # improvements never fail
