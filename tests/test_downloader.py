"""downloader/ tests — mirrors reference ``downloader/`` DownloaderSuite."""

import json
import os

import numpy as np

import pytest

from mmlspark_tpu.downloader import (
    FaultToleranceUtils,
    LocalRepo,
    ModelDownloader,
    ModelSchema,
)


def test_schema_roundtrip():
    s = ModelSchema(name="resnet50", uri="resnet50.bin", inputNode="input",
                    layerNames=["fc", "pool"])
    s2 = ModelSchema.from_json(s.to_json())
    assert s2 == s


def test_local_repo_add_list_download(tmp_path):
    repo_dir = str(tmp_path / "repo")
    cache_dir = str(tmp_path / "cache")
    repo = LocalRepo(repo_dir)
    repo.add(ModelSchema(name="m1", uri=""), b"payload-bytes")
    dl = ModelDownloader(cache_dir, repo)
    models = dl.list_models()
    assert [m.name for m in models] == ["m1"]
    path = dl.download_by_name("m1")
    with open(path, "rb") as f:
        assert f.read() == b"payload-bytes"
    # cached second call returns same file without re-fetching
    assert dl.download_by_name("m1") == path


def test_hash_mismatch_raises(tmp_path):
    repo_dir = str(tmp_path / "repo")
    repo = LocalRepo(repo_dir)
    repo.add(ModelSchema(name="m", uri=""), b"data")
    # corrupt the payload after hashing
    with open(os.path.join(repo_dir, "m.bin"), "wb") as f:
        f.write(b"tampered")
    dl = ModelDownloader(str(tmp_path / "cache"), repo)
    with pytest.raises(IOError):
        dl.download_by_name("m")


def test_missing_model_raises(tmp_path):
    dl = ModelDownloader(str(tmp_path / "cache"), LocalRepo(str(tmp_path / "repo")))
    with pytest.raises(KeyError):
        dl.download_by_name("nope")


def test_retry_with_timeout():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise IOError("transient")
        return "ok"

    assert FaultToleranceUtils.retry_with_timeout(flaky, times=3, backoff=0.01) == "ok"
    with pytest.raises(IOError):
        FaultToleranceUtils.retry_with_timeout(
            lambda: (_ for _ in ()).throw(IOError("always")), times=2, backoff=0.01
        )


class TestZooArtifacts:
    """Trained-weight artifacts through the repository (the reference's
    ModelDownloader -> ImageFeaturizer flow with real learned weights)."""

    def test_params_npz_round_trip(self):
        import jax

        from mmlspark_tpu.models import (
            init_resnet, params_from_bytes, params_to_bytes,
        )

        p = init_resnet(variant="resnet18", num_classes=3, small_inputs=True,
                        in_channels=1)
        p2 = params_from_bytes(params_to_bytes(p))
        for a, b in zip(jax.tree_util.tree_leaves(p), jax.tree_util.tree_leaves(p2)):
            np.testing.assert_array_equal(a, b)

    def test_publish_download_featurize(self, tmp_path):
        from mmlspark_tpu.data.table import Table
        from mmlspark_tpu.image import ImageFeaturizer
        from mmlspark_tpu.models import (
            init_resnet, load_zoo_params, publish_model,
            train_resnet_classifier,
        )

        rng = np.random.default_rng(0)
        X = rng.uniform(size=(32, 1, 16, 16)).astype(np.float32)
        y = (X[:, 0, :8].mean(axis=(1, 2)) > X[:, 0, 8:].mean(axis=(1, 2))).astype(int)
        p0 = init_resnet(variant="resnet18", num_classes=2, small_inputs=True,
                         in_channels=1)
        trained, _ = train_resnet_classifier(p0, X, y, num_steps=2, batch_size=8)
        schema = publish_model(str(tmp_path / "repo"), "tiny", trained, (16, 16))
        assert schema.hash and schema.numLayers

        dl = ModelDownloader(str(tmp_path / "cache"), LocalRepo(str(tmp_path / "repo")))
        loaded = load_zoo_params(dl, "tiny")
        import jax

        for a, b in zip(jax.tree_util.tree_leaves(trained),
                        jax.tree_util.tree_leaves(loaded)):
            np.testing.assert_array_equal(a, b)

        imgs = np.empty(4, dtype=object)
        for i in range(4):
            imgs[i] = X[i, 0][:, :, None]
        t = Table({"image": imgs})
        out = ImageFeaturizer(
            inputCol="image", outputCol="features", modelParams=loaded,
            inputHeight=16, inputWidth=16, scale=1.0, batchSize=4,
        ).transform(t)
        feats = np.asarray(out["features"])
        assert feats.shape == (4, 512) and np.isfinite(feats).all()

    def test_corrupted_payload_rejected(self, tmp_path):
        from mmlspark_tpu.models import init_resnet, publish_model

        p = init_resnet(variant="resnet18", num_classes=2, small_inputs=True,
                        in_channels=1)
        schema = publish_model(str(tmp_path / "repo"), "tiny2", p, (16, 16))
        # corrupt the payload behind the schema's hash
        with open(tmp_path / "repo" / "tiny2.bin", "ab") as f:
            f.write(b"x")
        dl = ModelDownloader(str(tmp_path / "cache2"), LocalRepo(str(tmp_path / "repo")))
        with pytest.raises(IOError, match="hash mismatch"):
            dl.download_by_name("tiny2")

    def test_digit_keyed_dicts_round_trip(self):
        from mmlspark_tpu.models import params_from_bytes, params_to_bytes

        tree = {"blocks": {"0": np.ones(2), "2": np.zeros(3)},
                "layers": [np.arange(2.0), {"w": np.eye(2)}]}
        out = params_from_bytes(params_to_bytes(tree))
        assert isinstance(out["blocks"], dict)  # digit keys stay a dict
        np.testing.assert_array_equal(out["blocks"]["2"], np.zeros(3))
        assert isinstance(out["layers"], list)
        np.testing.assert_array_equal(out["layers"][1]["w"], np.eye(2))
        with pytest.raises(ValueError, match="may not contain"):
            params_to_bytes({"a/b": np.ones(1)})
