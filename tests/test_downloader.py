"""downloader/ tests — mirrors reference ``downloader/`` DownloaderSuite."""

import json
import os

import pytest

from mmlspark_tpu.downloader import (
    FaultToleranceUtils,
    LocalRepo,
    ModelDownloader,
    ModelSchema,
)


def test_schema_roundtrip():
    s = ModelSchema(name="resnet50", uri="resnet50.bin", inputNode="input",
                    layerNames=["fc", "pool"])
    s2 = ModelSchema.from_json(s.to_json())
    assert s2 == s


def test_local_repo_add_list_download(tmp_path):
    repo_dir = str(tmp_path / "repo")
    cache_dir = str(tmp_path / "cache")
    repo = LocalRepo(repo_dir)
    repo.add(ModelSchema(name="m1", uri=""), b"payload-bytes")
    dl = ModelDownloader(cache_dir, repo)
    models = dl.list_models()
    assert [m.name for m in models] == ["m1"]
    path = dl.download_by_name("m1")
    with open(path, "rb") as f:
        assert f.read() == b"payload-bytes"
    # cached second call returns same file without re-fetching
    assert dl.download_by_name("m1") == path


def test_hash_mismatch_raises(tmp_path):
    repo_dir = str(tmp_path / "repo")
    repo = LocalRepo(repo_dir)
    repo.add(ModelSchema(name="m", uri=""), b"data")
    # corrupt the payload after hashing
    with open(os.path.join(repo_dir, "m.bin"), "wb") as f:
        f.write(b"tampered")
    dl = ModelDownloader(str(tmp_path / "cache"), repo)
    with pytest.raises(IOError):
        dl.download_by_name("m")


def test_missing_model_raises(tmp_path):
    dl = ModelDownloader(str(tmp_path / "cache"), LocalRepo(str(tmp_path / "repo")))
    with pytest.raises(KeyError):
        dl.download_by_name("nope")


def test_retry_with_timeout():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise IOError("transient")
        return "ok"

    assert FaultToleranceUtils.retry_with_timeout(flaky, times=3, backoff=0.01) == "ok"
    with pytest.raises(IOError):
        FaultToleranceUtils.retry_with_timeout(
            lambda: (_ for _ in ()).throw(IOError("always")), times=2, backoff=0.01
        )
