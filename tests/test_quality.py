"""Model-quality plane tests — deterministic sketches, reference
profiles, drift monitoring, and burn-rate alerting.

The determinism suite is the load-bearing part: the fleet merges
per-replica sketch state, so ``merge`` must be exactly associative and
the canonical serialization byte-stable across every merge order — a
federated fold must equal the single-process sketch over the
concatenated stream, not approximate it. Statistics are checked against
straight numpy golden computations over the same fixed bins.

Monitors and evaluators run against FRESH ``MetricsRegistry`` instances
and injected clocks/sources so nothing here touches the process-global
plane or wall time.
"""

import itertools
import json
import math
import random

import numpy as np
import pytest

from mmlspark_tpu.observability import (
    AlertEvaluator,
    ColumnSketch,
    DriftCleared,
    DriftDetected,
    MetricsFederator,
    MetricsRegistry,
    QualityMonitor,
    QuantileCompactor,
    ReferenceProfile,
    drift_table_from_summary,
    get_bus,
    ks_statistic,
    load_profile,
    merge_all,
    psi,
)
from mmlspark_tpu.observability.profiler import (
    UNKNOWN_PLATFORM,
    DevicePeaks,
    FunctionProfile,
    device_peaks,
)
from mmlspark_tpu.observability.slo import SLOTargets
from mmlspark_tpu.runtime.journal import ModelStore


def _stream(seed: int, n: int, mu: float = 0.0, sigma: float = 1.0):
    rng = random.Random(seed)
    return [rng.gauss(mu, sigma) for _ in range(n)]


def _sketch(edges, values) -> ColumnSketch:
    s = ColumnSketch(edges)
    s.observe_many(values)
    return s


class TestSketchDeterminism:
    def test_shuffled_merge_is_byte_stable(self):
        """Any shard split + any merge order reproduces the
        single-process sketch byte-for-byte."""
        values = _stream(7, 2000)
        comp = QuantileCompactor()
        comp.extend(values)
        edges = comp.edges()
        whole = _sketch(edges, values)
        shards = [
            _sketch(edges, values[i::5]) for i in range(5)
        ]
        rng = random.Random(13)
        for _ in range(8):
            order = shards[:]
            rng.shuffle(order)
            merged = merge_all(order)
            assert merged.to_json() == whole.to_json()

    def test_merge_is_associative(self):
        edges = [0.0, 1.0, 2.0, 3.0]
        a = _sketch(edges, [0.1, 1.5, None, 2.9])
        b = _sketch(edges, [0.5, 0.6, float("nan"), 2.2])
        c = _sketch(edges, [1.1, -5.0, 99.0])  # clamps into edge bins
        left = a.merge(b).merge(c)
        right = a.merge(b.merge(c))
        assert left.to_json() == right.to_json()

    def test_federated_equals_single_process(self):
        """The fleet fold: 3 'replica' sketches over disjoint traffic
        merge to exactly the sketch of the concatenated stream —
        counts, moments (Fractions), min/max, and missing all exact."""
        values = _stream(21, 900, mu=2.0) + [None] * 30
        random.Random(3).shuffle(values)
        comp = QuantileCompactor()
        comp.extend(values)
        edges = comp.edges()
        whole = _sketch(edges, values)
        replicas = [_sketch(edges, values[i::3]) for i in range(3)]
        merged = merge_all(replicas)
        assert merged.counts == whole.counts
        assert merged.sum == whole.sum and merged.sumsq == whole.sumsq
        assert merged.missing == whole.missing
        assert merged.to_json() == whole.to_json()

    def test_compactor_is_deterministic(self):
        values = _stream(42, 5000)
        edges = []
        for _ in range(2):
            comp = QuantileCompactor()
            comp.extend(values)
            edges.append(comp.edges())
        assert edges[0] == edges[1]
        assert all(b > a for a, b in zip(edges[0], edges[0][1:]))

    def test_compactor_edges_near_equidepth(self):
        values = _stream(5, 8000)
        comp = QuantileCompactor()
        comp.extend(values)
        edges = comp.edges(10)
        counts, _ = np.histogram(values, bins=edges)
        # each of the 10 bins should hold roughly 1/10 of the mass
        assert counts.min() > 0.04 * len(values)
        assert counts.max() < 0.25 * len(values)

    def test_serialization_round_trip(self):
        s = _sketch([0.0, 0.5, 1.0], [0.1, 0.2, 0.7, None, 1.5])
        back = ColumnSketch.from_dict(json.loads(s.to_json()))
        assert back.to_json() == s.to_json()
        assert back.sum == s.sum and back.mean() == s.mean()

    def test_degenerate_streams(self):
        empty = QuantileCompactor()
        assert empty.edges() == [0.0, 1.0]
        const = QuantileCompactor()
        const.extend([3.0] * 50)
        edges = const.edges()
        assert len(edges) == 2 and edges[0] < 3.0 < edges[1]


class TestDriftStatistics:
    def test_psi_golden_vs_numpy(self):
        """PSI from sketch state must equal the straight numpy
        computation over the same bins and the same eps smoothing."""
        ref_vals = _stream(1, 4000)
        live_vals = _stream(2, 3000, mu=1.0)
        comp = QuantileCompactor()
        comp.extend(ref_vals)
        edges = comp.edges()
        ref, live = _sketch(edges, ref_vals), _sketch(edges, live_vals)

        eps = 1e-6
        e = np.asarray(edges)
        rc, _ = np.histogram(np.clip(ref_vals, e[0], e[-1]), bins=e)
        lc, _ = np.histogram(np.clip(live_vals, e[0], e[-1]), bins=e)
        p = (rc + eps) / (rc.sum() + eps * len(rc))
        q = (lc + eps) / (lc.sum() + eps * len(lc))
        golden = float(np.sum((q - p) * np.log(q / p)))

        assert psi(ref, live) == pytest.approx(golden, rel=1e-9)
        # same distribution scores near zero; shifted scores large
        same = _sketch(edges, _stream(9, 3000))
        assert psi(ref, same) < 0.05
        assert psi(ref, live) > 0.2

    def test_ks_golden_vs_numpy(self):
        ref_vals = _stream(11, 2500)
        live_vals = _stream(12, 2500, mu=0.8)
        comp = QuantileCompactor()
        comp.extend(ref_vals)
        edges = comp.edges()
        ref, live = _sketch(edges, ref_vals), _sketch(edges, live_vals)

        e = np.asarray(edges)
        rc, _ = np.histogram(np.clip(ref_vals, e[0], e[-1]), bins=e)
        lc, _ = np.histogram(np.clip(live_vals, e[0], e[-1]), bins=e)
        golden = float(
            np.max(np.abs(np.cumsum(rc) / rc.sum() - np.cumsum(lc) / lc.sum()))
        )
        assert ks_statistic(ref, live) == pytest.approx(golden, rel=1e-9)
        assert ks_statistic(ref, ref) == 0.0

    def test_mismatched_edges_refused(self):
        a = ColumnSketch([0.0, 1.0])
        b = ColumnSketch([0.0, 2.0])
        with pytest.raises(ValueError):
            a.merge(b)
        with pytest.raises(ValueError):
            psi(a, b)
        with pytest.raises(ValueError):
            ks_statistic(a, b)


class TestReferenceProfile:
    def test_store_round_trip(self, tmp_path):
        store = ModelStore(str(tmp_path))
        store.commit("model-text", name="m")
        profile = ReferenceProfile.capture(
            "m", 1,
            {"input": [[x, -x] for x in _stream(4, 300)],
             "prediction": _stream(5, 300)},
        )
        # vector column fanned out per index, scalar kept bare
        assert set(profile.features) == {"input[0]", "input[1]", "prediction"}
        profile.commit(store)
        back = load_profile(store, "m", 1)
        assert back is not None
        assert back.to_dict() == profile.to_dict()

    def test_corrupt_artifact_reads_as_missing(self, tmp_path):
        store = ModelStore(str(tmp_path))
        profile = ReferenceProfile.capture("m", 1, {"x": _stream(6, 100)})
        fname = profile.commit(store)
        path = tmp_path / fname
        path.write_bytes(path.read_bytes()[:-4] + b"!!!!")
        assert store.read_artifact("m", 1, "quality") is None
        assert load_profile(store, "m", 1) is None

    def test_capture_is_deterministic(self):
        cols = {"x": _stream(8, 500)}
        a = ReferenceProfile.capture("m", 1, cols)
        b = ReferenceProfile.capture("m", 1, cols)
        assert json.dumps(a.to_dict(), sort_keys=True) == json.dumps(
            b.to_dict(), sort_keys=True
        )


class TestQualityMonitor:
    def _monitor(self, ref_vals, **kw):
        profile = ReferenceProfile.capture("m", 1, {"x": ref_vals})
        kw.setdefault("registry", MetricsRegistry())
        kw.setdefault("window", 256)
        kw.setdefault("eval_every", 64)
        kw.setdefault("min_window", 128)
        return QualityMonitor(profile=profile, **kw)

    def test_detect_then_clear_with_paired_events(self):
        mon = self._monitor(_stream(30, 2000))
        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        try:
            # stable traffic: no drift
            mon.observe_columns({"x": _stream(31, 256)})
            assert mon.drifted_features() == []
            # shifted traffic turns the window over: drift fires once
            mon.observe_columns({"x": _stream(32, 256, mu=4.0)})
            assert mon.drifted_features() == ["x"]
            detected = [e for e in seen if isinstance(e, DriftDetected)]
            assert len(detected) == 1
            assert detected[0].feature == "x"
            assert detected[0].value > detected[0].threshold
            # reverting the traffic clears it (hysteresis satisfied)
            mon.observe_columns({"x": _stream(33, 512)})
            assert mon.drifted_features() == []
            cleared = [e for e in seen if isinstance(e, DriftCleared)]
            assert len(cleared) == 1 and cleared[0].feature == "x"
        finally:
            bus.remove_listener(seen.append)

    def test_gauges_and_snapshot(self):
        reg = MetricsRegistry()
        mon = self._monitor(_stream(40, 2000), registry=reg)
        mon.observe_columns({"x": _stream(41, 256, mu=4.0)})
        summary = reg.summary()
        psi_series = summary["quality_psi"]
        (key,) = psi_series.keys()
        assert "feature=x" in key and "model=m" in key
        assert psi_series[key] > 0.2
        snap = mon.snapshot()
        assert snap["model"] == "m"
        (row,) = snap["drift"]
        assert row["feature"] == "x" and row["drifted"] is True
        # the federated rebuild agrees with the local snapshot
        table = drift_table_from_summary(summary)
        assert len(table) == 1
        assert table[0]["feature"] == "x" and table[0]["drifted"] is True
        assert table[0]["psi"] == pytest.approx(row["psi"])

    def test_min_window_blocks_small_sample_psi_bias(self):
        """A short same-distribution window reads high on PSI by
        construction (E[PSI] ~ (bins-1)/n) — min_window must keep it
        from scoring at all."""
        mon = self._monitor(_stream(50, 2000), min_window=128, eval_every=8)
        mon.observe_columns({"x": _stream(51, 40)})
        assert mon.snapshot()["drift"] == []
        assert mon.drifted_features() == []

    def test_unprofiled_columns_ignored(self):
        reg = MetricsRegistry()
        mon = self._monitor(_stream(60, 500), registry=reg)
        mon.observe_columns({"y": [1.0] * 100})
        assert reg.summary().get("quality_observations_total", 0) == 0

    def test_suppression_nests(self):
        mon = self._monitor(_stream(61, 100))
        assert not mon.transform_suppressed
        with mon.suppress_transform():
            with mon.suppress_transform():
                assert mon.transform_suppressed
            assert mon.transform_suppressed
        assert not mon.transform_suppressed

    def test_version_zero_never_reloads(self, tmp_path):
        store = ModelStore(str(tmp_path))
        profile = ReferenceProfile.capture("m", 1, {"x": _stream(62, 200)})
        profile.commit(store)
        store.commit("text", name="m")
        mon = QualityMonitor(
            store=store, model="m", registry=MetricsRegistry()
        )
        assert mon.version == 1
        mon.note_version(0)  # untracked loop: must not reset the profile
        assert mon.version == 1 and mon.profile is not None
        # a profile-less new version keeps the reference, relabels only
        store.commit("text2", name="m")
        mon.note_version(2)
        assert mon.version == 2
        assert mon.profile is not None and mon.profile.version == 1


class TestAlertEvaluator:
    def _run(self, mean_apply_ms):
        """Drive one evaluator over a scripted metric timeline; returns
        (evaluator, fired, resolved, registry)."""
        t = {"now": 0.0}
        state = {"req": 0.0, "apply_sum": 0.0, "count": 0.0}

        def source():
            return {
                "serving_requests_total": state["req"],
                "serving_replies_failed_total": 0.0,
                "serving_apply_latency_seconds": {
                    "sum": state["apply_sum"], "count": state["count"],
                },
            }

        reg = MetricsRegistry()
        ev = AlertEvaluator(
            targets=SLOTargets(),  # p99 <= 50 ms
            source=source, registry=reg,
            windows=(2.0, 8.0), clock=lambda: t["now"],
        )
        seen = []
        bus = get_bus()
        bus.add_listener(seen.append)
        try:
            for step, ms in enumerate(mean_apply_ms):
                t["now"] = step * 1.0
                state["req"] += 10
                state["count"] += 10
                state["apply_sum"] += 10 * ms / 1e3
                ev.tick()
        finally:
            bus.remove_listener(seen.append)
        from mmlspark_tpu.observability.events import AlertFired, AlertResolved

        fired = [e for e in seen if isinstance(e, AlertFired)]
        resolved = [e for e in seen if isinstance(e, AlertResolved)]
        return ev, fired, resolved, reg

    def test_latency_storm_fires_and_resolves(self):
        # 10 quiet ticks (ring spans the 8 s window), 12 storm ticks at
        # 120 ms mean (2.4x the 50 ms budget), then recovery
        timeline = [5.0] * 10 + [120.0] * 12 + [5.0] * 12
        ev, fired, resolved, reg = self._run(timeline)
        assert [e.alert for e in fired] == ["latency"]
        assert fired[0].burn_short > 1.0 and fired[0].burn_long > 1.0
        assert fired[0].window_short_s == 2.0
        assert [e.alert for e in resolved] == ["latency"]
        assert ev.active_alerts() == ()
        assert reg.summary()["alerts_active"] == 0.0

    def test_short_blip_does_not_page(self):
        """The long window is the flap guard: a 2-tick spike burns the
        short window but never the long one."""
        timeline = [5.0] * 10 + [120.0] * 2 + [5.0] * 14
        _, fired, _, _ = self._run(timeline)
        assert fired == []

    def test_young_ring_never_fires(self):
        _, fired, _, _ = self._run([500.0] * 5)  # < long window of history
        assert fired == []

    def test_active_alerts_pins_fleet_controller(self):
        """The advisory hook: a firing alert blocks the idle scale-down
        path until it resolves."""
        from types import SimpleNamespace

        from mmlspark_tpu.serving.fleet import FleetController

        alerts = {"active": ("latency",)}
        ctl = FleetController(
            supervisor=SimpleNamespace(live_count=3, _procs={}),
            registry=SimpleNamespace(services=[]),
            min_replicas=1, max_replicas=4,
            cooldown_s=0.0, down_sustain_s=1.0,
            clock=lambda: 0.0,
            alert_advisor=lambda: alerts["active"],
        )
        idle = []  # no registered replicas -> zero inflight, zero shed
        for now in (0.0, 2.0, 4.0):
            assert ctl.decide(idle, now=now) is None
        alerts["active"] = ()
        assert ctl.decide(idle, now=5.0) is None  # idle clock restarts
        decision = ctl.decide(idle, now=7.0)
        assert decision is not None and decision[0] == "down"


class TestRoofline:
    def test_unknown_platform_skips_bound_classification(self):
        peaks = DevicePeaks(0.0, 0.0, UNKNOWN_PLATFORM)
        assert not peaks.known
        prof = FunctionProfile(
            name="f", executions=4, device_seconds=0.01,
            flops=1e9, bytes_accessed=1e6,
        )
        row = prof.roofline(*peaks, platform=peaks.platform)
        assert row["bound"] == "unknown"
        assert row["mxu_frac"] is None and row["hbm_frac"] is None
        assert row["platform"] == UNKNOWN_PLATFORM

    def test_zero_execution_profile_never_divides(self):
        row = FunctionProfile(name="f").roofline(0.0, 0.0, UNKNOWN_PLATFORM)
        assert row["mean_ms"] == 0.0 and row["bound"] == "unknown"

    def test_unrecognized_device_kind_is_sentinel(self, monkeypatch):
        from types import SimpleNamespace

        monkeypatch.delenv("MMLSPARK_TPU_PEAK_FLOPS", raising=False)
        monkeypatch.delenv("MMLSPARK_TPU_PEAK_HBM_BYTES", raising=False)
        peaks = device_peaks(SimpleNamespace(device_kind="Weird Chip 9000"))
        assert peaks.platform == UNKNOWN_PLATFORM
        assert tuple(peaks) == (0.0, 0.0)

    def test_env_override_labels_provenance(self, monkeypatch):
        from types import SimpleNamespace

        monkeypatch.setenv("MMLSPARK_TPU_PEAK_FLOPS", "1e12")
        monkeypatch.setenv("MMLSPARK_TPU_PEAK_HBM_BYTES", "1e11")
        peaks = device_peaks(SimpleNamespace(device_kind="whatever"))
        assert peaks.platform == "env-override"
        assert peaks.known and tuple(peaks) == (1e12, 1e11)


class TestQualityPairingCheck:
    def _records(self, *events):
        return [dict(e) for e in events]

    def test_paired_log_passes(self):
        from tools.check_eventlog import check_quality_pairing

        records = self._records(
            {"event": "DriftDetected", "feature": "x", "stat": "psi"},
            {"event": "AlertFired", "alert": "latency", "slo": "p99"},
            {"event": "DriftCleared", "feature": "x"},
            {"event": "AlertResolved", "alert": "latency"},
        )
        problems, summary = check_quality_pairing(records)
        assert problems == []
        assert "2/2" in summary

    def test_unpaired_onsets_flagged(self):
        from tools.check_eventlog import check_quality_pairing

        records = self._records(
            {"event": "DriftDetected", "feature": "x", "stat": "ks"},
            # a clear on ANOTHER feature must not pair feature x
            {"event": "DriftCleared", "feature": "y"},
            {"event": "AlertFired", "alert": "availability", "slo": "a"},
        )
        problems, _ = check_quality_pairing(records)
        assert len(problems) == 2
        assert any("'x'" in p for p in problems)
        assert any("availability" in p for p in problems)

    def test_clear_before_onset_does_not_pair(self):
        from tools.check_eventlog import check_quality_pairing

        records = self._records(
            {"event": "DriftCleared", "feature": "x"},
            {"event": "DriftDetected", "feature": "x", "stat": "psi"},
        )
        problems, _ = check_quality_pairing(records)
        assert len(problems) == 1


class TestFederatorServices:
    def test_bare_list_and_envelope_both_parse(self):
        svc = [{"name": "r0", "host": "127.0.0.1", "port": 9001}]
        for body in (json.dumps(svc), json.dumps({"services": svc})):
            fed = MetricsFederator(
                "http://reg", fetch=lambda url, t, b=body: b
            )
            assert fed.services() == svc

    def test_unreachable_registry_is_empty(self):
        def boom(url, timeout_s):
            raise OSError("connection refused")

        assert MetricsFederator("http://reg", fetch=boom).services() == []
