"""Featurization (reference ``featurize/`` suites — SURVEY.md §2.10)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.featurize import (
    AssembleFeatures,
    CleanMissingData,
    DataConversion,
    Featurize,
    IndexToValue,
    MultiNGram,
    PageSplitter,
    TextFeaturizer,
    ValueIndexer,
)


def test_value_indexer_roundtrip():
    t = Table({"cat": np.array(["b", "a", "b", "c"], dtype=object)})
    model = ValueIndexer(inputCol="cat", outputCol="idx").fit(t)
    out = model.transform(t)
    assert list(out["idx"]) == [1, 0, 1, 2]
    assert out.metadata("idx")["categorical"]
    back = IndexToValue(inputCol="idx", outputCol="orig").transform(out)
    assert list(back["orig"]) == ["b", "a", "b", "c"]
    # Unseen value -> unknown bucket -> None on inverse.
    t2 = Table({"cat": np.array(["a", "zzz"], dtype=object)})
    out2 = model.transform(t2)
    assert list(out2["idx"]) == [0, 3]
    assert IndexToValue(inputCol="idx", outputCol="v").transform(out2)["v"][1] is None


def test_value_indexer_numeric():
    t = Table({"x": np.array([10, 5, 10, 7])})
    model = ValueIndexer(inputCol="x", outputCol="idx").fit(t)
    assert list(model.transform(t)["idx"]) == [2, 0, 2, 1]


def test_clean_missing_data():
    t = Table(
        {
            "a": np.array([1.0, np.nan, 3.0]),
            "b": np.array([np.nan, 4.0, 8.0]),
        }
    )
    model = CleanMissingData(inputCols=["a", "b"], cleaningMode="Mean").fit(t)
    out = model.transform(t)
    np.testing.assert_allclose(out["a"], [1.0, 2.0, 3.0])
    np.testing.assert_allclose(out["b"], [6.0, 4.0, 8.0])
    model = CleanMissingData(
        inputCols=["a"], cleaningMode="Custom", customValue=-1
    ).fit(t)
    np.testing.assert_allclose(model.transform(t)["a"], [1.0, -1.0, 3.0])
    model = CleanMissingData(inputCols=["a"], cleaningMode="Median").fit(t)
    np.testing.assert_allclose(model.transform(t)["a"], [1.0, 2.0, 3.0])


def test_data_conversion():
    t = Table({"x": np.array(["1", "2"], dtype=object), "y": np.array([1.5, 2.5])})
    out = DataConversion(inputCols=["x"], convertTo="double").transform(t)
    assert out["x"].dtype == np.float64
    out = DataConversion(inputCols=["y"], convertTo="string").transform(t)
    assert out["y"].dtype == object and out["y"][0] == "1.5"
    out = DataConversion(inputCols=["x"], convertTo="toCategorical").transform(t)
    assert out.metadata("x").get("categorical")
    back = DataConversion(inputCols=["x"], convertTo="clearCategorical").transform(out)
    assert list(back["x"]) == ["1", "2"]


def test_assemble_features():
    t = Table(
        {
            "num": np.array([1.0, 2.0]),
            "vec": np.array([[1.0, 2.0], [3.0, 4.0]]),
            "flag": np.array([True, False]),
        }
    )
    out = AssembleFeatures(inputCols=["num", "vec", "flag"]).transform(t)
    np.testing.assert_allclose(
        out["features"], [[1.0, 1.0, 2.0, 1.0], [2.0, 3.0, 4.0, 0.0]]
    )
    with pytest.raises(ValueError):
        AssembleFeatures(inputCols=["s"]).transform(
            Table({"s": np.array(["x", "y"], dtype=object)})
        )


def test_featurize_mixed_columns():
    rng = np.random.default_rng(0)
    n = 50
    t = Table(
        {
            "num": rng.normal(size=n),
            "with_nan": np.where(rng.random(n) < 0.2, np.nan, rng.normal(size=n)),
            "cat": np.array([["red", "green", "blue"][i % 3] for i in range(n)], dtype=object),
            "text": np.array([f"word{i} common tokens here {i%7}" for i in range(n)], dtype=object),
        }
    )
    model = Featurize(
        inputCols=["num", "with_nan", "cat", "text"],
        outputCol="features",
        numberOfFeatures=64,
    ).fit(t)
    out = model.transform(t)
    f = out["features"]
    # 1 numeric + 1 numeric + (3 levels + unknown) one-hot + 64 hash dims.
    assert f.shape == (n, 2 + 4 + 64)
    assert np.isfinite(f).all()
    # Unknown categorical at transform time goes to the unknown slot.
    t2 = Table(
        {
            "num": np.zeros(1),
            "with_nan": np.array([np.nan]),
            "cat": np.array(["violet"], dtype=object),
            "text": np.array(["common tokens"], dtype=object),
        }
    )
    f2 = model.transform(t2)["features"]
    assert f2[0, 2 + 3] == 1.0  # unknown bucket


def test_featurize_single_vector_passthrough():
    t = Table({"vec": np.array([[1.0, 2.0], [3.0, 4.0]])})
    model = Featurize(inputCols=["vec"], outputCol="features").fit(t)
    np.testing.assert_allclose(model.transform(t)["features"], t["vec"])


def test_text_featurizer_idf():
    docs = ["the cat sat", "the dog sat", "a bird flew"]
    t = Table({"text": np.array(docs, dtype=object)})
    model = TextFeaturizer(
        inputCol="text", outputCol="tf", numFeatures=256, useIDF=True
    ).fit(t)
    out = model.transform(t)
    assert out["tf"].shape == (3, 256)
    # 'the' appears in 2/3 docs; its idf weight is below a unique token's.
    assert out["tf"].max() > 0


def test_text_featurizer_ngrams_binary():
    t = Table({"text": np.array(["a b a b", "c d"], dtype=object)})
    model = TextFeaturizer(
        inputCol="text", outputCol="tf", numFeatures=64,
        useNGram=True, nGramLength=2, binary=True, useIDF=False,
    ).fit(t)
    out = model.transform(t)
    assert set(np.unique(out["tf"])) <= {0.0, 1.0}


def test_text_featurizer_token_list_input():
    t = Table({"tokens": [["x", "y"], ["z"]]})
    model = TextFeaturizer(
        inputCol="tokens", outputCol="tf", numFeatures=32, useIDF=False
    ).fit(t)
    assert model.transform(t)["tf"].shape == (2, 32)


def test_multi_ngram():
    t = Table({"tokens": [["a", "b", "c"]]})
    out = MultiNGram(inputCol="tokens", outputCol="grams", lengths=[1, 2, 3]).transform(t)
    assert list(out["grams"][0]) == ["a", "b", "c", "a b", "b c", "a b c"]


def test_page_splitter():
    text = "word " * 100  # 500 chars
    t = Table({"doc": np.array([text.strip()], dtype=object)})
    out = PageSplitter(
        inputCol="doc", outputCol="pages",
        maximumPageLength=100, minimumPageLength=80,
    ).transform(t)
    pages = out["pages"][0]
    assert "".join(pages) == text.strip()
    assert all(len(p) <= 100 for p in pages)
    assert all(len(p) >= 80 for p in pages[:-1])


def test_featurize_serialization(tmp_path):
    t = Table(
        {
            "num": np.arange(5.0),
            "cat": np.array(list("ababa"), dtype=object),
        }
    )
    model = Featurize(inputCols=["num", "cat"], outputCol="features").fit(t)
    model.save(str(tmp_path / "feat"))
    from mmlspark_tpu.core.pipeline import PipelineStage

    loaded = PipelineStage.load(str(tmp_path / "feat"))
    np.testing.assert_allclose(loaded.transform(t)["features"], model.transform(t)["features"])
