"""dataguard/ tests — corrupt-record read modes (Spark's ``mode`` /
``badRecordsPath`` / ``ignoreCorruptFiles`` analogues), the epoch-keyed
dead-letter store, fit-time NaN/Inf guards, and the malformed-request
serving edge (structured traced 400s + the poison-client breaker)."""

import json
import time
import urllib.error
import urllib.request
import zipfile

import numpy as np
import pytest

from mmlspark_tpu.data.sharded import ShardedDataset, fit_gbdt_sharded
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.dataguard import (
    BadRecordsError,
    CorruptRecord,
    DeadLetterStore,
    MalformedRateBreaker,
    RequestValidator,
    guard_arrays,
    guard_table,
    normalize_mode,
)
from mmlspark_tpu.lightgbm import LightGBMClassifier
from mmlspark_tpu.runtime.lineage import PartitionLostError

NUM_SHARDS = 6
ROWS = 50
TORN, STALE = 1, 4  # corruption styles per shard index


def _make_shards(out_dir, seed=3, num_features=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(NUM_SHARDS * ROWS, num_features))
    y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
    ds = ShardedDataset.write_shards(str(out_dir), X, y, rows_per_shard=ROWS)
    return list(ds.paths)


def _corrupt(paths):
    """Tear shard TORN's bytes; stale-sidecar shard STALE."""
    with open(paths[TORN], "rb+") as fh:
        fh.truncate(200)
    with open(paths[STALE] + ".crc32", "w") as fh:
        fh.write("deadbeef")
    return [p for i, p in enumerate(paths) if i not in (TORN, STALE)]


class TestReadModes:
    def test_failfast_is_default_and_raises(self, tmp_path):
        paths = _make_shards(tmp_path)
        _corrupt(paths)
        ds = ShardedDataset(paths)
        assert ds.mode == "failfast"
        with pytest.raises((PartitionLostError, zipfile.BadZipFile, ValueError)):
            ds.num_rows  # noqa: B018 - property triggers the scan

    def test_failfast_stale_sidecar_raises_on_load(self, tmp_path):
        paths = _make_shards(tmp_path)
        _corrupt(paths)
        # the stale-sidecar shard has intact headers, so the scan passes;
        # the CRC check at decode time must still kill a FAILFAST read
        ds = ShardedDataset([paths[STALE]])
        with pytest.raises(PartitionLostError):
            list(ds.iter_shards())

    def test_permissive_quarantines_and_letters(self, tmp_path):
        paths = _make_shards(tmp_path)
        clean = _corrupt(paths)
        dlq_root = str(tmp_path / "bad")
        ds = ShardedDataset(
            paths, mode="PERMISSIVE", bad_records_path=dlq_root
        )
        assert ds.num_rows == len(clean) * ROWS
        assert sorted(r.source for r in ds.quarantined) == sorted(
            [paths[TORN], paths[STALE]]
        )
        assert ds.paths == clean  # survivor order is listing order
        dlq = DeadLetterStore(dlq_root, name="sharded")
        assert dlq.epochs() == [0]
        assert dlq.manifest()[0]["count"] == 2
        assert sorted(r.source for r in dlq.replay()) == sorted(
            [paths[TORN], paths[STALE]]
        )

    def test_dropmalformed_counts_without_lettering(self, tmp_path):
        paths = _make_shards(tmp_path)
        _corrupt(paths)
        dlq_root = str(tmp_path / "bad")
        ds = ShardedDataset(
            paths, mode="dropmalformed", bad_records_path=dlq_root
        )
        assert ds.num_rows == (NUM_SHARDS - 2) * ROWS
        assert len(ds.quarantined) == 2
        # dropmalformed drops and counts — it never writes the DLQ
        assert DeadLetterStore(dlq_root).epochs() == []

    def test_ignore_corrupt_files_upgrades_failfast(self, tmp_path):
        paths = _make_shards(tmp_path)
        _corrupt(paths)
        ds = ShardedDataset(paths, ignore_corrupt_files=True)
        assert ds.mode == "dropmalformed"
        assert ds.num_rows == (NUM_SHARDS - 2) * ROWS

    def test_all_corrupt_raises_bad_records(self, tmp_path):
        paths = _make_shards(tmp_path)
        for p in paths:
            with open(p + ".crc32", "w") as fh:
                fh.write("deadbeef")
        with pytest.raises(BadRecordsError) as ei:
            ShardedDataset(paths, mode="permissive").num_rows  # noqa: B018
        assert len(ei.value.records) == NUM_SHARDS

    def test_normalize_mode(self):
        assert normalize_mode("PERMISSIVE") == "permissive"
        assert normalize_mode(" FailFast ") == "failfast"
        with pytest.raises(ValueError, match="unknown read mode"):
            normalize_mode("lenient")


class TestQuarantineByteIdentity:
    """The tentpole property: quarantining a seeded K-shard subset yields
    the same model bytes as fitting the clean complement — on the
    quantized out-of-core path (bin mapper + uint8 memmap)."""

    def test_permissive_fit_equals_clean_complement(self, tmp_path):
        paths = _make_shards(tmp_path, seed=11)
        import os

        seed = int(os.environ.get("MMLSPARK_TPU_FAULT_SEED", "23"))
        rng = np.random.default_rng(seed)
        k_bad = sorted(rng.choice(NUM_SHARDS, size=2, replace=False).tolist())
        for i in k_bad:
            with open(paths[i] + ".crc32", "w") as fh:
                fh.write("00000000")
        clean = [p for i, p in enumerate(paths) if i not in k_bad]

        def est():
            return LightGBMClassifier(numIterations=5, numLeaves=7, seed=9)

        ref = fit_gbdt_sharded(est(), ShardedDataset(clean))
        got = fit_gbdt_sharded(
            est(), ShardedDataset(paths, mode="permissive")
        )
        assert got.booster.model_to_string() == ref.booster.model_to_string()


class TestDeadLetterStore:
    REC = CorruptRecord(source="s.npz", index=-1, reason="torn", detail="x")

    def test_commit_replay_roundtrip(self, tmp_path):
        dlq = DeadLetterStore(str(tmp_path), name="t")
        assert dlq.commit_epoch(3, [self.REC]) is True
        assert dlq.has_epoch(3) and dlq.epochs() == [3]
        (rec,) = dlq.replay(3)
        assert (rec.source, rec.index, rec.reason) == ("s.npz", -1, "torn")
        assert dlq.count() == 1

    def test_commit_is_epoch_idempotent(self, tmp_path):
        dlq = DeadLetterStore(str(tmp_path))
        assert dlq.commit_epoch(1, [self.REC]) is True
        # the replayed epoch (WAL'd, SIGKILL'd before its commit log)
        # re-quarantines identical records: nothing may be written twice
        other = CorruptRecord(source="other", index=0, reason="torn")
        assert dlq.commit_epoch(1, [self.REC, other]) is False
        assert dlq.manifest()[1]["count"] == 1

    def test_empty_commit_is_a_noop(self, tmp_path):
        dlq = DeadLetterStore(str(tmp_path))
        assert dlq.commit_epoch(0, []) is False
        assert dlq.letter([]) is None
        assert dlq.epochs() == []

    def test_letter_allocates_next_epoch(self, tmp_path):
        dlq = DeadLetterStore(str(tmp_path))
        assert dlq.letter([self.REC]) == 0
        assert dlq.letter([self.REC]) == 1
        assert dlq.epochs() == [0, 1]

    def test_replay_verifies_crc(self, tmp_path):
        dlq = DeadLetterStore(str(tmp_path))
        dlq.commit_epoch(0, [self.REC])
        path = dlq._records_path(0)
        with open(path, "ab") as fh:
            fh.write(b'{"source": "injected", "index": 0}\n')
        with pytest.raises(ValueError, match="CRC"):
            dlq.replay(0)

    def test_dict_records_coerce(self, tmp_path):
        dlq = DeadLetterStore(str(tmp_path))
        dlq.commit_epoch(0, [{"source": "a", "index": 2, "reason": "bad"}])
        (rec,) = dlq.replay()
        assert rec.index == 2 and rec.reason == "bad"


class TestJsonlQuarantine:
    def test_bad_line_quarantines_under_permissive(self, tmp_path):
        from mmlspark_tpu.streaming.source import _load_json_rows

        path = tmp_path / "rows.jsonl"
        path.write_text(
            '{"a": 1.0}\n{"a": not json\n{"a": 3.0}\n'
        )
        quarantined = []
        table = _load_json_rows(
            str(path), mode="permissive", quarantined=quarantined
        )
        assert table.num_rows == 2
        assert np.allclose(table.column("a"), [1.0, 3.0])
        (rec,) = quarantined
        assert rec.index == 1 and rec.source == str(path)

    def test_bad_line_raises_under_failfast(self, tmp_path):
        from mmlspark_tpu.streaming.source import _load_json_rows

        path = tmp_path / "rows.jsonl"
        path.write_text('{"a": 1.0}\nnope\n')
        with pytest.raises(ValueError):
            _load_json_rows(str(path), mode="failfast", quarantined=[])


class TestFitGuards:
    def _dirty(self):
        X = np.array([
            [1.0, 2.0], [np.nan, 4.0], [5.0, 6.0], [7.0, np.inf],
        ])
        y = np.array([0.0, 1.0, np.nan, 1.0])
        return X, y

    def test_fail_policy_raises_naming_columns(self):
        X, y = self._dirty()
        with pytest.raises(BadRecordsError) as ei:
            guard_arrays(X, y, policy="fail")
        cols = {r.detail.split(":")[0] for r in ei.value.records}
        assert cols == {"f0", "f1", "label"}

    def test_drop_policy_keeps_clean_complement(self):
        X, y = self._dirty()
        Xg, yg, _, report = guard_arrays(X, y, policy="drop")
        np.testing.assert_array_equal(Xg, [[1.0, 2.0]])
        np.testing.assert_array_equal(yg, [0.0])
        assert report.rows_dropped == 3

    def test_impute_fills_features_but_drops_bad_labels(self):
        X, y = self._dirty()
        Xg, yg, _, report = guard_arrays(X, y, policy="impute")
        # row 2 (NaN label) is dropped — a label cannot be conjured
        assert len(Xg) == 3 and report.rows_dropped == 1
        assert report.values_imputed == 2
        assert np.isfinite(Xg).all()
        # the NaN in f0 became the mean of f0's finite entries
        finite_f0 = [1.0, 5.0, 7.0]
        assert Xg[1, 0] == pytest.approx(np.mean(finite_f0))

    def test_classifier_label_domain(self):
        X = np.ones((3, 2))
        y = np.array([0.0, 1.0, 0.5])
        with pytest.raises(BadRecordsError):
            guard_arrays(X, y, policy="fail", label_domain="classifier")
        Xg, yg, _, rep = guard_arrays(
            X, y, policy="drop", label_domain="classifier"
        )
        assert len(Xg) == 2 and rep.bad_label_rows == 1

    def test_weight_column_guarded(self):
        X = np.ones((3, 2))
        y = np.zeros(3)
        w = np.array([1.0, np.nan, 1.0])
        Xg, yg, wg, rep = guard_arrays(X, y, w, policy="drop")
        assert len(Xg) == 2 and np.isfinite(wg).all()

    def test_guard_table_drop_and_impute(self):
        t = Table({
            "features": np.array([[1.0, 2.0], [np.nan, 4.0], [5.0, 6.0]]),
            "label": np.array([0.0, 1.0, 1.0]),
            "name": np.array(["a", "b", "c"], dtype=object),
        })
        out, rep = guard_table(t, policy="drop", label_col="label")
        assert out.num_rows == 2 and rep.rows_dropped == 1
        out, rep = guard_table(t, policy="impute", label_col="label")
        assert out.num_rows == 3 and rep.values_imputed == 1
        assert np.isfinite(out.column("features")).all()

    def test_clean_input_passes_untouched(self):
        X = np.ones((4, 2))
        Xg, yg, _, rep = guard_arrays(X, np.zeros(4), policy="fail")
        assert rep.clean and Xg is X


class TestPipelineGuard:
    def _table(self):
        rng = np.random.default_rng(5)
        X = rng.normal(size=(80, 4))
        y = (X[:, 0] > 0).astype(np.float64)
        return X, y

    def test_fail_policy_raises_at_fit(self):
        from mmlspark_tpu.core.pipeline import Pipeline

        X, y = self._table()
        X[3, 1] = np.nan
        pipe = Pipeline(
            stages=[LightGBMClassifier(numIterations=3, numLeaves=7)],
            invalidDataPolicy="fail",
        )
        with pytest.raises(BadRecordsError):
            pipe.fit(Table({"features": X, "label": y}))

    def test_drop_policy_matches_clean_complement_fit(self):
        from mmlspark_tpu.core.pipeline import Pipeline

        X, y = self._table()
        Xd = X.copy()
        Xd[7, 2] = np.inf
        yd = y.copy()
        yd[11] = np.nan

        def pipe(policy=""):
            return Pipeline(
                stages=[LightGBMClassifier(
                    numIterations=4, numLeaves=7, seed=2,
                )],
                invalidDataPolicy=policy,
            )

        keep = np.ones(len(X), dtype=bool)
        keep[[7, 11]] = False
        ref = pipe().fit(Table({"features": X[keep], "label": y[keep]}))
        got = pipe("drop").fit(Table({"features": Xd, "label": yd}))
        assert got.getStages()[-1].booster.model_to_string() == \
            ref.getStages()[-1].booster.model_to_string()

    def test_classifier_stage_pins_label_domain(self):
        from mmlspark_tpu.core.pipeline import Pipeline

        X, y = self._table()
        y[0] = 0.5  # finite, but not a class id
        pipe = Pipeline(
            stages=[LightGBMClassifier(numIterations=3, numLeaves=7)],
            invalidDataPolicy="fail",
        )
        with pytest.raises(BadRecordsError):
            pipe.fit(Table({"features": X, "label": y}))


class TestRequestValidator:
    def test_structural_rejections(self):
        v = RequestValidator(input_col="input", width=3)
        assert v.check_payload(None) == (
            "empty-payload", "request body is empty"
        )
        assert v.check_payload({"other": 1})[0] == "missing-input-col"
        assert v.check_payload({"input": float("nan")})[0] == \
            "non-finite-value"
        assert v.check_payload({"input": [1.0, None, 2.0]})[0] == "null-value"
        assert v.check_payload({"input": [1.0, 2.0]})[0] == "shape-mismatch"
        assert v.check_payload({"input": [[1.0, 2.0, 3.0], [1.0, 2.0]]})[0] \
            == "shape-mismatch"
        assert v.check_payload({"input": [1.0, 2.0, 3.0]}) is None
        assert v.check_payload({"input": "some text"}) is None

    def test_for_model_infers_booster_width(self, tmp_path):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(60, 4))
        y = (X[:, 0] > 0).astype(np.float64)
        model = LightGBMClassifier(numIterations=2, numLeaves=7).fit(
            Table({"features": X, "label": y})
        )
        v = RequestValidator.for_model(model, input_col="features")
        assert v.width == 4

    def test_for_model_unknown_width_validates_structure_only(self):
        v = RequestValidator.for_model(object())
        assert v.width is None
        assert v.check_payload({"input": [1.0, 2.0]}) is None
        assert v.check_payload({"input": float("inf")})[0] == \
            "non-finite-value"

    def test_disabled_passes_everything(self):
        v = RequestValidator(enabled=False)
        assert v.check_payload(None) is None


class TestMalformedRateBreaker:
    def test_trip_and_release_with_injected_clock(self):
        now = [0.0]
        b = MalformedRateBreaker(
            threshold=3, window_s=10.0, reset_s=5.0, clock=lambda: now[0]
        )
        assert b.record_malformed("evil") is False
        assert b.record_malformed("evil") is False
        assert b.record_malformed("evil") is True  # third one trips
        assert b.blocked("evil") is True
        assert b.blocked("innocent") is False  # per-client isolation
        now[0] = 5.1
        assert b.blocked("evil") is False  # released after reset_s
        assert b.record_malformed("evil") is False  # window restarts

    def test_old_events_age_out_of_window(self):
        now = [0.0]
        b = MalformedRateBreaker(
            threshold=3, window_s=2.0, reset_s=1.0, clock=lambda: now[0]
        )
        b.record_malformed("c")
        b.record_malformed("c")
        now[0] = 3.0  # both events aged out
        assert b.record_malformed("c") is False
        assert b.blocked("c") is False


def _post_raw(url, data, headers=None, timeout=10):
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


class TestServingEdge:
    """Pre-admission hardening: structured, traced 400s and the breaker."""

    def _model(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(60, 3))
        y = (X[:, 0] > 0).astype(np.float64)
        return LightGBMClassifier(numIterations=2, numLeaves=7).fit(
            Table({"features": X, "label": y})
        )

    def test_invalid_json_gets_traced_structured_400(self):
        from mmlspark_tpu.serving import ServingServer

        with ServingServer(self._model(), input_col="features") as srv:
            status, body, headers = _post_raw(
                srv.info.url, b'{"features": [1.0, broken'
            )
            assert status == 400
            # the regression this guards: the 400 path must carry the
            # trace id even though no span existed before the parse
            assert headers.get("X-Trace-Id")
            err = json.loads(body)["error"]
            assert err["kind"] == "invalid-json" and err["rid"]

    def test_schema_violations_get_structured_400(self):
        from mmlspark_tpu.serving import ServingServer

        with ServingServer(self._model(), input_col="features") as srv:
            cases = [
                (json.dumps({"wrong": [1.0]}).encode(), "missing-input-col"),
                (b'{"features": [1.0, 2.0]}', "shape-mismatch"),
                (b'{"features": [NaN, 1.0, 2.0]}', "non-finite-value"),
            ]
            for payload, kind in cases:
                status, body, headers = _post_raw(srv.info.url, payload)
                assert status == 400, (kind, status, body)
                assert json.loads(body)["error"]["kind"] == kind
                assert headers.get("X-Trace-Id")
            # a valid request on the same (kept-alive) endpoint still serves
            status, _, _ = _post_raw(
                srv.info.url, json.dumps({"features": [0.1, 0.2, 0.3]}).encode()
            )
            assert status == 200

    def test_poison_client_shed_then_released(self):
        from mmlspark_tpu.serving import ServingServer

        with ServingServer(
            self._model(), input_col="features",
            malformed_threshold=3, malformed_window_s=30.0,
            malformed_reset_s=0.3,
        ) as srv:
            poison = {"X-Client-Id": "poison"}
            for _ in range(3):
                status, _, _ = _post_raw(
                    srv.info.url, b'{"features": bad', headers=poison
                )
                assert status == 400
            good = json.dumps({"features": [0.1, 0.2, 0.3]}).encode()
            status, body, headers = _post_raw(srv.info.url, good, headers=poison)
            assert status == 429, body
            assert "Retry-After" in headers
            assert json.loads(body)["error"]["kind"] == "malformed-rate"
            # a different client on the same replica is untouched
            status, _, _ = _post_raw(
                srv.info.url, good, headers={"X-Client-Id": "healthy"}
            )
            assert status == 200
            time.sleep(0.35)
            status, _, _ = _post_raw(srv.info.url, good, headers=poison)
            assert status == 200

    def test_validator_off_restores_old_edge(self):
        from mmlspark_tpu.serving import ServingServer

        with ServingServer(
            self._model(), input_col="features", request_validator="off"
        ) as srv:
            # shape garbage reaches the model unchecked (the booster
            # happens to tolerate short rows) — the point is that the
            # edge no longer pre-rejects: opt-out is explicit
            status, _, _ = _post_raw(srv.info.url, b'{"features": [1.0]}')
            assert status != 400


class TestFaultPlanMalformed:
    def test_take_malformed_drains_in_order(self):
        from mmlspark_tpu.runtime.faults import FaultPlan

        plan = FaultPlan(seed=1)
        plan.malformed_request(count=2, kind="json")
        plan.malformed_request(count=1, kind="nan")
        kinds = [plan.take_malformed() for _ in range(4)]
        assert kinds == ["json", "json", "nan", None]
        fired = [f for f in plan.fired if f[0] == "malformed_request"]
        assert len(fired) == 3

    def test_unknown_kind_rejected(self):
        from mmlspark_tpu.runtime.faults import FaultPlan

        with pytest.raises(ValueError, match="malformed-request kind"):
            FaultPlan(seed=1).malformed_request(kind="gibberish")
