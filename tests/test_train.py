"""Train/eval API (reference ``train/`` suites — SURVEY.md §2.12)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.train import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
    TrainClassifier,
    TrainRegressor,
)
from mmlspark_tpu.train.statistics import binary_auc


@pytest.fixture()
def mixed_classification_table(rng):
    n = 200
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    cat = np.array([["u", "v"][i % 2] for i in range(n)], dtype=object)
    margin = 2.0 * x1 - x2 + np.where(cat == "u", 1.0, -1.0)
    label = np.array(["yes" if m > 0 else "no" for m in margin], dtype=object)
    return Table({"x1": x1, "x2": x2, "cat": cat, "label": label})


def test_train_classifier_string_labels(mixed_classification_table):
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    t = mixed_classification_table
    trainer = TrainClassifier(
        model=LightGBMClassifier(numIterations=20, numLeaves=7),
        labelCol="label",
    )
    model = trainer.fit(t)
    out = model.transform(t)
    # Predictions decoded back to the original string labels.
    assert set(np.unique(out["prediction"].astype(str))) <= {"yes", "no"}
    acc = (out["prediction"].astype(str) == t["label"].astype(str)).mean()
    assert acc > 0.9


def test_train_regressor(rng):
    from mmlspark_tpu.lightgbm import LightGBMRegressor

    n = 300
    x = rng.normal(size=(n, 4))
    y = x[:, 0] * 3 + x[:, 1] + 0.05 * rng.normal(size=n)
    t = Table({"f": x, "label": y})
    model = TrainRegressor(
        model=LightGBMRegressor(numIterations=40, numLeaves=15), labelCol="label"
    ).fit(t)
    out = model.transform(t)
    stats = ComputeModelStatistics(
        labelCol="label", evaluationMetric="regression"
    ).transform(out)
    assert stats["R^2"][0] > 0.8


def test_compute_model_statistics_classification():
    t = Table(
        {
            "label": np.array([0, 0, 1, 1, 1, 0]),
            "prediction": np.array([0, 1, 1, 1, 0, 0]),
            "probability": np.array(
                [[0.8, 0.2], [0.4, 0.6], [0.1, 0.9], [0.2, 0.8], [0.7, 0.3], [0.9, 0.1]]
            ),
        }
    )
    stats = ComputeModelStatistics(labelCol="label").transform(t)
    assert stats["accuracy"][0] == pytest.approx(4 / 6)
    assert 0.5 < stats["AUC"][0] <= 1.0
    cm = stats["confusion_matrix"][0].reshape(2, 2)
    assert cm.sum() == 6 and cm[0, 0] == 2 and cm[1, 1] == 2


def test_binary_auc_known_value():
    labels = np.array([0, 0, 1, 1])
    scores = np.array([0.1, 0.4, 0.35, 0.8])
    # sklearn-verified value for this classic example.
    assert binary_auc(labels, scores) == pytest.approx(0.75)
    assert binary_auc(labels, np.array([0.5, 0.5, 0.5, 0.5])) == pytest.approx(0.5)


def test_compute_model_statistics_regression():
    t = Table(
        {"label": np.array([1.0, 2.0, 3.0]), "prediction": np.array([1.1, 1.9, 3.2])}
    )
    stats = ComputeModelStatistics(
        labelCol="label", evaluationMetric="regression"
    ).transform(t)
    assert stats["mean_squared_error"][0] == pytest.approx(0.02, abs=1e-9)
    assert stats["R^2"][0] > 0.96


def test_per_instance_statistics():
    t = Table(
        {
            "label": np.array([0.0, 1.0]),
            "prediction": np.array([0.0, 0.0]),
            "probability": np.array([[0.9, 0.1], [0.6, 0.4]]),
        }
    )
    out = ComputePerInstanceStatistics(labelCol="label").transform(t)
    np.testing.assert_allclose(out["correct"], [1.0, 0.0])
    np.testing.assert_allclose(out["log_loss"], [-np.log(0.9), -np.log(0.4)])
    t2 = Table({"label": np.array([1.0, 2.0]), "prediction": np.array([1.5, 2.0])})
    out2 = ComputePerInstanceStatistics(
        labelCol="label", evaluationMetric="regression"
    ).transform(t2)
    np.testing.assert_allclose(out2["L2_loss"], [0.25, 0.0])


def test_model_statistics_string_labels(mixed_classification_table):
    # Regression: TrainClassifier emits decoded string predictions; the
    # metrics stage must compose with them directly.
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    t = mixed_classification_table
    model = TrainClassifier(
        model=LightGBMClassifier(numIterations=10, numLeaves=7), labelCol="label"
    ).fit(t)
    out = model.transform(t)
    stats = ComputeModelStatistics(labelCol="label").transform(out)
    assert stats["accuracy"][0] > 0.8
    assert "AUC" in stats.columns
    per = ComputePerInstanceStatistics(labelCol="label").transform(out)
    assert set(np.unique(per["correct"])) <= {0.0, 1.0}


def test_per_instance_log_loss_shifted_binary_labels():
    # Regression: labels {1,2} with 1-D probabilities = P(higher class).
    t = Table(
        {
            "label": np.array([1.0, 2.0]),
            "prediction": np.array([1.0, 2.0]),
            "probability": np.array([0.1, 0.9]),
        }
    )
    out = ComputePerInstanceStatistics(labelCol="label").transform(t)
    np.testing.assert_allclose(out["log_loss"], [-np.log(0.9), -np.log(0.9)])


def test_index_to_value_numeric_unknown():
    # Regression: numeric levels + unknown bucket -> NaN, not a crash.
    from mmlspark_tpu.featurize import IndexToValue, ValueIndexer

    t = Table({"x": np.array([10, 5, 7])})
    model = ValueIndexer(inputCol="x", outputCol="idx").fit(t)
    out = model.transform(Table({"x": np.array([10, 999])}))
    back = IndexToValue(inputCol="idx", outputCol="v").transform(out)
    assert back["v"][0] == 10.0 and np.isnan(back["v"][1])


def test_trained_model_serialization(tmp_path, mixed_classification_table):
    from mmlspark_tpu.core.pipeline import PipelineStage
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    t = mixed_classification_table
    model = TrainClassifier(
        model=LightGBMClassifier(numIterations=5, numLeaves=7), labelCol="label"
    ).fit(t)
    model.save(str(tmp_path / "trained"))
    loaded = PipelineStage.load(str(tmp_path / "trained"))
    a = model.transform(t)["prediction"].astype(str)
    b = loaded.transform(t)["prediction"].astype(str)
    assert list(a) == list(b)


def test_log_loss_subset_classes_aligns_with_model_columns():
    # Regression: eval rows observing only classes {0, 2} of a 3-class model
    # must index probability column 2 for class 2, not dense-remapped id 1.
    t = Table(
        {
            "label": np.array([0.0, 2.0]),
            "prediction": np.array([0.0, 2.0]),
            "probability": np.array([[0.8, 0.1, 0.1], [0.1, 0.1, 0.8]]),
        }
    )
    out = ComputePerInstanceStatistics(labelCol="label").transform(t)
    np.testing.assert_allclose(out["log_loss"], [-np.log(0.8), -np.log(0.8)])


def test_no_auc_for_two_class_slice_of_multiclass_model():
    t = Table(
        {
            "label": np.array([0.0, 2.0]),
            "prediction": np.array([0.0, 2.0]),
            "probability": np.array([[0.8, 0.1, 0.1], [0.1, 0.1, 0.8]]),
        }
    )
    out = ComputeModelStatistics(labelCol="label").transform(t)
    assert "AUC" not in out.columns


def test_trained_classifier_custom_prediction_col():
    # Regression: label decoding must follow the learner's predictionCol.
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    rng = np.random.default_rng(3)
    X = rng.normal(size=(80, 4))
    y = np.array(["yes" if v > 0 else "no" for v in X[:, 0]], dtype=object)
    t = Table({"f": X, "label": y})
    model = TrainClassifier(
        model=LightGBMClassifier(numIterations=5, numLeaves=7, predictionCol="pred"),
        labelCol="label",
    ).fit(t)
    out = model.transform(t)
    assert set(np.unique(out["pred"].astype(str))) <= {"yes", "no"}


def test_train_features_col_collision():
    # Regression: a real column named TrainedFeatures must not be clobbered.
    rng = np.random.default_rng(4)
    t = Table(
        {
            "TrainedFeatures": rng.normal(size=100),
            "other": rng.normal(size=100),
            "label": (rng.normal(size=100) > 0).astype(np.float64),
        }
    )
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    model = TrainClassifier(
        model=LightGBMClassifier(numIterations=3, numLeaves=7), labelCol="label"
    ).fit(t)
    out = model.transform(t)
    np.testing.assert_array_equal(out["TrainedFeatures"], t["TrainedFeatures"])


def test_log_loss_reindexed_binary_labels():
    # Regression: labels {1,2} on a 2-column model use the dense remap.
    t = Table(
        {
            "label": np.array([1.0, 2.0]),
            "prediction": np.array([1.0, 2.0]),
            "probability": np.array([[0.9, 0.1], [0.2, 0.8]]),
        }
    )
    out = ComputePerInstanceStatistics(labelCol="label").transform(t)
    np.testing.assert_allclose(out["log_loss"], [-np.log(0.9), -np.log(0.8)])
    stats = ComputeModelStatistics(labelCol="label").transform(t)
    assert "AUC" in stats.columns
