"""Image pipeline (reference ``opencv/``/``image/`` suites — SURVEY.md §2.5)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.image import (
    ImageFeaturizer,
    ImageSetAugmenter,
    ImageTransformer,
    UnrollImage,
    roll_image,
    unroll_image,
)


@pytest.fixture()
def image_table(rng):
    images = np.empty(3, dtype=object)
    for i in range(3):
        images[i] = rng.integers(0, 256, size=(20, 24, 3), dtype=np.uint8)
    return Table({"id": np.arange(3), "image": images})


def test_resize_crop(image_table):
    t = (
        ImageTransformer(inputCol="image", outputCol="out")
        .resize(10, 12)
        .crop(2, 1, 8, 8)
        .transform(image_table)
    )
    assert t["out"][0].shape == (8, 8, 3)
    assert t["out"][0].dtype == np.uint8


def test_flip_matches_numpy(image_table):
    out = (
        ImageTransformer(inputCol="image", outputCol="out")
        .flip(1)
        .transform(image_table)
    )
    np.testing.assert_array_equal(out["out"][0], image_table["image"][0][:, ::-1, :])
    out = (
        ImageTransformer(inputCol="image", outputCol="out")
        .flip(0)
        .transform(image_table)
    )
    np.testing.assert_array_equal(out["out"][0], image_table["image"][0][::-1, :, :])


def test_gray_threshold(image_table):
    out = (
        ImageTransformer(inputCol="image", outputCol="out")
        .color_format("gray")
        .threshold(127.0)
        .transform(image_table)
    )
    img = out["out"][0]
    assert img.shape == (20, 24, 1)
    assert set(np.unique(img)) <= {0, 255}


def test_blur_constant_image():
    images = np.empty(1, dtype=object)
    images[0] = np.full((8, 8, 3), 100, dtype=np.uint8)
    t = Table({"image": images})
    out = (
        ImageTransformer(inputCol="image", outputCol="out")
        .blur(3, 3)
        .transform(t)
    )
    # Box blur of a constant image keeps the interior constant.
    np.testing.assert_array_equal(out["out"][0][2:-2, 2:-2], 100)


def test_gaussian_kernel_smooths(rng):
    images = np.empty(1, dtype=object)
    img = np.zeros((9, 9, 1), dtype=np.uint8)
    img[4, 4, 0] = 255
    images[0] = img
    t = Table({"image": images})
    out = (
        ImageTransformer(inputCol="image", outputCol="out", toFloat=True)
        .gaussian_kernel(5, 1.0)
        .transform(t)
    )
    res = out["out"][0][..., 0]
    assert res[4, 4] == res.max() and res[4, 4] < 255
    assert res[2, 4] > 0


def test_mixed_shapes_grouped(rng):
    images = np.empty(4, dtype=object)
    images[0] = rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)
    images[1] = rng.integers(0, 255, (20, 10, 3), dtype=np.uint8)
    images[2] = rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)
    images[3] = rng.integers(0, 255, (20, 10, 3), dtype=np.uint8)
    t = Table({"image": images})
    out = ImageTransformer(inputCol="image", outputCol="out").resize(8, 8).transform(t)
    assert all(im.shape == (8, 8, 3) for im in out["out"])


def test_augmenter(image_table):
    out = ImageSetAugmenter(inputCol="image", outputCol="image").transform(image_table)
    assert out.num_rows == 6
    np.testing.assert_array_equal(out["image"][3], image_table["image"][0][:, ::-1, :])


def test_unroll_roll_roundtrip(image_table):
    out = UnrollImage(inputCol="image", outputCol="vec").transform(image_table)
    vec = out["vec"]
    assert vec.shape == (3, 20 * 24 * 3)
    rolled = roll_image(vec[0], 20, 24, 3)
    np.testing.assert_array_equal(rolled, image_table["image"][0].astype(np.float64))
    # Single-image helper agrees with the column path.
    np.testing.assert_array_equal(unroll_image(image_table["image"][0]), vec[0])


def test_image_featurizer(image_table):
    from mmlspark_tpu.models import init_resnet

    params = init_resnet(variant="resnet18", num_classes=6, small_inputs=True)
    feat = ImageFeaturizer(
        inputCol="image",
        outputCol="features",
        modelParams=params,
        inputHeight=32,
        inputWidth=32,
        batchSize=4,
    )
    out = feat.transform(image_table)
    assert out["features"].shape == (3, 512)
    assert np.isfinite(out["features"]).all()
    # Headful: cut=0 emits class scores.
    logits = feat.copy({"cutOutputLayers": 0}).transform(image_table)
    assert logits["features"].shape == (3, 6)


def test_read_images(tmp_path, rng):
    from PIL import Image

    from mmlspark_tpu.io import read_binary_files, read_images

    for i in range(3):
        arr = rng.integers(0, 255, (10, 12, 3), dtype=np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img_{i}.png")
    (tmp_path / "notes.txt").write_text("not an image")

    files = read_binary_files(str(tmp_path))
    assert files.num_rows == 4
    imgs = read_images(str(tmp_path), pattern="*.png")
    assert imgs.num_rows == 3
    assert imgs["image"][0].shape == (10, 12, 3)
    # Undecodable files are dropped (reference emits null images).
    all_files = read_images(str(tmp_path))
    assert all_files.num_rows == 3


def test_read_zip(tmp_path):
    import zipfile

    with zipfile.ZipFile(tmp_path / "archive.zip", "w") as zf:
        zf.writestr("a.txt", "alpha")
        zf.writestr("sub/b.txt", "beta")
    from mmlspark_tpu.io import read_binary_files

    t = read_binary_files(str(tmp_path))
    assert t.num_rows == 2
    assert any(p.endswith("!a.txt") for p in t["path"])
    assert b"beta" in list(t["bytes"])
