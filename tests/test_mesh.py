"""Mesh/topology tests — run on the 8-virtual-device CPU platform."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.parallel.mesh import (
    MeshConfig,
    get_topology,
    make_mesh,
    pad_to_multiple,
)


def test_topology_discovery():
    topo = get_topology()
    assert topo.num_devices == 8
    assert topo.platform == "cpu"


def test_default_mesh_all_data():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8
    assert mesh.shape["model"] == 1


def test_mesh_config_resolution():
    cfg = MeshConfig(model=2)
    sizes = cfg.resolve(8)
    assert sizes["data"] == 4 and sizes["model"] == 2
    with pytest.raises(ValueError):
        MeshConfig(model=3).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(data=3, model=2).resolve(8)


def test_psum_over_mesh():
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh()
    x = jnp.arange(8.0)

    def f(x):
        return jax.lax.psum(x, "data")

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P())
    )(x)
    assert float(out[0]) == 28.0


def test_pad_to_multiple():
    assert pad_to_multiple(10, 8) == (16, 6)
    assert pad_to_multiple(16, 8) == (16, 0)
