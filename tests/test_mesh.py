"""Mesh/topology tests — run on the 8-virtual-device CPU platform."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.parallel.mesh import (
    MeshConfig,
    get_topology,
    make_mesh,
    pad_to_multiple,
)


def test_topology_discovery():
    topo = get_topology()
    assert topo.num_devices == 8
    assert topo.platform == "cpu"


def test_default_mesh_all_data():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8
    assert mesh.shape["model"] == 1


def test_mesh_config_resolution():
    cfg = MeshConfig(model=2)
    sizes = cfg.resolve(8)
    assert sizes["data"] == 4 and sizes["model"] == 2
    with pytest.raises(ValueError):
        MeshConfig(model=3).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(data=3, model=2).resolve(8)


def test_psum_over_mesh():
    from jax.sharding import PartitionSpec as P

    from mmlspark_tpu.ops.shmap import shard_map

    mesh = make_mesh()
    x = jnp.arange(8.0)

    def f(x):
        return jax.lax.psum(x, "data")

    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P())
    )(x)
    assert float(out[0]) == 28.0


def test_pad_to_multiple():
    assert pad_to_multiple(10, 8) == (16, 6)
    assert pad_to_multiple(16, 8) == (16, 0)


class TestDistributedBootstrap:
    def test_executor_keyed_numbering(self):
        from mmlspark_tpu.parallel.mesh import distributed_init

        # single-executor: no process group to form, returns local topology
        topo = distributed_init(
            executor_ids=["exec-1"], local_executor_id="exec-1"
        )
        assert topo.num_devices >= 1
        # multi-executor derivation without a coordinator must fail loudly,
        # not silently run single-host
        with pytest.raises(ValueError, match="coordinator_address"):
            distributed_init(
                executor_ids=["exec-3", "exec-1", "exec-2"],
                local_executor_id="exec-2",
            )

    def test_executor_keyed_validation(self):
        from mmlspark_tpu.parallel.mesh import distributed_init

        with pytest.raises(ValueError, match="local_executor_id"):
            distributed_init(executor_ids=["a", "b"])
        with pytest.raises(ValueError, match="not in executor_ids"):
            distributed_init(executor_ids=["a", "b"], local_executor_id="c")

    def test_partition_assignment(self, mesh8):
        from mmlspark_tpu.parallel.mesh import partition_assignment

        assign = partition_assignment(16, mesh8)
        assert len(assign) == 16
        data_coords = [c[0] for c in assign.values()]
        # round-robin covers every data slice exactly twice
        assert sorted(data_coords) == sorted(list(range(8)) * 2)

    def test_partition_assignment_underfull_raises(self, mesh8):
        from mmlspark_tpu.parallel.mesh import partition_assignment

        with pytest.raises(ValueError, match="empty mesh slices"):
            partition_assignment(4, mesh8)


class TestModelAxis:
    def _mesh42(self):
        from mmlspark_tpu.parallel.mesh import MeshConfig, make_mesh

        return make_mesh(MeshConfig(data=4, model=2))

    def test_feature_parallel_gbdt_matches_serial(self):
        from mmlspark_tpu.lightgbm.binning import bin_dataset
        from mmlspark_tpu.lightgbm.train import TrainOptions, train

        rng = np.random.default_rng(0)
        X = rng.normal(size=(256, 8))  # 8 features over model=2
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
        bins, mapper = bin_dataset(X, max_bin=31)
        opts = TrainOptions(objective="binary", num_iterations=5, num_leaves=7, max_bin=31)
        r_serial = train(bins, y, opts, mapper=mapper)
        r_fp = train(bins, y, opts, mapper=mapper, mesh=self._mesh42())
        np.testing.assert_array_equal(
            r_serial.booster.split_feature, r_fp.booster.split_feature
        )
        np.testing.assert_allclose(
            r_serial.booster.leaf_values, r_fp.booster.leaf_values, rtol=1e-5, atol=1e-6
        )

    def test_dnn_tensor_parallel_matches_replicated(self):
        from mmlspark_tpu.dnn import DNNModel
        from mmlspark_tpu.parallel.mesh import MeshConfig

        rng = np.random.default_rng(1)
        w1 = rng.normal(size=(6, 16)).astype(np.float32)
        w2 = rng.normal(size=(16, 3)).astype(np.float32)

        def mlp(params, inputs):
            import jax.numpy as jnp

            h = jnp.maximum(inputs["x"] @ params["w1"], 0)
            return {"y": h @ params["w2"]}

        X = rng.normal(size=(16, 6)).astype(np.float64)
        t = Table({"f": X})
        base = dict(
            applyFn=mlp, modelParams={"w1": w1, "w2": w2},
            feedDict={"x": "f"}, fetchDict={"out": "y"}, batchSize=8,
        )
        plain = DNNModel(**base).transform(t)
        tp = DNNModel(
            **base,
            shardOverMesh=True,
            meshConfig=MeshConfig(data=4, model=2),
            # w1 sharded over its output dim, w2 over its input dim — the
            # classic column-then-row TP split of an MLP
            paramShardings={"w1": 1, "w2": 0},
        ).transform(t)
        np.testing.assert_allclose(
            plain.column("out"), tp.column("out"), rtol=1e-4, atol=1e-5
        )
