"""Tests for the perf-observability plane: DeviceProfiler, event-log
rotation, fit-scale buckets, the SLO fold, and the history render."""

import json
import os
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu import observability as obs
from mmlspark_tpu.observability.events import EventLogSink
from mmlspark_tpu.observability.history import main as history_main
from mmlspark_tpu.observability.history import render_report
from mmlspark_tpu.observability.profiler import (
    DeviceProfiler,
    device_peaks,
    get_profiler,
)
from mmlspark_tpu.observability.registry import (
    DEFAULT_BUCKETS,
    FIT_BUCKETS,
    MetricsRegistry,
)
from mmlspark_tpu.observability.slo import SLOReport, SLOTargets


def _fresh_profiler():
    bus = obs.EventBus()
    seen = []
    bus.add_listener(seen.append)
    prof = DeviceProfiler(registry=MetricsRegistry(), bus=bus)
    return prof, seen


class TestDeviceProfiler:
    def test_compile_then_execute_event_ordering(self):
        prof, seen = _fresh_profiler()
        fn = prof.wrap(jax.jit(lambda x: x * 2.0), name="double")
        x = jnp.ones((8, 8), jnp.float32)
        fn(x)
        fn(x)
        kinds = [type(e).__name__ for e in seen]
        # first call compiles (and executes); second is a warm execution
        assert kinds == [
            "ProfileCompiled", "ProfileExecuted", "ProfileExecuted",
        ], kinds
        assert seen[0].name == "double"
        assert seen[0].seconds > 0
        p = prof.snapshot()["functions"]["double"]
        assert p["compiles"] == 1
        assert p["executions"] == 2
        assert p["cache_hits"] == 1

    def test_new_shape_books_a_second_compile(self):
        prof, seen = _fresh_profiler()
        fn = prof.wrap(jax.jit(lambda x: x + 1.0), name="inc")
        fn(jnp.ones((4,), jnp.float32))
        fn(jnp.ones((8,), jnp.float32))  # new shape -> retrace
        kinds = [type(e).__name__ for e in seen]
        assert kinds.count("ProfileCompiled") == 2, kinds

    def test_cost_analysis_folds_flops_and_bytes(self):
        prof, _ = _fresh_profiler()
        fn = prof.wrap(jax.jit(lambda a, b: a @ b), name="matmul")
        a = jnp.ones((32, 32), jnp.float32)
        fn(a, a)
        p = prof.snapshot()["functions"]["matmul"]
        # XLA's estimate for one execution of the compiled program
        assert p["flops"] > 0
        assert p["bytes_accessed"] > 0
        row = prof.roofline()[0]
        assert row["name"] == "matmul"
        assert row["achieved_flops_per_s"] > 0
        assert row["bound"] in ("compute", "memory")

    def test_memory_stats_absent_on_cpu_backend(self):
        prof, _ = _fresh_profiler()
        # CPU devices return None from memory_stats(): the sample must be
        # safe, empty, and set no per-device gauge series
        sample = prof.sample_memory()
        assert sample == {}
        gauge = prof.registry.get("profiler_hbm_bytes_in_use")
        assert gauge is not None and not gauge._children

    def test_disabled_profiler_is_identity(self):
        prof = DeviceProfiler(registry=MetricsRegistry(), bus=obs.EventBus(),
                              enabled=False)
        fn = jax.jit(lambda x: x)
        assert prof.wrap(fn) is fn
        assert prof.wrap_host(fn, "h") is fn
        assert not prof.active

    def test_transfer_counter(self):
        prof, _ = _fresh_profiler()
        prof.note_transfer(1024, "h2d", name="up")
        prof.note_transfer(256, "d2h", name="up")
        prof.note_transfer(-5, "h2d")  # ignored
        c = prof.registry.get("profiler_transfer_bytes_total")
        assert c.labels(direction="h2d").value == 1024
        assert c.labels(direction="d2h").value == 256
        assert prof.snapshot()["functions"]["up"]["transfer_bytes"] == 1280

    def test_merge_folds_external_totals(self):
        prof, _ = _fresh_profiler()
        prof.merge("procfit.allreduce[m0]", executions=10, device_seconds=0.5)
        prof.merge("procfit.allreduce[m0]", executions=5, device_seconds=0.25)
        p = prof.snapshot()["functions"]["procfit.allreduce[m0]"]
        assert p["executions"] == 15
        assert p["device_seconds"] == pytest.approx(0.75)

    def test_measure_and_wrap_host(self):
        prof, seen = _fresh_profiler()
        with prof.measure("window"):
            pass
        timed = prof.wrap_host(lambda v: v + 1, "hostfn")
        assert timed(41) == 42
        fns = prof.snapshot()["functions"]
        assert fns["window"]["executions"] == 1
        assert fns["hostfn"]["executions"] == 1
        assert all(type(e).__name__ == "ProfileExecuted" for e in seen)

    def test_peak_env_overrides(self, monkeypatch):
        monkeypatch.setenv("MMLSPARK_TPU_PEAK_FLOPS", "1e12")
        monkeypatch.setenv("MMLSPARK_TPU_PEAK_HBM_BYTES", "1e11")
        assert device_peaks() == (1e12, 1e11)

    def test_global_profiler_env_resync(self, monkeypatch):
        monkeypatch.setenv("MMLSPARK_TPU_PROFILE", "1")
        assert get_profiler().active
        monkeypatch.setenv("MMLSPARK_TPU_PROFILE", "0")
        assert not get_profiler().active

    def test_compile_metrics_use_fit_buckets(self):
        prof, _ = _fresh_profiler()
        prof.note_compile("slow", 120.0)  # a 2-minute XLA compile
        h = prof.registry.get("profiler_compile_seconds")
        assert h.buckets == FIT_BUCKETS
        assert h.percentile(0.99) > 10.0  # not clamped at DEFAULT's top


class TestEventLogRotation:
    def _events(self, n):
        return [obs.ProfileExecuted(name=f"fn{i}", seconds=float(i))
                for i in range(n)]

    def test_rotation_and_ordered_replay(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        sink = EventLogSink(path, max_bytes=150)
        events = self._events(12)
        for e in events:
            sink(e)
        sink.close()
        segs = obs.log_segments(path)
        assert len(segs) > 1, "log never rotated"
        assert segs[-1] == path  # live file last
        # every rotated segment respects the bound
        for seg in segs[:-1]:
            assert os.path.getsize(seg) <= 150
        replayed = obs.replay(path)
        assert [e.name for e in replayed] == [e.name for e in events]

    def test_oversized_event_does_not_rotate_forever(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        sink = EventLogSink(path, max_bytes=10)  # smaller than any record
        for e in self._events(3):
            sink(e)
        sink.close()
        # each event rotates the previous one out; all three survive
        assert len(obs.replay(path)) == 3

    def test_max_bytes_from_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MMLSPARK_TPU_EVENT_LOG_MAX_BYTES", "123")
        sink = EventLogSink(str(tmp_path / "ev.jsonl"))
        assert sink.max_bytes == 123
        sink.close()
        monkeypatch.setenv("MMLSPARK_TPU_EVENT_LOG_MAX_BYTES", "0")
        sink = EventLogSink(str(tmp_path / "ev2.jsonl"))
        assert sink.max_bytes is None  # 0 = unbounded
        sink.close()

    def test_unrelated_siblings_are_not_segments(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        (tmp_path / "ev.jsonl.bak").write_text("not a segment\n")
        (tmp_path / "ev.jsonl.2") .write_text("")
        EventLogSink(path).close()
        segs = obs.log_segments(path)
        assert str(tmp_path / "ev.jsonl.bak") not in segs
        assert segs == [str(tmp_path / "ev.jsonl.2"), path]

    def test_reopened_sink_continues_sequence(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        sink = EventLogSink(path, max_bytes=150)
        for e in self._events(8):
            sink(e)
        sink.close()
        before = len(obs.log_segments(path))
        sink = EventLogSink(path, max_bytes=150)  # a restarted process
        for e in self._events(8):
            sink(e)
        sink.close()
        assert len(obs.log_segments(path)) > before
        assert len(obs.replay(path)) == 16


class TestFitBuckets:
    def test_fit_scale_percentile_is_not_clamped(self):
        reg = MetricsRegistry()
        h = reg.histogram("fit_seconds", buckets=FIT_BUCKETS)
        for v in (45.0, 90.0, 200.0, 400.0):
            h.observe(v)
        assert h.percentile(0.99) > 10.0
        # the old DEFAULT_BUCKETS behavior this fixes: everything in +Inf
        d = reg.histogram("fit_seconds_default")
        for v in (45.0, 90.0, 200.0, 400.0):
            d.observe(v)
        assert d.percentile(0.99) == DEFAULT_BUCKETS[-1]

    def test_fit_buckets_are_sorted_and_extend_default(self):
        assert list(FIT_BUCKETS) == sorted(FIT_BUCKETS)
        assert FIT_BUCKETS[-1] > DEFAULT_BUCKETS[-1]


class TestSLOReport:
    def _served(self, n, latency=0.002, status=200):
        return [obs.RequestServed(rid=f"r{i}", status=status, latency=latency)
                for i in range(n)]

    def test_fold_determinism_under_seeded_chaos(self, monkeypatch):
        """The report must equal the registry fold exactly — the PR 3
        summary-equality posture — even with unrelated seeded-chaos
        events (task kills, retries) interleaved in the stream."""
        monkeypatch.setenv("MMLSPARK_TPU_FAULT_SEED", "0")
        from mmlspark_tpu import runtime

        plan = runtime.FaultPlan(seed=0).kill_task(1)
        pol = runtime.SchedulerPolicy(max_workers=2, backoff_base=0.01,
                                      faults=plan)
        bus = obs.get_bus()
        chaos = []
        bus.add_listener(chaos.append)
        try:
            out = runtime.run_partitioned(lambda x: x * 2, [1, 2, 3], pol)
        finally:
            bus.remove_listener(chaos.append)
        assert out == [2, 4, 6]
        assert any(isinstance(e, obs.TaskFailed) for e in chaos)

        reg = MetricsRegistry()
        reg.counter("serving_requests_total").inc(6)
        reg.counter("serving_shed_total").inc(2)
        q = reg.histogram("serving_queue_wait_seconds")
        a = reg.histogram("serving_apply_latency_seconds")
        for v in (0.001, 0.002, 0.003):
            q.observe(v)
            a.observe(v)
        events = chaos + self._served(5) + self._served(1, status=503)

        report = SLOReport.fold(reg, events=events)
        summary = reg.summary()
        # exact equality between the report and the registry fold
        assert report.requests == summary["serving_requests_total"]
        assert report.shed == summary["serving_shed_total"]
        assert report.stages["queue"] == summary["serving_queue_wait_seconds"]
        assert report.stages["apply"] == summary["serving_apply_latency_seconds"]
        assert report.e2e["count"] == 6  # chaos events never count
        assert report.errors == 1
        # folding the summary DICT (the history server's path) is
        # byte-identical to folding the registry object
        assert SLOReport.fold(summary, events=events).to_dict() == \
            report.to_dict()
        # and the fold is a pure function of its inputs
        assert SLOReport.fold(reg, events=events).to_json() == \
            report.to_json()

    def test_shed_pct_and_error_budget(self):
        reg = MetricsRegistry()
        reg.counter("serving_requests_total").inc(98)
        reg.counter("serving_shed_total").inc(2)
        events = self._served(97) + self._served(1, status=500)
        report = SLOReport.fold(reg, events=events)
        assert report.shed_pct == pytest.approx(2.0)
        assert report.error_rate == pytest.approx(1 / 98)
        # 3 nines = 0.1% budget; 1/98 errors blows it
        assert report.error_budget_consumed > 1.0
        assert not report.ok()

    def test_event_only_fold(self):
        report = SLOReport.fold(None, events=self._served(4, latency=0.01))
        assert report.requests == 4
        assert report.e2e["p50"] == pytest.approx(0.01)

    def test_renderers(self):
        report = SLOReport.fold(None, events=self._served(3),
                                targets=SLOTargets(p50_ms=1.0))
        md = report.to_markdown()
        assert "| apply p50 |" in md and "| stage |" in md
        parsed = json.loads(report.to_json())
        assert parsed["requests"] == 3
        assert "stages" in parsed and "targets" in parsed


class TestTrainProfilerWiring:
    @pytest.fixture()
    def data(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(300, 6)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        return X, y

    def _fit(self, X, y, **kw):
        from mmlspark_tpu.lightgbm.train import TrainOptions, train

        return train(
            X, y, TrainOptions(objective="binary", num_iterations=3,
                               num_leaves=7), **kw,
        )

    def test_loop_path_books_per_iteration_windows(self, data):
        X, y = data
        prof = get_profiler().enable()
        prof.clear()
        try:
            # iteration_hook forces the loop path
            self._fit(X, y, iteration_hook=lambda it, tree: None)
            p = prof.snapshot()["functions"]["gbdt.step"]
            assert p["executions"] == 3
            assert p["compiles"] >= 1
            assert p["device_seconds"] > 0
        finally:
            prof.disable()
            prof.clear()

    def test_scan_path_books_segment_windows(self, data):
        X, y = data
        prof = get_profiler().enable()
        prof.clear()
        try:
            self._fit(X, y)
            p = prof.snapshot()["functions"]["gbdt.scan"]
            assert p["executions"] >= 1
            assert p["device_seconds"] > 0
        finally:
            prof.disable()
            prof.clear()

    def test_disabled_profiler_books_nothing(self, data):
        X, y = data
        prof = get_profiler()
        prof.disable()
        prof.clear()
        self._fit(X, y, iteration_hook=lambda it, tree: None)
        assert "gbdt.step" not in prof.snapshot()["functions"]


class TestServingProfilerWiring:
    def test_serving_apply_booked(self):
        from mmlspark_tpu.core.pipeline import Model
        from mmlspark_tpu.data.table import Table
        from mmlspark_tpu.serving import ServingServer

        class _Echo(Model):
            def transform(self, t):
                return Table({
                    "prediction": np.asarray(t.column("input"), np.float64)
                })

        prof = get_profiler().enable()
        prof.clear()
        try:
            with ServingServer(_Echo(), max_latency_ms=1.0) as srv:
                base = srv.info.url.rstrip("/")
                req = urllib.request.Request(
                    base, data=json.dumps({"input": 1.0}).encode(),
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=10).read()
            p = prof.snapshot()["functions"]["serving.apply"]
            assert p["executions"] >= 1
            assert p["transfer_bytes"] > 0
        finally:
            prof.disable()
            prof.clear()


class TestHistoryReport:
    def _events(self):
        return [
            obs.StageStarted(job_id=0, stage_id=0, name="Binning", t=1.0),
            obs.StageCompleted(job_id=0, stage_id=0, name="Binning",
                               duration=0.5, t=1.5),
            obs.StageStarted(job_id=0, stage_id=1, name="Boost", t=1.5),
            obs.StageCompleted(job_id=0, stage_id=1, name="Boost",
                               duration=1.0, status="ValueError", t=2.5),
            obs.TaskFailed(job_id=0, task_id=1, reason="executor_death",
                           worker=0, duration=0.1, attempt=0),
            obs.TaskFailed(job_id=0, task_id=1, reason="timeout", worker=1,
                           duration=0.2, attempt=1, speculative=True),
            obs.RequestServed(rid="r1", status=200, latency=0.002),
            obs.RequestShed(reason="queue_full", queue_depth=9),
            obs.BreakerTripped(breaker="apply", failures=3, window_s=30.0),
            obs.ModelSwapped(name="m", version=2, server="s1"),
            obs.ProfileCompiled(name="gbdt.step", seconds=0.4, flops=1e9,
                                bytes_accessed=1e8),
            obs.ProfileExecuted(name="gbdt.step", seconds=0.01),
            obs.StreamEpochCommitted(query="q", epoch=0, rows=100),
        ]

    def test_render_contains_all_sections(self):
        doc = render_report(self._events(), title="t")
        for needle in (
            "Stage timeline", "Task attempts", "Serving SLO",
            "Profiler roofline", "Resilience", "Streaming",
            "executor_death", "gbdt.step", "apply p50",
            "bar failed",  # the failed Boost stage renders red
        ):
            assert needle in doc, f"report missing {needle!r}"
        # self-contained: no external refs
        assert "http://" not in doc and "https://" not in doc

    def test_render_escapes_html(self):
        evs = [obs.StageStarted(job_id=0, stage_id=0,
                                name="<script>alert(1)</script>")]
        doc = render_report(evs)
        assert "<script>alert(1)" not in doc
        assert "&lt;script&gt;" in doc

    def test_cli_writes_report(self, tmp_path, capsys):
        log = tmp_path / "ev.jsonl"
        sink = EventLogSink(str(log))
        for e in self._events():
            sink(e)
        sink.close()
        metrics = tmp_path / "metrics.json"
        metrics.write_text(json.dumps({"serving_requests_total": 1.0}))
        out = tmp_path / "report.html"
        rc = history_main([str(log), "-o", str(out),
                           "--metrics", str(metrics), "--title", "ci run"])
        assert rc == 0
        assert capsys.readouterr().out.strip() == str(out)
        doc = out.read_text()
        assert "ci run" in doc and "Stage timeline" in doc

    def test_cli_default_output_path(self, tmp_path, capsys):
        log = tmp_path / "ev.jsonl"
        sink = EventLogSink(str(log))
        sink(obs.RequestServed(rid="r", status=200, latency=0.001))
        sink.close()
        assert history_main([str(log)]) == 0
        assert (tmp_path / "ev.jsonl.html").exists()
