"""serving/ tests — real servers + real clients, matching the reference
``HTTPv2Suite``/``DistributedHTTPSuite`` approach (latency + fault paths)."""

import json
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.serving import DistributedServingServer, ServingServer


class _Doubler(Transformer):
    def transform(self, table):
        x = np.asarray(table.column("input"), dtype=np.float64)
        return table.with_column("prediction", x * 2)


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class TestServingServer:
    def test_single_request(self):
        with ServingServer(_Doubler(), max_latency_ms=1.0) as srv:
            status, out = _post(srv.info.url, {"input": 21.0})
            assert status == 200 and out["prediction"] == 42.0

    def test_vector_payloads(self):
        class VecModel(Transformer):
            def transform(self, table):
                X = np.asarray(table.column("input"), dtype=np.float64)
                return table.with_column("prediction", X.sum(axis=1))

        with ServingServer(VecModel()) as srv:
            status, out = _post(srv.info.url, {"input": [1.0, 2.0, 3.0]})
            assert status == 200 and out["prediction"] == 6.0

    def test_concurrent_batching(self):
        with ServingServer(_Doubler(), max_batch_size=16, max_latency_ms=5.0) as srv:
            with ThreadPoolExecutor(max_workers=16) as pool:
                results = list(pool.map(
                    lambda i: _post(srv.info.url, {"input": float(i)}),
                    range(32),
                ))
            assert all(s == 200 for s, _ in results)
            assert [o["prediction"] for _, o in results] == [2.0 * i for i in range(32)]

    def test_model_error_returns_500(self):
        class Exploder(Transformer):
            def transform(self, table):
                raise RuntimeError("boom")

        with ServingServer(Exploder()) as srv:
            try:
                status, _ = _post(srv.info.url, {"input": 1.0})
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 500

    def test_invalid_json_400(self):
        with ServingServer(_Doubler()) as srv:
            req = urllib.request.Request(
                srv.info.url, data=b"{not json", method="POST")
            try:
                urllib.request.urlopen(req, timeout=5)
                status = 200
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 400

    def test_latency_single_row(self):
        # p50 well under the 5ms BASELINE target for a trivial model on CPU;
        # the real-chip number is measured by bench configs.
        with ServingServer(_Doubler(), max_latency_ms=0.5) as srv:
            _post(srv.info.url, {"input": 1.0})  # warmup
            times = []
            for i in range(30):
                t0 = time.perf_counter()
                _post(srv.info.url, {"input": float(i)})
                times.append(time.perf_counter() - t0)
            p50 = sorted(times)[len(times) // 2]
            assert p50 < 0.05, f"p50 {p50 * 1e3:.1f}ms"


class TestDistributedServing:
    def test_multiple_endpoints(self):
        with DistributedServingServer(_Doubler(), num_servers=3) as srv:
            infos = srv.service_info
            assert len({i.port for i in infos}) == 3
            for info in infos:
                status, out = _post(info.url, {"input": 2.0})
                assert status == 200 and out["prediction"] == 4.0
