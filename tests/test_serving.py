"""serving/ tests — real servers + real clients, matching the reference
``HTTPv2Suite``/``DistributedHTTPSuite`` approach (latency + fault paths)."""

import json
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.serving import (
    DistributedServingServer,
    RegistrationService,
    ServingServer,
)


class _Doubler(Transformer):
    def transform(self, table):
        x = np.asarray(table.column("input"), dtype=np.float64)
        return table.with_column("prediction", x * 2)


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class TestServingServer:
    def test_single_request(self):
        with ServingServer(_Doubler(), max_latency_ms=1.0) as srv:
            status, out = _post(srv.info.url, {"input": 21.0})
            assert status == 200 and out["prediction"] == 42.0

    def test_vector_payloads(self):
        class VecModel(Transformer):
            def transform(self, table):
                X = np.asarray(table.column("input"), dtype=np.float64)
                return table.with_column("prediction", X.sum(axis=1))

        with ServingServer(VecModel()) as srv:
            status, out = _post(srv.info.url, {"input": [1.0, 2.0, 3.0]})
            assert status == 200 and out["prediction"] == 6.0

    def test_concurrent_batching(self):
        with ServingServer(_Doubler(), max_batch_size=16, max_latency_ms=5.0) as srv:
            with ThreadPoolExecutor(max_workers=16) as pool:
                results = list(pool.map(
                    lambda i: _post(srv.info.url, {"input": float(i)}),
                    range(32),
                ))
            assert all(s == 200 for s, _ in results)
            assert [o["prediction"] for _, o in results] == [2.0 * i for i in range(32)]

    def test_model_error_returns_500(self):
        class Exploder(Transformer):
            def transform(self, table):
                raise RuntimeError("boom")

        with ServingServer(Exploder()) as srv:
            try:
                status, _ = _post(srv.info.url, {"input": 1.0})
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 500

    def test_invalid_json_400(self):
        with ServingServer(_Doubler()) as srv:
            req = urllib.request.Request(
                srv.info.url, data=b"{not json", method="POST")
            try:
                urllib.request.urlopen(req, timeout=5)
                status = 200
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 400

    def test_latency_single_row(self):
        # The BASELINE config-5 target: p50 < 5 ms end-to-end through the
        # HTTP edge (measured ~1.8 ms for this model; the real-model device
        # composition is benchmarks/serving_latency.py).
        with ServingServer(_Doubler(), max_latency_ms=0.5) as srv:
            for _ in range(5):
                _post(srv.info.url, {"input": 1.0})  # warmup
            times = []
            for i in range(50):
                t0 = time.perf_counter()
                _post(srv.info.url, {"input": float(i)})
                times.append(time.perf_counter() - t0)
            p50 = sorted(times)[len(times) // 2]
            # sanity bound only: wall-clock through a real socket flakes on
            # loaded CI hosts; the 5 ms target claim is measured and recorded
            # by benchmarks/serving_latency.py + docs/serving_latency.md
            assert p50 < 0.015, f"p50 {p50 * 1e3:.1f}ms"


class TestDistributedServing:
    def test_multiple_endpoints(self):
        with DistributedServingServer(_Doubler(), num_servers=3) as srv:
            infos = srv.service_info
            assert len({i.port for i in infos}) == 3
            for info in infos:
                status, out = _post(info.url, {"input": 2.0})
                assert status == 200 and out["prediction"] == 4.0


class TestFaultTolerance:
    def test_task_retry_rehydration(self):
        """A batch whose evaluation dies is re-enqueued and replayed — the
        client still gets a 200 (``registerPartition`` re-hydration,
        HTTPSourceV2.scala:470-487)."""

        class FlakyOnce(Transformer):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.calls = 0

            def transform(self, table):
                self.calls += 1
                if self.calls == 1:
                    raise RuntimeError("transient task death")
                x = np.asarray(table.column("input"), dtype=np.float64)
                return table.with_column("prediction", x * 2)

        model = FlakyOnce()
        with ServingServer(model, max_retries=2) as srv:
            status, out = _post(srv.info.url, {"input": 5.0})
            assert status == 200 and out["prediction"] == 10.0
            assert model.calls == 2  # first attempt died, replay answered

    def test_retries_exhausted_500(self):
        class AlwaysDies(Transformer):
            def transform(self, table):
                raise RuntimeError("permanent")

        with ServingServer(AlwaysDies(), max_retries=1) as srv:
            try:
                status, _ = _post(srv.info.url, {"input": 1.0})
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 500

    def test_recover_replays_uncommitted_epoch(self):
        """Kill the worker mid-batch; recover() re-hydrates the uncommitted
        epoch and a restarted worker answers it."""
        import threading

        release = threading.Event()
        died = threading.Event()

        class BlocksThenDies(Transformer):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.calls = 0

            def transform(self, table):
                self.calls += 1
                if self.calls == 1:
                    died.set()
                    release.wait(timeout=10)
                    raise SystemExit  # hard worker death mid-epoch
                x = np.asarray(table.column("input"), dtype=np.float64)
                return table.with_column("prediction", x + 1)

        model = BlocksThenDies()
        srv = ServingServer(model, max_retries=0).start()
        try:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=1) as pool:
                fut = pool.submit(_post, srv.info.url, {"input": 41.0}, 15)
                assert died.wait(timeout=5)  # worker is inside the doomed epoch
                release.set()  # let it die
                time.sleep(0.2)
                assert srv.loop.uncommitted_epochs  # epoch never committed
                replayed = srv.loop.recover()
                assert replayed == 1
                srv.loop.start()  # restarted worker
                status, out = fut.result(timeout=10)
                assert status == 200 and out["prediction"] == 42.0
        finally:
            srv.stop()


class TestDistributedV2:
    def test_cross_listener_reply_routing(self):
        """Requests hitting DIFFERENT listeners are answered through the one
        shared loop — reply routing is by request id, not by listener
        (the cross-worker reply HTTPSourceV2.scala:509-533 left
        unimplemented)."""
        calls = []

        class Recorder(Transformer):
            def transform(self, table):
                x = np.asarray(table.column("input"), dtype=np.float64)
                calls.append(len(x))
                return table.with_column("prediction", x * 3)

        with DistributedServingServer(
            Recorder(), num_servers=3, max_batch_size=8, max_latency_ms=50.0
        ) as srv:
            urls = [i.url for i in srv.service_info]
            with ThreadPoolExecutor(max_workers=6) as pool:
                results = list(pool.map(
                    lambda i: _post(urls[i % 3], {"input": float(i)}), range(6)
                ))
            assert all(s == 200 for s, _ in results)
            assert [o["prediction"] for _, o in results] == [3.0 * i for i in range(6)]
        # the shared loop batched across listeners (fewer calls than requests)
        assert sum(calls) == 6 and len(calls) < 6

    def test_registration_service(self):
        with RegistrationService() as reg:
            with DistributedServingServer(
                _Doubler(), num_servers=2, registry_url=reg.info.url
            ) as srv:
                # client-side discovery via the driver service
                with urllib.request.urlopen(reg.info.url + "services", timeout=5) as r:
                    services = json.loads(r.read())
                assert len(services) == 2
                ports = {s["port"] for s in services}
                assert ports == {i.port for i in srv.service_info}
                # discovered endpoints actually answer
                s0 = services[0]
                status, out = _post(f"http://{s0['host']}:{s0['port']}/", {"input": 7.0})
                assert status == 200 and out["prediction"] == 14.0


class TestConcurrentLoad:
    def test_distributed_under_load_with_worker_death(self):
        """The HTTPv2Suite.scala:315-387 shape: concurrent clients hammer
        multiple listeners; one listener dies mid-stream and its clients
        fail over to the surviving endpoints. Every request must succeed
        with the correct answer and the latency distribution stays sane."""
        import threading
        import time as _time

        from benchmarks.serving_latency import concurrent_load_latency

        out = concurrent_load_latency(
            num_servers=3, num_clients=8, reqs_per_client=15, kill_worker=True
        )
        assert out["requests"] == 8 * 15
        assert out["errors"] == 0, out  # failover absorbed the worker death
        assert out["failovers"] >= 1, out  # the death actually happened mid-stream
        assert out["p50_ms"] < 250, out


def test_distributed_base_port_binds_sequential_ports():
    """base_port pins listener ports (the k8s Service contract)."""
    srv = DistributedServingServer(_Doubler(), num_servers=2, base_port=28990)
    with srv:
        ports = [i.port for i in srv.service_info]
        assert ports == [28990, 28991]
        status, out = _post(srv.service_info[1].url, {"input": 4.0})
        assert status == 200 and out["prediction"] == 8.0


class TestSchedulerBackedDispatch:
    """DistributedServingServer routed through mmlspark_tpu.runtime — the
    Spark-cluster posture where micro-batches evaluate on executors the
    driver can lose (and replace) without a client ever seeing it."""

    def _policy(self, **kw):
        from mmlspark_tpu import runtime

        base = dict(max_workers=2, backoff_base=0.01, heartbeat_interval=0.02)
        base.update(kw)
        return runtime.SchedulerPolicy(**base)

    def test_num_executors_routes_batches_through_scheduler(self):
        srv = DistributedServingServer(
            _Doubler(), num_servers=2, num_executors=2, max_latency_ms=1.0
        )
        with srv:
            for i, info in enumerate(srv.service_info):
                status, out = _post(info.url, {"input": float(i)})
                assert status == 200 and out["prediction"] == i * 2.0
        assert srv.scheduler is not None
        assert srv.scheduler.metrics.summary()["tasks_done"] >= 2

    def test_injected_executor_death_absorbed(self):
        """An executor killed mid-batch retries its partition; the client
        still gets 200 with the right answer, and metrics show the death."""
        from mmlspark_tpu import runtime

        plan = runtime.FaultPlan(seed=9).kill_task(0)
        srv = DistributedServingServer(
            _Doubler(), num_servers=1, num_executors=2,
            executor_policy=self._policy(faults=plan), max_latency_ms=1.0,
        )
        with srv:
            status, out = _post(srv.service_info[0].url, {"input": 21.0})
            assert status == 200 and out["prediction"] == 42.0
        assert plan.fired == [("kill", 0, 0)]
        s = srv.scheduler.metrics.summary()
        assert s["failures_executor_death"] == 1 and s["retries_total"] == 1

    def test_ambient_policy_activates_scheduler(self):
        from mmlspark_tpu import runtime

        with runtime.policy(max_workers=2, backoff_base=0.01):
            srv = DistributedServingServer(
                _Doubler(), num_servers=1, max_latency_ms=1.0
            )
        with srv:
            status, out = _post(srv.service_info[0].url, {"input": 3.0})
            assert status == 200 and out["prediction"] == 6.0
        assert srv.scheduler is not None

    def test_batch_split_preserves_request_order(self):
        """A >1-request micro-batch splits across executor tasks; replies
        must route back to the right requester."""
        srv = DistributedServingServer(
            _Doubler(), num_servers=2, num_executors=3,
            max_batch_size=16, max_latency_ms=30.0,
        )
        with srv:
            urls = [i.url for i in srv.service_info]
            with ThreadPoolExecutor(max_workers=8) as ex:
                futs = [
                    ex.submit(_post, urls[k % len(urls)], {"input": float(k)})
                    for k in range(24)
                ]
                results = [f.result() for f in futs]
        for k, (status, out) in enumerate(results):
            assert status == 200 and out["prediction"] == k * 2.0


class TestRegistrationLeases:
    """Registration-service TTL: registrations are leases refreshed by
    replica heartbeats; a silent crash drops out of discovery."""

    class _Clock:
        def __init__(self):
            self.t = 1000.0

        def now(self):
            return self.t

    def _svc(self, name, port=9001):
        from mmlspark_tpu.serving.server import ServiceInfo

        return ServiceInfo(name=name, host="127.0.0.1", port=port)

    def test_lease_expires_without_heartbeat(self):
        clock = self._Clock()
        reg = RegistrationService(ttl_s=10.0, clock=clock.now)
        reg.register(self._svc("replica-0"))
        reg.register(self._svc("replica-1", port=9002))
        assert {s.name for s in reg.services} == {"replica-0", "replica-1"}
        # replica-1 keeps heartbeating; replica-0 goes silent
        clock.t += 8.0
        assert reg.heartbeat("replica-1")
        clock.t += 4.0  # replica-0 is now 12 s stale, replica-1 only 4 s
        assert {s.name for s in reg.services} == {"replica-1"}

    def test_heartbeat_refreshes_lease_indefinitely(self):
        clock = self._Clock()
        reg = RegistrationService(ttl_s=10.0, clock=clock.now)
        reg.register(self._svc("replica-0"))
        for _ in range(5):
            clock.t += 9.0
            assert reg.heartbeat("replica-0")
        assert {s.name for s in reg.services} == {"replica-0"}

    def test_heartbeat_after_expiry_demands_reregistration(self):
        clock = self._Clock()
        reg = RegistrationService(ttl_s=10.0, clock=clock.now)
        reg.register(self._svc("replica-0"))
        clock.t += 11.0
        # the lease lapsed: heartbeat is refused, replica must re-register
        assert not reg.heartbeat("replica-0")
        assert reg.services == []
        reg.register(self._svc("replica-0"))
        assert {s.name for s in reg.services} == {"replica-0"}

    def test_no_ttl_means_everlasting_registrations(self):
        clock = self._Clock()
        reg = RegistrationService(clock=clock.now)  # ttl_s=None
        reg.register(self._svc("replica-0"))
        clock.t += 1e9
        assert {s.name for s in reg.services} == {"replica-0"}

    def test_http_heartbeat_endpoint(self):
        with RegistrationService(ttl_s=30.0) as reg:
            reg.register(self._svc("replica-0"))
            req = urllib.request.Request(
                reg.info.url + "heartbeat",
                data=json.dumps({"name": "replica-0"}).encode(),
                method="POST", headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                assert r.status == 200
            # unknown replica -> 404, the re-register signal
            req = urllib.request.Request(
                reg.info.url + "heartbeat",
                data=json.dumps({"name": "ghost"}).encode(),
                method="POST", headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 404

    def test_distributed_server_heartbeats_keep_lease_alive(self):
        with RegistrationService(ttl_s=1.0) as reg:
            with DistributedServingServer(
                _Doubler(), num_servers=2, registry_url=reg.info.url,
                registry_heartbeat_s=0.2,
            ) as srv:
                deadline = time.monotonic() + 2.5
                while time.monotonic() < deadline:
                    # the replicas outlive several TTL windows because the
                    # heartbeat thread keeps refreshing the lease
                    assert len(reg.services) == 2
                    time.sleep(0.25)
            # servers stopped -> heartbeats stop -> leases lapse
            deadline = time.monotonic() + 5.0
            while reg.services and time.monotonic() < deadline:
                time.sleep(0.1)
            assert reg.services == []
