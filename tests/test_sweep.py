"""Many-models sweep plane tests (``mmlspark_tpu.sweep``): shape-
bucketing rules, batched-vs-sequential parity, the ``TrainValidSweep``
estimator (selection + ModelStore commit), golden selection parity with
the thread-pool ``TuneHyperparameters`` baseline, compile amortization
(the bench regression guard), and the gang/chaos path — a SIGKILL'd
sweep worker must not change the selected model."""

import os

import numpy as np
import pytest

from mmlspark_tpu.automl.hyperparam import (
    DefaultHyperparams,
    DiscreteHyperParam,
    DoubleRangeHyperParam,
    GridSpace,
)
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import LightGBMClassifier, LightGBMRegressor
from mmlspark_tpu.sweep import (
    GBDT_VMAPPED,
    VW_VMAPPED,
    TrainValidSweep,
    bucket_candidates,
    fit_bucket,
)
from mmlspark_tpu.vw import VowpalWabbitClassifier


@pytest.fixture
def clf_table(rng):
    X = rng.normal(size=(240, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return Table({"features": X, "label": y})


class TestBucketing:
    def test_vmapped_params_share_one_bucket(self):
        est = LightGBMClassifier(numIterations=5)
        maps = [
            {"learningRate": 0.05},
            {"learningRate": 0.1, "featureFraction": 0.8},
            {"learningRate": 0.2, "baggingFraction": 0.7, "baggingFreq": 1},
        ]
        buckets = bucket_candidates([(est, m) for m in maps])
        assert len(buckets) == 1
        assert buckets[0].kind == "gbdt"
        assert buckets[0].size == 3
        assert buckets[0].indices == [0, 1, 2]

    def test_static_params_split_buckets(self):
        est = LightGBMClassifier(numIterations=5)
        maps = [
            {"learningRate": 0.1, "numLeaves": 7},
            {"learningRate": 0.2, "numLeaves": 7},
            {"learningRate": 0.1, "numLeaves": 15},
        ]
        buckets = bucket_candidates([(est, m) for m in maps])
        assert sorted(b.size for b in buckets) == [1, 2]
        # the union of indices is exactly the candidate list
        assert sorted(i for b in buckets for i in b.indices) == [0, 1, 2]

    def test_classifier_and_regressor_never_share(self):
        cands = [
            (LightGBMClassifier(numIterations=5), {"learningRate": 0.1}),
            (LightGBMRegressor(numIterations=5), {"learningRate": 0.1}),
        ]
        buckets = bucket_candidates(cands)
        assert len(buckets) == 2

    def test_unbatchable_gbdt_falls_back_to_singletons(self):
        est = LightGBMClassifier(numIterations=5, earlyStoppingRound=2)
        buckets = bucket_candidates(
            [(est, {"learningRate": lr}) for lr in (0.1, 0.2)]
        )
        assert [b.kind for b in buckets] == [None, None]
        assert all(b.size == 1 for b in buckets)

    def test_vw_bucket_and_arg_conflict(self):
        est = VowpalWabbitClassifier(numPasses=2)
        buckets = bucket_candidates(
            [(est, {"learningRate": lr}) for lr in (0.3, 0.6)]
        )
        assert len(buckets) == 1 and buckets[0].kind == "vw"
        # a pass-through flag pinning a vmapped lane breaks batching
        pinned = VowpalWabbitClassifier(
            numPasses=2, passThroughArgs="--learning_rate 0.5"
        )
        buckets = bucket_candidates(
            [(pinned, {"powerT": p}) for p in (0.0, 0.5)]
        )
        assert [b.kind for b in buckets] == [None, None]

    def test_vmapped_name_sets(self):
        est = LightGBMClassifier()
        assert all(est.hasParam(n) for n in GBDT_VMAPPED)
        vw = VowpalWabbitClassifier()
        assert all(vw.hasParam(n) for n in VW_VMAPPED)


class TestBatchedParity:
    def test_gbdt_batched_scores_match_sequential(self, clf_table):
        est = LightGBMClassifier(
            labelCol="label", numIterations=5, numLeaves=7, maxBin=32
        )
        maps = [{"learningRate": lr} for lr in (0.05, 0.1, 0.2)]
        (bucket,) = bucket_candidates([(est, m) for m in maps])
        mask = np.zeros(clf_table.num_rows, dtype=bool)
        mask[: clf_table.num_rows * 3 // 4] = True
        train, valid = clf_table.filter(mask), clf_table.filter(~mask)
        scored = fit_bucket(bucket, train, valid, "label", "AUC")
        from mmlspark_tpu.automl.tune import _evaluate

        for m, (metric, _model) in zip(maps, scored):
            ref = est.copy(m).fit(train)
            ref_metric = _evaluate(ref.transform(valid), "label", "AUC")
            assert np.isclose(metric, ref_metric, rtol=1e-5), (m, metric)

    def test_vw_batched_scores_match_sequential(self, clf_table, monkeypatch):
        # the vmapped core is single-device; the sequential reference must
        # run mesh-free too (row sharding reorders SGD accumulation)
        from mmlspark_tpu.vw.base import VowpalWabbitBase

        monkeypatch.setattr(
            VowpalWabbitBase, "_select_mesh", lambda self: None
        )
        est = VowpalWabbitClassifier(labelCol="label", numPasses=2)
        maps = [
            {"learningRate": 0.3, "powerT": 0.5},
            {"learningRate": 0.6, "powerT": 0.0, "l2": 1e-6},
        ]
        (bucket,) = bucket_candidates([(est, m) for m in maps])
        mask = np.zeros(clf_table.num_rows, dtype=bool)
        mask[: clf_table.num_rows * 3 // 4] = True
        train, valid = clf_table.filter(mask), clf_table.filter(~mask)
        scored = fit_bucket(bucket, train, valid, "label", "accuracy")
        from mmlspark_tpu.automl.tune import _evaluate

        for m, (metric, _model) in zip(maps, scored):
            ref = est.copy(m).fit(train)
            ref_metric = _evaluate(ref.transform(valid), "label", "accuracy")
            assert np.isclose(metric, ref_metric, rtol=1e-5), (m, metric)


class TestTrainValidSweep:
    def test_selects_best_and_commits_standalone_bytes(
        self, clf_table, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("MMLSPARK_TPU_CHECKPOINT_DIR", str(tmp_path))
        est = LightGBMClassifier(
            labelCol="label", numIterations=5, numLeaves=7, maxBin=32
        )
        sweep = TrainValidSweep(
            estimator=est,
            paramSpace=GridSpace({
                "learningRate": [0.05, 0.1, 0.2],
                "numLeaves": [7, 15],
            }),
            labelCol="label",
            evaluationMetric="AUC",
            seed=3,
        )
        model = sweep.fit(clf_table)
        metrics = model.getAllMetrics()
        assert len(metrics) == 6
        higher_best = int(np.nanargmax(np.asarray(metrics)))
        assert metrics[higher_best] == model.getBestMetric()
        assert "prediction" in model.transform(clf_table)

        board = model.leaderboard()
        assert list(board.column("rank")) == list(range(6))
        assert board.column("metric")[0] == model.getBestMetric()

        # the committed model IS a standalone fit with the winning
        # params, byte for byte (the refit-on-full-table contract)
        from mmlspark_tpu.runtime.journal import ModelStore

        store = ModelStore(str(tmp_path / "models"))
        version, text = store.latest("sweep-lightgbmclassificationmodel")
        assert version == model.getModelVersion() == 1
        standalone = est.copy(model.getBestParams()).fit(clf_table)
        assert text == standalone.get_model_string()

    def test_dist_dict_space_samples_num_runs(self, clf_table):
        sweep = TrainValidSweep(
            estimator=LightGBMClassifier(
                labelCol="label", numIterations=3, numLeaves=7, maxBin=32
            ),
            paramSpace={
                "learningRate": DoubleRangeHyperParam(0.05, 0.3),
                "numLeaves": DiscreteHyperParam([7, 15]),
            },
            labelCol="label",
            numRuns=3,
            seed=1,
            commitModel=False,
        )
        model = sweep.fit(clf_table)
        assert len(model.getAllMetrics()) == 3
        assert model.getModelVersion() == -1

    def test_tune_batched_selection_matches_threadpool(self, clf_table):
        """Golden parity: TuneHyperparameters routed through the batched
        plane must pick the SAME best candidate as the thread-pool
        baseline under a fixed seed (metric values match to float
        tolerance; selection must match exactly)."""
        from mmlspark_tpu.automl import TuneHyperparameters

        kwargs = dict(
            models=LightGBMClassifier(numIterations=5, maxBin=32),
            paramSpace={
                "numLeaves": DiscreteHyperParam([3, 15]),
                "learningRate": DoubleRangeHyperParam(0.05, 0.3),
            },
            evaluationMetric="AUC",
            numFolds=2,
            numRuns=3,
            seed=5,
        )
        batched = TuneHyperparameters(
            sweepMode="batched", **kwargs
        ).fit(clf_table)
        threadpool = TuneHyperparameters(
            sweepMode="threadpool", **kwargs
        ).fit(clf_table)
        assert batched.getBestParams() == threadpool.getBestParams()
        np.testing.assert_allclose(
            batched.getAllMetrics(), threadpool.getAllMetrics(), rtol=1e-5
        )


class TestDefaultHyperparams:
    def test_spaces_name_real_estimator_params(self):
        gbdt = LightGBMClassifier()
        for name in DefaultHyperparams.lightgbm():
            assert gbdt.hasParam(name), name
        vw = VowpalWabbitClassifier()
        for name in DefaultHyperparams.sgd():
            assert vw.hasParam(name), name
        for name in DefaultHyperparams.vw():
            assert vw.hasParam(name), name


@pytest.mark.slow
class TestCompileAmortization:
    def test_bench_guard_at_smoke_scale(self, monkeypatch):
        """The bench regression guard (satellite of the acceptance
        criterion): a >=12-candidate sweep must compile strictly fewer
        batched programs than it has candidates and beat the sequential
        baseline on models/sec. Reuses bench._sweep_block + sweep_guard
        verbatim so the CI bench job and this test enforce one rule."""
        import bench
        from mmlspark_tpu.observability.profiler import get_profiler

        monkeypatch.setattr(bench, "N_ROWS", 1200)
        monkeypatch.setattr(bench, "N_ITERS", 3)
        monkeypatch.setenv("BENCH_SWEEP_ROWS", "1200")
        monkeypatch.setenv("BENCH_SWEEP_ITERS", "3")
        prof = get_profiler()
        was_enabled = prof.enabled
        prof.enable()
        try:
            block = bench.sweep_guard(bench._sweep_block())
        finally:
            if not was_enabled:
                prof.disable()
        assert block["sweep_candidates"] >= 12
        assert block["sweep_batched_compiles"] < block["sweep_candidates"]
        assert max(block["sweep_bucket_sizes"]) > 1


def _gang_grid_sweep(table, num_processes=0, group_options=None):
    est = LightGBMClassifier(
        labelCol="label", numIterations=4, numLeaves=7, maxBin=32
    )
    sweep = TrainValidSweep(
        estimator=est,
        paramSpace=GridSpace({
            "learningRate": [0.05, 0.2],
            "numLeaves": [7, 15],
        }),
        labelCol="label",
        evaluationMetric="AUC",
        seed=3,
        numProcesses=num_processes,
    )
    if group_options is not None:
        sweep._group_options = group_options
    return sweep, sweep.fit(table)


@pytest.mark.slow
class TestSweepGang:
    def test_gang_matches_inline(self, clf_table, tmp_path, monkeypatch):
        monkeypatch.setenv(
            "MMLSPARK_TPU_CHECKPOINT_DIR", str(tmp_path / "inline")
        )
        _, inline = _gang_grid_sweep(clf_table)
        monkeypatch.setenv(
            "MMLSPARK_TPU_CHECKPOINT_DIR", str(tmp_path / "gang")
        )
        sweep, gang = _gang_grid_sweep(
            clf_table, num_processes=2,
            group_options={"epoch_timeout_s": 180.0},
        )
        assert sweep._process_sweep["epochs"] == 1
        np.testing.assert_allclose(
            gang.getAllMetrics(), inline.getAllMetrics(), rtol=1e-5
        )
        assert gang.getBestParams() == inline.getBestParams()


@pytest.mark.slow
class TestSweepChaos:
    def test_sigkill_mid_sweep_does_not_change_selection(
        self, clf_table, tmp_path, monkeypatch
    ):
        """Satellite chaos pass: kill a sweep worker mid-bucket; the gang
        re-forms, journaled buckets resume with zero re-execution, and
        the final leaderboard + committed ModelStore version/bytes are
        identical to the undisturbed run."""
        from mmlspark_tpu import observability as obs
        from mmlspark_tpu.runtime.faults import FaultPlan
        from mmlspark_tpu.runtime.journal import ModelStore

        event_log = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("MMLSPARK_TPU_EVENT_LOG", event_log)
        monkeypatch.setenv(
            "MMLSPARK_TPU_CHECKPOINT_DIR", str(tmp_path / "base")
        )
        _, base = _gang_grid_sweep(
            clf_table, num_processes=2,
            group_options={"epoch_timeout_s": 180.0},
        )

        # kill member 1 at bucket index 1 (the grid above makes 2
        # buckets, so the directive must target an index < 2)
        monkeypatch.setenv(
            "MMLSPARK_TPU_CHECKPOINT_DIR", str(tmp_path / "chaos")
        )
        plan = FaultPlan(seed=11).kill_process(1, iteration=1)
        sweep, chaos = _gang_grid_sweep(
            clf_table, num_processes=2,
            group_options={"faults": plan, "epoch_timeout_s": 180.0},
        )
        monkeypatch.delenv("MMLSPARK_TPU_EVENT_LOG")

        assert plan.fired == [("kill_process", 1, 0)]
        info = sweep._process_sweep
        assert info["epochs"] == 2
        killed = [s for s in info["exit_statuses"] if s.reason == "signal:9"]
        assert killed and killed[0].member == 1

        # selection unchanged: metrics, winner, committed version + bytes
        assert chaos.getAllMetrics() == base.getAllMetrics()
        assert chaos.getBestParams() == base.getBestParams()
        assert chaos.getModelVersion() == base.getModelVersion() == 1
        name = "sweep-lightgbmclassificationmodel"
        _, base_text = ModelStore(str(tmp_path / "base/models")).latest(name)
        _, chaos_text = ModelStore(str(tmp_path / "chaos/models")).latest(name)
        assert chaos_text == base_text

        events = obs.replay(event_log)
        names = [type(e).__name__ for e in events]
        assert names.count("ProcessLost") == 1
        assert names.count("GroupReformed") == 1
        assert names.count("SweepStarted") == 2
        assert names.count("SweepCompleted") == 2
