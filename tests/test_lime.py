"""lime/ tests — mirrors reference ``lime/`` suites (TabularLIMESuite,
ImageLIMESuite, SuperpixelSuite)."""

import numpy as np
import pytest

from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lime import (
    ImageLIME,
    SuperpixelTransformer,
    TabularLIME,
    fit_lasso_batch,
    mask_image,
    slic,
)


class _LinearModel(Transformer):
    """Inner model: y = x @ w, exposes inputCol/predictionCol contract."""

    def __init__(self, w, input_col="features", pred_col="prediction", **kw):
        super().__init__(**kw)
        self._w = np.asarray(w, dtype=np.float64)
        self._in = input_col
        self._out = pred_col

    def transform(self, table):
        X = np.asarray(table.column(self._in), dtype=np.float64)
        if X.ndim > 2:  # image input: mean intensity per quadrant-weight
            X = X.reshape(len(X), -1)[:, : len(self._w)]
        return table.with_column(self._out, X @ self._w)


class TestLasso:
    def test_least_squares_recovery(self, rng):
        # lambda=0 -> plain least squares; recover true weights
        X = rng.normal(size=(4, 200, 3))
        w_true = np.array([2.0, -1.0, 0.5])
        y = X @ w_true
        W = fit_lasso_batch(X, y, 0.0)
        np.testing.assert_allclose(W, np.tile(w_true, (4, 1)), atol=1e-3)

    def test_soft_threshold_sparsity(self, rng):
        X = rng.normal(size=(1, 400, 5))
        w_true = np.array([3.0, 0.0, 0.0, 0.0, 0.0])
        y = X @ w_true
        W = fit_lasso_batch(X, y, 0.5)
        assert abs(W[0, 0]) > 2.0
        assert np.abs(W[0, 1:]).max() < 0.2


class TestTabularLIME:
    def test_recovers_linear_model(self, rng):
        w_true = np.array([1.5, -2.0, 0.0, 3.0])
        X = rng.normal(size=(6, 4))
        t = Table({"features": X})
        lime = TabularLIME(
            model=_LinearModel(w_true),
            inputCol="features",
            outputCol="weights",
            nSamples=400,
            seed=1,
        )
        model = lime.fit(t)
        out = model.transform(t)
        W = np.asarray(out["weights"], dtype=np.float64)
        # local explanation of a global linear model = its weights, every row
        np.testing.assert_allclose(W, np.tile(w_true, (6, 1)), atol=0.05)

    def test_save_load(self, rng, tmp_path):
        from mmlspark_tpu.lime import TabularLIMEModel

        X = rng.normal(size=(3, 2))
        model = TabularLIME(
            model=_LinearModel(np.ones(2)), inputCol="features",
            outputCol="w", nSamples=50,
        ).fit(Table({"features": X}))
        model.save(str(tmp_path / "lime"))
        loaded = TabularLIMEModel.load(str(tmp_path / "lime"))
        np.testing.assert_allclose(loaded.getColumnMeans(), model.getColumnMeans())


class TestSuperpixel:
    def test_slic_covers_image(self):
        img = np.zeros((32, 32, 3))
        img[:, 16:] = 1.0  # two homogeneous halves
        sp = slic(img, cell_size=8)
        assert sp.labels.shape == (32, 32)
        assert sp.num_clusters >= 2
        # every pixel belongs to exactly one cluster
        total = sum(len(c) for c in sp.clusters)
        assert total == 32 * 32

    def test_mask_image(self):
        img = np.ones((16, 16, 3))
        sp = slic(img, cell_size=8)
        none_on = mask_image(img, sp, np.zeros(sp.num_clusters, dtype=bool))
        assert none_on.sum() == 0
        all_on = mask_image(img, sp, np.ones(sp.num_clusters, dtype=bool))
        np.testing.assert_array_equal(all_on, img)

    def test_transformer(self):
        imgs = np.stack([np.random.default_rng(0).random((16, 16, 3))] * 2)
        t = Table({"image": imgs})
        out = SuperpixelTransformer(inputCol="image", cellSize=8).transform(t)
        assert out["superpixels"][0].num_clusters > 0


class TestImageLIME:
    def test_finds_informative_region(self, rng):
        # model responds to top-left pixel block intensity
        H = W = 16

        class _RegionModel(Transformer):
            def transform(self, table):
                imgs = np.asarray(table.column("image"), dtype=np.float64)
                score = imgs[:, :8, :8].mean(axis=(1, 2, 3))
                return table.with_column("prediction", score)

        img = rng.random((H, W, 3))
        t = Table({"image": img[None]})
        lime = ImageLIME(
            model=_RegionModel(),
            inputCol="image",
            outputCol="weights",
            predictionCol="prediction",
            cellSize=8,
            nSamples=200,
            seed=2,
        )
        out = lime.transform(t)
        sp = out["superpixels"][0]
        w = out["weights"][0]
        assert len(w) == sp.num_clusters
        # clusters centered in the top-left quadrant should carry the weight
        centers = np.array([c.mean(axis=0) for c in sp.clusters])
        informative = (centers[:, 0] < 8) & (centers[:, 1] < 8)
        assert w[informative].sum() > w[~informative].sum()
