"""observability/ tests — event bus, tracing, metrics registry, and the
bridges into the runtime scheduler and serving layers.

The registry tests use FRESH ``MetricsRegistry`` instances (never the
process-global one) so they cannot interfere with other tests feeding the
shared plane; the fault-injection bridge test pins
``MMLSPARK_TPU_FAULT_SEED`` so the recovery sequence — and therefore every
counter — is identical on every run.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu import runtime
from mmlspark_tpu.core.pipeline import Pipeline, Transformer
from mmlspark_tpu.core.profiling import StopWatch
from mmlspark_tpu.data import Table
from mmlspark_tpu.observability import (
    PARENT_HEADER,
    TRACE_HEADER,
    BatchFormed,
    BreakerTripped,
    EventBus,
    EventLogSink,
    FlightRecorder,
    IncidentRecorded,
    MetricsFederator,
    MetricsRegistry,
    ModelCommitted,
    RequestServed,
    RequestShed,
    SpanRecorded,
    StageCompleted,
    StageStarted,
    TaskDispatched,
    TaskFailed,
    TaskRetried,
    TraceContext,
    Tracer,
    collect,
    fleet_summary,
    format_timeline,
    from_record,
    get_bus,
    get_tracer,
    merge,
    parse_exposition,
    process_log_path,
    replay,
    timeline,
    write_merged,
)
from mmlspark_tpu.observability.slo import SLOReport
from mmlspark_tpu.serving import ServingServer
from mmlspark_tpu.serving.server import _BatchLoop


class _Doubler(Transformer):
    def transform(self, table):
        x = np.asarray(table.column("input"), dtype=np.float64)
        return table.with_column("prediction", x * 2)


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_get_or_create_and_monotonic(self):
        reg = MetricsRegistry()
        c = reg.counter("requests_total", "Requests")
        assert reg.counter("requests_total") is c
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_type_collision_is_an_error(self):
        reg = MetricsRegistry()
        reg.counter("x_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")

    def test_gauge_set_max(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth")
        g.set_max(3)
        g.set_max(1)
        assert g.value == 3
        g.set(0.5)
        g.dec(0.25)
        assert g.value == 0.25

    def test_labels_render_as_child_series(self):
        reg = MetricsRegistry()
        c = reg.counter("failures_total", "By reason")
        c.labels(reason="timeout").inc(2)
        c.labels(reason="timeout").inc()
        c.labels(reason='we"ird\\').inc()
        text = reg.exposition()
        assert '# TYPE failures_total counter' in text
        assert 'failures_total{reason="timeout"} 3' in text
        assert 'failures_total{reason="we\\"ird\\\\"} 1' in text

    def test_histogram_percentiles_and_exposition(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "Latency", buckets=(0.01, 0.1, 1.0))
        for v in (0.005,) * 50 + (0.05,) * 45 + (0.5,) * 5:
            h.observe(v)
        s = h.summary()
        assert s["count"] == 100
        assert 0.0 < s["p50"] <= 0.01
        assert 0.01 < s["p95"] <= 0.1
        assert 0.1 < s["p99"] <= 1.0
        text = reg.exposition()
        assert "# TYPE lat_seconds histogram" in text
        assert 'lat_seconds_bucket{le="0.01"} 50' in text
        assert 'lat_seconds_bucket{le="0.1"} 95' in text
        assert 'lat_seconds_bucket{le="+Inf"} 100' in text
        assert "lat_seconds_count 100" in text

    def test_histogram_overflow_clamps_to_last_bucket(self):
        h = MetricsRegistry().histogram("h", buckets=(1.0, 2.0))
        h.observe(50.0)
        assert h.percentile(0.99) == 2.0

    def test_summary_shape(self):
        reg = MetricsRegistry()
        reg.counter("a_total").inc()
        reg.counter("b_total").labels(kind="x").inc(2)
        reg.histogram("h_seconds").observe(0.2)
        s = reg.summary()
        assert s["a_total"] == 1
        assert s["b_total"]["kind=x"] == 2
        assert s["h_seconds"]["count"] == 1


# ---------------------------------------------------------------------------
# event bus + event log
# ---------------------------------------------------------------------------


class TestEventBus:
    def test_publish_reaches_listeners_in_order(self):
        bus = EventBus()
        seen = []
        bus.add_listener(lambda e: seen.append(("first", e)))
        bus.add_listener(lambda e: seen.append(("second", e)))
        assert bus.active
        ev = BatchFormed(epoch=0, size=4)
        bus.publish(ev)
        assert [tag for tag, _ in seen] == ["first", "second"]
        assert all(e is ev for _, e in seen)

    def test_inactive_without_listeners(self):
        assert not EventBus().active

    def test_listener_errors_never_propagate(self):
        bus = EventBus()
        seen = []
        bus.add_listener(lambda e: (_ for _ in ()).throw(RuntimeError("boom")))
        bus.add_listener(seen.append)
        bus.publish(ModelCommitted(model="M"))
        assert len(seen) == 1  # the broken listener was skipped, not fatal

    def test_events_carry_monotonic_timestamps(self):
        a = StageStarted(job_id=0, stage_id=0, name="s")
        b = StageCompleted(job_id=0, stage_id=0, name="s", duration=0.1)
        assert 0 < a.t <= b.t

    def test_record_round_trip(self):
        ev = TaskRetried(job_id=1, task_id=2, failures=1, reason="timeout")
        back = from_record(ev.to_record())
        assert back == ev
        with pytest.raises(ValueError, match="unknown event"):
            from_record({"event": "NotAnEvent"})

    def test_env_sink_replay_and_timeline(self, tmp_path, monkeypatch):
        path = tmp_path / "events.jsonl"
        monkeypatch.setenv("MMLSPARK_TPU_EVENT_LOG", str(path))
        bus = get_bus()
        try:
            assert bus.active
            bus.publish(StageStarted(job_id=0, stage_id=0, name="Scale"))
            bus.publish(StageCompleted(
                job_id=0, stage_id=0, name="Scale", duration=0.5
            ))
            bus.publish(TaskDispatched(
                job_id=0, task_id=0, attempt=0, queue_depth=1
            ))
            bus.publish(TaskFailed(job_id=0, task_id=0, reason="error"))
            bus.publish(RequestServed(rid="r1", status=200, latency=0.002))
            bus.publish(ModelCommitted(model="PipelineModel", version=3))
        finally:
            monkeypatch.delenv("MMLSPARK_TPU_EVENT_LOG")
            get_bus()  # re-sync detaches + closes the sink
        events = replay(str(path))
        assert [type(e).__name__ for e in events] == [
            "StageStarted", "StageCompleted", "TaskDispatched", "TaskFailed",
            "RequestServed", "ModelCommitted",
        ]
        summary = timeline(events)
        assert summary["stages"][0]["duration"] == 0.5
        assert summary["tasks"] == {
            "dispatched": 1, "retried": 0, "failed": 1, "failed_permanent": 0,
            "retry_reasons": {}, "speculated": 0, "recovered": 0,
            "attempts": {0: [{
                "attempt": 0, "worker": -1, "reason": "error",
                "duration": 0.0, "speculative": False, "permanent": False,
            }]},
        }
        assert summary["requests"]["statuses"] == {200: 1}
        assert summary["models"] == ["PipelineModel"]
        text = format_timeline(summary)
        assert "Scale" in text and "dispatched=1" in text

    def test_sink_is_json_lines(self, tmp_path):
        sink = EventLogSink(str(tmp_path / "ev.jsonl"))
        sink(BatchFormed(epoch=1, size=2, trace_id="t01"))
        sink.close()
        [line] = (tmp_path / "ev.jsonl").read_text().splitlines()
        rec = json.loads(line)
        assert rec["event"] == "BatchFormed"
        assert rec["epoch"] == 1 and rec["size"] == 2


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


class TestTracing:
    def test_nesting_follows_call_stack(self):
        tr = Tracer(xprof=False)
        with tr.span("outer") as outer:
            with tr.span("inner") as inner:
                assert tr.current() is inner
            assert tr.current() is outer
        assert tr.current() is None
        assert inner.trace_id == outer.trace_id
        assert inner.parent_id == outer.span_id
        assert outer.parent_id is None

    def test_ids_are_deterministic(self):
        # counter-based ids: two fresh tracers mint identical sequences
        tr1, tr2 = Tracer(xprof=False), Tracer(xprof=False)
        ids1 = [(s.trace_id, s.span_id)
                for s in (tr1.start_span("a") for _ in range(3))]
        ids2 = [(s.trace_id, s.span_id)
                for s in (tr2.start_span("a") for _ in range(3))]
        assert ids1 == ids2
        assert len(set(ids1)) == 3

    def test_exception_sets_status(self):
        tr = Tracer(xprof=False)
        with pytest.raises(KeyError):
            with tr.span("doomed"):
                raise KeyError("k")
        [rec] = tr.export()
        assert rec["status"] == "KeyError"

    def test_cross_thread_propagation_via_attach(self):
        tr = Tracer(xprof=False)
        root = tr.start_span("request")
        child_ids = []

        def worker():
            with tr.attach(root):
                with tr.span("batch"):
                    child_ids.append(tr.current().parent_id)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        tr.finish(root)
        assert child_ids == [root.span_id]
        tree = tr.span_tree(root.trace_id)
        assert tree["roots"][0]["name"] == "request"
        assert tree["roots"][0]["children"][0]["name"] == "batch"

    def test_export_filters_by_trace(self):
        tr = Tracer(xprof=False)
        with tr.span("a") as a:
            pass
        with tr.span("b"):
            pass
        assert [r["name"] for r in tr.export(a.trace_id)] == ["a"]
        assert len(tr.export()) == 2
        tr.clear()
        assert tr.export() == []


# ---------------------------------------------------------------------------
# profiling satellite: StopWatch.add
# ---------------------------------------------------------------------------


class TestStopWatchAdd:
    def test_add_is_the_public_form_of_measure(self):
        sw = StopWatch()
        sw.add("run", 1.5)
        sw.add("run", 0.5)
        with sw.measure("other"):
            pass
        s = sw.summary()
        assert s["run"] == 2.0
        assert s["other"] >= 0.0

    def test_runtime_metrics_uses_public_api(self):
        # the encapsulation leak (reaching into StopWatch._totals) is gone
        m = runtime.RuntimeMetrics(registry=MetricsRegistry())
        m.note_start(0, 0.25)
        m.note_done(0, 1.0)
        assert m.stopwatch.summary() == {"queue_wait": 0.25, "run": 1.0}


# ---------------------------------------------------------------------------
# scheduler bridge: registry counters == RuntimeMetrics.summary() EXACTLY,
# under deterministic fault injection
# ---------------------------------------------------------------------------


class TestSchedulerRegistryBridge:
    def _run_chaos(self):
        # one executor death, one heartbeat loss, one lineage recompute —
        # every recovery path feeds the registry
        plan = runtime.FaultPlan().kill_task(1).drop_heartbeat(0)
        lin = runtime.Lineage()
        for i, v in enumerate((10, 20, 30, 40)):
            lin.record(i, (lambda v=v: v), describe=f"src{v}")
        first = {"seen": False}
        lock = threading.Lock()

        def work(x):
            with lock:
                if not first["seen"]:
                    first["seen"] = True
                    raise runtime.PartitionLostError("buffer evicted")
            # first dispatch hands the shard; a post-recompute retry hands
            # the already-materialized value
            v = x.materialize() if hasattr(x, "materialize") else x
            return v * 2

        reg = MetricsRegistry()
        m = runtime.RuntimeMetrics(registry=reg)
        pol = runtime.SchedulerPolicy(
            max_workers=2, backoff_base=0.01, heartbeat_interval=0.02,
            heartbeat_timeout=0.15, faults=plan,
        )
        out = runtime.run_partitioned(
            work, list(lin._shards.values()), pol, metrics=m, lineage=lin,
        )
        assert out == [20, 40, 60, 80]
        assert ("kill", 1, 0) in plan.fired
        return reg, m

    def test_counters_match_summary_exactly(self, monkeypatch):
        monkeypatch.setenv("MMLSPARK_TPU_FAULT_SEED", "0")
        reg, m = self._run_chaos()
        s = m.summary()
        r = reg.summary()
        # chaos actually happened
        assert s["retries_total"] >= 2
        assert s["failures_executor_death"] == 1
        assert s["lineage_recomputes"] == 1
        # exact equality between the two planes, counter by counter
        assert r["scheduler_tasks_done_total"] == s["tasks_done"]
        assert r["scheduler_dispatches_total"] == s["dispatches"]
        assert r["scheduler_retries_total"] == s["retries_total"]
        assert r["scheduler_lineage_recomputes_total"] == s["lineage_recomputes"]
        assert r["scheduler_wasted_results_total"] == s["wasted_results"]
        assert r["scheduler_max_queue_depth"] == s["max_queue_depth"]
        failures = r["scheduler_failures_total"]
        for reason in ("error", "executor_death", "timeout", "heartbeat"):
            assert failures.get(f"reason={reason}", 0) == s[f"failures_{reason}"]
        assert sum(failures.values()) == s["failures_total"]
        # phase totals mirror the latency histograms
        phases = s["phases"]
        assert r["scheduler_task_queue_wait_seconds"]["sum"] == pytest.approx(
            phases.get("queue_wait", 0.0)
        )
        assert r["scheduler_task_run_seconds"]["sum"] == pytest.approx(
            phases.get("run", 0.0)
        )

    def test_scheduler_publishes_task_events(self, monkeypatch):
        monkeypatch.setenv("MMLSPARK_TPU_FAULT_SEED", "0")
        events = []
        listener = events.append
        bus = get_bus()
        bus.add_listener(listener)
        try:
            plan = runtime.FaultPlan().kill_task(0)
            pol = runtime.SchedulerPolicy(
                max_workers=2, backoff_base=0.01, heartbeat_interval=0.02,
                faults=plan,
            )
            out = runtime.run_partitioned(lambda x: x + 1, [1, 2], pol)
        finally:
            bus.remove_listener(listener)
        assert out == [2, 3]
        kinds = [type(e).__name__ for e in events]
        assert kinds.count("TaskDispatched") == 3  # 2 tasks + 1 retry
        assert "TaskFailed" in kinds
        assert "TaskRetried" in kinds
        retried = next(e for e in events if isinstance(e, TaskRetried))
        assert retried.reason == "executor_death"
        failed = next(e for e in events if isinstance(e, TaskFailed))
        assert failed.permanent is False


# ---------------------------------------------------------------------------
# serving bridge: endpoints, histograms, reply-failure satellite
# ---------------------------------------------------------------------------


class TestServingObservability:
    def test_metrics_and_healthz_endpoints(self):
        reg = MetricsRegistry()
        with ServingServer(_Doubler(), max_latency_ms=1.0, registry=reg) as srv:
            base = srv.info.url.rstrip("/")
            for i in range(4):
                status, out = _post(base, {"input": float(i)})
                assert status == 200 and out["prediction"] == 2.0 * i
            status, ctype, text = _get(base + "/metrics")
            assert status == 200
            assert ctype.startswith("text/plain")
            assert "# TYPE serving_requests_total counter" in text
            assert "serving_requests_total 4" in text
            assert "# TYPE serving_queue_wait_seconds histogram" in text
            assert "serving_apply_latency_seconds_count" in text
            status, ctype, body = _get(base + "/healthz")
            health = json.loads(body)
            assert status == 200 and health["status"] == "ok"
            assert health["uptime_seconds"] >= 0
            assert health["model_epoch"] >= 1
            assert health["last_batch_age_seconds"] is not None
            assert health["uncommitted_epochs"] == 0
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(base + "/nope")
            assert err.value.code == 404
        # histogram counts line up with the traffic
        s = reg.summary()
        assert s["serving_queue_wait_seconds"]["count"] == 4
        assert s["serving_batch_size"]["count"] >= 1
        assert s["serving_apply_latency_seconds"]["count"] >= 1

    def test_request_trace_threads_into_batch_and_apply(self):
        with ServingServer(_Doubler(), max_latency_ms=1.0,
                           registry=MetricsRegistry()) as srv:
            status, _ = _post(srv.info.url, {"input": 1.0})
            assert status == 200
        # the handler finishes the root span AFTER writing the reply, so
        # the client can observe the response a beat before the span lands
        tracer = get_tracer()
        deadline = time.monotonic() + 2.0
        root = None
        while root is None and time.monotonic() < deadline:
            root = next(
                (r for r in reversed(tracer.export())
                 if r["name"] == "serving.request"), None,
            )
            if root is None:
                time.sleep(0.01)
        assert root is not None, "request span never finished"
        names = {r["name"] for r in tracer.export(root["trace_id"])}
        assert {"serving.request", "serving.batch", "serving.apply"} <= names

    def test_reply_failure_counts_and_logs_debug(self, caplog):
        reg = MetricsRegistry()
        loop = _BatchLoop(_Doubler(), "input", "prediction", 8, 1.0,
                          registry=reg)
        events = []
        listener = events.append
        bus = get_bus()
        bus.add_listener(listener)
        try:
            with caplog.at_level("DEBUG", logger="mmlspark_tpu.serving"):
                loop.note_reply_failure("rid-1", BrokenPipeError(32, "gone"))
        finally:
            bus.remove_listener(listener)
        assert reg.summary()["serving_replies_failed_total"] == 1
        served = [e for e in events if isinstance(e, RequestServed)]
        assert served and served[0].status == 499 and served[0].rid == "rid-1"
        assert any(
            "client disconnected" in r.message and r.levelname == "DEBUG"
            for r in caplog.records
        )


# ---------------------------------------------------------------------------
# pipeline bridge
# ---------------------------------------------------------------------------


class TestPipelineEvents:
    def test_fit_emits_stage_and_model_events(self):
        events = []
        listener = events.append
        bus = get_bus()
        bus.add_listener(listener)
        try:
            table = Table({"input": np.arange(4.0)})
            model = Pipeline(stages=[_Doubler()]).fit(table)
            out = model.transform(table)
        finally:
            bus.remove_listener(listener)
        assert np.allclose(out.column("prediction"), np.arange(4.0) * 2)
        kinds = [type(e).__name__ for e in events]
        assert kinds[0] == "StageStarted"
        assert "StageCompleted" in kinds
        assert kinds[-1] == "ModelCommitted"
        started = next(e for e in events if isinstance(e, StageStarted))
        completed = next(e for e in events if isinstance(e, StageCompleted))
        assert started.name == completed.name == "_Doubler"
        assert completed.status == "ok" and completed.duration >= 0

    def test_fit_failure_reports_status(self):
        class _Boom(Transformer):
            def transform(self, table):
                raise RuntimeError("no")

        events = []
        listener = events.append
        bus = get_bus()
        bus.add_listener(listener)
        try:
            with pytest.raises(RuntimeError):
                # two stages force a transform of the first stage's output
                Pipeline(stages=[_Boom(), _Doubler()]).fit(
                    Table({"input": np.arange(3.0)})
                )
        finally:
            bus.remove_listener(listener)
        completed = [e for e in events if isinstance(e, StageCompleted)]
        assert completed and completed[0].status == "RuntimeError"

    def test_transform_opens_stage_spans_inside_a_trace(self):
        tracer = get_tracer()
        model = Pipeline(stages=[_Doubler()]).fit(
            Table({"input": np.arange(2.0)})
        )
        with tracer.span("request") as root:
            model.transform(Table({"input": np.arange(2.0)}))
        names = [r["name"] for r in tracer.export(root.trace_id)]
        assert "transform:_Doubler" in names

    def test_untraced_transform_opens_no_spans(self):
        tracer = get_tracer()
        model = Pipeline(stages=[_Doubler()]).fit(
            Table({"input": np.arange(2.0)})
        )
        before = len(tracer.export())
        # no ambient span: the hot path must not pay per-stage spans
        model.transform(Table({"input": np.arange(2.0)}))
        assert len(tracer.export()) == before


# ---------------------------------------------------------------------------
# wire-propagated trace context
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_header_round_trip(self):
        ctx = TraceContext(trace_id="t00ab", parent_span_id="driver:00000003")
        headers = ctx.to_headers()
        assert headers == {
            TRACE_HEADER: "t00ab",
            PARENT_HEADER: "driver:00000003",
        }
        assert TraceContext.from_headers(headers) == ctx

    def test_no_trace_header_means_no_context(self):
        assert TraceContext.from_headers({}) is None
        assert TraceContext.from_headers(None) is None
        # a parent without a trace id is noise, not a context
        assert TraceContext.from_headers({PARENT_HEADER: "x:01"}) is None

    def test_start_span_adopts_remote_context(self):
        tr = Tracer(xprof=False)
        ctx = TraceContext(trace_id="t00ab", parent_span_id="driver:00000003")
        span = tr.start_span("serving.request", context=ctx)
        assert span.trace_id == "t00ab"
        assert span.parent_id == "driver:00000003"

    def test_local_parent_wins_over_context(self):
        tr = Tracer(xprof=False)
        ctx = TraceContext(trace_id="remote", parent_span_id="driver:01")
        with tr.span("local-root") as root:
            child = tr.start_span("child", context=ctx)
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id

    def test_from_span_qualifies_parent_with_process_label(self, monkeypatch):
        monkeypatch.setenv("MMLSPARK_TPU_EVENT_LOG_PROCESS", "replica-7")
        tr = Tracer(xprof=False)
        span = tr.start_span("router.hop")
        ctx = TraceContext.from_span(span)
        assert ctx.trace_id == span.trace_id
        assert ctx.parent_span_id == f"replica-7:{span.span_id}"

    def test_dict_round_trip_for_epoch_specs(self):
        ctx = TraceContext(trace_id="t01", parent_span_id="driver:02")
        assert TraceContext.from_dict(ctx.to_dict()) == ctx
        assert TraceContext.from_dict(None) is None
        assert TraceContext.from_dict({"parent_span_id": "x"}) is None


class TestSpanRecorded:
    def test_finished_spans_publish_when_bus_active(self):
        bus = get_bus()
        seen = []
        bus.add_listener(seen.append)
        try:
            tr = Tracer(xprof=False)
            with tr.span("outer"):
                with tr.span("inner"):
                    pass
        finally:
            bus.remove_listener(seen.append)
        spans = [e for e in seen if isinstance(e, SpanRecorded)]
        assert [s.name for s in spans] == ["inner", "outer"]
        inner, outer = spans
        assert inner.trace_id == outer.trace_id
        assert inner.parent_id == outer.span_id
        assert inner.duration >= 0 and inner.wall_start > 0


# ---------------------------------------------------------------------------
# fleet event-log federation
# ---------------------------------------------------------------------------


class TestEventLogFederation:
    def test_process_log_path_suffixes_the_base(self):
        assert (
            process_log_path("/tmp/ev.jsonl", "replica-0")
            == "/tmp/ev.jsonl@replica-0"
        )
        for bad in ("a.b", "a@b", "a/b", "a\\b"):
            with pytest.raises(ValueError, match="invalid process label"):
                process_log_path("/tmp/ev.jsonl", bad)

    def test_sink_stamps_process_and_wall_time(self, tmp_path):
        path = tmp_path / "ev.jsonl"
        sink = EventLogSink(str(path), process="replica-3")
        sink(RequestServed(rid="r1", status=200, latency=0.01))
        sink.close()
        [line] = path.read_text().splitlines()
        rec = json.loads(line)
        assert rec["process"] == "replica-3"
        assert rec["wt"] > 0

    def _write_fleet_log(self, tmp_path):
        base = str(tmp_path / "events.jsonl")
        driver = EventLogSink(base, process="driver")
        replicas = [
            EventLogSink(process_log_path(base, f"replica-{i}"),
                         process=f"replica-{i}")
            for i in range(2)
        ]
        driver(StageStarted(job_id=0, stage_id=0, name="route"))
        replicas[0](RequestServed(rid="r0", status=200, latency=0.001))
        replicas[1](RequestServed(rid="r1", status=200, latency=0.002))
        driver(StageCompleted(job_id=0, stage_id=0, name="route",
                              duration=0.01))
        for sink in [driver, *replicas]:
            sink.close()
        return base

    def test_collect_finds_driver_and_siblings(self, tmp_path):
        base = self._write_fleet_log(tmp_path)
        segments = collect(base)
        assert sorted(segments) == ["driver", "replica-0", "replica-1"]
        assert segments["driver"] == [base]
        assert segments["replica-0"] == [base + "@replica-0"]

    def test_merge_orders_by_wall_clock_and_tags_process(self, tmp_path):
        base = self._write_fleet_log(tmp_path)
        events = merge(base)
        assert len(events) == 4
        stamps = [e.wt for e in events]
        assert stamps == sorted(stamps)
        assert {e.process for e in events} == {
            "driver", "replica-0", "replica-1",
        }
        served = [e for e in events if isinstance(e, RequestServed)]
        assert {e.process for e in served} == {"replica-0", "replica-1"}

    def test_write_merged_is_byte_identical_across_remerges(self, tmp_path):
        base = self._write_fleet_log(tmp_path)
        out1 = str(tmp_path / "merged-1.jsonl")
        out2 = str(tmp_path / "merged-2.jsonl")
        n1 = write_merged(base, out1)
        n2 = write_merged(base, out2)
        assert n1 == n2 == 4
        with open(out1, "rb") as a, open(out2, "rb") as b:
            assert a.read() == b.read()

    def test_timeline_counts_per_process(self, tmp_path):
        base = self._write_fleet_log(tmp_path)
        summary = timeline(merge(base))
        assert summary["by_process"] == {
            "driver": 2, "replica-0": 1, "replica-1": 1,
        }


# ---------------------------------------------------------------------------
# fleet metrics federation
# ---------------------------------------------------------------------------


def _replica_exposition(latencies, inflight, shed):
    """One fake replica's /metrics body, built from a real registry so
    parse_exposition stays the exact inverse of exposition()."""
    reg = MetricsRegistry()
    h = reg.histogram("serving_queue_wait_seconds", "Queue wait")
    for v in latencies:
        h.observe(v)
    reg.gauge("serving_inflight").set(inflight)
    reg.counter("serving_shed_total").inc(shed)
    return reg.exposition()


class TestMetricsFederation:
    def test_parse_exposition_inverts_registry_exposition(self):
        reg = MetricsRegistry()
        reg.counter("requests_total", "Requests").inc(3)
        reg.counter("failures_total").labels(reason="timeout").inc(2)
        reg.histogram("lat_seconds", buckets=[0.01, 0.1]).observe(0.05)
        kinds, samples = parse_exposition(reg.exposition())
        assert kinds["requests_total"] == "counter"
        assert kinds["lat_seconds"] == "histogram"
        by_name = {}
        for name, labels, value in samples:
            by_name.setdefault(name, []).append((labels, value))
        assert by_name["requests_total"] == [({}, 3.0)]
        assert by_name["failures_total"] == [({"reason": "timeout"}, 2.0)]
        buckets = dict(
            (labels["le"], value)
            for labels, value in by_name["lat_seconds_bucket"]
        )
        assert buckets["0.1"] == 1.0 and buckets["+Inf"] == 1.0

    def _federator(self, bodies):
        """A MetricsFederator whose fetch is served from ``bodies``:
        {url-substring: text}."""
        def fetch(url, timeout_s):
            for part, body in bodies.items():
                if part in url:
                    return body
            raise OSError(f"no route to {url}")

        return MetricsFederator("http://registry:0", fetch=fetch)

    def test_scrape_labels_every_series_with_the_replica(self):
        fed = self._federator({
            ":9000": _replica_exposition([0.002, 0.004], inflight=1, shed=0),
            ":9001": _replica_exposition([0.2, 0.4], inflight=3, shed=5),
        })
        services = [
            {"name": "replica-0", "host": "h", "port": 9000},
            {"name": "replica-1", "host": "h", "port": 9001},
        ]
        reg = fed.scrape(services)
        summary = reg.summary()
        assert summary["serving_inflight"] == {
            "replica=replica-0": 1.0, "replica=replica-1": 3.0,
        }
        hist = reg.histogram("serving_queue_wait_seconds")
        assert hist.labels(replica="replica-0").count == 2
        assert hist.labels(replica="replica-1").count == 2
        # reconstructed buckets interpolate per-replica quantiles
        assert hist.labels(replica="replica-0").percentile(0.5) < 0.05
        assert hist.labels(replica="replica-1").percentile(0.5) > 0.05

    def test_fleet_signals_read_load_at_the_source(self):
        fed = self._federator({
            ":9000": _replica_exposition([0.001] * 99, inflight=2, shed=1),
        })
        signals = fed.fleet_signals(
            services=[{"name": "replica-0", "host": "h", "port": 9000}]
        )
        sig = signals["replica-0"]
        assert sig["inflight"] == 2.0
        assert sig["shed_total"] == 1.0
        assert sig["p99_ms"] > 0

    def test_scrape_failure_is_recorded_not_raised(self):
        fed = self._federator({":9000": _replica_exposition([], 0, 0)})
        services = [
            {"name": "replica-0", "host": "h", "port": 9000},
            {"name": "replica-gone", "host": "h", "port": 9999},
        ]
        reg = fed.scrape(services)
        assert "replica-gone" in fed.last_errors
        assert reg.summary()["serving_inflight"] == {
            "replica=replica-0": 0.0,
        }

    def test_fleet_summary_merges_histogram_children(self):
        fed = self._federator({
            ":9000": _replica_exposition([0.002, 0.004], 0, 0),
            ":9001": _replica_exposition([0.2, 0.4], 0, 0),
        })
        reg = fed.scrape([
            {"name": "replica-0", "host": "h", "port": 9000},
            {"name": "replica-1", "host": "h", "port": 9001},
        ])
        # the parent histogram has no direct observations, so the plain
        # summary reports count=0 — the fleet fold must merge children
        assert reg.summary()["serving_queue_wait_seconds"]["count"] == 0
        merged = fleet_summary(reg)["serving_queue_wait_seconds"]
        assert merged["count"] == 4
        # the fleet fold interpolates over the union of observations
        report = SLOReport.fold_fleet(reg)
        assert report.stages["queue"]["count"] == 4
        assert report.stages["queue"]["p99"] > 0


# ---------------------------------------------------------------------------
# incident flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_breaker_trip_dumps_an_atomic_bundle(self, tmp_path):
        recorder = FlightRecorder(str(tmp_path), registry=MetricsRegistry(),
                                  tracer=Tracer(xprof=False))
        bus = get_bus()
        seen = []
        bus.add_listener(seen.append)
        recorder.install()
        try:
            bus.publish(RequestServed(rid="r1", status=200, latency=0.001))
            bus.publish(BreakerTripped(breaker="replica-0", failures=3,
                                       window_s=10.0))
        finally:
            recorder.uninstall()
            bus.remove_listener(seen.append)
        [path] = recorder.recorded
        manifest = json.loads(
            (tmp_path / path.split("/")[-1] / "manifest.json").read_text()
        )
        assert manifest["trigger"] == "breaker_tripped"
        assert "3 failures" in manifest["detail"]
        lines = (
            tmp_path / path.split("/")[-1] / "events.jsonl"
        ).read_text().splitlines()
        kinds = [json.loads(line)["event"] for line in lines]
        assert kinds == ["RequestServed", "BreakerTripped"]
        assert (tmp_path / path.split("/")[-1] / "metrics.json").exists()
        assert (tmp_path / path.split("/")[-1] / "trace.json").exists()
        booked = [e for e in seen if isinstance(e, IncidentRecorded)]
        assert len(booked) == 1 and booked[0].path == path

    def test_cooldown_suppresses_repeat_triggers(self, tmp_path):
        clock = [1000.0]
        recorder = FlightRecorder(str(tmp_path), cooldown_s=30.0,
                                  registry=MetricsRegistry(),
                                  tracer=Tracer(xprof=False),
                                  clock=lambda: clock[0])
        assert recorder.record("slo_budget", detail="p99 over") is not None
        assert recorder.record("slo_budget") is None  # inside the window
        # a different trigger has its own cooldown
        assert recorder.record("gang_failed") is not None
        clock[0] += 31.0
        assert recorder.record("slo_budget") is not None
        assert len(recorder.recorded) == 3

    def test_incident_recorded_does_not_retrip(self, tmp_path):
        recorder = FlightRecorder(str(tmp_path), registry=MetricsRegistry(),
                                  tracer=Tracer(xprof=False))
        recorder.install()
        try:
            get_bus().publish(IncidentRecorded(
                incident_id="x", trigger="breaker_tripped", path="/p"
            ))
        finally:
            recorder.uninstall()
        assert recorder.recorded == []

    def test_env_driven_recorder_lifecycle(self, tmp_path, monkeypatch):
        from mmlspark_tpu.observability import incidents

        monkeypatch.delenv("MMLSPARK_TPU_INCIDENT_DIR", raising=False)
        assert incidents.get_recorder() is None
        assert incidents.maybe_record("gang_failed") is None  # no-op
        monkeypatch.setenv("MMLSPARK_TPU_INCIDENT_DIR", str(tmp_path / "inc"))
        try:
            recorder = incidents.get_recorder()
            assert recorder is not None
            assert recorder.directory == str(tmp_path / "inc")
            path = incidents.maybe_record("gang_failed", detail="epoch budget")
            assert path is not None and path.startswith(str(tmp_path / "inc"))
        finally:
            monkeypatch.delenv("MMLSPARK_TPU_INCIDENT_DIR")
            incidents.get_recorder()  # re-sync uninstalls
