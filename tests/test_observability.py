"""observability/ tests — event bus, tracing, metrics registry, and the
bridges into the runtime scheduler and serving layers.

The registry tests use FRESH ``MetricsRegistry`` instances (never the
process-global one) so they cannot interfere with other tests feeding the
shared plane; the fault-injection bridge test pins
``MMLSPARK_TPU_FAULT_SEED`` so the recovery sequence — and therefore every
counter — is identical on every run.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu import runtime
from mmlspark_tpu.core.pipeline import Pipeline, Transformer
from mmlspark_tpu.core.profiling import StopWatch
from mmlspark_tpu.data import Table
from mmlspark_tpu.observability import (
    BatchFormed,
    EventBus,
    EventLogSink,
    MetricsRegistry,
    ModelCommitted,
    RequestServed,
    StageCompleted,
    StageStarted,
    TaskDispatched,
    TaskFailed,
    TaskRetried,
    Tracer,
    format_timeline,
    from_record,
    get_bus,
    get_tracer,
    replay,
    timeline,
)
from mmlspark_tpu.serving import ServingServer
from mmlspark_tpu.serving.server import _BatchLoop


class _Doubler(Transformer):
    def transform(self, table):
        x = np.asarray(table.column("input"), dtype=np.float64)
        return table.with_column("prediction", x * 2)


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_get_or_create_and_monotonic(self):
        reg = MetricsRegistry()
        c = reg.counter("requests_total", "Requests")
        assert reg.counter("requests_total") is c
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_type_collision_is_an_error(self):
        reg = MetricsRegistry()
        reg.counter("x_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")

    def test_gauge_set_max(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth")
        g.set_max(3)
        g.set_max(1)
        assert g.value == 3
        g.set(0.5)
        g.dec(0.25)
        assert g.value == 0.25

    def test_labels_render_as_child_series(self):
        reg = MetricsRegistry()
        c = reg.counter("failures_total", "By reason")
        c.labels(reason="timeout").inc(2)
        c.labels(reason="timeout").inc()
        c.labels(reason='we"ird\\').inc()
        text = reg.exposition()
        assert '# TYPE failures_total counter' in text
        assert 'failures_total{reason="timeout"} 3' in text
        assert 'failures_total{reason="we\\"ird\\\\"} 1' in text

    def test_histogram_percentiles_and_exposition(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "Latency", buckets=(0.01, 0.1, 1.0))
        for v in (0.005,) * 50 + (0.05,) * 45 + (0.5,) * 5:
            h.observe(v)
        s = h.summary()
        assert s["count"] == 100
        assert 0.0 < s["p50"] <= 0.01
        assert 0.01 < s["p95"] <= 0.1
        assert 0.1 < s["p99"] <= 1.0
        text = reg.exposition()
        assert "# TYPE lat_seconds histogram" in text
        assert 'lat_seconds_bucket{le="0.01"} 50' in text
        assert 'lat_seconds_bucket{le="0.1"} 95' in text
        assert 'lat_seconds_bucket{le="+Inf"} 100' in text
        assert "lat_seconds_count 100" in text

    def test_histogram_overflow_clamps_to_last_bucket(self):
        h = MetricsRegistry().histogram("h", buckets=(1.0, 2.0))
        h.observe(50.0)
        assert h.percentile(0.99) == 2.0

    def test_summary_shape(self):
        reg = MetricsRegistry()
        reg.counter("a_total").inc()
        reg.counter("b_total").labels(kind="x").inc(2)
        reg.histogram("h_seconds").observe(0.2)
        s = reg.summary()
        assert s["a_total"] == 1
        assert s["b_total"]["kind=x"] == 2
        assert s["h_seconds"]["count"] == 1


# ---------------------------------------------------------------------------
# event bus + event log
# ---------------------------------------------------------------------------


class TestEventBus:
    def test_publish_reaches_listeners_in_order(self):
        bus = EventBus()
        seen = []
        bus.add_listener(lambda e: seen.append(("first", e)))
        bus.add_listener(lambda e: seen.append(("second", e)))
        assert bus.active
        ev = BatchFormed(epoch=0, size=4)
        bus.publish(ev)
        assert [tag for tag, _ in seen] == ["first", "second"]
        assert all(e is ev for _, e in seen)

    def test_inactive_without_listeners(self):
        assert not EventBus().active

    def test_listener_errors_never_propagate(self):
        bus = EventBus()
        seen = []
        bus.add_listener(lambda e: (_ for _ in ()).throw(RuntimeError("boom")))
        bus.add_listener(seen.append)
        bus.publish(ModelCommitted(model="M"))
        assert len(seen) == 1  # the broken listener was skipped, not fatal

    def test_events_carry_monotonic_timestamps(self):
        a = StageStarted(job_id=0, stage_id=0, name="s")
        b = StageCompleted(job_id=0, stage_id=0, name="s", duration=0.1)
        assert 0 < a.t <= b.t

    def test_record_round_trip(self):
        ev = TaskRetried(job_id=1, task_id=2, failures=1, reason="timeout")
        back = from_record(ev.to_record())
        assert back == ev
        with pytest.raises(ValueError, match="unknown event"):
            from_record({"event": "NotAnEvent"})

    def test_env_sink_replay_and_timeline(self, tmp_path, monkeypatch):
        path = tmp_path / "events.jsonl"
        monkeypatch.setenv("MMLSPARK_TPU_EVENT_LOG", str(path))
        bus = get_bus()
        try:
            assert bus.active
            bus.publish(StageStarted(job_id=0, stage_id=0, name="Scale"))
            bus.publish(StageCompleted(
                job_id=0, stage_id=0, name="Scale", duration=0.5
            ))
            bus.publish(TaskDispatched(
                job_id=0, task_id=0, attempt=0, queue_depth=1
            ))
            bus.publish(TaskFailed(job_id=0, task_id=0, reason="error"))
            bus.publish(RequestServed(rid="r1", status=200, latency=0.002))
            bus.publish(ModelCommitted(model="PipelineModel", version=3))
        finally:
            monkeypatch.delenv("MMLSPARK_TPU_EVENT_LOG")
            get_bus()  # re-sync detaches + closes the sink
        events = replay(str(path))
        assert [type(e).__name__ for e in events] == [
            "StageStarted", "StageCompleted", "TaskDispatched", "TaskFailed",
            "RequestServed", "ModelCommitted",
        ]
        summary = timeline(events)
        assert summary["stages"][0]["duration"] == 0.5
        assert summary["tasks"] == {
            "dispatched": 1, "retried": 0, "failed": 1, "failed_permanent": 0,
            "retry_reasons": {}, "speculated": 0, "recovered": 0,
            "attempts": {0: [{
                "attempt": 0, "worker": -1, "reason": "error",
                "duration": 0.0, "speculative": False, "permanent": False,
            }]},
        }
        assert summary["requests"]["statuses"] == {200: 1}
        assert summary["models"] == ["PipelineModel"]
        text = format_timeline(summary)
        assert "Scale" in text and "dispatched=1" in text

    def test_sink_is_json_lines(self, tmp_path):
        sink = EventLogSink(str(tmp_path / "ev.jsonl"))
        sink(BatchFormed(epoch=1, size=2, trace_id="t01"))
        sink.close()
        [line] = (tmp_path / "ev.jsonl").read_text().splitlines()
        rec = json.loads(line)
        assert rec["event"] == "BatchFormed"
        assert rec["epoch"] == 1 and rec["size"] == 2


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


class TestTracing:
    def test_nesting_follows_call_stack(self):
        tr = Tracer(xprof=False)
        with tr.span("outer") as outer:
            with tr.span("inner") as inner:
                assert tr.current() is inner
            assert tr.current() is outer
        assert tr.current() is None
        assert inner.trace_id == outer.trace_id
        assert inner.parent_id == outer.span_id
        assert outer.parent_id is None

    def test_ids_are_deterministic(self):
        # counter-based ids: two fresh tracers mint identical sequences
        tr1, tr2 = Tracer(xprof=False), Tracer(xprof=False)
        ids1 = [(s.trace_id, s.span_id)
                for s in (tr1.start_span("a") for _ in range(3))]
        ids2 = [(s.trace_id, s.span_id)
                for s in (tr2.start_span("a") for _ in range(3))]
        assert ids1 == ids2
        assert len(set(ids1)) == 3

    def test_exception_sets_status(self):
        tr = Tracer(xprof=False)
        with pytest.raises(KeyError):
            with tr.span("doomed"):
                raise KeyError("k")
        [rec] = tr.export()
        assert rec["status"] == "KeyError"

    def test_cross_thread_propagation_via_attach(self):
        tr = Tracer(xprof=False)
        root = tr.start_span("request")
        child_ids = []

        def worker():
            with tr.attach(root):
                with tr.span("batch"):
                    child_ids.append(tr.current().parent_id)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        tr.finish(root)
        assert child_ids == [root.span_id]
        tree = tr.span_tree(root.trace_id)
        assert tree["roots"][0]["name"] == "request"
        assert tree["roots"][0]["children"][0]["name"] == "batch"

    def test_export_filters_by_trace(self):
        tr = Tracer(xprof=False)
        with tr.span("a") as a:
            pass
        with tr.span("b"):
            pass
        assert [r["name"] for r in tr.export(a.trace_id)] == ["a"]
        assert len(tr.export()) == 2
        tr.clear()
        assert tr.export() == []


# ---------------------------------------------------------------------------
# profiling satellite: StopWatch.add
# ---------------------------------------------------------------------------


class TestStopWatchAdd:
    def test_add_is_the_public_form_of_measure(self):
        sw = StopWatch()
        sw.add("run", 1.5)
        sw.add("run", 0.5)
        with sw.measure("other"):
            pass
        s = sw.summary()
        assert s["run"] == 2.0
        assert s["other"] >= 0.0

    def test_runtime_metrics_uses_public_api(self):
        # the encapsulation leak (reaching into StopWatch._totals) is gone
        m = runtime.RuntimeMetrics(registry=MetricsRegistry())
        m.note_start(0, 0.25)
        m.note_done(0, 1.0)
        assert m.stopwatch.summary() == {"queue_wait": 0.25, "run": 1.0}


# ---------------------------------------------------------------------------
# scheduler bridge: registry counters == RuntimeMetrics.summary() EXACTLY,
# under deterministic fault injection
# ---------------------------------------------------------------------------


class TestSchedulerRegistryBridge:
    def _run_chaos(self):
        # one executor death, one heartbeat loss, one lineage recompute —
        # every recovery path feeds the registry
        plan = runtime.FaultPlan().kill_task(1).drop_heartbeat(0)
        lin = runtime.Lineage()
        for i, v in enumerate((10, 20, 30, 40)):
            lin.record(i, (lambda v=v: v), describe=f"src{v}")
        first = {"seen": False}
        lock = threading.Lock()

        def work(x):
            with lock:
                if not first["seen"]:
                    first["seen"] = True
                    raise runtime.PartitionLostError("buffer evicted")
            # first dispatch hands the shard; a post-recompute retry hands
            # the already-materialized value
            v = x.materialize() if hasattr(x, "materialize") else x
            return v * 2

        reg = MetricsRegistry()
        m = runtime.RuntimeMetrics(registry=reg)
        pol = runtime.SchedulerPolicy(
            max_workers=2, backoff_base=0.01, heartbeat_interval=0.02,
            heartbeat_timeout=0.15, faults=plan,
        )
        out = runtime.run_partitioned(
            work, list(lin._shards.values()), pol, metrics=m, lineage=lin,
        )
        assert out == [20, 40, 60, 80]
        assert ("kill", 1, 0) in plan.fired
        return reg, m

    def test_counters_match_summary_exactly(self, monkeypatch):
        monkeypatch.setenv("MMLSPARK_TPU_FAULT_SEED", "0")
        reg, m = self._run_chaos()
        s = m.summary()
        r = reg.summary()
        # chaos actually happened
        assert s["retries_total"] >= 2
        assert s["failures_executor_death"] == 1
        assert s["lineage_recomputes"] == 1
        # exact equality between the two planes, counter by counter
        assert r["scheduler_tasks_done_total"] == s["tasks_done"]
        assert r["scheduler_dispatches_total"] == s["dispatches"]
        assert r["scheduler_retries_total"] == s["retries_total"]
        assert r["scheduler_lineage_recomputes_total"] == s["lineage_recomputes"]
        assert r["scheduler_wasted_results_total"] == s["wasted_results"]
        assert r["scheduler_max_queue_depth"] == s["max_queue_depth"]
        failures = r["scheduler_failures_total"]
        for reason in ("error", "executor_death", "timeout", "heartbeat"):
            assert failures.get(f"reason={reason}", 0) == s[f"failures_{reason}"]
        assert sum(failures.values()) == s["failures_total"]
        # phase totals mirror the latency histograms
        phases = s["phases"]
        assert r["scheduler_task_queue_wait_seconds"]["sum"] == pytest.approx(
            phases.get("queue_wait", 0.0)
        )
        assert r["scheduler_task_run_seconds"]["sum"] == pytest.approx(
            phases.get("run", 0.0)
        )

    def test_scheduler_publishes_task_events(self, monkeypatch):
        monkeypatch.setenv("MMLSPARK_TPU_FAULT_SEED", "0")
        events = []
        listener = events.append
        bus = get_bus()
        bus.add_listener(listener)
        try:
            plan = runtime.FaultPlan().kill_task(0)
            pol = runtime.SchedulerPolicy(
                max_workers=2, backoff_base=0.01, heartbeat_interval=0.02,
                faults=plan,
            )
            out = runtime.run_partitioned(lambda x: x + 1, [1, 2], pol)
        finally:
            bus.remove_listener(listener)
        assert out == [2, 3]
        kinds = [type(e).__name__ for e in events]
        assert kinds.count("TaskDispatched") == 3  # 2 tasks + 1 retry
        assert "TaskFailed" in kinds
        assert "TaskRetried" in kinds
        retried = next(e for e in events if isinstance(e, TaskRetried))
        assert retried.reason == "executor_death"
        failed = next(e for e in events if isinstance(e, TaskFailed))
        assert failed.permanent is False


# ---------------------------------------------------------------------------
# serving bridge: endpoints, histograms, reply-failure satellite
# ---------------------------------------------------------------------------


class TestServingObservability:
    def test_metrics_and_healthz_endpoints(self):
        reg = MetricsRegistry()
        with ServingServer(_Doubler(), max_latency_ms=1.0, registry=reg) as srv:
            base = srv.info.url.rstrip("/")
            for i in range(4):
                status, out = _post(base, {"input": float(i)})
                assert status == 200 and out["prediction"] == 2.0 * i
            status, ctype, text = _get(base + "/metrics")
            assert status == 200
            assert ctype.startswith("text/plain")
            assert "# TYPE serving_requests_total counter" in text
            assert "serving_requests_total 4" in text
            assert "# TYPE serving_queue_wait_seconds histogram" in text
            assert "serving_apply_latency_seconds_count" in text
            status, ctype, body = _get(base + "/healthz")
            health = json.loads(body)
            assert status == 200 and health["status"] == "ok"
            assert health["uptime_seconds"] >= 0
            assert health["model_epoch"] >= 1
            assert health["last_batch_age_seconds"] is not None
            assert health["uncommitted_epochs"] == 0
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(base + "/nope")
            assert err.value.code == 404
        # histogram counts line up with the traffic
        s = reg.summary()
        assert s["serving_queue_wait_seconds"]["count"] == 4
        assert s["serving_batch_size"]["count"] >= 1
        assert s["serving_apply_latency_seconds"]["count"] >= 1

    def test_request_trace_threads_into_batch_and_apply(self):
        with ServingServer(_Doubler(), max_latency_ms=1.0,
                           registry=MetricsRegistry()) as srv:
            status, _ = _post(srv.info.url, {"input": 1.0})
            assert status == 200
        # the handler finishes the root span AFTER writing the reply, so
        # the client can observe the response a beat before the span lands
        tracer = get_tracer()
        deadline = time.monotonic() + 2.0
        root = None
        while root is None and time.monotonic() < deadline:
            root = next(
                (r for r in reversed(tracer.export())
                 if r["name"] == "serving.request"), None,
            )
            if root is None:
                time.sleep(0.01)
        assert root is not None, "request span never finished"
        names = {r["name"] for r in tracer.export(root["trace_id"])}
        assert {"serving.request", "serving.batch", "serving.apply"} <= names

    def test_reply_failure_counts_and_logs_debug(self, caplog):
        reg = MetricsRegistry()
        loop = _BatchLoop(_Doubler(), "input", "prediction", 8, 1.0,
                          registry=reg)
        events = []
        listener = events.append
        bus = get_bus()
        bus.add_listener(listener)
        try:
            with caplog.at_level("DEBUG", logger="mmlspark_tpu.serving"):
                loop.note_reply_failure("rid-1", BrokenPipeError(32, "gone"))
        finally:
            bus.remove_listener(listener)
        assert reg.summary()["serving_replies_failed_total"] == 1
        served = [e for e in events if isinstance(e, RequestServed)]
        assert served and served[0].status == 499 and served[0].rid == "rid-1"
        assert any(
            "client disconnected" in r.message and r.levelname == "DEBUG"
            for r in caplog.records
        )


# ---------------------------------------------------------------------------
# pipeline bridge
# ---------------------------------------------------------------------------


class TestPipelineEvents:
    def test_fit_emits_stage_and_model_events(self):
        events = []
        listener = events.append
        bus = get_bus()
        bus.add_listener(listener)
        try:
            table = Table({"input": np.arange(4.0)})
            model = Pipeline(stages=[_Doubler()]).fit(table)
            out = model.transform(table)
        finally:
            bus.remove_listener(listener)
        assert np.allclose(out.column("prediction"), np.arange(4.0) * 2)
        kinds = [type(e).__name__ for e in events]
        assert kinds[0] == "StageStarted"
        assert "StageCompleted" in kinds
        assert kinds[-1] == "ModelCommitted"
        started = next(e for e in events if isinstance(e, StageStarted))
        completed = next(e for e in events if isinstance(e, StageCompleted))
        assert started.name == completed.name == "_Doubler"
        assert completed.status == "ok" and completed.duration >= 0

    def test_fit_failure_reports_status(self):
        class _Boom(Transformer):
            def transform(self, table):
                raise RuntimeError("no")

        events = []
        listener = events.append
        bus = get_bus()
        bus.add_listener(listener)
        try:
            with pytest.raises(RuntimeError):
                # two stages force a transform of the first stage's output
                Pipeline(stages=[_Boom(), _Doubler()]).fit(
                    Table({"input": np.arange(3.0)})
                )
        finally:
            bus.remove_listener(listener)
        completed = [e for e in events if isinstance(e, StageCompleted)]
        assert completed and completed[0].status == "RuntimeError"

    def test_transform_opens_stage_spans_inside_a_trace(self):
        tracer = get_tracer()
        model = Pipeline(stages=[_Doubler()]).fit(
            Table({"input": np.arange(2.0)})
        )
        with tracer.span("request") as root:
            model.transform(Table({"input": np.arange(2.0)}))
        names = [r["name"] for r in tracer.export(root.trace_id)]
        assert "transform:_Doubler" in names

    def test_untraced_transform_opens_no_spans(self):
        tracer = get_tracer()
        model = Pipeline(stages=[_Doubler()]).fit(
            Table({"input": np.arange(2.0)})
        )
        before = len(tracer.export())
        # no ambient span: the hot path must not pay per-stage spans
        model.transform(Table({"input": np.arange(2.0)}))
        assert len(tracer.export()) == before
