"""Process-parallel fit tests (``mmlspark_tpu.lightgbm.procfit``).

Fast tests cover the option gate (shard-dependent semantics are rejected,
not silently divergent), the TrainOptions JSON round-trip, and the
distributed model-text comparator. The ``slow`` tests spawn REAL worker
processes: 2-process histogram-allreduce fit with AUC and model-text
parity against the single-process fit, and the tentpole chaos claim — a
member SIGKILL'd mid-collective, the gang re-formed, and the fit resumed
from the journal with ZERO re-execution of committed iterations
(bitwise-identical final model, ``TaskRecovered`` per restored
iteration).
"""

import dataclasses
import os
import tempfile

import numpy as np
import pytest

from mmlspark_tpu.lightgbm.procfit import (
    model_texts_close,
    options_from_payload,
    options_to_payload,
    validate_process_options,
)
from mmlspark_tpu.lightgbm.train import TrainOptions


def _auc(scores, labels):
    order = np.argsort(scores, kind="stable")
    ranks = np.empty(len(scores))
    ranks[order] = np.arange(1, len(scores) + 1)
    pos = labels > 0
    return (ranks[pos].sum() - pos.sum() * (pos.sum() + 1) / 2) / (
        pos.sum() * (~pos).sum()
    )


def _toy(n=400, f=5, seed=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] + rng.normal(scale=0.4, size=n) > 0).astype(
        np.float32
    )
    return X, y


class TestOptionGate:
    def test_defaults_pass(self):
        validate_process_options(TrainOptions(objective="binary"))

    @pytest.mark.parametrize(
        "kwargs,needle",
        [
            (dict(bagging_fraction=0.8, bagging_freq=1), "bagging"),
            (dict(pos_bagging_fraction=0.5, bagging_freq=1), "bagging"),
            (dict(boosting_type="goss"), "goss"),
            (dict(boosting_type="dart"), "dart"),
            (dict(objective="quantile"), "quantile"),
            (dict(tree_learner="voting_parallel"), "voting_parallel"),
            (dict(use_quantized_grad=True), "quantized"),
            (dict(provide_training_metric=True), "training_metric"),
            (dict(early_stopping_round=5), "early stopping"),
        ],
    )
    def test_shard_dependent_options_rejected(self, kwargs, needle):
        base = dict(objective="binary")
        base.update(kwargs)
        with pytest.raises(ValueError, match=needle):
            validate_process_options(TrainOptions(**base))

    def test_feature_fraction_allowed(self):
        # feature draws depend only on the (global) schedule, never on
        # local row counts — identical on every shard
        validate_process_options(
            TrainOptions(objective="binary", feature_fraction=0.7)
        )


class TestOptionsPayload:
    def test_json_round_trip_restores_tuples(self):
        opts = TrainOptions(
            objective="multiclass", num_class=3, categorical_slots=(1, 3),
            onehot_slots=(2,), num_iterations=7, seed=11,
        )
        import json

        payload = json.loads(json.dumps(options_to_payload(opts)))
        back = options_from_payload(payload)
        assert back == opts
        assert isinstance(back.categorical_slots, tuple)
        assert isinstance(back.onehot_slots, tuple)


class TestModelTextComparator:
    HEADER = "tree\nversion=v3\nsplit_feature=0 1 2\n"

    def test_identical(self):
        a = self.HEADER + "leaf_value=0.5 0.25\n"
        assert model_texts_close(a, a)

    def test_float_jitter_ok_structure_not(self):
        a = self.HEADER + "leaf_value=0.5 0.25\n"
        b = self.HEADER + "leaf_value=0.50000001 0.25\n"
        assert model_texts_close(a, b)
        c = "tree\nversion=v3\nsplit_feature=0 2 1\nleaf_value=0.5 0.25\n"
        assert not model_texts_close(a, c)

    def test_tree_sizes_exempt_but_counted(self):
        a = self.HEADER + "tree_sizes=100 200\n"
        b = self.HEADER + "tree_sizes=101 199\n"
        c = self.HEADER + "tree_sizes=100\n"
        assert model_texts_close(a, b)
        assert not model_texts_close(a, c)

    def test_large_float_divergence_fails(self):
        a = self.HEADER + "leaf_value=0.5 0.25\n"
        b = self.HEADER + "leaf_value=0.9 0.25\n"
        assert not model_texts_close(a, b)


@pytest.mark.slow
class TestProcessFitLive:
    def _reference(self, X, y, opts):
        from mmlspark_tpu.lightgbm.binning import bin_dataset
        from mmlspark_tpu.lightgbm.train import train

        bins, mapper = bin_dataset(X, max_bin=opts.max_bin)
        return train(bins, y, opts, mapper=mapper)

    def test_two_process_parity(self):
        from mmlspark_tpu.lightgbm.procfit import fit_process_group

        X, y = _toy()
        opts = TrainOptions(
            objective="binary", num_iterations=6, num_leaves=7,
            max_bin=32, min_data_in_leaf=5, seed=2,
        )
        ref = self._reference(X, y, opts)
        ref_text = ref.booster.model_to_string()
        result = fit_process_group(
            X, y, opts, num_processes=2,
            group_options={"epoch_timeout_s": 180.0},
        )
        assert result.epochs == 1
        assert result.recovered_iterations == 0
        assert result.iterations == 6
        # structure byte-identical; float cells within shard-sum tolerance
        assert model_texts_close(result.model_text, ref_text)
        auc_ref = _auc(ref.booster.raw_margin(X).ravel(), y)
        auc_proc = _auc(result.booster.raw_margin(X).ravel(), y)
        assert abs(auc_ref - auc_proc) < 1e-6, (auc_ref, auc_proc)

    def test_sigkill_mid_fit_resumes_with_zero_reexecution(self, tmp_path):
        from mmlspark_tpu import observability as obs
        from mmlspark_tpu.lightgbm.procfit import fit_process_group
        from mmlspark_tpu.runtime.faults import FaultPlan

        event_log = str(tmp_path / "events.jsonl")
        os.environ["MMLSPARK_TPU_EVENT_LOG"] = event_log
        try:
            X, y = _toy()
            opts = TrainOptions(
                objective="binary", num_iterations=6, num_leaves=7,
                max_bin=32, min_data_in_leaf=5, seed=2,
            )
            baseline = fit_process_group(
                X, y, opts, num_processes=2,
                group_options={"epoch_timeout_s": 180.0},
            )
            kill_at = 3
            plan = FaultPlan(seed=11).kill_process(1, iteration=kill_at)
            result = fit_process_group(
                X, y, opts, num_processes=2,
                group_options={"faults": plan, "epoch_timeout_s": 180.0},
            )
        finally:
            del os.environ["MMLSPARK_TPU_EVENT_LOG"]

        # the recovered fit IS the undisturbed fit, bit for bit
        assert result.model_text == baseline.model_text
        assert result.epochs == 2
        assert result.recovered_iterations == kill_at
        assert plan.fired == [("kill_process", 1, 0)]
        killed = [s for s in result.exit_statuses if s.reason == "signal:9"]
        assert killed and killed[0].member == 1

        events = obs.replay(event_log)
        names = [type(e).__name__ for e in events]
        assert names.count("ProcessLost") == 1
        assert names.count("GroupReformed") == 1
        # one TaskRecovered per committed iteration NOT re-executed
        recovered = [e for e in events if type(e).__name__ == "TaskRecovered"]
        assert sorted(e.task_id for e in recovered) == list(range(kill_at))

    def test_two_deaths_quarantine_worker(self, tmp_path):
        from mmlspark_tpu import observability as obs
        from mmlspark_tpu.lightgbm.procfit import fit_process_group
        from mmlspark_tpu.runtime.faults import FaultPlan

        event_log = str(tmp_path / "events.jsonl")
        os.environ["MMLSPARK_TPU_EVENT_LOG"] = event_log
        try:
            X, y = _toy()
            opts = TrainOptions(
                objective="binary", num_iterations=6, num_leaves=7,
                max_bin=32, min_data_in_leaf=5, seed=2,
            )
            baseline = fit_process_group(
                X, y, opts, num_processes=2,
                group_options={"epoch_timeout_s": 180.0},
            )
            # kill member 1 twice: second death quarantines it, and the
            # gang SHRINKS to one member that still finishes the fit
            plan = (
                FaultPlan(seed=12)
                .kill_process(1, iteration=2)
                .kill_process(1, iteration=4, epoch=1)
            )
            result = fit_process_group(
                X, y, opts, num_processes=2,
                group_options={"faults": plan, "epoch_timeout_s": 180.0},
            )
        finally:
            del os.environ["MMLSPARK_TPU_EVENT_LOG"]

        # after the shrink the survivor holds ALL rows, so its tail-tree
        # histogram sums are single-shard — structure-identical to the
        # baseline but not bitwise (same reason 2-proc vs 1-proc isn't)
        assert model_texts_close(result.model_text, baseline.model_text)
        assert result.epochs == 3
        assert len([s for s in result.exit_statuses
                    if s.reason == "signal:9"]) == 2
        events = obs.replay(event_log)
        names = [type(e).__name__ for e in events]
        assert names.count("WorkerQuarantined") == 1
        assert names.count("GroupReformed") == 2

    def test_estimator_num_processes(self):
        from mmlspark_tpu.data.table import Table
        from mmlspark_tpu.lightgbm.classifier import LightGBMClassifier

        X, y = _toy()
        t = Table({"features": X.astype(np.float64), "label": y.astype(np.float64)})
        kwargs = dict(numIterations=6, numLeaves=7, seed=2)
        m_ref = LightGBMClassifier(**kwargs).fit(t)
        est = LightGBMClassifier(numProcesses=2, **kwargs)
        m_proc = est.fit(t)
        assert model_texts_close(
            m_ref.get_model_string(), m_proc.get_model_string()
        )
        assert est._process_fit.epochs == 1
        p_ref = np.asarray(m_ref.transform(t).column("prediction"))
        p_proc = np.asarray(m_proc.transform(t).column("prediction"))
        assert (p_ref == p_proc).all()

    def test_estimator_rejects_bagging(self):
        from mmlspark_tpu.data.table import Table
        from mmlspark_tpu.lightgbm.classifier import LightGBMClassifier

        X, y = _toy(n=80)
        t = Table({"features": X.astype(np.float64), "label": y.astype(np.float64)})
        est = LightGBMClassifier(
            numProcesses=2, baggingFraction=0.8, baggingFreq=1, numIterations=2
        )
        with pytest.raises(ValueError, match="bagging"):
            est.fit(t)
